"""Observability subsystem (ISSUE 3): span tracer (Chrome trace-event JSON,
nesting, thread names, jax mirror), metrics registry (Prometheus text),
exporter endpoints (/metrics parses, /healthz reflects step progress), hang
watchdog (simulated stall -> diagnostics dump), REST request logging, the
run-start metrics marker, the profile-window knobs, and the live-during-
training acceptance run."""
import argparse
import json
import os
import re
import socket
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from homebrewnlp_tpu import main as cli
from homebrewnlp_tpu.obs import (Health, MetricsRegistry, Obs, Watchdog,
                                 dump_diagnostics, start_server, stop_server)
from homebrewnlp_tpu.obs import spans as spans_mod
from homebrewnlp_tpu.obs.spans import NULL_SPAN, SpanTracer, set_tracer, span

from .backend import tiny_config


def _args(steps, profile=""):
    return argparse.Namespace(steps=steps, profile=profile, workers=None)


def _get(url, timeout=10):
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return r.status, r.read()


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


# one sample line: name{labels} value  (value may be int/float/+Inf)
_PROM_SAMPLE = re.compile(
    r"^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^}]*\})? (-?[0-9.e+-]+|\+Inf|NaN)$")


def _assert_prometheus_text(text):
    """Every non-empty line is a HELP/TYPE comment or a well-formed sample."""
    assert text.endswith("\n")
    for line in text.splitlines():
        if not line or line.startswith("# HELP ") or line.startswith("# TYPE "):
            continue
        assert _PROM_SAMPLE.match(line), f"bad prometheus line: {line!r}"


# -- span tracer -------------------------------------------------------------

def test_span_tracer_chrome_json_nesting_and_threads(tmp_path):
    tracer = SpanTracer(mirror_jax=False)
    with tracer.span("outer", update=3):
        with tracer.span("inner"):
            time.sleep(0.005)

    def worker():
        with tracer.span("worker-span"):
            pass

    t = threading.Thread(target=worker, name="feeder-like")
    t.start()
    t.join()
    path = tracer.export(str(tmp_path / "trace.json"))
    doc = json.load(open(path))
    assert isinstance(doc["traceEvents"], list)
    xs = {e["name"]: e for e in doc["traceEvents"] if e.get("ph") == "X"}
    assert set(xs) == {"outer", "inner", "worker-span"}
    for e in xs.values():  # complete events carry the required fields
        assert {"ts", "dur", "pid", "tid"} <= set(e)
    # nesting: inner lies within outer's interval, on the same thread
    out, inn = xs["outer"], xs["inner"]
    assert out["tid"] == inn["tid"]
    assert out["ts"] <= inn["ts"]
    assert inn["ts"] + inn["dur"] <= out["ts"] + out["dur"] + 1e-3
    assert out["args"]["update"] == "3"
    # thread-name metadata rows label each track
    names = {e["args"]["name"] for e in doc["traceEvents"]
             if e.get("ph") == "M" and e["name"] == "thread_name"}
    assert "feeder-like" in names and "MainThread" in names
    totals = tracer.phase_totals()
    assert totals["outer"] >= totals["inner"] >= 0.005


def test_span_tracer_mirrors_into_jax_annotation():
    """mirror_jax=True wraps spans in jax.profiler.TraceAnnotation (free
    without an active capture — this pins that the wiring doesn't raise)."""
    tracer = SpanTracer(mirror_jax=True)
    assert tracer._mirror is not None
    with tracer.span("mirrored"):
        pass
    assert [n for n, *_ in tracer._events] == ["mirrored"]


def test_ambient_span_is_noop_when_disabled():
    assert spans_mod.get_tracer() is None
    assert span("anything") is NULL_SPAN  # shared no-op object, no alloc
    with span("anything"):
        pass

    @spans_mod.traced("fn")
    def f(x):
        return x + 1

    assert f(1) == 2  # decorator resolves the (absent) tracer per call
    tracer = SpanTracer(mirror_jax=False)
    prev = set_tracer(tracer)
    try:
        assert f(2) == 3
        with span("live"):
            pass
    finally:
        set_tracer(prev)
    assert {n for n, *_ in tracer._events} == {"fn", "live"}


def test_span_tracer_ring_bounds_memory():
    """max_events is a ring keeping the MOST RECENT spans; phase totals stay
    exact and the export records the drop count."""
    tracer = SpanTracer(mirror_jax=False, max_events=3)
    for i in range(10):
        with tracer.span(f"s{i}"):
            pass
    assert [e[0] for e in tracer._events] == ["s7", "s8", "s9"]
    assert len(tracer.phase_totals()) == 10  # totals survive the ring
    import tempfile
    with tempfile.TemporaryDirectory() as d:
        doc = json.load(open(tracer.export(os.path.join(d, "t.json"))))
    assert doc["otherData"]["dropped_events"] == 7


# -- registry ----------------------------------------------------------------

def test_registry_prometheus_rendering():
    reg = MetricsRegistry()
    reg.counter("steps_total", "steps").inc(5)
    reg.gauge("depth", "queue depth").set(2)
    reg.gauge("cb", "callback gauge", fn=lambda: 7.5)
    h = reg.histogram("lat_seconds", "latency", buckets=(0.1, 1.0))
    h.observe(0.05)
    h.observe(0.5)
    h.observe(30.0)
    lab = reg.counter("req_total", "requests", labelnames=("path", "status"))
    lab.labels(path="/x", status=200).inc()
    lab.labels(path="/x", status=500).inc(2)
    text = reg.render()
    _assert_prometheus_text(text)
    assert "steps_total 5" in text
    assert "depth 2" in text
    assert "cb 7.5" in text
    # histogram: cumulative buckets, +Inf == count, sum accumulates
    assert 'lat_seconds_bucket{le="0.1"} 1' in text
    assert 'lat_seconds_bucket{le="1"} 2' in text
    assert 'lat_seconds_bucket{le="+Inf"} 3' in text
    assert "lat_seconds_count 3" in text
    assert 'req_total{path="/x",status="200"} 1' in text
    assert 'req_total{path="/x",status="500"} 2' in text
    # idempotent re-registration returns the same metric; kind clash raises
    assert reg.counter("steps_total") is not None
    reg.counter("steps_total").inc()
    assert reg.counter("steps_total").value() == 6
    with pytest.raises(ValueError, match="already registered"):
        reg.gauge("steps_total")


# -- exporter ----------------------------------------------------------------

def test_exporter_metrics_and_healthz_reflect_progress():
    reg = MetricsRegistry()
    reg.counter("c_total", "c").inc(3)
    health = Health()
    server = start_server(0, registry=reg, health=health)
    try:
        port = server.server_address[1]
        status, body = _get(f"http://127.0.0.1:{port}/metrics")
        assert status == 200
        _assert_prometheus_text(body.decode())
        assert "c_total 3" in body.decode()
        status, body = _get(f"http://127.0.0.1:{port}/healthz")
        h = json.loads(body)
        assert status == 200 and h["status"] == "starting"
        assert h["last_completed_step"] is None
        health.step_completed(4)
        health.step_completed(5)
        status, body = _get(f"http://127.0.0.1:{port}/healthz")
        h = json.loads(body)
        assert status == 200 and h["status"] == "ok"
        assert h["last_completed_step"] == 5
        assert h["ema_step_seconds"] is not None
        with pytest.raises(urllib.error.HTTPError):
            _get(f"http://127.0.0.1:{port}/nope")
    finally:
        stop_server(server)


def test_burst_drain_does_not_collapse_ema():
    """A checkpoint/profiler flush() drains the whole in-flight window
    back-to-back; the EMA must track DISPATCH spacing, or the near-zero
    drain gaps would shrink the stall threshold and 503 a healthy run."""
    health = Health()
    t0 = time.time()
    health.step_completed(0, dispatch_wall=t0)
    health.step_completed(1, dispatch_wall=t0 + 2.0)
    # burst-drained window: steps dispatched 2s apart, drained same instant
    health.step_completed(2, dispatch_wall=t0 + 4.0)
    health.step_completed(3, dispatch_wall=t0 + 6.0)
    assert health.ema_step_seconds() == pytest.approx(2.0)


def test_pause_excluded_from_dispatch_ema():
    """A declared checkpoint pause between dispatches must not inflate the
    EMA (and with it the stall threshold) when steps resume."""
    health = Health()
    t0 = time.time()
    health.step_completed(0, dispatch_wall=t0 - 4.0)
    health.step_completed(1, dispatch_wall=t0 - 2.0)  # cadence 2s
    health.begin_pause("checkpoint")
    health._pause_wall = t0 - 2.0  # simulate: the save took ~2s
    health.end_pause()
    health.step_completed(2, dispatch_wall=t0 + 2.0)  # 4s gap incl. pause
    # the ~2s pause is excluded: EMA stays at the 2s cadence (not 0.2*4+..)
    assert health.ema_step_seconds() == pytest.approx(2.0, rel=0.2)


def test_startup_bound_disabled_with_zero():
    health = Health(startup_stall_s=0.0)
    health.started -= 10_000  # ancient start, still no steps
    assert health.stalled() is False
    assert health.snapshot()["status"] == "starting"


def test_healthz_reports_stalled_as_503():
    health = Health(stall_factor=1.0)
    health.step_completed(0)
    health._last_wall -= 100.0  # simulate: last step 100s ago
    health._ema_step_s = 0.01
    # the stall threshold shares the watchdog's 5s floor: a 2s checkpoint
    # pause on a fast-step run must NOT flip /healthz to 503
    assert health.min_stall_s == 5.0
    fast = Health(stall_factor=10.0)
    fast.step_completed(0)
    fast._last_wall -= 2.0
    fast._ema_step_s = 0.05
    assert fast.snapshot()["status"] == "ok"
    server = start_server(0, registry=MetricsRegistry(), health=health)
    try:
        port = server.server_address[1]
        with pytest.raises(urllib.error.HTTPError) as e:
            _get(f"http://127.0.0.1:{port}/healthz")
        assert e.value.code == 503
        assert json.loads(e.value.read())["status"] == "stalled"
    finally:
        stop_server(server)


# -- watchdog ----------------------------------------------------------------

def test_watchdog_stall_dumps_diagnostics_once(tmp_path):
    health = Health(stall_factor=2.0)
    health.step_completed(0)
    time.sleep(0.02)
    health.step_completed(1)  # EMA ~20ms
    wd = Watchdog(health, str(tmp_path), factor=2.0, poll_s=0.02,
                  min_stall_s=0.05)
    wd.start()
    time.sleep(0.5)  # no further steps: a stall
    wd.stop()
    files = sorted((tmp_path / "diagnostics").glob("hang_*.txt"))
    assert len(files) == 1, "one dump per stall, not one per poll"
    content = files[0].read_text()
    assert "reason: watchdog" in content
    assert "MainThread" in content            # thread stacks present
    assert "device_memory_stats" in content   # memory section present
    assert "last step 1" in content


def test_watchdog_rearms_after_steps_resume(tmp_path):
    health = Health(stall_factor=2.0)
    health.step_completed(0)
    time.sleep(0.02)
    health.step_completed(1)
    wd = Watchdog(health, str(tmp_path), factor=2.0, poll_s=0.02,
                  min_stall_s=0.05)
    wd.start()
    time.sleep(0.3)           # first stall -> dump 1
    health.step_completed(2)  # resume re-arms
    time.sleep(0.3)           # second stall -> dump 2
    wd.stop()
    assert len(list((tmp_path / "diagnostics").glob("hang_*.txt"))) == 2


def test_declared_pause_suppresses_stall_and_watchdog(tmp_path):
    """A declared pause (checkpoint save) keeps /healthz 'ok' and holds the
    watchdog's fire; end_pause restarts the stall clock."""
    health = Health(stall_factor=2.0)
    health.step_completed(0)
    time.sleep(0.02)
    health.step_completed(1)
    wd = Watchdog(health, str(tmp_path), factor=2.0, poll_s=0.02,
                  min_stall_s=0.05)
    wd.start()
    health.begin_pause("checkpoint")
    time.sleep(0.3)  # would be a stall without the pause
    assert health.snapshot()["status"] == "ok"
    assert health.snapshot()["paused_for"] == "checkpoint"
    assert not (tmp_path / "diagnostics").exists()
    health.end_pause()
    # the paused interval does not count toward the next stall window
    assert health.seconds_since_last_step() < 0.05
    time.sleep(0.3)  # a REAL stall after the pause still fires
    wd.stop()
    assert len(list((tmp_path / "diagnostics").glob("hang_*.txt"))) == 1


def test_hung_pause_exceeding_bound_fires_watchdog(tmp_path):
    """A checkpoint save hung past max_pause_s must NOT hide behind its own
    declared pause: /healthz flips to stalled and the watchdog dumps."""
    health = Health(stall_factor=2.0)
    health.step_completed(0)
    time.sleep(0.02)
    health.step_completed(1)
    wd = Watchdog(health, str(tmp_path), factor=2.0, poll_s=0.02,
                  min_stall_s=0.05, max_pause_s=0.1)
    wd.start()
    health.begin_pause("checkpoint")
    time.sleep(0.4)  # never ends: a wedged save
    assert health.snapshot()["status"] == "stalled"
    wd.stop()
    files = list((tmp_path / "diagnostics").glob("hang_*.txt"))
    assert len(files) == 1
    assert "exceeded" in files[0].read_text()


def test_watchdog_quiet_before_first_step(tmp_path):
    """No EMA yet (still compiling): the watchdog holds fire until the
    generous absolute startup bound."""
    wd = Watchdog(Health(), str(tmp_path), factor=2.0, poll_s=0.02,
                  min_stall_s=0.01)
    wd.start()
    time.sleep(0.2)
    wd.stop()
    assert not (tmp_path / "diagnostics").exists()


def test_watchdog_startup_hang_fires_after_absolute_bound(tmp_path):
    """A run wedged BEFORE any step cadence exists (deadlocked compile /
    restore / first step) must still dump once the startup bound passes —
    the opaque startup death is exactly what the watchdog insures."""
    health = Health(startup_stall_s=0.1)
    wd = Watchdog(health, str(tmp_path), factor=2.0, poll_s=0.02,
                  min_stall_s=0.01)
    wd.start()
    time.sleep(0.4)
    assert health.snapshot()["status"] == "stalled"
    wd.stop()
    files = list((tmp_path / "diagnostics").glob("hang_*.txt"))
    assert len(files) == 1, "one dump, deduped across polls"
    assert "startup" in files[0].read_text()


def test_dump_diagnostics_direct(tmp_path):
    p = dump_diagnostics(str(tmp_path), Health(), reason="unit test")
    content = open(p).read()
    assert "reason: unit test" in content and "pid:" in content


# -- REST request logging ----------------------------------------------------

def test_rest_request_logging_counts_and_latency():
    from homebrewnlp_tpu.serve import rest

    class StubAPI:
        ENDPOINTS = ("encode", "boom")

        def encode(self, body):
            return {"tokens": [1, 2]}

        def boom(self, body):
            raise RuntimeError("kaput")

    reg = MetricsRegistry()
    server = rest.serve(None, None, port=0, background=True, api=StubAPI(),
                        registry=reg)
    try:
        port = server.server_address[1]

        def post(path):
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}/{path}", data=b"{}",
                headers={"Content-Type": "application/json"})
            try:
                with urllib.request.urlopen(req, timeout=10) as r:
                    return r.status
            except urllib.error.HTTPError as e:
                return e.code

        assert post("encode") == 200
        assert post("encode") == 200
        assert post("boom") == 500
        assert post("missing") == 404
        c = reg.counter("hbnlp_serve_requests_total")
        # the handler records the request in its `finally`, AFTER the
        # response bytes are on the wire — the client can observe the last
        # 404 before the server thread increments, so wait for it to land
        deadline = time.time() + 5.0
        while (time.time() < deadline
               and c.value(method="POST", path="other", status="404") < 1):
            time.sleep(0.01)
        assert c.value(method="POST", path="/encode", status="200") == 2
        assert c.value(method="POST", path="/boom", status="500") == 1
        # unmatched paths fold into the fixed "other" bucket — a scanner
        # must not be able to grow the label set without bound
        assert c.value(method="POST", path="other", status="404") == 1
        assert c.value(method="POST", path="/missing", status="404") == 0
        h = reg.histogram("hbnlp_serve_request_seconds")
        assert h.count(path="/encode") == 2
        _assert_prometheus_text(reg.render())
    finally:
        server.shutdown()


def test_rest_background_obs_exporter_stops_with_server():
    """serve(background=True) with cfg.obs_port: the exporter serves while
    the API runs and the caller's shutdown() stops BOTH (no leaked thread /
    bound port)."""
    from homebrewnlp_tpu.serve import rest

    class StubAPI:
        ENDPOINTS = ("encode",)

        def encode(self, body):
            return {"tokens": []}

    obs_port = _free_port()
    cfg = tiny_config(obs_port=obs_port)
    reg = MetricsRegistry()
    reg.counter("alive_total", "x").inc()
    server = rest.serve(cfg, None, port=0, background=True, api=StubAPI(),
                        registry=reg)
    try:
        status, body = _get(f"http://127.0.0.1:{obs_port}/metrics")
        assert status == 200 and "alive_total 1" in body.decode()
        # no Health is wired in serve mode: /healthz must say so instead of
        # claiming "ok" (a liveness probe must not be misled)
        _, body = _get(f"http://127.0.0.1:{obs_port}/healthz")
        assert json.loads(body)["status"] == "metrics-only"
    finally:
        server.shutdown()
        server.server_close()
    with pytest.raises((urllib.error.URLError, OSError)):
        _get(f"http://127.0.0.1:{obs_port}/metrics", timeout=2)


# -- config knobs ------------------------------------------------------------

def test_obs_config_validation():
    with pytest.raises(ValueError, match="obs_port"):
        tiny_config(obs_port=-1)
    with pytest.raises(ValueError, match="watchdog_factor"):
        tiny_config(watchdog_factor=-0.5)
    with pytest.raises(ValueError, match="profile_start"):
        tiny_config(profile_start=0)
    with pytest.raises(ValueError, match="profile_steps"):
        tiny_config(profile_steps=0)
    cfg = tiny_config()
    assert cfg.obs_port == 0 and not cfg.obs_spans
    assert cfg.watchdog_factor == 0.0
    assert Obs.from_config(cfg).enabled is False


def test_obs_start_failure_unwinds_ambient_tracer(tmp_path, eight_devices):
    """A partial Obs.start (obs_port already bound) must not leak the
    ambient span tracer into later runs in the same process."""
    blocker = socket.socket()
    blocker.bind(("127.0.0.1", 0))
    blocker.listen(1)
    try:
        cfg = tiny_config(model_path=str(tmp_path),
                          obs_port=blocker.getsockname()[1], obs_spans=True)
        with pytest.raises(OSError):
            cli.train(cfg, _args(2))
    finally:
        blocker.close()
    assert spans_mod.get_tracer() is None


def test_disabled_obs_is_inert(tmp_path):
    obs = Obs.from_config(tiny_config(model_path=str(tmp_path)))
    obs.start()
    obs.close()
    assert spans_mod.get_tracer() is None
    assert not (tmp_path / "trace.json").exists()


def test_profile_window_knobs_drive_profiler(tmp_path, eight_devices):
    """profile_start/profile_steps replace the hardcoded u0+3..u0+6 window;
    a window starting at update 1 works on short runs."""
    trace_dir = str(tmp_path / "trace")
    cfg = tiny_config(model_path=str(tmp_path / "run"), profile_start=1,
                      profile_steps=2)
    cli.train(cfg, _args(5, profile=trace_dir))
    assert os.path.isdir(trace_dir)
    assert any(files for _, _, files in os.walk(trace_dir))


# -- acceptance: live obs during a training run ------------------------------

def test_train_serves_live_obs_and_exports_trace(tmp_path, eight_devices):
    """A synthetic run with obs_port set serves /healthz + /metrics WHILE
    stepping, and on exit writes a Perfetto-loadable trace.json covering
    the step/feed/drain/checkpoint phases."""
    port = _free_port()
    cfg = tiny_config(model_path=str(tmp_path), obs_port=port, obs_spans=True,
                      watchdog_factor=100.0, use_checkpointing=True,
                      steps_per_checkpoint=50, async_inflight_steps=2,
                      device_prefetch_depth=1)
    done = threading.Event()
    errs = []

    def run():
        try:
            cli.train(cfg, _args(120))
        except BaseException as e:  # surfaced below
            errs.append(e)
        finally:
            done.set()

    t = threading.Thread(target=run, name="train-under-test")
    t.start()
    live_health = live_metrics = None
    deadline = time.time() + 300
    while time.time() < deadline and not done.is_set():
        try:
            _, body = _get(f"http://127.0.0.1:{port}/healthz", timeout=5)
            h = json.loads(body)
            if h.get("last_completed_step") is not None and not done.is_set():
                live_health = h
                _, mbody = _get(f"http://127.0.0.1:{port}/metrics", timeout=5)
                live_metrics = mbody.decode()
                break
        except (urllib.error.URLError, OSError):
            pass  # server not up yet
        time.sleep(0.02)
    t.join(600)
    assert not errs, errs
    assert live_health is not None, "never saw a completed step while live"
    assert live_health["status"] in ("ok", "starting")
    assert live_health["feeder_alive"] is True
    _assert_prometheus_text(live_metrics)
    for metric in ("hbnlp_train_steps_total", "hbnlp_train_tokens_total",
                   "hbnlp_feeder_queue_depth", "hbnlp_last_completed_step",
                   "hbnlp_metric_drain_seconds_count",
                   "hbnlp_feeder_h2d_seconds_count"):
        assert metric in live_metrics, metric
    # exporter is gone after the run, tracer restored
    assert spans_mod.get_tracer() is None
    with pytest.raises((urllib.error.URLError, OSError)):
        _get(f"http://127.0.0.1:{port}/healthz", timeout=2)
    # Obs.close froze the callback gauges: the process-global registry no
    # longer references the run's feeder/health (no leak into later scrapes)
    from homebrewnlp_tpu.obs.registry import REGISTRY
    assert REGISTRY.get("hbnlp_feeder_queue_depth").value() == 0
    assert REGISTRY.get("hbnlp_last_completed_step").value() >= 0
    # trace.json: valid Chrome trace covering the required phases
    doc = json.load(open(tmp_path / "trace.json"))
    names = {e["name"] for e in doc["traceEvents"] if e.get("ph") == "X"}
    assert {"step", "feed", "drain", "checkpoint"} <= names, names
    threads = {e["args"]["name"] for e in doc["traceEvents"]
               if e.get("ph") == "M" and e["name"] == "thread_name"}
    assert any("device-feeder" in n for n in threads), threads
    # the metrics file carries finite losses for every step
    rows = [json.loads(l) for l in open(tmp_path / "metrics.jsonl")]
    assert all(np.isfinite(r["loss"]) for r in rows if "loss" in r)


def test_obs_off_loss_sequence_matches_obs_on(tmp_path, eight_devices):
    """Observability must not perturb training: the loss sequence with
    spans + registry + watchdog armed equals the all-off sequence."""
    base = dict(async_inflight_steps=0, device_prefetch_depth=0)
    cfg_off = tiny_config(model_path=str(tmp_path / "off"), **base)
    cli.train(cfg_off, _args(8))
    cfg_on = tiny_config(model_path=str(tmp_path / "on"), obs_spans=True,
                         watchdog_factor=100.0, **base)
    cli.train(cfg_on, _args(8))

    from homebrewnlp_tpu.train.metrics import read_metric_rows

    def losses(p):
        return [r["loss"] for r in read_metric_rows(str(p))]

    assert losses(tmp_path / "off") == losses(tmp_path / "on")
