"""SPMD sharding propagation (analysis/spmd.py): seeding, per-primitive
propagation, implicit-collective charging, the lowered/best strategy split,
the ratcheted implicit-collective rule, committed-golden stability for all
bundled configs, and the HLO cross-validation honesty check."""
import dataclasses
import glob
import json
import os
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.dirname(__file__))

from homebrewnlp_tpu.analysis import spmd, trace as atrace  # noqa: E402
from homebrewnlp_tpu.analysis.graph_rules import (_IntendedMesh,  # noqa: E402
                                                  intended_mesh)
from homebrewnlp_tpu.analysis.trace import (StepTrace,  # noqa: E402
                                            trace_config)
from homebrewnlp_tpu.config import Config  # noqa: E402

from backend import tiny_config  # noqa: E402

ALL_AXES = _IntendedMesh({"data": 2, "sequence_parallel": 1, "pipeline": 1,
                          "model": 2})
DP4 = _IntendedMesh({"data": 4, "sequence_parallel": 1, "pipeline": 1,
                     "model": 1})


def _trace_of(fn, in_axes, *args) -> StepTrace:
    """Hand-built StepTrace over a tiny function with explicit seeds."""
    jaxpr = jax.make_jaxpr(fn)(*args)
    return StepTrace("train", jaxpr, None, in_axes=list(in_axes))


def _census(st, imesh, strategy="lowered"):
    return spmd.census(spmd.propagate(st, imesh), imesh, strategy=strategy)


# -- seeding -----------------------------------------------------------------

def test_in_axes_seed_lists_align_with_invars():
    """Every traced step of a KV-eligible config carries a seed entry per
    flattened jaxpr input — the alignment the propagation depends on."""
    from backend import mixer_config
    cfg = mixer_config(tpu_size=2)
    traces = trace_config(cfg, "seedcheck",
                          steps=("train", "eval", "decode", "prefill"),
                          quiet=True)
    assert not traces.errors
    assert set(traces.steps) == {"train", "eval", "decode", "prefill"}
    for name, st in traces.steps.items():
        inner = st.jaxpr.jaxpr
        assert st.in_axes is not None, name
        assert len(st.in_axes) == len(inner.invars), name


def test_unseeded_trace_reports_not_audited():
    st = _trace_of(lambda x: x * 2.0, [("batch", "heads")],
                   jnp.zeros((4, 4)))
    st.in_axes = None
    r = spmd.propagate(st, ALL_AXES)
    assert not r.seeded and not r.records


def test_rank_drifted_seed_degrades_to_unknown():
    """Axis metadata whose length disagrees with the value's rank (the
    stacked-pipeline-vs-unstacked-decode shape) must seed UNKNOWN, never a
    truncated — wrong — spec."""
    st = _trace_of(lambda x, w: jnp.einsum("bh,ho->bo", x, w),
                   [("pipe_stage", "batch", "heads"), ()],
                   jnp.zeros((4, 4)), jnp.zeros((4, 8)))
    assert _census(st, ALL_AXES) == {}


# -- propagation + charging --------------------------------------------------

def test_sharded_contraction_charges_psum():
    """dot_general contracting a model-sharded dim leaves partial sums —
    one implicit all-reduce of the output, per-device payload divided by
    the output's own sharding."""
    x = jnp.zeros((8, 4))   # [batch, heads]
    w = jnp.zeros((4, 16))  # [heads, out]
    st = _trace_of(lambda x, w: jnp.einsum("bh,ho->bo", x, w),
                   [("batch", "heads"), ("heads", "_o")], x, w)
    c = _census(st, ALL_AXES)
    assert list(c) == ["psum"] and list(c["psum"]) == ["model"]
    slot = c["psum"]["model"]
    # output [8, 16] f32 = 512 B, batch dim sharded over data(2) -> 256 B
    assert slot == {"count": 1, "payload_bytes": 256, "bytes": 256}
    # the same trace under a pure-DP mask has no sharded contraction
    assert _census(st, DP4) == {}


def test_replicated_contraction_is_free():
    st = _trace_of(lambda x, w: jnp.einsum("bf,fo->bo", x, w),
                   [("batch", "_f"), ("_f", "_o")],
                   jnp.zeros((8, 4)), jnp.zeros((4, 16)))
    assert _census(st, ALL_AXES) == {}


def test_sharded_reduction_charges_psum():
    """A reduce_sum over the data-sharded batch dim (the loss mean) is an
    implicit scalar all-reduce."""
    st = _trace_of(lambda x: jnp.sum(x, axis=0), [("batch", "_f")],
                   jnp.zeros((8, 4)))
    c = _census(st, DP4)
    assert c["psum"]["data"]["count"] == 1
    assert c["psum"]["data"]["payload_bytes"] == 16  # [4] f32 output


def test_scalar_and_broadcast_operands_never_conflict():
    def fn(x):
        return jnp.maximum(x * 2.0, 0.0) / jnp.float32(3.0)

    st = _trace_of(fn, [("batch", "_f")], jnp.zeros((8, 4)))
    r = spmd.propagate(st, DP4)
    assert r.seeded and not r.conflicts and not r.records


def test_conflicting_shardings_lint_and_charge_reshard():
    """Two operands sharding the same dim over different axes: the lint
    finding plus an implicit all_gather of the yielding side."""
    st = _trace_of(lambda a, b: a * b,
                   [("batch", "_f"), ("heads", "_f")],
                   jnp.zeros((4, 8)), jnp.zeros((4, 8)))
    r = spmd.propagate(st, ALL_AXES)
    assert len(r.conflicts) == 1
    assert r.conflicts[0].prim == "mul"
    c = spmd.census(r, ALL_AXES)
    assert c["all_gather"]["model"]["count"] == 1


def test_scan_body_charges_multiply_by_trip_count():
    w = jnp.zeros((4, 4))

    def fn(w, xs):
        def body(carry, x):
            return carry, jnp.einsum("bh,ho->bo", x, w)

        return jax.lax.scan(body, 0.0, xs)

    # xs seed: leading scan dim (anonymous) + [batch, heads]
    st = _trace_of(fn, [("heads", "_o"), ("_s", "batch", "heads")],
                   w, jnp.zeros((5, 8, 4)))
    c = _census(st, ALL_AXES)
    assert c["psum"]["model"]["count"] == 5


def test_cond_branch_with_sharded_contraction_charges():
    """A lax.cond whose costlier branch contracts a sharded dim: the
    branch's charges (first-option cost proxy) survive into the census
    instead of crashing branch selection (seeded regression for the
    ChargeOption refactor)."""
    def fn(x, w):
        return jax.lax.cond(
            x.sum() > 0,
            lambda: jnp.einsum("bh,ho->bo", x, w).sum(),
            lambda: jnp.float32(0.0))

    st = _trace_of(fn, [("batch", "heads"), ("heads", "_o")],
                   jnp.zeros((8, 4)), jnp.zeros((4, 16)))
    r = spmd.propagate(st, ALL_AXES)
    assert r.error == "", r.error
    c = spmd.census(r, ALL_AXES)
    assert c["psum"]["model"]["count"] >= 1


def test_single_device_mesh_short_circuits():
    """An all-size-1 mesh can never shard anything: propagation returns an
    empty, seeded result without walking the jaxpr (the 1-chip configs'
    audit-cost guard)."""
    st = _trace_of(lambda x, w: jnp.einsum("bh,ho->bo", x, w),
                   [("batch", "heads"), ("heads", "_o")],
                   jnp.zeros((8, 4)), jnp.zeros((4, 16)))
    one = _IntendedMesh({"data": 1, "sequence_parallel": 1, "pipeline": 1,
                         "model": 1})
    r = spmd.propagate(st, one)
    assert r.seeded and not r.records and not r.conflicts
    assert not hasattr(st, "_spmd_cache")  # never walked, never cached


def test_embedding_gather_carries_index_sharding():
    """jnp.take from a replicated table with data-sharded indices: the
    output rides the index sharding, so the downstream weight-grad
    scatter-add charges the implicit table all-reduce."""
    table = jnp.zeros((32, 8))
    idx = jnp.zeros((16, 4), jnp.int32)

    def fwd(table, idx):
        return jnp.take(table, idx, axis=0).sum()

    def grad_fn(table, idx):
        return jax.grad(fwd)(table, idx)

    st = _trace_of(grad_fn, [("_v", "_f"), ("batch", "_s")], table, idx)
    c = _census(st, DP4)
    # the table gradient (scatter-add of data-sharded updates) all-reduces
    assert c["psum"]["data"]["count"] >= 1
    biggest = max(s["payload_bytes"] for s in c["psum"].values())
    assert biggest >= table.size * 4  # full table grad, unsharded


def test_sharding_constraint_pins_named_dims_and_keeps_open_ones():
    """The trace-time annotation (built on the LOCAL mesh) under-specifies:
    dims it leaves open must keep the propagated sharding."""
    from jax.sharding import PartitionSpec
    from homebrewnlp_tpu.parallel import make_mesh
    cfg = tiny_config()
    mesh = make_mesh(cfg, devices=jax.devices()[:1], quiet=True)

    def fn(x):
        y = jax.lax.with_sharding_constraint(
            x, jax.sharding.NamedSharding(mesh, PartitionSpec()))
        return jnp.einsum("bh,bo->ho", y, y)

    st = _trace_of(fn, [("batch", "heads")], jnp.zeros((8, 4)))
    c = _census(st, ALL_AXES)
    # batch sharding survives the empty constraint -> grad-style
    # contraction over batch still charges a data-axis psum
    assert c["psum"]["data"]["count"] == 1


# -- strategy split + pricing ------------------------------------------------

def test_census_strategy_lowered_vs_best():
    """A giant partial-sum output next to a tiny sharded weight: lowered
    pins the all-reduce today's partitioner emits; best takes the
    all-gather-the-weight bound the pricing uses."""
    x = jnp.zeros((64, 4))     # [batch, heads]
    w = jnp.zeros((4, 4096))   # [heads, out] - output dwarfs the weight
    st = _trace_of(lambda x, w: jnp.einsum("bh,ho->bo", x, w),
                   [("batch", "heads"), ("heads", "_o")], x, w)
    lowered = _census(st, ALL_AXES, "lowered")
    best = _census(st, ALL_AXES, "best")
    assert list(lowered) == ["psum"]
    assert list(best) == ["all_gather"]
    assert (best["all_gather"]["model"]["bytes"]
            < lowered["psum"]["model"]["bytes"])
    with pytest.raises(ValueError, match="strategy"):
        _census(st, ALL_AXES, "typo")


def test_implicit_comm_fuses_launches_like_the_combiner():
    """Many tiny same-axis psums price as ONE launch (alpha term), while
    the census keeps the true per-op count."""
    def fn(x, w):
        out = 0.0
        for _ in range(6):
            out = out + jnp.einsum("bh,ho->bo", x, w).sum()
        return out

    st = _trace_of(fn, [("batch", "heads"), ("heads", "_o")],
                   jnp.zeros((8, 4)), jnp.zeros((4, 16)))
    r = spmd.propagate(st, ALL_AXES)
    c = spmd.census(r, ALL_AXES)
    assert sum(s["count"] for s in c["psum"].values()) >= 6
    comm = spmd.implicit_comm(r, ALL_AXES)
    assert comm.count_per_axis["model"] == 1  # combiner-fused
    assert comm.bytes_per_axis["model"] > 0


def test_step_resources_price_implicit_bytes():
    """cost_model wires the propagation into total_comm: a pure-DP tiny
    config's train step prices a nonzero data-axis communication term
    even though its jaxpr contains no manual collective."""
    from homebrewnlp_tpu.analysis import cost_model
    cfg = tiny_config(heads=1, features_per_head=64, tpu_size=2)
    traces = trace_config(cfg, "dp2", steps=("train",), quiet=True)
    imesh = intended_mesh(cfg)
    res = cost_model.step_resources(traces, "train",
                                    traces.steps["train"], imesh)
    assert res.spmd_error == ""
    # the only manual entries are the input sharding constraints; the
    # gradient all-reduce the propagation predicts dwarfs them
    manual = res.comm.bytes_per_axis.get("data", 0)
    implicit = res.implicit_comm.bytes_per_axis["data"]
    assert implicit > 10 * max(manual, 1)
    total = res.total_comm()
    assert total.bytes_per_axis["data"] == manual + implicit
    times = cost_model.step_static_times(res, dict(imesh.shape), "v4")
    assert times["ici_per_axis"]["data"] > 0


# -- the implicit-collective rule --------------------------------------------

@pytest.fixture(scope="module")
def tp2_traces():
    cfg = tiny_config(tpu_size=2)
    return cfg, trace_config(cfg, "tp2", steps=("train", "decode"),
                             quiet=True)


def test_rule_golden_roundtrip_and_drift(tp2_traces, monkeypatch, tmp_path):
    cfg, traces = tp2_traces
    monkeypatch.setattr(spmd, "GOLDENS_DIR", str(tmp_path))
    # missing golden is an error naming the update command
    missing = spmd.check_implicit_collectives(traces)
    assert any(f.severity == "error" and "no spmd golden" in f.message
               for f in missing)
    # record, then a clean re-check
    rec = spmd.check_implicit_collectives(traces, update_goldens=True)
    assert [f.severity for f in rec] == ["info"]
    assert spmd.check_implicit_collectives(traces) == []
    # seeded regression: mis-shard ONE weight (its head axis renamed to
    # batch -> the data axis) via dataclasses.replace — the propagated
    # census drifts and the ratchet must name it
    st = traces.steps["train"]
    idx = next(i for i, names in enumerate(st.in_axes)
               if names and "heads" in names)
    bad_axes = list(st.in_axes)
    bad_axes[idx] = tuple("batch" if n == "heads" else n
                          for n in bad_axes[idx])
    bad_st = dataclasses.replace(st, in_axes=bad_axes)
    bad = dataclasses.replace(traces,
                              steps=dict(traces.steps, train=bad_st))
    findings = spmd.check_implicit_collectives(bad)
    errs = [f for f in findings if f.severity == "error"]
    assert errs and any("implicit" in f.message for f in errs)


def test_rule_conflict_growth_is_an_error(monkeypatch, tmp_path):
    """A clean golden, then a trace whose operands carry conflicting
    shardings: the lint warning fires AND the conflict-count ratchet
    errors."""
    from homebrewnlp_tpu.analysis.trace import ConfigTraces
    monkeypatch.setattr(spmd, "GOLDENS_DIR", str(tmp_path))
    # 4 devices over heads=2 -> intended mesh data2 x model2: BOTH axes
    # live, so the batch-vs-heads mis-seed below genuinely collides
    cfg = tiny_config(heads=2, features_per_head=64, tpu_size=4)
    a, b = jnp.zeros((4, 8)), jnp.zeros((4, 8))
    clean = _trace_of(lambda a, b: a * b,
                      [("batch", "_f"), ("batch", "_f")], a, b)
    wrap = lambda st: ConfigTraces("conflicty", cfg, None, {"train": st},
                                   {}, {}, {})  # noqa: E731
    spmd.check_implicit_collectives(wrap(clean), update_goldens=True)
    assert spmd.check_implicit_collectives(wrap(clean)) == []
    bad = _trace_of(lambda a, b: a * b,
                    [("batch", "_f"), ("heads", "_f")], a, b)
    findings = spmd.check_implicit_collectives(wrap(bad))
    assert any(f.severity == "warning" and "conflicting" in f.message
               for f in findings)
    assert any(f.severity == "error" and "conflicts grew" in f.message
               for f in findings)


def test_committed_spmd_goldens_cover_all_configs():
    names = {os.path.splitext(os.path.basename(p))[0]
             for p in glob.glob(os.path.join(REPO, "configs", "*.json"))}
    have = {os.path.splitext(f)[0]
            for f in os.listdir(os.path.join(
                os.path.dirname(spmd.__file__), "goldens", "spmd"))
            if f.endswith(".json")}
    assert names == have


@pytest.mark.parametrize("path", sorted(glob.glob(
    os.path.join(REPO, "configs", "*.json"))))
def test_committed_spmd_golden_byte_stable(path):
    """Re-deriving each bundled config's implicit census must reproduce
    the committed golden exactly — the propagation is deterministic and
    the goldens are in sync with the tree."""
    name = os.path.splitext(os.path.basename(path))[0]
    raw = json.load(open(path))
    raw.pop("_comment", None)
    cfg = Config(raw)
    traces = trace_config(cfg, name, steps=("train", "decode"), quiet=True)
    golden = json.load(open(spmd.spmd_golden_path(name)))
    imesh = intended_mesh(cfg)
    for step, st in sorted(traces.steps.items()):
        r = spmd.propagate(st, imesh)
        assert spmd._step_golden(r, imesh) == golden["steps"][step], \
            (name, step)


# -- HLO cross-validation ----------------------------------------------------

_HLO_FIXTURE = """
  %all-reduce.1 = f32[2,16]{1,0} all-reduce(f32[2,16]{1,0} %x), replica_groups={{0,1}}
  %all-reduce.2 = (f32[4]{0}, f32[8,2]{1,0}) all-reduce(f32[4]{0} %a, f32[8,2]{1,0} %b)
  %all-gather-start.3 = bf16[32]{0} all-gather-start(bf16[16]{0} %c)
  %fusion.9 = f32[2,16]{1,0} fusion(f32[2,16]{1,0} %y), kind=kLoop
"""


def test_hlo_collective_parser_counts_and_bytes():
    got = spmd.hlo_collectives(_HLO_FIXTURE)
    assert got["all-reduce"]["count"] == 2
    # 2*16*4 + (4*4 + 8*2*4) = 128 + 80
    assert got["all-reduce"]["bytes"] == 208
    assert got["all-gather"] == {"count": 1, "bytes": 64}
    assert "fusion" not in got


def test_compare_hlo_tolerance_edges():
    pred = {"psum": {"model": {"count": 10, "payload_bytes": 1 << 20,
                               "bytes": 1 << 20}}}
    ok = spmd.compare_hlo(pred, {"all-reduce": {"count": 12,
                                                "bytes": 1 << 20}})
    assert ok["ok"], ok["reasons"]
    # presence mismatch: predicted collectives, none lowered
    miss = spmd.compare_hlo(pred, {})
    assert not miss["ok"]
    # empty-empty agrees (1-chip configs)
    assert spmd.compare_hlo({}, {})["ok"]
    # payload out past the ratio + slack
    far = spmd.compare_hlo(pred, {"all-reduce": {
        "count": 10, "bytes": (1 << 20) * 3 + spmd.HLO_BYTES_SLACK * 3}})
    assert any("payload" in r for r in far["reasons"])


def test_validate_hlo_matches_partitioner_tp2(tp2_traces):
    """The honesty check, live: compile the TP-2 tiny train step on CPU
    devices and require census/HLO agreement within tolerance."""
    cfg, traces = tp2_traces
    v = spmd.validate_hlo(traces)
    assert "skipped" not in v, v
    assert v["ok"], v["reasons"]
    assert v["hlo"]["count"] > 0 and v["predicted"]["count"] > 0


def test_validate_hlo_skips_shard_map_structures():
    raw = json.load(open(os.path.join(REPO, "configs",
                                      "8dev_composed_dryrun.json")))
    raw.pop("_comment", None)
    cfg = Config(raw)
    ok, reason = spmd.hlo_compilable(cfg)
    assert not ok and "shard_map" in reason


def test_graftspmd_cli_check_and_json():
    import subprocess
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "graftspmd.py"),
         "--config", os.path.join(REPO, "configs", "bpe65k_1chip.json"),
         "--check", "--json"],
        capture_output=True, text=True, timeout=300)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    rows = json.loads(proc.stdout)
    assert rows[0]["config"] == "bpe65k_1chip"
    assert rows[0]["steps"]["train"]["seeded"]
    assert rows[0]["findings"] == []


def test_graftspmd_cli_rejects_unknown_step():
    import subprocess
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "graftspmd.py"),
         "--config", os.path.join(REPO, "configs", "bpe65k_1chip.json"),
         "--steps", "trian"],
        capture_output=True, text=True, timeout=120)
    assert proc.returncode == 2
    assert "unknown step" in proc.stderr
