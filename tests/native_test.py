"""Native C++ layer: builds, CRC matches the Python implementation, records
readable by the Python reader, text cleaner, BPE train/encode roundtrip and
native-vs-python parity, tooling scripts end-to-end."""
import json
import os
import subprocess
import sys

import numpy as np
import pytest

from homebrewnlp_tpu.data.tfrecord import crc32c as py_crc
from homebrewnlp_tpu.data.tfrecord import decode_example, read_records
from homebrewnlp_tpu.native import (_bpe_encode_py, _bpe_train_py,
                                    _clean_text_py, _stream_to_words,
                                    available, bpe_encode, bpe_train,
                                    clean_text, crc32c, masked_crc,
                                    write_records)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_native_builds():
    assert available(), "C++ toolchain present in image; build must succeed"


def test_crc_matches_python():
    for data in (b"", b"a", b"hello world" * 97, bytes(range(256)) * 33):
        assert crc32c(data) == py_crc(data), data[:16]
    assert crc32c(b"123456789") == 0xE3069283  # crc32c known-answer


def test_native_records_readable(tmp_path):
    p = str(tmp_path / "x.tfrecord")
    payloads = [b"abc", b"d" * 5000, b""]
    write_records(p, payloads)
    assert list(read_records(p, verify=True)) == payloads
    write_records(p, [b"tail"], append=True)
    assert list(read_records(p, verify=True)) == payloads + [b"tail"]


def test_clean_text():
    out = clean_text(b"a\r\nb\rc\x00\x01d\n\n\n\n\ne\tf")
    assert out == b"a\nb\nc d\n\ne\tf".replace(b"c d", b"cd")


def test_clean_text_fallback_parity():
    """The Python fallback must be byte-exact vs the native state machine
    (shards built without a toolchain must match native-built ones)."""
    cases = [b"a\r\nb\rc\x00\x01d\n\n\n\n\ne\tf", b"\n\n\x01\n", b"\r\r\n",
             b"", b"\x1f\x20", bytes(range(64)) * 3]
    for data in cases:
        assert _clean_text_py(data) == clean_text(data), data


def test_bpe_train_finds_frequent_pair():
    # "ababab..." -> first merge must be (97, 98)
    corpus = np.asarray(list(b"ab" * 50) + [-1] + list(b"xy" * 10), np.int32)
    pairs = bpe_train(corpus, 2)
    assert pairs[0].tolist() == [97, 98]
    assert len(pairs) == 2


def test_bpe_native_matches_python_fallback():
    rng = np.random.default_rng(0)
    corpus = rng.integers(0, 8, 500).astype(np.int32)
    corpus[::7] = -1  # lots of word boundaries
    words = _stream_to_words(corpus)
    native_pairs = bpe_train(corpus, 6)
    py_pairs = _bpe_train_py(words, 6, 256)
    np.testing.assert_array_equal(native_pairs, py_pairs)
    toks = rng.integers(0, 8, 100).astype(np.int32)
    np.testing.assert_array_equal(bpe_encode(toks, native_pairs),
                                  _bpe_encode_py(toks.copy(), py_pairs, 256))


def test_bpe_encode_roundtrip_compression():
    corpus = np.asarray(list(b"the cat sat on the mat " * 40), np.int32)
    pairs = bpe_train(corpus, 20)
    enc = bpe_encode(np.asarray(list(b"the cat"), np.int32), pairs)
    assert len(enc) < len(b"the cat")


def test_tooling_scripts_end_to_end(tmp_path):
    corpus = tmp_path / "corpus.txt"
    corpus.write_text("hello world, hello tpu. " * 200)
    tok = tmp_path / "tok.json"
    subprocess.run([sys.executable, os.path.join(REPO, "tools/train_tokenizer.py"),
                    "--input", str(corpus), "--vocab-size", "300",
                    "--output", str(tok)], check=True, capture_output=True)
    vocab = json.loads(tok.read_text())
    assert 0 < len(vocab["merges"]) <= 44
    out_dir = tmp_path / "shards"
    subprocess.run([sys.executable, os.path.join(REPO, "tools/text2tfrecord.py"),
                    "--input", str(corpus), "--output-dir", str(out_dir),
                    "--tokenizer", str(tok), "--procs", "1"],
                   check=True, capture_output=True)
    shards = list(out_dir.glob("*.tfrecord"))
    assert len(shards) == 1
    # filename carries the token count (run-log replay contract)
    n_tokens = int(shards[0].stem.split("_")[-1])
    (payload,) = list(read_records(str(shards[0])))
    ex = decode_example(payload)
    assert len(ex["text"]) == n_tokens
    assert n_tokens < 200 * 24  # BPE compressed below byte count


def test_bpe_encode_preserves_negative_sentinels():
    """Negative tokens (word-boundary sentinels in the train-corpus format)
    must survive encoding unmerged and in place — the heap encoder tracks
    consumption separately from the token values (round-5 regression)."""
    pairs = np.asarray([[1, 2]], np.int32)
    src = np.asarray([1, 2, -1, 1, 2, -7, 3], np.int32)
    want = [256, -1, 256, -7, 3]
    assert bpe_encode(src, pairs).tolist() == want
    assert _bpe_encode_py(src.copy(), pairs, 256).tolist() == want
