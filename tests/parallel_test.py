"""SPMD coverage on the virtual 8-device CPU mesh: mesh factoring, param
sharding placement, sharded train step correctness vs single-device, grad
accumulation equivalence, checkpoint roundtrip."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from homebrewnlp_tpu.parallel import make_mesh, param_shardings, spec_for
from homebrewnlp_tpu.parallel.mesh import DATA_AXIS, MODEL_AXIS, axis_sizes
from homebrewnlp_tpu.train import Checkpointer, Trainer

from .backend import mixer_config, text_batch


def test_axis_sizes_factoring():
    cfg = mixer_config()  # heads=4
    sizes = axis_sizes(cfg, 8)
    assert sizes[MODEL_AXIS] == 4 and sizes[DATA_AXIS] == 2
    # non-divisible head count shrinks the model axis — and the shrunk axis
    # must still divide the head count (else params can't be placed)
    cfg3 = mixer_config(heads=3, features_per_head=32)
    sizes3 = axis_sizes(cfg3, 8)
    assert sizes3[MODEL_AXIS] * sizes3[DATA_AXIS] == 8
    assert cfg3.heads % sizes3[MODEL_AXIS] == 0


def test_spec_rules(eight_devices):
    cfg = mixer_config()
    mesh = make_mesh(cfg)
    assert spec_for(("batch", "sequence", "heads", "features_per_head"), mesh
                    ) == jax.sharding.PartitionSpec("data", None, "model")
    # anonymized axes are replicated
    assert spec_for(("_sequence", "heads"), mesh
                    ) == jax.sharding.PartitionSpec(None, "model")


def test_params_shard_over_model_axis(eight_devices):
    cfg = mixer_config(train_batch_size=4)
    mesh = make_mesh(cfg)
    trainer = Trainer(cfg, mesh)
    batch = text_batch(cfg)
    state = trainer.init(batch)
    shardings = param_shardings(trainer.axes, mesh)
    head_sharded = [k for k, names in trainer.axes.items() if "heads" in names]
    assert head_sharded, "expected head-axis parameters"
    for k in head_sharded:
        v = state.params[k]
        n_shards = len({d for shard in v.addressable_shards for d in [shard.device]})
        assert n_shards == 8, k
        # shard shape smaller than global along the head axis
        hidx = trainer.axes[k].index("heads")
        assert v.addressable_shards[0].data.shape[hidx] * 4 == v.shape[hidx], k


def test_sharded_training_decreases_loss(eight_devices):
    cfg = mixer_config(train_batch_size=4, depth=1,
                       optimizer="adaptive_clip:0.003-sm3-momentum:0.9:1:1-learning_rate",
                       learning_rate=3e-3)
    trainer = Trainer(cfg)
    batch = text_batch(cfg)
    state = trainer.init(batch)
    first = last = None
    for i in range(10):
        state, metrics = trainer.step(state, batch, jax.random.key(i))
        last = float(metrics["loss"])
        if first is None:
            first = last
    assert last < first, (first, last)
    assert int(state.step) == 10


def test_grad_accumulation_matches_large_batch(eight_devices):
    """accum=2 over batch 4 must match accum=1 on the same 4 samples (mean
    loss path), to tolerance of micro-batch RNG differences (dropout off)."""
    base = dict(depth=1, optimizer="learning_rate", learning_rate=1e-2,
                weight_decay=0.0, input_dropout=0.0)
    cfg_big = mixer_config(train_batch_size=4, grad_accumulation=1, **base)
    cfg_acc = mixer_config(train_batch_size=2, grad_accumulation=2,
                           macro_batching=2, **base)

    batch = text_batch(cfg_big)  # batch axis 4
    t_big = Trainer(cfg_big)
    s_big = t_big.init(batch)
    t_acc = Trainer(cfg_acc)
    s_acc = t_acc.init(batch)

    s_big, m_big = t_big.step(s_big, batch, jax.random.key(0))
    s_acc, m_acc = t_acc.step(s_acc, batch, jax.random.key(0))

    for k in s_big.params:
        np.testing.assert_allclose(np.asarray(s_big.params[k]),
                                   np.asarray(s_acc.params[k]),
                                   rtol=2e-4, atol=2e-6, err_msg=k)


def test_checkpoint_roundtrip(tmp_path, eight_devices):
    cfg = mixer_config(train_batch_size=4, depth=1)
    trainer = Trainer(cfg)
    batch = text_batch(cfg)
    state = trainer.init(batch)
    state, _ = trainer.step(state, batch, jax.random.key(0))
    ckpt = Checkpointer(str(tmp_path / "ckpt"))
    ckpt.save(state, data_state={"file_idx": 3, "skip": 17})
    ckpt.wait()

    trainer2 = Trainer(cfg)
    template = trainer2.init(batch)
    restored, data_state = Checkpointer(str(tmp_path / "ckpt")).restore(template)
    assert int(restored.step) == 1
    assert data_state == {"file_idx": 3, "skip": 17}
    for k in state.params:
        np.testing.assert_array_equal(np.asarray(state.params[k]),
                                      np.asarray(restored.params[k]), err_msg=k)


def test_macro_batching_semantics(eight_devices):
    """macro_batching=2: host batch is inflated 2x, ONE update per step from
    averaged grads (matching a single big batch), the step counter advances by
    macro_batching (reference run.py:155-156), and first/last/mean losses are
    reported (reference train.py:48-52)."""
    base = dict(depth=1, optimizer="learning_rate", learning_rate=1e-2,
                weight_decay=0.0, input_dropout=0.0,
                weight_standardisation=False)
    cfg_big = mixer_config(train_batch_size=4, **base)
    cfg_mac = mixer_config(train_batch_size=2, macro_batching=2,
                           macro_batch_loss_smoothing=True, **base)

    batch = text_batch(cfg_big)  # 4 rows = 2 * macro_batching
    t_big, t_mac = Trainer(cfg_big), Trainer(cfg_mac)
    s_big = t_big.init(batch)
    s_mac = t_mac.init(batch)

    s_big, m_big = t_big.step(s_big, batch, jax.random.key(0))
    s_mac, m_mac = t_mac.step(s_mac, batch, jax.random.key(0))

    assert int(s_mac.step) == 2 and int(s_big.step) == 1
    assert "first_loss" in m_mac and "last_loss" in m_mac
    # smoothing=True: reported loss is the mean over micro-batches
    np.testing.assert_allclose(
        float(m_mac["loss"]),
        (float(m_mac["first_loss"]) + float(m_mac["last_loss"])) / 2, rtol=1e-5)
    # aux metrics survive accumulation (round-1 weakness)
    assert "token_loss" in m_mac and "accuracy" in m_mac
    for k in s_big.params:
        np.testing.assert_allclose(np.asarray(s_big.params[k]),
                                   np.asarray(s_mac.params[k]),
                                   rtol=2e-4, atol=2e-6, err_msg=k)


def test_macro_loss_smoothing_off_reports_last(eight_devices):
    cfg = mixer_config(train_batch_size=2, macro_batching=2,
                       macro_batch_loss_smoothing=False, depth=1,
                       optimizer="learning_rate", weight_decay=0.0)
    trainer = Trainer(cfg)
    batch = text_batch(cfg)
    state = trainer.init(batch)
    _, m = trainer.step(state, batch, jax.random.key(0))
    np.testing.assert_allclose(float(m["loss"]), float(m["last_loss"]),
                               rtol=1e-6)


def test_weight_standardisation(eight_devices):
    """Large weights stay zero-mean with their norm preserved after updates."""
    from homebrewnlp_tpu.optim import is_large_tensor
    cfg = mixer_config(train_batch_size=2, depth=1,
                       optimizer="adaptive_clip:0.003-sm3-momentum:0.9:1:1-learning_rate",
                       learning_rate=1e-3, weight_standardisation=True)
    trainer = Trainer(cfg)
    batch = text_batch(cfg)
    state = trainer.init(batch)
    for i in range(3):
        state, m = trainer.step(state, batch, jax.random.key(i))
    checked = 0
    for name, v in state.params.items():
        if is_large_tensor(name, trainer.axes.get(name, ()),
                           int(v.size), cfg):
            arr = np.asarray(v, np.float32)
            assert abs(arr.mean()) < 1e-3 * (abs(arr).mean() + 1e-8), name
            checked += 1
    assert checked, "no large tensors found"
    assert np.isfinite(float(m["loss"]))


def test_debug_gradients_metrics(eight_devices):
    cfg = mixer_config(train_batch_size=2, depth=1, debug_gradients=True)
    trainer = Trainer(cfg)
    batch = text_batch(cfg)
    state = trainer.init(batch)
    _, m = trainer.step(state, batch, jax.random.key(0))
    per_var = [k for k in m if k.startswith("grad_norm/")]
    assert len(per_var) == len(state.params)
    total = np.sqrt(sum(float(m[k]) ** 2 for k in per_var))
    np.testing.assert_allclose(total, float(m["grad_norm"]), rtol=1e-4)


def test_checkpoint_master_dtype_roundtrip(tmp_path, eight_devices):
    """storage_dtype is the checkpoint master copy: saving with a bf16 master
    halves checkpoint size and restores back onto the f32 device slices
    (MTF master/slice split, reference dataclass.py:253-255)."""
    cfg = mixer_config(train_batch_size=4, depth=1)
    trainer = Trainer(cfg)
    batch = text_batch(cfg)
    state = trainer.init(batch)
    state, _ = trainer.step(state, batch, jax.random.key(0))
    ckpt = Checkpointer(str(tmp_path / "ckpt"))
    ckpt.save(state, master_dtype=jnp.bfloat16)
    ckpt.wait()

    template = Trainer(cfg).init(batch)
    restored, _ = Checkpointer(str(tmp_path / "ckpt")).restore(template)
    for k, v in restored.params.items():
        assert v.dtype == template.params[k].dtype, k
        np.testing.assert_allclose(
            np.asarray(state.params[k], np.float32),
            np.asarray(v, np.float32), rtol=8e-3, atol=1e-5, err_msg=k)


def _routed_cfg(**over):
    base = dict(model_mode="gpt", use_video=False, sequence_length=16,
                heads=2, features_per_head=32, vocab_size=64, depth=1,
                train_batch_size=8, experts=4, calc_accuracy=False,
                memory_reduction_strategy="none", weight_decay=0.0,
                optimizer="adam-learning_rate", learning_rate=1e-2,
                intermediate_feed_forward_multiplier_multiplier=0.5,
                block_config=[{"layer": ["norm-shift-scale",
                                         "routed_moe-topk2-capacity8"]}])
    base.update(over)
    from homebrewnlp_tpu.config import Config
    return Config(base)


def test_routed_moe_identical_experts_reduce_to_ffn(eight_devices):
    """With every expert holding the same weights and ample capacity, the
    routed layer must equal a single FFN exactly (combine weights are
    normalized over the selected k)."""
    import jax.numpy as jnp
    from homebrewnlp_tpu.models import build, init_params
    from homebrewnlp_tpu.models.ctx import Ctx
    cfg = _routed_cfg()
    batch = text_batch(cfg)
    params, axes = init_params(cfg, batch)
    w_in = [k for k in params if "routed_moe" in k and "orthogonal_var/" in k]
    w_out = [k for k in params if "routed_moe" in k and "orthogonal_var1/" in k]
    assert w_in and w_out, sorted(k for k in params if "routed" in k)
    for k in w_in + w_out:  # tile expert 0 across the expert axis
        v = params[k]
        params[k] = jnp.broadcast_to(v[:1], v.shape)

    # capture the layer's input/output via the registry
    from homebrewnlp_tpu.models import registry
    from homebrewnlp_tpu.models import layers as L
    rec = {}
    orig = registry.LAYER_FUNCTIONS["routed_moe"]
    def spy(args):
        out = orig(args)
        rec["in"], rec["out"] = args.tensor, out
        return out
    registry.LAYER_FUNCTIONS["routed_moe"] = spy
    try:
        ctx = Ctx(cfg, params=params, train=False, rng=jax.random.key(0))
        build(ctx, batch)
    finally:
        registry.LAYER_FUNCTIONS["routed_moe"] = orig

    x = np.asarray(rec["in"].x, np.float32)          # [b, s, h, k]
    wi = np.asarray(params[w_in[0]], np.float32)     # [E, h, k, m]
    wo = np.asarray(params[w_out[0]], np.float32)    # [E, m, h, k]
    h = np.maximum(np.einsum("bshk,hkm->bsm", x, wi[0]), 0)
    want = np.einsum("bsm,mhk->bshk", h, wo[0])
    got = np.asarray(rec["out"].transpose_to(rec["in"].names).x, np.float32)
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-4)


def test_routed_moe_expert_parallel_training(eight_devices):
    """Expert weights shard over the DATA axis; the sharded step trains."""
    cfg = _routed_cfg(train_batch_size=8)
    mesh = make_mesh(cfg)
    assert mesh.shape["data"] == 4 and mesh.shape["model"] == 2
    trainer = Trainer(cfg, mesh)
    batch = text_batch(cfg)
    state = trainer.init(batch)
    expert_keys = [k for k, names in trainer.axes.items()
                   if "routed_experts" in names]
    assert expert_keys
    for k in expert_keys:
        v = state.params[k]
        idx = trainer.axes[k].index("routed_experts")
        # expert axis (size 4) split over the 4-way data axis
        assert v.addressable_shards[0].data.shape[idx] * 4 == v.shape[idx], k
    first = last = None
    for i in range(8):
        state, m = trainer.step(state, batch, jax.random.key(i))
        last = float(m["loss"])
        first = first if first is not None else last
    assert np.isfinite(last) and last < first, (first, last)


def test_routed_moe_balance_loss_collected(eight_devices):
    """The Switch balance aux loss rides ctx.aux_losses into the total loss
    for non-reversible bodies; weight 0 disables it exactly."""
    from homebrewnlp_tpu.models import build, init_params
    from homebrewnlp_tpu.models.ctx import Ctx
    cfg_on = _routed_cfg(moe_balance_weight=0.5)
    cfg_off = _routed_cfg(moe_balance_weight=0.0)
    batch = text_batch(cfg_on)
    params, _ = init_params(cfg_on, batch)
    ctx_on = Ctx(cfg_on, params=params, train=True, rng=jax.random.key(0))
    out_on = build(ctx_on, batch)
    assert len(ctx_on.aux_losses) == 1
    ctx_off = Ctx(cfg_off, params=params, train=True, rng=jax.random.key(0))
    out_off = build(ctx_off, batch)
    assert not ctx_off.aux_losses
    delta = float(out_on.loss) - float(out_off.loss)
    # balance term ~= weight * (E * sum f*p / topk); positive, order weight
    assert 0.1 < delta < 1.5, delta


def test_routed_moe_balance_loss_under_checkpoint(eight_devices):
    """The balance aux loss threads through jax.checkpoint as a real block
    output: same total loss as strategy 'none', and its gradient reaches the
    router weights."""
    from homebrewnlp_tpu.models import build, init_params
    from homebrewnlp_tpu.models.ctx import Ctx
    cfg_none = _routed_cfg(moe_balance_weight=0.5)
    cfg_ckpt = _routed_cfg(moe_balance_weight=0.5,
                           memory_reduction_strategy="checkpoint")
    batch = text_batch(cfg_none)
    params, _ = init_params(cfg_none, batch)

    def loss_fn(cfg):
        def f(p):
            return build(Ctx(cfg, params=p, train=True,
                             rng=jax.random.key(0)), batch).loss
        return f

    l_none = float(jax.jit(loss_fn(cfg_none))(params))
    l_ckpt = float(jax.jit(loss_fn(cfg_ckpt))(params))
    np.testing.assert_allclose(l_ckpt, l_none, rtol=1e-5)

    g_none = jax.jit(jax.grad(loss_fn(cfg_none)))(params)
    g_ckpt = jax.jit(jax.grad(loss_fn(cfg_ckpt)))(params)
    router = [k for k in params if "router" in k]
    assert router, sorted(params)
    for k in g_none:
        np.testing.assert_allclose(np.asarray(g_ckpt[k]),
                                   np.asarray(g_none[k]),
                                   rtol=2e-4, atol=2e-5, err_msg=k)
    assert any(float(np.abs(np.asarray(g_ckpt[k])).max()) > 0
               for k in router)


def test_routed_moe_rejects_reversible_strategies():
    """revnet/momentum would silently drop the balance aux loss — the config
    must reject the combination unless the weight is zero."""
    for strategy in ("revnet", "momentum"):
        with pytest.raises(ValueError, match="custom_vjp"):
            _routed_cfg(memory_reduction_strategy=strategy, depth=2)
    # weight 0: nothing to drop, combination allowed
    cfg = _routed_cfg(memory_reduction_strategy="revnet", depth=2,
                      moe_balance_weight=0.0)
    assert cfg.memory_reduction_strategy == "revnet"


def _pipe_base(**overrides):
    """Shared tiny-gpt config dict for the pipeline-parallel tests."""
    base = dict(model_mode="gpt", use_video=False, sequence_length=16,
                heads=1, features_per_head=32, vocab_size=64, depth=2,
                train_batch_size=8, memory_reduction_strategy="none",
                weight_decay=0.0, optimizer="adam-learning_rate",
                learning_rate=1e-2, calc_accuracy=False,
                intermediate_feed_forward_multiplier_multiplier=0.5,
                block_config=[{"layer": ["norm-shift-scale",
                                         "feed_forward-in:relu"]}])
    base.update(overrides)
    return base


def test_pipeline_parallel_parity_and_training(eight_devices):
    """GPipe pipelined body (pipeline_parallel=4 on a data x pipe mesh) must
    match the sequential body exactly — same flat params, same loss, same
    grads — and train."""
    from homebrewnlp_tpu.config import Config
    from homebrewnlp_tpu.models import build, init_params
    from homebrewnlp_tpu.models.ctx import Ctx
    base = _pipe_base(depth=4)
    from homebrewnlp_tpu.models import (stack_pipeline_params,
                                        unstack_pipeline_params)
    cfg1 = Config(dict(base))
    cfgp = Config(dict(base, pipeline_parallel=4))
    batch = text_batch(cfg1)
    params, _ = init_params(cfg1, batch)
    # stage-stacked layout: roundtrip must be exact
    paramsP = stack_pipeline_params(cfgp, params)
    assert set(unstack_pipeline_params(cfgp, paramsP)) == set(params)
    for k, v in unstack_pipeline_params(cfgp, paramsP).items():
        np.testing.assert_array_equal(np.asarray(v), np.asarray(params[k]), err_msg=k)
    meshp = make_mesh(cfgp)
    assert meshp.shape["pipeline"] == 4

    def loss1(p, b):
        return build(Ctx(cfg1, params=p, train=True,
                         rng=jax.random.key(0)), b).loss

    def lossp(p, b):
        return build(Ctx(cfgp, params=p, train=True, rng=jax.random.key(0),
                         mesh=meshp), b).loss

    l1 = float(jax.jit(loss1)(params, batch))
    with meshp:
        lp = float(jax.jit(lossp)(paramsP, batch))
    np.testing.assert_allclose(lp, l1, rtol=1e-5)

    g1 = jax.jit(jax.grad(loss1))(params, batch)
    with meshp:
        gp = unstack_pipeline_params(
            cfgp, jax.jit(jax.grad(lossp))(paramsP, batch))
    for k in g1:
        np.testing.assert_allclose(np.asarray(gp[k]), np.asarray(g1[k]),
                                   rtol=2e-4, atol=2e-5, err_msg=k)

    # end-to-end training on the pipelined mesh: body params + optimizer
    # slots must live 1/P per device (true per-stage residency)
    from homebrewnlp_tpu.parallel.mesh import PIPE_AXIS
    trainer = Trainer(cfgp, meshp)
    state = trainer.init(batch)
    stacked_keys = [k for k in state.params if "/body/@d" in k]
    assert stacked_keys
    for k in stacked_keys:
        v = state.params[k]
        assert v.sharding.spec[0] == PIPE_AXIS, (k, v.sharding)
        assert v.addressable_shards[0].data.shape[0] * 4 == v.shape[0], k
        for slot in state.opt_state[k].values():
            assert slot.sharding.spec[:1] == (PIPE_AXIS,), (k, slot.sharding)
    first = last = None
    for i in range(6):
        state, m = trainer.step(state, batch, jax.random.key(i))
        last = float(m["loss"])
        first = first if first is not None else last
    assert last < first, (first, last)


def test_pipeline_parallel_config_validation():
    from homebrewnlp_tpu.config import Config
    base = _pipe_base(depth=4,
                      block_config=[{"layer": ["feed_forward-in:relu"]}])
    del base["memory_reduction_strategy"]  # each case sets its own
    with pytest.raises(ValueError, match="divide depth"):
        Config(dict(base, pipeline_parallel=3,
                    memory_reduction_strategy="none"))
    with pytest.raises(ValueError, match="memory_reduction_strategy"):
        Config(dict(base, pipeline_parallel=2,
                    memory_reduction_strategy="revnet"))
    # cross-depth 'shared' weights COMPOSE with pipelining since round 4
    # (stage-replicated, grad-synced — test_pipeline_shared_weights_parity)
    Config(dict(base, pipeline_parallel=2,
                memory_reduction_strategy="none",
                block_config=[{"layer": [
                    "attention-biased_attention_map-absolute-input_as_value-shared"]}]))
    with pytest.raises(ValueError, match="routed_moe"):
        Config(dict(base, pipeline_parallel=2, experts=4,
                    memory_reduction_strategy="none",
                    block_config=[{"layer": ["routed_moe-topk2"]}]))
    with pytest.raises(ValueError, match="text"):
        Config(dict(base, pipeline_parallel=2, model_mode="jannet",
                    use_video=True, memory_reduction_strategy="none",
                    frame_height=32, frame_width=32, patch_size=16,
                    experts=1))


def test_pipeline_parallel_checkpoint_strategy(eight_devices):
    """The remat branch (memory_reduction_strategy=checkpoint) composes with
    the pipelined body and still matches the sequential model."""
    from homebrewnlp_tpu.config import Config
    from homebrewnlp_tpu.models import build, init_params
    from homebrewnlp_tpu.models.ctx import Ctx
    base = _pipe_base()
    from homebrewnlp_tpu.models import (stack_pipeline_params,
                                        unstack_pipeline_params)
    cfg1 = Config(dict(base, memory_reduction_strategy="none"))
    cfgp = Config(dict(base, memory_reduction_strategy="checkpoint",
                       pipeline_parallel=2))
    batch = text_batch(cfg1)
    params, _ = init_params(cfg1, batch)
    paramsP = stack_pipeline_params(cfgp, params)
    meshp = make_mesh(cfgp)

    def loss1(p, b):
        return build(Ctx(cfg1, params=p, train=True,
                         rng=jax.random.key(0)), b).loss

    def lossp(p, b):
        return build(Ctx(cfgp, params=p, train=True, rng=jax.random.key(0),
                         mesh=meshp), b).loss

    l1 = float(jax.jit(loss1)(params, batch))
    with meshp:
        lp = float(jax.jit(lossp)(paramsP, batch))
        gp = unstack_pipeline_params(
            cfgp, jax.jit(jax.grad(lossp))(paramsP, batch))
    np.testing.assert_allclose(lp, l1, rtol=1e-5)
    g1 = jax.jit(jax.grad(loss1))(params, batch)
    for k in g1:
        np.testing.assert_allclose(np.asarray(gp[k]), np.asarray(g1[k]),
                                   rtol=2e-4, atol=2e-5, err_msg=k)


def test_pipeline_checkpoint_roundtrip_and_decode(eight_devices, tmp_path):
    """Stage-stacked checkpoints save/restore exactly, and the serving engine
    flattens the stacked layout for the plain decode chain."""
    from homebrewnlp_tpu.config import Config
    from homebrewnlp_tpu.serve.interface import CompletionEngine
    cfgp = Config(_pipe_base(pipeline_parallel=2))
    batch = text_batch(cfgp)
    trainer = Trainer(cfgp)
    state = trainer.init(batch)
    state, _ = trainer.step(state, batch, jax.random.key(0))
    ckpt = Checkpointer(str(tmp_path / "pipe_ckpt"))
    ckpt.save(state, data_state={"pos": 1})
    ckpt.wait()

    trainer2 = Trainer(cfgp)
    template = trainer2.init(batch)
    restored, data_state = Checkpointer(str(tmp_path / "pipe_ckpt")).restore(template)
    assert data_state == {"pos": 1}
    for k in state.params:
        np.testing.assert_array_equal(np.asarray(state.params[k]),
                                      np.asarray(restored.params[k]), err_msg=k)
        np.testing.assert_array_equal(
            np.asarray(restored.params[k].sharding.spec),
            np.asarray(state.params[k].sharding.spec), err_msg=k)

    # the engine must accept the stage-stacked layout directly
    host_params = {k: jnp.asarray(np.asarray(v))
                   for k, v in restored.params.items()}
    engine = CompletionEngine(cfgp, host_params)
    out = engine.complete_tokens([1, 2, 3], temperature=0.0, max_tokens=4)
    assert len(out) >= 7


def test_pipeline_with_grad_accumulation(eight_devices):
    """GPipe composes with the micro-batch accumulation scan: the pipelined
    trainer under grad_accumulation=2 must track the non-pipelined trainer's
    loss trajectory exactly (pipeline is an exact execution strategy, not an
    approximation)."""
    from homebrewnlp_tpu.config import Config
    base = _pipe_base(grad_accumulation=2)
    losses = {}
    for name, cfg in (("plain", Config(dict(base))),
                      ("piped", Config(dict(base, pipeline_parallel=2)))):
        trainer = Trainer(cfg)
        batch = text_batch(cfg)
        state = trainer.init(batch)
        ls = []
        for i in range(4):
            state, m = trainer.step(state, batch, jax.random.key(7))
            ls.append(float(m["loss"]))
        losses[name] = ls
    np.testing.assert_allclose(losses["piped"], losses["plain"], rtol=2e-5)
    assert losses["piped"][-1] < losses["piped"][0]


_BF16_PIPE_SNIPPET = """
import os
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=8")
import jax
jax.config.update("jax_platforms", "cpu")
from homebrewnlp_tpu.config import Config
from homebrewnlp_tpu.train import Trainer
from homebrewnlp_tpu.utils import random_text_batch
cfg = Config(dict(model_mode="gpt", use_video=False, sequence_length=16,
                  heads=1, features_per_head=32, vocab_size=64, depth=2,
                  train_batch_size=8, memory_reduction_strategy="none",
                  weight_decay=0.0, optimizer="adam-learning_rate",
                  learning_rate=1e-2, calc_accuracy=False,
                  pipeline_parallel=2, pipeline_schedule="SCHED",
                  calculation_dtype="bfloat16", storage_dtype="bfloat16",
                  intermediate_feed_forward_multiplier_multiplier=0.5,
                  block_config=[{"layer": ["norm-shift-scale",
                                           "feed_forward-in:relu"]}]))
tr = Trainer(cfg)
batch = random_text_batch(cfg)
state = tr.init(batch)
import math
for i in range(3):
    state, m = tr.step(state, batch, jax.random.key(i))
    assert math.isfinite(float(m["loss"])), m
print("BF16_PIPE_OK", float(m["loss"]))
"""


def _run_bf16_pipe(schedule: str):
    import os
    import subprocess
    import sys
    return subprocess.run(
        [sys.executable, "-c", _BF16_PIPE_SNIPPET.replace("SCHED", schedule)],
        capture_output=True, text=True, timeout=600,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def test_bf16_pipeline_probe():
    """Half-precision GPipe training (VERDICT r2 item 7).  XLA:CPU
    currently CHECK-aborts compiling a bf16 copy inside the gpipe autodiff
    backward's manual shard_map region ('Invalid binary instruction opcode
    copy', re-probed on jax 0.9/2026-07) and the bench env has a single
    real chip (a pipe axis needs >= 2).  The probe runs in a subprocess:
    the day the toolchain fixes the abort, this test STOPS skipping and
    becomes real bf16-gpipe coverage.  (The 1F1B schedule already runs
    bf16 pipelines — see test_bf16_pipeline_1f1b below.)"""
    proc = _run_bf16_pipe("gpipe")
    if proc.returncode != 0:
        blob = proc.stdout + proc.stderr
        assert ("Invalid binary instruction opcode" in blob
                or "Check failed" in blob), blob[-2000:]
        pytest.skip("XLA:CPU still aborts on bf16 gpipe copies "
                    "(known compiler limitation; f32 pipeline is covered)")
    assert "BF16_PIPE_OK" in proc.stdout


def test_bf16_pipeline_1f1b():
    """REAL half-precision pipelined training: the 1F1B schedule's
    vjp-per-tick backward avoids the transposed-scan bf16 copy that
    CHECK-aborts XLA:CPU under gpipe, so bf16-in-the-pipe finally executes
    (VERDICT r3 'missing' item 3) — no skip."""
    proc = _run_bf16_pipe("1f1b")
    assert proc.returncode == 0, (proc.stdout + proc.stderr)[-2000:]
    assert "BF16_PIPE_OK" in proc.stdout


def test_gpipe_op_matches_sequential(eight_devices):
    """ops/pipeline.gpipe against the plain sequential composition: exact
    forward and gradients, microbatch count != stage count."""
    from jax.sharding import Mesh

    from homebrewnlp_tpu.ops.pipeline import gpipe
    devices = np.asarray(jax.devices()[:8]).reshape(2, 4)
    mesh = Mesh(devices, ("data", "pipeline"))
    P, D, B = 4, 16, 8

    ws = jax.random.normal(jax.random.key(0), (P, D, D), jnp.float32) * 0.4
    x = jax.random.normal(jax.random.key(1), (B, D), jnp.float32)

    def stage_fn(w, idx, xm):
        return jax.nn.relu(xm @ w)

    def loss_pipe(ws, x):
        y = gpipe(stage_fn, ws, x, P, n_micro=8, mesh=mesh, axis="pipeline")
        return jnp.sum(y.astype(jnp.float32) ** 2)

    def loss_seq(ws, x):
        y = x
        for i in range(P):
            y = jax.nn.relu(y @ ws[i])
        return jnp.sum(y ** 2)

    with mesh:
        lp = float(jax.jit(loss_pipe)(ws, x))
        gp = jax.jit(jax.grad(loss_pipe))(ws, x)
    ls = float(jax.jit(loss_seq)(ws, x))
    gs = jax.jit(jax.grad(loss_seq))(ws, x)
    np.testing.assert_allclose(lp, ls, rtol=1e-5)
    np.testing.assert_allclose(np.asarray(gp), np.asarray(gs),
                               rtol=1e-4, atol=1e-5)


def test_pipeline_flat_checkpoint_migration(eight_devices, tmp_path):
    """Checkpoints written before stage-stacked pipeline residency (flat
    per-depth params + flat optimizer slots) restore into the stacked
    template via the one-time migration in Checkpointer.restore."""
    import zlib

    from homebrewnlp_tpu.config import Config
    from homebrewnlp_tpu.models import init_params, stack_pipeline_params
    from homebrewnlp_tpu.optim import Optimizer
    from homebrewnlp_tpu.train.state import TrainState

    cfgp = Config(_pipe_base(pipeline_parallel=2))
    batch = text_batch(cfgp)
    params, axes = init_params(cfgp, batch)  # flat per-depth layout

    # distinct constant per (param, slot) leaf so the migration's key mapping
    # is actually verified, not just its shapes
    opt_state = {
        name: {slot: jnp.full(v.shape, zlib.crc32((name + slot).encode())
                              % 1000 / 100.0, v.dtype)
               for slot, v in slots.items()}
        for name, slots in Optimizer(cfgp, axes).init(params).items()}
    flat_state = TrainState(params, opt_state, jnp.asarray(7, jnp.int32))
    ckpt = Checkpointer(str(tmp_path / "flat_ckpt"))
    ckpt.save(flat_state, data_state={"pos": 2})
    ckpt.wait()

    trainer = Trainer(cfgp)
    template = trainer.init(batch)
    assert set(template.params) != set(params)  # layouts genuinely differ
    restored, data_state = Checkpointer(str(tmp_path / "flat_ckpt")).restore(
        template, cfgp)
    assert data_state == {"pos": 2}
    assert int(restored.step) == 7

    want_params = stack_pipeline_params(cfgp, params)
    want_opt = stack_pipeline_params(cfgp, opt_state)
    for k in template.params:
        np.testing.assert_array_equal(np.asarray(restored.params[k]),
                                      np.asarray(want_params[k]), err_msg=k)
        assert (restored.params[k].sharding.spec
                == template.params[k].sharding.spec), k
        for slot in template.opt_state[k]:
            np.testing.assert_array_equal(
                np.asarray(restored.opt_state[k][slot]),
                np.asarray(want_opt[k][slot]), err_msg=f"{k}:{slot}")

    # the migrated state must actually train
    state2, metrics = trainer.step(restored, batch, jax.random.key(0))
    assert int(state2.step) == 8
    assert np.isfinite(float(metrics["loss"]))


def test_pipeline_shared_weights_parity_and_sync(eight_devices):
    """VERDICT r3 item 5: the flagship 32big_mixer block DSL (cross-depth
    'shared' mixer maps) trains under pipeline_parallel=2 with exact parity
    vs the sequential body, and the per-stage shared replicas stay
    bit-identical across optimizer updates."""
    from homebrewnlp_tpu.config import PIPE_STAGE, Config
    from homebrewnlp_tpu.models import (build, init_params,
                                        stack_pipeline_params,
                                        sync_shared_pipeline_grads,
                                        unstack_pipeline_params)
    from homebrewnlp_tpu.models.ctx import Ctx
    from .backend import mixer_config

    base = dict(mixer_config(depth=4).dict())
    cfg1 = Config(dict(base, memory_reduction_strategy="none"))
    cfgp = Config(dict(base, memory_reduction_strategy="none",
                       pipeline_parallel=2))
    batch = text_batch(cfg1)
    params, axes = init_params(cfg1, batch)
    assert any("/shared_" in k for k in params)
    paramsP, axesP = stack_pipeline_params(cfgp, params, axes)
    shared_keys = [k for k in paramsP
                   if "/shared_" in k and axesP[k][0] == PIPE_STAGE]
    assert shared_keys
    meshp = make_mesh(cfgp)

    def loss1(p, b):
        return build(Ctx(cfg1, params=p, train=True,
                         rng=jax.random.key(0)), b).loss

    def lossp(p, b):
        return build(Ctx(cfgp, params=p, train=True, rng=jax.random.key(0),
                         mesh=meshp), b).loss

    l1 = float(jax.jit(loss1)(params, batch))
    with meshp:
        lp = float(jax.jit(lossp)(paramsP, batch))
        gp_raw = jax.jit(jax.grad(lossp))(paramsP, batch)
        gp_sync = sync_shared_pipeline_grads(cfgp, gp_raw, axesP)
    np.testing.assert_allclose(lp, l1, rtol=1e-5)
    g1 = jax.jit(jax.grad(loss1))(params, batch)
    gp = unstack_pipeline_params(cfgp, gp_sync)
    for k in g1:
        np.testing.assert_allclose(np.asarray(gp[k], np.float32),
                                   np.asarray(g1[k], np.float32),
                                   rtol=2e-4, atol=2e-5, err_msg=k)

    # end-to-end: Trainer on the pipe mesh; shared replicas stay bit-synced
    trainer = Trainer(cfgp)
    state = trainer.init(batch)
    for i in range(3):
        state, m = trainer.step(state, batch, jax.random.key(i))
    assert np.isfinite(float(m["loss"]))
    for k in shared_keys:
        v = np.asarray(state.params[k])
        for s in range(1, v.shape[0]):
            np.testing.assert_array_equal(v[0], v[s], err_msg=k)
        slots = state.opt_state[k]
        for sk, sv in slots.items():
            sv = np.asarray(sv)
            for s in range(1, sv.shape[0]):
                np.testing.assert_array_equal(sv[0], sv[s],
                                              err_msg=f"{k}:{sk}")


def test_pipeline_1f1b_op_parity(eight_devices):
    """1F1B combined loss-and-grad schedule (ops/pipeline.py): loss and all
    three gradient groups (stage weights, tail params, input cotangent)
    match the sequential composition exactly."""
    from jax.sharding import Mesh

    from homebrewnlp_tpu.ops.pipeline import pipeline_1f1b

    P, M, B, D = 4, 8, 16, 32
    mesh = Mesh(np.array(jax.devices()[:P]), ("pipeline",))
    rng = np.random.RandomState(0)
    ws = jnp.asarray(rng.standard_normal((P, D, D)).astype(np.float32) * 0.3)
    wt = jnp.asarray(rng.standard_normal((D,)).astype(np.float32))
    x = jnp.asarray(rng.standard_normal((B, D)).astype(np.float32))
    tgt = jnp.asarray(rng.standard_normal((B, D)).astype(np.float32))

    def stage_fn(w, idx, xm):
        # tiny per-stage aux loss exercises the stage aux stream end to end
        return jax.nn.relu(xm @ w), 1e-3 * jnp.mean(xm.astype(jnp.float32) ** 2)

    def tail_fn(wt, y, t):
        loss = jnp.mean((y * wt - t) ** 2)
        return loss, {"mae": jnp.mean(jnp.abs(y * wt - t))}

    def run(ws, wt, x, tgt):
        with mesh:
            return pipeline_1f1b(stage_fn, tail_fn, ws, wt, x, (tgt,),
                                 P, M, mesh)

    loss, aux, dws, dwt, dx = jax.jit(run)(ws, wt, x, tgt)

    def seq_out(ws, x):
        y = x
        for i in range(P):
            y = jax.nn.relu(y @ ws[i])
        return y

    def seq_loss(ws, wt, x, tgt):
        # sequential reference INCLUDING the per-stage aux terms, computed
        # per microbatch like the schedule does (mean over micros)
        total = 0.0
        for m in range(M):
            r = x.shape[0] // M
            xm, tm = x[m * r:(m + 1) * r], tgt[m * r:(m + 1) * r]
            y = xm
            for i in range(P):
                total = total + 1e-3 * jnp.mean(
                    y.astype(jnp.float32) ** 2) / M
                y = jax.nn.relu(y @ ws[i])
            total = total + tail_fn(wt, y, tm)[0] / M
        return total

    gw, gt, gx = jax.grad(seq_loss, argnums=(0, 1, 2))(ws, wt, x, tgt)
    np.testing.assert_allclose(float(loss), float(seq_loss(ws, wt, x, tgt)),
                               rtol=1e-5)
    # aux metrics averaged over microbatches == full-batch value (equal
    # micro sizes, mean metric)
    full_mae = float(jnp.mean(jnp.abs(seq_out(ws, x) * wt - tgt)))
    np.testing.assert_allclose(float(aux["mae"]), full_mae, rtol=1e-5)
    np.testing.assert_allclose(np.asarray(dws), np.asarray(gw),
                               rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(np.asarray(dwt), np.asarray(gt),
                               rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(np.asarray(dx), np.asarray(gx),
                               rtol=1e-4, atol=1e-5)
    # the M-independent memory claim, structurally: the stash ring inside
    # the scan holds 2*P stage inputs regardless of M (vs GPipe's autodiff
    # residuals across M+P-1 ticks) — pin by ACTUALLY raising M to B (max
    # microbatching, in-flight count reaches the ring bound) and checking
    # loss and grads still match the sequential composition
    def run_mb(ws, wt, x, tgt):
        with mesh:
            return pipeline_1f1b(stage_fn, tail_fn, ws, wt, x, (tgt,),
                                 P, B, mesh)

    lossB, _, dwsB, dwtB, dxB = jax.jit(run_mb)(ws, wt, x, tgt)
    np.testing.assert_allclose(float(lossB), float(loss), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(dwsB), np.asarray(gw),
                               rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(np.asarray(dwtB), np.asarray(gt),
                               rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(np.asarray(dxB), np.asarray(gx),
                               rtol=1e-4, atol=1e-5)


def test_pipeline_1f1b_trains_with_parity(eight_devices):
    """pipeline_schedule='1f1b': the interleaved loss-and-grad schedule must
    match the gpipe-under-autodiff path — same loss, same grads, same params
    after an optimizer step — including a config with cross-depth shared
    weights and grad accumulation."""
    from homebrewnlp_tpu.config import Config
    base = _pipe_base(depth=4, train_batch_size=16)
    cfg_g = Config(dict(base, pipeline_parallel=4, pipeline_schedule="gpipe"))
    cfg_f = Config(dict(base, pipeline_parallel=4, pipeline_schedule="1f1b"))
    batch = text_batch(cfg_g)

    tg, tf = Trainer(cfg_g), Trainer(cfg_f)
    sg = tg.init(batch)
    sf = tf.init(batch)
    for k in sg.params:
        np.testing.assert_array_equal(np.asarray(sg.params[k]),
                                      np.asarray(sf.params[k]), err_msg=k)
    gg, og = tg._grads(sg.params, batch, jax.random.key(0))
    gf, of = tf._grads(sf.params, batch, jax.random.key(0))
    np.testing.assert_allclose(float(of.loss), float(og.loss), rtol=1e-5)
    assert set(gg) == set(gf)
    for k in gg:
        np.testing.assert_allclose(np.asarray(gf[k], np.float32),
                                   np.asarray(gg[k], np.float32),
                                   rtol=2e-4, atol=2e-6, err_msg=k)
    for i in range(2):
        sg, mg = tg.step(sg, batch, jax.random.key(i))
        sf, mf = tf.step(sf, batch, jax.random.key(i))
    np.testing.assert_allclose(float(mf["loss"]), float(mg["loss"]),
                               rtol=1e-4)
    for k in sg.params:
        np.testing.assert_allclose(np.asarray(sg.params[k], np.float32),
                                   np.asarray(sf.params[k], np.float32),
                                   rtol=2e-4, atol=2e-6, err_msg=k)

    # shared weights + 1f1b compose (the flagship mixer DSL), and the
    # accuracy/token_loss metrics ride the schedule's aux stream
    from .backend import mixer_config
    mcfg = dict(mixer_config(depth=4, calc_accuracy=True).dict())
    cfg_ms = Config(dict(mcfg, memory_reduction_strategy="none",
                         pipeline_parallel=2, pipeline_schedule="1f1b"))
    cfg_mg = Config(dict(mcfg, memory_reduction_strategy="none",
                         pipeline_parallel=2, pipeline_schedule="gpipe"))
    mbatch = text_batch(cfg_ms)
    tms, tmg = Trainer(cfg_ms), Trainer(cfg_mg)
    sms = tms.init(mbatch)
    smg = tmg.init(mbatch)
    gms, oms = tms._grads(sms.params, mbatch, jax.random.key(1))
    gmg, omg = tmg._grads(smg.params, mbatch, jax.random.key(1))
    np.testing.assert_allclose(float(oms.loss), float(omg.loss), rtol=1e-5)
    np.testing.assert_allclose(float(oms.accuracy), float(omg.accuracy),
                               rtol=1e-5)
    np.testing.assert_allclose(float(oms.token_loss), float(omg.token_loss),
                               rtol=1e-5)
    for k in gmg:
        np.testing.assert_allclose(np.asarray(gms[k], np.float32),
                                   np.asarray(gmg[k], np.float32),
                                   rtol=5e-4, atol=5e-6, err_msg=k)


def test_pipeline_1f1b_config_validation():
    from homebrewnlp_tpu.config import Config
    base = _pipe_base(depth=4)
    with pytest.raises(ValueError, match="pipeline_schedule"):
        Config(dict(base, pipeline_parallel=2, pipeline_schedule="zigzag"))
    # accuracy rides the schedule's aux stream since round 4 — accepted
    Config(dict(base, pipeline_parallel=2, pipeline_schedule="1f1b",
                calc_accuracy=True))
    with pytest.raises(ValueError, match="multi-loss"):
        Config(dict(base, pipeline_parallel=2, pipeline_schedule="1f1b",
                    multi_loss_strategy="pcgrad"))


def test_pipeline_1f1b_routed_moe(eight_devices):
    """Expert parallelism composes with pipeline parallelism under 1F1B:
    the routed-MoE balance aux loss rides the schedule's stage stream (value
    AND gradient), lifting the gpipe-era rejection.  The loss must equal the
    mean over microbatches of the sequential per-micro model's total, and
    grads the mean of per-micro grads."""
    from homebrewnlp_tpu.config import Config
    from homebrewnlp_tpu.models import build, init_params
    from homebrewnlp_tpu.models.ctx import Ctx
    from homebrewnlp_tpu.nd import NT

    base = _pipe_base(
        depth=2, train_batch_size=16, heads=2, experts=4,
        block_config=[{"layer": ["norm-shift-scale", "feed_forward-in:relu"]},
                      {"layer": ["norm-shift-scale",
                                 "routed_moe-topk2-capacity2"]}])
    with pytest.raises(ValueError, match="gpipe"):
        Config(dict(base, pipeline_parallel=2, pipeline_schedule="gpipe"))
    cfg_f = Config(dict(base, pipeline_parallel=2, pipeline_schedule="1f1b"))
    batch = text_batch(cfg_f)
    trainer = Trainer(cfg_f)
    state = trainer.init(batch)
    gf, of = trainer._grads(state.params, batch, jax.random.key(0))

    # sequential per-micro reference matching the schedule's microbatch
    # choice (_pipeline_n_micro(16, 2, "1f1b") = 2 micros of 8 rows)
    from homebrewnlp_tpu.models import _pipeline_n_micro
    M = _pipeline_n_micro(16, 2, "1f1b")
    assert M == 2
    r = 16 // M
    cfg_1 = Config(dict(base, train_batch_size=r))
    params1, _ = init_params(cfg_1, {k: NT(v.x[:r], v.names)
                                     for k, v in batch.items()})

    def micro_total(p, mb):
        return build(Ctx(cfg_1, params=p, train=True,
                         rng=jax.random.key(0)), mb).loss

    total = 0.0
    gacc = None
    for m in range(M):
        mb = {k: NT(v.x[m * r:(m + 1) * r], v.names)
              for k, v in batch.items()}
        l, g = jax.value_and_grad(micro_total)(params1, mb)
        total = total + float(l) / M
        g = {k: np.asarray(v, np.float32) / M for k, v in g.items()}
        gacc = g if gacc is None else {k: gacc[k] + g[k] for k in g}
    np.testing.assert_allclose(float(of.loss), total, rtol=1e-4)

    from homebrewnlp_tpu.models import unstack_pipeline_params
    gf_flat = unstack_pipeline_params(cfg_f, gf)
    for k in gacc:
        np.testing.assert_allclose(np.asarray(gf_flat[k], np.float32),
                                   gacc[k], rtol=5e-4, atol=5e-6, err_msg=k)

    # the forward/eval path (build under gpipe-with-aux) reports the SAME
    # total loss the 1F1B training path optimizes — the balance term is not
    # silently dropped from eval
    o_eval = trainer._losses(state.params, batch, jax.random.key(0))
    np.testing.assert_allclose(float(o_eval.loss), float(of.loss), rtol=1e-4)

    # and it trains end to end
    state2, m2 = trainer.step(state, batch, jax.random.key(1))
    assert np.isfinite(float(m2["loss"]))


def test_cli_train_1f1b_checkpoint_resume(eight_devices, tmp_path):
    """Whole-CLI integration under the 1F1B schedule: train with routed-MoE
    + accuracy metrics + checkpointing, then a second invocation restores
    the step and continues — the paths unit tests cover individually, run
    through main.py as a user would."""
    import json

    from homebrewnlp_tpu.main import main as cli_main

    cfg = dict(
        model_mode="gpt", use_video=False, sequence_length=16, heads=2,
        features_per_head=32, vocab_size=64, depth=4, train_batch_size=16,
        memory_reduction_strategy="none", optimizer="adam-learning_rate",
        learning_rate=1e-2, weight_decay=0.0, experts=4,
        intermediate_feed_forward_multiplier_multiplier=0.5,
        pipeline_parallel=2, pipeline_schedule="1f1b", calc_accuracy=True,
        tpu_size=8, use_checkpointing=True, steps_per_checkpoint=4,
        model_path=str(tmp_path / "run"),
        block_config=[
            {"layer": ["norm-shift-scale", "feed_forward-in:relu"]},
            {"layer": ["norm-shift-scale", "routed_moe-topk2-capacity2"]}])
    cfg_path = tmp_path / "cfg.json"
    cfg_path.write_text(json.dumps(cfg))

    cli_main(["--model", str(cfg_path), "--run_mode", "train",
              "--steps", "6"])
    from homebrewnlp_tpu.train.metrics import read_metric_rows
    metrics_file = tmp_path / "run" / "metrics.jsonl"
    rows = read_metric_rows(str(metrics_file))
    assert rows[-1]["step"] == 5
    assert "accuracy" in rows[-1] and "token_loss" in rows[-1]

    cli_main(["--model", str(cfg_path), "--run_mode", "train",
              "--steps", "9"])
    rows = read_metric_rows(str(metrics_file))
    # restore picked up the step-4+ checkpoint and continued to 9
    assert rows[-1]["step"] == 8
    assert all(np.isfinite(r["loss"]) for r in rows)
