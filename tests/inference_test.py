"""Inference + serving tests: sampler determinism/prompt preservation,
greedy self-consistency (the reference's debug mode as a test), completion
engine, REST API over a live socket, CLI train mode end-to-end."""
import json
import urllib.request

import jax
import numpy as np
import pytest

from homebrewnlp_tpu.infer import autoregressive_text, make_text_sampler
from homebrewnlp_tpu.models import init_params
from homebrewnlp_tpu.nd import NT
from homebrewnlp_tpu.serve import (CompletionEngine, InterfaceWrapper,
                                   similarity_score)
from homebrewnlp_tpu.serve.interface import TEXT_AXES
from homebrewnlp_tpu.utils import random_text_batch

from .backend import mixer_config


def _small_cfg(**over):
    base = dict(depth=1, sequence_length=12, heads=2, features_per_head=16,
                vocab_size=32, train_batch_size=1,
                initial_autoregressive_position=4, sampling_temperature=0.0,
                use_autoregressive_sampling=True)
    base.update(over)
    return mixer_config(**base)


@pytest.fixture(scope="module")
def cfg_params():
    cfg = _small_cfg()
    params, _ = init_params(cfg, random_text_batch(cfg))
    return cfg, params


def test_sampler_preserves_prompt_and_fills(cfg_params):
    cfg, params = cfg_params
    toks = jnp_toks = np.zeros((1, cfg.sequence_length, 1), np.int32)
    toks[0, :4, 0] = [5, 9, 3, 7]
    out = autoregressive_text(cfg, params, NT(jax.numpy.asarray(toks), TEXT_AXES),
                              initial_pos=4, temperature=0.0,
                              rng=jax.random.key(0))
    out = np.asarray(out)
    np.testing.assert_array_equal(out[0, :4, 0], [5, 9, 3, 7])
    assert (out[0, 4:, 0] < cfg.vocab_size).all()


def test_greedy_sampling_deterministic(cfg_params):
    """Greedy samples from identical prompts must agree 100% (the debug run
    mode's property, reference interface.py:283-302)."""
    cfg, params = cfg_params
    sampler = make_text_sampler(cfg, params)
    toks = np.zeros((1, cfg.sequence_length, 1), np.int32)
    outs = [np.asarray(sampler(NT(jax.numpy.asarray(toks), TEXT_AXES),
                               np.int32(2), np.float32(0.0),
                               jax.random.key(i)))
            for i in range(3)]
    assert similarity_score(outs) == 1.0


def test_temperature_changes_samples(cfg_params):
    cfg, params = cfg_params
    sampler = make_text_sampler(cfg, params)
    toks = np.zeros((1, cfg.sequence_length, 1), np.int32)
    a = np.asarray(sampler(NT(jax.numpy.asarray(toks), TEXT_AXES), np.int32(1),
                           np.float32(5.0), jax.random.key(1)))
    b = np.asarray(sampler(NT(jax.numpy.asarray(toks), TEXT_AXES), np.int32(1),
                           np.float32(5.0), jax.random.key(2)))
    assert not np.array_equal(a, b)


def test_completion_engine_text_roundtrip(cfg_params):
    cfg, params = cfg_params
    engine = CompletionEngine(cfg, params)
    out = engine.complete_tokens([1, 2, 3], temperature=0.0, max_tokens=4)
    assert list(out[:3]) == [1, 2, 3]
    assert len(out) == 7
    wrapper = InterfaceWrapper(engine)
    sync = wrapper.complete([1, 2, 3], response_len=4)
    fetch = wrapper.complete([1, 2, 3], response_len=4, asynchronous=True)
    np.testing.assert_array_equal(np.asarray(sync), np.asarray(fetch()))
    wrapper.close()


def test_effective_truncation_bucketing():
    """The compile-cache bucketing contract (serve/interface.py): requested
    top_k rounds UP to the next power of two capped at vocab, top_p snaps
    to the 0.05 grid, and None keeps the config's exact knob un-bucketed —
    the values completion responses echo back."""
    from homebrewnlp_tpu.serve.interface import effective_truncation
    cfg = _small_cfg(sampling_top_k=6, sampling_top_p=0.33)
    # None keeps the config's EXACT values (no bucketing)
    assert effective_truncation(cfg, None, None) == (6, 0.33)
    # k rounds up to the next power of two; exact powers stay put
    assert effective_truncation(cfg, 3, None)[0] == 4
    assert effective_truncation(cfg, 4, None)[0] == 4
    assert effective_truncation(cfg, 5, None)[0] == 8
    assert effective_truncation(cfg, 1, None)[0] == 1
    # capped at vocab (32), and 0 = disabled passes through
    assert effective_truncation(cfg, 1000, None)[0] == cfg.vocab_size
    assert effective_truncation(cfg, 0, None)[0] == 0
    # p snaps to the 0.05 grid, floored at 0.05, >= 1 collapses to 1.0
    assert effective_truncation(cfg, None, 0.42)[1] == pytest.approx(0.4)
    assert effective_truncation(cfg, None, 0.43)[1] == pytest.approx(0.45)
    assert effective_truncation(cfg, None, 0.01)[1] == pytest.approx(0.05)
    assert effective_truncation(cfg, None, 1.7)[1] == 1.0
    # both requested at once bucket independently
    assert effective_truncation(cfg, 9, 0.87) == (16, pytest.approx(0.85))


def test_rest_api_endpoints(cfg_params):
    cfg, params = cfg_params
    from homebrewnlp_tpu.serve import serve
    server = serve(cfg, params, port=0, background=True)
    port = server.server_address[1]

    def post(path, body):
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/{path}",
            data=json.dumps(body).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=120) as r:
            return json.loads(r.read())

    enc = post("encode", {"prompt": "ab"})
    assert enc["tokens"] == [97, 98] or isinstance(enc["tokens"], list)
    dec = post("decode", {"prompt": [1, 2, 999999]})
    assert isinstance(dec["completion"], str)
    chk = post("check_tokens", {"prompt": [0, 31, 32, -5]})
    assert chk["tokens"] == [0, 31, 31, 0]
    comp = post("token_completion", {"prompt": [1, 2], "temperature": 0.0,
                                     "response_len": 3})
    assert comp["completion"][:2] == [1, 2]
    # per-request truncation rides through the wrapper to the engine, and the
    # response echoes the EFFECTIVE (bucketed) knobs: top_k=3 compiles the
    # top-4 bucket, top_p snaps to the 0.05 grid
    trunc = post("token_completion", {"prompt": [1, 2], "temperature": 5.0,
                                      "response_len": 3, "top_k": 1})
    assert trunc["completion"][:2] == [1, 2]
    assert trunc["top_k"] == 1 and trunc["top_p"] == cfg.sampling_top_p
    bucketed = post("token_completion", {"prompt": [1, 2], "temperature": 5.0,
                                         "response_len": 3, "top_k": 3,
                                         "top_p": 0.42})
    assert bucketed["top_k"] == 4 and bucketed["top_p"] == 0.4
    server.shutdown()


def test_video_sampler_runs():
    from homebrewnlp_tpu.infer import autoregressive_video
    cfg = mixer_config(model_mode="jannet", use_video=True, use_language=False,
                       frame_height=32, frame_width=32, patch_size=16,
                       sequence_length=4, experts=1, depth=1, heads=2,
                       features_per_head=16, train_batch_size=1,
                       initial_autoregressive_position=1)
    frames = np.random.default_rng(0).random(
        (1, 5, 2, 2, 16 * 16 * 3), np.float32)
    batch = {"frame": NT(jax.numpy.asarray(frames),
                         ("batch", "_sequence", "height", "width",
                          "color_channels"))}
    params, _ = init_params(cfg, batch)
    _, filled = jax.jit(lambda p, b: autoregressive_video(cfg, p, b))(params, batch)
    assert np.isfinite(np.asarray(filled, np.float32)).all()


def test_cli_train_synthetic(tmp_path, capsys):
    from homebrewnlp_tpu.main import main
    cfg_path = tmp_path / "cfg.json"
    cfg_path.write_text(json.dumps(dict(
        model_mode="gpt", use_video=False, sequence_length=12, heads=2,
        features_per_head=16, depth=1, vocab_size=32, train_batch_size=2,
        memory_reduction_strategy="none", optimizer="adam-learning_rate",
        intermediate_feed_forward_multiplier_multiplier=0.5,
        block_config=[{"layer": ["norm-shift-scale", "feed_forward-in:relu"]}],
        model_path=str(tmp_path / "run"), use_checkpointing=True,
        steps_per_checkpoint=5)))
    main(["--model", str(cfg_path), "--run_mode", "train", "--steps", "6"])
    assert (tmp_path / "run" / "run_config.json").exists()
    assert (tmp_path / "run" / "model_size.info").exists()
    assert (tmp_path / "run" / "metrics.jsonl").exists()
    assert (tmp_path / "run" / "data_log.json").exists()
    # resume: second invocation restores step 6 and continues to 8
    main(["--model", str(cfg_path), "--run_mode", "train", "--steps", "8"])
    lines = [json.loads(l) for l in
             (tmp_path / "run" / "metrics.jsonl").read_text().splitlines()]
    assert lines[-1]["step"] == 7
    # run-start boundary markers: one per invocation, resume step recorded
    markers = [l for l in lines if l.get("run_start")]
    assert [m["resume_step"] for m in markers] == [0, 6]
    assert markers[0]["config_hash"] == markers[1]["config_hash"]


def test_cli_sample_video_writes_avi(tmp_path):
    cv2 = pytest.importorskip("cv2")
    from homebrewnlp_tpu.main import main
    cfg_path = tmp_path / "cfg.json"
    cfg_path.write_text(json.dumps(dict(
        model_mode="jannet", use_video=True, use_language=False,
        frame_height=32, frame_width=32, patch_size=16, sequence_length=4,
        experts=1, depth=1, heads=2, features_per_head=16,
        memory_reduction_strategy="none", num_of_sample=2,
        use_autoregressive_sampling=True, initial_autoregressive_position=2,
        intermediate_feed_forward_multiplier_multiplier=0.5,
        block_config=[{"layer": ["norm-shift-scale", "feed_forward-in:relu"]}],
        model_path=str(tmp_path / "run"))))
    main(["--model", str(cfg_path), "--run_mode", "sample"])
    avis = sorted((tmp_path / "run" / "samples").glob("*.avi"))
    assert len(avis) == 2
    cap = cv2.VideoCapture(str(avis[0]))
    ok, frame = cap.read()
    cap.release()
    assert ok and frame.shape == (32, 32, 3)


def test_cli_sample_video_single_forward(tmp_path):
    cv2 = pytest.importorskip("cv2")
    from homebrewnlp_tpu.main import main
    cfg_path = tmp_path / "cfg.json"
    cfg_path.write_text(json.dumps(dict(
        model_mode="jannet", use_video=True, use_language=False,
        frame_height=32, frame_width=32, patch_size=16, sequence_length=4,
        experts=1, depth=1, heads=2, features_per_head=16,
        memory_reduction_strategy="none", num_of_sample=1,
        use_autoregressive_sampling=False,
        intermediate_feed_forward_multiplier_multiplier=0.5,
        block_config=[{"layer": ["norm-shift-scale", "feed_forward-in:relu"]}],
        model_path=str(tmp_path / "run"))))
    main(["--model", str(cfg_path), "--run_mode", "sample"])
    samples = tmp_path / "run" / "samples"
    assert (samples / "sample_0_output.avi").exists()
    assert (samples / "sample_0_input.avi").exists()


def test_cli_debug_old_similarity(tmp_path, capsys):
    from homebrewnlp_tpu.main import main
    from homebrewnlp_tpu.data import write_text_tfrecords
    paths = write_text_tfrecords(str(tmp_path / "data"), 2, 3, 64, seed=9)
    cfg_path = tmp_path / "cfg.json"
    cfg_path.write_text(json.dumps(dict(
        model_mode="gpt", use_video=False, sequence_length=12, heads=2,
        features_per_head=16, depth=1, vocab_size=32,
        memory_reduction_strategy="none", initial_autoregressive_position=4,
        intermediate_feed_forward_multiplier_multiplier=0.5,
        dataset_configs=[{"type": "text",
                          "path": str(tmp_path / "data" / "*.tfrecord")}],
        block_config=[{"layer": ["norm-shift-scale", "feed_forward-in:relu"]}],
        model_path=str(tmp_path / "run"))))
    main(["--model", str(cfg_path), "--run_mode", "debug_old"])
    out = capsys.readouterr().out
    assert "similarity score: 100%" in out


def _kv_cfg(**over):
    base = dict(depth=2, sequence_length=16, heads=2, features_per_head=16,
                vocab_size=32, train_batch_size=1,
                memory_reduction_strategy="none",
                use_autoregressive_sampling=True,
                block_config=[
                    {"layer": ["norm-shift-scale",
                               "attention-in:relu-dot_product-embedded-relative"]},
                    {"layer": ["norm-shift-scale", "feed_forward-in:relu"]},
                ])
    base.update(over)
    return mixer_config(**base)


def test_kv_cache_eligibility():
    from homebrewnlp_tpu.infer import cache_eligible
    assert cache_eligible(_kv_cfg())
    # decode-mode slicing of the initial position table is wired up
    assert cache_eligible(_kv_cfg(use_initial_position_embedding=True))
    # mixer bias maps cache V + gather map rows (round 4; the flagship's
    # own architecture finally gets the fast sampler)
    assert cache_eligible(mixer_config())
    assert cache_eligible(_kv_cfg(block_config=[
        {"layer": ["attention-biased_attention_map-absolute-input_as_value"]}]))
    # non-attention sequence mixers keep the rebuild path
    assert not cache_eligible(_kv_cfg(block_config=[{"layer": ["cummean"]}]))
    # UNMASKED map attention attends to future positions (stale in the
    # cache): rebuild-only; the unconditionally-causal dot product is exempt
    assert not cache_eligible(mixer_config(masked_attention_dimensions=[]))
    assert cache_eligible(_kv_cfg(masked_attention_dimensions=[]))


def test_kv_cache_initial_position_embedding_parity():
    """Greedy cached decode under use_initial_position_embedding: the table
    is added full-length in training but sliced per decoded row in cache
    mode — tokens must match the rebuild sampler exactly."""
    from homebrewnlp_tpu.infer import make_cached_text_sampler
    cfg = _kv_cfg(use_initial_position_embedding=True)
    params, _ = init_params(cfg, random_text_batch(cfg))
    assert any("position_embedding" in k or "body/embed" in k
               for k in params), sorted(params)[:8]
    toks = np.zeros((1, cfg.sequence_length, 1), np.int32)
    toks[0, :5, 0] = [3, 14, 15, 9, 2]
    nt = NT(jax.numpy.asarray(toks), TEXT_AXES)
    a = np.asarray(make_text_sampler(cfg, params)(
        nt, np.int32(5), np.float32(0.0), jax.random.key(0)))
    b = np.asarray(make_cached_text_sampler(cfg, params)(
        nt, np.int32(5), np.float32(0.0), jax.random.key(0)))
    np.testing.assert_array_equal(a, b)


def test_kv_cache_greedy_matches_rebuild():
    """Greedy cached decode must produce the same tokens as the
    rebuild-everything sampler (VERDICT r1 item 7)."""
    from homebrewnlp_tpu.infer import make_cached_text_sampler
    cfg = _kv_cfg()
    params, _ = init_params(cfg, random_text_batch(cfg))
    toks = np.zeros((1, cfg.sequence_length, 1), np.int32)
    toks[0, :5, 0] = [3, 14, 15, 9, 2]
    nt = NT(jax.numpy.asarray(toks), TEXT_AXES)

    rebuild = make_text_sampler(cfg, params)
    cached = make_cached_text_sampler(cfg, params)
    a = np.asarray(rebuild(nt, np.int32(5), np.float32(0.0), jax.random.key(0)))
    b = np.asarray(cached(nt, np.int32(5), np.float32(0.0), jax.random.key(0)))
    np.testing.assert_array_equal(a, b)

    # partial range: end_iterations respected identically
    a = np.asarray(rebuild(nt, np.int32(5), np.float32(0.0), jax.random.key(0),
                           np.int32(9)))
    b = np.asarray(cached(nt, np.int32(5), np.float32(0.0), jax.random.key(0),
                          np.int32(9)))
    np.testing.assert_array_equal(a, b)


def test_kv_cache_mixer_greedy_matches_rebuild():
    """The flagship mixer architecture (biased_attention_map + input_as_value
    + shared, no dot product) decodes against the V-cache + map-row gather
    path; greedy tokens must match the rebuild sampler (VERDICT r3 item 2)."""
    from homebrewnlp_tpu.infer import cache_eligible, make_cached_text_sampler
    cfg = mixer_config(memory_reduction_strategy="none")
    assert cache_eligible(cfg)
    params, _ = init_params(cfg, random_text_batch(cfg))
    toks = np.zeros((2, cfg.sequence_length, 1), np.int32)
    toks[0, :5, 0] = [3, 14, 15, 9, 2]
    toks[1, :5, 0] = [1, 1, 2, 3, 5]
    nt = NT(jax.numpy.asarray(toks), TEXT_AXES)

    rebuild = make_text_sampler(cfg, params)
    cached = make_cached_text_sampler(cfg, params)
    a = np.asarray(rebuild(nt, np.int32(5), np.float32(0.0), jax.random.key(0)))
    b = np.asarray(cached(nt, np.int32(5), np.float32(0.0), jax.random.key(0)))
    np.testing.assert_array_equal(a, b)


def test_kv_cache_map_flag_variants_match_rebuild():
    """biased_softmax (map + softmax) and scale_attention_map (map scaling a
    dot-product softmax) both decode cached with greedy parity."""
    from homebrewnlp_tpu.infer import cache_eligible, make_cached_text_sampler
    for block in (["norm-shift-scale",
                   "attention-in:relu-biased_softmax-dot_product-embedded-absolute"],
                  ["norm-shift-scale",
                   "attention-biased_softmax-absolute-input_as_value"],
                  ["norm-shift-scale",
                   "attention-in:relu-scale_attention_map-dot_product-embedded-absolute"]):
        cfg = _kv_cfg(block_config=[{"layer": block}])
        assert cache_eligible(cfg)
        params, _ = init_params(cfg, random_text_batch(cfg))
        toks = np.zeros((1, cfg.sequence_length, 1), np.int32)
        toks[0, :4, 0] = [3, 14, 15, 9]
        nt = NT(jax.numpy.asarray(toks), TEXT_AXES)
        a = np.asarray(make_text_sampler(cfg, params)(
            nt, np.int32(4), np.float32(0.0), jax.random.key(0)))
        b = np.asarray(make_cached_text_sampler(cfg, params)(
            nt, np.int32(4), np.float32(0.0), jax.random.key(0)))
        np.testing.assert_array_equal(a, b, err_msg=str(block))


def test_truncated_sampling():
    """top-k / nucleus truncation (extension; reference is temperature-only):
    top_k=1 is greedy at any temperature, top_k=k confines hot samples to
    the top-k set, a tiny top_p collapses to greedy, bad knobs are
    rejected."""
    import jax.numpy as jnp

    from homebrewnlp_tpu.infer.sampler import _gumbel_argmax
    logits = np.random.RandomState(0).standard_normal((4, 32)).astype(np.float32)
    greedy = np.argmax(logits, -1)
    for key in range(3):
        s = np.asarray(_gumbel_argmax(jnp.asarray(logits), jnp.float32(5.0),
                                      jax.random.key(key), top_k=1))
        np.testing.assert_array_equal(s, greedy)
    top3 = np.argsort(logits, -1)[:, -3:]
    hits = set()
    for key in range(8):
        s = np.asarray(_gumbel_argmax(jnp.asarray(logits), jnp.float32(3.0),
                                      jax.random.key(key), top_k=3))
        for r in range(4):
            assert s[r] in top3[r], (r, s[r], top3[r])
            hits.add((r, int(s[r])))
    assert len(hits) > 4  # actually stochastic within the set
    s = np.asarray(_gumbel_argmax(jnp.asarray(logits), jnp.float32(5.0),
                                  jax.random.key(0), top_p=1e-6))
    np.testing.assert_array_equal(s, greedy)

    # engine level: knobs are honored by both sampler paths
    cfg = _kv_cfg(sampling_top_k=1, sampling_temperature=9.0)
    params, _ = init_params(cfg, random_text_batch(cfg))
    a = CompletionEngine(cfg, params).complete_tokens([1, 2, 3], None, 4)
    b = CompletionEngine(cfg, params).complete_tokens([1, 2, 3], None, 4)
    np.testing.assert_array_equal(a[:3], [1, 2, 3])
    np.testing.assert_array_equal(a, b)  # top_k=1: greedy despite T=9

    with pytest.raises(ValueError, match="sampling_top_k"):
        _kv_cfg(sampling_top_k=999)
    with pytest.raises(ValueError, match="sampling_top_p"):
        _kv_cfg(sampling_top_p=0.0)


def test_per_request_truncation_buckets():
    """Per-request top_k/top_p: bucketed compile cache — k rounds to the
    next power of two, repeated requests reuse one sampler, top_k=1 forces
    greedy even though the engine's config is unrestricted and hot."""
    cfg = _kv_cfg(sampling_temperature=9.0)
    params, _ = init_params(cfg, random_text_batch(cfg))
    eng = CompletionEngine(cfg, params)
    a = eng.complete_tokens([1, 2, 3], None, 4, top_k=1)
    b = eng.complete_tokens([1, 2, 3], None, 4, top_k=1)
    np.testing.assert_array_equal(a, b)  # greedy despite T=9
    # k=3 and k=4 share the power-of-two bucket; p grid at 0.05
    eng.complete_tokens([1], None, 2, top_k=3)
    eng.complete_tokens([1], None, 2, top_k=4)
    eng.complete_tokens([1], None, 2, top_p=0.52)
    eng.complete_tokens([1], None, 2, top_p=0.50)
    assert set(eng._samplers) == {(1, 1.0), (4, 1.0), (0, 0.5)}, eng._samplers
    # no-knob requests keep using the default sampler (no extra compiles)
    eng.complete_tokens([1], None, 2)
    assert len(eng._samplers) == 3


def test_kv_cache_engine_routing():
    from homebrewnlp_tpu.infer.kv_cache import make_cached_text_sampler
    cfg = _kv_cfg(sequence_length=12, initial_autoregressive_position=4,
                  sampling_temperature=0.0)
    params, _ = init_params(cfg, random_text_batch(cfg))
    engine = CompletionEngine(cfg, params)
    out = engine.complete_tokens([1, 2, 3], temperature=0.0, max_tokens=4)
    assert list(out[:3]) == [1, 2, 3] and len(out) == 7
    # force_rebuild pins the rebuild sampler and agrees greedily
    engine_rb = CompletionEngine(cfg, params, force_rebuild=True)
    out_rb = engine_rb.complete_tokens([1, 2, 3], temperature=0.0, max_tokens=4)
    assert len(out_rb) == 7


def test_cli_debug_video_similarity(tmp_path, capsys):
    from homebrewnlp_tpu.main import main
    cfg_path = tmp_path / "cfg.json"
    cfg_path.write_text(json.dumps(dict(
        model_mode="jannet", use_video=True, use_language=False,
        frame_height=32, frame_width=32, patch_size=16, sequence_length=4,
        experts=1, depth=1, heads=2, features_per_head=16,
        memory_reduction_strategy="none", initial_autoregressive_position=1,
        intermediate_feed_forward_multiplier_multiplier=0.5,
        block_config=[{"layer": ["norm-shift-scale", "feed_forward-in:relu"]}],
        model_path=str(tmp_path / "run"))))
    main(["--model", str(cfg_path), "--run_mode", "debug"])
    assert "similarity: 100.00%" in capsys.readouterr().out


def test_cli_debug_text_similarity(tmp_path, capsys):
    from homebrewnlp_tpu.main import main
    cfg_path = tmp_path / "cfg.json"
    cfg_path.write_text(json.dumps(dict(
        model_mode="gpt", use_video=False, sequence_length=12, heads=2,
        features_per_head=16, depth=1, vocab_size=32,
        memory_reduction_strategy="none",
        intermediate_feed_forward_multiplier_multiplier=0.5,
        block_config=[{"layer": ["norm-shift-scale", "feed_forward-in:relu"]}],
        model_path=str(tmp_path / "run"))))
    main(["--model", str(cfg_path), "--run_mode", "debug"])
    assert "similarity: 100.00%" in capsys.readouterr().out


def test_repl_smoke(cfg_params, monkeypatch, capsys):
    """The interactive query REPL completes a prompt and exits on EOF."""
    from homebrewnlp_tpu.serve import repl
    cfg, params = cfg_params
    feeds = ["ab"]

    def fake_input(*_):
        if feeds:
            return feeds.pop(0)
        raise EOFError

    monkeypatch.setattr("builtins.input", fake_input)
    repl(cfg, params)
    out = capsys.readouterr().out
    # more than the banner: a completion line was actually printed
    assert len([l for l in out.splitlines() if l.strip()]) >= 2


def test_hbnlp_bpe_tokenizer_roundtrip():
    """Serving codec for the committed in-house tokenizer artifact: encode
    through the native BPE encoder, decode by merge-table expansion;
    roundtrip must be identity for UTF-8 text and match the tfrecord
    builder's token stream."""
    import os
    import numpy as np
    from homebrewnlp_tpu.native import bpe_encode, clean_text
    from homebrewnlp_tpu.serve.interface import HbnlpBpeTokenizer
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    path = os.path.join(repo, "datasets", "tokenizer65k.json")
    tok = HbnlpBpeTokenizer(path)
    text = "def main() -> None:\n    return os.path.join(a, b)  # comment\n"
    ids = tok.encode(text)
    assert len(ids) < len(text.encode())  # actually compresses code
    assert tok.decode(ids) == text
    # identical stream to the tfrecord builder's encode of the same bytes
    raw = np.frombuffer(text.encode(), np.uint8).astype(np.int32)
    np.testing.assert_array_equal(np.asarray(ids, np.int32),
                                  bpe_encode(raw, tok._merges))
    # unicode replacement path stays total
    assert tok.decode([0, 70000, 5]) == tok.decode([0, 5])
