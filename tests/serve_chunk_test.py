"""Chunked-prefill tests (serve/engine.py ``serve_prefill_chunk_tokens``,
docs/observability.md "Continuous batching"): knob validation, chunked vs
monolithic bit-identicality across chunk geometries (ragged last chunk,
prompt shorter than one chunk, empty prompt, exact fit), AOT round-trip
with the third executable, mid-admission chunk failure recycling blocks,
the stalled-lane-seconds A/B (chunked admission contributes zero), and the
trace-level proof that decode steps fire BETWEEN a long prompt's chunks."""
from __future__ import annotations

import json
import os
import sys

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(__file__))
from backend import mixer_config  # noqa: E402

from homebrewnlp_tpu.config import Config  # noqa: E402
from homebrewnlp_tpu.models import init_params  # noqa: E402
from homebrewnlp_tpu.utils import random_text_batch  # noqa: E402


def _chunk_cfg(**over) -> Config:
    base = dict(depth=1, sequence_length=12, heads=2, features_per_head=16,
                vocab_size=32, train_batch_size=1, sampling_temperature=0.0,
                use_autoregressive_sampling=True, serve_max_batch=3)
    base.update(over)
    return mixer_config(**base)


@pytest.fixture(scope="module")
def chunk_setup():
    cfg = _chunk_cfg()
    params, _ = init_params(cfg, random_text_batch(cfg))
    return cfg, params


# one of each chunk-coverage geometry: multi-chunk with a ragged last
# chunk (7 rows / chunk 4), shorter than one chunk, empty prompt (the
# seed row still needs its token written), and an exact one-chunk fit
PROMPTS = ([1, 2, 3, 4, 5, 6, 7], [9, 8], [], [4, 4, 4, 4])


def _run_engine(cfg, params, prompts=PROMPTS, temperature=0.7,
                response_len=4):
    from homebrewnlp_tpu.serve.engine import BatchEngine
    eng = BatchEngine(cfg, params)
    try:
        reqs = [eng.submit(list(p), temperature, response_len, 0, 1.0)
                for p in prompts]
        return [list(map(int, eng.fetch(r))) for r in reqs]
    finally:
        eng.close()


def test_chunk_knob_validation():
    with pytest.raises(ValueError, match="serve_prefill_chunk_tokens"):
        _chunk_cfg(serve_prefill_chunk_tokens=-1)
    # chunks scatter whole blocks: the knob must divide into block units
    with pytest.raises(ValueError, match="multiple"):
        _chunk_cfg(serve_block_tokens=4, serve_prefill_chunk_tokens=6)
    assert _chunk_cfg(serve_block_tokens=4,
                      serve_prefill_chunk_tokens=8) is not None
    assert _chunk_cfg(serve_prefill_chunk_tokens=0) is not None


@pytest.fixture(scope="module")
def monolithic_tokens(chunk_setup):
    cfg, params = chunk_setup
    return _run_engine(cfg, params)


@pytest.mark.parametrize("chunk_tokens", [1, 2, 4, 12])
def test_chunked_prefill_bit_identical_tokens(chunk_setup, monolithic_tokens,
                                              chunk_tokens):
    """Chunked and monolithic prefill sample IDENTICAL tokens (stochastic
    temperature, so logits agree to the bit): every sequence-axis
    reduction runs full-length with masked rows contributing exact 0.0,
    and the clamped ragged last chunk recomputes identical rows."""
    cfg, params = chunk_setup
    chunked = _run_engine(
        _chunk_cfg(serve_prefill_chunk_tokens=chunk_tokens), params)
    assert chunked == monolithic_tokens


def test_aot_round_trip_includes_chunk_executable(tmp_path, chunk_setup):
    """knob > 0 serializes THREE executables; a half-populated pre-chunk
    cache must miss (AOT_FORMAT key bump), and a second engine reloads
    all three with identical outputs."""
    from homebrewnlp_tpu.serve.engine import BatchEngine, aot_cache_key
    _, params = chunk_setup
    cfg = _chunk_cfg(serve_prefill_chunk_tokens=4,
                     serve_aot_cache_dir=str(tmp_path))
    e1 = BatchEngine(cfg, params)
    assert e1.aot_cache_hit is False and e1.compile_s is not None
    key = aot_cache_key(cfg, e1.params, cfg.serve_max_batch)
    assert sorted(os.listdir(tmp_path)) == [
        f"decode-{key}.jaxexec", f"prefill-{key}.jaxexec",
        f"prefill_chunk-{key}.jaxexec"]
    out1 = np.asarray(e1.complete_tokens([1, 2, 3], 0.0, 5))
    e1.close()
    e2 = BatchEngine(cfg, params)
    assert e2.aot_cache_hit is True and e2.aot_reload_s is not None
    assert e2.compile_s is None
    out2 = np.asarray(e2.complete_tokens([1, 2, 3], 0.0, 5))
    assert out1.tolist() == out2.tolist()
    e2.close()


def test_chunk_failure_mid_admission_frees_blocks(chunk_setup):
    """A chunk dispatch failure mid-admission must fail THAT request and
    recycle its whole block allocation — the lane was occupied but never
    armed for decode, so nothing else can clean it up."""
    from homebrewnlp_tpu.serve.engine import BatchEngine
    _, params = chunk_setup
    cfg = _chunk_cfg(serve_prefill_chunk_tokens=2)
    eng = BatchEngine(cfg, params)
    try:
        def broken_chunk(*a, **k):
            raise RuntimeError("injected chunk failure")

        eng._prefill_chunk = broken_chunk
        req = eng.submit([1, 2, 3, 4, 5], 0.7, 4, 0, 1.0)
        with pytest.raises(RuntimeError, match="injected chunk"):
            eng.fetch(req)
        assert eng.kv_blocks_free() == eng.allocator.n_blocks
        assert eng.active_lanes() == 0 and eng.queue_depth() == 0
    finally:
        eng.close()


def _drive_with_stall(cfg, params, prompts, response_len=6):
    """Run the prompts through a fresh engine while a step observer sums
    the stalled-lane-seconds the SLO layer would publish."""
    from homebrewnlp_tpu.serve.engine import BatchEngine
    stall = [0.0]
    eng = BatchEngine(cfg, params)
    eng.set_step_observer(
        lambda wall, phases, n_active, stall_s, stepped:
        stall.__setitem__(0, stall[0] + stall_s))
    try:
        reqs = [eng.submit(list(p), 0.0, response_len, None, None)
                for p in prompts]
        for r in reqs:
            eng.fetch(r)
    finally:
        eng.close()
    return stall[0]


def test_stall_ab_and_idle_admission_zero(chunk_setup):
    """The stall counter is stalled-LANE-seconds: a monolithic admission
    while other lanes decode stalls them (> 0); admission into an IDLE
    engine stalls nobody (== 0); chunked admission dispatches
    asynchronously and NEVER increments the counter."""
    cfg, params = chunk_setup
    burst = ([1, 2], [3, 4, 5, 6, 7, 8], [5, 6, 7])
    # all three queued before the admit scan: the 2nd/3rd monolithic
    # prefills run with >= 1 lane already active — deterministic stall
    mono = _drive_with_stall(cfg, params, burst)
    assert mono > 0.0
    # idle engine, one request: n_stalled snapshots 0 active lanes
    assert _drive_with_stall(cfg, params, ([1, 2, 3],)) == 0.0
    chunked = _drive_with_stall(
        _chunk_cfg(serve_prefill_chunk_tokens=1), params, burst)
    assert chunked == 0.0


def test_decode_interleaves_between_chunks(tmp_path, chunk_setup):
    """The exported lane trace proves the scheduler alternates: a short
    request armed first keeps decoding (engine/dispatch spans) strictly
    between the long prompt's per-chunk ``prefilling`` spans."""
    from homebrewnlp_tpu.serve.engine import BatchEngine
    _, params = chunk_setup
    trace_path = os.path.join(str(tmp_path), "chunked.trace.json")
    cfg = _chunk_cfg(serve_prefill_chunk_tokens=1,
                     serve_trace_path=trace_path)
    eng = BatchEngine(cfg, params)
    try:
        short = eng.submit([1, 2], 0.0, 8, None, None)
        long_ = eng.submit([3] * 8, 0.0, 2, None, None)
        eng.fetch(short)
        eng.fetch(long_)
        long_rid = str(long_.rid)
    finally:
        eng.close()
    with open(trace_path) as f:
        events = json.load(f)["traceEvents"]
    spans = sorted((e["ts"], e["ts"] + e["dur"]) for e in events
                   if e.get("name") == "prefilling" and e.get("ph") == "X"
                   and (e.get("args") or {}).get("rid") == long_rid)
    assert len(spans) == 8, spans  # one span per chunk row
    dispatch = [e["ts"] for e in events
                if e.get("name") == "engine/dispatch" and e.get("ph") == "X"]
    first_end, last_start = spans[0][1], spans[-1][0]
    assert any(first_end < ts < last_start for ts in dispatch), (
        spans, dispatch)


def test_evaluate_serve_baseline_chunked_ratchets():
    """The bench A/B probe's ON arm ratchets once recorded: stall fraction
    with the ratio + 0.05 absolute slack, itl_p95 like the other
    latencies; a baseline without the probe skips (absence is not a
    regression)."""
    import bench
    on = {"prefill_stall_fraction": 0.02, "itl_p95": 0.010}
    row = {"e2e_p50_s": 1.0,
           "chunked_prefill": {"chunk_tokens": 8, "on": dict(on)}}
    base = {"e2e_p50_s": 1.0,
            "chunked_prefill": {"chunk_tokens": 8, "on": dict(on)}}
    out, ok = bench.evaluate_serve_baseline(row, base)
    assert ok and out["chunked_stall_fraction"]["pass"]
    assert out["chunked_itl_p95"]["pass"]
    row["chunked_prefill"]["on"]["prefill_stall_fraction"] = 0.30
    out, ok = bench.evaluate_serve_baseline(row, base)
    # 0.30 > 0.02 * 1.5 + 0.05 = 0.08 -> the stall regressed
    assert not ok and not out["chunked_stall_fraction"]["pass"]
    row["chunked_prefill"]["on"]["prefill_stall_fraction"] = 0.02
    row["chunked_prefill"]["on"]["itl_p95"] = 0.020  # 2x -> fail
    out, ok = bench.evaluate_serve_baseline(row, base)
    assert not ok and not out["chunked_itl_p95"]["pass"]
    out, ok = bench.evaluate_serve_baseline(
        row, {"e2e_p50_s": 1.0})  # probe never recorded -> skipped
    assert ok and "chunked_stall_fraction" not in (out or {})
