"""Fused bottleneck-group-linear block — an EVALUATED EXPERIMENT, measured
REJECT at the 32mixer_group operating point (docs/perf/README.md round 5b:
259.3 ms unfused vs 305.9-310.7 ms across three kernel variants).  The
round-5 byte budget named this block the largest remaining byte pool
(2.441 GB/call x 32 calls = 78 GB of the 177.9 GB step), but after the
mixer fusion the step sits above its bandwidth bound, so removing bytes
from the step's FLOP-densest segment only trades XLA's near-peak batched
GEMM schedule for per-grid-cell matmuls + in-kernel recompute.  The
kernel stays in-tree behind the default-off ``fused_group_linear`` knob
(full parity/accumulation/fallback tests) for shapes that ARE HBM-bound.

The group configs' first block (configs/32mixer_group.json, reference
semantics basic.py:122-126 for the bottleneck MLP + normalization.py:22-34
for the group norms) is the per-position chain

    n   = groupnorm_{s0,h0}(x)            # per-head over K features
    b   = relu(sum_h n[:,h,:] @ W1[h])    # dense bottleneck, W1 [H,K,I]
    m_h = relu(b @ W2[:,h,:])             # per-head widen,   W2 [I,H,J]
    mn  = groupnorm_{s1,h1}(m)            # per-head over J
    out_h = mn_h @ W3[h]                  # per-head out,     W3 [H,J,K]

on a ``[B,S,H,K]`` activation.  Every position is independent (no
sequence mixing), so the batch*sequence product flattens to a row axis N
and the kernels grid over row blocks.  Under XLA each arrow is a full
``[B,S,H,K]``-class HBM round-trip and the backward adds recompute reads
plus f32 grad temporaries.

Why TWO kernels instead of one (the VMEM analysis from the round-5 perf
notes, docs/perf/README.md): a single fused backward must keep all three
f32 dW accumulators (2+4+4 = 10 MB) plus all weights (5 MB bf16) resident
across the row grid — over the ~16 MB/core VMEM budget once row tiles are
added.  Splitting at the bottleneck activation ``b`` (tiny: [N, I] bf16)
gives each kernel only its stage's weights and accumulators:

- kernel IN  (norm0 + W1 + relu):  W1 1 MB + dW1 2 MB f32;
- kernel OUT (W2 + relu + norm1 + W3): W2+W3 4 MB + dW2+dW3 8 MB f32.

``b`` is materialized between them (8 MB for the full workload batch —
0.3% of the unfused block's traffic).  The backward of each kernel
recomputes its stage's internals in VMEM (remat-in-kernel) and
accumulates parameter grads in f32 across the row-grid axis; heads are
python-unrolled so only ONE head's [R, J] intermediates are live at a
time.  All matmuls take calculation-dtype operands with f32 MXU
accumulation and cast back (nd.einsum's policy); norms compute f32 from
the stored dtype (models/layers.py::norm).  Bit-parity with XLA is NOT
expected in bf16 (fusion changes rounding order); f32 parity is pinned in
tests/model_test.py.

The kernels are single-device (used under jit on an unsharded mesh; the
GSPMD/sharded paths keep the unfused chain — same guard as
ops/pallas_mixer.py).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from .pallas_mixer import _norm_bwd, _norm_fwd


def _row_block(n_rows: int, budget_rows: int) -> int:
    """Largest divisor of n_rows <= budget_rows (rows per grid cell)."""
    r = min(budget_rows, n_rows)
    while n_rows % r:
        r -= 1
    return r


# -- kernel IN: norm0 -> dense bottleneck -> relu ---------------------------

def _in_fwd_kernel(x_ref, w1_ref, s0_ref, h0_ref, b_ref, *,
                   n_h: int, key: int):
    cdtype = x_ref.dtype
    f32 = jnp.float32
    # per-head group norms (VPU), then ONE wide MXU matmul over the flat
    # (H*K) contraction -- per-head unrolled [R,K]@[K,I] partial dots
    # measured 20% slower at the workload shape (small-matmul overhead)
    n = jnp.concatenate(
        [_norm_fwd(x_ref[:, h * key:(h + 1) * key].astype(f32),
                   s0_ref[h].astype(f32),
                   h0_ref[h].astype(f32)).astype(cdtype)
         for h in range(n_h)], axis=1)
    acc = jnp.dot(n, w1_ref[...], preferred_element_type=f32)
    b_ref[...] = jax.nn.relu(acc.astype(cdtype))


def _in_bwd_kernel(x_ref, w1_ref, s0_ref, h0_ref, db_ref,
                   dx_ref, dw1_ref, ds0_ref, dh0_ref, *,
                   n_h: int, key: int):
    from jax.experimental import pallas as pl

    cdtype = x_ref.dtype
    f32 = jnp.float32
    r = pl.program_id(0)

    # recompute the forward: per-head norms concatenated, one wide matmul
    n = jnp.concatenate(
        [_norm_fwd(x_ref[:, h * key:(h + 1) * key].astype(f32),
                   s0_ref[h].astype(f32),
                   h0_ref[h].astype(f32)).astype(cdtype)
         for h in range(n_h)], axis=1)
    acc = jnp.dot(n, w1_ref[...], preferred_element_type=f32)
    b = jax.nn.relu(acc.astype(cdtype))
    # relu vjp mask on the cdtype-rounded value, like the unfused chain
    # (comparison runs in f32: mosaic has no bf16 vector cmpf on v5e)
    g = jnp.where(b.astype(f32) > 0, db_ref[...].astype(f32),
                  0).astype(cdtype)

    # dense contractions as single wide MXU matmuls over the flat axis
    dn = jnp.dot(g, w1_ref[...].T, preferred_element_type=f32)
    dw1 = jnp.dot(n.T, g, preferred_element_type=f32)
    # per-head norm vjps (VPU)
    ds0s, dh0s = [], []
    for h in range(n_h):
        xh = x_ref[:, h * key:(h + 1) * key].astype(f32)
        dxh, ds0_h, dh0_h = _norm_bwd(xh, s0_ref[h].astype(f32),
                                      dn[:, h * key:(h + 1) * key])
        dx_ref[:, h * key:(h + 1) * key] = dxh.astype(dx_ref.dtype)
        ds0s.append(ds0_h[None])
        dh0s.append(dh0_h[None])
    ds0 = jnp.concatenate(ds0s, axis=0)
    dh0 = jnp.concatenate(dh0s, axis=0)

    @pl.when(r == 0)
    def _init():
        dw1_ref[...] = dw1
        ds0_ref[...] = ds0
        dh0_ref[...] = dh0

    @pl.when(r != 0)
    def _acc():
        dw1_ref[...] += dw1
        ds0_ref[...] += ds0
        dh0_ref[...] += dh0


# -- kernel OUT: per-head widen -> relu -> norm1 -> per-head out ------------

def _out_fwd_kernel(b_ref, w2_ref, w3_ref, s1_ref, h1_ref, out_ref, *,
                    n_h: int, mid: int, key: int):
    cdtype = b_ref.dtype
    f32 = jnp.float32
    b = b_ref[...]
    # ONE wide widen matmul [R,I]@[I,H*J]; only the block-diagonal W3 stays
    # per-head (its per-head [R,J]@[J,K] tiles are MXU-sized already)
    m2 = jax.nn.relu(
        jnp.dot(b, w2_ref[...], preferred_element_type=f32).astype(cdtype))
    for h in range(n_h):
        mnh = _norm_fwd(m2[:, h * mid:(h + 1) * mid].astype(f32),
                        s1_ref[h].astype(f32),
                        h1_ref[h].astype(f32)).astype(cdtype)
        o = jnp.dot(mnh, w3_ref[h], preferred_element_type=f32)
        out_ref[:, h * key:(h + 1) * key] = o.astype(cdtype)


def _out_bwd_kernel(b_ref, w2_ref, w3_ref, s1_ref, h1_ref, dout_ref,
                    db_ref, dw2_ref, dw3_ref, ds1_ref, dh1_ref, *,
                    n_h: int, mid: int, key: int):
    from jax.experimental import pallas as pl

    cdtype = b_ref.dtype
    f32 = jnp.float32
    r = pl.program_id(0)
    b = b_ref[...]
    # recompute the widen stage with one wide matmul
    m2 = jax.nn.relu(
        jnp.dot(b, w2_ref[...], preferred_element_type=f32).astype(cdtype))
    dms, ds1s, dh1s = [], [], []
    for h in range(n_h):
        # W3 (block-diagonal) + the norm vjp stay per-head
        mh32 = m2[:, h * mid:(h + 1) * mid].astype(f32)
        s1h = s1_ref[h].astype(f32)
        mnh = _norm_fwd(mh32, s1h, h1_ref[h].astype(f32)).astype(cdtype)

        douth = dout_ref[:, h * key:(h + 1) * key]
        dmnh = jnp.dot(douth, w3_ref[h].T, preferred_element_type=f32)
        dw3_h = jnp.dot(mnh.T.astype(cdtype), douth,
                        preferred_element_type=f32)
        dmh, ds1_h, dh1_h = _norm_bwd(mh32, s1h, dmnh)
        # relu mask compared in f32 (mosaic: no bf16 vector cmpf on v5e)
        dmh = jnp.where(mh32 > 0, dmh, 0).astype(cdtype)
        dms.append(dmh)
        ds1s.append(ds1_h[None])
        dh1s.append(dh1_h[None])

        @pl.when(r == 0)
        def _init(h=h, dw3_h=dw3_h):
            dw3_ref[h] = dw3_h

        @pl.when(r != 0)
        def _acc(h=h, dw3_h=dw3_h):
            dw3_ref[h] += dw3_h

    dm = jnp.concatenate(dms, axis=1)
    # dense contractions as single wide MXU matmuls
    dw2 = jnp.dot(b.T, dm, preferred_element_type=f32)
    db_ref[...] = jnp.dot(dm, w2_ref[...].T,
                          preferred_element_type=f32).astype(db_ref.dtype)
    ds1 = jnp.concatenate(ds1s, axis=0)
    dh1 = jnp.concatenate(dh1s, axis=0)

    @pl.when(r == 0)
    def _init2():
        dw2_ref[...] = dw2
        ds1_ref[...] = ds1
        dh1_ref[...] = dh1

    @pl.when(r != 0)
    def _acc2():
        dw2_ref[...] += dw2
        ds1_ref[...] += ds1
        dh1_ref[...] += dh1


# -- pallas_call wrappers ---------------------------------------------------

def _whole(shape):
    from jax.experimental import pallas as pl
    n = len(shape)
    return pl.BlockSpec(shape, lambda r, _n=n: (0,) * _n)


@functools.partial(jax.jit, static_argnames=("interpret",))
def _in_pallas(x2d, w1, s0, h0, interpret: bool = False):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n, hk = x2d.shape
    n_h, key, inter = w1.shape
    w1f = w1.reshape(hk, inter)  # flat (H*K, I): one wide MXU contraction
    rows = _row_block(n, 512)  # 1024 measured 16.16M -- over the vmem limit
    x_spec = pl.BlockSpec((rows, hk), lambda r: (r, 0))
    b_spec = pl.BlockSpec((rows, inter), lambda r: (r, 0))
    out = pl.pallas_call(
        functools.partial(_in_fwd_kernel, n_h=n_h, key=key),
        grid=(n // rows,),
        in_specs=[x_spec, _whole(w1f.shape), _whole(s0.shape),
                  _whole(h0.shape)],
        out_specs=b_spec,
        out_shape=jax.ShapeDtypeStruct((n, inter), x2d.dtype),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel",)),
        interpret=interpret,
    )(x2d, w1f, s0, h0)
    return out


@functools.partial(jax.jit, static_argnames=("interpret",))
def _in_bwd_pallas(x2d, w1, s0, h0, db, interpret: bool = False):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n, hk = x2d.shape
    n_h, key, inter = w1.shape
    w1f = w1.reshape(hk, inter)
    # smaller than the fwd budget: the bwd cell holds x+dx+n tiles, the
    # f32 dW1 accumulator and norm-vjp temps (512 rows measured 18.5 MB on
    # v5e -- over the 16 MB scoped-vmem limit; 256 fits)
    rows = _row_block(n, 256)
    f32 = jnp.float32
    x_spec = pl.BlockSpec((rows, hk), lambda r: (r, 0))
    b_spec = pl.BlockSpec((rows, inter), lambda r: (r, 0))
    outs = (jax.ShapeDtypeStruct((n, hk), x2d.dtype),     # dx
            jax.ShapeDtypeStruct(w1f.shape, f32),         # dW1 (flat)
            jax.ShapeDtypeStruct(s0.shape, f32),          # dscale0
            jax.ShapeDtypeStruct(h0.shape, f32))          # dshift0
    res = pl.pallas_call(
        functools.partial(_in_bwd_kernel, n_h=n_h, key=key),
        grid=(n // rows,),
        in_specs=[x_spec, _whole(w1f.shape), _whole(s0.shape),
                  _whole(h0.shape), b_spec],
        out_specs=(x_spec, _whole(w1f.shape), _whole(s0.shape),
                   _whole(h0.shape)),
        out_shape=outs,
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("arbitrary",)),
        interpret=interpret,
    )(x2d, w1f, s0, h0, db)
    dx, dw1f, ds0, dh0 = res
    return dx, dw1f.reshape(w1.shape), ds0, dh0


@functools.partial(jax.jit, static_argnames=("interpret",))
def _out_pallas(b, w2, w3, s1, h1, interpret: bool = False):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n, inter = b.shape
    n_h, mid, key = w3.shape
    w2f = w2.reshape(inter, n_h * mid)  # storage [I,H,J] flat: (I, H*J)
    rows = _row_block(n, 512)
    b_spec = pl.BlockSpec((rows, inter), lambda r: (r, 0))
    o_spec = pl.BlockSpec((rows, n_h * key), lambda r: (r, 0))
    out = pl.pallas_call(
        functools.partial(_out_fwd_kernel, n_h=n_h, mid=mid, key=key),
        grid=(n // rows,),
        in_specs=[b_spec, _whole(w2f.shape), _whole(w3.shape),
                  _whole(s1.shape), _whole(h1.shape)],
        out_specs=o_spec,
        out_shape=jax.ShapeDtypeStruct((n, n_h * key), b.dtype),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel",)),
        interpret=interpret,
    )(b, w2f, w3, s1, h1)
    return out


@functools.partial(jax.jit, static_argnames=("interpret",))
def _out_bwd_pallas(b, w2, w3, s1, h1, dout, interpret: bool = False):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n, inter = b.shape
    n_h, mid, key = w3.shape
    w2f = w2.reshape(inter, n_h * mid)
    # bwd budget: W2+W3 (4 MB) + f32 dW2+dW3 (8 MB) are VMEM-resident, so
    # row tiles get the remainder
    rows = _row_block(n, 128)
    f32 = jnp.float32
    b_spec = pl.BlockSpec((rows, inter), lambda r: (r, 0))
    o_spec = pl.BlockSpec((rows, n_h * key), lambda r: (r, 0))
    outs = (jax.ShapeDtypeStruct((n, inter), b.dtype),    # db
            jax.ShapeDtypeStruct(w2f.shape, f32),         # dW2 (flat)
            jax.ShapeDtypeStruct(w3.shape, f32),          # dW3
            jax.ShapeDtypeStruct(s1.shape, f32),          # dscale1
            jax.ShapeDtypeStruct(h1.shape, f32))          # dshift1
    res = pl.pallas_call(
        functools.partial(_out_bwd_kernel, n_h=n_h, mid=mid, key=key),
        grid=(n // rows,),
        in_specs=[b_spec, _whole(w2f.shape), _whole(w3.shape),
                  _whole(s1.shape), _whole(h1.shape), o_spec],
        out_specs=(b_spec, _whole(w2f.shape), _whole(w3.shape),
                   _whole(s1.shape), _whole(h1.shape)),
        out_shape=outs,
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("arbitrary",)),
        interpret=interpret,
    )(b, w2f, w3, s1, h1, dout)
    db, dw2f, dw3, ds1, dh1 = res
    return db, dw2f.reshape(w2.shape), dw3, ds1, dh1


# -- public op --------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(8,))
def fused_group_linear_block(x, w1, w2, w3, s0, h0, s1, h1,
                             interpret: bool = False):
    """norm -> dense bottleneck -> relu -> per-head widen -> relu -> norm ->
    per-head out, as two pallas kernels split at the bottleneck activation.

    x: [B,S,H,K]; w1: [H,K,I]; w2: [I,H,J] (the model's storage layout —
    flattened to (I, H*J) for the wide widen matmul); w3: [H,J,K];
    s0/h0: [H,K]; s1/h1: [H,J] (all calculation dtype).  Param cotangents
    come back in the primal dtype (f32-accumulated in-kernel, cast on
    exit)."""
    n_b, seq, n_h, key = x.shape
    x2d = x.reshape(n_b * seq, n_h * key)
    b = _in_pallas(x2d, w1, s0, h0, interpret=interpret)
    out = _out_pallas(b, w2, w3, s1, h1, interpret=interpret)
    return out.reshape(x.shape)


def _fgl_fwd(x, w1, w2, w3, s0, h0, s1, h1, interpret: bool = False):
    n_b, seq, n_h, key = x.shape
    x2d = x.reshape(n_b * seq, n_h * key)
    b = _in_pallas(x2d, w1, s0, h0, interpret=interpret)
    out = _out_pallas(b, w2, w3, s1, h1, interpret=interpret)
    # b rides in the residuals: [N, I] bf16 is ~0.3% of the block's unfused
    # traffic and saves a whole kernel-IN recompute pass in the backward
    # (under revnet the residual lives only inside the reconstruction vjp)
    return out.reshape(x.shape), (x, w1, w2, w3, s0, h0, s1, h1, b)


def _fgl_bwd(interpret, res, dout):
    x, w1, w2, w3, s0, h0, s1, h1, b = res
    n_b, seq, n_h, key = x.shape
    x2d = x.reshape(n_b * seq, n_h * key)
    dout2d = dout.reshape(n_b * seq, n_h * key)
    db, dw2, dw3, ds1, dh1 = _out_bwd_pallas(b, w2, w3, s1, h1, dout2d,
                                             interpret=interpret)
    dx2d, dw1, ds0, dh0 = _in_bwd_pallas(x2d, w1, s0, h0, db,
                                         interpret=interpret)
    return (dx2d.reshape(x.shape), dw1.astype(w1.dtype),
            dw2.astype(w2.dtype), dw3.astype(w3.dtype),
            ds0.astype(s0.dtype), dh0.astype(h0.dtype),
            ds1.astype(s1.dtype), dh1.astype(h1.dtype))


fused_group_linear_block.defvjp(_fgl_fwd, _fgl_bwd)


def group_chain_reference(x, w1, w2, w3, s0, h0, s1, h1):
    """The unfused chain as plain jnp on [B,S,H,K] (same math the layer
    stack composes) — parity oracle for the kernels."""
    cdtype = x.dtype
    f32 = jnp.float32

    def norm(t, scale, shift):
        t32 = t.astype(f32)
        m1 = jnp.mean(t32, axis=-1, keepdims=True)
        m2 = jnp.mean(t32 * t32, axis=-1, keepdims=True)
        var = jnp.maximum(m2 - m1 * m1, 0.0)
        mul = jax.lax.rsqrt(var + 1e-5) * scale[None, None].astype(f32)
        add = shift[None, None].astype(f32) - m1 * mul
        return (t32 * mul + add).astype(cdtype)

    n = norm(x, s0, h0)
    b = jnp.einsum("bshk,hki->bsi", n, w1,
                   preferred_element_type=f32).astype(cdtype)
    b = jax.nn.relu(b)
    m = jnp.einsum("bsi,ihj->bshj", b, w2,
                   preferred_element_type=f32).astype(cdtype)
    m = jax.nn.relu(m)
    mn = norm(m, s1, h1)
    out = jnp.einsum("bshj,hjk->bshk", mn, w3,
                     preferred_element_type=f32).astype(cdtype)
    return out
