"""Pallas TPU kernel for causal bias-map ("mixer") attention.

The flagship mixer layers (configs/32big_mixer.json block 2) use attention
with a LEARNED per-head position-pair map and no dot-product: per layer

    out[b,s,h,k] = sum_{t<=s} bias[h,s,t] * val[b,t,h,k]

XLA executes this as mask-multiply + full [S,S]@[S,K] batched matmul — it
cannot skip the strictly-upper-triangular tiles the causal mask zeroes.  This
kernel tiles the row/col axes at the 128-lane MXU size and only issues the
lower-triangle tile matmuls (4 row tiles at S=512: 10 of 16 tile products,
asymptotically 2x fewer MXU FLOPs), masking just the diagonal tiles on the
VPU.  f32 accumulation, output cast back to the value dtype.

The backward pass stays in XLA einsums (jax.custom_vjp below).

**Status: evaluated and REJECTED for the production path** (docs/perf/
README.md): measured on a real v5e at flagship shapes the kernel is bit-exact
but 10-25% slower than the XLA masked einsum — XLA's batched-matmul
pipelining beats the 1.6x causal FLOP skip.  models/layers.py::attention
keeps the einsum (reference semantics: spatial.py:19-23,65-75); this module
remains as the measured experiment with an interpret-mode parity test.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

TILE = 128


def _fwd_kernel(bias_ref, val_ref, out_ref, *, seq: int, key: int):
    n = seq // TILE
    for i in range(n):
        width = (i + 1) * TILE
        b = bias_ref[0, i * TILE:(i + 1) * TILE, 0:width]
        # causal mask: row (i*TILE + r) sees columns <= that row; only the
        # last column tile is partial, but one fused where is VPU-cheap
        row = jax.lax.broadcasted_iota(jnp.int32, (TILE, width), 0)
        col = jax.lax.broadcasted_iota(jnp.int32, (TILE, width), 1)
        b = jnp.where(row + i * TILE >= col, b, jnp.zeros_like(b))
        v = val_ref[0, 0:width, :]
        acc = jnp.dot(b, v, preferred_element_type=jnp.float32)
        out_ref[0, i * TILE:(i + 1) * TILE, :] = acc.astype(out_ref.dtype)


@functools.partial(jax.jit, static_argnames=("interpret",))
def _fwd_pallas(bias: jnp.ndarray, val: jnp.ndarray, interpret: bool = False
                ) -> jnp.ndarray:
    """bias [H,S,S], val [B,S,H,K] -> out [B,S,H,K]."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n_b, seq, n_h, key = val.shape
    # view the (head, key) pair as one lane axis so the per-head block is a
    # [seq, key] column slice — pallas requires the trailing block dims be
    # lane/sublane aligned, which a size-1 head axis is not
    val2 = val.reshape(n_b, seq, n_h * key)
    kern = functools.partial(_fwd_kernel, seq=seq, key=key)
    # batch is the fastest-varying grid axis: the bias block index is then
    # unchanged across consecutive steps, so pallas skips re-fetching the
    # [seq, seq] map for every batch row
    grid = (n_h, n_b)
    out = pl.pallas_call(
        kern,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, seq, seq), lambda h, b: (h, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, seq, key), lambda h, b: (b, 0, h),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, seq, key), lambda h, b: (b, 0, h),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct(val2.shape, val.dtype),
        interpret=interpret,
    )(bias, val2)
    return out.reshape(val.shape)


def _tril(seq: int, dtype) -> jnp.ndarray:
    row = jax.lax.broadcasted_iota(jnp.int32, (seq, seq), 0)
    col = jax.lax.broadcasted_iota(jnp.int32, (seq, seq), 1)
    return (row >= col).astype(dtype)


def _fwd_einsum(bias: jnp.ndarray, val: jnp.ndarray) -> jnp.ndarray:
    masked = (bias.astype(jnp.float32)
              * _tril(bias.shape[-1], jnp.float32)).astype(bias.dtype)
    out = jnp.einsum("hst,bthk->bshk", masked, val,
                     preferred_element_type=jnp.float32)
    return out.astype(val.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def causal_map_attention(bias: jnp.ndarray, val: jnp.ndarray,
                         use_pallas: bool = True) -> jnp.ndarray:
    """out[b,s,h,k] = sum_{t<=s} bias[h,s,t] * val[b,t,h,k]."""
    if use_pallas:
        return _fwd_pallas(bias, val)
    return _fwd_einsum(bias, val)


def _vjp_fwd(bias, val, use_pallas):
    return causal_map_attention(bias, val, use_pallas), (bias, val)


def _vjp_bwd(use_pallas, res, d_out):
    bias, val = res
    tril = _tril(bias.shape[-1], jnp.float32)
    masked = (bias.astype(jnp.float32) * tril).astype(bias.dtype)
    d_val = jnp.einsum("hst,bshk->bthk", masked, d_out,
                       preferred_element_type=jnp.float32).astype(val.dtype)
    d_bias = jnp.einsum("bshk,bthk->hst", d_out, val,
                        preferred_element_type=jnp.float32)
    d_bias = (d_bias * tril).astype(bias.dtype)
    return d_bias, d_val


causal_map_attention.defvjp(_vjp_fwd, _vjp_bwd)


def pallas_eligible(seq: int, key: int, backend: str) -> bool:
    return (backend in ("tpu", "axon") and seq % TILE == 0
            and key % TILE == 0)
