"""Reversible residual streams with O(1) activation memory.

The reference implements reversible (RevNet) and MomentumNet layers by cloning
graph operations and hand-walking them in reverse inside Mesh-TF
(/root/reference/src/model/revnet.py:14-120, momentumnet.py:14-125).  The JAX
equivalent is a ``custom_vjp`` over the whole chain: forward stores only the
two final streams; backward reconstructs each block's inputs from its outputs
and re-plays the block under ``jax.vjp``.  Works unchanged under pjit/shard_map
because reconstruction is ordinary traced computation.

Chain state is a pair of like-shaped pytrees (x1, x2):
  revnet step   : (x1, x2) -> (x2, x1 + f(p, x2))          [final out: x1 + x2]
  momentum step : (x, v)   -> (x + v', v'),  v' = a*v + (1-a)*f(p, x)
The reference's 4-tuple stream (x, x_backwards, v, v_backwards) carries the
reconstruction slots explicitly; here they are implicit in the vjp residuals.
"""
from __future__ import annotations

import typing

import jax

Pytree = typing.Any


def make_reversible_chain(fs: typing.Sequence[typing.Callable],
                          mode: str = "revnet", alpha: float = 0.99,
                          cotangent_dtype=None, remat_blocks: bool = False):
    """Build a reversible chain over residual-branch functions ``fs``.

    Each ``fs[i](params_i, x) -> y`` must be shape-preserving and
    deterministic (re-executed during backward).  Returns
    ``chain(params_tuple, x1, x2) -> (y1, y2)``.

    ``cotangent_dtype`` (e.g. ``jnp.bfloat16``) inserts a precision squash
    on the inter-block cotangent streams during backward: dy1/dy2 are
    rounded through the reduced dtype between blocks (cast down and back
    up, so each block's vjp still sees cotangents of its output dtype —
    vjp rejects a dtype mismatch outright).  None keeps the exact default.

    ``remat_blocks`` wraps blocks in ``jax.checkpoint`` for the
    backward's ``jax.vjp`` replay: the replay forward then stores no
    internal residuals (norm stats, pre-activations, widened mids) and the
    pullback recomputes them — more FLOPs for fewer HBM bytes, profitable
    exactly when the step sits on the bandwidth roofline while the MXU is
    idle (docs/perf/README.md round 4: the 32mixer_group workload).
    Numerics are unchanged (same math, different schedule).  A bool
    applies to every block; a per-block sequence lets callers skip blocks
    that are already byte-minimal (round 5: a fused-kernel block's
    custom_vjp stores only its inputs, so checkpointing it would re-add
    the exact recompute the kernel already performs).
    """
    fs = tuple(fs)
    if isinstance(remat_blocks, (list, tuple)):
        assert len(remat_blocks) == len(fs), (len(remat_blocks), len(fs))
        remat_flags = tuple(bool(r) for r in remat_blocks)
    else:
        remat_flags = (bool(remat_blocks),) * len(fs)

    tsub = jax.tree_util.tree_map
    if mode == "revnet":
        def step(f, p, x1, x2):
            return x2, tsub(lambda a, b: a + b, x1, f(p, x2))

        def inv_and_grads(f, p, y1, y2, dy1, dy2, remat):
            x2 = y1
            fx, vjp = jax.vjp(jax.checkpoint(f) if remat else f, p, x2)
            x1 = tsub(lambda a, b: a - b, y2, fx)
            dp, dx2_f = vjp(dy2)
            dx1 = dy2
            dx2 = tsub(lambda a, b: a + b, dy1, dx2_f)
            return x1, x2, dx1, dx2, dp
    elif mode == "momentum":
        def step(f, p, x, v):
            fx = f(p, x)
            new_v = tsub(lambda a, b: alpha * a + (1 - alpha) * b, v, fx)
            new_x = tsub(lambda a, b: a + b, x, new_v)
            return new_x, new_v

        def inv_and_grads(f, p, y1, y2, dy1, dy2, remat):
            # y1 = x + v', y2 = v' = a*v + (1-a)*f(p, x)
            x = tsub(lambda a, b: a - b, y1, y2)
            fx, vjp = jax.vjp(jax.checkpoint(f) if remat else f, p, x)
            v = tsub(lambda a, b: (a - (1 - alpha) * b) / alpha, y2, fx)
            d_sum = tsub(lambda a, b: a + b, dy1, dy2)
            dp, dx_f = vjp(tsub(lambda a: (1 - alpha) * a, d_sum))
            dx = tsub(lambda a, b: a + b, dy1, dx_f)
            dv = tsub(lambda a: alpha * a, d_sum)
            return x, v, dx, dv, dp
    else:
        raise ValueError(f"unknown reversible mode {mode}")

    def forward(params, x1, x2):
        for f, p in zip(fs, params):
            x1, x2 = step(f, p, x1, x2)
        return x1, x2

    @jax.custom_vjp
    def chain(params, x1, x2):
        return forward(params, x1, x2)

    def chain_fwd(params, x1, x2):
        y1, y2 = forward(params, x1, x2)
        return (y1, y2), (params, y1, y2)

    def chain_bwd(res, cotangents):
        params, y1, y2 = res
        dy1, dy2 = cotangents
        dparams = [None] * len(fs)
        for i in range(len(fs) - 1, -1, -1):
            y1, y2, dy1, dy2, dparams[i] = inv_and_grads(
                fs[i], params[i], y1, y2, dy1, dy2, remat_flags[i])
            if cotangent_dtype is not None and i > 0:
                squash = lambda d: d.astype(cotangent_dtype).astype(d.dtype)
                dy1 = tsub(squash, dy1)
                dy2 = tsub(squash, dy2)
        return tuple(dparams), dy1, dy2

    chain.defvjp(chain_fwd, chain_bwd)
    return chain
