"""Triangular bias-map attention for long sequences (the 32ctx FLOP lever).

The mixer attention ``out[b,s,h,k] = sum_{t<=s} bias[h,s,t] * val[b,t,h,k]``
(reference spatial.py:19-23,65-75) is a masked [S,S]@[S,K] matmul.  XLA
executes the FULL rectangle (the causal mask only zeroes operands), and at
seq 2048 the seq^2 map family is over half the 32ctx step's 46.4 TFLOP —
the step is compute-bound at 50.6% MFU (docs/perf/README.md), so skipping
the strictly-upper-triangular tile products is the lever that pays there:
(n+1)/2n of the tile matmuls at n = S/256 row tiles (56% at n=8), applied
to the forward AND both backward contractions.

Round 2 measured a whole-[S,S]-resident variant (ops/pallas_attn.py) LOSING
10-25% at the flagship's seq 512 — that step is HBM-bound, where a FLOP
skip buys nothing.  This module is the large-S redesign: row/column PANELS
of the map are blocked per grid cell and the triangular inner loop runs as
a ``fori_loop`` over dynamic 256-aligned slices (mosaic supports
lane-dynamic reads/writes at these alignments — probed on v5e).  Block
residency is sized for the 16 MB scoped-VMEM limit: the fwd/dval value and
cotangent panels split the per-head key axis across the grid (a full-batch
[B,S,K] panel measured 18.25 MB double-buffered — over the limit), and the
dbias kernel walks per-batch value blocks while its [TILE,S] f32 row panel
accumulates across the batch grid axis (b fastest, init at b==0).

Three kernels:

- fwd   (grid hk,i,b): bias row panel [T,S] x val half-panel -> out rows
- dval  (grid hk,j,b): bias col panel [S,T]^T x dout half-panel -> dval
- dbias (grid h,i,b):  dout rows x val^T -> dbias row panel [T,S] f32

The kernels keep the model's [B,S,H,K] activation layout ((head,key) viewed
as one lane axis — no relayouts) and never materialize the masked bias,
removing the mask-multiply traffic as a side effect.  Dtype walk matches
nd.einsum: calculation-dtype operands, f32 MXU accumulation, cast on exit
(dbias accumulates f32 across batch and casts outside the kernel).

Single-device (same guard as the other fused kernels); the GSPMD/sharded
paths keep the einsum chain.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

TILE = 256
KSPLIT = 128  # lane-axis half-panel width for the fwd/dval value blocks


def _diag_mask(t: int):
    row = jax.lax.broadcasted_iota(jnp.int32, (t, t), 0)
    col = jax.lax.broadcasted_iota(jnp.int32, (t, t), 1)
    return row >= col


def _fwd_kernel(bias_ref, val_ref, out_ref, *, n_tiles: int):
    from jax.experimental import pallas as pl

    i = pl.program_id(1)
    b = pl.program_id(2)
    f32 = jnp.float32
    t = TILE
    k = out_ref.shape[-1]

    def body(j, acc):
        bt = bias_ref[0, :, pl.ds(j * t, t)]
        vt = val_ref[b, pl.ds(j * t, t), :]
        return acc + jnp.dot(bt, vt, preferred_element_type=f32)

    acc = jax.lax.fori_loop(0, i, body, jnp.zeros((t, k), f32))
    # diagonal tile: rows i*t+r see columns <= their own position
    bt = bias_ref[0, :, pl.ds(i * t, t)]
    bt = jnp.where(_diag_mask(t), bt, jnp.zeros_like(bt))
    vt = val_ref[b, pl.ds(i * t, t), :]
    acc = acc + jnp.dot(bt, vt, preferred_element_type=f32)
    out_ref[0] = acc.astype(out_ref.dtype)


def _dval_kernel(bias_ref, dout_ref, dval_ref, *, n_tiles: int):
    from jax.experimental import pallas as pl

    j = pl.program_id(1)
    b = pl.program_id(2)
    f32 = jnp.float32
    t = TILE
    k = dval_ref.shape[-1]
    cdims = (((0,), (0,)), ((), ()))  # bias^T: contract the row axis

    def body(i, acc):
        bt = bias_ref[0, pl.ds(i * t, t), :]
        dt = dout_ref[b, pl.ds(i * t, t), :]
        return acc + jax.lax.dot_general(bt, dt, cdims,
                                         preferred_element_type=f32)

    acc = jax.lax.fori_loop(j + 1, n_tiles, body, jnp.zeros((t, k), f32))
    bt = bias_ref[0, pl.ds(j * t, t), :]
    bt = jnp.where(_diag_mask(t), bt, jnp.zeros_like(bt))
    dt = dout_ref[b, pl.ds(j * t, t), :]
    acc = acc + jax.lax.dot_general(bt, dt, cdims,
                                    preferred_element_type=f32)
    dval_ref[0] = acc.astype(dval_ref.dtype)


def _dbias_kernel(dout_ref, val_ref, dbias_ref, *, n_tiles: int):
    from jax.experimental import pallas as pl

    i = pl.program_id(1)
    b = pl.program_id(2)
    f32 = jnp.float32
    t = TILE
    cdims = (((1,), (1,)), ((), ()))  # contract the key axis

    @pl.when(b == 0)
    def _zero():
        dbias_ref[0] = jnp.zeros_like(dbias_ref[0])

    dt = dout_ref[0]

    def body(j, _):
        vt = val_ref[0, pl.ds(j * t, t), :]
        prod = jax.lax.dot_general(dt, vt, cdims,
                                   preferred_element_type=f32)
        dbias_ref[0, :, pl.ds(j * t, t)] += prod
        return 0

    jax.lax.fori_loop(0, i, body, 0)
    vt = val_ref[0, pl.ds(i * t, t), :]
    prod = jax.lax.dot_general(dt, vt, cdims, preferred_element_type=f32)
    prod = jnp.where(_diag_mask(t), prod, jnp.zeros_like(prod))
    dbias_ref[0, :, pl.ds(i * t, t)] += prod


def _grid_call(kern, grid, specs, out_spec, out_shape, interpret, *args):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    return pl.pallas_call(
        kern, grid=grid, in_specs=specs, out_specs=out_spec,
        out_shape=out_shape,
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("arbitrary", "arbitrary", "arbitrary")),
        interpret=interpret,
    )(*args)


def _ksplit(key: int) -> int:
    return KSPLIT if key % KSPLIT == 0 and key > KSPLIT else key


@functools.partial(jax.jit, static_argnames=("interpret",))
def _fwd(bias, val, interpret: bool = False):
    from jax.experimental import pallas as pl

    n_b, seq, n_h, key = val.shape
    n = seq // TILE
    ks = _ksplit(key)
    splits = key // ks  # key half-panels per head; grid axis 0 = h*splits
    val2 = val.reshape(n_b, seq, n_h * key)
    out = _grid_call(
        functools.partial(_fwd_kernel, n_tiles=n),
        (n_h * splits, n, n_b),
        [pl.BlockSpec((1, TILE, seq),
                      lambda hk, i, b: (hk // splits, i, 0)),
         # full-batch per-(head, key-half) value panel: constant across the
         # row/batch grid axes, sized to half the double-buffered VMEM limit
         pl.BlockSpec((n_b, seq, ks), lambda hk, i, b: (0, 0, hk))],
        pl.BlockSpec((1, TILE, ks), lambda hk, i, b: (b, i, hk)),
        jax.ShapeDtypeStruct(val2.shape, val.dtype),
        interpret, bias, val2)
    return out.reshape(val.shape)


@functools.partial(jax.jit, static_argnames=("interpret",))
def _dval(bias, dout, interpret: bool = False):
    from jax.experimental import pallas as pl

    n_b, seq, n_h, key = dout.shape
    n = seq // TILE
    ks = _ksplit(key)
    splits = key // ks
    dout2 = dout.reshape(n_b, seq, n_h * key)
    dval = _grid_call(
        functools.partial(_dval_kernel, n_tiles=n),
        (n_h * splits, n, n_b),
        [pl.BlockSpec((1, seq, TILE),
                      lambda hk, j, b: (hk // splits, 0, j)),
         pl.BlockSpec((n_b, seq, ks), lambda hk, j, b: (0, 0, hk))],
        pl.BlockSpec((1, TILE, ks), lambda hk, j, b: (b, j, hk)),
        jax.ShapeDtypeStruct(dout2.shape, dout.dtype),
        interpret, bias, dout2)
    return dval.reshape(dout.shape)


@functools.partial(jax.jit, static_argnames=("interpret",))
def _dbias(dout, val, interpret: bool = False):
    from jax.experimental import pallas as pl

    n_b, seq, n_h, key = val.shape
    n = seq // TILE
    val2 = val.reshape(n_b, seq, n_h * key)
    dout2 = dout.reshape(n_b, seq, n_h * key)
    dbias = _grid_call(
        functools.partial(_dbias_kernel, n_tiles=n),
        (n_h, n, n_b),
        [pl.BlockSpec((1, TILE, key), lambda h, i, b: (b, i, h)),
         # per-batch value block (a full-batch panel would double-buffer
         # over the VMEM limit); refetched per grid step — ~0.6 ms/call of
         # overlapped DMA at the 32ctx shape
         pl.BlockSpec((1, seq, key), lambda h, i, b: (b, 0, h))],
        pl.BlockSpec((1, TILE, seq), lambda h, i, b: (h, i, 0)),
        jax.ShapeDtypeStruct((n_h, seq, seq), jnp.float32),
        interpret, dout2, val2)
    return dbias


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def tri_map_attention(bias, val, interpret: bool = False):
    """out[b,s,h,k] = sum_{t<=s} bias[h,s,t] * val[b,t,h,k].

    bias [H,S,S] UNMASKED (the causal triangle is applied in-kernel);
    val [B,S,H,K]; both in the calculation dtype.  Equivalent to
    ``einsum(bias * tril, val)`` with nd.einsum's f32-accumulate policy;
    executes only the lower-triangle tile products."""
    return _fwd(bias, val, interpret=interpret)


def _tri_vjp_fwd(bias, val, interpret: bool = False):
    return _fwd(bias, val, interpret=interpret), (bias, val)


def _tri_vjp_bwd(interpret, res, dout):
    bias, val = res
    d_val = _dval(bias, dout, interpret=interpret)
    d_bias = _dbias(dout, val, interpret=interpret)
    return d_bias.astype(bias.dtype), d_val


tri_map_attention.defvjp(_tri_vjp_fwd, _tri_vjp_bwd)


def tri_reference(bias, val):
    """Masked-einsum oracle (the unfused model path's math)."""
    seq = bias.shape[-1]
    row = jax.lax.broadcasted_iota(jnp.int32, (seq, seq), 0)
    col = jax.lax.broadcasted_iota(jnp.int32, (seq, seq), 1)
    masked = bias * (row >= col).astype(bias.dtype)
    out = jnp.einsum("hst,bthk->bshk", masked, val,
                     preferred_element_type=jnp.float32)
    return out.astype(val.dtype)


def tri_eligible(seq: int, key: int, n_b: int, backend: str) -> bool:
    """Tiling + residency constraints: 256-aligned seq, lane-aligned key,
    and the full-batch (key-split) value half-panel must fit VMEM
    double-buffered next to a bias panel."""
    ks = KSPLIT if key % KSPLIT == 0 and key > KSPLIT else key
    return (backend in ("tpu", "axon", "cpu")
            and seq % TILE == 0
            and key % 128 == 0
            and n_b * seq * ks * 2 * 2 <= 11 * 1024 * 1024)
