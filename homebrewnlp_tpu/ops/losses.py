"""Loss functions.

z-loss-regularized softmax cross-entropy follows the reference's stable
formulation (/root/reference/src/mtf_wrapper.py:64-75): loss =
-mean(logit_target - log_z) + z_loss * mean(log_z^2).  Accumulation happens in
float32 (the reference sums in the bf16 activation dtype; on TPU the f32
accumulation is free via the MXU/VPU accumulators and strictly better
numerically).
"""
from __future__ import annotations

import typing

import jax
import jax.numpy as jnp

from .. import nd
from ..config import VOCAB
from ..nd import NT


def softmax_cross_entropy_with_logits(logits: NT, targets: NT, z_loss: float
                                      ) -> jnp.ndarray:
    """logits [..., vocab] f32/bf16; targets [...] int; returns scalar f32."""
    x = logits.x.astype(jnp.float32)
    vocab_axis = logits.names.index(VOCAB)
    max_logit = jax.lax.stop_gradient(jnp.max(x, axis=vocab_axis, keepdims=True))
    log_z = jnp.log(jnp.sum(jnp.exp(x - max_logit), axis=vocab_axis,
                            keepdims=True)) + max_logit
    tgt = jnp.expand_dims(targets.x.astype(jnp.int32), vocab_axis)
    logit_tgt = jnp.take_along_axis(x, tgt, axis=vocab_axis)
    size = targets.size
    loss = -jnp.sum(logit_tgt - log_z) / size
    if z_loss:
        loss = loss + jnp.sum(jnp.square(log_z)) * (z_loss / size)
    return loss


def accuracy(logits: NT, targets: NT) -> jnp.ndarray:
    vocab_axis = logits.names.index(VOCAB)
    pred = jnp.argmax(logits.x, axis=vocab_axis)
    return jnp.mean((pred == targets.x.astype(pred.dtype)).astype(jnp.float32))


def video_l1_loss(frame_out: NT, vid_tgt: NT, vid_msk: typing.Optional[NT],
                  cat_msk: typing.Optional[NT]) -> typing.Tuple[jnp.ndarray, jnp.ndarray]:
    """Masked L1 via sign-einsum (reference src/model/__init__.py:189-199).
    Returns (train_loss, display_loss) — display is renormalized by mask
    density."""
    diff = frame_out - vid_tgt
    factors = [diff, nd.stop_gradient(NT(jnp.sign(diff.x), diff.names))]
    if vid_msk is not None:
        factors.append(vid_msk)
    if cat_msk is not None:
        factors.append(cat_msk)
    prod = factors[0]
    for f in factors[1:]:
        prod = prod * f
    loss = jnp.sum(prod.x.astype(jnp.float32)) / frame_out.size
    display = loss
    if vid_msk is not None:
        display = display * (vid_msk.size / jnp.maximum(
            jnp.sum(vid_msk.x.astype(jnp.float32)), 1.0))
    if cat_msk is not None:
        display = display * (cat_msk.size / jnp.maximum(
            jnp.sum(cat_msk.x.astype(jnp.float32)), 1.0))
    return loss, display
