"""Ring attention: sequence-parallel exact attention over the mesh.

The reference has no sequence parallelism at all — long context is attacked
with memory-reduction tricks only (SURVEY.md §5.7); this module is the
TPU-native extension that makes the ``sequence_parallel`` mesh axis
first-class.  Design (Liu et al. 2023 ring attention / flash-style online
softmax): queries stay put, K/V blocks rotate around the ring via
``jax.lax.ppermute`` over ICI; each hop contracts the local Q block against
the visiting K/V block and folds the result into running (max, denominator,
accumulator) statistics, so the full softmax is exact while no device ever
holds more than one (s_local x s_local) logit block.

Used from models/layers.attention through ``shard_map`` when the mesh's
sequence axis is >1 and the layer is plain dot-product attention; the
bias-map mixer variants keep the GSPMD path (their learned seq x seq maps are
row-sharded parameters instead).
"""
from __future__ import annotations

import functools
import typing

import jax
import jax.numpy as jnp

NEG_INF = -2e38  # the reference's mask value (spatial.py:68)


def _block(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
           row0: jnp.ndarray, col0: jnp.ndarray, causal: bool,
           m: jnp.ndarray, l: jnp.ndarray, acc: jnp.ndarray):
    """Fold one K/V block into the running softmax statistics.

    q [b, sq, h, d]; k/v [b, sk, h, d]; m/l [b, h, sq]; acc [b, sq, h, d];
    row0/col0 are the global offsets of the local q rows / visiting k cols.
    """
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k)
    if causal:
        rows = row0 + jnp.arange(q.shape[1])
        cols = col0 + jnp.arange(k.shape[1])
        mask = rows[:, None] >= cols[None, :]
        logits = jnp.where(mask[None, None], logits, NEG_INF)
    block_max = jnp.max(logits, axis=-1)  # [b, h, q]
    new_m = jnp.maximum(m, block_max)
    correction = jnp.exp(m - new_m)
    p = jnp.exp(logits - new_m[..., None])  # [b, h, q, k]
    new_l = l * correction + jnp.sum(p, axis=-1)
    pv = jnp.einsum("bhqk,bkhd->bqhd", p, v)
    new_acc = acc * correction.transpose(0, 2, 1)[..., None] + pv
    return new_m, new_l, new_acc


def ring_attention_kernel(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                          axis_name: str, causal: bool = True) -> jnp.ndarray:
    """Per-shard body (run under shard_map): exact attention over the ring.

    All inputs are local blocks [b, s_local, h, d] of the sequence-sharded
    global arrays; returns the local output block."""
    n = jax.lax.axis_size(axis_name)
    idx = jax.lax.axis_index(axis_name)
    s_local = q.shape[1]
    row0 = idx * s_local

    m = jnp.full(q.shape[:1] + (q.shape[2], s_local), NEG_INF,
                 jnp.float32)  # [b, h, sq]
    l = jnp.zeros_like(m)
    acc = jnp.zeros(q.shape, jnp.float32)
    qf = q.astype(jnp.float32)

    def fold(kv, vv, col_shard, m, l, acc):
        # k/v ride the ring in their input dtype (half the ICI bytes under
        # bf16); the f32 upcast happens per-block, and the f32 m/l/acc
        # accumulators keep the softmax exact
        return _block(qf, kv.astype(jnp.float32), vv.astype(jnp.float32),
                      row0, col_shard * s_local, causal, m, l, acc)

    # hop 0: own block, no rotation; hops 1..n-1 rotate first then fold, so
    # exactly n-1 ppermute pairs ride the ring
    m, l, acc = fold(k, v, idx, m, l, acc)
    perm = [(j, (j + 1) % n) for j in range(n)]

    def hop(i, carry):
        kc, vc, m, l, acc = carry
        kc = jax.lax.ppermute(kc, axis_name, perm)
        vc = jax.lax.ppermute(vc, axis_name, perm)
        m, l, acc = fold(kc, vc, (idx - i) % n, m, l, acc)
        return kc, vc, m, l, acc

    _, _, m, l, acc = jax.lax.fori_loop(1, n, hop, (k, v, m, l, acc))
    out = acc / jnp.maximum(l, 1e-30).transpose(0, 2, 1)[..., None]
    return out.astype(q.dtype)


def ring_attention(q, k, v, mesh, seq_axis: str, spec, causal: bool = True):
    """shard_map wrapper: q/k/v are global [b, s, h, d] arrays inside jit;
    ``spec`` is their full PartitionSpec (batch/seq/heads dims per the
    caller's sharding rules — heads stay model-sharded inside the kernel)."""
    kernel = functools.partial(ring_attention_kernel, axis_name=seq_axis,
                               causal=causal)
    return jax.shard_map(kernel, mesh=mesh, in_specs=(spec, spec, spec),
                         out_specs=spec, check_vma=False)(q, k, v)
