"""Ring attention: sequence-parallel exact attention over the mesh.

The reference has no sequence parallelism at all — long context is attacked
with memory-reduction tricks only (SURVEY.md §5.7); this module is the
TPU-native extension that makes the ``sequence_parallel`` mesh axis
first-class.  Design (Liu et al. 2023 ring attention / flash-style online
softmax): queries stay put, K/V blocks rotate around the ring via
``jax.lax.ppermute`` over ICI; each hop contracts the local Q block against
the visiting K/V block and folds the result into running (max, denominator,
accumulator) statistics, so the full softmax is exact while no device ever
holds more than one (s_local x s_local) logit block.

Differentiation is a ``jax.custom_vjp`` with an explicit flash-style ring
backward rather than autodiff through the forward ring: the backward saves
only the per-row softmax stats (m, l — O(b*h*s), never an [s, s] block) and
recomputes each probability block from the visiting K/V as the gradient
accumulators ride one full lap around the ring (dk/dv travel WITH their
blocks and arrive home after n hops).  Besides the memory profile, the
explicit vjp is what lets the ring NEST inside the pipeline's manual region:
autodiff through a nested shard_map forwards region-internal residuals into
the transposed region, which the shardy partitioner cannot express when
those residuals are also varying over the outer (pipe) axis — with
custom_vjp, only explicit arguments with explicit specs ever cross a region
boundary.

Used from models/layers.attention when the mesh's sequence axis is >1 and
the layer is plain dot-product attention; the bias-map mixer variants keep
the GSPMD path (their learned seq x seq maps are row-sharded parameters
instead).

Composition with pipeline parallelism: when the caller already sits inside a
manual ``shard_map`` region (the pipeline stage body, ops/pipeline.py —
manual over ONLY the pipe axis), ``ring_attention`` opens a NESTED region
over the context mesh that manualizes just the sequence axis; data/model
axes stay automatic in both regions.  Three lowering constraints shape the
code: the inner region's specs may only name its own (seq) axis;
``jax.lax.axis_index`` cannot lower inside a nested manual region under the
shardy partitioner, so the kernel takes its ring position as a seq-sharded
iota argument; and the nested region keeps vma typing ON — with
``check_vma=False`` its output would drop the varying-over-pipe type and the
enclosing region's transpose would insert a hidden psum over the pipe axis,
silently summing every stage's cotangent into each (measured: body grads off
by O(1) relative while the forward stayed exact).
"""
from __future__ import annotations

import functools
import typing

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec

NEG_INF = -2e38  # the reference's mask value (spatial.py:68)


def _match_vma(x: jnp.ndarray, target: frozenset) -> jnp.ndarray:
    """pvary ``x`` over whatever axes of ``target`` it is not yet varying
    over (idempotent — pcast rejects no-ops).  Under ``check_vma=False``
    every vma set is empty and this is a no-op; under the typed nested
    region the loop carries below must enter with their steady-state vma."""
    have = getattr(jax.typeof(x), "vma", frozenset())
    missing = tuple(a for a in target if a not in have)
    return jax.lax.pcast(x, missing, to="varying") if missing else x


def _input_vma(*tensors) -> frozenset:
    return frozenset().union(*(getattr(jax.typeof(t), "vma", frozenset())
                               for t in tensors))


def _block(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
           row0: jnp.ndarray, col0: jnp.ndarray, causal: bool,
           m: jnp.ndarray, l: jnp.ndarray, acc: jnp.ndarray):
    """Fold one K/V block into the running softmax statistics.

    q [b, sq, h, d]; k/v [b, sk, h, d]; m/l [b, h, sq]; acc [b, sq, h, d];
    row0/col0 are the global offsets of the local q rows / visiting k cols.
    """
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k)
    if causal:
        rows = row0 + jnp.arange(q.shape[1])
        cols = col0 + jnp.arange(k.shape[1])
        mask = rows[:, None] >= cols[None, :]
        logits = jnp.where(mask[None, None], logits, NEG_INF)
    block_max = jnp.max(logits, axis=-1)  # [b, h, q]
    new_m = jnp.maximum(m, block_max)
    correction = jnp.exp(m - new_m)
    p = jnp.exp(logits - new_m[..., None])  # [b, h, q, k]
    new_l = l * correction + jnp.sum(p, axis=-1)
    pv = jnp.einsum("bhqk,bkhd->bqhd", p, v)
    new_acc = acc * correction.transpose(0, 2, 1)[..., None] + pv
    return new_m, new_l, new_acc


def ring_attention_kernel(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                          idx_arr: jnp.ndarray, axis_name: str,
                          n_shards: int, causal: bool = True):
    """Per-shard forward (run under shard_map): exact attention over the
    ring.  All inputs are local blocks [b, s_local, h, d] of the
    sequence-sharded global arrays; returns ``(out, m, l)`` — the local
    output block plus the f32 row stats [b, h, s_local] the backward needs.
    ``idx_arr`` is this shard's slice of a seq-sharded ``arange(n_shards)``
    — its one element is the shard's ring position (``jax.lax.axis_index``
    cannot lower inside a nested manual region, so the position arrives as
    data)."""
    n = n_shards
    idx = idx_arr[0]
    s_local = q.shape[1]
    row0 = idx * s_local

    vma = _input_vma(q, k, v, idx_arr)
    m = _match_vma(jnp.full(q.shape[:1] + (q.shape[2], s_local), NEG_INF,
                            jnp.float32), vma)  # [b, h, sq]
    l = _match_vma(jnp.zeros(m.shape, jnp.float32), vma)
    acc = _match_vma(jnp.zeros(q.shape, jnp.float32), vma)
    k = _match_vma(k, vma)
    v = _match_vma(v, vma)
    qf = q.astype(jnp.float32)

    def fold(kv, vv, col_shard, m, l, acc):
        # k/v ride the ring in their input dtype (half the ICI bytes under
        # bf16); the f32 upcast happens per-block, and the f32 m/l/acc
        # accumulators keep the softmax exact
        return _block(qf, kv.astype(jnp.float32), vv.astype(jnp.float32),
                      row0, col_shard * s_local, causal, m, l, acc)

    # hop 0: own block, no rotation; hops 1..n-1 rotate first then fold, so
    # exactly n-1 ppermute pairs ride the ring
    m, l, acc = fold(k, v, idx, m, l, acc)
    perm = [(j, (j + 1) % n) for j in range(n)]

    def hop(i, carry):
        kc, vc, m, l, acc = carry
        kc = jax.lax.ppermute(kc, axis_name, perm)
        vc = jax.lax.ppermute(vc, axis_name, perm)
        m, l, acc = fold(kc, vc, (idx - i) % n, m, l, acc)
        return kc, vc, m, l, acc

    _, _, m, l, acc = jax.lax.fori_loop(1, n, hop, (k, v, m, l, acc))
    out = acc / jnp.maximum(l, 1e-30).transpose(0, 2, 1)[..., None]
    return out.astype(q.dtype), m, l


def ring_attention_bwd_kernel(q, k, v, idx_arr, out, m, l, dout,
                              axis_name: str, n_shards: int,
                              causal: bool = True):
    """Per-shard backward: flash-style recompute over one full ring lap.

    Each probability block is rebuilt from the saved row stats (m, l) as the
    K/V blocks revisit; dk/dv accumulators travel WITH their blocks, so
    after ``n_shards`` process-and-rotate steps every block's gradient has
    collected its contribution from every query shard and sits back on its
    home device.  Identity: with normalized p = exp(z - m)/l,
    ``ds = p * (dp - rowsum(dout * out))`` — the softmax normalizer's
    derivative is already inside (standard flash attention backward)."""
    n = n_shards
    idx = idx_arr[0]
    s_local = q.shape[1]
    row0 = idx * s_local
    f32 = jnp.float32
    qf = q.astype(f32)
    doutf = dout.astype(f32)
    inv_l = 1.0 / jnp.maximum(l, 1e-30)  # [b, h, sq], matches the fwd clamp
    D = jnp.einsum("bshd,bshd->bhs", doutf, out.astype(f32))  # [b, h, sq]

    vma = _input_vma(q, k, v, idx_arr, out, m, l, dout)
    dq = _match_vma(jnp.zeros(q.shape, f32), vma)
    kc = _match_vma(k, vma)
    vc = _match_vma(v, vma)
    dkc = _match_vma(jnp.zeros(k.shape, f32), vma)
    dvc = _match_vma(jnp.zeros(v.shape, f32), vma)
    perm = [(j, (j + 1) % n) for j in range(n)]

    def fold(kc, vc, dkc, dvc, dq, i):
        """Accumulate the local queries' contribution to the visiting block
        (idx - i) and to dq."""
        kf = kc.astype(f32)
        vf = vc.astype(f32)
        col0 = jnp.mod(idx - i, n) * s_local
        logits = jnp.einsum("bqhd,bkhd->bhqk", qf, kf)
        if causal:
            rows = row0 + jnp.arange(s_local)
            cols = col0 + jnp.arange(s_local)
            mask = rows[:, None] >= cols[None, :]
            logits = jnp.where(mask[None, None], logits, NEG_INF)
        p = jnp.exp(logits - m[..., None]) * inv_l[..., None]
        dvc = dvc + jnp.einsum("bhqk,bqhd->bkhd", p, doutf)
        dp = jnp.einsum("bqhd,bkhd->bhqk", doutf, vf)
        ds = p * (dp - D[..., None])
        dq = dq + jnp.einsum("bhqk,bkhd->bqhd", ds, kf)
        dkc = dkc + jnp.einsum("bhqk,bqhd->bkhd", ds, qf)
        return dkc, dvc, dq

    # mirror the forward's hop structure: fold the own block first, then
    # rotate-and-fold n-1 times, so kc/vc ride exactly n-1 ppermute pairs
    # (a process-then-rotate loop would send one dead K/V rotation per
    # call — XLA cannot DCE collectives out of the loop body); dkc/dvc
    # take one extra hop after the loop to land back on their home shard
    dkc, dvc, dq = fold(kc, vc, dkc, dvc, dq, 0)

    def step(i, carry):
        kc, vc, dkc, dvc, dq = carry
        kc = jax.lax.ppermute(kc, axis_name, perm)
        vc = jax.lax.ppermute(vc, axis_name, perm)
        dkc = jax.lax.ppermute(dkc, axis_name, perm)
        dvc = jax.lax.ppermute(dvc, axis_name, perm)
        dkc, dvc, dq = fold(kc, vc, dkc, dvc, dq, i)
        return kc, vc, dkc, dvc, dq

    _, _, dkc, dvc, dq = jax.lax.fori_loop(
        1, n, step, (kc, vc, dkc, dvc, dq))
    dkc = jax.lax.ppermute(dkc, axis_name, perm)
    dvc = jax.lax.ppermute(dvc, axis_name, perm)
    return dq.astype(q.dtype), dkc.astype(k.dtype), dvc.astype(v.dtype)


def _seq_only(spec: PartitionSpec, seq_axis: str) -> PartitionSpec:
    """The spec as seen by a NESTED region that manualizes only the seq
    axis: every other entry must be None (specs may only name axes the
    region itself manualizes; data/model sharding stays automatic)."""
    return PartitionSpec(*[p if p == seq_axis else None for p in spec])


def _run(kernel, args, mesh, seq_axis: str, in_specs, out_specs):
    """Dispatch one ring kernel as a top-level (all-manual, untyped) or
    nested (seq-manual, vma-typed) shard_map region."""
    manual = getattr(jax.sharding.get_abstract_mesh(), "manual_axes", ())
    if manual:
        assert seq_axis not in manual, (
            f"ring_attention cannot nest inside a region already manual "
            f"over {seq_axis!r}")
        in_s = tuple(_seq_only(s, seq_axis) for s in in_specs)
        out_s = tuple(_seq_only(s, seq_axis) for s in out_specs)
        return jax.shard_map(kernel, in_specs=in_s, out_specs=out_s,
                             axis_names=frozenset({seq_axis}))(*args)
    return jax.shard_map(kernel, mesh=mesh, in_specs=in_specs,
                         out_specs=out_specs, check_vma=False)(*args)


def _specs(spec: PartitionSpec):
    """(tensor spec, row-stats spec): stats are [b, h, sq] from a
    [b, s, h, d] tensor spec."""
    e = list(spec) + [None] * (4 - len(list(spec)))
    return spec, PartitionSpec(e[0], e[2], e[1])


@functools.partial(jax.custom_vjp, nondiff_argnums=(0, 1, 2, 3))
def _ring_attention(mesh, seq_axis, spec, causal, q, k, v):
    out, _, _ = _ring_fwd(mesh, seq_axis, spec, causal, q, k, v)
    return out


def _ring_fwd(mesh, seq_axis, spec, causal, q, k, v):
    n = mesh.shape[seq_axis]
    kernel = functools.partial(ring_attention_kernel, axis_name=seq_axis,
                               n_shards=n, causal=causal)
    idxs = jnp.arange(n, dtype=jnp.int32)
    tspec, sspec = _specs(spec)
    idx_spec = PartitionSpec(seq_axis)
    return _run(kernel, (q, k, v, idxs), mesh, seq_axis,
                (tspec, tspec, tspec, idx_spec), (tspec, sspec, sspec))


def _ring_attention_vjp_fwd(mesh, seq_axis, spec, causal, q, k, v):
    out, m, l = _ring_fwd(mesh, seq_axis, spec, causal, q, k, v)
    return out, (q, k, v, out, m, l)


def _ring_attention_vjp_bwd(mesh, seq_axis, spec, causal, res, dout):
    q, k, v, out, m, l = res
    n = mesh.shape[seq_axis]
    kernel = functools.partial(ring_attention_bwd_kernel, axis_name=seq_axis,
                               n_shards=n, causal=causal)
    # Partial-eval barrier (load-bearing): when this vjp is staged out under
    # delayed partial evaluation (jax.grad around the enclosing jit /
    # shard_map), every cotangent-independent subcomputation of the backward
    # is "known" and gets hoisted into the FORWARD pass as residuals — and
    # since the whole flash recompute (masks, logits, probabilities) depends
    # only on saved residuals, all of it qualifies.  That defeats the
    # recompute's O(b*h*s) memory profile outright, and inside the pipeline
    # the hoisted seq-manual values cannot be expressed by the partitioner
    # at all when they also vary over the pipe axis (sdy rejects the factor
    # order; this is why seq x pipe additionally requires the 1f1b schedule,
    # whose per-tick jax.vjp never delays the backward — config.py).  A
    # zero-valued data dependency on the cotangent makes every kernel input
    # "unknown", pinning the entire kernel to the backward pass; XLA folds
    # the zero after partitioning, so the runtime cost is nil.
    zero = dout.ravel()[0] * 0
    izero = zero.astype(jnp.int32)
    q, k, v, out, m, l = (t + zero.astype(t.dtype)
                          for t in (q, k, v, out, m, l))
    idxs = jnp.arange(n, dtype=jnp.int32) + izero
    tspec, sspec = _specs(spec)
    idx_spec = PartitionSpec(seq_axis)
    return _run(kernel, (q, k, v, idxs, out, m, l, dout), mesh, seq_axis,
                (tspec, tspec, tspec, idx_spec, tspec, sspec, sspec, tspec),
                (tspec, tspec, tspec))


_ring_attention.defvjp(_ring_attention_vjp_fwd, _ring_attention_vjp_bwd)


def ring_attention(q, k, v, mesh, seq_axis: str, spec,
                   causal: bool = True) -> jnp.ndarray:
    """shard_map wrapper: q/k/v are global [b, s, h, d] arrays inside jit;
    ``spec`` is their full PartitionSpec (batch/seq/heads dims per the
    caller's sharding rules — heads stay model-sharded inside the kernel).

    Inside an enclosing manual region (the pipeline stage body), the call
    nests over the context mesh manualizing only ``seq_axis`` — see the
    module docstring for the constraints that shape this."""
    return _ring_attention(mesh, seq_axis, spec, causal, q, k, v)
