"""Parameter initializers.

Orthogonal init reproduces the reference's fan computation, sign-corrected QR
and ``1/sqrt(depth)`` last-layer scaling (/root/reference/src/model/backend.py:18-40);
normal init mirrors ``normal_var`` (backend.py:103-105).
"""
from __future__ import annotations

import typing

import jax
import jax.numpy as jnp


def feature_dims_used(names: typing.Sequence[str],
                      feature_names: typing.Sequence[str]) -> bool:
    """True when at least half of {heads, key, _heads, _key} appear
    (reference utils_mtf.py:354-361)."""
    anon = ["_" + n for n in feature_names]
    return sum(n in names for n in list(feature_names) + anon) // 2 > 0


def default_fan_in(names: typing.Sequence[str], feature_names: typing.Sequence[str]
                   ) -> typing.Sequence[str]:
    """Fan-in dims when not explicitly given (reference utils_mtf.py:429-436)."""
    if feature_dims_used(names, feature_names):
        return names[:2]
    return names[:1]


def orthogonal_init(sizes: typing.Sequence[int], fan_in_sizes: typing.Sequence[int],
                    scale: float = 1.0):
    """Returns init_fn(key, shape)->f32 with sign-corrected QR orthogonality."""
    fan_in = 1
    for s in fan_in_sizes:
        fan_in *= int(s)
    total = 1
    for s in sizes:
        total *= int(s)
    fan_out = total // max(fan_in, 1)
    transpose = fan_out > fan_in
    qr_shape = (fan_out, fan_in) if transpose else (fan_in, fan_out)

    def init(key, shape):
        del shape
        a = jax.random.normal(key, qr_shape, dtype=jnp.float32)
        q, r = jnp.linalg.qr(a)
        q = q * jnp.sign(jnp.diagonal(r))
        if transpose:
            q = q.T
        out = q.reshape(tuple(int(s) for s in sizes))
        return out * scale

    return init


def normal_init(stddev: float = 0.02, mean: float = 0.0):
    def init(key, shape):
        return jax.random.normal(key, shape, dtype=jnp.float32) * stddev + mean

    return init


def constant_init(value: float = 0.0):
    def init(key, shape):
        del key
        return jnp.full(shape, value, dtype=jnp.float32)

    return init
