"""Activation registry.

The reference hand-writes forward/backward slicewise op pairs for Mish, SiLU,
LeCunTanh and Softsign purely to avoid storing activations in Mesh-TF
(/root/reference/src/model/activation.py:13-145).  On TPU/XLA that machinery is
counter-productive: elementwise chains fuse into the surrounding matmuls and
`jax.checkpoint` governs what is stored, so these are plain jnp functions.
LeCunTanh keeps the reference's (nonstandard) ``tanh(x) + 0.1 x`` definition
(activation.py:96).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..nd import NT


def _wrap(fn):
    def inner(t: NT) -> NT:
        return NT(fn(t.x), t.names)

    return inner


def mish(x):
    return x * jnp.tanh(jax.nn.softplus(x))


def lecun_tanh(x):
    return jnp.tanh(x) + x * 0.1


def softsign(x):
    return x / (1 + jnp.abs(x))


ACTIVATIONS = {
    "relu": _wrap(jax.nn.relu),
    "sigmoid": _wrap(jax.nn.sigmoid),
    "tanh": _wrap(jnp.tanh),
    "gelu": _wrap(jax.nn.gelu),
    "lecun_tanh": _wrap(lecun_tanh),
    "silu": _wrap(jax.nn.silu),
    "mish": _wrap(mish),
    "mtf_mish": _wrap(mish),
    "softsign": _wrap(softsign),
    "exp": _wrap(jnp.exp),
}


def activate(args) -> NT:
    """Dispatch on the first known activation name in the DSL extras
    (reference activation.py:201-211); identity fallback."""
    for fn_name in args:
        if fn_name in ACTIVATIONS:
            return ACTIVATIONS[fn_name](args.tensor)
    return args.tensor
