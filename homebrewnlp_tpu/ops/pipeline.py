"""GPipe-style pipeline parallelism over a mesh axis.

The reference has no pipeline parallelism (SURVEY.md §2.12 row PP); round 1
shipped the knob as a dead axis and round 2 first removed it.  This is the
real implementation: the body's layer stack is split into P contiguous
stages, each living on one coordinate of the ``pipeline`` mesh axis; the
batch is split into M microbatches that flow through the stages in the
classic GPipe schedule (M + P - 1 ticks), activations hopping stages via
``ppermute`` over ICI.  Gradients flow through the schedule exactly
(``ppermute`` transposes to the reverse rotation), verified against the
sequential composition in tests.

Mechanics (jax >= 0.8 shard_map typing):
- ``shard_map`` is manual over ONLY the pipe axis (``axis_names``); data /
  model / sequence axes stay automatic, so GSPMD keeps handling batch and
  head sharding inside each stage.
- the scan carry is ``pvary``-ed over the pipe axis up front so its
  varying-manual-axes type is loop-invariant.
- the output keeps the pipe axis SHARDED (each stage returns its slice;
  only the last stage's slice holds data) — claiming replication instead
  breaks the transpose rule and silently corrupts gradients.

Stage parameters arrive STACKED: a pytree whose leaves have a leading
``[P, ...]`` stage axis, sharded over the pipe axis, so each device holds
exactly its stage's weights inside the manual region.  Since round 3 the
body's parameters are CREATED stage-stacked (models.stack_pipeline_params,
applied at Trainer.init) and their optimizer slots are sharded the same way,
so per-device body param + optimizer memory is 1/P and there is no per-step
stack/gather — true per-stage weight residency, not just compute overlap.
"""
from __future__ import annotations

import typing

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec


def gpipe(stage_fn: typing.Callable, stacked_params, x: jnp.ndarray,
          n_stages: int, n_micro: int, mesh: Mesh,
          axis: str = "pipeline") -> jnp.ndarray:
    """Apply ``n_stages`` sequential stages to ``x`` with GPipe overlap.

    ``stage_fn(stage_params, stage_index, x_micro) -> y_micro`` runs ONE
    stage on one microbatch (stage_params = the pytree with the leading
    stage axis already stripped).  ``x`` is [B, ...]; B must divide by
    ``n_micro``.  Returns [B, ...] after all stages.
    """
    assert x.shape[0] % n_micro == 0, (x.shape, n_micro)

    def body(params, xs):
        params = jax.tree_util.tree_map(lambda p: p[0], params)
        idx = jax.lax.axis_index(axis)
        micro = jax.lax.pcast(
            xs.reshape((n_micro, xs.shape[0] // n_micro) + xs.shape[1:]),
            (axis,), to="varying")
        buf = jnp.zeros_like(micro[0])
        outs = jnp.zeros_like(micro)
        perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]

        def tick(carry, t):
            buf, outs = carry
            # boolean where-selects, not arithmetic masking: warm-up/drain
            # ticks compute on zero or stale rotated activations, and a
            # non-finite garbage y would poison real lanes via NaN*0=NaN
            inject = (idx == 0) & (t < n_micro)
            feed = jnp.where(inject, micro[jnp.minimum(t, n_micro - 1)], buf)
            y = stage_fn(params, idx, feed)
            emit_t = t - (n_stages - 1)
            mask = ((jnp.arange(n_micro) == emit_t)
                    & (idx == n_stages - 1))
            mask = mask.reshape((n_micro,) + (1,) * y.ndim)
            outs = jnp.where(mask, y[None], outs)
            buf = jax.lax.ppermute(y, axis, perm)
            return (buf, outs), None

        (_, outs), _ = jax.lax.scan(tick, (buf, outs),
                                    jnp.arange(n_micro + n_stages - 1))
        return outs[None]  # [1(stage), M, b/M, ...] — pipe stays sharded

    leading = PartitionSpec(axis)
    param_specs = jax.tree_util.tree_map(lambda _: leading, stacked_params)
    piped = jax.shard_map(
        body, mesh=mesh, axis_names=frozenset({axis}),
        in_specs=(param_specs, PartitionSpec()),
        out_specs=PartitionSpec(axis))
    outs = piped(stacked_params, x)      # [P, M, b/M, ...]
    final = outs[n_stages - 1]           # last stage's slice
    return final.reshape(x.shape)
