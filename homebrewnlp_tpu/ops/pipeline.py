"""GPipe-style pipeline parallelism over a mesh axis.

The reference has no pipeline parallelism (SURVEY.md §2.12 row PP); round 1
shipped the knob as a dead axis and round 2 first removed it.  This is the
real implementation: the body's layer stack is split into P contiguous
stages, each living on one coordinate of the ``pipeline`` mesh axis; the
batch is split into M microbatches that flow through the stages in the
classic GPipe schedule (M + P - 1 ticks), activations hopping stages via
``ppermute`` over ICI.  Gradients flow through the schedule exactly
(``ppermute`` transposes to the reverse rotation), verified against the
sequential composition in tests.

Mechanics (jax >= 0.8 shard_map typing):
- ``shard_map`` is manual over ONLY the pipe axis (``axis_names``); data /
  model / sequence axes stay automatic, so GSPMD keeps handling batch and
  head sharding inside each stage.  A stage body may open a NESTED manual
  region over an axis that is still automatic here — the sequence-parallel
  ring attention does exactly that (ops/ring.py), which is how seq and pipe
  parallelism compose.
- the scan carry is ``pvary``-ed over the pipe axis up front so its
  varying-manual-axes type is loop-invariant.
- the output keeps the pipe axis SHARDED (each stage returns its slice;
  only the last stage's slice holds data) — claiming replication instead
  breaks the transpose rule and silently corrupts gradients.

Stage parameters arrive STACKED: a pytree whose leaves have a leading
``[P, ...]`` stage axis, sharded over the pipe axis, so each device holds
exactly its stage's weights inside the manual region.  Since round 3 the
body's parameters are CREATED stage-stacked (models.stack_pipeline_params,
applied at Trainer.init) and their optimizer slots are sharded the same way,
so per-device body param + optimizer memory is 1/P and there is no per-step
stack/gather — true per-stage weight residency, not just compute overlap.
"""
from __future__ import annotations

import typing

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec


def gpipe(stage_fn: typing.Callable, stacked_params, x: jnp.ndarray,
          n_stages: int, n_micro: int, mesh: Mesh,
          axis: str = "pipeline", with_aux: bool = False):
    """Apply ``n_stages`` sequential stages to ``x`` with GPipe overlap.

    ``stage_fn(stage_params, stage_index, x_micro) -> y_micro`` runs ONE
    stage on one microbatch (stage_params = the pytree with the leading
    stage axis already stripped).  ``x`` is [B, ...]; B must divide by
    ``n_micro``.  Returns [B, ...] after all stages.

    ``with_aux``: stage_fn returns ``(y_micro, aux_loss_scalar)`` instead;
    valid ticks' aux terms are averaged over microbatches, summed over
    stages, and returned as ``(y, aux_total)`` — so the forward/eval path
    of an aux-carrying model (routed-MoE balance) reports the same total
    loss as the 1F1B training path."""
    assert x.shape[0] % n_micro == 0, (x.shape, n_micro)

    def body(params, xs):
        params = jax.tree_util.tree_map(lambda p: p[0], params)
        idx = jax.lax.axis_index(axis)
        micro = jax.lax.pcast(
            xs.reshape((n_micro, xs.shape[0] // n_micro) + xs.shape[1:]),
            (axis,), to="varying")
        buf = jnp.zeros_like(micro[0])
        outs = jnp.zeros_like(micro)
        aux_acc = jax.lax.pcast(jnp.zeros((), jnp.float32), (axis,),
                                to="varying")
        perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]

        def tick(carry, t):
            buf, outs, aux_acc = carry
            # boolean where-selects, not arithmetic masking: warm-up/drain
            # ticks compute on zero or stale rotated activations, and a
            # non-finite garbage y would poison real lanes via NaN*0=NaN
            inject = (idx == 0) & (t < n_micro)
            feed = jnp.where(inject, micro[jnp.minimum(t, n_micro - 1)], buf)
            if with_aux:
                y, aux = stage_fn(params, idx, feed)
                m_f = t - idx
                fvalid = (m_f >= 0) & (m_f < n_micro)
                aux_acc = aux_acc + jnp.where(
                    fvalid, aux.astype(jnp.float32) / n_micro, 0)
            else:
                y = stage_fn(params, idx, feed)
            emit_t = t - (n_stages - 1)
            mask = ((jnp.arange(n_micro) == emit_t)
                    & (idx == n_stages - 1))
            mask = mask.reshape((n_micro,) + (1,) * y.ndim)
            outs = jnp.where(mask, y[None], outs)
            buf = jax.lax.ppermute(y, axis, perm)
            return (buf, outs, aux_acc), None

        (_, outs, aux_acc), _ = jax.lax.scan(
            tick, (buf, outs, aux_acc), jnp.arange(n_micro + n_stages - 1))
        # [1(stage), ...] — pipe stays sharded
        return outs[None], aux_acc[None]

    leading = PartitionSpec(axis)
    param_specs = jax.tree_util.tree_map(lambda _: leading, stacked_params)
    piped = jax.shard_map(
        body, mesh=mesh, axis_names=frozenset({axis}),
        in_specs=(param_specs, PartitionSpec()),
        out_specs=(PartitionSpec(axis), PartitionSpec(axis)))
    outs, aux_p = piped(stacked_params, x)   # [P, M, b/M, ...], [P]
    final = outs[n_stages - 1].reshape(x.shape)
    if with_aux:
        return final, jnp.sum(aux_p)
    return final


def pipeline_1f1b(stage_fn: typing.Callable, tail_fn: typing.Callable,
                  stacked_params, tail_params, x: jnp.ndarray,
                  tail_args: typing.Sequence[jnp.ndarray],
                  n_stages: int, n_micro: int, mesh: Mesh,
                  axis: str = "pipeline"):
    """One-forward-one-backward (1F1B) pipeline schedule computing the LOSS
    AND ALL GRADIENTS in a single interleaved scan.

    GPipe (above) runs all-forward-then-all-backward under autodiff, so the
    forward scan's per-tick stage residuals — every microbatch's internals —
    coexist until the backward consumes them: peak activation state grows
    with M.  1F1B starts microbatch m's backward on the last stage in the
    same tick its forward completes; a stage's forward stash therefore only
    holds the microbatches currently in flight between its forward and
    backward — a ring of ``2*P`` stage INPUTS, independent of M — and each
    backward tick recomputes its block internals from the stashed input
    (``jax.vjp`` replay), trading FLOPs for the M-proportional residual
    memory.  The loss must ride inside the schedule (the cotangent that
    seeds microbatch m's backward is d loss_m / d y_m), which is why this
    op takes ``tail_fn`` instead of composing with an outer ``jax.grad``:

      stage_fn(stage_params, stage_idx, x_micro)
          -> (y_micro, stage_aux_loss)   # y shape-kept; stage_aux_loss: a
                                         # scalar LOSS term arising inside
                                         # the stage (e.g. the routed-MoE
                                         # balance loss), averaged over
                                         # microbatches and summed over
                                         # stages into the total — its
                                         # cotangent seeds the stage vjp
                                         # alongside the activation's
      tail_fn(tail_params, y_micro, *tail_args_micro)
          -> (scalar mean loss, aux)   # aux: pytree of scalar metrics
                                       # (e.g. accuracy), averaged over
                                       # microbatches like the loss

    Schedule: scan step k runs forward tick ``f = k`` (exactly GPipe's) and
    backward tick ``b = k - (P-1)``; stage s handles microbatch ``k - s``
    forward and ``k - 2(P-1) + s`` backward, so the last stage's backward
    consumes the forward output produced in the same step.  Total steps
    ``M + 2P - 2``; each device does at most one forward and one backward
    stage-call per step (steady-state 1F1B).

    Returns ``(loss, aux, dstacked, dtail, dx)``: the mean loss (tail
    loss + stage aux-loss terms) and aux metrics over all microbatches,
    gradients in the stacked [P, ...] layout, gradients for
    ``tail_params`` (f32), and the cotangent of ``x``.

    Known overhead: the tail (output projection over the vocab + loss +
    its vjp) runs on EVERY stage every tick and only the last stage's
    result survives the where-select, so ~(P-1)/P of the tail compute — a
    d_model x vocab matmul + backward per tick — is discarded.  This is
    forced by SPMD: all devices in the shard_map region trace one program
    with uniform shapes, a ``lax.cond`` on the (per-device) stage index
    lowers to select-both-branches on TPU, and a smaller dummy tail input
    on non-last stages would break shape uniformity.  For the byte-level
    configs shipped here (vocab 256) the tail is <1% of a tick; on a
    large-vocab model prefer more pipeline microbatches (amortizes every
    per-tick overhead) or a factorized vocab projection
    (``vocab_weight_factorization``) which shrinks the discarded matmul
    to d_model x factor.
    """
    assert x.shape[0] % n_micro == 0, (x.shape, n_micro)
    P, M = n_stages, n_micro
    S = 2 * P  # stash ring: ticks between fwd and bwd on stage s = 2(P-1-s)

    def body(stacked, tailp, xs, targs):
        params = jax.tree_util.tree_map(lambda p: p[0], stacked)
        idx = jax.lax.axis_index(axis)

        def to_var(a):
            # idempotent pvary: values derived from the manual-sliced params
            # are already varying over the pipe axis; pcast rejects a no-op
            if axis in getattr(jax.typeof(a), "vma", ()):
                return a
            return jax.lax.pcast(a, (axis,), to="varying")
        # pvary the tail params BEFORE any vjp: a replicated-typed primal
        # feeding a varying output makes the vjp transpose insert a hidden
        # psum over the pipe axis, summing every stage's (masked-out) tail
        # grads into each device's dtail_m
        tailp = jax.tree_util.tree_map(to_var, tailp)
        micro = to_var(xs.reshape((M, xs.shape[0] // M) + xs.shape[1:]))
        targs_m = tuple(
            to_var(t.reshape((M, t.shape[0] // M) + t.shape[1:]))
            for t in targs)
        f32 = jnp.float32
        zeros_f32 = lambda tree: jax.tree_util.tree_map(
            lambda p: to_var(jnp.zeros(jnp.shape(p), f32)), tree)
        carry0 = (
            to_var(jnp.zeros_like(micro[0])),            # fwd hop buffer
            to_var(jnp.zeros_like(micro[0])),            # bwd cotangent hop
            to_var(jnp.zeros((S,) + micro.shape[1:], micro.dtype)),  # stash
            zeros_f32(params),                           # stage grads
            zeros_f32(tailp),                            # tail grads
            to_var(jnp.zeros_like(micro)),               # dx per microbatch
            to_var(jnp.zeros((), f32)),                  # tail loss acc
            to_var(jnp.zeros((), f32)),                  # stage aux-loss acc
            zeros_f32(aux_proto),                        # aux metric means
        )
        fperm = [(i, (i + 1) % P) for i in range(P)]
        rperm = [(i, (i - 1) % P) for i in range(P)]
        is_last = idx == P - 1

        def tick(carry, k):
            (fbuf, bbuf, stash, dstage, dtail, dxs, loss, stage_aux,
             aux) = carry
            # ---- forward half: GPipe tick k ----
            m_f = k - idx
            inject = (idx == 0) & (k < M)
            feed = jnp.where(inject,
                             jax.lax.dynamic_index_in_dim(
                                 micro, jnp.clip(k, 0, M - 1), 0, False),
                             fbuf)
            fvalid = (m_f >= 0) & (m_f < M)
            slot_f = jnp.mod(m_f, S)
            stash = jnp.where(
                fvalid,
                jax.lax.dynamic_update_index_in_dim(stash, feed, slot_f, 0),
                stash)
            y, _ = stage_fn(params, idx, feed)
            # ---- backward half: tick k - (P-1) ----
            m_b = k - 2 * (P - 1) + idx
            bvalid = (m_b >= 0) & (m_b < M)
            m_bc = jnp.clip(m_b, 0, M - 1)
            x_in = jax.lax.dynamic_index_in_dim(
                stash, jnp.mod(m_bc, S), 0, False)
            tmicro = tuple(jax.lax.dynamic_index_in_dim(t, m_bc, 0, False)
                           for t in targs_m)
            # last stage: this step's forward output IS microbatch m_b's
            # (schedule identity k-(P-1) = m_b there), so the tail vjp seeds
            # the backward without ever storing last-stage outputs
            loss_m, tail_vjp, aux_m = jax.vjp(
                lambda tp, yy: tail_fn(tp, yy, *tmicro), tailp, y,
                has_aux=True)
            dtail_m, dy_tail = tail_vjp(to_var(jnp.asarray(1.0 / M,
                                                           loss_m.dtype)))
            cot = jnp.where(is_last, dy_tail, bbuf)
            def stage_varying_aux(p, xx):
                # a stage whose aux term is a CONSTANT (no aux layers)
                # returns an unvarying scalar; pvary it so the vjp accepts
                # the varying seed (no-op when aux depends on the varying
                # inputs/params, and a constant carries no grads anyway)
                yy, aux_out = stage_fn(p, idx, xx)
                return yy, to_var(aux_out)

            (_, aux_loss_m), svjp = jax.vjp(stage_varying_aux, params, x_in)
            # the stage aux loss enters the total with weight 1/M; its
            # cotangent seeds the replay vjp alongside the activation's.
            # (dtype pinned BEFORE the where: bare Python floats would
            # become f64 under x64 — flagged by graftcheck's dtype audit)
            aux_seed = to_var(jnp.where(
                bvalid, jnp.asarray(1.0 / M, aux_loss_m.dtype),
                jnp.zeros((), aux_loss_m.dtype)))
            dp, dx = svjp((cot, aux_seed))
            stage_aux = stage_aux + jnp.where(
                bvalid, aux_loss_m.astype(f32) / M, 0)
            acc = lambda a, b, gate: jax.tree_util.tree_map(
                lambda u, v: u + jnp.where(gate, v.astype(f32), 0), a, b)
            dstage = acc(dstage, dp, bvalid)
            dtail = acc(dtail, dtail_m, bvalid & is_last)
            loss = loss + jnp.where(bvalid & is_last,
                                    loss_m.astype(f32) / M, 0)
            aux = acc(aux, jax.tree_util.tree_map(
                lambda a: a / M, aux_m), bvalid & is_last)
            wmask = ((jnp.arange(M) == m_b) & bvalid & (idx == 0))
            dxs = jnp.where(wmask.reshape((M,) + (1,) * dx.ndim),
                            dx[None], dxs)
            fbuf = jax.lax.ppermute(y, axis, fperm)
            bbuf = jax.lax.ppermute(dx, axis, rperm)
            return (fbuf, bbuf, stash, dstage, dtail, dxs, loss, stage_aux,
                    aux), None

        carry, _ = jax.lax.scan(tick, carry0, jnp.arange(M + 2 * P - 2))
        _, _, _, dstage, dtail, dxs, loss, stage_aux, aux = carry
        lead = lambda tree: jax.tree_util.tree_map(lambda v: v[None], tree)
        return (loss[None], stage_aux[None], lead(aux), lead(dstage),
                lead(dtail), dxs[None])

    # the aux carry/out_spec must mirror the tail's (unknown-here) metric
    # pytree: discover it ONCE via abstract eval on microbatch shapes
    aux_proto = jax.eval_shape(
        lambda tp, x0, *t: tail_fn(
            tp, x0[:x.shape[0] // M],
            *(ti[:x.shape[0] // M] for ti in t))[1],
        tail_params, x, *tail_args)
    leading = PartitionSpec(axis)
    stage_specs = jax.tree_util.tree_map(lambda _: leading, stacked_params)
    rep = PartitionSpec()
    rep_tree = jax.tree_util.tree_map(lambda _: rep, tail_params)
    piped = jax.shard_map(
        body, mesh=mesh, axis_names=frozenset({axis}),
        in_specs=(stage_specs, rep_tree, rep,
                  tuple(rep for _ in tail_args)),
        out_specs=(PartitionSpec(axis),
                   PartitionSpec(axis),
                   jax.tree_util.tree_map(lambda _: leading, aux_proto),
                   jax.tree_util.tree_map(lambda _: leading, stacked_params),
                   jax.tree_util.tree_map(lambda _: leading, tail_params),
                   PartitionSpec(axis)))
    loss_p, stage_aux_p, aux_p, dstacked, dtail_p, dxs_p = piped(
        stacked_params, tail_params, x, tuple(tail_args))
    # total = the last stage's tail loss + every stage's aux-loss terms
    loss = loss_p[P - 1] + jnp.sum(stage_aux_p)
    aux = jax.tree_util.tree_map(lambda v: v[P - 1], aux_p)
    dtail = jax.tree_util.tree_map(lambda v: jnp.sum(v, axis=0), dtail_p)
    dx = dxs_p[0].reshape(x.shape)
    return loss, aux, dstacked, dtail, dx
