"""Fused mixer-block pallas kernel: the bytes lever for the map-attention
blocks (VERDICT r4 item 4).

The mixer configs' second block (configs/32mixer_group.json /
32big_mixer.json, reference semantics spatial.py:65-75 + frontend chain)
is the 5-layer chain

    n1  = norm_{scale1,shift1}(x)          # per-head, over features
    a1  = (bias1 . causal) @ n1            # learned [H,S,S] map, masked
    n2  = norm_{scale2,shift2}(a1)
    g   = gelu(n2)
    out = (bias2 . causal) @ g

on a ``[B, S, H, K]`` activation.  Under XLA every arrow above is a
separate HLO with a full ``[B,S,H,K]`` HBM round-trip (measured: the
32mixer_group step is bandwidth-bound at 266.7 GB with the MXU 4x idle —
docs/perf/README.md roofline), and the backward doubles it with recompute
reads plus f32 grad temporaries.  Per (batch, head) slice, however, the
whole chain is a pair of tiny ``[S,S] @ [S,K]`` matmuls with elementwise
glue — it fits VMEM whole.  This kernel runs the chain (forward) and its
entire vjp (backward) per ``(head, batch-block)`` grid cell — each cell
covers ``_block_rows`` batch rows (python-unrolled), amortizing the
per-cell bias load, causal-mask build and DMA latency: the forward reads
x and writes out ONCE; the backward reads x and d(out) once, writes dx
once, recomputes the internals in VMEM (remat-in-kernel — the same FLOPs
XLA's remat executes, for a fraction of the bytes), and accumulates the
parameter gradients (dbias1, dbias2, dscale/dshift) in f32 across the
batch grid axis.

Layout notes (pallas TPU tiling): activations are viewed as
``[B, S, H*K]`` so the per-head block is a stack of ``[S, K]``
lane-aligned column slices (the same trick ops/pallas_attn.py uses); the
tiny ``[H, K]`` scale/shift vectors ride as ``[H, 1, K]`` with a
``(1, 1, K)`` per-head block — mosaic rejects dynamic sublane offsets
into a whole-``[H, K]`` tile, and a head-blocked window needs no
in-kernel dynamic indexing at all.

Numerics match the unfused chain's dtype walk: norms compute in f32 from
the stored dtype (models/layers.py::norm), map matmuls take
calculation-dtype operands with f32 MXU accumulation and cast back
(nd.einsum policy), gelu runs in the calculation dtype.  Bit-parity with
XLA is NOT expected in bf16 (the fusion changes rounding order, like any
remat/fusion change — guarded the same way, by the real-corpus trajectory
check); f32 parity is pinned in tests/model_test.py.

The kernel is single-device (used under jit on an unsharded mesh; the
GSPMD/sharded paths keep the unfused chain).
"""
from __future__ import annotations

import functools
import typing

import jax
import jax.numpy as jnp


def _norm_fwd(x32: jnp.ndarray, scale: jnp.ndarray, shift: jnp.ndarray
              ) -> jnp.ndarray:
    """models/layers.py::norm on one [S, K] slice, f32 in/out: one-pass
    moments, clamped var, affine fold."""
    m1 = jnp.mean(x32, axis=1, keepdims=True)
    m2 = jnp.mean(x32 * x32, axis=1, keepdims=True)
    var = jnp.maximum(m2 - m1 * m1, 0.0)
    mul = jax.lax.rsqrt(var + 1e-5) * scale[None, :]
    return x32 * mul + (shift[None, :] - m1 * mul)


def _norm_bwd(x32: jnp.ndarray, scale: jnp.ndarray,
              dy: jnp.ndarray) -> typing.Tuple[jnp.ndarray, jnp.ndarray,
                                               jnp.ndarray]:
    """vjp of _norm_fwd wrt (x, scale, shift); all f32 [S, K] / [K]."""
    m1 = jnp.mean(x32, axis=1, keepdims=True)
    m2 = jnp.mean(x32 * x32, axis=1, keepdims=True)
    var = jnp.maximum(m2 - m1 * m1, 0.0)
    r = jax.lax.rsqrt(var + 1e-5)
    xhat = (x32 - m1) * r
    u = dy * scale[None, :]
    dx = r * (u - jnp.mean(u, axis=1, keepdims=True)
              - xhat * jnp.mean(u * xhat, axis=1, keepdims=True))
    dscale = jnp.sum(dy * xhat, axis=0)
    dshift = jnp.sum(dy, axis=0)
    return dx, dscale, dshift


def _causal(seq: int, dtype) -> jnp.ndarray:
    row = jax.lax.broadcasted_iota(jnp.int32, (seq, seq), 0)
    col = jax.lax.broadcasted_iota(jnp.int32, (seq, seq), 1)
    return (row >= col).astype(dtype)


def _chain_fwd_tiles(x, b1m, b2m, s1, sh1, s2, sh2, cdtype):
    """Forward chain on one [S, K] slice; returns (out, intermediates).
    Dtype walk mirrors the unfused layers: f32 norms, cdtype matmul
    operands with f32 accumulation, cdtype gelu."""
    n1 = _norm_fwd(x.astype(jnp.float32), s1, sh1).astype(cdtype)
    a1 = jnp.dot(b1m, n1, preferred_element_type=jnp.float32).astype(cdtype)
    n2 = _norm_fwd(a1.astype(jnp.float32), s2, sh2).astype(cdtype)
    g = jax.nn.gelu(n2)
    out = jnp.dot(b2m, g, preferred_element_type=jnp.float32).astype(cdtype)
    return out, (n1, a1, n2, g)


def _fwd_kernel(x_ref, b1_ref, b2_ref, s1_ref, sh1_ref, s2_ref, sh2_ref,
                out_ref, *, seq: int, n_bt: int):
    cdtype = x_ref.dtype
    mask = _causal(seq, cdtype)
    b1m = b1_ref[0] * mask
    b2m = b2_ref[0] * mask
    s1 = s1_ref[0, 0].astype(jnp.float32)
    sh1 = sh1_ref[0, 0].astype(jnp.float32)
    s2 = s2_ref[0, 0].astype(jnp.float32)
    sh2 = sh2_ref[0, 0].astype(jnp.float32)
    for i in range(n_bt):  # unrolled: amortizes mask/bias setup + grid DMA
        out, _ = _chain_fwd_tiles(x_ref[i], b1m, b2m, s1, sh1, s2, sh2,
                                  cdtype)
        out_ref[i] = out


def _bwd_kernel(x_ref, b1_ref, b2_ref, s1_ref, sh1_ref, s2_ref, sh2_ref,
                dout_ref, dx_ref, db1_ref, db2_ref, ds1_ref, dsh1_ref,
                ds2_ref, dsh2_ref, *, seq: int, n_bt: int):
    from jax.experimental import pallas as pl

    cdtype = x_ref.dtype
    f32 = jnp.float32
    b = pl.program_id(1)  # batch is the fastest grid axis: accumulate here

    mask = _causal(seq, cdtype)
    b1m = b1_ref[0] * mask
    b2m = b2_ref[0] * mask
    s1 = s1_ref[0, 0].astype(f32)
    sh1 = sh1_ref[0, 0].astype(f32)
    s2 = s2_ref[0, 0].astype(f32)
    sh2 = sh2_ref[0, 0].astype(f32)
    maskf = mask.astype(f32)

    db1 = db2 = ds1 = dsh1 = ds2 = dsh2 = None
    acc = lambda t, u: u if t is None else t + u
    for i in range(n_bt):  # unrolled over the cell's batch rows
        x = x_ref[i]
        # recompute the forward internals in VMEM (remat-in-kernel)
        _, (n1, a1, n2, g) = _chain_fwd_tiles(x, b1m, b2m, s1, sh1, s2,
                                              sh2, cdtype)
        dout = dout_ref[i]
        # out = b2m @ g
        dg = jnp.dot(b2m.T, dout, preferred_element_type=f32)
        db2 = acc(db2, jnp.dot(dout, g.T, preferred_element_type=f32))
        # g = gelu(n2) in cdtype (vjp evaluated in f32 of the cdtype-rounded
        # n2, matching the unfused chain's value to rounding); the vjp
        # cotangent comes back in n2's dtype — grads accumulate in f32
        _, gelu_vjp = jax.vjp(lambda t: jax.nn.gelu(t.astype(f32)), n2)
        (dn2,) = gelu_vjp(dg)
        dn2 = dn2.astype(f32)
        # n2 = norm(a1)
        da1, ds2_i, dsh2_i = _norm_bwd(a1.astype(f32), s2, dn2)
        da1c = da1.astype(cdtype)
        # a1 = b1m @ n1
        dn1 = jnp.dot(b1m.T, da1c, preferred_element_type=f32)
        db1 = acc(db1, jnp.dot(da1c, n1.T, preferred_element_type=f32))
        # n1 = norm(x)
        dx, ds1_i, dsh1_i = _norm_bwd(x.astype(f32), s1, dn1)
        dx_ref[i] = dx.astype(dx_ref.dtype)
        ds1 = acc(ds1, ds1_i)
        dsh1 = acc(dsh1, dsh1_i)
        ds2 = acc(ds2, ds2_i)
        dsh2 = acc(dsh2, dsh2_i)
    db1 = db1 * maskf
    db2 = db2 * maskf

    # parameter grads accumulate across the batch grid axis in f32; every
    # param block window is per-head and moves only when the head
    # coordinate advances, so each re-inits at b == 0 and accumulates
    # across the (fastest) batch axis
    @pl.when(b == 0)
    def _init():
        db1_ref[0] = db1
        db2_ref[0] = db2
        ds1_ref[0, 0] = ds1
        dsh1_ref[0, 0] = dsh1
        ds2_ref[0, 0] = ds2
        dsh2_ref[0, 0] = dsh2

    @pl.when(b != 0)
    def _acc():
        db1_ref[0] += db1
        db2_ref[0] += db2
        ds1_ref[0, 0] += ds1
        dsh1_ref[0, 0] += dsh1
        ds2_ref[0, 0] += ds2
        dsh2_ref[0, 0] += dsh2


def _block_rows(n_b: int, seq: int, key: int) -> int:
    """Batch rows per grid cell: amortize the per-cell bias load + mask
    build + DMA latency, bounded by a ~14 MB VMEM budget for the backward's
    ~12 live [S,K]-f32 tiles per row."""
    budget = 14 * 1024 * 1024 // max(1, 12 * seq * key * 4)
    bt = max(1, min(8, budget))
    while n_b % bt:
        bt -= 1
    return bt


def _specs(seq: int, key: int, n_bt: int):
    from jax.experimental import pallas as pl
    # activations viewed as [B, S, H*K]: per-head block = [n_bt, S, K]
    # lane-aligned column slices; maps blocked per head
    x_spec = pl.BlockSpec((n_bt, seq, key), lambda h, b: (b, 0, h))
    map_spec = pl.BlockSpec((1, seq, seq), lambda h, b: (h, 0, 0))
    # [H,K] vectors ride as [H,1,K] with a (1,1,K) per-head block: mosaic
    # rejects dynamic sublane offsets into a whole-[H,K] tile, but a
    # head-blocked window needs no in-kernel dynamic indexing at all
    vec_spec = pl.BlockSpec((1, 1, key), lambda h, b: (h, 0, 0))
    return x_spec, map_spec, vec_spec


def _flat(x):
    n_b, seq, n_h, key = x.shape
    return x.reshape(n_b, seq, n_h * key)


@functools.partial(jax.jit, static_argnames=("interpret",))
def _fwd_pallas(x, bias1, bias2, scale1, shift1, scale2, shift2,
                interpret: bool = False):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n_b, seq, n_h, key = x.shape
    n_bt = _block_rows(n_b, seq, key)
    x_spec, map_spec, vec_spec = _specs(seq, key, n_bt)
    out = pl.pallas_call(
        functools.partial(_fwd_kernel, seq=seq, n_bt=n_bt),
        grid=(n_h, n_b // n_bt),
        in_specs=[x_spec, map_spec, map_spec, vec_spec, vec_spec, vec_spec,
                  vec_spec],
        out_specs=x_spec,
        out_shape=jax.ShapeDtypeStruct((n_b, seq, n_h * key), x.dtype),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("arbitrary", "arbitrary")),
        interpret=interpret,
    )(_flat(x), bias1, bias2,
      scale1[:, None], shift1[:, None], scale2[:, None], shift2[:, None])
    return out.reshape(x.shape)


@functools.partial(jax.jit, static_argnames=("interpret",))
def _bwd_pallas(x, bias1, bias2, scale1, shift1, scale2, shift2, dout,
                interpret: bool = False):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n_b, seq, n_h, key = x.shape
    n_bt = _block_rows(n_b, seq, key)
    x_spec, map_spec, vec_spec = _specs(seq, key, n_bt)
    f32 = jnp.float32
    vec3 = (n_h, 1, key)
    outs = (jax.ShapeDtypeStruct((n_b, seq, n_h * key), x.dtype),  # dx
            jax.ShapeDtypeStruct(bias1.shape, f32),                # dbias1
            jax.ShapeDtypeStruct(bias2.shape, f32),                # dbias2
            jax.ShapeDtypeStruct(vec3, f32),                       # dscale1
            jax.ShapeDtypeStruct(vec3, f32),                       # dshift1
            jax.ShapeDtypeStruct(vec3, f32),                       # dscale2
            jax.ShapeDtypeStruct(vec3, f32))                       # dshift2
    res = pl.pallas_call(
        functools.partial(_bwd_kernel, seq=seq, n_bt=n_bt),
        grid=(n_h, n_b // n_bt),
        in_specs=[x_spec, map_spec, map_spec, vec_spec, vec_spec, vec_spec,
                  vec_spec, x_spec],
        out_specs=(x_spec, map_spec, map_spec, vec_spec, vec_spec, vec_spec,
                   vec_spec),
        out_shape=outs,
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("arbitrary", "arbitrary")),
        interpret=interpret,
    )(_flat(x), bias1, bias2,
      scale1[:, None], shift1[:, None], scale2[:, None], shift2[:, None],
      _flat(dout))
    dx, db1, db2, ds1, dsh1, ds2, dsh2 = res
    return (dx.reshape(x.shape), db1, db2, ds1[:, 0], dsh1[:, 0],
            ds2[:, 0], dsh2[:, 0])


def mixer_chain_reference(x, bias1, bias2, scale1, shift1, scale2, shift2):
    """The unfused chain as plain jnp on [B,S,H,K] (same math the layer
    stack composes) — parity oracle for the kernels."""
    cdtype = x.dtype
    f32 = jnp.float32
    mask = _causal(x.shape[1], cdtype)

    def norm(t, scale, shift):
        t32 = t.astype(f32)
        m1 = jnp.mean(t32, axis=-1, keepdims=True)
        m2 = jnp.mean(t32 * t32, axis=-1, keepdims=True)
        var = jnp.maximum(m2 - m1 * m1, 0.0)
        mul = jax.lax.rsqrt(var + 1e-5) * scale[None, None].astype(f32)
        add = shift[None, None].astype(f32) - m1 * mul
        return (t32 * mul + add).astype(cdtype)

    def apply_map(bias, v):
        bm = bias * mask[None]
        out = jnp.einsum("hst,bthk->bshk", bm, v,
                         preferred_element_type=f32)
        return out.astype(cdtype)

    n1 = norm(x, scale1, shift1)
    a1 = apply_map(bias1, n1)
    n2 = norm(a1, scale2, shift2)
    g = jax.nn.gelu(n2)
    return apply_map(bias2, g)


@functools.partial(jax.custom_vjp, nondiff_argnums=(7,))
def fused_mixer_block(x, bias1, bias2, scale1, shift1, scale2, shift2,
                      interpret: bool = False):
    """norm -> masked-map attention -> norm -> gelu -> masked-map attention
    in one pallas kernel (fwd) + one kernel for the full vjp (bwd).

    x: [B,S,H,K]; bias*: [H,S,S]; scale/shift*: [H,K] (all in the
    calculation dtype).  Param cotangents come back in the primal dtype
    (f32-accumulated in-kernel, cast on exit — nd.einsum's policy)."""
    return _fwd_pallas(x, bias1, bias2, scale1, shift1, scale2, shift2,
                       interpret=interpret)


def _fused_fwd(x, bias1, bias2, scale1, shift1, scale2, shift2,
               interpret: bool = False):
    out = _fwd_pallas(x, bias1, bias2, scale1, shift1, scale2, shift2,
                      interpret=interpret)
    return out, (x, bias1, bias2, scale1, shift1, scale2, shift2)


def _fused_bwd(interpret, res, dout):
    x, bias1, bias2, scale1, shift1, scale2, shift2 = res
    dx, db1, db2, ds1, dsh1, ds2, dsh2 = _bwd_pallas(
        x, bias1, bias2, scale1, shift1, scale2, shift2, dout,
        interpret=interpret)
    return (dx, db1.astype(bias1.dtype), db2.astype(bias2.dtype),
            ds1.astype(scale1.dtype), dsh1.astype(shift1.dtype),
            ds2.astype(scale2.dtype), dsh2.astype(shift2.dtype))


fused_mixer_block.defvjp(_fused_fwd, _fused_bwd)
