"""Quantized-compute layer: int8/fp8 matmuls for the DSL linear family.

The grouped-mixer workload sits at 0.31 algorithmic MFU and is ABOVE its
bandwidth bound after the round-5 fusion experiments (ops/pallas_group.py
header: moving fewer bytes was measured REJECT), so the remaining lever is
making the MXU math itself cheaper.  TPU MXUs run int8 matmuls at 2-4x the
bf16 rate (and fp8 at 2x on v5p+); this module provides the quantized
forward path behind the ``quant_blocks`` / ``quant_dtype`` config knobs
(docs/performance.md "Low-precision compute"):

- **Dynamic symmetric quantization, scales computed in-graph** — no
  calibration pass, no extra state: ``per_tensor_scale`` /
  ``per_channel_scale`` reduce |max| at trace time, so every step
  re-derives its own scales from the live values.
- **W8A8 forward** (``quant_einsum``): activations are quantized per
  output row (per-token — the kept, non-contracted axes), weights per
  output channel; the contraction runs as a quantized ``dot_general`` with
  **f32 accumulation** pinned by ``preferred_element_type`` (exact for
  int8 products; the classic silent-failure mode of int8 paths is an s8
  or bf16 accumulator), then the two scale vectors multiply back in f32
  and the result casts to the calculation dtype.
- **High-precision backward** (``custom_vjp``): the residuals are the
  UN-quantized operands and the backward is the ordinary
  calculation-dtype (bf16) einsum pair with f32 accumulation — i.e. a
  straight-through estimator through the rounding: quantized forward,
  exactly the gradients of the unquantized contraction.  Training
  stability rides on the backward, which is why it stays high-precision.

Default-off contract: with ``quant_blocks`` unset, ``models/linear.py``
never calls into this module and the graph is bit-identical to the
pre-quant one (parity-tested at 8 and 300 steps like
``telemetry_interval=0`` and ``fused_group_linear=False`` before it).
The graftcheck ``quant-dtype`` graph rule pins the complement: an int8/fp8
op in a config that declares no quant scope — or a declared scope whose
traced train step contains NO quantized dot (a silent high-precision
fallback) — fails static analysis (docs/static_analysis.md).
"""
from __future__ import annotations

import functools
import typing

import jax
import jax.numpy as jnp

from ..nd import NT, contraction_spec

#: quant_dtype knob -> jnp dtype.  fp8 uses e4m3 (the forward-pass format:
#: 3 mantissa bits, +-448 range); e5m2 is a gradient format and the
#: backward here stays bf16 anyway.
QUANT_DTYPES: typing.Dict[str, typing.Any] = {"int8": jnp.int8}
if hasattr(jnp, "float8_e4m3fn"):  # toolchain-gated
    QUANT_DTYPES["fp8"] = jnp.float8_e4m3fn

#: symmetric range limit per quant dtype ("qmax"): values quantize into
#: [-qmax, qmax].  int8 uses 127 (not 128) so the range stays symmetric;
#: fp8_e4m3fn's largest finite is 448.
_QMAX = {"int8": 127.0, "fp8": 448.0}

_EPS = 1e-12  # scale floor: an all-zero operand must not divide by zero


def supported(quant_dtype: str) -> bool:
    """True when this toolchain can represent ``quant_dtype``."""
    return quant_dtype in QUANT_DTYPES


# -- scale computation (in-graph, dynamic) -----------------------------------

def per_tensor_scale(x: jnp.ndarray, quant_dtype: str = "int8") -> jnp.ndarray:
    """One f32 scalar scale: amax(|x|) / qmax, floored away from zero."""
    amax = jnp.max(jnp.abs(x.astype(jnp.float32)))
    return jnp.maximum(amax / _QMAX[quant_dtype], _EPS)


def per_channel_scale(x: jnp.ndarray, reduce_axes: typing.Sequence[int],
                      quant_dtype: str = "int8") -> jnp.ndarray:
    """Per-channel f32 scales: amax over ``reduce_axes`` (the contracted
    axes), keeping one scale per kept-axis coordinate.  With
    ``reduce_axes`` covering every axis this degenerates to (a rank-0)
    ``per_tensor_scale``."""
    amax = jnp.max(jnp.abs(x.astype(jnp.float32)), axis=tuple(reduce_axes))
    return jnp.maximum(amax / _QMAX[quant_dtype], _EPS)


def quantize(x: jnp.ndarray, scale: jnp.ndarray,
             quant_dtype: str = "int8") -> jnp.ndarray:
    """Symmetric quantization: round(x/scale) clipped to the dtype range.
    ``scale`` broadcasts against ``x`` (scalar for per-tensor; the caller
    reshapes per-channel scales)."""
    qmax = _QMAX[quant_dtype]
    v = jnp.clip(x.astype(jnp.float32) / scale, -qmax, qmax)
    if quant_dtype == "int8":
        v = jnp.round(v)
    return v.astype(QUANT_DTYPES[quant_dtype])


def dequantize(q: jnp.ndarray, scale: jnp.ndarray,
               dtype=jnp.float32) -> jnp.ndarray:
    return (q.astype(jnp.float32) * scale).astype(dtype)


# -- the quantized contraction ----------------------------------------------

def _parse_spec(spec: str) -> typing.Tuple[str, str, str]:
    ins, out = spec.split("->")
    x_l, w_l = ins.split(",")
    return x_l, w_l, out


def _channel_scale_for(arr: jnp.ndarray, letters: str, out_letters: str,
                       qname: str) -> typing.Tuple[jnp.ndarray, jnp.ndarray]:
    """(broadcastable-to-output scale, quantized operand) for one einsum
    operand: scales reduce over the operand's contracted axes (one scale
    per kept coordinate — per-token for activations, per-channel for
    weights), then transpose/reshape into the output letter order."""
    reduce_axes = [i for i, l in enumerate(letters) if l not in out_letters]
    kept = [l for l in letters if l in out_letters]
    if not kept:
        s = per_tensor_scale(arr, qname)
        return s, quantize(arr, s, qname)
    s = per_channel_scale(arr, reduce_axes, qname)
    # quantize wants the scale aligned to the OPERAND layout
    op_shape = [arr.shape[i] if l in out_letters else 1
                for i, l in enumerate(letters)]
    q = quantize(arr, s.reshape(op_shape), qname)
    # dequant wants it aligned to the OUTPUT layout: kept letters arrive in
    # operand order — permute into output order, then broadcast-reshape
    perm = sorted(range(len(kept)), key=lambda i: out_letters.index(kept[i]))
    s = jnp.transpose(s, perm)
    out_shape = []
    it = iter(s.shape)
    for l in out_letters:
        out_shape.append(next(it) if l in kept else 1)
    return s.reshape(out_shape), q


def _reference_einsum(spec: str, x: jnp.ndarray, w: jnp.ndarray,
                      out_dtype) -> jnp.ndarray:
    """The high-precision twin of the quantized contraction (nd.einsum's
    accumulation policy: f32 accumulator, cast back) — the backward below
    differentiates exactly this."""
    return jnp.einsum(spec, x, w,
                      preferred_element_type=jnp.float32).astype(out_dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def _qdot(x: jnp.ndarray, w: jnp.ndarray, spec: str, qname: str
          ) -> jnp.ndarray:
    x_l, w_l, out_l = _parse_spec(spec)
    sx, xq = _channel_scale_for(x, x_l, out_l, qname)
    sw, wq = _channel_scale_for(w, w_l, out_l, qname)
    # the quantized MXU contraction: int8 x int8 (or fp8 x fp8) operands,
    # f32 accumulation pinned — this dot_general is what the graftcheck
    # quant-dtype census counts
    acc = jnp.einsum(spec, xq, wq, preferred_element_type=jnp.float32)
    return (acc * sx * sw).astype(x.dtype)


def _qdot_fwd(x, w, spec, qname):
    return _qdot(x, w, spec, qname), (x, w)


def _qdot_bwd(spec, qname, res, g):
    x, w = res
    # high-precision grads: differentiate the unquantized contraction on
    # the stored (calculation-dtype) operands — straight-through through
    # the forward rounding
    _, vjp = jax.vjp(
        lambda a, b: _reference_einsum(spec, a, b, x.dtype), x, w)
    return vjp(g)


_qdot.defvjp(_qdot_fwd, _qdot_bwd)


def quant_einsum(x: NT, w: NT, out_names: typing.Sequence[str],
                 quant_dtype: str = "int8") -> NT:
    """Quantized twin of ``nd.einsum([x, w], out_names)``: same named
    contraction semantics (the spec comes from the same
    ``nd.contraction_spec`` builder, so the twins cannot drift), W8A8
    forward, high-precision backward."""
    out_names = tuple(out_names)
    spec = contraction_spec([x, w], out_names)
    return NT(_qdot(x.x, w.x, spec, quant_dtype), out_names)


# -- scope selection ---------------------------------------------------------

def scope_matches(quant_blocks: typing.Sequence[str], scope_path: str) -> bool:
    """True when any ``quant_blocks`` entry occurs in the model scope path
    (the DSL layer names ARE the scope components, models/ctx.py, so
    ``"bottleneck_group_linear"`` selects every linear inside that layer;
    note substring semantics — ``"group_linear"`` also matches the
    bottleneck layer, use ``"/group_linear"`` to select only the plain
    per-head linear)."""
    return any(s in scope_path for s in quant_blocks)


def eligible(cfg, tensor: NT) -> bool:
    """Static (trace-time) eligibility of one linear call: the knob is on,
    the dtype is representable on this toolchain, and the operand is a
    float tensor (the quantizer is meaningless on integer ids)."""
    return (bool(cfg.quant_blocks)
            and supported(cfg.quant_dtype)
            and jnp.issubdtype(tensor.dtype, jnp.floating))


def pattern_quantized(cfg, layer_specs: typing.Sequence[str]) -> bool:
    """True when any layer of a fused-kernel pattern falls inside the
    declared quant scope — the fused pallas paths (ops/pallas_group.py /
    ops/pallas_mixer.py) run their own unquantized matmuls, so fusion must
    yield to quantization or the declared scope would silently fall back
    (exactly what the graftcheck quant-dtype rule rejects).

    Each layer name is tested as a SYNTHESIZED scope path fragment
    (``block_/<name>_/``) rather than the bare name, so this check agrees
    with the path ``linear()`` matches against: a slash-anchored entry like
    ``"/bottleneck_group_linear"`` (the documented disambiguation form)
    must disable fusion exactly when it would quantize the linear."""
    if not cfg.quant_blocks:
        return False
    names = [spec.split("-")[0] for spec in layer_specs]
    return any(scope_matches(cfg.quant_blocks, f"block_/{name}_/")
               for name in names)


__all__ = ["QUANT_DTYPES", "supported", "per_tensor_scale",
           "per_channel_scale", "quantize", "dequantize", "quant_einsum",
           "scope_matches", "eligible", "pattern_quantized"]
