"""Video TFRecord pipeline: JPEG frames -> patchified uint8/uint32 tensors.

Port of the reference video decoder + dataset (/root/reference/src/inputs.py:
131-228, 370-483): per-record Example features are {frame: JPEG bytes,
concat: int64, skip_frame: int64} plus optional {tokens: int64[ltpf],
mask: int64}.  Frames are color-quantized, patchified via the reference's
reshape/transpose ((hp,P,wp,P,C) -> transpose(1,3,0,2,4) -> (hp,wp,P*P*C)),
optionally bit-folded (several low-bit color values packed per uint32,
inputs.py:174-198), windowed over ``sequence_length + time_patch`` frames
with shift ``sequence_length``, and emitted with src/tgt frame masks and
concat masks (dataset_video._pre_func, inputs.py:412-465).
"""
from __future__ import annotations

import functools
import typing

import numpy as np

from ..config import Config
from ..reliability import CorruptRecordBudget, faults
from .pipeline import _ShuffleBuffer, split_files
from .tfrecord import decode_example, read_records


def _decode_jpeg(data: bytes) -> np.ndarray:
    import cv2
    arr = cv2.imdecode(np.frombuffer(data, np.uint8), cv2.IMREAD_COLOR)
    if arr is None:
        raise ValueError("undecodable frame")
    return cv2.cvtColor(arr, cv2.COLOR_BGR2RGB)


class FrameDecoder:
    """Single-record decoder (reference get_video_decoder)."""

    def __init__(self, cfg: Config):
        self.cfg = cfg
        # in bit-fold mode channel_color_size is already divided by
        # fold_count (config.py derivation)
        cc = cfg.channel_color_size
        self.frame_shape = ((cfg.frame_height_patch, cfg.frame_width_patch, cc)
                            if cfg.three_axes else
                            (cfg.frame_height_patch * cfg.frame_width_patch, cc))
        self.dtype = np.uint32 if cfg.use_bit_fold_input_pipeline else np.uint8
        self.multi = np.array(
            [(2 ** cfg.bit_fold_value) ** i for i in range(cfg.fold_count)],
            np.int64)[None, :, None]

    def _op_decode(self, frame: np.ndarray) -> np.ndarray:
        cfg = self.cfg
        q = cfg.color_quantization_value
        if q != 256:
            frame = np.round(frame.astype(np.float32) * ((q - 1) / 255))
            frame = frame.astype(np.int64 if cfg.use_bit_fold_input_pipeline
                                 else np.uint8)
        p = cfg.patch_size
        frame = frame.reshape(cfg.frame_height_patch, p, cfg.frame_width_patch,
                              p, cfg.color_channels)
        frame = frame.transpose(1, 3, 0, 2, 4)
        if cfg.use_bit_fold_input_pipeline:
            out_shape = (list(self.frame_shape[:-1])
                         + [cfg.fold_count, self.frame_shape[-1]])
            frame = frame.reshape(out_shape)
            frame = (frame.astype(np.int64) * self.multi).sum(axis=-2)
            return frame.astype(np.uint32)
        return frame.reshape(self.frame_shape)

    def __call__(self, payload: bytes) -> typing.Tuple[np.ndarray, int, int,
                                                       typing.Optional[np.ndarray],
                                                       typing.Optional[np.ndarray]]:
        cfg = self.cfg
        ex = decode_example(payload)
        concat = int(ex["concat"][0])
        skip = int(ex["skip_frame"][0])
        if skip > 0 or concat > 0:
            frame = np.zeros(self.frame_shape, self.dtype)
        else:
            frame = self._op_decode(_decode_jpeg(ex["frame"][0]))
        tokens = mask = None
        if cfg.language_token_per_frame > 0:
            tokens = np.asarray(ex["tokens"], np.int32)
            token_range = np.arange(cfg.language_token_per_frame)
            mask = token_range <= int(ex["mask"][0])
        return frame, concat, skip, tokens, mask

    def skipped(self) -> typing.Tuple[np.ndarray, int, int,
                                      typing.Optional[np.ndarray],
                                      typing.Optional[np.ndarray]]:
        """Placeholder for an undecodable record under the corrupt-record
        budget: a zero frame flagged ``skip`` — exactly the shape the model
        already handles for real skip-frames, so window/batch alignment and
        the resume cursor are unaffected by the substitution (unlike the
        text pipeline, where a skipped record shifts window numbering)."""
        cfg = self.cfg
        tokens = mask = None
        if cfg.language_token_per_frame > 0:
            tokens = np.zeros(cfg.language_token_per_frame, np.int32)
            mask = np.zeros(cfg.language_token_per_frame, bool)
        return (np.zeros(self.frame_shape, self.dtype), 0, 1, tokens, mask)


class VideoPipeline:
    """Windowed, batched video (+token) samples (reference dataset_video).

    Resume is exact at batch granularity: the cursor records (file index,
    windows emitted within that file) as of the last yielded batch — the
    batch buffer is empty at every yield, so replaying from the cursor
    reproduces the uninterrupted stream (the round-1 ``next_file``-only
    cursor lost intra-file position)."""

    def __init__(self, cfg: Config, sub_batch_size: int, slice_index: int = 0,
                 slice_count: int = 1,
                 paths: typing.Optional[typing.Sequence[str]] = None,
                 path_glob: typing.Optional[str] = None):
        from . import fs
        if paths is None:
            paths = fs.glob(path_glob) if path_glob else []
        self.cfg = cfg
        self.batch = sub_batch_size
        self.files, _ = split_files(paths, slice_index, slice_count,
                                    cfg.data_seed * int(cfg.shuffle_input_filenames))
        self.decoder = FrameDecoder(cfg)
        # corrupt_record_budget > 0: per-frame decode errors substitute a
        # skipped frame (counted on hbnlp_corrupt_records_total{
        # pipeline="video"}) and framing errors abandon the shard, up to the
        # budget, instead of killing the run (docs/reliability.md)
        self.budget = (CorruptRecordBudget(cfg.corrupt_record_budget,
                                           pipeline="video")
                       if cfg.corrupt_record_budget > 0 else None)
        # cursor: next window position in the stream (file_idx may equal
        # len(files): the repeat loop wraps it)
        self.file_idx = 0
        self.windows_done = 0
        # deterministic order-preserving JPEG decode parallelism (the tf.data
        # ``num_parallel_calls=parallel_interleave`` analogue, reference
        # inputs.py:556-559); cv2 releases the GIL
        self._workers = int(cfg.parallel_interleave or 1)

    def _iter_records(self, path: str, skip_records: int = 0):
        """Record payloads of one shard; under a budget, a read/framing
        error spends it and abandons the rest of the shard (the reader
        position is unknown past a framing error — same rule as the text
        pipeline)."""
        records = read_records(path, skip=skip_records)
        while True:
            try:
                # fault site "data_read:fail@N" exercises the budget path
                faults.hit("data_read")
                payload = next(records)
            except StopIteration:
                return
            except Exception as e:
                if self.budget is None:
                    raise
                self.budget.spend(path, e)  # raises when over budget
                return
            yield payload

    def _safe_decode(self, path: str, payload: bytes):
        """Frame decode with the budget: an undecodable JPEG / bad Example
        spends the budget and yields a skipped-frame placeholder (decoder
        docstring) — per-frame decode errors skip-and-count, never raise."""
        try:
            return self.decoder(payload)
        except Exception as e:
            if self.budget is None:
                raise
            self.budget.spend(f"{path} (frame decode)", e)
            return self.decoder.skipped()

    def _decode_records(self, path: str, skip_records: int = 0):
        records = self._iter_records(path, skip_records=skip_records)
        decode = functools.partial(self._safe_decode, path)
        if self._workers <= 1:
            for payload in records:
                yield decode(payload)
            return
        from multiprocessing.pool import ThreadPool
        # pool per file so worker threads are torn down deterministically
        # (a long-lived pool would keep non-daemon threads alive at exit)
        with ThreadPool(self._workers) as pool:
            yield from pool.imap(decode, records, chunksize=4)

    def _file_windows(self, path: str, skip_windows: int = 0):
        cfg = self.cfg
        size = cfg.sequence_length + cfg.time_patch
        # window k consumes records [k*shift, k*shift + size): resume skips
        # the first skip_windows*shift records RAW (no JPEG decode) and
        # restarts the window buffer at that record boundary
        start_record = skip_windows * cfg.sequence_length
        buf: typing.List[tuple] = []
        for item in self._decode_records(path, skip_records=start_record):
            buf.append(item)
            if len(buf) == size:
                yield buf
                buf = buf[cfg.sequence_length:]

    def __iter__(self) -> typing.Iterator[typing.Dict[str, np.ndarray]]:
        batch_buf: typing.List[list] = []
        file_idx = self.file_idx
        skip = self.windows_done
        while True:
            if file_idx >= len(self.files):
                file_idx = 0  # dataset_video repeats (inputs.py:475)
                skip = 0
                if not self.files:
                    return
            path = self.files[file_idx]
            produced = 0
            for window in self._file_windows(path, skip_windows=skip):
                produced += 1
                batch_buf.append(window)
                if len(batch_buf) < self.batch:
                    continue
                batch = self._assemble(batch_buf)
                batch_buf.clear()
                # commit the cursor only at batch boundaries (the buffer is
                # empty, so (file, window) identifies the next item of the
                # uninterrupted stream even when the buffer spanned a file
                # boundary) and BEFORE the yield — the generator suspends at
                # the yield, so a state_dict taken after consuming this batch
                # must already see the advanced cursor
                self.file_idx = file_idx
                self.windows_done = skip + produced
                yield batch
            skip = 0
            file_idx += 1

    def _assemble(self, windows: typing.List[list]) -> typing.Dict[str, np.ndarray]:
        cfg = self.cfg
        t = cfg.time_patch_size
        frames = np.stack([np.stack([w[0] for w in win]) for win in windows])
        concat = np.stack([[w[1] for w in win] for win in windows])
        skip = np.stack([[w[2] for w in win] for win in windows])
        out_shape = ((self.batch, t + 1, cfg.frame_height_patch,
                      cfg.frame_width_patch, cfg.channel_color_size)
                     if cfg.three_axes else
                     (self.batch, t + 1,
                      cfg.frame_height_patch * cfg.frame_width_patch,
                      cfg.channel_color_size))
        frames = frames.reshape(out_shape)
        cat = (1 - concat).astype(bool)
        fmask = (1 - skip).astype(bool)
        out = {
            "frame": frames if cfg.use_bit_fold_input_pipeline
            else frames.astype(np.int32),
            "vid_msk_src": fmask[:, :t], "vid_msk_tgt": fmask[:, 1:t + 1],
            "cat_mask_x": cat[:, :t], "cat_mask_y": cat[:, 1:t + 1],
        }
        if cfg.use_language and cfg.language_token_per_frame > 0:
            tokens = np.stack([[w[3] for w in win] for win in windows])
            tmask = np.stack([[w[4] for w in win] for win in windows])
            tokens = tokens.reshape(self.batch, t + 1, cfg.language_token_patch,
                                    cfg.token_patch_size).astype(np.int32)
            out["token_x"] = tokens[:, :t]
            out["token_y"] = tokens[:, 1:t + 1]
            out["txt_msk"] = tmask[:, 1:t + 1].reshape(
                self.batch, t, cfg.language_token_patch, cfg.token_patch_size)
        return out

    def state_dict(self) -> dict:
        return {"file_idx": self.file_idx, "windows_done": self.windows_done}

    def load_state_dict(self, state: dict) -> None:
        if "next_file" in state:  # round-1 coarse cursor (file-level only)
            self.file_idx = state["next_file"]
            self.windows_done = 0
            return
        self.file_idx = state["file_idx"]
        self.windows_done = state["windows_done"]
