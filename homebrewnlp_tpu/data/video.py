"""Video TFRecord pipeline: JPEG frames -> patchified uint8/uint32 tensors.

Port of the reference video decoder + dataset (/root/reference/src/inputs.py:
131-228, 370-483): per-record Example features are {frame: JPEG bytes,
concat: int64, skip_frame: int64} plus optional {tokens: int64[ltpf],
mask: int64}.  Frames are color-quantized, patchified via the reference's
reshape/transpose ((hp,P,wp,P,C) -> transpose(1,3,0,2,4) -> (hp,wp,P*P*C)),
optionally bit-folded (several low-bit color values packed per uint32,
inputs.py:174-198), windowed over ``sequence_length + time_patch`` frames
with shift ``sequence_length``, and emitted with src/tgt frame masks and
concat masks (dataset_video._pre_func, inputs.py:412-465).
"""
from __future__ import annotations

import typing

import numpy as np

from ..config import Config
from .pipeline import _ShuffleBuffer, split_files
from .tfrecord import decode_example, read_records


def _decode_jpeg(data: bytes) -> np.ndarray:
    import cv2
    arr = cv2.imdecode(np.frombuffer(data, np.uint8), cv2.IMREAD_COLOR)
    if arr is None:
        raise ValueError("undecodable frame")
    return cv2.cvtColor(arr, cv2.COLOR_BGR2RGB)


class FrameDecoder:
    """Single-record decoder (reference get_video_decoder)."""

    def __init__(self, cfg: Config):
        self.cfg = cfg
        # in bit-fold mode channel_color_size is already divided by
        # fold_count (config.py derivation)
        cc = cfg.channel_color_size
        self.frame_shape = ((cfg.frame_height_patch, cfg.frame_width_patch, cc)
                            if cfg.three_axes else
                            (cfg.frame_height_patch * cfg.frame_width_patch, cc))
        self.dtype = np.uint32 if cfg.use_bit_fold_input_pipeline else np.uint8
        self.multi = np.array(
            [(2 ** cfg.bit_fold_value) ** i for i in range(cfg.fold_count)],
            np.int64)[None, :, None]

    def _op_decode(self, frame: np.ndarray) -> np.ndarray:
        cfg = self.cfg
        q = cfg.color_quantization_value
        if q != 256:
            frame = np.round(frame.astype(np.float32) * ((q - 1) / 255))
            frame = frame.astype(np.int64 if cfg.use_bit_fold_input_pipeline
                                 else np.uint8)
        p = cfg.patch_size
        frame = frame.reshape(cfg.frame_height_patch, p, cfg.frame_width_patch,
                              p, cfg.color_channels)
        frame = frame.transpose(1, 3, 0, 2, 4)
        if cfg.use_bit_fold_input_pipeline:
            out_shape = (list(self.frame_shape[:-1])
                         + [cfg.fold_count, self.frame_shape[-1]])
            frame = frame.reshape(out_shape)
            frame = (frame.astype(np.int64) * self.multi).sum(axis=-2)
            return frame.astype(np.uint32)
        return frame.reshape(self.frame_shape)

    def __call__(self, payload: bytes) -> typing.Tuple[np.ndarray, int, int,
                                                       typing.Optional[np.ndarray],
                                                       typing.Optional[np.ndarray]]:
        cfg = self.cfg
        ex = decode_example(payload)
        concat = int(ex["concat"][0])
        skip = int(ex["skip_frame"][0])
        if skip > 0 or concat > 0:
            frame = np.zeros(self.frame_shape, self.dtype)
        else:
            frame = self._op_decode(_decode_jpeg(ex["frame"][0]))
        tokens = mask = None
        if cfg.language_token_per_frame > 0:
            tokens = np.asarray(ex["tokens"], np.int32)
            token_range = np.arange(cfg.language_token_per_frame)
            mask = token_range <= int(ex["mask"][0])
        return frame, concat, skip, tokens, mask


class VideoPipeline:
    """Windowed, batched video (+token) samples (reference dataset_video)."""

    def __init__(self, cfg: Config, sub_batch_size: int, slice_index: int = 0,
                 slice_count: int = 1,
                 paths: typing.Optional[typing.Sequence[str]] = None,
                 path_glob: typing.Optional[str] = None):
        import glob as globlib
        if paths is None:
            paths = globlib.glob(path_glob) if path_glob else []
        self.cfg = cfg
        self.batch = sub_batch_size
        self.files, _ = split_files(paths, slice_index, slice_count,
                                    cfg.data_seed * int(cfg.shuffle_input_filenames))
        self.decoder = FrameDecoder(cfg)
        self.next_file = 0

    def _file_windows(self, path: str):
        cfg = self.cfg
        size = cfg.sequence_length + cfg.time_patch
        buf: typing.List[tuple] = []
        for payload in read_records(path):
            buf.append(self.decoder(payload))
            if len(buf) == size:
                yield buf
                buf = buf[cfg.sequence_length:]

    def __iter__(self) -> typing.Iterator[typing.Dict[str, np.ndarray]]:
        cfg = self.cfg
        t = cfg.time_patch_size
        batch_buf: typing.List[list] = []
        while True:
            if self.next_file >= len(self.files):
                self.next_file = 0  # dataset_video repeats (inputs.py:475)
                if not self.files:
                    return
            path = self.files[self.next_file]
            self.next_file += 1
            for window in self._file_windows(path):
                batch_buf.append(window)
                if len(batch_buf) < self.batch:
                    continue
                yield self._assemble(batch_buf)
                batch_buf.clear()

    def _assemble(self, windows: typing.List[list]) -> typing.Dict[str, np.ndarray]:
        cfg = self.cfg
        t = cfg.time_patch_size
        frames = np.stack([np.stack([w[0] for w in win]) for win in windows])
        concat = np.stack([[w[1] for w in win] for win in windows])
        skip = np.stack([[w[2] for w in win] for win in windows])
        out_shape = ((self.batch, t + 1, cfg.frame_height_patch,
                      cfg.frame_width_patch, cfg.channel_color_size)
                     if cfg.three_axes else
                     (self.batch, t + 1,
                      cfg.frame_height_patch * cfg.frame_width_patch,
                      cfg.channel_color_size))
        frames = frames.reshape(out_shape)
        cat = (1 - concat).astype(bool)
        fmask = (1 - skip).astype(bool)
        out = {
            "frame": frames if cfg.use_bit_fold_input_pipeline
            else frames.astype(np.int32),
            "vid_msk_src": fmask[:, :t], "vid_msk_tgt": fmask[:, 1:t + 1],
            "cat_mask_x": cat[:, :t], "cat_mask_y": cat[:, 1:t + 1],
        }
        if cfg.use_language and cfg.language_token_per_frame > 0:
            tokens = np.stack([[w[3] for w in win] for win in windows])
            tmask = np.stack([[w[4] for w in win] for win in windows])
            tokens = tokens.reshape(self.batch, t + 1, cfg.language_token_patch,
                                    cfg.token_patch_size).astype(np.int32)
            out["token_x"] = tokens[:, :t]
            out["token_y"] = tokens[:, 1:t + 1]
            out["txt_msk"] = tmask[:, 1:t + 1].reshape(
                self.batch, t, cfg.language_token_patch, cfg.token_patch_size)
        return out

    def state_dict(self) -> dict:
        return {"next_file": self.next_file}

    def load_state_dict(self, state: dict) -> None:
        self.next_file = state["next_file"]
