"""TFRecord container + tf.train.Example wire-format codec, dependency-free.

The reference reads/writes TFRecords through tf.data / tf.io
(/root/reference/src/inputs.py:231-268, scripts/text2tfrecord.py:57-107).  The
on-disk formats are tiny specs, so this framework implements them directly —
the training path needs numpy arrays for ``jax.make_array_from_callback``,
not TF tensors, and dropping the TF dependency keeps the loader importable
everywhere.  Layout per record: u64-LE length, masked-crc32c(length),
payload, masked-crc32c(payload).  Payloads are tf.train.Example protobufs;
only the three Feature kinds exist (bytes/float/int64 lists).

A C++ fast path for the record framing + CRC lives in native/ (used by the
data tooling); this module is the portable fallback and the source of truth
for tests.
"""
from __future__ import annotations

import os
import struct
import typing

# -- crc32c (Castagnoli, reflected poly 0x82F63B78) --------------------------

_CRC_TABLE: typing.List[int] = []


def _build_table() -> None:
    for i in range(256):
        c = i
        for _ in range(8):
            c = (c >> 1) ^ 0x82F63B78 if c & 1 else c >> 1
        _CRC_TABLE.append(c)


_build_table()


def crc32c(data: bytes) -> int:
    crc = 0xFFFFFFFF
    for b in data:
        crc = _CRC_TABLE[(crc ^ b) & 0xFF] ^ (crc >> 8)
    return crc ^ 0xFFFFFFFF


def masked_crc(data: bytes) -> int:
    crc = crc32c(data)
    return (((crc >> 15) | (crc << 17)) + 0xA282EAD8) & 0xFFFFFFFF


# -- varint ------------------------------------------------------------------

def _write_varint(out: bytearray, value: int) -> None:
    value &= 0xFFFFFFFFFFFFFFFF
    while True:
        b = value & 0x7F
        value >>= 7
        if value:
            out.append(b | 0x80)
        else:
            out.append(b)
            return


def _read_varint(buf: bytes, pos: int) -> typing.Tuple[int, int]:
    result = shift = 0
    while True:
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7


# -- tf.train.Example --------------------------------------------------------

def _field(out: bytearray, number: int, payload: bytes) -> None:
    _write_varint(out, (number << 3) | 2)  # len-delimited wire type
    _write_varint(out, len(payload))
    out.extend(payload)


def encode_example(features: typing.Dict[str, typing.Union[bytes, typing.Sequence[int], typing.Sequence[float]]]
                   ) -> bytes:
    """Build an Example proto.  Values: bytes -> BytesList, list of int ->
    packed Int64List, list of float -> packed FloatList."""
    feats = bytearray()
    for key, value in features.items():
        feature = bytearray()
        if isinstance(value, bytes):
            blist = bytearray()
            _field(blist, 1, value)
            _field(feature, 1, bytes(blist))  # Feature.bytes_list
        elif len(value) and isinstance(value[0], float):
            packed = struct.pack(f"<{len(value)}f", *value)
            flist = bytearray()
            _field(flist, 1, packed)
            _field(feature, 2, bytes(flist))  # Feature.float_list
        else:
            packed = bytearray()
            for v in value:
                _write_varint(packed, int(v))
            ilist = bytearray()
            _field(ilist, 1, bytes(packed))
            _field(feature, 3, bytes(ilist))  # Feature.int64_list
        entry = bytearray()
        _field(entry, 1, key.encode())
        _field(entry, 2, bytes(feature))
        _field(feats, 1, bytes(entry))  # Features.feature map entry
    out = bytearray()
    _field(out, 1, bytes(feats))  # Example.features
    return bytes(out)


def _parse_fields(buf: bytes) -> typing.Iterator[typing.Tuple[int, int, typing.Union[int, bytes]]]:
    pos = 0
    while pos < len(buf):
        tag, pos = _read_varint(buf, pos)
        number, wire = tag >> 3, tag & 7
        if wire == 0:
            value, pos = _read_varint(buf, pos)
        elif wire == 2:
            length, pos = _read_varint(buf, pos)
            value = buf[pos:pos + length]
            pos += length
        elif wire == 5:
            value = buf[pos:pos + 4]
            pos += 4
        elif wire == 1:
            value = buf[pos:pos + 8]
            pos += 8
        else:
            raise ValueError(f"unsupported wire type {wire}")
        yield number, wire, value


def decode_example(buf: bytes) -> typing.Dict[str, typing.Union[typing.List[bytes], typing.List[int], typing.List[float]]]:
    """Parse an Example into {key: list-of-values}."""
    out: typing.Dict[str, typing.Any] = {}
    for num, _, features_buf in _parse_fields(buf):
        if num != 1:
            continue
        for fnum, _, entry in _parse_fields(features_buf):
            if fnum != 1:
                continue
            key = None
            feature = b""
            for enum_, _, val in _parse_fields(entry):
                if enum_ == 1:
                    key = val.decode()
                elif enum_ == 2:
                    feature = val
            values: typing.List[typing.Any] = []
            for knum, wire, lst in _parse_fields(feature):
                if knum == 1:  # bytes_list
                    values.extend(v for n, _, v in _parse_fields(lst) if n == 1)
                elif knum == 2:  # float_list
                    for n, w, v in _parse_fields(lst):
                        if n != 1:
                            continue
                        if w == 2:  # packed
                            values.extend(struct.unpack(f"<{len(v) // 4}f", v))
                        else:
                            values.append(struct.unpack("<f", v)[0])
                elif knum == 3:  # int64_list
                    for n, w, v in _parse_fields(lst):
                        if n != 1:
                            continue
                        if w == 2:  # packed varints
                            p = 0
                            while p < len(v):
                                x, p = _read_varint(v, p)
                                values.append(x - (1 << 64) if x >> 63 else x)
                        else:
                            values.append(v - (1 << 64) if v >> 63 else v)
            out[key] = values
    return out


# -- record framing ----------------------------------------------------------

class RecordWriter:
    def __init__(self, path: str, append: bool = False):
        from . import fs
        if not fs.is_remote(path):
            os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        self._f = fs.open_stream(path, "ab" if append else "wb")

    def write(self, record: bytes) -> None:
        header = struct.pack("<Q", len(record))
        self._f.write(header)
        self._f.write(struct.pack("<I", masked_crc(header)))
        self._f.write(record)
        self._f.write(struct.pack("<I", masked_crc(record)))

    def close(self) -> None:
        self._f.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def read_records(path: str, verify: bool = False,
                 skip: int = 0) -> typing.Iterator[bytes]:
    """Yield raw record payloads; ``skip`` fast-forwards without CRC work.
    ``path`` may be a remote URL (gs://...) — see data/fs.py.  The open is
    retried with backoff (reliability.retry): a transient storage hiccup at
    shard-open must not kill a multi-day run."""
    from . import fs
    from ..reliability import retry_call
    with retry_call(lambda: fs.open_stream(path, "rb"),
                    site="data_open") as f:
        index = 0
        while True:
            header = f.read(8)
            if len(header) < 8:
                return
            (length,) = struct.unpack("<Q", header)
            if index < skip:
                f.seek(4 + length + 4, os.SEEK_CUR)
                index += 1
                continue
            f.seek(4, os.SEEK_CUR)  # length crc
            record = f.read(length)
            if len(record) < length:
                return
            crc_bytes = f.read(4)
            if verify:
                (expect,) = struct.unpack("<I", crc_bytes)
                if masked_crc(record) != expect:
                    raise IOError(f"crc mismatch in {path} record {index}")
            index += 1
            yield record


def count_records(path: str) -> int:
    from . import fs
    n = 0
    with fs.open_stream(path, "rb") as f:
        while True:
            header = f.read(8)
            if len(header) < 8:
                return n
            (length,) = struct.unpack("<Q", header)
            f.seek(4 + length + 4, os.SEEK_CUR)
            n += 1
