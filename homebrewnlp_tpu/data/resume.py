"""Deterministic data-stream resume.

Two mechanisms:

1. **Cursor checkpointing (primary)**: every pipeline exposes
   ``state_dict``/``load_state_dict`` and the cursor rides along with orbax
   checkpoints (train/checkpoint.py) — simpler and exact.

2. **Run-log replay (reference parity)**: the reference reconstructs per-file
   skip counts by replaying previous runs' consumption arithmetic against the
   token counts encoded in filenames (``..._<n>.tfrecord``), never storing
   iterator state (/root/reference/src/inputs.py:33-128,
   src/run/dataloader_placement.py:101-136).  ``simulate_consumption`` ports
   that: round-robin window consumption inside interleave groups, per slice,
   until the run's step budget is exhausted.  Files are treated as one token
   stream (the reference's single-document assumption).
"""
from __future__ import annotations

import json
import os
import time
import typing


class RunLog:
    """The DataLog artifact: one entry per completed run."""

    def __init__(self, model_path: str):
        from ..reliability import retry_call
        self.path = os.path.join(model_path, "data_log.json")
        self.runs: typing.List[dict] = []
        if os.path.exists(self.path):
            def _read() -> str:
                with open(self.path) as f:  # graftcheck: disable=bare-io
                    return f.read()
            self.runs = json.loads(retry_call(_read, site="runlog"))

    def append(self, *, steps: int, batch_size: int, slice_count: int,
               ctx: int, grad_accumulation: int = 1, interleave_size: int = 1,
               token_patch_size: int = 1) -> None:
        self.runs.append(dict(steps=steps, batch_size=batch_size,
                              slice_count=slice_count, ctx=ctx,
                              grad_accumulation=grad_accumulation,
                              interleave_size=interleave_size,
                              token_patch_size=token_patch_size,
                              timestamp=time.time()))

    def save(self) -> None:
        from ..reliability import retry_call
        os.makedirs(os.path.dirname(self.path), exist_ok=True)

        def _write() -> None:
            with open(self.path, "w") as f:  # graftcheck: disable=bare-io
                json.dump(self.runs, f)

        retry_call(_write, site="runlog")


def tokens_from_filename(path: str) -> int:
    """``shard..._<n>.tfrecord`` -> n (reference inputs.py:34)."""
    stem = os.path.basename(str(path))
    return int(stem.split("_")[-1].replace(".tfrecord", ""))


def simulate_consumption(file_tokens: typing.Sequence[int],
                         runs: typing.Sequence[dict]
                         ) -> typing.Tuple[typing.List[bool], typing.List[int]]:
    """Replay runs -> (file fully consumed?, tokens consumed per file).

    Window arithmetic per file: usable tokens = ``c - ((c - patch) % ctx) -
    patch`` (windows of ctx+patch shifted by ctx drop the remainder); each
    window consumes ``ctx`` tokens.  Consumption is round-robin one window at
    a time across each interleave group (tf.data interleave block_length=1),
    groups processed in order, per slice (reference inputs.py:33-128)."""
    n = len(file_tokens)
    consumed = [0] * n
    depleted = [False] * n

    for run in runs:
        ctx = run["ctx"]
        patch = run.get("token_patch_size", 1)
        slice_count = run["slice_count"]
        interleave = max(1, run["interleave_size"])
        budget_per_slice = (run["steps"] * run.get("grad_accumulation", 1)
                            * (run["batch_size"] // slice_count))

        # live files in original order (replicates the reference re-deriving
        # the active file list at the start of each run)
        live = [i for i in range(n) if not depleted[i]]

        for slice_index in range(slice_count):
            slice_files = live[slice_index::slice_count]
            budget = budget_per_slice
            for g in range(0, len(slice_files), interleave):
                group = slice_files[g:g + interleave]
                # remaining windows per file in this group
                windows = []
                for i in group:
                    c = file_tokens[i] - consumed[i]
                    usable = c - ((c - patch) % ctx) - patch
                    windows.append(max(0, usable // ctx))
                total = sum(windows)
                if total <= budget:
                    budget -= total
                    for i, w in zip(group, windows):
                        consumed[i] += w * ctx
                        depleted[i] = True
                    if budget == 0:
                        break
                    continue
                # partial group: round-robin single windows
                idx = 0
                while budget > 0 and sum(windows) > 0:
                    while windows[idx] <= 0:
                        idx = (idx + 1) % len(group)
                    windows[idx] -= 1
                    consumed[group[idx]] += ctx
                    budget -= 1
                    idx = (idx + 1) % len(group)
                for i, w in zip(group, windows):
                    if w <= 0:
                        depleted[i] = True
                break  # budget exhausted inside this group

        # the reference only skips whole files when an entire interleave
        # group is depleted (inputs.py:117-127): partially-depleted groups
        # must be revisited so the interleave pattern replays identically
        for slice_index in range(slice_count):
            slice_files = live[slice_index::slice_count]
            for g in range(0, len(slice_files), interleave):
                group = slice_files[g:g + interleave]
                full = all(depleted[i] for i in group)
                for i in group:
                    depleted[i] = full

    return depleted, consumed


def skips_for_restart(filenames: typing.Sequence[str], runs: typing.Sequence[dict]
                      ) -> typing.Tuple[typing.List[str], typing.List[int]]:
    """Files to keep + per-file token skips for a restarted run."""
    tokens = [tokens_from_filename(f) for f in filenames]
    depleted, consumed = simulate_consumption(tokens, runs)
    keep = [f for f, d in zip(filenames, depleted) if not d]
    skips = [c for c, d in zip(consumed, depleted) if not d]
    return keep, skips
