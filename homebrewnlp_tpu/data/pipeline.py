"""Input pipeline: windowed token/video streams from TFRecords, interleaved,
batched, resumable.

Re-designs the reference tf.data pipeline (/root/reference/src/inputs.py) as
deterministic pure-Python iterators over numpy arrays — the right shape for
JAX, where host code assembles global device arrays per step
(data/feed.py) instead of TF infeed queues.  Parity map:

- ``split_files``          <- inputs.py:15-30 (sorted + seeded shuffle + host slice)
- ``_FileWindows``         <- ``_text_decoder`` window(size=ctx+patch, shift=ctx)
                              per record (documents never cross records),
                              inputs.py:231-251
- ``GptPipeline``          <- ``gpt_neo_input`` (inputs.py:528-568): interleave
                              cycle over files, batch, x/y split by output_offset
- ``JannetTextPipeline``   <- ``dataset_text`` (inputs.py:271-367): padding
                              frames/masks zipped with token windows
- ``VideoPipeline``        <- ``dataset_video``+``get_video_decoder``
                              (inputs.py:131-228,370-483): JPEG decode,
                              patchify transpose, quantization, bit-fold,
                              concat/skip masks
- ``MixturePipeline``      <- weighted ``sample_from_datasets`` (inputs.py:486-525)

Divergences (documented): byte records decode as raw bytes (vocab 256) rather
than UTF-8 codepoints which can exceed the vocab; every pipeline exposes
``state_dict``/``load_state_dict`` so resume checkpoints the cursor directly
(the reference's run-log replay is kept as an alternative in data/resume.py).
"""
from __future__ import annotations

import random
import typing

import numpy as np

from ..config import Config
from ..reliability import CorruptRecordBudget, faults
from .tfrecord import decode_example, read_records


def split_files(filenames: typing.Sequence[str], slice_index: int,
                slice_count: int, seed: int,
                runs_log: typing.Optional[typing.Sequence[dict]] = None
                ) -> typing.Tuple[typing.List[str], typing.List[int]]:
    """Sorted + seeded shuffle + optional run-log replay + per-host slice
    (reference inputs.py:15-30): replay runs against the ordered full list,
    drop depleted files, then slice files and skips together."""
    if not filenames:
        raise ValueError("no input files")
    files = sorted(filenames)
    if seed != 0:
        rng = random.Random(seed)
        rng.shuffle(files)
    skips = [0] * len(files)
    if runs_log:
        from .resume import skips_for_restart
        files, skips = skips_for_restart(files, runs_log)
    return files[slice_index::slice_count], skips[slice_index::slice_count]


def decode_bytes_record(payload: bytes) -> np.ndarray:
    ex = decode_example(payload)
    (raw,) = ex["text"]
    return np.frombuffer(raw, dtype=np.uint8).astype(np.int32)


def decode_int64_record(payload: bytes) -> np.ndarray:
    ex = decode_example(payload)
    return np.asarray(ex["text"], dtype=np.int32)


def decoder_for(path: str) -> typing.Callable[[bytes], np.ndarray]:
    # filename convention from the reference (inputs.py:541): int64 in the
    # name marks BPE-encoded shards, else byte-level
    return decode_int64_record if "int64" in path else decode_bytes_record


class _FileWindows:
    """Windows of ``window`` tokens, shift ``shift``, per record of one file.
    ``skip_tokens`` drops leading tokens of the file's concatenated stream
    (for run-log resume); ``skip_windows`` drops emitted windows (for direct
    cursor resume).  ``budget`` (a reliability.CorruptRecordBudget) turns an
    unreadable record into skip-and-log instead of run death: a bad decode
    skips the record, a failed read abandons the rest of the shard (the
    reader position is unknown after a framing error); None keeps the
    strict fail-fast behavior.  NOTE a skipped record shifts this file's
    window numbering, so a cursor checkpointed across a skip replays
    identically only while the corruption persists — the skip is logged
    loudly for exactly that reason."""

    def __init__(self, path: str, window: int, shift: int,
                 skip_tokens: int = 0, skip_windows: int = 0,
                 budget: typing.Optional[CorruptRecordBudget] = None):
        self.path = path
        self.window = window
        self.shift = shift
        self.skip_tokens = skip_tokens
        self.emitted = 0
        self._skip_windows = skip_windows
        self.budget = budget

    def __iter__(self) -> typing.Iterator[np.ndarray]:
        decode = decoder_for(self.path)
        remaining_skip = self.skip_tokens
        records = read_records(self.path)
        while True:
            try:
                # fault site "data_read:fail@N" exercises the budget path
                faults.hit("data_read")
                payload = next(records)
            except StopIteration:
                return
            except Exception as e:
                if self.budget is None:
                    raise
                self.budget.spend(self.path, e)  # raises when over budget
                return  # framing broken: reader position unknown past here
            try:
                tokens = decode(payload)
            except Exception as e:
                if self.budget is None:
                    raise
                self.budget.spend(self.path, e)
                continue  # one bad record: the framing still holds
            if remaining_skip:
                take = min(remaining_skip, len(tokens))
                tokens = tokens[take:]
                remaining_skip -= take
                if not len(tokens):
                    continue
            for start in range(0, len(tokens) - self.window + 1, self.shift):
                if self._skip_windows:
                    self._skip_windows -= 1
                    self.emitted += 1
                    continue
                self.emitted += 1
                yield tokens[start:start + self.window]


class _Interleave:
    """Round-robin over up to ``cycle`` concurrently-open file window streams
    (tf.data interleave, block_length=1).  Resumable: records per-file window
    counts for the open slots plus the next file index."""

    def __init__(self, files: typing.Sequence[str], skips: typing.Sequence[int],
                 window: int, shift: int, cycle: int, repeat: bool,
                 budget: typing.Optional[CorruptRecordBudget] = None):
        self.files = list(files)
        self.skips = list(skips)
        self.window = window
        self.shift = shift
        self.cycle = max(1, cycle)
        self.repeat = repeat
        self.budget = budget
        self.next_file = 0
        self._pos = 0
        self._slots: typing.List[typing.Tuple[int, _FileWindows, typing.Iterator]] = []

    def _open(self, file_idx: int, skip_windows: int = 0
              ) -> typing.Tuple[int, _FileWindows, typing.Iterator]:
        src = _FileWindows(self.files[file_idx % len(self.files)],
                           self.window, self.shift,
                           skip_tokens=self.skips[file_idx % len(self.files)],
                           skip_windows=skip_windows, budget=self.budget)
        return file_idx, src, iter(src)

    def _fill(self) -> None:
        limit = len(self.files) if not self.repeat else float("inf")
        while len(self._slots) < self.cycle and self.next_file < limit:
            self._slots.append(self._open(self.next_file))
            self.next_file += 1

    def __iter__(self) -> typing.Iterator[np.ndarray]:
        self._fill()
        while self._slots:
            self._pos %= len(self._slots)
            _, src, it = self._slots[self._pos]
            try:
                item = next(it)
                self._pos += 1
                yield item
            except StopIteration:
                del self._slots[self._pos]
                self._fill()

    def state_dict(self) -> dict:
        return {"next_file": self.next_file, "pos": self._pos,
                "slots": [[idx, src.emitted] for idx, src, _ in self._slots]}

    def load_state_dict(self, state: dict) -> None:
        self.next_file = state["next_file"]
        self._pos = state.get("pos", 0)
        self._slots = [self._open(idx, skip_windows=emitted)
                       for idx, emitted in state["slots"]]


class _ShuffleBuffer:
    """Seeded reservoir shuffle (tf.data Dataset.shuffle semantics).

    Resumable by replay: the whole state is the count of items pulled from
    the inner stream — ``load_state_dict`` replays that many pulls (same rng
    draw sequence, discarding the yields) against a fresh inner iterator to
    rebuild the buffer exactly.  Costs one sequential re-read of consumed
    data on resume, but avoids serializing up to ``shuffle_buffer`` windows."""

    def __init__(self, inner: typing.Iterable, size: int, seed: int):
        self.inner = inner
        self.size = size
        self.seed = seed
        self.pulled = 0

    def _replay(self) -> typing.Tuple[typing.List[np.ndarray],
                                      np.random.Generator,
                                      typing.Iterator]:
        rng = np.random.default_rng(self.seed)
        buf: typing.List[np.ndarray] = []
        it = iter(self.inner)
        for _ in range(self.pulled):
            item = next(it)
            if len(buf) < self.size:
                buf.append(item)
                continue
            idx = int(rng.integers(len(buf)))
            buf[idx] = item  # the swapped-out item was already yielded
        return buf, rng, it

    def __iter__(self):
        if self.size <= 1:
            yield from self.inner
            return
        buf, rng, it = self._replay()
        for item in it:
            self.pulled += 1
            if len(buf) < self.size:
                buf.append(item)
                continue
            idx = int(rng.integers(len(buf)))
            buf[idx], item = item, buf[idx]
            yield item
        rng.shuffle(buf)  # drain
        yield from buf

    def state_dict(self) -> dict:
        return {"pulled": self.pulled}

    def load_state_dict(self, state: dict) -> None:
        self.pulled = state["pulled"]


class GptPipeline:
    """Pure-text batches {token_x, token_y} of shape
    [batch, seq // token_patch, token_patch] (reference inputs.py:528-568)."""

    def __init__(self, cfg: Config, sub_batch_size: int, slice_index: int = 0,
                 slice_count: int = 1,
                 paths: typing.Optional[typing.Sequence[str]] = None,
                 runs_log: typing.Optional[typing.Sequence[dict]] = None):
        from . import fs
        if paths is None:
            paths = []
            for dset in cfg.dataset_configs:
                paths.extend(fs.glob(dset["path"]))
        self.cfg = cfg
        self.batch = sub_batch_size
        files, file_skips = split_files(
            paths, slice_index, slice_count,
            cfg.data_seed * int(cfg.shuffle_input_filenames), runs_log)
        window = cfg.sequence_length + cfg.token_patch_size * cfg.output_offset
        self.rows = cfg.sequence_length // cfg.token_patch_size
        # repeat_dataset=None keeps the reference's rule (only the random
        # dataloader repeats, inputs.py:540-541 — the sequential reader is
        # single-epoch and training DIES at exhaustion there); explicit
        # true/false overrides it (epoch wrap-around reuses the modulo file
        # indexing of _Interleave._open, so the deterministic order and the
        # resume cursor survive the epoch boundary)
        repeat = (cfg.use_random_dataloader if cfg.repeat_dataset is None
                  else bool(cfg.repeat_dataset))
        # corrupt_record_budget > 0: unreadable records/shards are skipped
        # (logged + counted) up to the budget instead of killing the run
        budget = (CorruptRecordBudget(cfg.corrupt_record_budget,
                                      pipeline="text")
                  if cfg.corrupt_record_budget > 0 else None)
        self.interleave = _Interleave(
            files, file_skips, window, cfg.sequence_length,
            cfg.interleaved_datasets, repeat=repeat, budget=budget)
        self.stream: typing.Iterable = self.interleave
        if cfg.use_random_dataloader and cfg.shuffle_buffer > 1:
            self.stream = _ShuffleBuffer(self.interleave, cfg.shuffle_buffer,
                                         cfg.data_seed)

    def __iter__(self) -> typing.Iterator[typing.Dict[str, np.ndarray]]:
        cfg = self.cfg
        patch = cfg.token_patch_size
        buf: typing.List[np.ndarray] = []
        for window in self.stream:
            buf.append(window)
            if len(buf) < self.batch:
                continue
            x = np.stack(buf)
            buf.clear()
            x = x.reshape(self.batch, self.rows + cfg.output_offset, patch)
            if cfg.output_offset > 0:
                token_x = x[:, :self.rows]
                token_y = x[:, cfg.output_offset:self.rows + cfg.output_offset]
            else:
                token_x = token_y = x
            yield {"token_x": np.ascontiguousarray(token_x),
                   "token_y": np.ascontiguousarray(token_y)}

    def state_dict(self) -> dict:
        if isinstance(self.stream, _ShuffleBuffer):
            return {"shuffle": self.stream.state_dict()}
        return {"interleave": self.interleave.state_dict()}

    def load_state_dict(self, state: dict) -> None:
        """Must be called on a freshly-constructed pipeline (checkpoint
        resume): shuffle replay re-pulls from the file start."""
        if "shuffle" in state:
            self.stream.load_state_dict(state["shuffle"])
        else:
            self.interleave.load_state_dict(state.get("interleave", state))


class JannetTextPipeline:
    """Text windows dressed as video-model inputs: zero frames, padding
    masks, concat-token text mask (reference dataset_text,
    inputs.py:271-367)."""

    def __init__(self, cfg: Config, sub_batch_size: int, slice_index: int = 0,
                 slice_count: int = 1,
                 paths: typing.Optional[typing.Sequence[str]] = None):
        from . import fs
        if paths is None:
            paths = []
            for dset in cfg.dataset_configs:
                if dset["type"] == "text":
                    paths.extend(fs.glob(dset["path"]))
        self.cfg = cfg
        self.batch = sub_batch_size
        files, skips = split_files(paths, slice_index, slice_count,
                                   cfg.data_seed * int(cfg.shuffle_input_filenames))
        per_frame = cfg.language_token_per_frame - 1
        window = (cfg.time_patch_size + 1) * per_frame
        budget = (CorruptRecordBudget(cfg.corrupt_record_budget,
                                      pipeline="text")
                  if cfg.corrupt_record_budget > 0 else None)
        self.interleave = _Interleave(files, skips, window, window,
                                      cfg.interleaved_datasets, repeat=True,
                                      budget=budget)
        self.stream: typing.Iterable = _ShuffleBuffer(
            self.interleave, cfg.shuffle_buffer, cfg.data_seed)

    def __iter__(self) -> typing.Iterator[typing.Dict[str, np.ndarray]]:
        cfg = self.cfg
        t = cfg.time_patch_size
        per_frame = cfg.language_token_per_frame - 1
        frame_shape = ((t + 1, cfg.frame_height_patch, cfg.frame_width_patch,
                        cfg.channel_color_size) if cfg.three_axes else
                       (t + 1, cfg.frame_height_patch * cfg.frame_width_patch,
                        cfg.channel_color_size))
        buf: typing.List[np.ndarray] = []
        for window in self.stream:
            buf.append(window)
            if len(buf) < self.batch:
                continue
            x = np.stack(buf).astype(np.int32)
            buf.clear()
            x = x.reshape(self.batch, t + 1, per_frame)
            pad = np.full((self.batch, t + 1, 1), cfg.padding_token, np.int32)
            x = np.concatenate([x, pad], axis=2)
            x = x.reshape(self.batch, t + 1, cfg.language_token_patch,
                          cfg.token_patch_size)
            token_x, token_y = x[:, :t], x[:, 1:t + 1]
            yield {
                "frame": np.zeros((self.batch,) + frame_shape, np.int32),
                "token_x": token_x, "token_y": token_y,
                "txt_msk": token_y != cfg.concat_token,
                "vid_msk_src": np.zeros((self.batch, t), bool),
                "vid_msk_tgt": np.zeros((self.batch, t), bool),
                "cat_mask_x": np.ones((self.batch, t), bool),
                "cat_mask_y": np.ones((self.batch, t), bool),
            }

    def state_dict(self) -> dict:
        return {"shuffle": self.stream.state_dict()}

    def load_state_dict(self, state: dict) -> None:
        self.stream.load_state_dict(state.get("shuffle", state))


class MixturePipeline:
    """Seeded weighted sampling across child pipelines (the reference's
    ``sample_from_datasets``, inputs.py:517-520)."""

    def __init__(self, children: typing.Sequence[typing.Iterable],
                 weights: typing.Sequence[float], seed: int):
        self.children = list(children)
        self.weights = np.asarray(weights, np.float64)
        self.weights /= self.weights.sum()
        self.seed = seed
        self.drawn = 0

    def __iter__(self):
        rng = np.random.default_rng(self.seed)
        live = list(range(len(self.children)))
        iters = [iter(c) for c in self.children]
        # replay the choice stream for deterministic resume
        for _ in range(self.drawn):
            rng.choice(len(self.children), p=self.weights)
        while live:
            weights = self.weights[live] / self.weights[live].sum()
            idx = live[int(rng.choice(len(live), p=weights))]
            self.drawn += 1
            try:
                yield next(iters[idx])
            except StopIteration:
                # keep sampling the remaining datasets (tf.data
                # sample_from_datasets with stop_on_empty_dataset=False)
                live.remove(idx)

    def state_dict(self) -> dict:
        return {"drawn": self.drawn,
                "children": [getattr(c, "state_dict", dict)() for c in self.children]}

    def load_state_dict(self, state: dict) -> None:
        self.drawn = state["drawn"]
        for child, s in zip(self.children, state["children"]):
            if hasattr(child, "load_state_dict"):
                child.load_state_dict(s)


class Prefetcher:
    """Background-thread batch prefetch with a bounded queue of
    ``cfg.buffer_size`` batches (the reference's ``dataset.prefetch(
    params.buffer_size)``, dataloader_placement.py:157).

    Resume stays exact: the producer snapshots the inner pipeline's cursor
    *after* producing each batch and attaches it to the queue entry, so
    ``state_dict`` reflects the last batch actually handed to the consumer —
    batches still sitting in the queue are not lost.  ``close()`` stops and
    joins the producer even when it is parked on a full queue (puts poll the
    stop flag), so an abandoning consumer — e.g. the async train loop's
    DeviceFeeder shutting down mid-stream — never strands the thread."""

    _DONE = object()

    def __init__(self, inner, depth: int):
        self.inner = inner
        self.depth = max(1, int(depth))
        self._state = getattr(inner, "state_dict", dict)()
        self._thread = None
        self._queue = None
        self._stop = None

    def __iter__(self):
        import queue as queuelib
        import threading

        self._queue = queuelib.Queue(maxsize=self.depth)
        self._stop = threading.Event()
        err: typing.List[BaseException] = []

        def put(item) -> bool:
            while not self._stop.is_set():
                try:
                    self._queue.put(item, timeout=0.05)
                    return True
                except queuelib.Full:
                    continue
            return False

        def produce():
            try:
                for item in self.inner:
                    if not put((item, getattr(self.inner, "state_dict",
                                              dict)())):
                        return
            except BaseException as e:  # surfaced on the consumer side
                err.append(e)
            put((self._DONE, None))

        self._thread = threading.Thread(target=produce, daemon=True)
        self._thread.start()
        while True:
            item, state = self._queue.get()
            if item is self._DONE:
                if err:
                    raise err[0]
                return
            self._state = state
            yield item

    def state_dict(self) -> dict:
        return dict(self._state)

    def load_state_dict(self, state: dict) -> None:
        if hasattr(self.inner, "load_state_dict"):
            self.inner.load_state_dict(state)
        self._state = dict(state)

    def close(self, timeout: float = 5.0) -> None:
        """Stop and join the producer thread; safe to call repeatedly."""
        if self._thread is None:
            return
        self._stop.set()
        import queue as queuelib
        try:
            while True:
                self._queue.get_nowait()
        except queuelib.Empty:
            pass
        # wake a consumer parked on the queue.  Bounded retry: a producer
        # put that entered before _stop was set can land in the freshly
        # drained queue and swallow a single-shot sentinel — re-drain and
        # retry until the sentinel sticks (the producer is stopping, so
        # this terminates after at most one in-flight item per slot)
        for _ in range(100):
            try:
                self._queue.put_nowait((self._DONE, None))
                break
            except queuelib.Full:
                try:
                    self._queue.get_nowait()
                except queuelib.Empty:
                    pass
        self._thread.join(timeout)
        self._thread = None


def dataset(cfg: Config, sub_batch_size: int, slice_index: int = 0,
            slice_count: int = 1, prefetch: bool = True):
    """Mixture entry point mirroring the reference API (inputs.py:486-525).
    ``prefetch=False`` skips the background-thread Prefetcher (for probe
    pipelines that read one template batch and are discarded)."""
    from .video import VideoPipeline
    children: typing.List[typing.Iterable] = []
    weights: typing.List[float] = []
    for dset in cfg.dataset_configs:
        kind = dset["type"]
        if kind == "video":
            children.append(VideoPipeline(cfg, sub_batch_size, slice_index,
                                          slice_count, paths=None,
                                          path_glob=dset["path"]))
        elif kind == "text" and cfg.use_language:
            if cfg.model_mode == "gpt":
                children.append(GptPipeline(cfg, sub_batch_size, slice_index,
                                            slice_count))
            else:
                children.append(JannetTextPipeline(
                    cfg, sub_batch_size, slice_index, slice_count,
                    paths=None))
        else:
            raise ValueError(f"unsupported dataset type {kind}")
        weights.append(dset.get("weight", 1.0))
    pipe = (children[0] if len(children) == 1
            else MixturePipeline(children, weights, cfg.data_seed))
    if prefetch and cfg.buffer_size and cfg.buffer_size > 0:
        pipe = Prefetcher(pipe, cfg.buffer_size)
    return pipe
