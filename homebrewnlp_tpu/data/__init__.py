"""Input pipeline layer: TFRecord IO, windowed text/video pipelines,
mixtures, host-sharded device feeding, deterministic resume.

JAX re-design of the reference's tf.data stack (/root/reference/src/inputs.py,
src/run/dataloader_placement.py) — see pipeline.py for the parity map.
"""
from .feed import DeviceFeeder, to_global  # noqa: F401
from .pipeline import (GptPipeline, JannetTextPipeline, MixturePipeline,  # noqa: F401
                       dataset, split_files)
from .resume import RunLog, skips_for_restart  # noqa: F401
from .synthetic import (synthetic_text_batch, write_text_tfrecords,  # noqa: F401
                        write_video_tfrecords)
from .tfrecord import (RecordWriter, count_records, decode_example,  # noqa: F401
                       encode_example, read_records)
from .video import VideoPipeline  # noqa: F401
