"""Synthetic data: deterministic token batches + TFRecord fixture writers.

The reference has no test data story (SURVEY.md §4); these helpers back the
test suite and bench.py, and double as the format reference for the real
TFRecord writers in tools/.
"""
from __future__ import annotations

import os
import typing

import numpy as np

from ..config import Config
from .tfrecord import RecordWriter, encode_example


def synthetic_text_batch(cfg: Config, step: int = 0, seed: int = 0
                         ) -> typing.Dict[str, np.ndarray]:
    rng = np.random.default_rng((seed, step))
    rows = cfg.sequence_length // cfg.token_patch_size
    # macro-batching inflates the host batch (reference
    # dataloader_placement.py:40-44)
    shape = (cfg.train_batch_size * cfg.macro_batching,
             rows + cfg.output_offset, cfg.token_patch_size)
    stream = rng.integers(0, cfg.vocab_size, shape, np.int32)
    return {"token_x": stream[:, :rows],
            "token_y": stream[:, cfg.output_offset:rows + cfg.output_offset]}


def synthetic_video_batch(cfg: Config, step: int = 0, seed: int = 0
                          ) -> typing.Dict[str, np.ndarray]:
    """Random jannet-mode batch matching VideoPipeline's output shapes."""
    rng = np.random.default_rng((seed, step, 7))
    b = cfg.train_batch_size * cfg.macro_batching
    t = cfg.time_patch_size
    frame_shape = ((b, t + 1, cfg.frame_height_patch, cfg.frame_width_patch,
                    cfg.channel_color_size) if cfg.three_axes else
                   (b, t + 1, cfg.frame_height_patch * cfg.frame_width_patch,
                    cfg.channel_color_size))
    out = {
        "frame": rng.integers(0, 256, frame_shape, np.int32),
        "vid_msk_src": np.ones((b, t), bool),
        "vid_msk_tgt": np.ones((b, t), bool),
        "cat_mask_x": np.ones((b, t), bool),
        "cat_mask_y": np.ones((b, t), bool),
    }
    if cfg.use_language and cfg.language_token_per_frame > 0:
        toks = rng.integers(0, cfg.vocab_size,
                            (b, t + 1, cfg.language_token_patch,
                             cfg.token_patch_size), np.int32)
        out["token_x"] = toks[:, :t]
        out["token_y"] = toks[:, 1:t + 1]
        out["txt_msk"] = np.ones_like(out["token_y"], bool)
    return out


def write_text_tfrecords(directory: str, n_files: int, records_per_file: int,
                         tokens_per_record: int, vocab: int = 256,
                         seed: int = 0, int64: bool = False
                         ) -> typing.List[str]:
    """Write synthetic text shards; filenames carry the token count the way
    the reference's run-log replay expects (``..._<n_tokens>.tfrecord``,
    inputs.py:34)."""
    rng = np.random.default_rng(seed)
    os.makedirs(directory, exist_ok=True)
    paths = []
    total = records_per_file * tokens_per_record
    for i in range(n_files):
        kind = "int64" if int64 else "bytes"
        path = os.path.join(directory, f"shard{kind}{i:04d}_{total}.tfrecord")
        with RecordWriter(path) as w:
            for _ in range(records_per_file):
                tokens = rng.integers(0, vocab, tokens_per_record)
                if int64:
                    w.write(encode_example({"text": [int(t) for t in tokens]}))
                else:
                    w.write(encode_example(
                        {"text": bytes(tokens.astype(np.uint8).tolist())}))
        paths.append(path)
    return paths


def write_video_tfrecords(directory: str, n_files: int, frames_per_file: int,
                          cfg: Config, seed: int = 0) -> typing.List[str]:
    """Synthetic video shards with JPEG frames + concat/skip flags (+ tokens
    when language_token_per_frame > 0)."""
    import cv2
    rng = np.random.default_rng(seed)
    os.makedirs(directory, exist_ok=True)
    paths = []
    for i in range(n_files):
        path = os.path.join(directory, f"video{i:04d}.tfrecord")
        with RecordWriter(path) as w:
            for j in range(frames_per_file):
                img = rng.integers(0, 256, (cfg.frame_height, cfg.frame_width,
                                            cfg.color_channels), np.uint8)
                ok, enc = cv2.imencode(".jpg", img)
                assert ok
                feats: typing.Dict[str, typing.Any] = {
                    "frame": enc.tobytes(),
                    "concat": [int(j == 0)],
                    "skip_frame": [0],
                }
                if cfg.language_token_per_frame > 0:
                    feats["tokens"] = [int(t) for t in rng.integers(
                        0, cfg.vocab_size, cfg.language_token_per_frame)]
                    feats["mask"] = [int(cfg.language_token_per_frame)]
                w.write(encode_example(feats))
        paths.append(path)
    return paths
