"""Host -> device feeding: numpy batches to globally-sharded jax Arrays.

Replaces the reference's TPU InfeedQueue + per-host dataloader placement
(/root/reference/src/run/dataloader_placement.py:17-231): each host runs its
slice of the pipeline (``slice_index = jax.process_index()``) and
``jax.make_array_from_callback`` assembles the global batch across the mesh —
the data axis sharding means each device fetches only its batch rows, giving
the same host-locality the reference's placement logic hand-computed.
"""
from __future__ import annotations

import functools
import queue as queuelib
import threading
import time
import typing

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding

from ..config import Config
from ..nd import NT
from ..obs import spans
from ..parallel.sharding import spec_for
from ..reliability import faults

# input name -> logical axis names (the input_pipeline_shape of the reference,
# dataclass.py:310-337)
TEXT_AXES = ("batch", "sequence", "language_token_patch")
INPUT_AXES: typing.Dict[str, typing.Tuple[str, ...]] = {
    "token_x": TEXT_AXES,
    "token_y": TEXT_AXES,
    "txt_msk": TEXT_AXES,
    "frame": ("batch", "_sequence", "height", "width", "color_channels"),
    "vid_msk_src": ("batch", "sequence"),
    "vid_msk_tgt": ("batch", "sequence"),
    "cat_mask_x": ("batch", "sequence"),
    "cat_mask_y": ("batch", "sequence"),
}


def axes_for(name: str, arr: np.ndarray, cfg: Config) -> typing.Tuple[str, ...]:
    names = INPUT_AXES[name]
    if name == "frame" and not cfg.three_axes:
        names = ("batch", "_sequence", "height", "color_channels")
    if name in ("token_x", "token_y", "txt_msk") and arr.ndim == 4:
        # joint video+language token layout: the patch-count dim is NAMED
        # "height" so text concatenates with the flattened video along one
        # shared spatial axis (reference dataclass.py:334)
        names = ("batch", "sequence", "height", "language_token_patch")
    return names[:arr.ndim]


@functools.lru_cache(maxsize=8)
def _local_data_coords(mesh: Mesh) -> typing.Tuple[int, ...]:
    """Data-axis coordinates covered by this process's devices (cached per
    mesh — the O(n_devices) grid scan must not run every training step).

    With the data axis outermost in the device order this is the classic
    disjoint rank slicing; when a REPLICATING axis (e.g. pipeline) spans
    processes, several processes cover the SAME coordinate and must load
    the same batch rows."""
    from ..parallel.mesh import DATA_AXIS
    ax = list(mesh.axis_names).index(DATA_AXIS)
    pid = jax.process_index()
    coords = sorted({idx[ax] for idx in np.ndindex(*mesh.devices.shape)
                     if mesh.devices[idx].process_index == pid})
    if coords != list(range(coords[0], coords[0] + len(coords))):
        raise ValueError(
            f"process covers non-contiguous data coords {coords}; the host "
            "batch cannot be one contiguous row range")
    return tuple(coords)


def data_slice_for_process(mesh: Mesh) -> typing.Tuple[int, int]:
    """(slice_index, slice_count) for the per-host dataset reader.

    Equal to (process_index, process_count) for data-major topologies;
    processes sharing data-axis coordinates (pipe axis spanning hosts) get
    the SAME slice index so their readers deliver identical rows — the
    host-locality answer the reference hand-computes in
    dataloader_placement.py:69-92."""
    from ..parallel.mesh import DATA_AXIS
    coords = _local_data_coords(mesh)
    d = int(mesh.shape[DATA_AXIS])
    k = len(coords)
    if coords[0] % k or d % k:
        # a coord block straddling a slice boundary would floor-divide to a
        # WRONG slice index and serve wrong rows inside the span guard
        raise ValueError(
            f"process data coords {coords} do not align with a uniform "
            f"slicing of the {d}-way data axis; choose a topology whose "
            "devices-per-process divides the data axis")
    return coords[0] // k, d // k


def local_row_slice(index: typing.Tuple[slice, ...], local_rows: int,
                    global_rows: int, start_row: int = 0) -> slice:
    """Translate a device's GLOBAL batch-row request into LOCAL row offsets
    relative to this process's span [start_row, start_row + local_rows)."""
    start = index[0].start or 0
    stop = index[0].stop if index[0].stop is not None else global_rows
    local_start = start - start_row
    if local_start < 0 or local_start + (stop - start) > local_rows:
        raise ValueError(
            f"device requests rows [{start},{stop}) outside this process's "
            f"span [{start_row},{start_row + local_rows}) — the data-axis "
            "sharding must align with per-process batches")
    return slice(local_start, local_start + (stop - start))


def to_global(batch: typing.Dict[str, np.ndarray], cfg: Config, mesh: Mesh
              ) -> typing.Dict[str, NT]:
    """Assemble the per-host numpy batch into global NT arrays on the mesh.

    The batch passed in is this host's data slice (see
    ``data_slice_for_process``); the global batch is ``local * slice_count``
    — processes sharing a data coordinate pass identical rows."""
    from ..parallel.mesh import DATA_AXIS
    out: typing.Dict[str, NT] = {}
    _, slice_count = data_slice_for_process(mesh)
    coords = _local_data_coords(mesh)
    data_axis_size = int(mesh.shape.get(DATA_AXIS, 1))
    for name, arr in batch.items():
        names = axes_for(name, arr, cfg)
        sharding = NamedSharding(mesh, spec_for(names, mesh))
        global_shape = (arr.shape[0] * slice_count,) + arr.shape[1:]
        rows_per_coord = global_shape[0] // max(1, data_axis_size)
        start_row = coords[0] * rows_per_coord

        def cb(index, arr=arr, global_rows=global_shape[0], start=start_row):
            rows = local_row_slice(index, arr.shape[0], global_rows, start)
            return arr[(rows,) + tuple(index[1:])]

        x = jax.make_array_from_callback(global_shape, sharding, cb)
        out[name] = NT(x, names)
    return out


class DeviceFeeder:
    """Host->device double buffer for the async step loop (main.py).

    A background thread pulls the NEXT host batch from ``source``, snapshots
    the host pipeline's cursor (``state_fn``), assembles the global device
    batch (``to_global`` — the H2D transfer), and parks it in a bounded
    queue of ``depth`` entries, so batch assembly never sits on the critical
    path between steps.  ``depth=0`` disables the thread and assembles
    inline — the synchronous parity-reference path.

    Checkpoint-cursor semantics: ``state_dict`` always reflects the last
    batch HANDED TO THE CONSUMER, never batches prefetched into the queue —
    each queue entry carries the cursor snapshot taken right after its batch
    left the host pipeline, and the snapshot only becomes ``state_dict``'s
    answer when the consumer receives that batch.  A checkpoint written
    after update N therefore resumes the stream at batch N+1 regardless of
    how far ahead the producer ran.

    Exhaustion and errors propagate to the consumer: the producer parks a
    sentinel, ``__next__`` raises ``StopIteration`` (or the producer's
    exception), and ``close()`` always leaves the thread joined — a full
    queue cannot strand it (puts poll the stop flag)."""

    _DONE = object()

    def __init__(self, source: typing.Iterable, cfg: Config, mesh: Mesh,
                 depth: int = 1,
                 state_fn: typing.Optional[typing.Callable[[], dict]] = None,
                 registry=None):
        self.source = iter(source)
        self.cfg = cfg
        self.mesh = mesh
        self.depth = int(depth)
        self.state_fn = state_fn
        # obs wiring (docs/observability.md): H2D assembly seconds histogram;
        # None (the default) records nothing
        self._h2d_hist = None if registry is None else registry.histogram(
            "hbnlp_feeder_h2d_seconds",
            "host batch assembly + host->device transfer seconds")
        self._state: dict = state_fn() if state_fn is not None else {}
        self._err: typing.List[BaseException] = []
        self._finished = False  # DONE sentinel consumed: every later
        #                         __next__ must re-raise, never re-get()
        # cross-thread flags are Events (atomic set/is_set), not bare bools:
        # _producer_done is written by the producer thread and read by
        # /healthz probes, _closed by the consumer and read by the probes
        self._producer_done = threading.Event()  # normal-tail exit, not a
        #                                          crash
        self._closed = threading.Event()
        self._thread: typing.Optional[threading.Thread] = None
        self._queue: typing.Optional[queuelib.Queue] = None
        self._stop = threading.Event()
        if self.depth > 0:
            self._queue = queuelib.Queue(maxsize=self.depth)
            self._thread = threading.Thread(target=self._produce,
                                            name="device-feeder", daemon=True)
            self._thread.start()

    def _put(self, item) -> bool:
        while not self._stop.is_set():
            try:
                self._queue.put(item, timeout=0.05)
                return True
            except queuelib.Full:
                continue
        return False

    def _produce(self) -> None:
        try:
            while not self._stop.is_set():
                # fault-injection site: "feeder:die@N" kills this producer
                # exactly like a real bug would — the error parks, the
                # consumer re-raises it, and the run exits nonzero for the
                # supervisor to relaunch (docs/reliability.md)
                faults.hit("feeder")
                try:
                    with spans.span("feed/source"):
                        np_batch = next(self.source)
                except StopIteration:
                    break
                snap = self.state_fn() if self.state_fn is not None else None
                gb = self._assemble(np_batch)
                if not self._put((gb, snap)):
                    return
        except BaseException as e:  # surfaced on the consumer side
            self._err.append(e)
        self._put((self._DONE, None))
        self._producer_done.set()

    def _assemble(self, np_batch):
        """``to_global`` (host assembly + H2D transfer) under a span + the
        transfer-seconds histogram."""
        t0 = time.perf_counter()
        with spans.span("feed/assemble"):
            gb = to_global(np_batch, self.cfg, self.mesh)
        if self._h2d_hist is not None:
            self._h2d_hist.observe(time.perf_counter() - t0)
        return gb

    def __iter__(self) -> "DeviceFeeder":
        return self

    def __next__(self) -> typing.Dict[str, NT]:
        if self._queue is None:  # depth 0: inline, synchronous
            np_batch = next(self.source)  # StopIteration propagates
            snap = self.state_fn() if self.state_fn is not None else None
            gb = self._assemble(np_batch)
            if snap is not None:
                self._state = snap
            return gb
        if self._finished:
            # iterator contract: keep raising after exhaustion — the single
            # DONE sentinel was already consumed, so another get() on the
            # empty queue (dead producer) would deadlock the consumer
            if self._err:
                raise self._err[0]
            raise StopIteration
        item, snap = self._queue.get()
        if item is self._DONE:
            self._finished = True
            if self._err:
                raise self._err[0]
            raise StopIteration
        if snap is not None:
            self._state = snap
        return item

    def state_dict(self) -> dict:
        """Cursor of the last CONSUMED batch (see class docstring)."""
        return dict(self._state)

    def qsize(self) -> int:
        """Prefetched device batches currently parked (0 when inline)."""
        return 0 if self._queue is None else self._queue.qsize()

    def alive(self) -> bool:
        """Producer liveness for /healthz: healthy means running OR
        finished for a benign reason.  A producer that exited through its
        normal tail (dataset exhaustion, or an error the consumer will be
        HANDED on its next read) is not a crash — only a thread that died
        without parking its sentinel reads as dead."""
        if self.depth == 0 or self._closed.is_set():
            return True  # inline path / run over: nothing to die separately
        if self._thread is not None and self._thread.is_alive():
            return True
        return self._producer_done.is_set()

    def close(self, timeout: float = 5.0) -> None:
        """Stop the producer and join it; safe to call repeatedly.

        Unlike ``Prefetcher.close`` no consumer-wake sentinel is needed:
        close() is called BY the consumer thread, so nothing can be parked
        on ``get()`` while it runs.  A producer blocked on the SOURCE
        (e.g. the host-prefetch queue) is woken by closing the source
        first — main.py closes the pipe before the feeder."""
        self._closed.set()
        if self._thread is None:
            return
        self._stop.set()
        try:  # unjam a put-blocked producer so it can see the stop flag
            while True:
                self._queue.get_nowait()
        except queuelib.Empty:
            pass
        # the handle is write-once (set in __init__, never cleared): alive()
        # reads it from probe threads, and join() on a finished thread is a
        # no-op, so repeated close() stays safe without nulling it
        self._thread.join(timeout)
