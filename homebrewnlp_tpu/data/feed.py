"""Host -> device feeding: numpy batches to globally-sharded jax Arrays.

Replaces the reference's TPU InfeedQueue + per-host dataloader placement
(/root/reference/src/run/dataloader_placement.py:17-231): each host runs its
slice of the pipeline (``slice_index = jax.process_index()``) and
``jax.make_array_from_callback`` assembles the global batch across the mesh —
the data axis sharding means each device fetches only its batch rows, giving
the same host-locality the reference's placement logic hand-computed.
"""
from __future__ import annotations

import typing

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding

from ..config import Config
from ..nd import NT
from ..parallel.sharding import spec_for

# input name -> logical axis names (the input_pipeline_shape of the reference,
# dataclass.py:310-337)
TEXT_AXES = ("batch", "sequence", "language_token_patch")
INPUT_AXES: typing.Dict[str, typing.Tuple[str, ...]] = {
    "token_x": TEXT_AXES,
    "token_y": TEXT_AXES,
    "txt_msk": TEXT_AXES,
    "frame": ("batch", "_sequence", "height", "width", "color_channels"),
    "vid_msk_src": ("batch", "sequence"),
    "vid_msk_tgt": ("batch", "sequence"),
    "cat_mask_x": ("batch", "sequence"),
    "cat_mask_y": ("batch", "sequence"),
}


def axes_for(name: str, arr: np.ndarray, cfg: Config) -> typing.Tuple[str, ...]:
    names = INPUT_AXES[name]
    if name == "frame" and not cfg.three_axes:
        names = ("batch", "_sequence", "height", "color_channels")
    if name in ("token_x", "token_y", "txt_msk") and arr.ndim == 4:
        # joint video+language token layout: the patch-count dim is NAMED
        # "height" so text concatenates with the flattened video along one
        # shared spatial axis (reference dataclass.py:334)
        names = ("batch", "sequence", "height", "language_token_patch")
    return names[:arr.ndim]


def local_row_slice(index: typing.Tuple[slice, ...], local_rows: int,
                    global_rows: int) -> slice:
    """Translate a device's GLOBAL batch-row request into LOCAL row offsets.

    Each process holds ``local_rows`` consecutive global rows (process p owns
    [p*local_rows, (p+1)*local_rows)); a device request must stay inside its
    process's span — the data-axis sharding guarantees it when the per-process
    batch divides evenly over that process's devices."""
    start = index[0].start or 0
    stop = index[0].stop if index[0].stop is not None else global_rows
    local_start = start % local_rows
    if local_start + (stop - start) > local_rows:
        raise ValueError(
            f"device requests rows [{start},{stop}) crossing a process "
            f"boundary (local batch {local_rows}) — the data-axis sharding "
            "must align with per-process batches")
    return slice(local_start, local_start + (stop - start))


def to_global(batch: typing.Dict[str, np.ndarray], cfg: Config, mesh: Mesh
              ) -> typing.Dict[str, NT]:
    """Assemble the per-host numpy batch into global NT arrays on the mesh.

    The batch passed in is this host's shard (local batch rows); global shape
    is inferred as local * process count."""
    out: typing.Dict[str, NT] = {}
    n_procs = jax.process_count()
    for name, arr in batch.items():
        names = axes_for(name, arr, cfg)
        sharding = NamedSharding(mesh, spec_for(names, mesh))
        global_shape = (arr.shape[0] * n_procs,) + arr.shape[1:]

        def cb(index, arr=arr, global_rows=global_shape[0]):
            rows = local_row_slice(index, arr.shape[0], global_rows)
            return arr[(rows,) + tuple(index[1:])]

        x = jax.make_array_from_callback(global_shape, sharding, cb)
        out[name] = NT(x, names)
    return out
