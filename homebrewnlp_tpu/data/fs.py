"""Pluggable filesystem layer: local paths + remote URLs (gs://, s3://, ...).

The reference trains straight from GCS: dataset globs are ``gs://`` paths
(/root/reference/configs/32big_mixer.json:37), the TFRecord builders upload
shards with bounded retry (scripts/text2tfrecord.py:61-89), and run logs
stream to GCS (scripts/run_manager.py:26-56).  This module is the single
switch point: anything with a ``://`` scheme goes through fsspec (gcsfs
backs ``gs://``); bare paths use the stdlib, so local work never pays the
fsspec import.

Orbax checkpoints take ``gs://`` paths natively (tensorstore), so checkpoint
IO needs no help from here.
"""
from __future__ import annotations

import glob as globlib
import os
import time
import typing


def is_remote(path: str) -> bool:
    return "://" in str(path)


def open_stream(path: str, mode: str = "rb"):
    """Open local files via the stdlib, ``scheme://`` URLs via fsspec.
    Remote reads are block-cached by fsspec, so the TFRecord reader's
    seek-heavy skip path stays efficient."""
    if not is_remote(path):
        return open(path, mode)
    import fsspec
    return fsspec.open(path, mode).open()


def glob(pattern: str) -> typing.List[str]:
    """Glob local patterns or remote URLs; remote results keep their scheme
    prefix so downstream opens route back through fsspec."""
    if not is_remote(pattern):
        return globlib.glob(pattern)
    import fsspec
    _, _, paths = fsspec.get_fs_token_paths(pattern)
    protocol = pattern.split("://", 1)[0]
    return [p if is_remote(p) else f"{protocol}://{p}" for p in paths]


def exists(path: str) -> bool:
    if not is_remote(path):
        return os.path.exists(path)
    import fsspec
    fsys, _, (p,) = fsspec.get_fs_token_paths(path)
    return fsys.exists(p)


def put_with_retry(local_path: str, remote_path: str, retries: int = 5,
                   base_delay: float = 1.0) -> None:
    """Upload a local file with exponential backoff (the reference's GCS
    upload loop, scripts/text2tfrecord.py:61-89).  A plain copy for local
    destinations."""
    if not is_remote(remote_path):
        import shutil
        os.makedirs(os.path.dirname(os.path.abspath(remote_path)), exist_ok=True)
        shutil.copyfile(local_path, remote_path)
        return
    import fsspec
    fsys, _, (dest,) = fsspec.get_fs_token_paths(remote_path)
    last: typing.Optional[BaseException] = None
    for attempt in range(retries):
        try:
            fsys.put_file(local_path, dest)
            return
        except Exception as e:  # noqa: BLE001 - network errors vary by backend
            last = e
            time.sleep(base_delay * 2 ** attempt)
    raise IOError(f"upload {local_path} -> {remote_path} failed "
                  f"after {retries} attempts") from last


def write_with_retry(path: str, data: bytes, retries: int = 5,
                     base_delay: float = 1.0) -> None:
    """Write bytes (small artifacts: logs, manifests) with retry on remote
    targets."""
    if not is_remote(path):
        d = os.path.dirname(os.path.abspath(path))
        if d:
            os.makedirs(d, exist_ok=True)
        with open(path, "wb") as f:
            f.write(data)
        return
    last: typing.Optional[BaseException] = None
    for attempt in range(retries):
        try:
            with open_stream(path, "wb") as f:
                f.write(data)
            return
        except Exception as e:  # noqa: BLE001
            last = e
            time.sleep(base_delay * 2 ** attempt)
    raise IOError(f"write {path} failed after {retries} attempts") from last
