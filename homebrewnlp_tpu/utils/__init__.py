"""Shared utilities: flagship config loading + random batch construction
(used by bench.py, __graft_entry__.py, and the CLI debug modes)."""
from __future__ import annotations

import json
import os
import typing

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def enable_compilation_cache(path: typing.Optional[str] = None):
    """Point XLA's persistent compilation cache at ``path`` so warm restarts
    skip the expensive compiles (~40 s for the d4096 sampler, ~25 s for the
    flagship step on the relay — BASELINE.md).

    Resolution order: explicit ``path`` argument (the ``compilation_cache_dir``
    config knob) > ``HBNLP_COMPILATION_CACHE_DIR`` env var > a per-user
    default.  An empty string at any level disables caching.  Returns the
    directory in use, or None when disabled."""
    import jax
    if path is None:
        path = os.environ.get("HBNLP_COMPILATION_CACHE_DIR",
                              "~/.cache/homebrewnlp_tpu/xla")
    if not path:
        return None
    path = os.path.expanduser(path)
    os.makedirs(path, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", path)
    # a small nonzero floor (vs the default 1 s, which would skip medium
    # programs whose relay round-trip still dominates a warm restart): keeps
    # trivial sub-100ms compiles from accumulating unboundedly in the
    # default-on per-user directory, which has no eviction — growth is
    # bounded by the set of distinct non-trivial programs, and the
    # directory is safe to rm -rf at any time
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.1)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
    return path


def load_config(path: str, **overrides):
    """Config from JSON with keyword overrides applied before derivation."""
    from ..config import Config
    if not os.path.isabs(path) and not os.path.exists(path):
        path = os.path.join(REPO_ROOT, path)
    with open(path) as f:
        raw = json.load(f)
    raw.update(overrides)
    return Config(raw)


def random_text_batch(cfg, seed: int = 0) -> typing.Dict[str, typing.Any]:
    """Uniform-random token batch as NTs (model input shape, reference
    dataclass.py:310-337 text entries)."""
    import jax
    from ..data.feed import TEXT_AXES as names
    from ..nd import NT
    shape = (cfg.train_batch_size * cfg.macro_batching,
             cfg.sequence_length // cfg.token_patch_size,
             cfg.token_patch_size)
    kx, ky = jax.random.split(jax.random.key(seed))
    return {
        "token_x": NT(jax.random.randint(kx, shape, 0, cfg.vocab_size), names),
        "token_y": NT(jax.random.randint(ky, shape, 0, cfg.vocab_size), names),
    }
