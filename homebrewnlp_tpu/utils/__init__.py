"""Shared utilities: flagship config loading + random batch construction
(used by bench.py, __graft_entry__.py, and the CLI debug modes)."""
from __future__ import annotations

import json
import os
import typing

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def load_config(path: str, **overrides):
    """Config from JSON with keyword overrides applied before derivation."""
    from ..config import Config
    if not os.path.isabs(path) and not os.path.exists(path):
        path = os.path.join(REPO_ROOT, path)
    with open(path) as f:
        raw = json.load(f)
    raw.update(overrides)
    return Config(raw)


def random_text_batch(cfg, seed: int = 0) -> typing.Dict[str, typing.Any]:
    """Uniform-random token batch as NTs (model input shape, reference
    dataclass.py:310-337 text entries)."""
    import jax
    from ..data.feed import TEXT_AXES as names
    from ..nd import NT
    shape = (cfg.train_batch_size * cfg.macro_batching,
             cfg.sequence_length // cfg.token_patch_size,
             cfg.token_patch_size)
    kx, ky = jax.random.split(jax.random.key(seed))
    return {
        "token_x": NT(jax.random.randint(kx, shape, 0, cfg.vocab_size), names),
        "token_y": NT(jax.random.randint(ky, shape, 0, cfg.vocab_size), names),
    }
