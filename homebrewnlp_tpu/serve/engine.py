"""Continuous-batching inference engine (docs/observability.md
"Continuous batching").

The reference serves one request at a time behind a single lock (its
Manager-queue bridge, /root/reference/src/rest_api.py), and our port kept
that shape: ``serve/interface.py::InterfaceWrapper`` serializes every
sampler call — the cost the serving-SLO layer's
``serialization_overhead_s`` was built to expose.  This module replaces it
with a real scheduler over the KV-cache sampler (Orca-style continuous /
in-flight batching, Yu et al. 2022; block-allocated KV accounting after
vLLM's PagedAttention, Kwon et al. 2023):

* one persistent DECODE loop over a fixed pool of ``serve_max_batch``
  lanes, each lane a row of the pooled per-layer KV caches
  (``infer/kv_cache.py``'s per-lane-position decode step);
* new requests are admitted BETWEEN decode steps — a finishing request's
  lane is re-prefilled while decode continues on the others;
* two separately compiled executables: ``prefill`` (one full-length
  forward writes a prompt's K/V into its lane) and ``decode`` (one
  incremental row per active lane, per-lane traced sampling knobs —
  one compilation serves every request mix);
* a :class:`~homebrewnlp_tpu.infer.kv_cache.BlockAllocator` prices
  admission in KV-pool blocks (``serve_kv_blocks`` x
  ``serve_block_tokens``): a request's whole footprint is taken up front
  and recycled on completion, a footprint that can NEVER fit is shed
  immediately (503 + Retry-After, like ``serve_queue_limit``);
* AOT executable serialization: both executables are compiled
  ahead-of-time and — when ``serve_aot_cache_dir`` is set — serialized to
  disk keyed by config hash + mesh + toolchain, so a second server start
  deserializes in seconds instead of re-paying the compile+warmup
  (BENCH_r05 measured ~135 s), which is what makes replica autoscaling
  plausible.

``serve_max_batch=1`` (the default) never constructs this engine: the
REST layer keeps the serialized ``InterfaceWrapper`` path byte-identical
to the pre-engine behavior (parity-tested).
"""
from __future__ import annotations

import hashlib
import json
import os
import pickle
import queue
import threading
import time
import typing

import jax
import jax.numpy as jnp
import numpy as np

from ..config import Config
from ..data.feed import TEXT_AXES
from ..infer import kv_cache as kvc
from ..infer.sampler import _fire_first_token, _gumbel_argmax_lanes
from ..reliability import faults
from ..sync import make_condition
from . import slo
from .interface import (QueueDeadlineExceeded, RequestCancelled, _RowStream,
                        effective_truncation, tokenizer_for)

#: bump when the executable calling convention changes (AOT cache keying).
#: Donation does NOT affect this: AOT-persisted executables are exactly the
#: ones compiled WITHOUT donation (serialize_executable cannot round-trip
#: input-output aliasing — see jit_executables), so the serialized calling
#: convention is unchanged and existing caches stay valid.
#: 2: the rng carry became a [n_lanes] key array (per-lane streams seeded
#: by fold_in(request id) — :func:`lane_key`) instead of one shared key.
#: 3: chunked prefill added a third executable (:func:`prefill_chunk_body`,
#: persisted as ``prefill_chunk-<key>.jaxexec`` when
#: ``serve_prefill_chunk_tokens > 0``) — the cache-hit contract now spans
#: all executables the knobs require, so pre-chunk caches must not
#: half-hit.
AOT_FORMAT = 3

#: donated argument positions of the jitted executables (relative to the
#: bound callables :func:`jit_executables` builds).  The pooled KV caches,
#: token pool, per-lane positions and the rng carry are pure step state:
#: without donation they round-trip as ordinary jit args and the device
#: pays a FULL POOL COPY per decode step.  The ``donation`` graph rule
#: audits these against the abstract serving traces (analysis), so a
#: dropped donate_argnums fails graftcheck before it doubles serving HBM.
DECODE_DONATE_ARGNUMS = (1, 2, 3, 10)  # caches, toks, pos, rng
PREFILL_DONATE_ARGNUMS = (1, 2)  # caches, toks
PREFILL_CHUNK_DONATE_ARGNUMS = (1, 2)  # caches, toks
#: human names for the donated positions above, keyed per executable so
#: the donation audit's messages stay in lockstep with the signatures —
#: update these tables together when reordering body arguments
DECODE_DONATE_ARG_NAMES = {1: "pooled KV caches", 2: "token pool",
                           3: "lane positions", 10: "rng carry"}
PREFILL_DONATE_ARG_NAMES = {1: "pooled KV caches", 2: "token pool"}
PREFILL_CHUNK_DONATE_ARG_NAMES = {1: "pooled KV caches", 2: "token pool"}


def lane_key(seed: int, rid: int) -> jax.Array:
    """The decode RNG stream for one admitted request: the run seed folded
    with the request id.  A pure function of ``(seed, rid)`` — never of
    lane index or admission order — so a request's sampled tokens are
    reproducible under ANY interleaving, and lane 0 parity-pins against
    the serialized sampler called with this same key
    (tests/serve_engine_test.py)."""
    return jax.random.fold_in(jax.random.key(seed), rid)


def decode_body(cfg: Config, rows: int, n_lanes: int,
                first_token_cb: typing.Optional[typing.Callable],
                params, caches, toks, pos, active, end_row,
                first_gen, temps, ks, ps, rng, tags):
    """One continuous-batching decode step: every ACTIVE lane decodes the
    row at its own position, samples under its own traced knobs, and
    writes the sampled row at position+1; inactive lanes carry through
    untouched.  Mirrors the serialized cached sampler's body
    (infer/kv_cache.py) with per-lane positions.  Module-level (bound via
    ``functools.partial``) so the static donation audit traces the exact
    function the engine compiles.

    ``rng`` is a [n_lanes] key array — one stream per lane, seeded at
    admission from :func:`lane_key`.  A lane's carry advances only on
    steps it actually decodes, so the stream is a pure function of
    (seed, rid, tokens generated so far): idle steps between admissions
    cannot shift a request's samples."""
    # the same carry/sub discipline as the serialized sampler's body
    # (``key, sub = split(key)``), vmapped over lanes
    pair = jax.vmap(jax.random.split)(rng)
    advanced, subs = pair[:, 0], pair[:, 1]
    row = jnp.take_along_axis(toks, pos[:, None, None], axis=1)
    logits, caches = kvc._decode_logits(cfg, params, row, pos, caches,
                                        rows, TEXT_AXES)
    sampled = _gumbel_argmax_lanes(logits, temps, subs, ks, ps)
    nxt = pos + 1
    write = active & (nxt < end_row) & (nxt < rows)
    tgt = jnp.minimum(nxt, rows - 1)
    cur = jnp.take_along_axis(toks, tgt[:, None, None], axis=1)
    new_row = jnp.where(write[:, None, None],
                        sampled.astype(toks.dtype), cur)
    row_at = (jnp.arange(rows)[None, :] == tgt[:, None])[:, :, None]
    toks = jnp.where(row_at, new_row, toks)
    if first_token_cb is not None:
        # per-lane TTFT: n_lanes is static, so this unrolls to one gated
        # callback per lane — each fires at most once per request (its
        # first generated row), tagged with that lane's request id
        for b in range(n_lanes):
            _fire_first_token(first_token_cb, tags[b],
                              write[b] & (nxt[b] == first_gen[b]),
                              new_row[b])
    pos = jnp.where(active, nxt, pos)
    # advance only the lanes that decoded (typed keys: select on the raw
    # key data, then re-wrap under the same impl)
    data = jax.random.key_data(rng)
    keep = active.reshape((-1,) + (1,) * (data.ndim - 1))
    rng = jax.random.wrap_key_data(
        jnp.where(keep, jax.random.key_data(advanced), data))
    return caches, toks, pos, rng, logits


def prefill_body(cfg: Config, rows: int,
                 params, caches, toks, prompt, lane, prompt_rows):
    """Prefill one request into lane ``lane``: a single full-length
    forward writes every prompt position's K/V at once (batch of 1,
    scalar position 0 — the serialized sampler's prefill), then the lane
    rows of every pooled cache and the token pool are overwritten (both
    donated — the update happens in the pool's own buffers).  An empty
    prompt skips the forward; its lane decodes from scratch."""
    lane0 = {k: tuple(jnp.zeros((1,) + v.shape[1:], v.dtype) for v in kv)
             for k, kv in caches.items()}
    filled = jax.lax.cond(
        prompt_rows > 0,
        lambda c: kvc._decode_logits(cfg, params, prompt, jnp.int32(0),
                                     c, rows, TEXT_AXES)[1],
        lambda c: c, lane0)
    out = {}
    for name, kv in caches.items():
        out[name] = tuple(
            jax.lax.dynamic_update_slice(
                pool, jnp.asarray(one, pool.dtype),
                (lane,) + (0,) * (pool.ndim - 1))
            for pool, one in zip(kv, filled[name]))
    toks = jax.lax.dynamic_update_slice(toks, prompt, (lane, 0, 0))
    return out, toks


def prefill_chunk_rows(cfg: Config) -> int:
    """Decode rows per prefill chunk — ``serve_prefill_chunk_tokens`` in
    rows, clamped to the sequence; 0 = chunking off (the monolithic
    :func:`prefill_body` path, byte-identical graphs)."""
    tokens = int(getattr(cfg, "serve_prefill_chunk_tokens", 0) or 0)
    if tokens <= 0:
        return 0
    rows = cfg.sequence_length // cfg.token_patch_size
    return max(1, min(rows, tokens // cfg.token_patch_size))


def prefill_chunk_body(cfg: Config, rows: int, chunk_rows: int,
                       params, caches, toks, chunk, lane, start_row):
    """Prefill ONE chunk of a request into lane ``lane``: a forward over
    ``chunk_rows`` rows at scalar position ``start_row`` against the
    lane's own cache (the model's cached-attention path is exact for any
    row count at a scalar position — masked positions contribute exact
    0.0 to every full-length reduction, so N chunk forwards are bitwise
    the monolithic prefill), then ONLY the chunk's KV rows and token rows
    are scatter-written back into the (donated) pools at the lane's
    running position.  The scheduler dispatches at most one chunk between
    decode steps and never blocks on the result — prefill device time
    hides under decode device time (docs/observability.md "Streaming and
    inter-token latency")."""
    lane_caches = kvc.lane_view(caches, lane)
    filled = kvc._decode_logits(cfg, params, chunk, start_row,
                                lane_caches, rows, TEXT_AXES)[1]
    caches = kvc.write_lane_rows(caches, filled, lane, start_row, chunk_rows)
    toks = jax.lax.dynamic_update_slice(toks, chunk, (lane, start_row, 0))
    return caches, toks


def jit_executables(cfg: Config, rows: int, n_lanes: int,
                    first_token_cb: typing.Optional[
                        typing.Callable] = None,
                    donate: bool = True):
    """The engine's jitted (not yet compiled) step functions with their
    donation contract applied — shared by :class:`BatchEngine` and the
    ``donation`` graph rule's abstract serving trace.  Returns
    ``(decode, prefill, prefill_chunk)``; the third element is ``None``
    when ``serve_prefill_chunk_tokens`` is 0 (the monolithic path — the
    compiled graph set is byte-identical to the pre-chunking engine).

    ``donate=False`` is the AOT-cache compromise: this toolchain's
    ``serialize_executable`` does not round-trip input-output aliasing
    safely (a deserialized donated executable intermittently corrupts the
    pool — reproduced on CPU as non-repeatable decode outputs), so
    engines persisting to ``serve_aot_cache_dir`` compile WITHOUT
    donation, the same class of tradeoff as their host-side TTFT stamp
    (docs/observability.md "Continuous batching")."""
    import functools
    dec = functools.partial(decode_body, cfg, rows, n_lanes, first_token_cb)
    pre = functools.partial(prefill_body, cfg, rows)
    chunk_rows = prefill_chunk_rows(cfg)
    chk = (functools.partial(prefill_chunk_body, cfg, rows, chunk_rows)
           if chunk_rows else None)
    if not donate:
        return jax.jit(dec), jax.jit(pre), (jax.jit(chk) if chk else None)
    return (jax.jit(dec, donate_argnums=DECODE_DONATE_ARGNUMS),
            jax.jit(pre, donate_argnums=PREFILL_DONATE_ARGNUMS),
            (jax.jit(chk, donate_argnums=PREFILL_CHUNK_DONATE_ARGNUMS)
             if chk else None))


def abstract_exec_args(cfg: Config, params_tree, rows: int, n_lanes: int):
    """Abstract (ShapeDtypeStruct) argument tuples for the decode,
    prefill and (when ``serve_prefill_chunk_tokens > 0``, else ``None``)
    prefill-chunk executables — ``params_tree`` may already be abstract
    (the static analysis path passes the traced param shapes)."""
    s = jax.ShapeDtypeStruct
    tree = jax.tree_util.tree_map(
        lambda a: a if isinstance(a, jax.ShapeDtypeStruct)
        else s(jnp.shape(a), jnp.asarray(a).dtype), params_tree)
    caches = kvc.cache_shapes(cfg, tree, n_lanes, rows)
    lanes = (n_lanes,)
    common = (tree, caches, s((n_lanes, rows, cfg.token_patch_size),
                              jnp.int32))
    rng = jax.eval_shape(lambda: jax.random.split(jax.random.key(0),
                                                  n_lanes))
    decode = common + (s(lanes, jnp.int32), s(lanes, jnp.bool_),
                       s(lanes, jnp.int32), s(lanes, jnp.int32),
                       s(lanes, jnp.float32), s(lanes, jnp.int32),
                       s(lanes, jnp.float32), rng, s(lanes, jnp.int32))
    prefill = common + (s((1, rows, cfg.token_patch_size), jnp.int32),
                        s((), jnp.int32), s((), jnp.int32))
    chunk_rows = prefill_chunk_rows(cfg)
    chunk = (common + (s((1, chunk_rows, cfg.token_patch_size), jnp.int32),
                       s((), jnp.int32), s((), jnp.int32))
             if chunk_rows else None)
    return decode, prefill, chunk


def use_batch_engine(cfg: Config) -> bool:
    """Whether serving should run the continuous-batching scheduler:
    opted in (``serve_max_batch > 1``) and the config's whole layer stack
    decodes against a KV cache (``infer/kv_cache.py::cache_eligible``)."""
    return int(getattr(cfg, "serve_max_batch", 1)) > 1 and kvc.cache_eligible(cfg)


def aot_cache_key(cfg: Config, params: dict, n_lanes: int) -> str:
    """Executable identity for the AOT cache: full derived config hash
    (train/metrics.py::config_hash) + parameter tree structure + mesh
    (device platform/kind/count) + toolchain versions + the engine's
    calling-convention format.  Any drift produces a different key, so a
    stale cache entry is simply never read — invalidation is by keying,
    never by mutation."""
    from ..train.metrics import config_hash
    leaves = [f"{k}:{tuple(v.shape)}:{jnp.asarray(v).dtype}"
              for k, v in sorted(params.items())]
    dev = jax.devices()[0]
    try:
        import jaxlib.version
        jaxlib_v = jaxlib.version.__version__
    except Exception:  # noqa: BLE001 - toolchain without the module
        jaxlib_v = ""
    doc = json.dumps({
        "config": config_hash(cfg),
        "params": hashlib.sha256("|".join(leaves).encode()).hexdigest()[:16],
        "lanes": int(n_lanes),
        "mesh": [dev.platform, getattr(dev, "device_kind", ""),
                 jax.device_count()],
        "jax": jax.__version__,
        "jaxlib": jaxlib_v,
        "format": AOT_FORMAT,
    }, sort_keys=True, default=str)
    return hashlib.sha256(doc.encode()).hexdigest()[:24]


def _aot_save(path: str, compiled) -> bool:
    """Best-effort serialize of a ``jax.stages.Compiled`` (atomic rename so
    a torn write is never read back as a cache hit)."""
    try:
        from jax.experimental import serialize_executable as se
        payload, in_tree, out_tree = se.serialize(compiled)
        blob = pickle.dumps((payload, in_tree, out_tree))
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "wb") as f:
            f.write(blob)
        os.replace(tmp, path)
        return True
    except Exception:  # noqa: BLE001 - AOT persistence is an optimization
        return False


def _aot_load(path: str):
    """Deserialize a cached executable; None on any failure (the caller
    falls back to a fresh compile — a corrupt cache entry costs nothing
    but the compile it failed to save)."""
    try:
        from jax.experimental import serialize_executable as se
        with open(path, "rb") as f:
            payload, in_tree, out_tree = pickle.loads(f.read())
        return se.deserialize_and_load(payload, in_tree, out_tree)
    except Exception:  # noqa: BLE001
        return None


class _BatchRequest:
    """One admitted-or-queued completion: prompt/knobs, the 1-slot result
    queue, the ambient SLO record snapshotted at submit, the optional
    streaming ``sink`` (token chunks + ``None`` sentinel, delivered while
    the lane decodes), and the cancellation event the queue-deadline
    protocol honors while the request is still QUEUED (an admitted request
    always finishes)."""

    __slots__ = ("rid", "prompt", "temperature", "max_tokens", "top_k",
                 "top_p", "rec", "out", "t_enq", "cancelled", "admitted",
                 "end", "end_row", "first_gen", "prompt_rows", "tag",
                 "sink", "rstream", "t_admitted",
                 # KV usage accounting: blocks the allocator granted and
                 # the wall instant it granted them — every free site
                 # integrates blocks x held-wall onto the SLO record
                 "n_blocks", "t_alloc",
                 # chunked-prefill state machine: the padded [1, rows,
                 # patch] token layout chunks are sliced from, the next
                 # chunk's start row, and the rows chunks must cover
                 # before the lane arms for decode
                 "padded", "next_chunk_row", "prefill_rows")

    def __init__(self, rid: int, prompt, temperature, max_tokens,
                 top_k, top_p, rec, sink=None):
        self.rid = rid
        self.prompt = list(prompt)
        self.temperature = temperature
        self.max_tokens = max_tokens
        self.top_k = top_k
        self.top_p = top_p
        self.rec = rec
        self.out: "queue.Queue[tuple]" = queue.Queue(1)
        self.t_enq = time.monotonic()
        self.cancelled = threading.Event()
        self.admitted = threading.Event()
        self.sink = sink
        self.rstream: typing.Optional[_RowStream] = None
        self.t_admitted: typing.Optional[float] = None
        self.n_blocks = 0
        self.t_alloc: typing.Optional[float] = None
        self.padded: typing.Optional[np.ndarray] = None
        self.next_chunk_row = 0
        self.prefill_rows = 0


class BatchEngine:
    """The scheduler: owns the pooled device state (per-layer KV caches
    ``[serve_max_batch, seq_rows, ...]``, the token pool, per-lane
    positions), the AOT executables (decode, prefill, and — when
    ``serve_prefill_chunk_tokens > 0`` — prefill-chunk), and one worker
    thread running admit -> prefill-chunk -> decode-step -> complete
    forever.

    ``first_token_callback`` is the serving TTFT hook (host
    ``(tag, token)``): the decode step fires it per lane at that lane's
    first generated row, carrying the request id its SLO record supplied —
    the traced-tag design (serve/slo.py) already supports many in-flight
    requests on one compilation."""

    def __init__(self, cfg: Config, params: dict,
                 first_token_callback: typing.Optional[
                     typing.Callable] = None):
        if not kvc.cache_eligible(cfg):
            raise ValueError(
                "continuous batching needs a KV-cache-eligible config "
                "(every sequence mixer an attention layer); this one keeps "
                "the serialized rebuild path")
        from ..models import pipeline_params_stacked, unstack_pipeline_params
        if pipeline_params_stacked(cfg, params):
            params = unstack_pipeline_params(cfg, params)
        self.cfg = cfg
        self.params = params
        self.tokenizer = tokenizer_for(cfg)
        self._first_token_cb = first_token_callback
        # TTFT source: the in-graph tagged callback serves the default
        # path, but a host callback is a PyCapsule the AOT pickler cannot
        # serialize — with ``serve_aot_cache_dir`` set the decode
        # executable is built callback-free and TTFT is stamped HOST-side
        # at the step boundary instead (the loop syncs every step, so the
        # stamp is one decode step coarse; docs/observability.md
        # "Continuous batching")
        self._graph_ttft = (first_token_callback is not None
                            and not getattr(cfg, "serve_aot_cache_dir", ""))
        self.patch = cfg.token_patch_size
        self.rows = cfg.sequence_length // self.patch
        self.n_lanes = int(cfg.serve_max_batch)
        self._chunk_rows = prefill_chunk_rows(cfg)
        self.allocator = kvc.BlockAllocator(
            kvc.pool_blocks(cfg), kvc.block_rows(cfg) * self.patch)
        # cold-start accounting (bench.py serving row: cold_start_s =
        # compile_s OR aot_reload_s + warmup)
        self.compile_s: typing.Optional[float] = None
        self.aot_reload_s: typing.Optional[float] = None
        self.aot_cache_hit: typing.Optional[bool] = None
        self._build_executables()
        # device state (pooled): lanes hold stale data between occupants by
        # design — decode rewrites each row before any query can see it
        # causally, so recycling never needs a zeroing pass (pinned by the
        # slot-reuse parity test)
        self._caches = kvc.init_caches(cfg, params, self.n_lanes, self.rows)
        self._toks = jnp.zeros((self.n_lanes, self.rows, self.patch),
                               jnp.int32)
        self._pos = jnp.zeros((self.n_lanes,), jnp.int32)
        # per-lane RNG carries; every admission overwrites its lane with
        # lane_key(seed, rid), so these initial streams never sample
        self._rngs = jax.random.split(jax.random.key(cfg.data_seed),
                                      self.n_lanes)
        # host mirrors (the scheduler thread is the only writer)
        self._pos_h = np.zeros(self.n_lanes, np.int32)
        self._end_row = np.zeros(self.n_lanes, np.int32)
        self._first_gen = np.zeros(self.n_lanes, np.int32)
        self._temps = np.zeros(self.n_lanes, np.float32)
        self._ks = np.zeros(self.n_lanes, np.int32)
        self._ps = np.ones(self.n_lanes, np.float32)
        self._tags = np.zeros(self.n_lanes, np.int32)
        self._logits = None  # last decode step's logits (tests/debug)
        self._lane_req: typing.List[typing.Optional[_BatchRequest]] = (
            [None] * self.n_lanes)
        # lanes mid-chunked-prefill, in admission order: the head lane
        # receives at most ONE chunk per loop iteration (between decode
        # steps), then arms for decode once its chunks cover the prompt
        self._prefill_fifo: typing.List[int] = []
        # scheduler plumbing
        self._cv = make_condition("serve.engine.BatchEngine._cv")
        self._queue: typing.List[_BatchRequest] = []
        self._pending = 0  # submitted, not yet admitted (queue_depth)
        self._closed = False
        self._batch_observer: typing.Optional[typing.Callable] = None
        self._step_observer: typing.Optional[typing.Callable] = None
        # decode-loop watchdog feed (slo.EngineHealth): the loop stamps
        # iteration start/end so /healthz can report a wedged scheduler
        self._health = None
        # serving trace (docs/observability.md "Streaming and inter-token
        # latency"): decode-loop phase spans on the scheduler thread's
        # track plus one virtual track per lane (prefilling/occupied with
        # request ids — idle shows as gaps), exported Chrome-trace JSON at
        # close(), alongside the training trace's format
        self.tracer = None
        self._trace_path = str(getattr(cfg, "serve_trace_path", "") or "")
        # flight_buffer_spans caps the ring AND arms rotation: when the
        # ring fills, the full segment rolls to <path>.NNN.json instead of
        # silently evicting — a crash loses at most one ring of spans
        self._trace_cap = int(getattr(cfg, "flight_buffer_spans", 0) or 0)
        self._trace_seq = 0
        self.trace_segments: typing.List[str] = []
        if self._trace_path:
            from ..obs.spans import SpanTracer
            self.tracer = (SpanTracer(max_events=self._trace_cap)
                           if self._trace_cap else SpanTracer())
        self._rid = 0
        self._pad_rng = np.random.default_rng(cfg.data_seed)
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="batch-engine")
        self._thread.start()

    # -- executables ---------------------------------------------------------
    def _build_executables(self) -> None:
        """AOT-compile (or AOT-deserialize) the prefill + decode (and,
        when chunking is on, prefill-chunk) executables — all with the
        pooled state DONATED
        (``DECODE_DONATE_ARGNUMS``/``PREFILL_DONATE_ARGNUMS``/
        ``PREFILL_CHUNK_DONATE_ARGNUMS``): the caches, token pool,
        positions and rng are step-carried state, and without
        input-output aliasing every decode step pays a full pool copy on
        device.  The cache key covers config + params structure + mesh +
        toolchain (``aot_cache_key``); a hit requires EVERY executable
        the knobs call for; a miss compiles and then best-effort
        persists all of them."""
        cfg = self.cfg
        decode_abs, prefill_abs, chunk_abs = abstract_exec_args(
            cfg, self.params, self.rows, self.n_lanes)
        cache_dir = getattr(cfg, "serve_aot_cache_dir", "")
        dec_path = pre_path = chk_path = None
        self._prefill_chunk = None
        if cache_dir:
            key = aot_cache_key(cfg, self.params, self.n_lanes)
            os.makedirs(cache_dir, exist_ok=True)
            dec_path = os.path.join(cache_dir, f"decode-{key}.jaxexec")
            pre_path = os.path.join(cache_dir, f"prefill-{key}.jaxexec")
            if chunk_abs is not None:
                chk_path = os.path.join(cache_dir,
                                        f"prefill_chunk-{key}.jaxexec")
            t0 = time.perf_counter()
            dec = _aot_load(dec_path)
            pre = _aot_load(pre_path) if dec is not None else None
            chk = (_aot_load(chk_path)
                   if chk_path is not None and pre is not None else None)
            if (dec is not None and pre is not None
                    and (chk_path is None or chk is not None)):
                self._decode, self._prefill = dec, pre
                self._prefill_chunk = chk
                self.aot_reload_s = time.perf_counter() - t0
                self.aot_cache_hit = True
                return
            self.aot_cache_hit = False
        dec_jit, pre_jit, chk_jit = jit_executables(
            cfg, self.rows, self.n_lanes,
            self._first_token_cb if self._graph_ttft else None,
            donate=not cache_dir)
        t0 = time.perf_counter()
        self._decode = dec_jit.lower(*decode_abs).compile()
        self._prefill = pre_jit.lower(*prefill_abs).compile()
        if chk_jit is not None:
            self._prefill_chunk = chk_jit.lower(*chunk_abs).compile()
        self.compile_s = time.perf_counter() - t0
        if dec_path is not None:
            _aot_save(dec_path, self._decode)
            _aot_save(pre_path, self._prefill)
            if chk_path is not None:
                _aot_save(chk_path, self._prefill_chunk)

    # -- submission (any thread) ---------------------------------------------
    def queue_depth(self) -> int:
        with self._cv:
            return self._pending

    def kv_blocks_free(self) -> int:
        return self.allocator.free_blocks

    def active_lanes(self) -> int:
        # _cv wraps an RLock, so the scheduler loop's locked wait
        # predicate re-enters here safely
        with self._cv:
            return sum(1 for r in self._lane_req if r is not None)

    def set_batch_observer(self, fn: typing.Optional[typing.Callable]
                           ) -> None:
        """Per-decode-step occupancy sink (``ServeSLO.observe_batch``):
        called with the number of active lanes after each step."""
        with self._cv:
            self._batch_observer = fn

    def set_step_observer(self, fn: typing.Optional[typing.Callable]
                          ) -> None:
        """Per-iteration phase sink (``ServeSLO.observe_step``): called
        with ``(wall_s, phases, n_active, prefill_stall_s, stepped)`` after
        every scheduler-loop iteration that did work.  The phase dict's
        values are contiguous host segments of the iteration, so they sum
        to ``wall_s`` (docs/observability.md "Streaming and inter-token
        latency")."""
        with self._cv:
            self._step_observer = fn

    def set_health(self, health) -> None:
        """Attach the decode-loop liveness probe (``slo.EngineHealth``):
        the scheduler stamps each iteration that has work, so a wedged
        dispatch flips ``/healthz`` to stalled while an idle loop stays
        healthy."""
        with self._cv:
            self._health = health

    def submit(self, prompt: typing.Sequence[int], temperature: float,
               max_tokens: typing.Optional[int],
               top_k: typing.Optional[int],
               top_p: typing.Optional[float],
               token_sink: typing.Optional["queue.Queue"] = None
               ) -> _BatchRequest:
        """Queue a completion; sheds immediately (503 semantics) when the
        backlog exceeds ``serve_queue_limit`` or the request's whole KV
        footprint can never fit the pool.  ``token_sink`` (streaming):
        completion-token chunks are pushed in generation order while the
        lane decodes, then a ``None`` sentinel — always delivered, success
        or failure."""
        cfg = self.cfg
        prompt = list(prompt)[:self.rows * self.patch]
        depth = self.queue_depth()
        limit = int(getattr(cfg, "serve_queue_limit", 0))
        if limit and depth >= limit:
            raise QueueDeadlineExceeded(
                0.0, float(getattr(cfg, "serve_queue_deadline_s", 0.0)),
                depth, shed=True)
        end = (self.rows * self.patch if max_tokens is None
               else min(self.rows * self.patch, len(prompt) + max_tokens))
        if not self.allocator.fits(end):
            raise QueueDeadlineExceeded(
                0.0, float(getattr(cfg, "serve_queue_deadline_s", 0.0)),
                depth, shed=True)
        rec = slo.current()
        if rec is not None:
            rec.mark_enqueued(queue_depth=depth)
        k, p = effective_truncation(cfg, top_k, top_p)
        with self._cv:
            if self._closed:
                raise RuntimeError("engine is closed")
            self._rid += 1
            req = _BatchRequest(self._rid, prompt, float(temperature),
                                max_tokens, int(k), float(p), rec,
                                sink=token_sink)
            req.end = end
            self._queue.append(req)
            self._pending += 1
            self._cv.notify_all()
        return req

    def complete_tokens(self, prompt: typing.Sequence[int],
                        temperature: typing.Optional[float] = None,
                        max_tokens: typing.Optional[int] = None,
                        top_k: typing.Optional[int] = None,
                        top_p: typing.Optional[float] = None,
                        token_sink: typing.Optional[
                            "queue.Queue"] = None) -> np.ndarray:
        """Blocking convenience with the CompletionEngine signature."""
        cfg = self.cfg
        req = self.submit(prompt,
                          cfg.sampling_temperature if temperature is None
                          else temperature, max_tokens, top_k, top_p,
                          token_sink=token_sink)
        return self.fetch(req)

    def fetch(self, req: _BatchRequest,
              deadline_s: typing.Optional[float] = None) -> np.ndarray:
        """Block for ``req``'s result; a still-QUEUED request past the
        deadline is cancelled and raises :class:`QueueDeadlineExceeded`
        (an admitted one always finishes — its lane is already decoding)."""
        deadline = (float(getattr(self.cfg, "serve_queue_deadline_s", 0.0))
                    if deadline_s is None else deadline_s)
        poll = max(0.01, float(self.cfg.default_sleep_duration))
        while True:
            try:
                status, value = req.out.get(timeout=poll)
                break
            except queue.Empty:
                waited = time.monotonic() - req.t_enq
                if (deadline and waited > deadline
                        and not req.admitted.is_set()):
                    req.cancelled.set()
                    raise QueueDeadlineExceeded(waited, deadline,
                                                self.queue_depth())
        if status == "err":
            raise value
        return value

    def close(self) -> None:
        with self._cv:
            self._closed = True
            self._cv.notify_all()
        self._thread.join(timeout=30.0)
        self.export_trace()

    def export_trace(self) -> typing.Optional[str]:
        """Write the serving Chrome trace (``serve_trace_path``): decode
        phase spans + per-lane occupancy tracks; None when tracing is
        off.  Safe to call repeatedly (close() calls it; a test may call
        earlier for a mid-flight snapshot)."""
        if self.tracer is None or not self._trace_path:
            return None
        try:
            return self.tracer.export(self._trace_path)
        except OSError:
            return None

    # -- scheduler thread ----------------------------------------------------
    def _pad_prompt(self, req: _BatchRequest) -> np.ndarray:
        """Prompt laid out row-major over the lane's full context, padded
        with random tokens the decode loop overwrites (the serialized
        engine's padding contract; only an empty prompt's row 0 ever
        influences sampling, as its seed row)."""
        flat = self._pad_rng.integers(
            0, self.cfg.vocab_size, size=self.rows * self.patch,
            dtype=np.int64).astype(np.int32)
        flat[:len(req.prompt)] = np.asarray(req.prompt, np.int32)
        return flat.reshape(1, self.rows, self.patch)

    def _admit(self, prefill_segs: typing.List[tuple],
               stall: typing.List[float]) -> None:
        """Fill free lanes from the queue between decode steps: allocate
        the KV-block footprint, then either prefill the lane and arm the
        mirrors (monolithic) or enqueue it on the chunked-prefill FIFO
        (``serve_prefill_chunk_tokens > 0`` — chunks dispatch one per loop
        iteration, :meth:`_advance_prefill`).  Stops at the first request
        the pool cannot hold RIGHT NOW (FIFO — a small request never
        starves a big one already at the head).

        ``prefill_segs`` collects each prefill dispatch's
        ``(t0, t1, lane, rid, xid)`` host segment; ``stall[0]`` accumulates
        stalled-lane-seconds — the monolithic path's BLOCKING prefill wall
        times the lanes that held active requests while the scheduler
        thread was pinned (docs/observability.md).  The chunked path never
        blocks, so it never stalls."""
        while True:
            with self._cv:
                # snapshot the cancel flags ONCE: a deadline-cancel landing
                # between two separate is_set() sweeps would put a request
                # in BOTH lists — kept queued yet counted as dropped, and
                # decremented again on the next prune (queue_depth
                # underflow)
                flags = [(r, r.cancelled.is_set()) for r in self._queue]
                live = [r for r, c in flags if not c]
                dropped = [r for r, c in flags if c]
                if dropped:
                    self._queue[:] = live
                    self._pending -= len(dropped)
            for r in dropped:
                if r.sink is not None:  # cancelled before admission: the
                    r.sink.put(None)    # stream ends with just the sentinel
                try:  # unblock a fetcher that didn't initiate the cancel
                    r.out.put_nowait(("err", RequestCancelled(r.rid)))
                except queue.Full:
                    pass  # deadline-cancel already consumed its slot
            with self._cv:
                if not self._queue:
                    return
                try:
                    lane = self._lane_req.index(None)
                except ValueError:
                    return
                req = self._queue[0]
                blocks = self.allocator.alloc(req.rid, req.end)
                if blocks is None:
                    return
                req.n_blocks = len(blocks)
                req.t_alloc = time.perf_counter()
                self._queue.pop(0)
                self._pending -= 1
            self._start_request(req, lane, prefill_segs, stall)

    def _start_request(self, req: _BatchRequest, lane: int,
                       prefill_segs: typing.List[tuple],
                       stall: typing.List[float]) -> None:
        rec = req.rec
        req.admitted.set()
        prompt_rows = len(req.prompt) // self.patch
        req.prompt_rows = prompt_rows
        req.end_row = (self.rows if req.max_tokens is None
                       else min(self.rows,
                                -(-(len(req.prompt) + req.max_tokens)
                                  // self.patch)))
        req.first_gen = max(prompt_rows, 1)
        req.tag = rec.rid if rec is not None and self._graph_ttft else 0
        if rec is not None:
            rec.mark_started()
            rec.tokens_generated = max(0, req.end - len(req.prompt))
        if req.tag:
            slo.register_first_token(req.tag, rec.mark_first_token)
        padded = self._pad_prompt(req)
        req.padded = padded
        if req.sink is not None:
            # streaming: chunks concatenate to exactly the completion; the
            # host-built padded layout covers positions decode never
            # rewrites (the seed row of an empty prompt)
            req.rstream = _RowStream(req.sink, len(req.prompt), req.end,
                                     self.patch, req.first_gen,
                                     initial_tokens=padded.reshape(-1),
                                     rec=rec)
        if self._chunk_rows:
            # chunked prefill: the lane is occupied (holds the request and
            # its blocks) but NOT armed for decode (_end_row stays 0, so
            # the decode mask skips it) until _advance_prefill has covered
            # the prompt.  Coverage is max(prompt_rows, 1): decode starts
            # at row prompt_rows - 1 and writes every later row itself,
            # and an empty prompt's seed row still needs its token written
            # (monolithic prefill writes the whole padded layout)
            req.prefill_rows = max(prompt_rows, 1)
            req.next_chunk_row = 0
            self._lane_req[lane] = req
            self._prefill_fifo.append(lane)
            return
        # monolithic (serve_prefill_chunk_tokens=0): timed INCLUDING the
        # device wall (block_until_ready) — the scheduler thread would pay
        # it at the next step's sync anyway, and attributing it here is the
        # whole point.  This wall, times the lanes concurrently holding
        # active requests, is hbnlp_serve_prefill_stall_seconds
        # (stalled-lane-seconds: an idle-engine admission stalls nobody)
        n_stalled = self.active_lanes()
        t_p0 = time.perf_counter()
        try:
            self._caches, self._toks = self._prefill(
                self.params, self._caches, self._toks, padded,
                np.int32(lane), np.int32(prompt_rows))
            jax.block_until_ready(self._toks)
        except Exception as e:  # noqa: BLE001 - fail THIS request, keep serving
            self._fail_admission(req, e)
            return
        t_p1 = time.perf_counter()
        prefill_segs.append((t_p0, t_p1, lane, req.rid,
                             rec.xid if rec is not None else ""))
        stall[0] += (t_p1 - t_p0) * n_stalled
        self._lane_req[lane] = req
        self._arm_lane(req, lane)

    def _settle_kv(self, req: _BatchRequest) -> None:
        """Integrate KV/lane occupancy onto the SLO record at the instant
        the blocks go back to the pool — every free site calls this first,
        so block-seconds is exactly blocks x (free wall - alloc wall) no
        matter which exit path (finish, prefill failure, cancel, pool
        loss) released them."""
        rec = req.rec
        if rec is None:
            return
        now = time.perf_counter()
        rec.kv_blocks = req.n_blocks
        if req.t_alloc is not None:
            rec.kv_block_seconds = req.n_blocks * (now - req.t_alloc)
        t0 = req.t_admitted if req.t_admitted is not None else req.t_alloc
        if t0 is not None:
            rec.lane_seconds = now - t0

    def _fail_admission(self, req: _BatchRequest, e: BaseException) -> None:
        """Fail ONE request whose prefill (monolithic or a chunk) raised,
        keep serving: the request is already admitted (deadline-cancel
        disabled) and holds blocks — an unhandled prefill error would leak
        both and leave its fetch() blocking forever.  Re-raises when the
        failed dispatch consumed the donated pool (the other lanes' state
        is gone too), escalating to the loop's fail-everything path, which
        reinitializes the pool."""
        self._settle_kv(req)
        self.allocator.free(req.rid)
        if req.tag:
            slo.unregister_first_token(req.tag)
        if req.rec is not None:
            req.rec.mark_engine_done()
        if req.rstream is not None:
            req.rstream.close()
        req.out.put(("err", e))
        if self._pool_deleted():
            raise e

    def _advance_prefill(self, prefill_segs: typing.List[tuple]) -> None:
        """Dispatch AT MOST ONE prefill chunk — the head-of-FIFO lane's
        next ``_chunk_rows`` rows — per scheduler iteration, WITHOUT
        blocking (overlapped dispatch): the chunk executable donates the
        pools, so the next decode step consumes its output by data
        dependence and the host never waits on prefill device time; a
        lane's readiness is synced implicitly at the first step that reads
        its state.  A long prompt therefore admits over N iterations while
        every armed lane keeps decoding.  The last chunk arms the lane.

        The final chunk's start row is clamped so the executable stays
        static-shaped: re-writing already-covered rows recomputes
        bit-identical values (same tokens against the same cache prefix),
        so a ragged last chunk costs overlap, never correctness."""
        lane = self._prefill_fifo[0]
        req = self._lane_req[lane]
        start = max(0, min(req.next_chunk_row, self.rows - self._chunk_rows))
        t_c0 = time.perf_counter()
        try:
            chunk = jnp.asarray(
                req.padded[:, start:start + self._chunk_rows, :])
            self._caches, self._toks = self._prefill_chunk(
                self.params, self._caches, self._toks, chunk,
                np.int32(lane), np.int32(start))
        except Exception as e:  # noqa: BLE001 - fail THIS request, keep serving
            # partially-admitted: release the lane and its whole block
            # footprint before failing the request
            self._prefill_fifo.pop(0)
            self._lane_req[lane] = None
            self._fail_admission(req, e)
            return
        t_c1 = time.perf_counter()
        prefill_segs.append((t_c0, t_c1, lane, req.rid,
                             req.rec.xid if req.rec is not None else ""))
        req.next_chunk_row += self._chunk_rows
        if req.next_chunk_row >= req.prefill_rows:
            self._prefill_fifo.pop(0)
            req.padded = None  # the chunks are on device; drop the host copy
            self._arm_lane(req, lane)

    def _arm_lane(self, req: _BatchRequest, lane: int) -> None:
        """Arm a prefilled lane for decode: host mirrors, the per-request
        RNG stream, the device position vector.  Completes the request
        immediately when there is nothing to generate (full prompt / zero
        budget) — the lane never joins the decode loop."""
        req.t_admitted = time.perf_counter()
        self._pos_h[lane] = max(req.prompt_rows - 1, 0)
        self._end_row[lane] = req.end_row
        self._first_gen[lane] = req.first_gen
        self._temps[lane] = req.temperature
        self._ks[lane] = req.top_k
        self._ps[lane] = req.top_p
        self._tags[lane] = req.tag
        # arm the lane's RNG stream: fold_in(seed, rid) — independent of
        # lane placement and admission order (typed keys have no .at, so
        # splice on the raw key data)
        data = jax.random.key_data(self._rngs)
        self._rngs = jax.random.wrap_key_data(data.at[lane].set(
            jax.random.key_data(lane_key(self.cfg.data_seed, req.rid))))
        self._pos = jnp.asarray(self._pos_h)
        if self._pos_h[lane] >= req.end_row - 1:
            self._finish_lane(lane)

    def _step(self, segs: typing.List[tuple], t_start: float) -> int:
        """One decode step over every active lane, then completion checks,
        attributed into contiguous host segments appended to ``segs``:

        - **dispatch** — building the active mask + the async decode call;
        - **sync** — blocking on the returned positions (the loop's pacing
          D2H; the device's decode wall lands here);
        - **sample** — materializing sampled rows on host: streamed lanes'
          new rows, finished lanes' outputs;
        - **emit** — observer callbacks, TTFT/ITL stamps, sink pushes,
          lane completion bookkeeping.

        Returns the number of lanes that shared the step."""
        prev_pos = self._pos_h.copy()
        active = (np.array([r is not None for r in self._lane_req])
                  & (self._pos_h < self._end_row - 1))
        self._caches, self._toks, self._pos, self._rngs, self._logits = (
            self._decode(self.params, self._caches, self._toks, self._pos,
                         active, self._end_row, self._first_gen, self._temps,
                         self._ks, self._ps, self._rngs, self._tags))
        t_dispatch = time.perf_counter()
        segs.append(("dispatch", t_start, t_dispatch))
        # blocks until the step lands (the loop's pacing sync); copy — the
        # zero-copy view over the device buffer is read-only, and admission
        # writes lanes into this mirror
        self._pos_h = np.array(self._pos, np.int32)
        t_sync = time.perf_counter()
        segs.append(("sync", t_dispatch, t_sync))
        n_active = int(active.sum())
        # sample pass: pull every token this step made visible — streamed
        # lanes' new rows, finished lanes' full outputs — so the emit pass
        # below never blocks on the device
        emissions: typing.List[tuple] = []
        finished: typing.List[tuple] = []
        for lane, req in enumerate(self._lane_req):
            if req is None or not active[lane]:
                continue
            new_pos = int(self._pos_h[lane])
            written = (new_pos > int(prev_pos[lane])
                       and new_pos < int(self._end_row[lane])
                       and new_pos < self.rows)
            if written:
                row = (np.asarray(self._toks[lane, new_pos]).reshape(-1)
                       if req.rstream is not None else None)
                emissions.append((lane, req, new_pos, row))
            if new_pos >= int(self._end_row[lane]) - 1:
                finished.append(
                    (lane,
                     np.asarray(self._toks[lane]).reshape(-1)[:req.end]))
        t_sample = time.perf_counter()
        segs.append(("sample", t_sync, t_sample))
        with self._cv:
            obs = self._batch_observer
        if obs is not None:
            try:
                obs(n_active)
            except Exception:  # noqa: BLE001 - metrics must not kill serving
                pass
        for lane, req, new_pos, row in emissions:
            if (not self._graph_ttft and req.rec is not None
                    and new_pos == int(self._first_gen[lane])):
                # host-side TTFT (AOT-cached executables carry no host
                # callback): the lane's first generated row landed in the
                # step that just synced — mark_first_token keeps the
                # first stamp, so a repeated hit is a no-op
                req.rec.mark_first_token()
            if req.rstream is not None:
                req.rstream.on_row(new_pos, row)  # stamps mark_token
            elif req.rec is not None:
                # no sink: stamp the emission instant anyway — ITL is the
                # engine's token cadence, what a streaming client of this
                # request WOULD have seen
                req.rec.mark_token()
        for lane, out in finished:
            self._finish_lane(lane, out=out)
        segs.append(("emit", t_sample, time.perf_counter()))
        return n_active

    def _finish_lane(self, lane: int,
                     out: typing.Optional[np.ndarray] = None) -> None:
        req = self._lane_req[lane]
        if out is None:
            out = np.asarray(self._toks[lane]).reshape(-1)[:req.end]
        rec = req.rec
        if req.tag:
            try:  # flush the in-flight TTFT callback before unrouting
                jax.effects_barrier()
            except Exception:  # noqa: BLE001 - older toolchains
                pass
            slo.unregister_first_token(req.tag)
        # settle + engine-done BEFORE publishing (the stream close below or
        # the out-queue put): the waiting handler's finish() runs the
        # instant fetch() wakes (serve/interface.py contract) and its usage
        # finalize must see the KV block-seconds already on the record
        self._settle_kv(req)
        if rec is not None:
            rec.mark_engine_done()
        if req.rstream is not None:
            req.rstream.flush_final(out)
            req.rstream.close()
        if self.tracer is not None and req.t_admitted is not None:
            args = {"rid": req.rid}
            if rec is not None:
                args["request"] = rec.rid
                if rec.xid:
                    args["xid"] = rec.xid
            self.tracer.add("occupied", req.t_admitted, time.perf_counter(),
                            track=f"lane{lane}", **args)
        self._lane_req[lane] = None
        self._end_row[lane] = 0
        self._tags[lane] = 0
        self.allocator.free(req.rid)
        req.out.put(("ok", out))

    def _decode_armed(self) -> bool:
        """Whether any lane is armed for decode.  Lanes mid-chunked-prefill
        occupy a lane (``active_lanes`` counts them, keeping the loop
        awake) but keep ``_end_row`` at 0 until :meth:`_arm_lane`, so a
        decode step never runs for prefill-only iterations."""
        return any(r is not None and self._end_row[lane] > 0
                   for lane, r in enumerate(self._lane_req))

    def _loop(self) -> None:
        while True:
            with self._cv:
                while (not self._queue and self.active_lanes() == 0
                       and not self._closed):
                    self._cv.wait(timeout=0.5)
                if self._closed and self.active_lanes() == 0 and not self._queue:
                    return
            with self._cv:
                health = self._health
            if health is not None:
                health.iteration_started()
            t0 = time.perf_counter()
            segs: typing.List[tuple] = []  # contiguous (name, t0, t1)
            prefill_segs: typing.List[tuple] = []
            stall = [0.0]
            stepped = False
            n_active = 0
            try:
                self._chaos_serve_step()
                self._reap_cancelled()
                self._admit(prefill_segs, stall)
                if self._prefill_fifo:
                    self._advance_prefill(prefill_segs)
                t_admit = time.perf_counter()
                segs.append(("admit", t0, t_admit))
                if self._decode_armed():
                    n_active = self._step(segs, t_admit)
                    stepped = True
            except Exception as e:  # noqa: BLE001 - fail every in-flight req
                self._fail_all(e)
                if health is not None:
                    health.iteration_completed(time.perf_counter() - t0)
                continue
            self._report_iteration(t0, segs, prefill_segs, stall[0],
                                   n_active, stepped)
            if health is not None:
                health.iteration_completed(time.perf_counter() - t0)

    def _chaos_serve_step(self) -> None:
        """Poll the ``serve_step`` fault site once per iteration that has
        work (reliability/faults.py; take-only — the actions need loop
        context): ``stall`` wedges THIS iteration past the watchdog bound
        (``HBNLP_SERVE_STALL_S`` overrides the 2 s default — drills hold
        the stall long enough for a router poll to observe it), ``fail``
        raises into the loop's fail-everything path."""
        for action in faults.take("serve_step"):
            if action == "stall":
                time.sleep(float(os.environ.get("HBNLP_SERVE_STALL_S",
                                                "2.0")))
            elif action == "fail":
                raise faults.FaultInjectedIOError(
                    "injected serve_step failure (chaos)")

    def _reap_cancelled(self) -> None:
        """Free lanes whose client walked away (SSE disconnect → the REST
        handler set ``req.cancelled``): release the lane and its KV blocks
        for queued work instead of decoding an abandoned stream to
        completion.  Mid-chunked-prefill lanes leave the FIFO too.  The
        result queue gets :class:`RequestCancelled` so any thread still
        blocked in ``fetch()`` unblocks."""
        reaped: typing.List[tuple] = []
        for lane, req in enumerate(self._lane_req):
            if req is None or not req.cancelled.is_set():
                continue
            if lane in self._prefill_fifo:
                self._prefill_fifo.remove(lane)
            generated = max(0, int(self._pos_h[lane])
                            - max(req.prompt_rows - 1, 0))
            self._lane_req[lane] = None
            self._end_row[lane] = 0
            if req.tag:
                slo.unregister_first_token(req.tag)
                self._tags[lane] = 0
            self._settle_kv(req)
            self.allocator.free(req.rid)
            reaped.append((lane, req, generated))
        for lane, req, generated in reaped:
            if req.rstream is not None:
                req.rstream.close()
            elif req.sink is not None:
                req.sink.put(None)
            if req.rec is not None:
                # the ACTUAL generation, not the plan: a disconnect stops
                # the lane mid-decode, and metering bills what was decoded
                plan = max(0, req.end - len(req.prompt))
                req.rec.tokens_generated = min(plan,
                                               generated * self.patch)
                req.rec.mark_engine_done()
            if self.tracer is not None and req.t_admitted is not None:
                self.tracer.add("occupied", req.t_admitted,
                                time.perf_counter(), track=f"lane{lane}",
                                rid=req.rid, cancelled=True)
            try:
                req.out.put_nowait(("err",
                                    RequestCancelled(req.rid, generated)))
            except queue.Full:
                pass

    def _report_iteration(self, t0: float, segs: typing.List[tuple],
                          prefill_segs: typing.List[tuple],
                          stall_s: float, n_active: int,
                          stepped: bool) -> None:
        """Close the books on one scheduler iteration: derive the phase
        decomposition (contiguous segments, prefill carved out of admit —
        the sum equals the iteration wall by construction), feed the step
        observer, and record the spans/lane tracks on the serving trace."""
        t_end = segs[-1][2] if segs else t0
        wall = t_end - t0
        if wall <= 0 or not segs:
            return
        prefill_s = sum(t1 - t0_ for t0_, t1, *_ in prefill_segs)
        phases = {name: 0.0 for name in slo.STEP_PHASES}
        for name, s0, s1 in segs:
            phases[name] = phases.get(name, 0.0) + (s1 - s0)
        phases["admit"] = max(0.0, phases["admit"] - prefill_s)
        phases["prefill"] = prefill_s
        with self._cv:
            observer = self._step_observer
        if observer is not None:
            try:
                observer(wall, phases, n_active, stall_s, stepped)
            except Exception:  # noqa: BLE001 - metrics must not kill serving
                pass
        tracer = self.tracer
        if tracer is not None:
            tracer.add("engine/step", t0, t_end, active=n_active)
            for name, s0, s1 in segs:
                tracer.add(f"engine/{name}", s0, s1)
            for s0, s1, lane, rid, xid in prefill_segs:
                args = {"rid": rid}
                if xid:
                    args["xid"] = xid
                tracer.add("engine/prefill", s0, s1, **args)
                tracer.add("prefilling", s0, s1, track=f"lane{lane}",
                           **args)
            if (self._trace_path and self._trace_cap
                    and tracer.event_count() >= self._trace_cap):
                self._rotate_trace()

    def _rotate_trace(self) -> None:
        """Roll the filled span ring out to the next ``<path>.NNN.json``
        segment and clear it: the capped serving trace persists in rolling
        segments instead of silently evicting its oldest spans, so a crash
        loses at most one ring (docs/observability.md "Request tracing").
        ``close()``'s final :meth:`export_trace` still writes the base
        path with whatever the last partial ring holds."""
        base, ext = os.path.splitext(self._trace_path)
        self._trace_seq += 1
        path = f"{base}.{self._trace_seq:03d}{ext or '.json'}"
        try:
            self.trace_segments.append(self.tracer.rotate(path))
        except OSError:
            pass  # tracing is evidence, not a gate

    def _pool_deleted(self) -> bool:
        """Whether a donated call consumed the pooled device state without
        returning replacements (an exception after dispatch)."""
        try:
            leaves = jax.tree_util.tree_leaves(self._caches)
            leaves += [self._toks, self._pos]
            return any(getattr(x, "is_deleted", lambda: False)()
                       for x in leaves)
        except Exception:  # noqa: BLE001 - conservative: assume dead
            return True

    def _reset_pool(self) -> None:
        """Fresh zeroed pool state (caches/toks/pos/rng) after a failure
        consumed the donated buffers — every lane was already failed, so
        losing their K/V is the correct outcome, not a data loss."""
        cfg = self.cfg
        self._caches = kvc.init_caches(cfg, self.params, self.n_lanes,
                                       self.rows)
        self._toks = jnp.zeros((self.n_lanes, self.rows, self.patch),
                               jnp.int32)
        self._pos = jnp.zeros((self.n_lanes,), jnp.int32)
        self._rngs = jax.random.split(jax.random.key(cfg.data_seed),
                                      self.n_lanes)
        self._pos_h = np.zeros(self.n_lanes, np.int32)

    def _fail_all(self, e: BaseException) -> None:
        self._prefill_fifo.clear()
        for lane, req in enumerate(self._lane_req):
            if req is not None:
                self._lane_req[lane] = None
                self._end_row[lane] = 0
                self._settle_kv(req)
                self.allocator.free(req.rid)
                if req.tag:
                    slo.unregister_first_token(req.tag)
                if req.rstream is not None:
                    req.rstream.close()
                if req.rec is not None:
                    # stamp engine-done even on failure: an unstamped
                    # record silently drops its engine/decode observations
                    # (serve/interface.py contract) — exactly during the
                    # failures the histograms should show
                    req.rec.mark_engine_done()
                req.out.put(("err", e))
        with self._cv:
            pending, self._queue = self._queue, []
            self._pending = 0
        for req in pending:
            if req.sink is not None:
                req.sink.put(None)
            req.out.put(("err", e))
        if self._pool_deleted():
            self._reset_pool()


class BatchInterface:
    """``InterfaceWrapper``-shaped facade over :class:`BatchEngine` so the
    REST layer (and bench/tests) swap engines by config: ``complete(...,
    asynchronous=True)`` returns a ``fetch`` callable, ``queue_depth`` /
    ``kv_blocks_free`` feed the SLO gauges, ``close`` drains the
    scheduler.  There are no worker threads to serialize behind — the
    queue here is the ADMISSION queue, drained between decode steps."""

    def __init__(self, engine: BatchEngine):
        self.engine = engine

    def queue_depth(self) -> int:
        return self.engine.queue_depth()

    def kv_blocks_free(self) -> int:
        return self.engine.kv_blocks_free()

    def set_batch_observer(self, fn) -> None:
        self.engine.set_batch_observer(fn)

    def set_step_observer(self, fn) -> None:
        self.engine.set_step_observer(fn)

    def set_health(self, health) -> None:
        self.engine.set_health(health)

    def lane_count(self) -> int:
        """Concurrent drain width (serve_max_batch) — Retry-After pricing
        divides the backlog by it (``ServeSLO.set_lane_count``)."""
        return self.engine.n_lanes

    def active_lanes(self) -> int:
        return self.engine.active_lanes()

    def complete(self, prompt: typing.Sequence[int], temperature: float = 0.0,
                 response_len: int = 64, asynchronous: bool = False,
                 top_k: typing.Optional[int] = None,
                 top_p: typing.Optional[float] = None,
                 token_sink: typing.Optional["queue.Queue"] = None):
        req = self.engine.submit(prompt, temperature, response_len,
                                 top_k, top_p, token_sink=token_sink)

        def fetch():
            return self.engine.fetch(req)

        # client-abandonment hook (SSE disconnect): the scheduler's reap
        # pass frees the lane + KV blocks at the next iteration
        fetch.cancel = req.cancelled.set
        return fetch if asynchronous else fetch()

    def close(self) -> None:
        self.engine.close()
