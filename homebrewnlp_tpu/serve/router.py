"""Health-aware replica router: one HTTP front over N engine replicas.

The serving scale-out story (ROADMAP "heavy traffic"; docs/reliability.md
"Serving resilience"): clients talk to ONE port; behind it the router
load-balances completion requests over a replica set, health-gated by each
replica's ``/healthz`` (the obs exporter's slo + alerts blocks):

- **shed** — a replica reporting ``stalled`` (decode-loop watchdog) or
  ``draining`` (SIGTERM grace drain), or whose health poll times out or
  errors, receives no new requests until a poll succeeds again; a replica
  with firing SLO alerts or a FULL KV pool is *degraded* — used only when
  no fully-healthy peer remains.
- **failover** — replica death observed by the router (connection refused,
  a 5xx answer, or the connection dropping before the FIRST response body
  byte) transparently retries the request on a healthy peer, preserving
  the client's ``X-Request-Id`` so the merged trace shows the failed and
  the retried attempt under a single id.  Once the first body byte has
  been relayed the stream is committed: the router NEVER retries past
  that point (at-most-once delivery past the first SSE token — a re-run
  could resample a divergent completion and the client has already seen
  the prefix).
- **drain** — SIGTERM starts the graceful exit: stop admitting (new
  completions answer 503), finish relaying in-flight streams bounded by
  ``grace_deadline_s``, then stop.

Stdlib-only, in the ``tools/supervise.py`` house style: loadable by file
path (graftserve) with import fallbacks for the sync shim and the metrics
registry.  Router metrics (docs/observability.md):
``hbnlp_router_requests_total{replica,outcome}``,
``hbnlp_router_failovers_total``, ``hbnlp_router_replicas_healthy``.
"""
from __future__ import annotations

import argparse
import collections
import http.client
import json
import logging
import signal
import threading
import time
import typing
import urllib.error
import urllib.parse
import urllib.request
import uuid
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

try:
    from ..sync import make_lock
except ImportError:  # loaded by file path (tools/graftserve.py _load_light)
    import sys as _sys
    _sync = (_sys.modules.get("homebrewnlp_tpu.sync")
             or _sys.modules.get("hbnlp_sync"))
    if _sync is not None:
        make_lock = _sync.make_lock
    else:
        def make_lock(name):
            return threading.Lock()

try:
    from ..obs.registry import REGISTRY, MetricsRegistry
except ImportError:  # standalone: load the registry next to this file
    import importlib.util as _ilu
    import os as _os
    import sys as _sys
    _reg = (_sys.modules.get("homebrewnlp_tpu.obs.registry")
            or _sys.modules.get("hbnlp_obs_registry"))
    if _reg is None:
        _spec = _ilu.spec_from_file_location(
            "hbnlp_obs_registry",
            _os.path.join(_os.path.dirname(_os.path.abspath(__file__)),
                          _os.pardir, "obs", "registry.py"))
        _reg = _ilu.module_from_spec(_spec)
        _spec.loader.exec_module(_reg)
        _sys.modules["hbnlp_obs_registry"] = _reg
    REGISTRY, MetricsRegistry = _reg.REGISTRY, _reg.MetricsRegistry

try:
    from ..obs import usage as usage_mod
except ImportError:  # standalone: load the usage meter next to this file
    import importlib.util as _ilu
    import os as _os
    import sys as _sys
    usage_mod = (_sys.modules.get("homebrewnlp_tpu.obs.usage")
                 or _sys.modules.get("hbnlp_obs_usage"))
    if usage_mod is None:
        _spec = _ilu.spec_from_file_location(
            "hbnlp_obs_usage",
            _os.path.join(_os.path.dirname(_os.path.abspath(__file__)),
                          _os.pardir, "obs", "usage.py"))
        usage_mod = _ilu.module_from_spec(_spec)
        _spec.loader.exec_module(usage_mod)
        _sys.modules["hbnlp_obs_usage"] = usage_mod

LOG = logging.getLogger("homebrewnlp_tpu.serve.router")

#: response-body relay unit; read1 returns whatever the socket has, so SSE
#: events relay at token cadence, never buffered up to this size
CHUNK = 8192

#: request paths eligible for proxying + failover (the engine's POST
#: surface); anything else 404s at the router
PROXY_POSTS = ("encode", "decode", "check_tokens", "token_completion",
               "completion", "debugz/dump")
#: paths the drain latch refuses (stop ADMITTING means stop accepting new
#: completions; cheap tokenizer calls keep working for in-flight clients)
ADMIT_PATHS = ("token_completion", "completion")


def router_metrics(registry=None):
    reg = registry if registry is not None else REGISTRY
    return (
        reg.counter("hbnlp_router_requests_total",
                    "proxied request attempts by replica and outcome",
                    labelnames=("replica", "outcome")),
        reg.counter("hbnlp_router_failovers_total",
                    "requests transparently retried on another replica"),
        reg.gauge("hbnlp_router_replicas_healthy",
                  "replicas currently eligible for new requests"),
    )


class Replica:
    """One backend: the serving URL requests proxy to and the obs URL
    whose ``/healthz`` gates routing (separate ports on one process)."""

    def __init__(self, url: str, obs_url: str = "", name: str = ""):
        self.url = url.rstrip("/")
        self.obs_url = (obs_url or url).rstrip("/")
        parsed = urllib.parse.urlsplit(self.url)
        self.host = parsed.hostname or "127.0.0.1"
        self.port = parsed.port or 80
        self.name = name or f"{self.host}:{self.port}"

    def __repr__(self):
        return f"Replica({self.name})"


class ReplicaState:
    """Router-side view of one replica.  All mutable fields are guarded by
    the owning Router's ``_lock`` (graftsync-declared)."""

    def __init__(self, replica: Replica):
        self.replica = replica
        self.healthy = False       # eligible for new requests
        self.degraded = False      # reachable but kv-full / alerts firing
        self.reason = "unpolled"
        self.inflight = 0
        self.last_poll_s = 0.0
        self.snapshot: typing.Optional[dict] = None


def classify_health(status: int, snap: typing.Optional[dict]
                    ) -> typing.Tuple[str, str]:
    """Map one health poll to a routing tier.

    Returns ``(tier, reason)`` with tier one of ``ok`` (route here),
    ``degraded`` (route only when no ok peer remains: the replica answers
    but its KV pool is exhausted or an SLO alert is firing), or ``down``
    (shed entirely: stalled, draining, or unparseable).  Pure function —
    the unit tests drive it straight from canned snapshots."""
    if snap is None or not isinstance(snap, dict):
        return "down", f"unparseable healthz (HTTP {status})"
    hstat = str(snap.get("status", ""))
    if hstat == "stalled" or status == 503:
        return "down", "stalled"
    if hstat == "draining":
        return "down", "draining"
    if status != 200:
        return "down", f"healthz HTTP {status}"
    alerts = snap.get("alerts") or {}
    firing = alerts.get("firing") or []
    if firing:
        return "degraded", "alerts firing: " + ",".join(
            str(f) for f in firing)[:120]
    slo = snap.get("slo") or {}
    kv_free = slo.get("kv_blocks_free")
    if kv_free is not None and int(kv_free) <= 0:
        return "degraded", "kv pool exhausted"
    return "ok", "ok"


class Router:
    """Routing brain + health watcher, independent of the HTTP front (the
    unit tests drive :meth:`pick` / :meth:`observe_poll` directly)."""

    def __init__(self, replicas: typing.Sequence[Replica],
                 health_interval_s: float = 1.0,
                 health_timeout_s: float = 2.0,
                 failover_retries: int = 1,
                 registry: typing.Optional[MetricsRegistry] = None):
        self.replicas = [ReplicaState(r) for r in replicas]
        self.health_interval_s = float(health_interval_s)
        self.health_timeout_s = float(health_timeout_s)
        self.failover_retries = int(failover_retries)
        self._lock = make_lock("serve.router.Router._lock")
        self._rr = 0  # round-robin tie-break cursor
        self.draining = False
        self._stop = threading.Event()
        self._threads: typing.List[threading.Thread] = []
        self.registry = registry if registry is not None else REGISTRY
        (self.m_requests, self.m_failovers,
         self.m_healthy) = router_metrics(registry)
        self.m_healthy.set(0.0)
        #: router-side attempt log, merged into GET /debugz/trace so a
        #: failed attempt survives even when its replica died with its
        #: span ring (bounded ring; drops oldest)
        self._attempts: "collections.deque[dict]" = collections.deque(
            maxlen=4096)

    # -- health watching -----------------------------------------------------
    def start_health_watch(self) -> None:
        for i, state in enumerate(self.replicas):
            t = threading.Thread(target=self._watch, args=(state,),
                                 daemon=True, name=f"router-health-{i}")
            t.start()
            self._threads.append(t)

    def stop(self) -> None:
        self._stop.set()
        for t in self._threads:
            t.join(timeout=self.health_timeout_s + 1.0)

    def _watch(self, state: ReplicaState) -> None:
        # poll immediately, then on the interval: a replica set is usable
        # the moment its healthz answers, not one interval later
        while True:
            self.poll_replica(state)
            if self._stop.wait(self.health_interval_s):
                return

    def poll_replica(self, state: ReplicaState) -> None:
        """One health poll: GET the replica's ``/healthz`` bounded by
        ``health_timeout_s`` (a WEDGED healthz — `replica:wedge_healthz`
        chaos — only ever fails by this timeout) and apply the tiering."""
        url = state.replica.obs_url + "/healthz"
        status, snap = 0, None
        try:
            with urllib.request.urlopen(
                    url, timeout=self.health_timeout_s) as resp:
                status = resp.status
                snap = json.loads(resp.read().decode() or "{}")
        except urllib.error.HTTPError as e:
            status = e.code
            try:
                snap = json.loads(e.read().decode() or "{}")
            except (ValueError, OSError):
                snap = None
        except Exception as e:  # noqa: BLE001 - conn refused/timeout/reset
            self.observe_poll(state, "down", f"{type(e).__name__}: {e}"[:120],
                              None)
            return
        tier, reason = classify_health(status, snap)
        self.observe_poll(state, tier, reason, snap)

    def observe_poll(self, state: ReplicaState, tier: str, reason: str,
                     snap: typing.Optional[dict]) -> None:
        with self._lock:
            was = (state.healthy, state.degraded)
            state.healthy = tier == "ok"
            state.degraded = tier == "degraded"
            state.reason = reason
            state.snapshot = snap
            state.last_poll_s = time.monotonic()
            healthy_n = sum(1 for s in self.replicas if s.healthy)
        self.m_healthy.set(float(healthy_n))
        if was != (state.healthy, state.degraded):
            LOG.info("replica %s -> %s (%s)", state.replica.name, tier,
                     reason)

    def mark_down(self, state: ReplicaState, reason: str) -> None:
        """Request-path demotion: an attempt just failed on this replica,
        so stop routing to it NOW — the next successful poll restores it."""
        self.observe_poll(state, "down", reason, None)

    # -- selection -----------------------------------------------------------
    def pick(self, tried: typing.Collection[ReplicaState] = ()
             ) -> typing.Optional[ReplicaState]:
        """Least-inflight healthy replica not in ``tried`` (round-robin
        tie-break); degraded replicas only when no healthy one remains.
        Increments the pick's inflight count — pair with :meth:`release`."""
        with self._lock:
            for pool in (
                    [s for s in self.replicas
                     if s.healthy and s not in tried],
                    [s for s in self.replicas
                     if s.degraded and s not in tried]):
                if not pool:
                    continue
                low = min(s.inflight for s in pool)
                candidates = [s for s in pool if s.inflight == low]
                choice = candidates[self._rr % len(candidates)]
                self._rr += 1
                choice.inflight += 1
                return choice
            return None

    def release(self, state: ReplicaState) -> None:
        with self._lock:
            state.inflight = max(0, state.inflight - 1)

    # -- bookkeeping ---------------------------------------------------------
    def note_attempt(self, replica_name: str, outcome: str, xid: str,
                     path: str, t0: float, attempt: int) -> None:
        self.m_requests.labels(replica=replica_name, outcome=outcome).inc()
        now = time.perf_counter()
        self._attempts.append({
            "name": f"router/{outcome}", "ph": "X", "pid": 0,
            "tid": threading.get_ident() % 10_000,
            "ts": t0 * 1e6, "dur": max(0.0, (now - t0) * 1e6),
            "args": {"xid": xid, "replica": replica_name, "path": path,
                     "attempt": attempt, "outcome": outcome}})

    def status(self) -> dict:
        with self._lock:
            doc = {
                "status": "draining" if self.draining else "ok",
                "healthy": sum(1 for s in self.replicas if s.healthy),
                "replicas": {
                    s.replica.name: {
                        "url": s.replica.url,
                        "healthy": s.healthy,
                        "degraded": s.degraded,
                        "reason": s.reason,
                        "inflight": s.inflight,
                    } for s in self.replicas}}
            usage_blocks = [
                s.snapshot.get("usage") for s in self.replicas
                if isinstance(s.snapshot, dict)
                and isinstance(s.snapshot.get("usage"), dict)]
        if usage_blocks:
            # federated per-tenant accounting: counters sum exactly across
            # replicas, then re-fold to the widest replica's top-K so the
            # fleet view obeys the same cardinality bound as any one replica
            try:
                top_k = max(int(b.get("top_k") or 0)
                            for b in usage_blocks) or 32
                merged = usage_mod.merge_usage(usage_blocks, top_k=top_k)
            except Exception:  # noqa: BLE001 - status must not 500 on this
                merged = None
            if merged is not None:
                doc["usage"] = merged
        return doc

    def merged_trace(self, timeout_s: float = 5.0) -> dict:
        """Fetch every live replica's ``/debugz/trace`` and merge under
        one timeline: replica i's events get pid ``i + 1``; the router's
        own attempt log is pid 0 — so a failed attempt and its failover
        retry appear under one ``xid`` even when the failed replica took
        its span ring down with it."""
        events: typing.List[dict] = [
            {"name": "process_name", "ph": "M", "pid": 0, "tid": 0,
             "args": {"name": "router"}}]
        with self._lock:
            events.extend(dict(e) for e in self._attempts)
            states = list(self.replicas)
        for i, state in enumerate(states):
            pid = i + 1
            events.append({"name": "process_name", "ph": "M", "pid": pid,
                           "tid": 0,
                           "args": {"name": state.replica.name}})
            try:
                with urllib.request.urlopen(
                        state.replica.url + "/debugz/trace",
                        timeout=timeout_s) as resp:
                    doc = json.loads(resp.read().decode() or "{}")
            except Exception:  # noqa: BLE001 - dead replica: keep merging
                continue
            for ev in doc.get("traceEvents", []):
                ev = dict(ev)
                ev["pid"] = pid
                events.append(ev)
        return {"traceEvents": events}


class _RouterServer(ThreadingHTTPServer):
    daemon_threads = True
    router: Router = None

    def __init__(self, *args, **kw):
        super().__init__(*args, **kw)
        #: proxied requests currently being relayed (drain gates on zero)
        self._inflight = 0
        self._inflight_lock = make_lock(
            "serve.router._RouterServer._inflight_lock")

    def track(self, delta: int) -> None:
        with self._inflight_lock:
            self._inflight += delta

    def inflight(self) -> int:
        with self._inflight_lock:
            return self._inflight

    def drain(self, grace_deadline_s: float = 30.0) -> bool:
        """Graceful drain (docs/reliability.md): stop admitting — new
        completions answer 503 and ``/healthz`` flips to draining — then
        wait for in-flight relays bounded by ``grace_deadline_s``, stop
        the health watchers, and stop serving.  True iff every in-flight
        stream finished inside the window."""
        self.router.draining = True
        deadline = time.monotonic() + max(0.0, float(grace_deadline_s))
        clean = True
        while self.inflight() > 0:
            if time.monotonic() >= deadline:
                clean = False
                break
            time.sleep(0.05)
        self.router.stop()
        self.shutdown()
        return clean


def _filtered_headers(headers, drop=("host", "connection", "keep-alive",
                                     "transfer-encoding",
                                     "content-length")) -> dict:
    return {k: v for k, v in headers.items() if k.lower() not in drop}


def serve_router(router: Router, host: str = "127.0.0.1", port: int = 0,
                 background: bool = False) -> _RouterServer:
    """Start the HTTP front: POSTs proxy with health-gated failover; GET
    ``/metrics`` renders the router registry, ``/healthz`` the replica
    table, ``/debugz/trace`` the merged timeline."""
    registry_ref = router.registry

    class Handler(BaseHTTPRequestHandler):

        # -- GET surfaces ----------------------------------------------------
        def do_GET(self):
            path = self.path.split("?", 1)[0].strip("/")
            if path == "metrics":
                reg = registry_ref if registry_ref is not None else REGISTRY
                body = reg.render().encode()
                self._reply(200, body,
                            "text/plain; version=0.0.4; charset=utf-8")
            elif path == "healthz":
                doc = router.status()
                code = 200 if doc["healthy"] > 0 else 503
                self._reply(code, json.dumps(doc).encode(),
                            "application/json")
            elif path == "debugz/trace":
                self._reply(200, json.dumps(router.merged_trace()).encode(),
                            "application/json")
            else:
                self.send_error(404)

        def _reply(self, status: int, body: bytes, ctype: str,
                   extra: typing.Optional[dict] = None) -> None:
            self.send_response(status)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            for k, v in (extra or {}).items():
                self.send_header(k, v)
            self.end_headers()
            self.wfile.write(body)

        # -- proxy -----------------------------------------------------------
        def do_POST(self):
            path = self.path.split("?", 1)[0].strip("/")
            if path not in PROXY_POSTS:
                self.send_error(404)
                return
            if router.draining and path in ADMIT_PATHS:
                self._reply(503, json.dumps(
                    {"error": "draining: router is shutting down",
                     "retry_after_s": 1.0}).encode(),
                    "application/json", {"Retry-After": "1"})
                return
            length = int(self.headers.get("Content-Length", 0))
            body = self.rfile.read(length) if length else b""
            xid = (self.headers.get("X-Request-Id") or "").strip()
            fwd = _filtered_headers(self.headers)
            if not xid:
                # mint here so EVERY attempt — including a failed one the
                # replica logged before dying — shares one correlation id
                xid = uuid.uuid4().hex[:16]
            fwd["X-Request-Id"] = xid
            self.server.track(+1)
            try:
                self._proxy(path, body, fwd, xid)
            finally:
                self.server.track(-1)

        def _proxy(self, path: str, body: bytes, fwd: dict,
                   xid: str) -> None:
            tried: typing.List[ReplicaState] = []
            attempts = 1 + max(0, router.failover_retries)
            for attempt in range(attempts):
                state = router.pick(tried)
                if state is None:
                    break
                tried.append(state)
                name = state.replica.name
                t0 = time.perf_counter()
                committed = False
                try:
                    committed, retryable, reason = self._relay(
                        state, path, body, fwd,
                        last=(attempt == attempts - 1))
                except Exception as e:  # noqa: BLE001 - relay internals
                    retryable = not committed
                    reason = f"{type(e).__name__}: {e}"[:160]
                if reason is None:
                    router.note_attempt(name, "ok", xid, path, t0,
                                        attempt)
                    router.release(state)
                    return
                router.release(state)
                if committed:
                    # at-most-once past the first relayed byte: the client
                    # saw a prefix; a retry could resample a DIFFERENT
                    # completion under the same id.  Truncate instead.
                    router.note_attempt(name, "truncated", xid, path, t0,
                                        attempt)
                    LOG.warning("replica %s died mid-stream (%s) xid=%s: "
                                "committed, not retrying", name, reason,
                                xid)
                    try:
                        self.wfile.flush()
                    except OSError:
                        pass
                    self.close_connection = True
                    return
                router.mark_down(state, f"request failed: {reason}")
                if not retryable or attempt == attempts - 1:
                    router.note_attempt(name, "error", xid, path, t0,
                                        attempt)
                    self._reply(502, json.dumps(
                        {"error": f"replica {name} failed: {reason}",
                         "xid": xid}).encode(), "application/json",
                        {"X-Request-Id": xid})
                    return
                router.note_attempt(name, "failover", xid, path, t0,
                                    attempt)
                router.m_failovers.inc()
                LOG.info("failover xid=%s path=/%s: %s failed pre-byte "
                         "(%s), retrying", xid, path, name, reason)
            self._reply(503, json.dumps(
                {"error": "no healthy replica", "xid": xid,
                 "retry_after_s": router.health_interval_s}).encode(),
                "application/json",
                {"Retry-After": "1", "X-Request-Id": xid})

        def _relay(self, state: ReplicaState, path: str, body: bytes,
                   fwd: dict, last: bool):
            """One proxied attempt.  Returns ``(committed, retryable,
            reason)`` — ``reason None`` means success.  Nothing reaches
            the client socket until the backend's status line, headers,
            AND first body chunk are in hand: every pre-commit failure
            (refused, 5xx, EOF before the first SSE token) stays
            failover-eligible."""
            rep = state.replica
            conn = http.client.HTTPConnection(rep.host, rep.port,
                                              timeout=None)
            try:
                headers = dict(fwd)
                headers["Content-Length"] = str(len(body))
                headers["Connection"] = "close"
                try:
                    conn.request("POST", "/" + path, body=body,
                                 headers=headers)
                    resp = conn.getresponse()
                except OSError as e:
                    return False, True, f"connect/send: {e}"[:160]
                except http.client.HTTPException as e:
                    return False, True, f"bad response: {e}"[:160]
                if resp.status >= 500 and not last:
                    # a shed/draining 503 or crashed-handler 500 lands
                    # BEFORE any body byte: route around it (the last
                    # attempt relays it so the client sees the real error)
                    return False, True, f"HTTP {resp.status}"
                ctype = resp.getheader("Content-Type", "")
                is_sse = "text/event-stream" in ctype
                try:
                    first = resp.read1(CHUNK)
                except (OSError, http.client.HTTPException) as e:
                    return False, True, f"pre-byte EOF: {e}"[:160]
                if is_sse and first == b"":
                    # the replica primes the first token BEFORE sending
                    # 200, so an empty SSE body means it died in between
                    return False, True, "pre-byte EOF (empty SSE)"
                # ---- commit: from here on, at-most-once (a retry could
                # resample a DIFFERENT completion under the same id, and
                # the client may already hold a prefix) ----
                clen = resp.getheader("Content-Length")
                try:
                    self.send_response(resp.status)
                    hop = ("connection", "keep-alive", "transfer-encoding",
                           "content-length")
                    for k, v in resp.getheaders():
                        if k.lower() not in hop:
                            self.send_header(k, v)
                    if clen is not None:
                        self.send_header("Content-Length", clen)
                    self.send_header("X-Replica", rep.name)
                    self.send_header("Connection", "close")
                    self.end_headers()
                    self.wfile.write(first)
                    self.wfile.flush()
                    while True:
                        try:  # backend-side death is truncation, not a
                            chunk = resp.read1(CHUNK)  # client disconnect
                        except (OSError,
                                http.client.HTTPException) as e:
                            return True, False, f"mid-stream: {e}"[:160]
                        if not chunk:
                            break
                        self.wfile.write(chunk)
                        self.wfile.flush()
                except OSError:
                    # CLIENT went away: close the backend connection so
                    # the replica's SSE writer hits its own OSError and
                    # cancels the request (lane + KV blocks reclaimed).
                    # Committed from the router's view either way — there
                    # is no client left to retry for.
                    conn.close()
                    return True, False, None
                except http.client.HTTPException as e:
                    return True, False, f"mid-stream: {e}"[:160]
                mid_eof = (clen is not None
                           and resp.length not in (0, None))
                if mid_eof:
                    return True, False, "mid-stream EOF"
                return True, False, None
            finally:
                conn.close()

        def log_message(self, fmt, *args):
            LOG.debug("router %s %s", self.address_string(), fmt % args)

    server = _RouterServer((host, port), Handler)
    server.router = router
    router.start_health_watch()
    if background:
        thread = threading.Thread(target=server.serve_forever, daemon=True,
                                  name="router")
        thread.start()
        return server
    try:
        server.serve_forever()
    finally:
        router.stop()
    return server


def _parse_replica(spec: str, index: int) -> Replica:
    """``URL[,OBS_URL]`` → Replica (graftserve/CLI spec format)."""
    parts = spec.split(",")
    url = parts[0]
    obs = parts[1] if len(parts) > 1 else ""
    return Replica(url, obs, name=f"replica{index}")


def main(argv: typing.Optional[typing.Sequence[str]] = None) -> int:
    p = argparse.ArgumentParser(
        description="health-aware router over engine replicas")
    p.add_argument("--port", type=int, default=8080)
    p.add_argument("--host", type=str, default="127.0.0.1")
    p.add_argument("--replica", action="append", default=[],
                   metavar="URL[,OBS_URL]", required=False,
                   help="replica serving URL + optional obs (/healthz) URL;"
                        " repeatable")
    p.add_argument("--health-interval-s", type=float, default=1.0)
    p.add_argument("--health-timeout-s", type=float, default=2.0)
    p.add_argument("--failover-retries", type=int, default=1)
    p.add_argument("--grace-deadline-s", type=float, default=30.0)
    args = p.parse_args(argv)
    if not args.replica:
        p.error("at least one --replica is required")
    logging.basicConfig(level=logging.INFO,
                        format="%(asctime)s %(name)s %(message)s")
    router = Router([_parse_replica(s, i)
                     for i, s in enumerate(args.replica)],
                    health_interval_s=args.health_interval_s,
                    health_timeout_s=args.health_timeout_s,
                    failover_retries=args.failover_retries)
    server = serve_router(router, host=args.host, port=args.port,
                          background=True)
    LOG.info("router on %s:%d over %d replica(s)", args.host,
             server.server_address[1], len(router.replicas))
    done = threading.Event()

    def _on_sigterm(signum, frame):
        threading.Thread(
            target=lambda: (server.drain(args.grace_deadline_s),
                            done.set()),
            daemon=True, name="router-drain").start()

    signal.signal(signal.SIGTERM, _on_sigterm)
    signal.signal(signal.SIGINT, _on_sigterm)
    while not done.wait(timeout=1.0):
        pass
    server.server_close()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
