"""Serving bridge: tokenizers, completion engine, async wrapper.

The reference couples serving to the TF session loop through a
multiprocessing-Manager queue (``InterfaceWrapper``, /root/reference/src/
interface.py:231-280); in JAX the sampler is an ordinary jitted function, so
the engine is a plain object and the async wrapper is a worker thread + queue
(same API: blocking or async ``complete``).

Tokenizers mirror the reference's two modes (interface.py:184-198): raw
byte-level for vocab<=256, HuggingFace GPT2 BPE otherwise.
"""
from __future__ import annotations

import queue
import threading
import time
import typing

import jax
import numpy as np

from ..config import Config
from ..data.feed import TEXT_AXES
from ..infer.sampler import make_text_sampler
from ..nd import NT
from . import slo
from ..sync import make_lock


class QueueDeadlineExceeded(RuntimeError):
    """A completion request spent longer than ``cfg.serve_queue_deadline_s``
    waiting on the serialized engine queue (or arrived past
    ``serve_queue_limit`` and was shed at admission).  The REST layer maps
    this to 503 + Retry-After (docs/observability.md "Serving SLOs")."""

    def __init__(self, waited_s: float, deadline_s: float, queue_depth: int,
                 shed: bool = False):
        self.waited_s = float(waited_s)
        self.deadline_s = float(deadline_s)
        self.queue_depth = int(queue_depth)
        self.shed = bool(shed)
        if shed:
            msg = (f"engine queue full ({queue_depth} waiting >= "
                   f"serve_queue_limit); request shed at admission")
        else:
            msg = (f"queue wait {waited_s:.2f}s exceeded "
                   f"serve_queue_deadline_s={deadline_s:g}s "
                   f"({queue_depth} still queued)")
        super().__init__(msg)


class RequestCancelled(RuntimeError):
    """The client abandoned this completion (SSE disconnect mid-stream,
    or an explicit ``fetch.cancel()``): the scheduler reaped the lane and
    freed its KV blocks instead of decoding to completion
    (docs/reliability.md "Serving resilience").  Raised from ``fetch()``
    so any thread still blocked on the result unblocks promptly."""

    def __init__(self, rid: int, generated: int = 0):
        self.rid = int(rid)
        self.generated = int(generated)
        super().__init__(
            f"request rid={rid} cancelled by client after "
            f"{generated} generated row(s); lane and KV blocks reclaimed")


class ByteTokenizer:
    def encode(self, text: str) -> typing.List[int]:
        return list(text.encode("utf-8", errors="replace"))

    def decode(self, ids: typing.Sequence[int]) -> str:
        return bytes(int(i) & 0xFF for i in ids).decode("utf-8", errors="replace")


class Gpt2Tokenizer:
    def __init__(self):
        from transformers import GPT2TokenizerFast
        self._tok = GPT2TokenizerFast.from_pretrained("gpt2")

    def encode(self, text: str) -> typing.List[int]:
        return self._tok.encode(text)

    def decode(self, ids: typing.Sequence[int]) -> str:
        return self._tok.decode(list(ids))


class HbnlpBpeTokenizer:
    """Serving-side codec for a tools/train_tokenizer.py artifact
    (byte-fallback BPE: ids < first_new_id are raw bytes, id
    first_new_id+i expands to merge i's pair).  Encoding runs the same
    heap-driven native encoder the tfrecord builder uses, so serving and
    training tokenize identically."""

    def __init__(self, path: str):
        import json

        import numpy as np
        with open(path) as f:
            art = json.load(f)
        self._merges = np.asarray(art["merges"], np.int32)
        self._first = int(art.get("first_new_id", 256))
        # id -> bytes, built bottom-up (merge i only references ids < i)
        table: typing.List[bytes] = [bytes([b]) for b in range(self._first)]
        for left, right in self._merges:
            table.append(table[int(left)] + table[int(right)])
        self._bytes = table

    def encode(self, text: str) -> typing.List[int]:
        import numpy as np

        from ..native import bpe_encode
        raw = np.frombuffer(text.encode("utf-8", errors="replace"),
                            np.uint8).astype(np.int32)
        return [int(t) for t in bpe_encode(raw, self._merges, self._first)]

    def decode(self, ids: typing.Sequence[int]) -> str:
        out = b"".join(self._bytes[int(i)] for i in ids
                       if 0 <= int(i) < len(self._bytes))
        return out.decode("utf-8", errors="replace")


def tokenizer_for(cfg: Config):
    if getattr(cfg, "tokenizer_path", ""):
        return HbnlpBpeTokenizer(cfg.tokenizer_path)
    if cfg.vocab_size <= 256:
        return ByteTokenizer()
    try:
        return Gpt2Tokenizer()
    except Exception:  # offline image: fall back to bytes
        return ByteTokenizer()


def effective_truncation(cfg: Config, top_k, top_p) -> typing.Tuple[int, float]:
    """The (k, p) bucket a request's truncation knobs actually compile to:
    k rounds up to the next power of two (capped at vocab), p snaps to a
    0.05 grid.  None keeps the config's exact value, un-bucketed.  Exposed
    so the REST layer can echo the EFFECTIVE values back to callers (e.g.
    requested top_k=3 samples top-4)."""
    if top_k is None:
        k = cfg.sampling_top_k
    else:
        k = max(0, int(top_k))
        if k > 0:
            k = min(1 << (k - 1).bit_length(), cfg.vocab_size)
    if top_p is None:
        p = cfg.sampling_top_p
    else:
        p = float(top_p)
        p = (1.0 if p >= 1.0
             else max(0.05, round(round(p / 0.05) * 0.05, 2)))
    return k, p


class _RowStream:
    """In-order visible-token emission from per-row callbacks
    (docs/observability.md "Streaming and inter-token latency").

    The samplers' row callback is UNORDERED (``_fire_token_row``), so rows
    are buffered and released in sequence; each release pushes the slice of
    the row that belongs to the COMPLETION — clipped against the prompt
    tail on the left (a partial prompt row is regenerated but its prompt
    tokens are not new output) and ``end`` on the right — into ``sink`` and
    stamps the ambient request record (``RequestRecord.mark_token``), so
    the concatenated stream is byte-identical to the buffered response.

    ``initial_tokens`` (the host-built padded layout) covers positions in
    rows the decode loop never rewrites — e.g. the seed row of an empty
    prompt under the KV sampler — which are emitted up front, unstamped
    (they carry no decode-cadence information).  ``flush_final`` emits any
    remainder from the final materialized output; ``close`` always delivers
    the ``None`` sentinel, success or not."""

    def __init__(self, sink, prompt_len: int, end: int, patch: int,
                 first_row: int, initial_tokens=None, rec=None):
        self.sink = sink
        self.rec = rec
        self.patch = int(patch)
        self.end = int(end)
        self.emitted = min(int(prompt_len), self.end)
        self.next_row = int(first_row)
        self.buf: typing.Dict[int, typing.List[int]] = {}
        self._lock = make_lock("serve.interface._RowStream._lock")
        self._closed = False
        if initial_tokens is not None:
            gap_hi = min(self.next_row * self.patch, self.end)
            if gap_hi > self.emitted:
                self._push(
                    [int(t) for t in initial_tokens[self.emitted:gap_hi]],
                    stamp=False)
                self.emitted = gap_hi

    def _push(self, toks: typing.List[int], stamp: bool = True) -> None:
        if not toks:
            return
        if stamp and self.rec is not None:
            self.rec.mark_token()
        if self.sink is not None:
            self.sink.put(list(toks))

    def on_row(self, pos: int, row_tokens: typing.Sequence[int]) -> None:
        """Callback sink: buffer row ``pos``, release everything in order."""
        with self._lock:
            self.buf[int(pos)] = [int(t) for t in row_tokens]
            while self.next_row in self.buf:
                row = self.buf.pop(self.next_row)
                lo = max(self.emitted, self.next_row * self.patch)
                hi = min((self.next_row + 1) * self.patch, self.end)
                if hi > lo:
                    off = lo - self.next_row * self.patch
                    self._push(row[off:off + (hi - lo)])
                    self.emitted = hi
                self.next_row += 1

    def flush_final(self, out_tokens: typing.Sequence[int]) -> None:
        """Emit whatever the row callbacks did not cover, from the final
        output — makes the stream complete regardless of which rows fired
        (callbacks are best-effort by contract)."""
        with self._lock:
            if self.emitted < self.end:
                self._push([int(t)
                            for t in out_tokens[self.emitted:self.end]])
                self.emitted = self.end

    def close(self) -> None:
        with self._lock:
            if not self._closed and self.sink is not None:
                self._closed = True
                self.sink.put(None)


class CompletionEngine:
    """Jit-compiled prompt completion (the reference's query loop,
    interface.py:177-220, with the padding behavior of ``complete``:
    the prompt is padded to full context with random tokens which the sampler
    overwrites)."""

    def __init__(self, cfg: Config, params: dict,
                 force_rebuild: bool = False,
                 first_token_callback: typing.Optional[
                     typing.Callable] = None,
                 token_callback: typing.Optional[
                     typing.Callable] = None):
        """``force_rebuild`` pins the rebuild-everything sampler even for
        KV-cache-eligible configs (the similarity debug mode exercises the
        production rebuild path, reference interface.py:283-302).

        ``first_token_callback`` (host ``(tag, token)``) arms the serving
        TTFT hook in every sampler this engine compiles: the graph notifies
        the host at the first generated position, carrying the request id
        the ambient :mod:`slo` record supplied.  ``token_callback`` (host
        ``(tag, pos, row)``) arms the per-row streaming hook the same way
        (runtime-gated per request by the traced stream flag, so only
        ``complete_tokens(..., token_sink=...)`` calls ever fire it).
        None (the default, and every non-serving caller) keeps the sampler
        graphs byte-identical to the pre-hook ones."""
        self.cfg = cfg
        self._first_token_cb = first_token_callback
        self._token_cb = token_callback
        from ..models import pipeline_params_stacked, unstack_pipeline_params
        if pipeline_params_stacked(cfg, params):
            # pipeline-trained checkpoints store body params stage-stacked;
            # decode runs the plain sequential chain, so flatten once here
            params = unstack_pipeline_params(cfg, params)
        self.params = params
        self.tokenizer = tokenizer_for(cfg)
        self._force_rebuild = force_rebuild
        # prompt completion is inherently autoregressive: the engine always
        # uses an AR sampler (use_autoregressive_sampling=False only affects
        # the dataset-driven sample run mode, reference inference.py:136-170)
        self._sampler = self._make_sampler(cfg)
        self._samplers: typing.Dict[tuple, typing.Callable] = {}
        self._samplers_lock = make_lock(
            "serve.interface.CompletionEngine._samplers_lock")
        self._rng = jax.random.key(cfg.data_seed)
        self._rng_lock = make_lock(
            "serve.interface.CompletionEngine._rng_lock")

    def _make_sampler(self, cfg: Config):
        from ..infer.kv_cache import cache_eligible, make_cached_text_sampler
        if cache_eligible(cfg) and not self._force_rebuild:
            return make_cached_text_sampler(
                cfg, self.params, first_token_callback=self._first_token_cb,
                token_callback=self._token_cb)
        return make_text_sampler(cfg, self.params,
                                 first_token_callback=self._first_token_cb,
                                 token_callback=self._token_cb)

    def _sampler_for(self, top_k, top_p):
        """Per-request truncation: the knobs are compile-time static, so
        REQUESTED values are BUCKETED (``effective_truncation``) and one
        sampler is compiled and cached per bucket — a handful of
        compilations serves every request mix.  An absent knob keeps the
        config's exact value, un-bucketed."""
        if top_k is None and top_p is None:
            return self._sampler
        cfg = self.cfg
        k, p = effective_truncation(cfg, top_k, top_p)
        if (k, p) == (cfg.sampling_top_k, cfg.sampling_top_p):
            return self._sampler
        # a dedicated lock: a cold-bucket compile must not stall the RNG
        # splits of concurrent knob-free requests
        with self._samplers_lock:
            if (k, p) not in self._samplers:
                import copy
                bcfg = copy.copy(cfg)
                bcfg.sampling_top_k, bcfg.sampling_top_p = k, p
                self._samplers[(k, p)] = self._make_sampler(bcfg)
            return self._samplers[(k, p)]

    def complete_tokens(self, prompt: typing.Sequence[int],
                        temperature: typing.Optional[float] = None,
                        max_tokens: typing.Optional[int] = None,
                        top_k: typing.Optional[int] = None,
                        top_p: typing.Optional[float] = None,
                        token_sink: typing.Optional[
                            "queue.Queue"] = None) -> np.ndarray:
        """Returns the flat token stream (prompt + completion), truncated to
        ``len(prompt) + max_tokens`` tokens.  The sampler works in rows of
        ``token_patch_size`` tokens; the prompt is laid out row-major and the
        loop stops at the last row needed.

        ``token_sink`` (streaming, needs the engine's ``token_callback``
        armed): completion tokens are pushed into the queue in generation
        order WHILE the sampler runs — row-callback chunks, then a final
        remainder, then a ``None`` sentinel (always delivered, success or
        error); the concatenated chunks equal the returned completion."""
        cfg = self.cfg
        patch = cfg.token_patch_size
        rows = cfg.sequence_length // patch
        prompt = list(prompt)[:rows * patch]
        with self._rng_lock:  # web_workers threads share this engine
            self._rng, pad_key, sample_key = jax.random.split(self._rng, 3)
        flat = jax.random.randint(pad_key, (rows * patch,), 0, cfg.vocab_size)
        flat = flat.at[:len(prompt)].set(np.asarray(prompt, np.int32))
        toks = flat.reshape(1, rows, patch)
        prompt_rows = len(prompt) // patch
        if max_tokens is None:
            end_row = rows
        else:
            end_row = min(rows, -(-(len(prompt) + max_tokens) // patch))
        end = (rows * patch if max_tokens is None
               else min(rows * patch, len(prompt) + max_tokens))
        # TTFT hook: route the graph's first-token callback to the ambient
        # request record (set by the InterfaceWrapper worker) via its id —
        # the tag is a TRACED argument, so every request shares one
        # compilation.  Tag 0 = no request / hook unarmed (never dispatched).
        rec = slo.current()
        streaming = token_sink is not None and self._token_cb is not None
        tag = (rec.rid if rec is not None
               and (self._first_token_cb is not None or streaming)
               else (slo.allocate_tag() if streaming else 0))
        if rec is not None:
            rec.tokens_generated = max(0, end - len(prompt))
        if tag and self._first_token_cb is not None and rec is not None:
            slo.register_first_token(tag, rec.mark_first_token)
        rstream = None
        if streaming:
            from ..infer.kv_cache import cache_eligible
            # the KV sampler's loop never rewrites rows before
            # max(initial_pos, 1) (row 0 of an empty prompt is the seed
            # row); the rebuild sampler fires from initial_pos itself
            first_row = (max(prompt_rows, 1)
                         if cache_eligible(cfg) and not self._force_rebuild
                         else prompt_rows)
            rstream = _RowStream(token_sink, len(prompt), end, patch,
                                 first_row,
                                 initial_tokens=np.asarray(flat), rec=rec)
            slo.register_token_sink(tag, rstream.on_row)
        elif token_sink is not None:
            # streaming requested but the engine's token hook is unarmed:
            # degrade to one final chunk (the sentinel contract holds)
            rstream = _RowStream(token_sink, len(prompt), end, patch,
                                 end_row, rec=rec)
        try:
            out = self._sampler_for(top_k, top_p)(
                NT(toks, TEXT_AXES), np.int32(prompt_rows),
                np.float32(cfg.sampling_temperature if temperature is None
                           else temperature),
                sample_key, np.int32(end_row), np.int32(tag),
                np.int32(1 if streaming else 0))
            out = np.asarray(out).reshape(-1)
            if rstream is not None:
                rstream.flush_final(out[:end])
        finally:
            if tag:
                try:  # flush any in-flight debug callback before unrouting
                    jax.effects_barrier()
                except Exception:  # noqa: BLE001 - older toolchains
                    pass
                slo.unregister_first_token(tag)
                if streaming:
                    slo.unregister_token_sink(tag)
            if rstream is not None:
                rstream.close()
        return out[:end]

    def complete_text(self, prompt: str, temperature=None, max_tokens=None,
                      top_k=None, top_p=None) -> str:
        ids = self.tokenizer.encode(prompt)
        out = self.complete_tokens(ids, temperature, max_tokens, top_k, top_p)
        return self.tokenizer.decode(out[len(ids):])


class _Job:
    """One queued completion: callable + args, the 1-slot result queue, the
    ambient SLO record snapshotted at enqueue, and the two state events the
    queue-deadline protocol needs.  ``cancelled`` is only honored while the
    job is still queued — a worker that already set ``started`` finishes
    the engine call (its result is simply dropped; the race window between
    the caller's started-check and the worker's cancelled-check is one
    instruction wide, so the waste is rare and bounded by one request)."""

    __slots__ = ("fn", "args", "out", "rec", "t_enq", "started", "cancelled",
                 "retired")

    def __init__(self, fn, args, rec):
        self.fn = fn
        self.args = args
        self.out: "queue.Queue[tuple]" = queue.Queue(1)
        self.rec = rec
        self.t_enq = time.monotonic()
        self.started = threading.Event()
        self.cancelled = threading.Event()
        self.retired = False  # left the pending count (claimed OR cancelled)


class InterfaceWrapper:
    """Serialized async facade over the engine — the reference's shape,
    and the default serving path; ``serve_max_batch > 1`` swaps it for
    the continuous-batching scheduler (serve/engine.py), which replaces
    the worker-thread queue below with lane admission between decode
    steps.  (Reference interface.py:231-280):
    ``complete(..., asynchronous=True)`` returns a handle whose ``fetch()``
    blocks for the result.  ``workers`` (cfg.web_workers, reference
    rest_api.py:86) sets the number of worker threads; ``fetch`` polls its
    result queue every cfg.default_sleep_duration seconds (the reference's
    Manager-dict poll, interface.py:243).

    Serving-SLO duties (docs/observability.md "Serving SLOs"): the ambient
    request record is stamped at enqueue (queue depth), claim (queue wait
    ends / engine busy starts) and completion (engine busy ends), and
    carried across the thread hop so the engine's TTFT hook can resolve the
    request id.  ``queue_deadline_s``/``queue_limit`` (default: the
    config's ``serve_*`` knobs) bound the wait: a request still unclaimed
    past the deadline — or arriving with ``queue_limit`` jobs already
    waiting — raises :class:`QueueDeadlineExceeded` instead of hanging."""

    def __init__(self, engine: CompletionEngine,
                 workers: typing.Optional[int] = None,
                 sleep_duration: typing.Optional[float] = None,
                 queue_deadline_s: typing.Optional[float] = None,
                 queue_limit: typing.Optional[int] = None):
        self.engine = engine
        cfg = engine.cfg
        self.sleep_duration = (cfg.default_sleep_duration
                               if sleep_duration is None else sleep_duration)
        self.queue_deadline_s = float(
            getattr(cfg, "serve_queue_deadline_s", 0.0)
            if queue_deadline_s is None else queue_deadline_s)
        self.queue_limit = int(getattr(cfg, "serve_queue_limit", 0)
                               if queue_limit is None else queue_limit)
        n = max(1, int(cfg.web_workers if workers is None else workers))
        self._q: "queue.Queue[typing.Optional[_Job]]" = queue.Queue()
        # live backlog, not _q.qsize(): deadline-cancelled jobs stay in _q
        # until a worker pops them, and counting those corpses would shed
        # healthy arrivals, inflate hbnlp_serve_queue_depth, and overprice
        # Retry-After for as long as the workers stay busy
        self._pending = 0
        self._pending_lock = make_lock(
            "serve.interface.InterfaceWrapper._pending_lock")
        self._threads = []
        for _ in range(n):
            t = threading.Thread(target=self._worker, daemon=True)
            t.start()
            self._threads.append(t)

    def queue_depth(self) -> int:
        with self._pending_lock:
            return self._pending

    def _retire(self, job: _Job) -> None:
        # exactly-once under the claim/cancel race (worker sets started
        # while fetch sets cancelled): whoever gets here first counts
        with self._pending_lock:
            if not job.retired:
                job.retired = True
                self._pending -= 1

    def _worker(self):
        while True:
            job = self._q.get()
            if job is None:
                self._q.put(None)  # let sibling workers drain too
                return
            self._retire(job)
            if job.cancelled.is_set():
                continue  # caller gave up while queued (deadline 503)
            job.started.set()
            rec = job.rec
            # the record travels with the job: the engine (this thread)
            # resolves slo.current() for the TTFT tag
            prev = slo.set_current(rec)
            if rec is not None:
                rec.mark_started()
            try:
                result = ("ok", job.fn(*job.args))
            except Exception as e:  # propagate to caller
                result = ("err", e)
            # engine-done must be stamped BEFORE the result is published:
            # the handler's finish() runs the instant fetch() wakes, and an
            # unstamped record silently drops its engine/decode observations
            if rec is not None:
                rec.mark_engine_done()
            slo.set_current(prev)
            job.out.put(result)

    def complete(self, prompt: typing.Sequence[int], temperature: float = 0.0,
                 response_len: int = 64, asynchronous: bool = False,
                 top_k: typing.Optional[int] = None,
                 top_p: typing.Optional[float] = None,
                 token_sink: typing.Optional["queue.Queue"] = None):
        depth = self.queue_depth()
        if self.queue_limit and depth >= self.queue_limit:
            raise QueueDeadlineExceeded(0.0, self.queue_deadline_s, depth,
                                        shed=True)
        rec = slo.current()
        if rec is not None:
            rec.mark_enqueued(queue_depth=depth)
        args = (prompt, temperature, response_len, top_k, top_p)
        if token_sink is not None:
            # streamed completions ride the same worker queue; the engine
            # delivers chunks + the None sentinel through the sink while
            # the job runs (complete_tokens' sentinel contract)
            args = args + (token_sink,)
        job = _Job(self.engine.complete_tokens, args, rec)
        with self._pending_lock:
            self._pending += 1
        self._q.put(job)
        deadline = self.queue_deadline_s

        def fetch():
            while True:
                try:
                    status, value = job.out.get(timeout=self.sleep_duration)
                    break
                except queue.Empty:
                    waited = time.monotonic() - job.t_enq
                    if (deadline and waited > deadline
                            and not job.started.is_set()):
                        job.cancelled.set()
                        self._retire(job)
                        raise QueueDeadlineExceeded(waited, deadline,
                                                    self.queue_depth())
                    continue
            if status == "err":
                raise value
            return value

        def cancel():
            # honored while queued (a worker drops cancelled jobs unrun);
            # a started job finishes its serialized engine call — this
            # wrapper decodes one request at a time, so there is no lane
            # or KV pool to reclaim early (BatchInterface has the real
            # mid-decode reap)
            job.cancelled.set()
            self._retire(job)

        fetch.cancel = cancel
        return fetch if asynchronous else fetch()

    def close(self):
        self._q.put(None)
