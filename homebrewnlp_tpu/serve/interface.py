"""Serving bridge: tokenizers, completion engine, async wrapper.

The reference couples serving to the TF session loop through a
multiprocessing-Manager queue (``InterfaceWrapper``, /root/reference/src/
interface.py:231-280); in JAX the sampler is an ordinary jitted function, so
the engine is a plain object and the async wrapper is a worker thread + queue
(same API: blocking or async ``complete``).

Tokenizers mirror the reference's two modes (interface.py:184-198): raw
byte-level for vocab<=256, HuggingFace GPT2 BPE otherwise.
"""
from __future__ import annotations

import queue
import threading
import typing

import jax
import numpy as np

from ..config import Config
from ..data.feed import TEXT_AXES
from ..infer.sampler import make_text_sampler
from ..nd import NT


class ByteTokenizer:
    def encode(self, text: str) -> typing.List[int]:
        return list(text.encode("utf-8", errors="replace"))

    def decode(self, ids: typing.Sequence[int]) -> str:
        return bytes(int(i) & 0xFF for i in ids).decode("utf-8", errors="replace")


class Gpt2Tokenizer:
    def __init__(self):
        from transformers import GPT2TokenizerFast
        self._tok = GPT2TokenizerFast.from_pretrained("gpt2")

    def encode(self, text: str) -> typing.List[int]:
        return self._tok.encode(text)

    def decode(self, ids: typing.Sequence[int]) -> str:
        return self._tok.decode(list(ids))


class HbnlpBpeTokenizer:
    """Serving-side codec for a tools/train_tokenizer.py artifact
    (byte-fallback BPE: ids < first_new_id are raw bytes, id
    first_new_id+i expands to merge i's pair).  Encoding runs the same
    heap-driven native encoder the tfrecord builder uses, so serving and
    training tokenize identically."""

    def __init__(self, path: str):
        import json

        import numpy as np
        with open(path) as f:
            art = json.load(f)
        self._merges = np.asarray(art["merges"], np.int32)
        self._first = int(art.get("first_new_id", 256))
        # id -> bytes, built bottom-up (merge i only references ids < i)
        table: typing.List[bytes] = [bytes([b]) for b in range(self._first)]
        for left, right in self._merges:
            table.append(table[int(left)] + table[int(right)])
        self._bytes = table

    def encode(self, text: str) -> typing.List[int]:
        import numpy as np

        from ..native import bpe_encode
        raw = np.frombuffer(text.encode("utf-8", errors="replace"),
                            np.uint8).astype(np.int32)
        return [int(t) for t in bpe_encode(raw, self._merges, self._first)]

    def decode(self, ids: typing.Sequence[int]) -> str:
        out = b"".join(self._bytes[int(i)] for i in ids
                       if 0 <= int(i) < len(self._bytes))
        return out.decode("utf-8", errors="replace")


def tokenizer_for(cfg: Config):
    if getattr(cfg, "tokenizer_path", ""):
        return HbnlpBpeTokenizer(cfg.tokenizer_path)
    if cfg.vocab_size <= 256:
        return ByteTokenizer()
    try:
        return Gpt2Tokenizer()
    except Exception:  # offline image: fall back to bytes
        return ByteTokenizer()


def effective_truncation(cfg: Config, top_k, top_p) -> typing.Tuple[int, float]:
    """The (k, p) bucket a request's truncation knobs actually compile to:
    k rounds up to the next power of two (capped at vocab), p snaps to a
    0.05 grid.  None keeps the config's exact value, un-bucketed.  Exposed
    so the REST layer can echo the EFFECTIVE values back to callers (e.g.
    requested top_k=3 samples top-4)."""
    if top_k is None:
        k = cfg.sampling_top_k
    else:
        k = max(0, int(top_k))
        if k > 0:
            k = min(1 << (k - 1).bit_length(), cfg.vocab_size)
    if top_p is None:
        p = cfg.sampling_top_p
    else:
        p = float(top_p)
        p = (1.0 if p >= 1.0
             else max(0.05, round(round(p / 0.05) * 0.05, 2)))
    return k, p


class CompletionEngine:
    """Jit-compiled prompt completion (the reference's query loop,
    interface.py:177-220, with the padding behavior of ``complete``:
    the prompt is padded to full context with random tokens which the sampler
    overwrites)."""

    def __init__(self, cfg: Config, params: dict,
                 force_rebuild: bool = False):
        """``force_rebuild`` pins the rebuild-everything sampler even for
        KV-cache-eligible configs (the similarity debug mode exercises the
        production rebuild path, reference interface.py:283-302)."""
        self.cfg = cfg
        from ..models import pipeline_params_stacked, unstack_pipeline_params
        if pipeline_params_stacked(cfg, params):
            # pipeline-trained checkpoints store body params stage-stacked;
            # decode runs the plain sequential chain, so flatten once here
            params = unstack_pipeline_params(cfg, params)
        self.params = params
        self.tokenizer = tokenizer_for(cfg)
        self._force_rebuild = force_rebuild
        # prompt completion is inherently autoregressive: the engine always
        # uses an AR sampler (use_autoregressive_sampling=False only affects
        # the dataset-driven sample run mode, reference inference.py:136-170)
        self._sampler = self._make_sampler(cfg)
        self._samplers: typing.Dict[tuple, typing.Callable] = {}
        self._samplers_lock = threading.Lock()
        self._rng = jax.random.key(cfg.data_seed)
        self._rng_lock = threading.Lock()

    def _make_sampler(self, cfg: Config):
        from ..infer.kv_cache import cache_eligible, make_cached_text_sampler
        if cache_eligible(cfg) and not self._force_rebuild:
            return make_cached_text_sampler(cfg, self.params)
        return make_text_sampler(cfg, self.params)

    def _sampler_for(self, top_k, top_p):
        """Per-request truncation: the knobs are compile-time static, so
        REQUESTED values are BUCKETED (``effective_truncation``) and one
        sampler is compiled and cached per bucket — a handful of
        compilations serves every request mix.  An absent knob keeps the
        config's exact value, un-bucketed."""
        if top_k is None and top_p is None:
            return self._sampler
        cfg = self.cfg
        k, p = effective_truncation(cfg, top_k, top_p)
        if (k, p) == (cfg.sampling_top_k, cfg.sampling_top_p):
            return self._sampler
        # a dedicated lock: a cold-bucket compile must not stall the RNG
        # splits of concurrent knob-free requests
        with self._samplers_lock:
            if (k, p) not in self._samplers:
                import copy
                bcfg = copy.copy(cfg)
                bcfg.sampling_top_k, bcfg.sampling_top_p = k, p
                self._samplers[(k, p)] = self._make_sampler(bcfg)
            return self._samplers[(k, p)]

    def complete_tokens(self, prompt: typing.Sequence[int],
                        temperature: typing.Optional[float] = None,
                        max_tokens: typing.Optional[int] = None,
                        top_k: typing.Optional[int] = None,
                        top_p: typing.Optional[float] = None) -> np.ndarray:
        """Returns the flat token stream (prompt + completion), truncated to
        ``len(prompt) + max_tokens`` tokens.  The sampler works in rows of
        ``token_patch_size`` tokens; the prompt is laid out row-major and the
        loop stops at the last row needed."""
        cfg = self.cfg
        patch = cfg.token_patch_size
        rows = cfg.sequence_length // patch
        prompt = list(prompt)[:rows * patch]
        with self._rng_lock:  # web_workers threads share this engine
            self._rng, pad_key, sample_key = jax.random.split(self._rng, 3)
        flat = jax.random.randint(pad_key, (rows * patch,), 0, cfg.vocab_size)
        flat = flat.at[:len(prompt)].set(np.asarray(prompt, np.int32))
        toks = flat.reshape(1, rows, patch)
        prompt_rows = len(prompt) // patch
        if max_tokens is None:
            end_row = rows
        else:
            end_row = min(rows, -(-(len(prompt) + max_tokens) // patch))
        out = self._sampler_for(top_k, top_p)(
            NT(toks, TEXT_AXES), np.int32(prompt_rows),
            np.float32(cfg.sampling_temperature if temperature is None
                       else temperature),
            sample_key, np.int32(end_row))
        out = np.asarray(out).reshape(-1)
        end = (rows * patch if max_tokens is None
               else min(rows * patch, len(prompt) + max_tokens))
        return out[:end]

    def complete_text(self, prompt: str, temperature=None, max_tokens=None,
                      top_k=None, top_p=None) -> str:
        ids = self.tokenizer.encode(prompt)
        out = self.complete_tokens(ids, temperature, max_tokens, top_k, top_p)
        return self.tokenizer.decode(out[len(ids):])


class InterfaceWrapper:
    """Async facade over the engine (reference interface.py:231-280):
    ``complete(..., asynchronous=True)`` returns a handle whose ``fetch()``
    blocks for the result.  ``workers`` (cfg.web_workers, reference
    rest_api.py:86) sets the number of worker threads; ``fetch`` polls its
    result queue every cfg.default_sleep_duration seconds (the reference's
    Manager-dict poll, interface.py:243)."""

    def __init__(self, engine: CompletionEngine,
                 workers: typing.Optional[int] = None,
                 sleep_duration: typing.Optional[float] = None):
        self.engine = engine
        cfg = engine.cfg
        self.sleep_duration = (cfg.default_sleep_duration
                               if sleep_duration is None else sleep_duration)
        n = max(1, int(cfg.web_workers if workers is None else workers))
        self._q: "queue.Queue[tuple]" = queue.Queue()
        self._threads = []
        for _ in range(n):
            t = threading.Thread(target=self._worker, daemon=True)
            t.start()
            self._threads.append(t)

    def _worker(self):
        while True:
            item = self._q.get()
            if item is None:
                self._q.put(None)  # let sibling workers drain too
                return
            fn, args, out = item
            try:
                out.put(("ok", fn(*args)))
            except Exception as e:  # propagate to caller
                out.put(("err", e))

    def complete(self, prompt: typing.Sequence[int], temperature: float = 0.0,
                 response_len: int = 64, asynchronous: bool = False,
                 top_k: typing.Optional[int] = None,
                 top_p: typing.Optional[float] = None):
        out: "queue.Queue[tuple]" = queue.Queue(1)
        self._q.put((self.engine.complete_tokens,
                     (prompt, temperature, response_len, top_k, top_p), out))

        def fetch():
            while True:
                try:
                    status, value = out.get(timeout=self.sleep_duration)
                    break
                except queue.Empty:
                    continue
            if status == "err":
                raise value
            return value

        return fetch if asynchronous else fetch()

    def close(self):
        self._q.put(None)
