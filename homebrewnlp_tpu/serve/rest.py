"""REST API on the stdlib HTTP server.

Endpoint-compatible with the reference's FastAPI app (/root/reference/src/
rest_api.py:13-89): POST /encode {prompt}, /decode {prompt: [ids]},
/token_completion {prompt|tokens, temperature, response_len, asynchronous},
/completion (same, returns text), /check_tokens.  fastapi/uvicorn are not in
the image, so this uses ``http.server.ThreadingHTTPServer`` — zero deps, and
the threaded wrapper serializes sampler calls exactly like the reference's
Manager-queue bridge.
"""
from __future__ import annotations

import json
import threading
import typing
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np

from ..config import Config
from .interface import CompletionEngine, InterfaceWrapper


def _sanitize_tokens(tokens: typing.Sequence[int], vocab: int) -> typing.List[int]:
    # the reference clamps out-of-vocab ids (rest_api.py:42-53)
    return [min(max(int(t), 0), vocab - 1) for t in tokens]


class RestAPI:
    def __init__(self, cfg: Config, params: dict):
        self.cfg = cfg
        self.engine = CompletionEngine(cfg, params)
        self.wrapper = InterfaceWrapper(self.engine)

    # -- endpoints -----------------------------------------------------------
    def encode(self, body: dict) -> dict:
        return {"tokens": self.engine.tokenizer.encode(body["prompt"])}

    def decode(self, body: dict) -> dict:
        toks = _sanitize_tokens(body["prompt"], self.cfg.vocab_size)
        return {"completion": self.engine.tokenizer.decode(toks)}

    def check_tokens(self, body: dict) -> dict:
        toks = body["prompt"]
        return {"tokens": _sanitize_tokens(toks, self.cfg.vocab_size)}

    def _truncation(self, body: dict) -> typing.Tuple[dict, dict]:
        """Optional per-request top_k/top_p -> (sampler kwargs, echo dict).

        Requested values are silently bucketed for the compile cache
        (interface.effective_truncation), so completion responses echo the
        EFFECTIVE values actually sampled with (e.g. top_k=3 -> top_k: 4)."""
        from .interface import effective_truncation
        kwargs = {"top_k": (None if body.get("top_k") is None
                            else int(body["top_k"])),
                  "top_p": (None if body.get("top_p") is None
                            else float(body["top_p"]))}
        k, p = effective_truncation(self.cfg, **kwargs)
        return kwargs, {"top_k": k, "top_p": p}

    def token_completion(self, body: dict) -> dict:
        toks = _sanitize_tokens(body.get("prompt", body.get("tokens", [])),
                                self.cfg.vocab_size)
        kwargs, echo = self._truncation(body)
        out = self.wrapper.complete(
            toks, float(body.get("temperature", self.cfg.sampling_temperature)),
            int(body.get("response_len", 64)), **kwargs)
        return dict({"completion": np.asarray(out).tolist()}, **echo)

    def completion(self, body: dict) -> dict:
        ids = self.engine.tokenizer.encode(body["prompt"])
        kwargs, echo = self._truncation(body)
        out = self.wrapper.complete(
            ids, float(body.get("temperature", self.cfg.sampling_temperature)),
            int(body.get("response_len", 64)), **kwargs)
        return dict({"completion": self.engine.tokenizer.decode(
            np.asarray(out)[len(ids):])}, **echo)

    ENDPOINTS = ("encode", "decode", "check_tokens", "token_completion",
                 "completion")


def serve(cfg: Config, params: dict, host: str = "127.0.0.1",
          port: int = 8000, background: bool = False):
    api = RestAPI(cfg, params)

    class Handler(BaseHTTPRequestHandler):
        def do_POST(self):
            name = self.path.strip("/")
            if name not in RestAPI.ENDPOINTS:
                self.send_error(404)
                return
            try:
                length = int(self.headers.get("Content-Length", 0))
                body = json.loads(self.rfile.read(length) or b"{}")
                result = getattr(api, name)(body)
                payload = json.dumps(result).encode()
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(payload)))
                self.end_headers()
                self.wfile.write(payload)
            except Exception as e:
                self.send_error(500, str(e))

        def log_message(self, fmt, *args):  # quiet
            pass

    server = ThreadingHTTPServer((host, port), Handler)
    if background:
        thread = threading.Thread(target=server.serve_forever, daemon=True)
        thread.start()
        return server
    server.serve_forever()
