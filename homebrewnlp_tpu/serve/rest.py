"""REST API on the stdlib HTTP server.

Endpoint-compatible with the reference's FastAPI app (/root/reference/src/
rest_api.py:13-89): POST /encode {prompt}, /decode {prompt: [ids]},
/token_completion {prompt|tokens, temperature, response_len, asynchronous},
/completion (same, returns text), /check_tokens.  fastapi/uvicorn are not in
the image, so this uses ``http.server.ThreadingHTTPServer`` — zero deps, and
the threaded wrapper serializes sampler calls exactly like the reference's
Manager-queue bridge.
"""
from __future__ import annotations

import json
import logging
import threading
import time
import typing
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np

from ..config import Config
from ..obs import exporter as obs_exporter
from ..obs import spans
from ..obs.registry import REGISTRY
from .interface import CompletionEngine, InterfaceWrapper

LOG = logging.getLogger("homebrewnlp_tpu.serve.rest")


def request_metrics(registry=None):
    """(counter, histogram) for REST request records, resolved ONCE per
    server (docs/observability.md) — the per-request path only pays the
    labels lookup + update.  Label values must be a MATCHED endpoint (or
    the fixed ``other`` bucket for unmatched requests): labelling with the
    raw request path would let a scanner grow the label set, and the
    registry, without bound."""
    reg = registry if registry is not None else REGISTRY
    return (reg.counter("hbnlp_serve_requests_total", "REST requests "
                        "served", labelnames=("method", "path", "status")),
            reg.histogram("hbnlp_serve_request_seconds",
                          "REST request latency", labelnames=("path",)))


def _sanitize_tokens(tokens: typing.Sequence[int], vocab: int) -> typing.List[int]:
    # the reference clamps out-of-vocab ids (rest_api.py:42-53)
    return [min(max(int(t), 0), vocab - 1) for t in tokens]


class RestAPI:
    def __init__(self, cfg: Config, params: dict):
        self.cfg = cfg
        self.engine = CompletionEngine(cfg, params)
        self.wrapper = InterfaceWrapper(self.engine)

    # -- endpoints -----------------------------------------------------------
    def encode(self, body: dict) -> dict:
        return {"tokens": self.engine.tokenizer.encode(body["prompt"])}

    def decode(self, body: dict) -> dict:
        toks = _sanitize_tokens(body["prompt"], self.cfg.vocab_size)
        return {"completion": self.engine.tokenizer.decode(toks)}

    def check_tokens(self, body: dict) -> dict:
        toks = body["prompt"]
        return {"tokens": _sanitize_tokens(toks, self.cfg.vocab_size)}

    def _truncation(self, body: dict) -> typing.Tuple[dict, dict]:
        """Optional per-request top_k/top_p -> (sampler kwargs, echo dict).

        Requested values are silently bucketed for the compile cache
        (interface.effective_truncation), so completion responses echo the
        EFFECTIVE values actually sampled with (e.g. top_k=3 -> top_k: 4)."""
        from .interface import effective_truncation
        kwargs = {"top_k": (None if body.get("top_k") is None
                            else int(body["top_k"])),
                  "top_p": (None if body.get("top_p") is None
                            else float(body["top_p"]))}
        k, p = effective_truncation(self.cfg, **kwargs)
        return kwargs, {"top_k": k, "top_p": p}

    def token_completion(self, body: dict) -> dict:
        toks = _sanitize_tokens(body.get("prompt", body.get("tokens", [])),
                                self.cfg.vocab_size)
        kwargs, echo = self._truncation(body)
        out = self.wrapper.complete(
            toks, float(body.get("temperature", self.cfg.sampling_temperature)),
            int(body.get("response_len", 64)), **kwargs)
        return dict({"completion": np.asarray(out).tolist()}, **echo)

    def completion(self, body: dict) -> dict:
        ids = self.engine.tokenizer.encode(body["prompt"])
        kwargs, echo = self._truncation(body)
        out = self.wrapper.complete(
            ids, float(body.get("temperature", self.cfg.sampling_temperature)),
            int(body.get("response_len", 64)), **kwargs)
        return dict({"completion": self.engine.tokenizer.decode(
            np.asarray(out)[len(ids):])}, **echo)

    ENDPOINTS = ("encode", "decode", "check_tokens", "token_completion",
                 "completion")


class _ApiServer(ThreadingHTTPServer):
    """REST server owning an optional obs exporter: any teardown path —
    ``shutdown()``, ``server_close()``, or the context-manager exit (which
    calls ``server_close``) — also stops the exporter, exactly once."""

    _obs_server = None

    def shutdown(self):
        super().shutdown()
        self._stop_obs()

    def server_close(self):
        super().server_close()
        self._stop_obs()

    def _stop_obs(self):
        obs, self._obs_server = self._obs_server, None
        if obs is not None:
            obs_exporter.stop_server(obs)


def serve(cfg: Config, params: dict, host: str = "127.0.0.1",
          port: int = 8000, background: bool = False, api=None,
          registry=None):
    """``api`` (tests) substitutes a prebuilt endpoint object; ``registry``
    overrides the process-default obs registry the request log records to.
    When ``cfg.obs_port`` is set, a /metrics + /healthz exporter runs
    alongside and is torn down with the returned server (docs/
    observability.md)."""
    api = api if api is not None else RestAPI(cfg, params)
    endpoints = getattr(api, "ENDPOINTS", RestAPI.ENDPOINTS)
    req_count, req_latency = request_metrics(registry)

    class Handler(BaseHTTPRequestHandler):
        def do_POST(self):
            t0 = time.perf_counter()
            name = self.path.strip("/")
            status = 500
            try:
                if name not in endpoints:
                    status = 404
                    self.send_error(404)
                    return
                try:
                    length = int(self.headers.get("Content-Length", 0))
                    body = json.loads(self.rfile.read(length) or b"{}")
                    with spans.span(f"serve/{name}"):
                        result = getattr(api, name)(body)
                    payload = json.dumps(result).encode()
                    status = 200
                    self.send_response(200)
                    self.send_header("Content-Type", "application/json")
                    self.send_header("Content-Length", str(len(payload)))
                    self.end_headers()
                    self.wfile.write(payload)
                except Exception as e:
                    status = 500
                    self.send_error(500, str(e))
            finally:
                # structured per-request record: registry metrics + a
                # debug-level log line, quiet on stdout by default
                label = f"/{name}" if name in endpoints else "other"
                dt = time.perf_counter() - t0
                req_count.labels(method="POST", path=label,
                                 status=str(status)).inc()
                req_latency.labels(path=label).observe(dt)
                LOG.debug("request method=POST path=%s status=%d "
                          "latency_ms=%.1f", label, status, dt * 1e3)

        def log_message(self, fmt, *args):
            # per-request records go through the registry metrics; raw
            # http.server chatter stays at debug level, off stdout
            LOG.debug("%s %s", self.address_string(), fmt % args)

    server = _ApiServer((host, port), Handler)
    if cfg is not None and getattr(cfg, "obs_port", 0):
        try:
            server._obs_server = obs_exporter.start_server(
                cfg.obs_port, registry=registry if registry is not None
                else REGISTRY)
        except OSError:
            server.server_close()  # don't leak the bound REST socket
            raise
    if background:
        thread = threading.Thread(target=server.serve_forever, daemon=True)
        thread.start()
        return server
    try:
        server.serve_forever()
    finally:
        server._stop_obs()
