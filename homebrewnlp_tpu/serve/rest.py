"""REST API on the stdlib HTTP server.

Endpoint-compatible with the reference's FastAPI app (/root/reference/src/
rest_api.py:13-89): POST /encode {prompt}, /decode {prompt: [ids]},
/token_completion {prompt|tokens, temperature, response_len, asynchronous},
/completion (same, returns text), /check_tokens.  fastapi/uvicorn are not in
the image, so this uses ``http.server.ThreadingHTTPServer`` — zero deps, and
the threaded wrapper serializes sampler calls exactly like the reference's
Manager-queue bridge.
"""
from __future__ import annotations

import json
import logging
import os
import queue
import threading
import time
import typing
import uuid
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np

from ..config import Config
from ..obs import exporter as obs_exporter
from ..obs import spans
from ..obs.registry import REGISTRY
from ..obs.usage import clean_tenant
from ..reliability import faults
from . import slo as slo_mod
from .interface import (CompletionEngine, InterfaceWrapper,
                        QueueDeadlineExceeded)

LOG = logging.getLogger("homebrewnlp_tpu.serve.rest")


def request_metrics(registry=None):
    """(counter, histogram) for REST request records, resolved ONCE per
    server (docs/observability.md) — the per-request path only pays the
    labels lookup + update.  Label values must be a MATCHED endpoint (or
    the fixed ``other`` bucket for unmatched requests): labelling with the
    raw request path would let a scanner grow the label set, and the
    registry, without bound."""
    reg = registry if registry is not None else REGISTRY
    return (reg.counter("hbnlp_serve_requests_total", "REST requests "
                        "served", labelnames=("method", "path", "status")),
            reg.histogram("hbnlp_serve_request_seconds",
                          "REST request latency", labelnames=("path",),
                          buckets=slo_mod.SERVE_LATENCY_BUCKETS))


def _sanitize_tokens(tokens: typing.Sequence[int], vocab: int) -> typing.List[int]:
    # the reference clamps out-of-vocab ids (rest_api.py:42-53)
    return [min(max(int(t), 0), vocab - 1) for t in tokens]


class _SseStream:
    """Iterator facade over a streaming generator carrying the abandon
    hook: a generator cannot take attributes, so this thin wrapper holds
    the engine-side ``fetch.cancel`` for the SSE writer — on client
    disconnect the handler calls :meth:`cancel` and the scheduler's reap
    pass frees the lane + KV blocks instead of decoding the abandoned
    stream to completion (docs/reliability.md "Serving resilience")."""

    def __init__(self, it, cancel=None):
        self._it = it
        self._cancel = cancel

    def __iter__(self):
        return self

    def __next__(self):
        return next(self._it)

    def cancel(self) -> None:
        if self._cancel is not None:
            self._cancel()


def _request_xid(headers) -> str:
    """Resolve the request's correlation id: the client's ``X-Request-Id``
    if present, else the trace-id field of a W3C ``traceparent`` header,
    else a fresh server-generated id.  Capped so a hostile header cannot
    bloat logs/spans; the id is echoed back on every response."""
    xid = (headers.get("X-Request-Id") or "").strip()
    if not xid:
        parts = (headers.get("traceparent") or "").strip().split("-")
        if len(parts) >= 2 and parts[1] and parts[1].strip("0"):
            xid = parts[1]
    if not xid:
        xid = uuid.uuid4().hex[:16]
    return xid[:128]


def _request_tenant(headers, header_name: str = "X-Tenant") -> str:
    """Resolve the request's tenant identity from the configured header
    (``usage_tenant_header``): the validated value, or ``anon`` for
    missing/invalid/reserved values (obs/usage.py::clean_tenant).  Rides
    next to the correlation id through log lines, span trails, flight
    trails, and the usage meter's accounts."""
    return clean_tenant(headers.get(header_name))


class RestAPI:
    def __init__(self, cfg: Config, params: dict):
        self.cfg = cfg
        # the engine's samplers carry the TTFT hook: the graph notifies the
        # host at the first sampled token, tagged with the request id the
        # ambient SLO record supplies (docs/observability.md "Serving SLOs").
        # serve_max_batch > 1 (on a KV-cache-eligible config) swaps the
        # serialized InterfaceWrapper for the continuous-batching scheduler
        # (serve/engine.py); the default keeps the serialized path
        # bit-identical to the pre-engine behavior
        from .engine import BatchEngine, BatchInterface, use_batch_engine
        # streaming (serve_stream, default on): the batch engine pushes
        # token chunks from its host loop; the serialized samplers arm the
        # per-row token callback (traced stream flag — a buffered request
        # never pays a host round-trip).  serve_stream=False keeps the
        # samplers callback-free and every stream=true request buffered.
        streaming = bool(getattr(cfg, "serve_stream", True))
        token_cb = slo_mod.dispatch_token_row if streaming else None
        if use_batch_engine(cfg):
            self.engine = BatchEngine(
                cfg, params,
                first_token_callback=slo_mod.dispatch_first_token)
            self.wrapper = BatchInterface(self.engine)
        else:
            if int(getattr(cfg, "serve_max_batch", 1)) > 1:
                LOG.warning(
                    "serve_max_batch=%d requested but the config is not "
                    "KV-cache eligible; serving stays serialized",
                    cfg.serve_max_batch)
            self.engine = CompletionEngine(
                cfg, params,
                first_token_callback=slo_mod.dispatch_first_token,
                token_callback=token_cb)
            self.wrapper = InterfaceWrapper(self.engine)
        self.streaming = streaming

    # -- endpoints -----------------------------------------------------------
    def encode(self, body: dict) -> dict:
        return {"tokens": self.engine.tokenizer.encode(body["prompt"])}

    def decode(self, body: dict) -> dict:
        toks = _sanitize_tokens(body["prompt"], self.cfg.vocab_size)
        return {"completion": self.engine.tokenizer.decode(toks)}

    def check_tokens(self, body: dict) -> dict:
        toks = body["prompt"]
        return {"tokens": _sanitize_tokens(toks, self.cfg.vocab_size)}

    def _truncation(self, body: dict) -> typing.Tuple[dict, dict]:
        """Optional per-request top_k/top_p -> (sampler kwargs, echo dict).

        Requested values are silently bucketed for the compile cache
        (interface.effective_truncation), so completion responses echo the
        EFFECTIVE values actually sampled with (e.g. top_k=3 -> top_k: 4)."""
        from .interface import effective_truncation
        kwargs = {"top_k": (None if body.get("top_k") is None
                            else int(body["top_k"])),
                  "top_p": (None if body.get("top_p") is None
                            else float(body["top_p"]))}
        k, p = effective_truncation(self.cfg, **kwargs)
        return kwargs, {"top_k": k, "top_p": p}

    @staticmethod
    def _stamp_prompt_tokens(n: int) -> None:
        # engine-agnostic prompt-size stamp for the usage meter: the
        # ambient SLO record exists on every handler thread, and the
        # endpoint is the one place that knows the parsed token count
        rec = slo_mod.current()
        if rec is not None:
            rec.prompt_tokens = int(n)

    def token_completion(self, body: dict) -> dict:
        toks = _sanitize_tokens(body.get("prompt", body.get("tokens", [])),
                                self.cfg.vocab_size)
        self._stamp_prompt_tokens(len(toks))
        kwargs, echo = self._truncation(body)
        out = self.wrapper.complete(
            toks, float(body.get("temperature", self.cfg.sampling_temperature)),
            int(body.get("response_len", 64)), **kwargs)
        return dict({"completion": np.asarray(out).tolist()}, **echo)

    def completion(self, body: dict) -> dict:
        ids = self.engine.tokenizer.encode(body["prompt"])
        self._stamp_prompt_tokens(len(ids))
        kwargs, echo = self._truncation(body)
        out = self.wrapper.complete(
            ids, float(body.get("temperature", self.cfg.sampling_temperature)),
            int(body.get("response_len", 64)), **kwargs)
        return dict({"completion": self.engine.tokenizer.decode(
            np.asarray(out)[len(ids):])}, **echo)

    # -- streaming (docs/observability.md "Streaming and inter-token
    # latency"): ``stream: true`` on a completion endpoint answers SSE —
    # one ``data:`` event per token chunk as the engine emits it, then a
    # final event carrying the exact buffered-response payload + ``done``.
    # The generator is primed BEFORE headers go out, so admission shedding
    # still maps to a clean 503.
    def _stream(self, toks: typing.List[int], body: dict,
                decode_text: bool, prompt_len: int):
        cfg = self.cfg
        self._stamp_prompt_tokens(prompt_len)
        kwargs, echo = self._truncation(body)
        sink: "queue.Queue" = queue.Queue()
        fetch = self.wrapper.complete(
            toks, float(body.get("temperature", cfg.sampling_temperature)),
            int(body.get("response_len", 64)), asynchronous=True,
            token_sink=sink, **kwargs)
        poll = max(0.01, float(cfg.default_sleep_duration))
        deadline = float(getattr(cfg, "serve_queue_deadline_s", 0.0))
        t0 = time.monotonic()
        state: dict = {"done": False, "result": None, "error": None,
                       "thread": None}

        def do_fetch():
            try:
                state["result"] = fetch()
            except BaseException as e:  # noqa: BLE001 - re-raised in gen
                state["error"] = e
            state["done"] = True

        def gen():
            # the deadline-cancel protocol lives in fetch(), but fetch()
            # BLOCKS until completion once the request is admitted — run
            # it on a side thread so a still-QUEUED request past the
            # deadline is cancelled (the error surfaces on the next poll)
            # while an admitted request's chunks keep streaming instead
            # of bursting at the end
            while True:
                try:
                    item = sink.get(timeout=poll)
                except queue.Empty:
                    if state["error"] is not None:
                        raise state["error"]
                    if (deadline and state["thread"] is None
                            and not state["done"]
                            and time.monotonic() - t0 > deadline):
                        t = threading.Thread(target=do_fetch, daemon=True)
                        state["thread"] = t
                        t.start()
                    continue
                if item is None:
                    break
                yield ({"text": self.engine.tokenizer.decode(item)}
                       if decode_text else {"tokens": list(item)})
            # sentinel delivered: the result lands immediately after
            if state["thread"] is not None:
                state["thread"].join()
            elif not state["done"]:
                do_fetch()
            if state["error"] is not None:
                raise state["error"]
            out = np.asarray(state["result"])
            final = ({"completion": self.engine.tokenizer.decode(
                          out[prompt_len:])} if decode_text
                     else {"completion": out.tolist()})
            yield dict(final, done=True, **echo)
        return _SseStream(gen(), getattr(fetch, "cancel", None))

    def token_completion_stream(self, body: dict):
        toks = _sanitize_tokens(body.get("prompt", body.get("tokens", [])),
                                self.cfg.vocab_size)
        return self._stream(toks, body, decode_text=False,
                            prompt_len=len(toks))

    def completion_stream(self, body: dict):
        ids = self.engine.tokenizer.encode(body["prompt"])
        return self._stream(ids, body, decode_text=True,
                            prompt_len=len(ids))

    ENDPOINTS = ("encode", "decode", "check_tokens", "token_completion",
                 "completion")
    #: endpoints honoring ``stream: true`` (SSE) when serve_stream is on
    STREAM_ENDPOINTS = ("token_completion", "completion")


class _ApiServer(ThreadingHTTPServer):
    """REST server owning an optional obs exporter: any teardown path —
    ``shutdown()``, ``server_close()``, or the context-manager exit (which
    calls ``server_close``) — also stops the exporter, exactly once, and
    detaches this server's queue probe from the SLO gauges (the registry
    outlives the server; a still-bound probe would pin the engine and its
    params forever)."""

    _obs_server = None
    _slo_probe = None
    _kv_probe = None
    _lane_probe = None
    _batch_wrapper = None
    _watchdog = None
    #: (registry, collector fn) pair for the usage meter's render-time
    #: collector — detached on teardown (the registry outlives the server;
    #: a still-registered collector would pin the meter and keep stale
    #: tenant series on /metrics)
    _usage_collector = None
    #: graceful-drain latch (docs/reliability.md "Serving resilience"):
    #: once set, new completion POSTs answer 503 while in-flight streams
    #: run to completion — flipped by drain(), read lock-free in do_POST
    #: (a stale read only delays the refusal by one request)
    draining = False
    health = None

    def drain(self, grace_deadline_s: float = 30.0) -> bool:
        """Graceful drain state machine: (1) stop admitting — the latch
        above 503s new completions and ``/healthz`` flips to ``draining``
        so the router sheds this replica; (2) finish in-flight streams,
        bounded by ``grace_deadline_s``; (3) stop serving.  Returns True
        when every in-flight request finished inside the grace window
        (zero 5xx to drained clients), False when the deadline cut the
        wait short.  Call from any thread EXCEPT a handler thread
        (``shutdown()`` would deadlock waiting on serve_forever)."""
        self.draining = True
        if self.health is not None:
            self.health.set_draining(True)
        deadline = time.monotonic() + max(0.0, float(grace_deadline_s))
        clean = True
        while self.slo.inflight() > 0:
            if time.monotonic() >= deadline:
                clean = False
                break
            time.sleep(0.05)
        self.shutdown()
        return clean

    def shutdown(self):
        super().shutdown()
        self._stop_obs()

    def server_close(self):
        super().server_close()
        self._stop_obs()

    def _stop_obs(self):
        obs, self._obs_server = self._obs_server, None
        if obs is not None:
            obs_exporter.stop_server(obs)
        wd, self._watchdog = self._watchdog, None
        if wd is not None:
            wd.stop()
        probe, self._slo_probe = self._slo_probe, None
        if probe is not None:
            self.slo.clear_queue_probe(probe)
        kv, self._kv_probe = self._kv_probe, None
        if kv is not None:
            self.slo.clear_kv_blocks_probe(kv)
        lane, self._lane_probe = self._lane_probe, None
        if lane is not None:
            self.slo.clear_lane_probe(lane)
        w, self._batch_wrapper = self._batch_wrapper, None
        if w is not None:
            try:  # detach the occupancy sinks: registry outlives the server
                w.set_batch_observer(None)
                if hasattr(w, "set_step_observer"):
                    w.set_step_observer(None)
            except Exception:  # noqa: BLE001
                pass
        uc, self._usage_collector = self._usage_collector, None
        if uc is not None:
            reg, fn = uc
            try:
                reg.unregister_collector(fn)
            except Exception:  # noqa: BLE001
                pass


def serve(cfg: Config, params: dict, host: str = "127.0.0.1",
          port: int = 8000, background: bool = False, api=None,
          registry=None, obs_port: typing.Optional[int] = None):
    """``api`` (tests) substitutes a prebuilt endpoint object; ``registry``
    overrides the process-default obs registry the request log records to.
    When ``cfg.obs_port`` is set — or ``obs_port`` is passed explicitly
    (0 = ephemeral, for tests/bench) — a /metrics + /healthz exporter runs
    alongside, its ``/healthz`` carrying the ``slo`` summary block, and is
    torn down with the returned server (docs/observability.md).

    Every request gets an id and a phase-attributed SLO record
    (parse -> queue wait -> prefill -> decode -> respond, serve/slo.py);
    a completion whose engine-queue wait exceeds
    ``cfg.serve_queue_deadline_s`` (or that arrives past
    ``serve_queue_limit``) is answered 503 with a Retry-After hint instead
    of hanging."""
    api = api if api is not None else RestAPI(cfg, params)
    endpoints = getattr(api, "ENDPOINTS", RestAPI.ENDPOINTS)
    req_count, req_latency = request_metrics(registry)
    serve_slo = slo_mod.ServeSLO(registry)
    wrapper = getattr(api, "wrapper", None)
    # one bound-method object, installed AND remembered: clear_queue_probe
    # compares by identity, and each `wrapper.queue_depth` access makes a
    # fresh bound method
    slo_probe = (wrapper.queue_depth
                 if wrapper is not None and hasattr(wrapper, "queue_depth")
                 else None)
    if slo_probe is not None:
        serve_slo.set_queue_probe(slo_probe)
    # continuous-batching hooks: the engine samples lane occupancy into
    # hbnlp_serve_batch_size each decode step and exposes the KV pool's
    # free-block level; both detach with the server (probe pinning hazard,
    # see _ApiServer)
    kv_probe = (wrapper.kv_blocks_free
                if wrapper is not None and hasattr(wrapper, "kv_blocks_free")
                else None)
    if kv_probe is not None:
        serve_slo.set_kv_blocks_probe(kv_probe)
    if wrapper is not None and hasattr(wrapper, "set_batch_observer"):
        wrapper.set_batch_observer(serve_slo.observe_batch)
    # token-level hooks (docs/observability.md "Streaming and inter-token
    # latency"): the engine's per-iteration phase decomposition, the live
    # lane-occupancy gauge, the Retry-After lane divisor, and — when a
    # serving trace is configured — the request span trails routed onto
    # the engine's tracer so one Chrome trace holds request anatomy,
    # decode phases, and lane timelines
    lane_probe = (wrapper.active_lanes
                  if wrapper is not None and hasattr(wrapper, "active_lanes")
                  else None)
    if lane_probe is not None:
        serve_slo.set_lane_probe(lane_probe)
    if wrapper is not None and hasattr(wrapper, "set_step_observer"):
        wrapper.set_step_observer(serve_slo.observe_step)
    if wrapper is not None and hasattr(wrapper, "lane_count"):
        serve_slo.set_lane_count(wrapper.lane_count())
    # -- tracing + flight recorder + SLO alerting (docs/observability.md
    # "Request tracing" / "Flight recorder" / "SLO alerting").  One shared
    # SpanTracer carries request trails, engine phases, and lane timelines:
    # the engine's own (serve_trace_path) when it made one, else a fresh
    # ring sized by flight_buffer_spans handed TO the engine so its spans
    # land in the same trace GET /debugz/trace serves.
    cap = (int(getattr(cfg, "flight_buffer_spans", 0) or 0)
           if cfg is not None else 0)
    engine = getattr(api, "engine", None)
    tracer = getattr(engine, "tracer", None)
    if tracer is None and cap > 0:
        tracer = spans.SpanTracer(max_events=cap)
        if engine is not None and hasattr(engine, "tracer"):
            # the scheduler thread only READS this attribute; assignment
            # happens here, before any request reaches the engine
            engine.tracer = tracer
    if tracer is not None:
        serve_slo.tracer = tracer
    flight = None
    alerts = None
    if cap > 0 and cfg is not None:
        from ..obs import fleet
        from ..obs.flight import FlightRecorder
        from ..train.metrics import config_hash
        try:
            chash = config_hash(cfg)
        except Exception:  # noqa: BLE001 - hash is evidence, not a gate
            chash = ""
        flight = FlightRecorder(
            max_spans=cap,
            triggers=tuple(getattr(cfg, "flight_dump_triggers",
                                   ("watchdog", "error", "slo", "manual"))),
            model_path=str(getattr(cfg, "model_path", "") or ""),
            config_hash=chash,
            identity=fleet.identity(cfg),
            registry=registry if registry is not None else REGISTRY)
        flight.tracer = tracer
    objectives = (dict(getattr(cfg, "slo_objectives", {}) or {})
                  if cfg is not None else {})
    if objectives:
        from ..obs.slo_alerts import SLOAlerts
        on_alert = None
        if flight is not None and flight.wants("slo"):
            def on_alert(key, info, _flight=flight):
                _flight.dump("slo", extra={"alert": info})
        alerts = SLOAlerts(objectives,
                           registry=(registry if registry is not None
                                     else REGISTRY), on_alert=on_alert)
        if flight is not None:
            flight.set_alerts_probe(alerts.summary)
    # -- replica liveness (docs/reliability.md "Serving resilience"):
    # EngineHealth turns the scheduler's iteration stamps into the
    # /healthz status the router health-gates on — stalled (503: a decode
    # iteration outlived watchdog_factor x its EMA), draining (SIGTERM
    # grace drain), or ok.  The serialized InterfaceWrapper path carries
    # no iteration stamps, so its health only ever reports ok/draining.
    health = None
    watchdog = None
    # no wrapper (stub APIs) → no liveness to attest: /healthz stays
    # "metrics-only" rather than claiming an engine is alive
    if cfg is not None and wrapper is not None:
        health = slo_mod.EngineHealth(
            factor=float(getattr(cfg, "watchdog_factor", 0.0) or 0.0),
            min_stall_s=float(getattr(cfg, "serve_watchdog_min_stall_s",
                                      1.0)))
        if wrapper is not None and hasattr(wrapper, "set_health"):
            wrapper.set_health(health)
            if health.factor > 0:
                # the watchdog thread only pays for evidence (stall
                # counter + flight bundle); detection is EngineHealth's
                watchdog = slo_mod.ServeWatchdog(
                    health, flight=flight,
                    registry=registry if registry is not None else REGISTRY)
                watchdog.start()
    # -- per-tenant usage metering (docs/observability.md "Usage metering
    # & capacity"): every finalized request lands in the meter's bounded
    # top-K accounts, rendered onto /metrics through the registry's
    # collector hook and onto /healthz as the `usage` block.  The flops
    # price sheet is traced once at startup (static step costs — the same
    # analytic counter graftcost uses); usage_top_k=0 turns it all off.
    meter = None
    tenant_header = (str(getattr(cfg, "usage_tenant_header", "X-Tenant")
                         or "X-Tenant") if cfg is not None else "X-Tenant")
    usage_top_k = (int(getattr(cfg, "usage_top_k", 0) or 0)
                   if cfg is not None else 0)
    if usage_top_k > 0:
        from ..obs import usage as usage_mod
        pricing = (usage_mod.price_serve_executables(cfg, params)
                   if params is not None else None)
        try:
            from ..analysis.cost_model import serve_capacity_ceiling
            capacity = serve_capacity_ceiling()
        except Exception:  # noqa: BLE001 - the ceiling is evidence
            capacity = None
        meter = usage_mod.UsageMeter(usage_top_k, capacity=capacity,
                                     pricing=pricing)
        usage_registry = registry if registry is not None else REGISTRY
        usage_registry.register_collector(meter.prom_lines)
        if flight is not None:
            flight.set_usage_probe(meter.summary)

    class Handler(BaseHTTPRequestHandler):
        #: in-flight record for the correlation-header hook (end_headers);
        #: reset per request — the handler instance outlives one request
        _rec = None
        _wall_recv = 0.0

        def end_headers(self):
            # one choke point every response path funnels through
            # (send_error included): echo the correlation id + the wall
            # clocks graftload pairs into its clock-offset estimate
            rec = self._rec
            if rec is not None and rec.xid:
                self.send_header("X-Request-Id", rec.xid)
                self.send_header("X-Server-Recv-S",
                                 f"{self._wall_recv:.6f}")
                self.send_header("X-Server-Send-S", f"{time.time():.6f}")
            super().end_headers()

        def do_POST(self):
            if self.path.rstrip("/") == "/debugz/dump":
                self._rec = None
                self._debugz_dump()
                return
            self._wall_recv = time.time()
            name = self.path.strip("/")
            known = name in endpoints
            label = f"/{name}" if known else "other"
            rec = serve_slo.begin(label)
            rec.xid = _request_xid(self.headers)
            rec.tenant = _request_tenant(self.headers, tenant_header)
            self._rec = rec
            prev = slo_mod.set_current(rec)
            status = 500
            try:
                if not known:
                    status = 404
                    self.send_error(404)
                    return
                if name in ("token_completion", "completion"):
                    if getattr(self.server, "draining", False):
                        # graceful drain: in-flight streams finish, new
                        # completions get a clean retryable refusal — the
                        # router stopped sending here the moment /healthz
                        # flipped to draining, so this only catches the
                        # poll-gap race (and a racer's 503 lands before any
                        # body byte, squarely in the failover window)
                        status = 503
                        payload = json.dumps(
                            {"error": "draining: replica is shutting down",
                             "retry_after_s": 1.0}).encode()
                        self.send_response(503)
                        self.send_header("Retry-After", "1")
                        self.send_header("Content-Type", "application/json")
                        self.send_header("Content-Length",
                                         str(len(payload)))
                        self.end_headers()
                        self.wfile.write(payload)
                        return
                    # chaos (reliability/faults.py `replica` site, polled
                    # once per completion request): `die` hard-kills this
                    # replica mid-request — the router observes the dropped
                    # connection; `wedge_healthz` hangs the health snapshot
                    # so only the router's poll TIMEOUT can catch it
                    for action in faults.take("replica"):
                        if action == "die":
                            os._exit(1)
                        elif (action == "wedge_healthz"
                              and health is not None):
                            health.wedge()
                try:
                    length = int(self.headers.get("Content-Length", 0))
                    body = json.loads(self.rfile.read(length) or b"{}")
                    rec.mark_parsed()
                    stream_fn = (
                        getattr(api, name + "_stream", None)
                        if body.get("stream")
                        and name in getattr(api, "STREAM_ENDPOINTS", ())
                        and getattr(api, "streaming", True) else None)
                    if stream_fn is not None:
                        # SSE: the buffered path below stays byte-identical
                        # — this branch only exists when the client asked
                        status = self._stream_sse(stream_fn, body, name)
                        return
                    with spans.span(f"serve/{name}"):
                        result = getattr(api, name)(body)
                    payload = json.dumps(result).encode()
                    status = 200
                    self.send_response(200)
                    self.send_header("Content-Type", "application/json")
                    self.send_header("Content-Length", str(len(payload)))
                    self.end_headers()
                    self.wfile.write(payload)
                except QueueDeadlineExceeded as e:
                    # the engine queue is the serialization bottleneck this
                    # module measures; when it exceeds the configured
                    # deadline the client gets a retryable answer, not a hang
                    status = 503
                    retry = serve_slo.retry_after_s(e.deadline_s)
                    payload = json.dumps(
                        {"error": str(e), "retry_after_s": retry}).encode()
                    self.send_response(503)
                    self.send_header("Retry-After", str(retry))
                    self.send_header("Content-Type", "application/json")
                    self.send_header("Content-Length", str(len(payload)))
                    self.end_headers()
                    self.wfile.write(payload)
                except Exception as e:
                    status = 500
                    self.send_error(500, str(e))
            finally:
                slo_mod.set_current(prev)
                # structured per-request record: registry metrics + a
                # debug-level log line, quiet on stdout by default; finish()
                # closes the SLO record (phase histograms + span trail)
                dt = time.perf_counter() - rec.t_arrival
                req_count.labels(method="POST", path=label,
                                 status=str(status)).inc()
                req_latency.labels(path=label).observe(dt)
                serve_slo.finish(rec, status)
                if meter is not None:
                    try:  # at-most-once: finalize() guards re-entry itself
                        meter.finalize(rec, status)
                    except Exception:  # noqa: BLE001 - metering must not 500
                        pass
                if flight is not None:
                    try:
                        trail = flight.observe_request(rec)
                        if status >= 500 and flight.wants("error"):
                            flight.dump("error",
                                        extra={"request": trail})
                    except Exception:  # noqa: BLE001 - evidence, not a gate
                        pass
                if alerts is not None:
                    try:
                        alerts.observe(status=status, ttft_s=rec.ttft_s(),
                                       e2e_s=rec.e2e_s(),
                                       queue_wait_s=rec.queue_wait_s())
                    except Exception:  # noqa: BLE001 - alerting must not 500
                        pass
                LOG.debug("request id=%d xid=%s tenant=%s method=POST "
                          "path=%s status=%d latency_ms=%.1f", rec.rid,
                          rec.xid or "-", rec.tenant or "-", label, status,
                          dt * 1e3)

        def _send_json(self, status: int, payload: dict) -> None:
            data = json.dumps(payload, default=str).encode()
            self.send_response(status)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)

        def _debugz_dump(self) -> None:
            """``POST /debugz/dump``: force a manual incident bundle to
            disk and return it inline (``graftwatch --dump`` validates the
            inline copy without filesystem access to the server)."""
            if flight is None:
                self.send_error(404, "flight recorder disabled "
                                     "(flight_buffer_spans=0)")
                return
            from ..obs import flight as flight_mod
            path = flight.dump("manual", force=True)
            doc = flight.bundle("manual")
            self._send_json(200, {
                "path": path, "bundle": doc,
                "problems": flight_mod.validate_bundle(doc)})

        def do_GET(self):
            # debug surfaces only — /metrics and /healthz live on the obs
            # exporter's port; these need the live tracer/recorder closure
            self._rec = None
            path = self.path.split("?", 1)[0].rstrip("/")
            if path == "/debugz/trace":
                if tracer is None:
                    self.send_error(404, "no span tracer (set "
                                         "flight_buffer_spans or "
                                         "serve_trace_path)")
                    return
                self._send_json(200, tracer.chrome_trace())
            elif path == "/debugz/flight":
                if flight is None:
                    self.send_error(404, "flight recorder disabled "
                                         "(flight_buffer_spans=0)")
                    return
                self._send_json(200, flight.status())
            else:
                self.send_error(404)

        def _stream_sse(self, stream_fn, body: dict, name: str) -> int:
            """Drain a streaming endpoint as Server-Sent Events.  The
            generator is PRIMED before any header goes out (admission
            shedding / queue-deadline still answer a clean 503 via the
            caller's except); after the first chunk the response is
            committed — a mid-stream engine failure is delivered as a
            final ``error`` event on the open stream, and a client
            disconnect (the routine SSE ending) is absorbed here: headers
            are already on the wire, so letting it escape would make
            do_POST stack a 500 status line onto a committed 200."""
            with spans.span(f"serve/{name}", stream=True):
                gen = stream_fn(body)
                first = next(gen)
                self.send_response(200)
                self.send_header("Content-Type", "text/event-stream")
                self.send_header("Cache-Control", "no-cache")
                self.send_header("Connection", "close")
                self.end_headers()
                try:
                    self._sse_event(first)
                    for event in gen:
                        self._sse_event(event)
                except OSError as e:  # client went away mid-stream
                    # reclaim promptly: flag the request cancelled so the
                    # scheduler's next reap pass frees the lane and its KV
                    # blocks for queued work instead of decoding the
                    # abandoned stream to completion
                    cancel = getattr(gen, "cancel", None)
                    if cancel is not None:
                        cancel()
                        # the usage finalize in do_POST's finally closes
                        # this request's books the moment we return; wait
                        # (bounded) for the reap to settle block-seconds
                        # onto the record so the abandoned stream is still
                        # billed the KV capacity it actually held
                        rec = self._rec
                        if meter is not None and rec is not None:
                            deadline = time.monotonic() + 10.0
                            while (rec.kv_block_seconds is None
                                   and time.monotonic() < deadline):
                                time.sleep(0.01)
                    LOG.debug("SSE client disconnected: xid=%s %s",
                              self._rec.xid or "-" if self._rec else "-", e)
                except Exception as e:  # noqa: BLE001 - headers are out
                    try:
                        self._sse_event(
                            {"error": f"{type(e).__name__}: {e}"[:200]})
                    except OSError:  # disconnected while failing: give up
                        LOG.debug("SSE client gone before error event: "
                                  "xid=%s",
                                  self._rec.xid or "-" if self._rec else "-")
            return 200

        def _sse_event(self, event: dict) -> None:
            self.wfile.write(b"data: " + json.dumps(event).encode()
                             + b"\n\n")
            self.wfile.flush()

        def log_message(self, fmt, *args):
            # per-request records go through the registry metrics; raw
            # http.server chatter stays at debug level, off stdout
            LOG.debug("%s %s", self.address_string(), fmt % args)

    server = _ApiServer((host, port), Handler)
    server.slo = serve_slo  # tests/bench read summaries off the live server
    server.usage = meter  # per-tenant usage meter (None when top_k=0)
    server._usage_collector = ((registry if registry is not None
                                else REGISTRY, meter.prom_lines)
                               if meter is not None else None)
    server.flight = flight  # incident bundles / debugz surfaces
    server.alerts = alerts  # SLO burn-rate evaluator (None w/o objectives)
    server.tracer = tracer  # the shared serving span ring
    server.health = health  # replica liveness (router health gate + drain)
    server._watchdog = watchdog
    server._slo_probe = slo_probe
    server._kv_probe = kv_probe
    server._lane_probe = lane_probe
    server._batch_wrapper = (wrapper if wrapper is not None
                             and hasattr(wrapper, "set_batch_observer")
                             else None)
    eff_obs = (obs_port if obs_port is not None
               else (getattr(cfg, "obs_port", 0) if cfg is not None else 0))
    if obs_port is not None or eff_obs:
        try:
            from ..obs import fleet
            server._obs_server = obs_exporter.start_server(
                eff_obs, registry=registry if registry is not None
                else REGISTRY, health=health,
                slo_probe=serve_slo.summary,
                identity=fleet.identity(cfg),
                alerts_probe=(alerts.summary if alerts is not None
                              else None),
                usage_probe=(meter.summary if meter is not None
                             else None))
        except OSError:
            server.server_close()  # don't leak the bound REST socket
            raise
    if background:
        thread = threading.Thread(target=server.serve_forever, daemon=True)
        thread.start()
        return server
    try:
        server.serve_forever()
    finally:
        server._stop_obs()
