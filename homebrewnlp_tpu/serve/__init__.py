"""Serving layer: completion engine, async wrapper, REPL, REST API, sample
renderers, similarity debug (JAX re-design of /root/reference/src/
interface.py + src/rest_api.py)."""
from .engine import (BatchEngine, BatchInterface,  # noqa: F401
                     use_batch_engine)
from .interface import (ByteTokenizer, CompletionEngine,  # noqa: F401
                        InterfaceWrapper, QueueDeadlineExceeded,
                        tokenizer_for)
from .repl import repl  # noqa: F401
from .rest import RestAPI, serve  # noqa: F401
from .slo import RequestRecord, ServeSLO  # noqa: F401
from .sample import (depatchify, render_text_samples, render_video,  # noqa: F401
                     similarity_score)
