"""Interactive query REPL (the reference's ``query`` run mode,
/root/reference/src/interface.py:177-220): read a prompt, print the
completion, loop."""
from __future__ import annotations

from ..config import Config
from .interface import CompletionEngine


def repl(cfg: Config, params: dict) -> None:
    engine = CompletionEngine(cfg, params)
    print("homebrewnlp_tpu query REPL — empty line to exit")
    while True:
        try:
            prompt = input("> ")
        except (EOFError, KeyboardInterrupt):
            return
        if not prompt:
            return
        print(engine.complete_text(prompt))
