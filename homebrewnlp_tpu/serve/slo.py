"""Per-request serving SLO instrumentation (docs/observability.md
"Serving SLOs").

The REST layer serializes every sampler call behind the engine queue
(faithful to the reference's Manager-queue bridge) — fine for one user, the
bottleneck for many.  Before continuous batching can replace it, that cost
has to be *measured*: every request gets an id and a phase-attributed trail

    parse -> queue_wait -> prefill -> decode -> respond

recorded as a :class:`RequestRecord` whose stamps come from three different
threads (the HTTP handler parses and responds, an ``InterfaceWrapper``
worker runs the engine, a JAX host callback marks the first sampled token).
On completion the record feeds:

- registry histograms — TTFT, queue wait, engine busy, decode tokens/s —
  next to the existing ``hbnlp_serve_request_seconds`` e2e histogram, plus
  the ``hbnlp_serve_inflight`` gauge, all on ``/metrics``;
- the span tracer (``obs/spans.py``), as a per-phase trail tagged with the
  request id, so an ``obs_spans`` capture shows each request's anatomy on
  the Perfetto timeline;
- ``summary()`` — p50/p95/p99 per phase + error rate — mirrored under
  ``/healthz`` ``slo`` (quantiles via the shared bucket-interpolated
  estimator, ``obs.registry.bucket_quantile``).

Phase semantics: **TTFT** is measured from request *arrival* (what a caller
experiences), so it includes parse + queue wait + prefill + the first
decode step.  **queue_wait** is the time between enqueue and an engine
worker claiming the request — the serialization cost this module exists to
expose, split out of the e2e number that used to hide it.  **prefill** is
engine start -> first token; **decode** is first token -> engine done.
"""
from __future__ import annotations

import itertools
import math
import threading
import time
import typing

from ..obs import spans
from ..obs.registry import (DEFAULT_BUCKETS, FINE_LATENCY_BUCKETS, REGISTRY,
                            Histogram, MetricsRegistry, bucket_quantile)
from ..sync import make_lock

#: decode-rate buckets (tokens/second) — latency buckets make no sense here
DECODE_RATE_BUCKETS = (0.5, 1.0, 2.0, 5.0, 10.0, 25.0, 50.0, 100.0, 250.0,
                       500.0, 1000.0, 2500.0, 5000.0, 10000.0)

#: decode-step occupancy buckets (lanes sharing one step) — the
#: continuous-batching engine samples ``hbnlp_serve_batch_size`` here every
#: decode step; a serialized engine never observes it (p50 pinned at
#: "absent", the batching smoke asserts p50 > 1)
BATCH_SIZE_BUCKETS = (1.0, 2.0, 3.0, 4.0, 6.0, 8.0, 12.0, 16.0, 24.0, 32.0,
                      48.0, 64.0)

#: latency buckets for every serving SLO histogram: DEFAULT_BUCKETS
#: resolution below 60 s plus a tail out to 600 s — a serialized engine on
#: a slow host (the committed CPU bench operating point sits past 60 s)
#: must still land in a finite bucket, or every server-side percentile
#: clamps to 60 and serialization overhead becomes clamp error.  Shared
#: with rest.request_metrics: bucket sets are first-registration-wins, so
#: both registration sites must agree.
SERVE_LATENCY_BUCKETS = DEFAULT_BUCKETS + (120.0, 300.0, 600.0)

#: per-token latency buckets (ITL + decode-step wall): the fine-resolution
#: set — a decode step is orders of magnitude below the request-level
#: buckets, and the streaming reconciliation tolerance is one bucket width
ITL_BUCKETS = FINE_LATENCY_BUCKETS

#: the decode-loop phase names the engine attributes each scheduler
#: iteration into (docs/observability.md "Streaming and inter-token
#: latency"); contiguous host segments, so their per-step sum equals the
#: decode-loop wall by construction.  ``prefill`` covers every prefill
#: dispatch in the iteration — the monolithic blocking call, or (under
#: ``serve_prefill_chunk_tokens``) the one asynchronous chunk dispatch
#: interleaved before the decode step, one segment per chunk
STEP_PHASES = ("admit", "prefill", "dispatch", "sync", "sample", "emit")

_REQUEST_IDS = itertools.count(1)
_CURRENT = threading.local()


def allocate_tag() -> int:
    """A fresh callback-routing tag off the request-id sequence, for
    streaming callers with no ambient :class:`RequestRecord` (direct engine
    use).  Shares the sequence so a synthetic tag can never collide with a
    live request id."""
    return next(_REQUEST_IDS)


class RequestRecord:
    """Mutable per-request stamp sheet.  Each ``mark_*`` records a
    ``time.perf_counter`` instant; writers are on different threads but
    each field has exactly one writer, and ``mark_first_token`` keeps the
    FIRST stamp (the JAX callback contract is at-most-once anyway)."""

    __slots__ = ("rid", "xid", "path", "t_arrival", "t_parsed", "t_enqueued",
                 "t_started", "t_first_token", "t_engine_done", "t_finished",
                 "queue_depth", "tokens_generated", "status", "token_times",
                 "tenant", "prompt_tokens", "kv_blocks", "kv_block_seconds",
                 "lane_seconds", "usage_done", "_lock")

    def __init__(self, rid: int, path: str = ""):
        self.rid = rid
        #: correlation id (the client's X-Request-Id, or server-generated):
        #: threads through log lines, span trails, and flight bundles so
        #: one grep follows a request across client and server evidence
        self.xid = ""
        #: validated tenant identity (obs/usage.py::clean_tenant over the
        #: usage_tenant_header value) — rides next to xid through log
        #: lines, span tags, flight trails, and the usage meter
        self.tenant = ""
        #: prompt token count as parsed (set by the endpoint method —
        #: engine-agnostic, unlike tokens_generated which each engine sets)
        self.prompt_tokens: typing.Optional[int] = None
        #: KV accounting, written once by the engine on the lane's exit
        #: path: blocks the allocator granted, blocks x wall held, and
        #: wall occupying a decode lane (admission -> free)
        self.kv_blocks: typing.Optional[int] = None
        self.kv_block_seconds: typing.Optional[float] = None
        self.lane_seconds: typing.Optional[float] = None
        #: at-most-once guard the usage meter test-and-sets under its own
        #: lock (obs/usage.py::UsageMeter.finalize)
        self.usage_done = False
        self.path = path
        self.t_arrival = time.perf_counter()
        self.t_parsed: typing.Optional[float] = None
        self.t_enqueued: typing.Optional[float] = None
        self.t_started: typing.Optional[float] = None
        self.t_first_token: typing.Optional[float] = None
        self.t_engine_done: typing.Optional[float] = None
        self.t_finished: typing.Optional[float] = None
        self.queue_depth: typing.Optional[int] = None
        self.tokens_generated: typing.Optional[int] = None
        self.status: typing.Optional[int] = None
        #: emission instants — one per token-row the engine made visible
        #: (the batch engine stamps every decode step that generated for
        #: this request; a streaming serialized sampler stamps per row; a
        #: non-streaming serialized request records none — its tokens only
        #: became visible at completion)
        self.token_times: typing.List[float] = []
        # the first-token stamp races two writers (the graph's TTFT
        # callback thread vs the engine's emit pass calling mark_token) —
        # "first stamp wins" needs the check-and-set atomic; instances
        # share the declared name, which the recorder merges by design
        self._lock = make_lock("serve.slo.RequestRecord._lock")

    # -- stamps (one writer each) -------------------------------------------
    def mark_parsed(self) -> None:
        self.t_parsed = time.perf_counter()

    def mark_enqueued(self, queue_depth: typing.Optional[int] = None) -> None:
        self.t_enqueued = time.perf_counter()
        self.queue_depth = queue_depth

    def mark_started(self) -> None:
        self.t_started = time.perf_counter()

    def mark_first_token(self, token: typing.Optional[int] = None) -> None:
        # first stamp wins; `token` (the sampled id) is accepted so the
        # engine dispatcher can hand the callback straight through
        with self._lock:
            if self.t_first_token is None:
                self.t_first_token = time.perf_counter()

    def mark_token(self, t: typing.Optional[float] = None) -> None:
        """Stamp one token-row emission (the engine's writer thread, or a
        streaming sampler's callback thread).  The first stamp doubles as
        a first-token stamp for engines without the in-graph TTFT
        callback."""
        now = time.perf_counter() if t is None else t
        with self._lock:
            self.token_times.append(now)
            if self.t_first_token is None:
                self.t_first_token = now

    def itl_gaps(self) -> typing.List[float]:
        """Client-visible inter-token gaps: the deltas between consecutive
        emission stamps.  One emission (or none) yields no gaps — a
        serialized non-streaming completion has no token-level cadence to
        report."""
        with self._lock:
            ts = list(self.token_times)
        return [max(0.0, ts[i] - ts[i - 1]) for i in range(1, len(ts))]

    def mark_engine_done(self) -> None:
        self.t_engine_done = time.perf_counter()

    def mark_finished(self, status: int) -> None:
        self.t_finished = time.perf_counter()
        self.status = int(status)

    # -- derived phase durations (None until both stamps exist) -------------
    @staticmethod
    def _dt(t0, t1) -> typing.Optional[float]:
        return None if t0 is None or t1 is None else max(0.0, t1 - t0)

    def e2e_s(self):
        return self._dt(self.t_arrival, self.t_finished)

    def parse_s(self):
        return self._dt(self.t_arrival, self.t_parsed)

    def queue_wait_s(self):
        return self._dt(self.t_enqueued, self.t_started)

    def ttft_s(self):
        with self._lock:
            t1 = self.t_first_token
        return self._dt(self.t_arrival, t1)

    def prefill_s(self):
        with self._lock:
            t1 = self.t_first_token
        return self._dt(self.t_started, t1)

    def decode_s(self):
        with self._lock:
            t0 = self.t_first_token
        return self._dt(t0, self.t_engine_done)

    def engine_s(self):
        return self._dt(self.t_started, self.t_engine_done)

    def decode_tokens_per_sec(self) -> typing.Optional[float]:
        dt = self.decode_s()
        if dt is None or not self.tokens_generated:
            return None
        # the first token belongs to prefill_s; rate covers the rest
        n = self.tokens_generated - 1
        return None if n <= 0 or dt <= 0 else n / dt


# -- TTFT host dispatcher -----------------------------------------------------
#
# The samplers carry their request id as a TRACED int32 tag (one compilation
# serves every request); the graph-side ``jax.debug.callback`` lands here on
# the host, and this table resolves the tag back to the per-request sink.

_TTFT_LOCK = make_lock("serve.slo._TTFT_LOCK")
_TTFT_SINKS: typing.Dict[int, typing.Callable] = {}


def register_first_token(tag: int, sink: typing.Callable) -> None:
    """Route first-token callbacks carrying ``tag`` to ``sink(token)`` until
    unregistered.  Tag 0 is reserved for "no request" (the samplers'
    default) and is never dispatched."""
    with _TTFT_LOCK:
        _TTFT_SINKS[int(tag)] = sink


def unregister_first_token(tag: int) -> None:
    with _TTFT_LOCK:
        _TTFT_SINKS.pop(int(tag), None)


def dispatch_first_token(tag, token) -> None:
    """Host side of the sampler's first-token callback (``infer/sampler.py::
    _fire_first_token``): resolve the traced tag to the registered sink.  An
    unknown tag (request already finished, or a non-serving caller) is a
    no-op — the callback contract is best-effort by design."""
    with _TTFT_LOCK:
        sink = _TTFT_SINKS.get(int(tag))
    if sink is not None:
        sink(int(token))


# -- per-row token dispatcher (streaming on the serialized samplers) ----------
#
# Same traced-tag design as TTFT, firing on EVERY generated row instead of
# just the first (``infer/sampler.py::_fire_token_row``).  The callback is
# UNORDERED — rows may land out of order — so the payload carries the row
# position and the sink (``interface._RowStream``) reorders.

_TOKEN_SINKS: typing.Dict[int, typing.Callable] = {}


def register_token_sink(tag: int, sink: typing.Callable) -> None:
    """Route per-row token callbacks carrying ``tag`` to
    ``sink(pos, tokens)`` until unregistered.  Tag 0 is never dispatched
    (the samplers' "no request" default)."""
    with _TTFT_LOCK:
        _TOKEN_SINKS[int(tag)] = sink


def unregister_token_sink(tag: int) -> None:
    with _TTFT_LOCK:
        _TOKEN_SINKS.pop(int(tag), None)


def dispatch_token_row(tag, pos, row) -> None:
    """Host side of ``_fire_token_row``: resolve the traced tag and hand
    the sink the row index + its token ids.  Unknown tags are no-ops
    (request finished, or a caller that never registered a sink — the
    stream flag is also traced, so un-streamed requests never fire)."""
    with _TTFT_LOCK:
        sink = _TOKEN_SINKS.get(int(tag))
    if sink is not None:
        import numpy as np
        sink(int(pos), [int(t) for t in np.asarray(row).reshape(-1)])


# -- ambient current record (handler thread -> endpoint -> wrapper) ----------

def set_current(rec: typing.Optional[RequestRecord]
                ) -> typing.Optional[RequestRecord]:
    """Install the handler thread's in-flight record; returns the previous
    one.  Endpoint methods and ``InterfaceWrapper.complete`` run on the
    SAME thread as the handler that set it, so no signatures change for
    the record to reach the queue."""
    prev = getattr(_CURRENT, "record", None)
    _CURRENT.record = rec
    return prev


def current() -> typing.Optional[RequestRecord]:
    return getattr(_CURRENT, "record", None)


class ServeSLO:
    """Owns the serving SLO metrics on one registry and turns finished
    :class:`RequestRecord`\\ s into histogram observations + span trails.
    Registration is idempotent (the registry contract), so repeated
    ``serve()`` calls in one process share the series."""

    def __init__(self, registry: typing.Optional[MetricsRegistry] = None):
        reg = registry if registry is not None else REGISTRY
        self.registry: MetricsRegistry = reg
        # guards the inflight count, probe attach/detach (server setup and
        # teardown threads vs the exporter's gauge scrapes) and lane count
        self._lock = make_lock("serve.slo.ServeSLO._lock")
        self._inflight = 0
        self.ttft = reg.histogram(
            "hbnlp_serve_ttft_seconds",
            "request arrival -> first sampled token (parse + queue wait + "
            "prefill + first decode step)", buckets=SERVE_LATENCY_BUCKETS)
        self.queue_wait = reg.histogram(
            "hbnlp_serve_queue_wait_seconds",
            "enqueue -> engine worker claim (the engine-serialization cost)",
            buckets=SERVE_LATENCY_BUCKETS)
        self.engine = reg.histogram(
            "hbnlp_serve_engine_seconds",
            "engine busy time per request (prefill + decode)",
            buckets=SERVE_LATENCY_BUCKETS)
        self.decode_rate = reg.histogram(
            "hbnlp_serve_decode_tokens_per_sec",
            "per-request decode rate after the first token",
            buckets=DECODE_RATE_BUCKETS)
        self.e2e = reg.histogram(
            "hbnlp_serve_request_seconds", "REST request latency",
            labelnames=("path",), buckets=SERVE_LATENCY_BUCKETS)
        self.requests = reg.counter(
            "hbnlp_serve_requests_total", "REST requests served",
            labelnames=("method", "path", "status"))
        reg.gauge("hbnlp_serve_inflight",
                  "requests currently being handled (accepted, not yet "
                  "responded)", fn=self.inflight)
        self._queue_probe: typing.Optional[typing.Callable[[], int]] = None
        reg.gauge("hbnlp_serve_queue_depth",
                  "completion requests waiting on the engine queue",
                  fn=self.queue_depth)
        # continuous-batching observability (docs/observability.md
        # "Continuous batching"): per-decode-step lane occupancy + the KV
        # pool's free-block level — BOTH absent-but-registered under the
        # serialized engine (histogram empty, gauge at the -1 "no pool"
        # sentinel), so scrapers see a stable series set either way
        self.batch_size = reg.histogram(
            "hbnlp_serve_batch_size",
            "active decode lanes per engine step (continuous batching)",
            buckets=BATCH_SIZE_BUCKETS)
        self._kv_blocks_probe: typing.Optional[
            typing.Callable[[], int]] = None
        reg.gauge("hbnlp_serve_kv_blocks_free",
                  "free blocks in the serving KV pool (-1 = no "
                  "block-allocated pool: serialized engine)",
                  fn=self.kv_blocks_free)
        # token-level serving observability (docs/observability.md
        # "Streaming and inter-token latency"): per-token cadence + the
        # decode-loop phase decomposition the batch engine reports each
        # scheduler iteration.  All registered up front so scrapers see a
        # stable series set under either engine.
        self.itl = reg.histogram(
            "hbnlp_serve_itl_seconds",
            "client-visible inter-token latency: gap between consecutive "
            "token-row emissions of one request", buckets=ITL_BUCKETS)
        self.decode_step = reg.histogram(
            "hbnlp_serve_decode_step_seconds",
            "wall time of one continuous-batching scheduler iteration "
            "(admit + prefill + dispatch + sync + sample + emit)",
            buckets=ITL_BUCKETS)
        self.step_phase = reg.counter(
            "hbnlp_serve_step_phase_seconds",
            "decode-loop wall attributed per scheduler phase; the phases "
            "sum to hbnlp_serve_decode_loop_seconds", labelnames=("phase",))
        self.decode_loop = reg.counter(
            "hbnlp_serve_decode_loop_seconds",
            "total wall spent inside decode-loop iterations (excludes idle "
            "waits between requests)")
        self.prefill_stall = reg.counter(
            "hbnlp_serve_prefill_stall_seconds",
            "stalled lane-seconds: BLOCKING admission-prefill wall times "
            "the lanes that held active requests while the scheduler "
            "thread was pinned (the cost of running monolithic prefill on "
            "the decode critical path; chunked prefill dispatches "
            "asynchronously and contributes zero)")
        self._lane_probe: typing.Optional[typing.Callable[[], int]] = None
        reg.gauge("hbnlp_serve_lane_occupancy",
                  "decode lanes currently holding a request (-1 = no "
                  "lane scheduler: serialized engine)",
                  fn=self.lane_occupancy)
        #: concurrent drain width for Retry-After pricing: the batch
        #: engine's lane count (serve_max_batch), 1 on the serialized path
        self._lane_count = 1
        #: optional explicit tracer for request span trails (the serving
        #: trace, serve_trace_path); None falls back to the ambient tracer
        self.tracer: typing.Optional[spans.SpanTracer] = None

    def inflight(self) -> int:
        with self._lock:
            return self._inflight

    def set_queue_probe(self, fn: typing.Callable[[], int]) -> None:
        """Live engine-queue depth source (``InterfaceWrapper``'s queue);
        graftload samples the resulting gauge over time for its queue-depth
        trace."""
        with self._lock:
            self._queue_probe = fn

    def clear_queue_probe(self, fn: typing.Callable[[], int]) -> None:
        """Detach ``fn`` if it is still the installed probe (a probe a
        later server installed stays).  Server teardown calls this: the
        registry's gauge callback otherwise pins probe -> wrapper ->
        engine -> params (the full model weights) for the process
        lifetime."""
        with self._lock:
            if self._queue_probe is fn:
                self._queue_probe = None

    def queue_depth(self) -> int:
        # snapshot under the lock, call outside it: a probe that blocks
        # (dying engine) must not hold up attach/detach or /metrics
        with self._lock:
            probe = self._queue_probe
        if probe is None:
            return 0
        try:
            return int(probe())
        except Exception:  # noqa: BLE001 - a dying queue must not kill /metrics
            return 0

    # -- continuous-batching hooks (serve/engine.py) -------------------------
    def observe_batch(self, n_active: int) -> None:
        """Engine hook: one observation per decode step with the number of
        lanes that shared it."""
        self.batch_size.observe(float(n_active))

    def set_kv_blocks_probe(self, fn: typing.Callable[[], int]) -> None:
        with self._lock:
            self._kv_blocks_probe = fn

    def clear_kv_blocks_probe(self, fn: typing.Callable[[], int]) -> None:
        """Detach ``fn`` if still installed (server teardown — same
        pinning hazard as :meth:`clear_queue_probe`)."""
        with self._lock:
            if self._kv_blocks_probe is fn:
                self._kv_blocks_probe = None

    def kv_blocks_free(self) -> int:
        with self._lock:
            probe = self._kv_blocks_probe
        if probe is None:
            return -1
        try:
            return int(probe())
        except Exception:  # noqa: BLE001 - a dying pool must not kill /metrics
            return -1

    # -- token-level hooks (docs/observability.md "Streaming and
    # inter-token latency") ---------------------------------------------------
    def observe_step(self, wall_s: float,
                     phases: typing.Optional[typing.Dict[str, float]] = None,
                     n_active: int = 0, prefill_stall_s: float = 0.0,
                     stepped: bool = True) -> None:
        """Engine hook, once per scheduler-loop iteration: the iteration's
        wall, its phase decomposition (contiguous host segments — they sum
        to ``wall_s``), and ``prefill_stall_s`` in stalled lane-seconds
        (blocking prefill wall x concurrently-active lanes; zero under
        chunked prefill, whose dispatches never block the thread).
        ``stepped=False`` (an iteration that only admitted or dispatched a
        prefill chunk, never decoded) still feeds the counters but not the
        per-step histogram."""
        if stepped:
            self.decode_step.observe(float(wall_s))
        self.decode_loop.inc(max(0.0, float(wall_s)))
        for phase, dt in (phases or {}).items():
            if dt > 0:
                self.step_phase.labels(phase=phase).inc(float(dt))
        if prefill_stall_s > 0:
            self.prefill_stall.inc(float(prefill_stall_s))

    def set_lane_probe(self, fn: typing.Callable[[], int]) -> None:
        with self._lock:
            self._lane_probe = fn

    def clear_lane_probe(self, fn: typing.Callable[[], int]) -> None:
        """Detach ``fn`` if still installed (server teardown — same
        pinning hazard as :meth:`clear_queue_probe`)."""
        with self._lock:
            if self._lane_probe is fn:
                self._lane_probe = None

    def lane_occupancy(self) -> int:
        with self._lock:
            probe = self._lane_probe
        if probe is None:
            return -1
        try:
            return int(probe())
        except Exception:  # noqa: BLE001 - a dying engine must not kill /metrics
            return -1

    def set_lane_count(self, n: int) -> None:
        """Concurrent drain width for :meth:`retry_after_s` (the batch
        engine's ``serve_max_batch``; the serialized engine stays 1)."""
        with self._lock:
            self._lane_count = max(1, int(n))

    def retry_after_s(self, deadline_s: float = 0.0) -> int:
        """Whole-second Retry-After hint for a shed/timed-out request: the
        current backlog priced at the engine's median busy time (the
        serialized engine drains one request per engine_s), floored at 1s;
        before any engine history exists, the deadline itself.

        Backlog is the LARGER of the two views, never their sum: every
        queued completion's handler is also counted in-flight (it blocks
        in fetch), so adding them would double-count and tell clients to
        back off ~2x longer than the drain actually takes.  inflight − 1
        excludes the rejected request asking for the hint; queue depth
        alone misses the request the engine is executing.

        The backlog drains ``lane_count`` requests at a time (the batch
        engine's ``serve_max_batch`` lanes decode concurrently, set via
        :meth:`set_lane_count`), so the hint divides by it — a batched
        server would otherwise overstate Retry-After by ~the batch
        factor."""
        p50 = self.engine.quantile(0.5)
        backlog = max(self.queue_depth(), self.inflight() - 1, 1)
        with self._lock:
            lanes = self._lane_count
        if p50 is not None and p50 > 0:
            return max(1, int(math.ceil(p50 * backlog / max(1, lanes))))
        return max(1, int(math.ceil(deadline_s))) if deadline_s else 1

    def begin(self, path: str = "") -> RequestRecord:
        with self._lock:
            self._inflight += 1
        return RequestRecord(next(_REQUEST_IDS), path)

    def finish(self, rec: RequestRecord, status: int) -> RequestRecord:
        """Close the record: decrement in-flight, observe every phase whose
        stamps exist, and emit the span trail.  The e2e histogram +
        request counter stay with the REST handler (they predate this
        module and cover non-engine endpoints too)."""
        with self._lock:
            self._inflight = max(0, self._inflight - 1)
        rec.mark_finished(status)
        qw = rec.queue_wait_s()
        if qw is None and rec.t_enqueued is not None:
            # rejected while still QUEUED (deadline 503): its wait ended at
            # the rejection — leaving it out would bias the queue-wait SLO
            # low exactly under the overload it exists to expose.  (A
            # shed-at-admission request never enqueued and records nothing.)
            qw = max(0.0, rec.t_finished - rec.t_enqueued)
        for hist, val in ((self.queue_wait, qw),
                          (self.engine, rec.engine_s()),
                          (self.ttft, rec.ttft_s()),
                          (self.decode_rate, rec.decode_tokens_per_sec())):
            if val is not None:
                hist.observe(val)
        for gap in rec.itl_gaps():
            self.itl.observe(gap)
        self._emit_spans(rec)
        return rec

    def _emit_spans(self, rec: RequestRecord) -> None:
        """The phase trail on the ambient tracer (no-op when obs is off):
        one parent serve/request span + one child per phase that has both
        stamps, all tagged with the request id."""
        tag = {"id": rec.rid, "path": rec.path, "status": rec.status}
        if rec.xid:
            tag["xid"] = rec.xid
        if rec.tenant:
            tag["tenant"] = rec.tenant
        phases = (("serve/request", rec.t_arrival, rec.t_finished),
                  ("serve/parse", rec.t_arrival, rec.t_parsed),
                  ("serve/queue_wait", rec.t_enqueued, rec.t_started),
                  ("serve/prefill", rec.t_started, rec.t_first_token),
                  ("serve/decode", rec.t_first_token, rec.t_engine_done),
                  ("serve/respond", rec.t_engine_done, rec.t_finished))
        tracer = self.tracer
        for name, t0, t1 in phases:
            if t0 is not None and t1 is not None:
                if tracer is not None:
                    tracer.add(name, t0, t1, **tag)
                else:
                    spans.add(name, t0, t1, **tag)

    # -- /healthz summary ----------------------------------------------------
    #: e2e percentiles in the slo block cover only these path children —
    #: the phases (ttft/queue_wait/engine) exist only for completions, and
    #: merging in sub-millisecond /encode//healthz-probe/404 requests would
    #: drag e2e_s below engine_s and make e2e − engine meaningless
    COMPLETION_PATHS = ("/token_completion", "/completion")

    def _completion_e2e_pcts(self) -> typing.Optional[dict]:
        merged: typing.Optional[list] = None
        for path in self.COMPLETION_PATHS:
            snap = self.e2e.snapshot(path=path)
            if snap["count"]:
                counts = snap["counts"]
                merged = (counts if merged is None
                          else [a + b for a, b in zip(merged, counts)])
        if merged is None:
            return None
        out = {}
        for q, key in ((0.5, "p50"), (0.95, "p95"), (0.99, "p99")):
            v = bucket_quantile(self.e2e.buckets, merged, q)
            if v is None:
                return None
            out[key] = round(v, 6)
        return out

    def _pcts(self, hist: Histogram) -> typing.Optional[dict]:
        if hist.count() == 0 and not hist.labelnames:
            return None
        out = {}
        for q, key in ((0.5, "p50"), (0.95, "p95"), (0.99, "p99")):
            v = hist.quantile(q)
            if v is None:
                return None
            out[key] = round(v, 6)
        return out

    def summary(self) -> dict:
        """The /healthz ``slo`` block: request totals, error rate, current
        in-flight depth, and p50/p95/p99 per phase — every percentile via
        the ONE shared bucket-interpolated estimator."""
        total = errors = 0.0
        for labels, n in self.requests.items().items():
            total += n
            try:  # label order is (method, path, status)
                if int(labels[2]) >= 500:
                    errors += n
            except (IndexError, ValueError):
                pass
        loop_s = self.decode_loop.value()
        stall_s = self.prefill_stall.value()
        # probe presence snapshotted under the lock, like the readers; the
        # kv_blocks_free()/lane_occupancy() calls re-snapshot and invoke
        # the probe OUTSIDE it (see those methods)
        with self._lock:
            have_kv = self._kv_blocks_probe is not None
            have_lane = self._lane_probe is not None
        return {
            "requests_total": int(total),
            "error_rate": round(errors / total, 6) if total else None,
            "inflight": self.inflight(),
            "e2e_s": self._completion_e2e_pcts(),
            "ttft_s": self._pcts(self.ttft),
            "queue_wait_s": self._pcts(self.queue_wait),
            "engine_s": self._pcts(self.engine),
            "decode_tokens_per_sec": self._pcts(self.decode_rate),
            # token-level block: None until the first emission/step — the
            # serialized non-streaming path never populates either
            # (parity contract, like batch_size below)
            "itl_s": self._pcts(self.itl) if self.itl.count() else None,
            "decode_step_s": (self._pcts(self.decode_step)
                              if self.decode_step.count() else None),
            "prefill_stall_fraction": (round(stall_s / loop_s, 6)
                                       if loop_s > 0 else None),
            # None until a batching engine serves its first step; the
            # serialized path never populates it (parity contract)
            "batch_size": (self._pcts(self.batch_size)
                           if self.batch_size.count() else None),
            "kv_blocks_free": self.kv_blocks_free() if have_kv else None,
            "lane_occupancy": self.lane_occupancy() if have_lane else None,
        }


class EngineHealth:
    """Serving liveness for the obs exporter's ``/healthz`` (the serving
    twin of the train ``obs/exporter.Health``, docs/reliability.md
    "Serving resilience").

    The continuous-batching scheduler stamps every loop iteration
    (:meth:`iteration_started` when it picks up work,
    :meth:`iteration_completed` when the iteration's books close); a
    STALL is an iteration that began and then outlived
    ``watchdog_factor`` x the EMA iteration time (floored at
    ``min_stall_s``) without completing — a wedged decode dispatch, a
    dead device, an injected ``serve_step:stall``.  An IDLE engine (the
    loop parked on its condition variable between requests) never reads
    as stalled: only an iteration in flight can be late.

    ``snapshot()`` is the exporter's health payload: ``status`` is
    ``stalled`` (healthz answers 503 — the router routes around this
    replica), ``draining`` (SIGTERM grace drain in progress: healthy for
    in-flight clients, shed by the router), or ``ok``.  ``wedge()`` is
    the ``replica:wedge_healthz`` chaos hook — the snapshot hangs, so
    the router's poll TIMEOUT, not a clean error, has to catch it."""

    #: how long a wedged snapshot hangs (bounded so teardown paths and
    #: tests never wait forever; far past any sane health-poll timeout)
    WEDGE_S = 600.0

    def __init__(self, factor: float = 0.0, min_stall_s: float = 1.0,
                 ema_alpha: float = 0.2):
        self.factor = float(factor)
        self.min_stall_s = float(min_stall_s)
        self.ema_alpha = float(ema_alpha)
        self._lock = make_lock("serve.slo.EngineHealth._lock")
        self._ema_s: typing.Optional[float] = None
        self._t_begin: typing.Optional[float] = None
        self._iterations = 0
        self._draining = False
        self._wedged = False

    # -- scheduler-thread stamps ---------------------------------------------
    def iteration_started(self) -> None:
        with self._lock:
            self._t_begin = time.monotonic()

    def iteration_completed(self, wall_s: float) -> None:
        with self._lock:
            self._t_begin = None
            self._iterations += 1
            a = self.ema_alpha
            self._ema_s = (wall_s if self._ema_s is None
                           else (1 - a) * self._ema_s + a * wall_s)

    # -- state flips (handler / drain threads) -------------------------------
    def set_draining(self, draining: bool = True) -> None:
        with self._lock:
            self._draining = bool(draining)

    def draining(self) -> bool:
        with self._lock:
            return self._draining

    def wedge(self) -> None:
        """Arm the ``replica:wedge_healthz`` chaos action: every
        subsequent :meth:`snapshot` hangs for :data:`WEDGE_S` seconds."""
        with self._lock:
            self._wedged = True

    # -- readers (exporter / watchdog threads) -------------------------------
    def stall_threshold_s(self) -> typing.Optional[float]:
        """The current late-iteration bound, or None while the watchdog
        is unarmed (``factor`` 0) or no iteration has completed yet (no
        cadence to scale — the floor alone bounds the first one)."""
        if self.factor <= 0:
            return None
        with self._lock:
            ema = self._ema_s
        if ema is None:
            return self.min_stall_s
        return max(self.factor * ema, self.min_stall_s)

    def stalled(self) -> typing.Optional[float]:
        """Seconds the in-flight iteration is overdue, or None when
        healthy (no iteration in flight, or still under the bound)."""
        bound = self.stall_threshold_s()
        if bound is None:
            return None
        with self._lock:
            t0 = self._t_begin
        if t0 is None:
            return None
        late = time.monotonic() - t0
        return late if late > bound else None

    def snapshot(self) -> dict:
        with self._lock:
            wedged = self._wedged
        if wedged:
            time.sleep(self.WEDGE_S)
        late = self.stalled()
        bound = self.stall_threshold_s()  # before _lock: it takes _lock too
        with self._lock:
            status = ("stalled" if late is not None
                      else ("draining" if self._draining else "ok"))
            return {
                "status": status,
                "iterations": self._iterations,
                "ema_iteration_s": self._ema_s,
                "stall_threshold_s": bound,
                "overdue_s": late,
                "watchdog_factor": self.factor,
            }


class ServeWatchdog(threading.Thread):
    """Poll :class:`EngineHealth` and fire ONCE per stall: count
    ``hbnlp_serve_watchdog_stalls_total`` and write a flight-recorder
    bundle (``reason="watchdog"``) carrying the overdue iteration's
    numbers — then re-arm only after the loop recovers, so a long wedge
    produces one bundle, not one per poll.  Detection itself lives in
    ``EngineHealth.stalled()`` (healthz flips 503 with no thread in the
    loop); this thread only pays for the evidence."""

    def __init__(self, health: EngineHealth, flight=None,
                 registry: typing.Optional[MetricsRegistry] = None,
                 poll_s: float = 0.25):
        super().__init__(daemon=True, name="serve-watchdog")
        self.health = health
        self.flight = flight
        self.poll_s = float(poll_s)
        reg = registry if registry is not None else REGISTRY
        self._stalls = reg.counter(
            "hbnlp_serve_watchdog_stalls_total",
            "decode-loop stalls the serving watchdog detected")
        self._armed = True
        # NB: must not be named _stop -- Thread.join() calls the
        # private Thread._stop() method this would shadow
        self._halt = threading.Event()

    def stop(self) -> None:
        self._halt.set()

    def run(self) -> None:
        while not self._halt.wait(self.poll_s):
            late = self.health.stalled()
            if late is None:
                self._armed = True
                continue
            if not self._armed:
                continue
            self._armed = False
            self._stalls.inc()
            if self.flight is not None and self.flight.wants("watchdog"):
                try:
                    self.flight.dump("watchdog", extra={
                        "why": "decode-loop stall",
                        "overdue_s": late,
                        "health": {k: v for k, v in
                                   self.health.snapshot().items()
                                   if k != "status"}})
                except Exception:  # noqa: BLE001 - evidence, not a gate
                    pass
