"""Sample rendering + debug similarity mode.

- ``render_text_samples``: the text branch of the reference's
  ``gen_sample_fn`` (/root/reference/src/interface.py:101-174) — prints or
  returns decoded continuations.
- ``render_video``: depatchify + write ``.avi`` via OpenCV
  (interface.py:13-98), gated on cv2.
- ``similarity_score``: the reference's ``debug`` run mode
  (interface.py:283-302): N greedy samples from identical prompts must agree;
  the %-agreement is an end-to-end nondeterminism detector.
"""
from __future__ import annotations

import typing

import numpy as np

from ..config import Config


def render_text_samples(tokens: np.ndarray, tokenizer,
                        printer: typing.Callable[[str], None] = print
                        ) -> typing.List[str]:
    outs = []
    for row in np.asarray(tokens):
        text = tokenizer.decode(row.reshape(-1))
        outs.append(text)
        printer(text)
    return outs


def depatchify(cfg: Config, frames: np.ndarray) -> np.ndarray:
    """[t, hp, wp, P*P*C] -> [t, H, W, C] (inverse of the decoder transpose,
    reference interface.py:61-98 / inputs.py:188-191)."""
    t = frames.shape[0]
    p = cfg.patch_size
    frames = frames.reshape(t, cfg.frame_height_patch, cfg.frame_width_patch,
                            p, p, cfg.color_channels)
    # inverse of transpose(1,3,0,2,4): patch dims lead in memory
    frames = frames.reshape(t, p, p, cfg.frame_height_patch,
                            cfg.frame_width_patch, cfg.color_channels)
    frames = frames.transpose(0, 3, 1, 4, 2, 5)
    return frames.reshape(t, cfg.frame_height_patch * p,
                          cfg.frame_width_patch * p, cfg.color_channels)


def render_video(cfg: Config, frames: np.ndarray, path: str,
                 fps: int = 8) -> str:
    import cv2
    imgs = depatchify(cfg, np.asarray(frames, np.float32))
    imgs = np.clip(imgs * 255, 0, 255).astype(np.uint8)
    h, w = imgs.shape[1:3]
    writer = cv2.VideoWriter(path, cv2.VideoWriter_fourcc(*"MJPG"), fps, (w, h))
    for img in imgs:
        writer.write(cv2.cvtColor(img, cv2.COLOR_RGB2BGR))
    writer.release()
    return path


def similarity_score(samples: typing.Sequence[np.ndarray]) -> float:
    """% agreement of supposedly-identical greedy samples (reference
    interface.py:283-302)."""
    base = np.asarray(samples[0])
    agree = [float(np.mean(np.asarray(s) == base)) for s in samples[1:]]
    return float(np.mean(agree)) if agree else 1.0
