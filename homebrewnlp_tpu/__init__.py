"""homebrewnlp_tpu: a TPU-native (JAX/XLA/pjit/pallas) training and inference
framework with the capabilities of ClashLuke/HomebrewNLP-MTF.

See SURVEY.md at the repo root for the structural analysis of the reference
and the mapping from its Mesh-TensorFlow stack to this JAX design.
"""
from .config import Config, ModelParameter  # noqa: F401
from .nd import NT  # noqa: F401

__version__ = "0.1.0"
