"""Central counters/gauges/histograms registry + Prometheus text rendering.

One process-wide ``REGISTRY`` collects everything the framework measures —
step/token counters from the train loop, feeder queue depth and H2D transfer
seconds from ``data/feed.py``, metric-drain latency from
``train/metrics.py``, per-request latency/status from ``serve/rest.py``,
and device ``memory_stats()`` gauges sampled each checkpoint window.  The
exporter (``obs/exporter.py``) renders it at ``/metrics`` in the Prometheus
text exposition format (version 0.0.4), so a stock Prometheus scrape — or a
``curl`` — sees the run the way fleet tooling expects.

Design notes:
- thread-safe (one registry lock + per-metric locks are overkill at this
  update rate; a single registry-level lock covers both).
- idempotent registration: ``registry.counter(name, ...)`` returns the
  existing metric when already registered (train() can run repeatedly in
  one process — tests, notebooks — without double-registration errors).
- gauges accept a ``fn`` callback evaluated at render time, so liveness
  probes (queue depth, EMA step time) cost nothing between scrapes.
"""
from __future__ import annotations

import math
import re
import sys
import threading
import time
import typing

try:
    from ..sync import make_rlock
except ImportError:  # loaded by file path (tools/supervise.py _load_light)
    _sync = (sys.modules.get("homebrewnlp_tpu.sync")
             or sys.modules.get("hbnlp_sync"))
    if _sync is not None:
        make_rlock = _sync.make_rlock
    else:  # truly standalone: plain lock, no recording

        def make_rlock(name: str) -> "threading.RLock":
            return threading.RLock()


_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")

#: exemplars retained per histogram (across all children/buckets) — the
#: flight recorder's tail sampler attaches at most one per (labels,
#: bucket), and insertion-order eviction bounds the rest
EXEMPLAR_CAP = 64

# latency-oriented default buckets (seconds), Prometheus-conventional
DEFAULT_BUCKETS = (0.001, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
                   1.0, 2.5, 5.0, 10.0, 30.0, 60.0)

# fine-resolution latency buckets for PER-TOKEN quantities (inter-token
# latency, decode-step wall): a TPU decode step sits in the hundreds of
# microseconds, below DEFAULT_BUCKETS' first edge — every percentile would
# interpolate inside one bucket and the reconciliation tolerance
# (``bucket_width_at``) would be the whole measurement
FINE_LATENCY_BUCKETS = (0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005,
                        0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0,
                        10.0, 30.0, 60.0)


def bucket_quantile(buckets: typing.Sequence[float],
                    counts: typing.Sequence[float],
                    q: float) -> typing.Optional[float]:
    """Bucket-interpolated quantile over a Prometheus-style histogram — the
    ONE percentile implementation /healthz, graftload and bench share
    (docs/observability.md "Serving SLOs").

    ``buckets`` are the finite upper bounds; ``counts`` are NON-cumulative
    per-bucket observation counts with one trailing entry for the +Inf
    bucket (``len(counts) == len(buckets) + 1``).  Semantics follow
    ``histogram_quantile``: linear interpolation inside the bucket holding
    the target rank (lower edge 0 for the first bucket); a rank landing in
    the +Inf bucket returns the highest finite bound — the estimator can
    never invent a value above what the buckets resolve.  None when the
    histogram is empty."""
    counts = [float(c) for c in counts]
    total = sum(counts)
    if total <= 0:
        return None
    q = min(max(float(q), 0.0), 1.0)
    rank = q * total
    cum = 0.0
    for j, c in enumerate(counts[:-1]):
        prev = cum
        cum += c
        if cum >= rank and c > 0:
            lo = 0.0 if j == 0 else float(buckets[j - 1])
            hi = float(buckets[j])
            return lo + (hi - lo) * (rank - prev) / c
    return float(buckets[-1])  # +Inf bucket: clamp to the last finite edge


def sample_quantile(samples: typing.Sequence[float], q: float
                    ) -> typing.Optional[float]:
    """Exact order-statistic quantile with linear interpolation (numpy's
    default) over raw samples — the client-side arm of the same shared
    percentile surface (graftload computes these over its own wall-clock
    timestamps and reconciles against :func:`bucket_quantile` of the
    server's histogram).  None on an empty sample set."""
    xs = sorted(float(s) for s in samples)
    if not xs:
        return None
    q = min(max(float(q), 0.0), 1.0)
    pos = q * (len(xs) - 1)
    lo = int(math.floor(pos))
    hi = int(math.ceil(pos))
    if lo == hi:
        return xs[lo]
    return xs[lo] + (xs[hi] - xs[lo]) * (pos - lo)


def merge_histogram_counts(
        parts: typing.Sequence[typing.Tuple[typing.Sequence[float],
                                            typing.Sequence[float]]]
) -> typing.Tuple[typing.Tuple[float, ...], typing.List[float]]:
    """Exact merge of Prometheus-style histogram snapshots from several
    sources (ranks): same finite bucket edges -> element-wise count sum,
    which is LOSSLESS — the merged histogram is exactly what one histogram
    observing every rank's samples would hold, so ``bucket_quantile`` over
    the merge has the same resolution as over any single rank.

    ``parts`` is a sequence of ``(edges, counts)`` pairs with
    NON-cumulative counts and one trailing +Inf entry
    (``len(counts) == len(edges) + 1`` — the :meth:`Histogram.snapshot`
    shape).  Mismatched edges are REJECTED loudly (ValueError): summing
    counts across different bucketings would silently reassign
    observations to wrong value ranges, which is exactly the corruption a
    fleet merge must never hide.  Returns ``(edges, merged_counts)``;
    raises on an empty ``parts``."""
    if not parts:
        raise ValueError("merge_histogram_counts: nothing to merge")
    edges0 = tuple(float(b) for b in parts[0][0])
    merged = [0.0] * (len(edges0) + 1)
    for i, (edges, counts) in enumerate(parts):
        edges = tuple(float(b) for b in edges)
        if edges != edges0:
            raise ValueError(
                f"histogram bucket edges differ between sources (part 0: "
                f"{list(edges0)}, part {i}: {list(edges)}); an exact merge "
                f"is only defined over identical edges")
        if len(counts) != len(edges0) + 1:
            raise ValueError(
                f"part {i}: expected {len(edges0) + 1} counts "
                f"(finite buckets + Inf), got {len(counts)}")
        for j, c in enumerate(counts):
            merged[j] += float(c)
    return edges0, merged


def bucket_width_at(buckets: typing.Sequence[float], value: float) -> float:
    """Width of the histogram bucket a value falls into — the resolution
    floor of any bucket-interpolated quantile at that point, used as the
    reconciliation tolerance (a client-vs-server disagreement smaller than
    one bucket is not measurable by the histogram)."""
    lo = 0.0
    for b in buckets:
        if value <= float(b):
            return float(b) - lo
        lo = float(b)
    return float("inf")  # +Inf bucket: the histogram resolves nothing here


def _fmt(v: float) -> str:
    if v == math.inf:
        return "+Inf"
    f = float(v)
    return str(int(f)) if f == int(f) else repr(f)


def _label_str(names: typing.Tuple[str, ...],
               values: typing.Tuple[str, ...]) -> str:
    if not names:
        return ""
    inner = ",".join(
        '%s="%s"' % (n, str(v).replace("\\", r"\\").replace('"', r'\"')
                     .replace("\n", r"\n"))
        for n, v in zip(names, values))
    return "{" + inner + "}"


class _Metric:
    kind = "untyped"

    def __init__(self, registry: "MetricsRegistry", name: str, help_text: str,
                 labelnames: typing.Tuple[str, ...]):
        self._registry = registry
        self.name = name
        self.help = help_text
        self.labelnames = tuple(labelnames)
        self._children: typing.Dict[tuple, typing.Any] = {}

    def labels(self, **kw) -> "_Metric":
        if set(kw) != set(self.labelnames):
            raise ValueError(f"{self.name}: expected labels "
                             f"{self.labelnames}, got {sorted(kw)}")
        values = tuple(str(kw[n]) for n in self.labelnames)
        with self._registry._lock:
            child = self._children.get(values)
            if child is None:
                child = self._make_child()
                self._children[values] = child
        return _Bound(self, values, child)

    def _default_child(self):
        # unlabelled metrics use the single ()-keyed child
        with self._registry._lock:
            child = self._children.get(())
            if child is None:
                child = self._make_child()
                self._children[()] = child
        return child

    def _make_child(self):
        raise NotImplementedError

    def _render_child(self, values: tuple, child) -> typing.List[str]:
        raise NotImplementedError

    def render(self) -> typing.List[str]:
        lines = [f"# HELP {self.name} {self.help}",
                 f"# TYPE {self.name} {self.kind}"]
        with self._registry._lock:
            items = sorted(self._children.items())
        for values, child in items:
            lines.extend(self._render_child(values, child))
        return lines

    def render_openmetrics(self) -> typing.List[str]:
        """OpenMetrics-flavored family rendering; identical to
        :meth:`render` except where a subclass has exemplars to attach
        (histograms)."""
        return self.render()


class _Bound:
    """A metric bound to one label-value combination."""

    __slots__ = ("_metric", "_values", "_child")

    def __init__(self, metric: _Metric, values: tuple, child):
        self._metric = metric
        self._values = values
        self._child = child

    def inc(self, n: float = 1.0) -> None:
        self._metric._inc(self._child, n)

    def set(self, v: float) -> None:
        self._metric._set(self._child, v)

    def set_function(self, fn: typing.Callable[[], float]) -> None:
        """Render-time callback for THIS label combination (gauges only) —
        a fleet of per-rank series can each expose a live value without a
        poller running between scrapes."""
        self._metric._set_child_fn(self._child, fn)

    def observe(self, v: float) -> None:
        self._metric._observe(self._child, v)


class Counter(_Metric):
    kind = "counter"

    def _make_child(self):
        return [0.0]

    def inc(self, n: float = 1.0) -> None:
        self._inc(self._default_child(), n)

    def _inc(self, child, n: float) -> None:
        if n < 0:
            raise ValueError(f"{self.name}: counters only go up")
        with self._registry._lock:
            child[0] += n

    def value(self, **labels) -> float:
        key = tuple(str(labels[n]) for n in self.labelnames) if labels else ()
        with self._registry._lock:
            child = self._children.get(key)
            return child[0] if child else 0.0

    def items(self) -> typing.Dict[tuple, float]:
        """{label-values tuple: value} snapshot across every child — lets a
        consumer aggregate without knowing the label values in advance
        (e.g. the SLO error rate summing 5xx statuses)."""
        with self._registry._lock:
            return {k: v[0] for k, v in self._children.items()}

    def _render_child(self, values, child):
        return [f"{self.name}{_label_str(self.labelnames, values)} "
                f"{_fmt(child[0])}"]


class Gauge(_Metric):
    kind = "gauge"

    def __init__(self, registry, name, help_text, labelnames,
                 fn: typing.Optional[typing.Callable[[], float]] = None):
        super().__init__(registry, name, help_text, labelnames)
        self._fn = fn

    def set_function(self, fn: typing.Callable[[], float]) -> None:
        """Render-time callback (only valid unlabelled; labelled gauges take
        per-child callbacks via ``labels(...).set_function``)."""
        if self.labelnames:
            raise ValueError(f"{self.name}: metric-level callbacks cannot "
                             "be labelled — use labels(...).set_function")
        self._fn = fn

    def _make_child(self):
        return [0.0, None]  # [value, render-time fn]

    def set(self, v: float) -> None:
        self._set(self._default_child(), v)

    def _set(self, child, v: float) -> None:
        with self._registry._lock:
            child[0] = float(v)
            child[1] = None  # an explicit set supersedes the callback

    def _set_child_fn(self, child, fn: typing.Callable[[], float]) -> None:
        with self._registry._lock:
            child[1] = fn

    @staticmethod
    def _child_value(child) -> float:
        fn = child[1]
        if fn is not None:
            try:
                return float(fn())
            except Exception:
                return math.nan
        return child[0]

    def value(self, **labels) -> float:
        if self._fn is not None:
            return float(self._fn())
        key = tuple(str(labels[n]) for n in self.labelnames) if labels else ()
        with self._registry._lock:
            child = self._children.get(key)
        return self._child_value(child) if child else 0.0

    def render(self) -> typing.List[str]:
        if self._fn is not None:
            try:
                v = float(self._fn())
            except Exception:
                v = math.nan
            return [f"# HELP {self.name} {self.help}",
                    f"# TYPE {self.name} gauge",
                    f"{self.name} {_fmt(v) if v == v else 'NaN'}"]
        return super().render()

    def _render_child(self, values, child):
        v = self._child_value(child)
        return [f"{self.name}{_label_str(self.labelnames, values)} "
                f"{_fmt(v) if v == v else 'NaN'}"]


class Histogram(_Metric):
    kind = "histogram"

    def __init__(self, registry, name, help_text, labelnames,
                 buckets: typing.Sequence[float] = DEFAULT_BUCKETS):
        super().__init__(registry, name, help_text, labelnames)
        self.buckets = tuple(sorted(float(b) for b in buckets))
        # OpenMetrics exemplars: {(label-values, bucket_i): (value,
        # labels, wall_ts)} in insertion order (eviction pops oldest);
        # NEVER rendered on the default Prometheus path — the fleet
        # parser's byte-identical contract holds with or without these
        self._exemplars: typing.Dict[tuple, tuple] = {}

    def _make_child(self):
        # per-bucket counts (non-cumulative) + [sum, count]
        return {"counts": [0] * (len(self.buckets) + 1), "sum": 0.0,
                "count": 0}

    def observe(self, v: float) -> None:
        self._observe(self._default_child(), v)

    def _observe(self, child, v: float) -> None:
        v = float(v)
        i = len(self.buckets)
        for j, b in enumerate(self.buckets):
            if v <= b:
                i = j
                break
        with self._registry._lock:
            child["counts"][i] += 1
            child["sum"] += v
            child["count"] += 1

    def count(self, **labels) -> int:
        key = tuple(str(labels[n]) for n in self.labelnames) if labels else ()
        with self._registry._lock:
            child = self._children.get(key)
            return child["count"] if child else 0

    def snapshot(self, **labels) -> dict:
        """{"counts", "sum", "count"} copy of one child (non-cumulative
        bucket counts, +Inf last) — all zeros when never observed."""
        key = tuple(str(labels[n]) for n in self.labelnames) if labels else ()
        with self._registry._lock:
            child = self._children.get(key)
            if child is None:
                return {"counts": [0] * (len(self.buckets) + 1),
                        "sum": 0.0, "count": 0}
            return {"counts": list(child["counts"]), "sum": child["sum"],
                    "count": child["count"]}

    def quantile(self, q: float, **labels) -> typing.Optional[float]:
        """Bucket-interpolated quantile of one child (:func:`bucket_quantile`
        — the shared implementation).  With labels declared but none given,
        aggregates across every child (the all-paths latency view)."""
        with self._registry._lock:
            if self.labelnames and not labels:
                merged = [0.0] * (len(self.buckets) + 1)
                for child in self._children.values():
                    for i, c in enumerate(child["counts"]):
                        merged[i] += c
                counts = merged
            else:
                key = (tuple(str(labels[n]) for n in self.labelnames)
                       if labels else ())
                child = self._children.get(key)
                if child is None:
                    return None
                counts = list(child["counts"])
        return bucket_quantile(self.buckets, counts, q)

    def attach_exemplar(self, value: float, exemplar_labels: dict,
                        **labels) -> None:
        """Attach an OpenMetrics exemplar (e.g. ``{"request_id": ...}``)
        on the bucket ``value`` falls into for the given label
        combination.  At most one exemplar per (labels, bucket); the
        histogram keeps at most :data:`EXEMPLAR_CAP` total, evicting the
        oldest attachment.  Invisible to the default Prometheus
        rendering — only :meth:`render_openmetrics` shows them."""
        if set(labels) != set(self.labelnames):
            raise ValueError(f"{self.name}: expected labels "
                             f"{self.labelnames}, got {sorted(labels)}")
        values = tuple(str(labels[n]) for n in self.labelnames)
        v = float(value)
        i = len(self.buckets)
        for j, b in enumerate(self.buckets):
            if v <= b:
                i = j
                break
        ex = (v, {str(k): str(x) for k, x in exemplar_labels.items()},
              time.time())
        with self._registry._lock:
            key = (values, i)
            self._exemplars.pop(key, None)  # re-attach moves to newest
            self._exemplars[key] = ex
            while len(self._exemplars) > EXEMPLAR_CAP:
                self._exemplars.pop(next(iter(self._exemplars)))

    def exemplars(self) -> typing.Dict[tuple, tuple]:
        """Snapshot of attached exemplars (tests + graftwatch)."""
        with self._registry._lock:
            return dict(self._exemplars)

    def _render_child(self, values, child, openmetrics: bool = False):
        with self._registry._lock:
            exemplars = ({k[1]: v for k, v in self._exemplars.items()
                          if k[0] == values} if openmetrics else {})
        lines = []
        cum = 0
        for j, (b, c) in enumerate(zip(self.buckets, child["counts"])):
            cum += c
            labels = _label_str(self.labelnames + ("le",),
                                values + (_fmt(b),))
            line = f"{self.name}_bucket{labels} {cum}"
            if j in exemplars:
                ev, elabels, ets = exemplars[j]
                line += (" # " + _label_str(tuple(elabels),
                                            tuple(elabels.values()))
                         + f" {_fmt(ev)} {ets:.3f}")
            lines.append(line)
        cum += child["counts"][-1]
        labels = _label_str(self.labelnames + ("le",), values + ("+Inf",))
        line = f"{self.name}_bucket{labels} {cum}"
        if len(self.buckets) in exemplars:
            ev, elabels, ets = exemplars[len(self.buckets)]
            line += (" # " + _label_str(tuple(elabels),
                                        tuple(elabels.values()))
                     + f" {_fmt(ev)} {ets:.3f}")
        lines.append(line)
        base = _label_str(self.labelnames, values)
        lines.append(f"{self.name}_sum{base} {_fmt(child['sum'])}")
        lines.append(f"{self.name}_count{base} {child['count']}")
        return lines

    def render_openmetrics(self) -> typing.List[str]:
        """Family rendering with exemplar suffixes on bucket lines
        (``... # {request_id="..."} value timestamp``) — the tail-sampled
        slow-request trails the flight recorder attaches."""
        lines = [f"# HELP {self.name} {self.help}",
                 f"# TYPE {self.name} {self.kind}"]
        with self._registry._lock:
            items = sorted(self._children.items())
        for values, child in items:
            lines.extend(self._render_child(values, child,
                                            openmetrics=True))
        return lines


class MetricsRegistry:
    def __init__(self):
        # reentrant: render() holds it while evaluating gauge callbacks,
        # and a callback may legitimately touch the same registry
        self._lock = make_rlock("obs.registry.MetricsRegistry._lock")
        self._metrics: typing.Dict[str, _Metric] = {}
        # render-time collectors: callables returning extra exposition
        # lines, appended after the registered families.  The hook exists
        # for CARDINALITY-BOUNDED sources (obs/usage.py's top-K tenant
        # sketch) — Counter label children are permanent, so an unbounded
        # label set must never pass through labels()
        self._collectors: typing.List[typing.Callable[
            [], typing.Iterable[str]]] = []

    def _get_or_make(self, cls, name: str, help_text: str,
                     labelnames: typing.Tuple[str, ...], **kw) -> _Metric:
        if not _NAME_RE.match(name):
            raise ValueError(f"invalid metric name {name!r}")
        with self._lock:
            m = self._metrics.get(name)
            if m is not None:
                if not isinstance(m, cls):
                    raise ValueError(f"{name} already registered as "
                                     f"{m.kind}, not {cls.kind}")
                if labelnames and m.labelnames != tuple(labelnames):
                    # a DECLARED label-set mismatch would surface later as a
                    # baffling labels() error (or silently split one logical
                    # series); fail at the second registration site instead.
                    # No labels declared = the getter idiom (fetch by name),
                    # always allowed.
                    raise ValueError(
                        f"{name} already registered with labels "
                        f"{m.labelnames}, not {tuple(labelnames)}")
                return m
            m = cls(self, name, help_text, tuple(labelnames), **kw)
            self._metrics[name] = m
            return m

    def counter(self, name: str, help_text: str = "",
                labelnames: typing.Sequence[str] = ()) -> Counter:
        return self._get_or_make(Counter, name, help_text, tuple(labelnames))

    def gauge(self, name: str, help_text: str = "",
              labelnames: typing.Sequence[str] = (),
              fn: typing.Optional[typing.Callable[[], float]] = None
              ) -> Gauge:
        g = self._get_or_make(Gauge, name, help_text, tuple(labelnames))
        if fn is not None:
            g.set_function(fn)
        return g

    def histogram(self, name: str, help_text: str = "",
                  labelnames: typing.Sequence[str] = (),
                  buckets: typing.Sequence[float] = DEFAULT_BUCKETS
                  ) -> Histogram:
        return self._get_or_make(Histogram, name, help_text,
                                 tuple(labelnames), buckets=buckets)

    def get(self, name: str) -> typing.Optional[_Metric]:
        """The registered metric, or None — lets callers reset a callback
        gauge only if it exists (Obs.close)."""
        with self._lock:
            return self._metrics.get(name)

    def register_collector(
            self, fn: typing.Callable[[], typing.Iterable[str]]) -> None:
        """Add a render-time collector: called on every :meth:`render` /
        :meth:`render_openmetrics` OUTSIDE the registry lock (a collector
        takes its own lock; holding both here would pin a lock order the
        collector's owner never agreed to) and expected to return complete
        exposition lines (HELP/TYPE + samples, no trailing newline).
        Idempotent per callable."""
        with self._lock:
            if fn not in self._collectors:
                self._collectors.append(fn)

    def unregister_collector(
            self, fn: typing.Callable[[], typing.Iterable[str]]) -> None:
        """Remove a collector; a no-op when it was never registered —
        shutdown paths detach unconditionally."""
        with self._lock:
            try:
                self._collectors.remove(fn)
            except ValueError:
                pass

    def _collector_lines(self) -> typing.List[str]:
        with self._lock:
            collectors = list(self._collectors)
        lines: typing.List[str] = []
        for fn in collectors:
            try:
                lines.extend(fn())
            except Exception:  # noqa: BLE001 - one bad collector must not
                pass  # take down the whole scrape
        return lines

    def render(self) -> str:
        """Prometheus text exposition (0.0.4): HELP/TYPE headers + samples,
        trailing newline."""
        with self._lock:
            metrics = [self._metrics[k] for k in sorted(self._metrics)]
        lines: typing.List[str] = []
        for m in metrics:
            lines.extend(m.render())
        lines.extend(self._collector_lines())
        return "\n".join(lines) + "\n" if lines else ""

    def render_openmetrics(self) -> str:
        """OpenMetrics-flavored exposition: the same families as
        :meth:`render` plus exemplar suffixes on histogram bucket lines
        and the closing ``# EOF`` marker.  Served by the exporter when a
        scraper asks for ``application/openmetrics-text``; the default
        rendering stays byte-identical whether exemplars exist or not
        (the fleet parser's compatibility contract)."""
        with self._lock:
            metrics = [self._metrics[k] for k in sorted(self._metrics)]
        lines: typing.List[str] = []
        for m in metrics:
            lines.extend(m.render_openmetrics())
        lines.extend(self._collector_lines())
        lines.append("# EOF")
        return "\n".join(lines) + "\n"


#: process-default registry: the train loop, feeder, metric drain, and REST
#: handler all record here unless handed an explicit registry
REGISTRY = MetricsRegistry()
