"""Per-tenant usage metering + capacity accounting for the serving stack.

Every request that enters ``serve/rest.py`` carries a tenant identity (the
``X-Tenant`` header, validated against :data:`TENANT_RE`, ``anon``
fallback); when the request finalizes — success, rejection, error, SSE
disconnect or failover alike, exactly once — the meter folds a
**UsageRecord** into its accounts: prompt/generated token counts,
queue-wait, lane-seconds, KV **block-seconds** (blocks held x wall,
integrated over the engine's lane occupancy), and estimated flops priced
from the cost model's static prefill/decode step costs
(``train/flops.py::jaxpr_flops`` over the serve executables' traces).

Cardinality is bounded by construction: a Misra-Gries (Frequent) heavy-
hitters sketch tracks the top-K tenants with EXACT accumulators and folds
the long tail into ``tenant="other"`` — a 10k-distinct-tenant drill holds
at most K+1 rows in memory and on ``/metrics``.  Accounting invariants:

- **totals are exact and monotonic**: every record lands in exactly one
  row (its own or ``other``), so the sum over all tenant rows equals the
  overall totals to the token.
- **per-tenant rows are fold-monotonic**: a tenant never evicted is exact;
  an evicted tenant's accumulated totals move into ``other`` (the series
  restarts at 0 if it is re-admitted) — consumers taking scrape deltas
  must clamp negatives, and reconciliation against client-side counts is
  exact whenever K covers the live tenant set (graftmeter ``--check``).
- tokens and flops are counted for status-200 completions only (the
  counts the client can verify); block/lane-seconds accrue for every
  admitted request — capacity was consumed whether or not it was billed.

The meter renders its own Prometheus families through the registry's
collector hook (``obs/registry.py::register_collector``) instead of
``Counter.labels`` — label children are permanent, which is exactly the
cardinality leak the sketch exists to prevent.  ``summary()`` feeds the
``/healthz`` ``usage`` block (metered flops/s and tokens/s against the
cost-model ceiling, ``capacity_utilization``, projected saturation
concurrency, per-tenant dominant-resource shares for noisy-neighbor
attribution); :func:`merge_usage` is the router's exact federation of
those blocks across replicas (counters sum, top-K re-folds).
"""
from __future__ import annotations

import collections
import re
import sys
import time
import typing

try:
    from ..sync import make_lock
except ImportError:  # loaded by file path (tools/supervise.py _load_light)
    _sync = (sys.modules.get("homebrewnlp_tpu.sync")
             or sys.modules.get("hbnlp_sync"))
    if _sync is not None:
        make_lock = _sync.make_lock
    else:  # truly standalone: plain lock, no recording
        import threading

        def make_lock(name: str):
            return threading.Lock()


#: legal tenant identities; anything else (or nothing) becomes ANON —
#: the charset is prom-label-safe by construction (no quotes/backslashes)
TENANT_RE = re.compile(r"^[A-Za-z0-9._:-]{1,64}$")

#: the two reserved tenant rows: unauthenticated traffic and the sketch's
#: long-tail fold target — both invalid as CLIENT-supplied identities so
#: they can never collide with a real tenant's exact row
ANON = "anon"
OTHER = "other"

#: resource dimensions a tenant's dominant share is taken over (DRF-style:
#: the max of its shares across dimensions)
_SHARE_DIMS = ("tokens", "kv_block_seconds", "flops")

#: per-tenant accumulator fields; every field sums exactly under folds and
#: under the router's cross-replica merge
_ACC_FIELDS = ("requests", "errors", "prompt_tokens", "generated_tokens",
               "kv_block_seconds", "lane_seconds", "flops",
               "queue_wait_s_sum", "queue_wait_n")

#: (metric family, HELP, value fn) for the collector rendering; tokens get
#: the extra ``kind`` label
_FAMILIES = (
    ("hbnlp_serve_tenant_requests_total",
     "Finalized requests by tenant (top-K exact, tail folds to other)",
     "requests"),
    ("hbnlp_serve_tenant_errors_total",
     "Finalized non-200 requests by tenant", "errors"),
    ("hbnlp_serve_kv_block_seconds_total",
     "KV cache block-seconds held by tenant (blocks x wall while admitted)",
     "kv_block_seconds"),
    ("hbnlp_serve_flops_total",
     "Estimated flops by tenant (cost-model static prefill/decode prices)",
     "flops"),
)

#: samples the rate window retains; each is (perf_counter, flops_total,
#: tokens_total, lane_seconds_total) appended per finalize — bounded
_WINDOW_CAP = 256


def clean_tenant(raw: typing.Optional[str]) -> str:
    """The validated tenant identity for a raw ``X-Tenant`` header value:
    the value itself when it matches :data:`TENANT_RE`, else :data:`ANON`
    (missing, empty, over-long, bad charset, or a reserved name — a client
    cannot claim ``other``'s fold row or spoof ``anon`` into a distinct
    series)."""
    if not raw:
        return ANON
    raw = raw.strip()
    if raw in (ANON, OTHER):
        return ANON
    if not TENANT_RE.match(raw):
        return ANON
    return raw


def _fmt(v: float) -> str:
    f = float(v)
    return str(int(f)) if f == int(f) else repr(f)


def _new_acc() -> dict:
    return {k: 0 for k in _ACC_FIELDS}


def _fold(dst: dict, src: dict) -> None:
    for k in _ACC_FIELDS:
        dst[k] += src[k]


class HeavyHitters:
    """Misra-Gries (Frequent) top-K sketch over tenant names.

    ``admit(name)`` returns ``(tracked, evicted)``: whether ``name`` holds
    a slot after this arrival, plus the names whose slots a decrement
    round just freed (their exact accumulators must fold into ``other``).
    On a miss with a full table every weight drops by 1, zeroed slots are
    evicted, and the newcomer takes a freed slot when one opened — the
    standard Frequent guarantee holds: any tenant with true frequency
    above ``n / (k + 1)`` is tracked, and at most ``k`` slots ever exist.
    NOT thread-safe; the owning :class:`UsageMeter` serializes access."""

    __slots__ = ("k", "weight")

    def __init__(self, k: int):
        self.k = max(1, int(k))
        self.weight: typing.Dict[str, int] = {}

    def admit(self, name: str
              ) -> typing.Tuple[bool, typing.List[str]]:
        w = self.weight
        if name in w:
            w[name] += 1
            return True, []
        if len(w) < self.k:
            w[name] = 1
            return True, []
        evicted = []
        for key in list(w):
            w[key] -= 1
            if w[key] <= 0:
                del w[key]
                evicted.append(key)
        if len(w) < self.k:
            w[name] = 1
            return True, evicted
        return False, evicted


class UsageMeter:
    """The serving process's usage accountant (one per ``serve()``).

    ``finalize(rec, status)`` is the single metering point — called from
    the REST handler's ``finally`` funnel, it is reached exactly once per
    request on every exit path and guards against double-finalization via
    a flag it sets on the record.  ``prom_lines()`` is the registry
    collector; ``summary()`` the ``/healthz`` usage block."""

    def __init__(self, top_k: int = 32,
                 capacity: typing.Optional[dict] = None,
                 pricing: typing.Optional[dict] = None):
        self._lock = make_lock("obs.usage.UsageMeter._lock")
        self._sketch = HeavyHitters(top_k)
        self._tenants: typing.Dict[str, dict] = {}
        self._other = _new_acc()
        self._total = _new_acc()
        self._folds = 0
        self._capacity = dict(capacity) if capacity else None
        self._pricing = dict(pricing) if pricing else None
        self._window: typing.Deque[tuple] = collections.deque(
            maxlen=_WINDOW_CAP)

    # -- metering ------------------------------------------------------------

    def price(self, prompt_tokens: int, generated_tokens: int
              ) -> typing.Optional[float]:
        """Estimated flops for one request under the static price sheet:
        one prefill executable (fixed padded shape — it runs once per
        request regardless of prompt length) plus the marginal per-token
        decode cost (one decode step's flops spread over its lanes and
        token patch).  None when no pricing is loaded (serialized engine,
        non-cache-eligible config)."""
        p = self._pricing
        if not p:
            return None
        return (float(p.get("prefill_flops") or 0.0)
                + float(p.get("decode_flops_per_token") or 0.0)
                * max(0, int(generated_tokens)))

    def finalize(self, rec, status: int) -> bool:
        """Meter one finished request exactly once; returns False when
        ``rec`` was already finalized (the at-most-once guard — SSE
        disconnects and failover retries funnel through the same handler
        ``finally``, and a second call must be a no-op)."""
        with self._lock:
            if getattr(rec, "usage_done", False):
                return False
            try:
                rec.usage_done = True
            except AttributeError:
                pass  # slotted fakes without the field still meter once
            tenant = clean_tenant(getattr(rec, "tenant", "") or "")
            ok = int(status) == 200
            prompt = max(0, int(getattr(rec, "prompt_tokens", 0) or 0))
            gen = max(0, int(getattr(rec, "tokens_generated", 0) or 0))
            try:
                qw = rec.queue_wait_s()
            except Exception:  # noqa: BLE001 - fakes/partial records
                qw = None
            kvbs = float(getattr(rec, "kv_block_seconds", 0.0) or 0.0)
            lane_s = float(getattr(rec, "lane_seconds", 0.0) or 0.0)
            flops = self.price(prompt, gen) if ok else None
            tracked, evicted = self._sketch.admit(tenant)
            for name in evicted:
                acc = self._tenants.pop(name, None)
                if acc is not None:
                    _fold(self._other, acc)
                    self._folds += 1
            if tracked:
                acc = self._tenants.setdefault(tenant, _new_acc())
            else:
                acc = self._other
            for dst in (acc, self._total):
                dst["requests"] += 1
                dst["errors"] += 0 if ok else 1
                if ok:
                    dst["prompt_tokens"] += prompt
                    dst["generated_tokens"] += gen
                    if flops is not None:
                        dst["flops"] += flops
                dst["kv_block_seconds"] += kvbs
                dst["lane_seconds"] += lane_s
                if qw is not None:
                    dst["queue_wait_s_sum"] += float(qw)
                    dst["queue_wait_n"] += 1
            t = self._total
            self._window.append((time.perf_counter(), t["flops"],
                                 t["prompt_tokens"] + t["generated_tokens"],
                                 t["lane_seconds"]))
        return True

    # -- export --------------------------------------------------------------

    def _rows(self) -> typing.List[typing.Tuple[str, dict]]:
        rows = sorted(self._tenants.items())
        if self._other["requests"] > 0:
            rows.append((OTHER, self._other))
        return rows

    def prom_lines(self) -> typing.List[str]:
        """Prometheus text lines for the registry collector hook — one
        bounded family set, at most K+1 ``tenant`` children each."""
        with self._lock:
            rows = [(name, dict(acc)) for name, acc in self._rows()]
        lines: typing.List[str] = []
        lines.append("# HELP hbnlp_serve_tokens_total Metered tokens by "
                     "tenant and kind (status-200 completions only)")
        lines.append("# TYPE hbnlp_serve_tokens_total counter")
        for name, acc in rows:
            for kind, field in (("prompt", "prompt_tokens"),
                                ("generated", "generated_tokens")):
                lines.append(
                    f'hbnlp_serve_tokens_total{{tenant="{name}",'
                    f'kind="{kind}"}} {_fmt(acc[field])}')
        for fam, help_text, field in _FAMILIES:
            lines.append(f"# HELP {fam} {help_text}")
            lines.append(f"# TYPE {fam} counter")
            for name, acc in rows:
                lines.append(f'{fam}{{tenant="{name}"}} {_fmt(acc[field])}')
        return lines

    def _rates(self) -> typing.Optional[dict]:
        if len(self._window) < 2:
            return None
        t0, f0, tok0, lane0 = self._window[0]
        t1, f1, tok1, lane1 = self._window[-1]
        span = t1 - t0
        if span <= 0:
            return None
        return {"window_s": round(span, 3),
                "flops_per_s": (f1 - f0) / span,
                "tokens_per_s": (tok1 - tok0) / span,
                "mean_inflight": (lane1 - lane0) / span}

    def summary(self) -> dict:
        """The ``/healthz`` ``usage`` block (and the unit the router
        federates): exact totals, windowed rates, capacity utilization
        against the cost-model ceiling, and per-tenant attribution."""
        with self._lock:
            totals = dict(self._total)
            rows = [(name, dict(acc)) for name, acc in self._rows()]
            rates = self._rates()
            folds = self._folds
        doc = {"top_k": self._sketch.k,
               "tracked_tenants": sum(1 for n, _ in rows if n != OTHER),
               "folds": folds,
               "totals": totals,
               "rates": rates,
               "pricing": dict(self._pricing) if self._pricing else None,
               "capacity": _capacity_block(self._capacity, rates),
               "per_tenant": _tenant_block(rows, totals)}
        return doc


def _capacity_block(capacity: typing.Optional[dict],
                    rates: typing.Optional[dict]) -> typing.Optional[dict]:
    """Metered load against the static ceiling: ``capacity_utilization``
    is windowed flops/s over the cost model's peak for this replica's
    devices; saturation concurrency projects the mean in-flight depth to
    utilization 1.0 (both None when the ceiling is unknown — CPU hosts
    price no peak)."""
    if not capacity:
        return None
    out = dict(capacity)
    peak = out.get("peak_flops_per_s")
    util = None
    if rates and peak:
        util = rates["flops_per_s"] / float(peak)
    out["capacity_utilization"] = util
    out["projected_saturation_concurrency"] = (
        rates["mean_inflight"] / util
        if util and util > 0 and rates else None)
    return out


def _tenant_block(rows: typing.Sequence[typing.Tuple[str, dict]],
                  totals: dict) -> typing.Dict[str, dict]:
    """Per-tenant attribution rows: exact counters, mean queue-wait (the
    noisy-neighbor symptom) and the DRF-style dominant resource share
    (the noisy-neighbor cause) — max of the tenant's share across tokens,
    KV block-seconds and flops."""
    tot = {"tokens": totals["prompt_tokens"] + totals["generated_tokens"],
           "kv_block_seconds": totals["kv_block_seconds"],
           "flops": totals["flops"]}
    out: typing.Dict[str, dict] = {}
    for name, acc in rows:
        mine = {"tokens": acc["prompt_tokens"] + acc["generated_tokens"],
                "kv_block_seconds": acc["kv_block_seconds"],
                "flops": acc["flops"]}
        share = max((mine[d] / tot[d] for d in _SHARE_DIMS if tot[d] > 0),
                    default=0.0)
        row = {k: acc[k] for k in _ACC_FIELDS}
        row["dominant_share"] = round(share, 6)
        row["queue_wait_mean_s"] = (
            round(acc["queue_wait_s_sum"] / acc["queue_wait_n"], 6)
            if acc["queue_wait_n"] else None)
        out[name] = row
    return out


def merge_usage(blocks: typing.Sequence[typing.Optional[dict]],
                top_k: int = 32) -> typing.Optional[dict]:
    """Exact federation of per-replica ``usage`` blocks (the router's
    fleet view, same discipline as ``obs/fleet.py``'s counter merge):
    totals and per-tenant counters SUM exactly — each replica's rows are
    disjoint accounts of disjoint requests — then the merged tenant set
    re-folds to ``top_k`` (ranked by token volume) so the federated view
    obeys the same cardinality bound as any single replica.  Rates and
    capacity ceilings sum across replicas; utilization is recomputed over
    the summed ceiling.  None when no block is usable."""
    blocks = [b for b in blocks if isinstance(b, dict)
              and isinstance(b.get("totals"), dict)]
    if not blocks:
        return None
    totals = _new_acc()
    tenants: typing.Dict[str, dict] = {}
    folds = 0
    for b in blocks:
        for k in _ACC_FIELDS:
            totals[k] += b["totals"].get(k, 0)
        folds += int(b.get("folds", 0) or 0)
        for name, row in (b.get("per_tenant") or {}).items():
            acc = tenants.setdefault(name, _new_acc())
            for k in _ACC_FIELDS:
                acc[k] += row.get(k, 0)
    other = tenants.pop(OTHER, _new_acc())
    ranked = sorted(tenants.items(),
                    key=lambda kv: (-(kv[1]["prompt_tokens"]
                                      + kv[1]["generated_tokens"]), kv[0]))
    kept = ranked[:max(1, int(top_k))]
    for _, acc in ranked[max(1, int(top_k)):]:
        _fold(other, acc)
        folds += 1
    rows = sorted(kept)
    if other["requests"] > 0:
        rows.append((OTHER, other))
    rates = None
    rate_blocks = [b["rates"] for b in blocks if b.get("rates")]
    if rate_blocks:
        rates = {"window_s": max(r.get("window_s") or 0.0
                                 for r in rate_blocks),
                 "flops_per_s": sum(r.get("flops_per_s") or 0.0
                                    for r in rate_blocks),
                 "tokens_per_s": sum(r.get("tokens_per_s") or 0.0
                                     for r in rate_blocks),
                 "mean_inflight": sum(r.get("mean_inflight") or 0.0
                                      for r in rate_blocks)}
    caps = [b["capacity"] for b in blocks if b.get("capacity")]
    capacity = None
    if caps:
        peaks = [c.get("peak_flops_per_s") for c in caps]
        peak = (sum(p for p in peaks if p) if any(peaks) else None)
        capacity = {"device_kind": caps[0].get("device_kind"),
                    "n_devices": sum(int(c.get("n_devices") or 0)
                                     for c in caps),
                    "peak_flops_per_s": peak}
    return {"replicas": len(blocks),
            "top_k": max(1, int(top_k)),
            "tracked_tenants": sum(1 for n, _ in rows if n != OTHER),
            "folds": folds,
            "totals": totals,
            "rates": rates,
            "capacity": _capacity_block(capacity, rates),
            "per_tenant": _tenant_block(rows, totals)}


def price_serve_executables(cfg, params) -> typing.Optional[dict]:
    """The static flops price sheet for one serve config: trace the
    engine's decode/prefill bodies over their abstract argument shapes
    (``serve/engine.py::abstract_exec_args`` — the exact executables the
    scheduler compiles) and count with the cost model's analytic counter
    (``train/flops.py::jaxpr_flops``).  The decode step price spreads over
    its lanes and token patch into a marginal per-generated-token cost;
    chunked prefill is priced at the monolithic prefill trace (a price,
    not a measurement — the chunk sum is bitwise the same forward).  None
    when the config cannot trace (serialized engine, non-cache-eligible
    stack) — the meter then reports token/block accounts without flops."""
    try:
        import functools

        import jax

        from ..serve import engine as serve_engine
        from ..train.flops import jaxpr_flops
        patch = max(1, int(cfg.token_patch_size))
        rows = int(cfg.sequence_length) // patch
        n_lanes = max(1, int(getattr(cfg, "serve_max_batch", 1)))
        decode_abs, prefill_abs, _ = serve_engine.abstract_exec_args(
            cfg, params, rows, n_lanes)
        dec = functools.partial(serve_engine.decode_body, cfg, rows,
                                n_lanes, None)
        pre = functools.partial(serve_engine.prefill_body, cfg, rows)
        dec_fl = float(jaxpr_flops(jax.make_jaxpr(dec)(*decode_abs)))
        pre_fl = float(jaxpr_flops(jax.make_jaxpr(pre)(*prefill_abs)))
        return {"prefill_flops": pre_fl,
                "decode_step_flops": dec_fl,
                "decode_flops_per_token": dec_fl / n_lanes / patch,
                "rows": rows, "n_lanes": n_lanes, "patch": patch}
    except Exception:  # noqa: BLE001 - pricing is best-effort by contract
        return None
