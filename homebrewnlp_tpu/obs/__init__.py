"""Unified observability: span tracing, metrics registry, health + watchdog.

The three pieces (docs/observability.md):

- :mod:`~homebrewnlp_tpu.obs.spans` — thread-aware host span tracer
  exporting Chrome trace-event JSON (Perfetto-loadable) and mirroring every
  span into ``jax.profiler.TraceAnnotation`` so ``--profile`` captures show
  host and device activity on one timeline.
- :mod:`~homebrewnlp_tpu.obs.registry` — central counters/gauges/histograms
  with Prometheus text rendering (process-default ``REGISTRY``).
- :mod:`~homebrewnlp_tpu.obs.exporter` — background ``/metrics`` +
  ``/healthz`` HTTP server, and the hang watchdog that dumps thread stacks
  + device memory stats to ``<model_path>/diagnostics/`` before a wedged
  run dies opaque.

``Obs.from_config(cfg)`` bundles them per run, gated by the config knobs
``obs_port`` / ``obs_spans`` / ``watchdog_factor`` — all default-off, and
every instrumentation site degrades to a shared no-op, so disabled runs pay
nothing and the synchronous parity path stays bit-identical.
"""
from __future__ import annotations

import os
import typing

from .registry import REGISTRY, MetricsRegistry  # noqa: F401
from .exporter import (Health, Watchdog, device_memory_stats,  # noqa: F401
                       dump_diagnostics, start_server, stop_server)
from .spans import (NULL_SPAN, SpanTracer, get_tracer,  # noqa: F401
                    set_tracer, span, traced)
from . import fleet  # noqa: F401  (stdlib-only; docs/observability.md)


class _HealthPause:
    __slots__ = ("_health", "_reason")

    def __init__(self, health: Health, reason: str):
        self._health = health
        self._reason = reason

    def __enter__(self) -> "_HealthPause":
        self._health.begin_pause(self._reason)
        return self

    def __exit__(self, *exc) -> bool:
        self._health.end_pause()
        return False


class Obs:
    """Per-run observability bundle with an explicit start/close lifecycle.

    ``start()`` installs the ambient span tracer and launches the exporter
    + watchdog threads; ``close()`` exports ``<model_path>/trace.json``,
    stops the threads, and restores the previous ambient tracer.  A fully
    disabled Obs (all knobs at their defaults) is inert: ``enabled`` is
    False and start/close are no-ops."""

    def __init__(self, model_path: str, port: int = 0, spans: bool = False,
                 watchdog_factor: float = 0.0,
                 startup_stall_s: float = 600.0,
                 registry: typing.Optional[MetricsRegistry] = None,
                 fleet_dir: str = "",
                 identity: typing.Optional[dict] = None):
        self.model_path = model_path
        self.port = int(port)
        self.spans_enabled = bool(spans)
        self.watchdog_factor = float(watchdog_factor)
        self.fleet_dir = str(fleet_dir or "")
        self.identity = identity if identity is not None else fleet.identity()
        self.enabled = bool(self.port or self.spans_enabled
                            or self.watchdog_factor or self.fleet_dir)
        self.registry = registry if registry is not None else REGISTRY
        #: cross-rank posting half (docs/observability.md "Fleet
        #: observability"); None outside a fleet — every consumer guards
        self.fleet_reporter: typing.Optional[fleet.FleetReporter] = None
        self.health = Health(stall_factor=self.watchdog_factor or 10.0,
                             startup_stall_s=startup_stall_s) \
            if self.enabled else None
        self.tracer: typing.Optional[SpanTracer] = None
        self.server = None
        self.watchdog: typing.Optional[Watchdog] = None
        self._prev_tracer: typing.Optional[SpanTracer] = None
        self._started = False
        self._steps = None
        self._tokens = None
        # latest graftprof window figures (record_profile): merged into the
        # /healthz utilization payload next to mfu/tokens_per_sec
        self._profile_extra: typing.Dict[str, float] = {}
        self._util_watched = False

    @classmethod
    def from_config(cls, cfg) -> "Obs":
        return cls(model_path=cfg.model_path,
                   port=getattr(cfg, "obs_port", 0),
                   spans=getattr(cfg, "obs_spans", False),
                   watchdog_factor=getattr(cfg, "watchdog_factor", 0.0),
                   startup_stall_s=getattr(cfg, "watchdog_startup_s",
                                           600.0),
                   fleet_dir=fleet.fleet_dir_from(cfg),
                   identity=fleet.identity(cfg))

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> "Obs":
        if not self.enabled or self._started:
            return self
        self._started = True
        if self.spans_enabled:
            self.tracer = SpanTracer()
            self._prev_tracer = set_tracer(self.tracer)
        if self.fleet_dir:
            self.fleet_reporter = fleet.FleetReporter(
                self.fleet_dir, self.identity.get("rank", 0),
                self.identity.get("world_size", 1),
                registry=self.registry)
        self._steps = self.registry.counter(
            "hbnlp_train_steps_total", "optimizer updates dispatched")
        self._tokens = self.registry.counter(
            "hbnlp_train_tokens_total", "tokens consumed by dispatched "
            "updates")
        h = self.health
        self.registry.gauge(
            "hbnlp_last_completed_step",
            "last step whose metrics materialized (drained)",
            fn=lambda: (-1 if h.last_step() is None else h.last_step()))
        self.registry.gauge(
            "hbnlp_step_seconds_ema", "EMA of completed-step wall spacing",
            fn=lambda: h.ema_step_seconds() or 0.0)
        if self.port:
            self.server = start_server(self.port, registry=self.registry,
                                       health=self.health,
                                       identity=self.identity)
        if self.watchdog_factor:
            r = self.fleet_reporter
            self.watchdog = Watchdog(self.health, self.model_path,
                                     factor=self.watchdog_factor,
                                     registry=self.registry,
                                     extra_fn=(r.skew_summary
                                               if r is not None else None))
            self.watchdog.start()
        return self

    def close(self) -> None:
        """Teardown is best-effort per stage: close() runs inside train()'s
        ``finally``, so a failing stage (broken exporter socket, full disk)
        is logged, never raised — raising would mask the exception that
        ended training — and must not skip the later stages (ambient-tracer
        restore and gauge freeze are the process-hygiene guarantees)."""
        if not self._started:
            return
        self._started = False
        import logging
        log = logging.getLogger("homebrewnlp_tpu.obs")
        if self.health is not None:
            self.health.mark_done()
        if self.watchdog is not None:
            try:
                self.watchdog.stop()
            except Exception as e:
                log.warning("watchdog stop failed: %s", e)
            self.watchdog = None
        if self.server is not None:
            try:
                stop_server(self.server)
            except Exception as e:
                log.warning("exporter stop failed: %s", e)
            self.server = None
        if self.tracer is not None:
            set_tracer(self._prev_tracer)
            try:
                self.tracer.export(
                    os.path.join(self.model_path, "trace.json"))
            except Exception as e:
                log.warning("trace.json export failed: %s", e)
            if self.fleet_reporter is not None:
                # the per-rank lane of the merged fleet trace
                self.fleet_reporter.export_trace(self.tracer)
            self.tracer = None
        if self.fleet_reporter is not None:
            try:
                self.fleet_reporter.close()  # final prom snapshot rides this
            except Exception as e:
                log.warning("fleet reporter close failed: %s", e)
            self.fleet_reporter = None
        self._freeze_gauges()

    def _freeze_gauges(self) -> None:
        """Re-point the run's callback gauges at frozen final values: the
        registry is process-global, so leaving closures over this run's
        Health/DeviceFeeder would keep them (and any device batches still
        parked in the feeder queue) alive for the process lifetime, and a
        later scrape (e.g. web_api's exporter) would render dead-run state
        as live."""
        last = self.health.last_step()
        ema = self.health.ema_step_seconds() or 0.0
        self.registry.gauge("hbnlp_last_completed_step",
                            fn=lambda: -1 if last is None else last)
        self.registry.gauge("hbnlp_step_seconds_ema", fn=lambda: ema)
        depth = self.registry.get("hbnlp_feeder_queue_depth")
        if depth is not None:  # only train runs register the feeder probe
            depth.set_function(lambda: 0)
        for name in self._UTIL_GAUGES:
            g = self.registry.get(name)
            if g is None:  # only telemetry-enabled runs register these
                continue
            try:
                final = float(g.value())
            except Exception:
                final = 0.0
            g.set_function(lambda final=final: final)

    def pause(self, reason: str):
        """Context manager declaring an expected no-steps window (checkpoint
        save): /healthz stays healthy and the watchdog holds fire for its
        duration.  No-op when obs is disabled."""
        if not self.enabled:
            return NULL_SPAN
        return _HealthPause(self.health, reason)

    # -- hot-path hooks (all guarded by ``enabled`` at the call site) --------
    def step_dispatched(self, tokens: int) -> None:
        self._steps.inc()
        self._tokens.inc(tokens)

    def watch_feeder(self, feeder) -> None:
        """Register feeder liveness + queue-depth probes (render-time
        callbacks: nothing runs between scrapes)."""
        self.health.set_feeder_probe(feeder.alive)
        self.registry.gauge(
            "hbnlp_feeder_queue_depth",
            "device batches parked in the feeder queue", fn=feeder.qsize)

    #: utilization gauges registered by watch_utilization; frozen on close
    _UTIL_GAUGES = ("hbnlp_mfu", "hbnlp_tokens_per_sec", "hbnlp_goodput",
                    "hbnlp_flops_per_step")

    def watch_utilization(self, writer, util) -> None:
        """Register the live utilization surface (docs/observability.md
        "Device telemetry"): MFU + tokens/s from the writer's most recent
        drained step, goodput (useful step time / wall time), and the static
        per-step FLOPs from the HLO cost analysis.  All render-time
        callbacks; /healthz mirrors them via the Health utilization probe."""
        self.registry.gauge(
            "hbnlp_mfu", "model FLOPs utilization of the last drained step "
            "(HLO cost-analysis flops / wall / peak)",
            fn=lambda: writer.last_rates.get("mfu", 0.0))
        self.registry.gauge(
            "hbnlp_tokens_per_sec", "training throughput of the last "
            "drained step", fn=lambda: writer.last_rates.get(
                "tokens_per_sec", 0.0))
        self.registry.gauge(
            "hbnlp_goodput", "useful step seconds / wall seconds this run",
            fn=writer.goodput)
        self.registry.gauge(
            "hbnlp_flops_per_step", "per-step FLOPs of the compiled train "
            "step (XLA cost analysis)", fn=lambda: util.flops_per_step)
        self._util_watched = True
        self.health.set_utilization_probe(
            lambda: dict(writer.last_rates, goodput=writer.goodput(),
                         **self._profile_extra))

    def record_profile(self, summary) -> None:
        """Publish the most recent graftprof window (docs/observability.md
        "Profile attribution") on the live surfaces:

        - ``hbnlp_step_time_ms{stat=...}`` — the measured ms_per_step
          decomposition (``total``/``mxu``/``hbm``/``comm``/``idle``) plus
          the ``busy``/``wall`` window stats;
        - ``hbnlp_profile_time_fraction{category=...}`` — the same split
          as fractions of the device wall window;
        - ``hbnlp_profile_attributed_fraction{kind=...}`` — how much of
          the device time the capture could attribute (category / scope);
        - ``comm_fraction`` under /healthz ``utilization`` (merged next to
          mfu/tokens_per_sec when telemetry runs, standalone otherwise).

        Plain value gauges (not callbacks): they freeze at their last
        window automatically, so close() needs no special-casing."""
        step_ms = self.registry.gauge(
            "hbnlp_step_time_ms", "graftprof ms-per-step decomposition of "
            "the most recent profile window", labelnames=("stat",))
        d = summary.decomposition_ms_per_step
        for stat in ("total", "mxu", "hbm", "comm", "idle"):
            step_ms.labels(stat=stat).set(float(d.get(stat, 0.0)))
        steps = max(1, summary.n_steps or 1)
        step_ms.labels(stat="busy").set(summary.busy_s * 1e3 / steps)
        step_ms.labels(stat="wall").set(summary.wall_s * 1e3 / steps)
        frac = self.registry.gauge(
            "hbnlp_profile_time_fraction", "per-category fraction of the "
            "device wall window (most recent profile capture)",
            labelnames=("category",))
        for cat, v in summary.fractions.items():
            frac.labels(category=cat).set(float(v))
        attr = self.registry.gauge(
            "hbnlp_profile_attributed_fraction", "device time the capture "
            "attributed to a known category / named scope",
            labelnames=("kind",))
        attr.labels(kind="category").set(summary.attributed_category_frac)
        attr.labels(kind="scope").set(summary.attributed_scope_frac)
        self._profile_extra["comm_fraction"] = float(
            summary.fractions.get("comm", 0.0))
        if not self._util_watched and self.health is not None:
            # no telemetry this run: the profile figures ARE the
            # utilization story /healthz can tell
            self.health.set_utilization_probe(
                lambda: dict(self._profile_extra))

    def sample_device_memory(self) -> None:
        """Refresh per-device memory gauges (called each checkpoint window;
        ``memory_stats()`` can sync, so it stays off the per-step path)."""
        g = self.registry.gauge(
            "hbnlp_device_memory_bytes", "device memory_stats() sampled at "
            "checkpoint windows", labelnames=("device", "stat"))
        for dev, stats in device_memory_stats().items():
            for stat, v in stats.items():
                g.labels(device=dev, stat=stat).set(v)
