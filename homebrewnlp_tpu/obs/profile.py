"""graftprof: device-time attribution from jax.profiler Chrome traces.

The ``--profile`` window (main.py) and bench.py's per-workload probe both
make ``jax.profiler`` write a Chrome-trace JSON
(``<dir>/plugins/profile/<session>/<host>.trace.json.gz``).  The reference
framework stopped there — a human eyeballed the TF profiler dump.  This
module turns the capture into machine-checkable numbers:

- **category bucketing**: device events (HLO op executions) are classified
  as MXU dots, collectives by kind, vector/elementwise fusions,
  copies/data movement, or infeed/outfeed, purely from the HLO op name —
  no sidecar needed.
- **scope attribution**: the model build mirrors the ``nd`` scope stack
  into ``jax.named_scope`` (nd.push_scope), so every compiled HLO
  instruction's ``metadata.op_name`` carries the layer path
  (``jit(step)/jit(main)/jvp(body)/@d0_.../dot_general``).  The kept AOT
  step executable dumps an op→op_name sidecar
  (:data:`OP_MAP_FILENAME`) next to the trace at ``stop_trace`` time, and
  the parser joins trace events against it — per-layer device time
  without a TPU-side dependency.
- **an ms_per_step decomposition** into ``mxu + hbm + comm + idle`` that
  sums to the device wall window, reconciled against graftcost's static
  alpha-beta / roofline estimates (``analysis/cost_model.py``) as
  per-component ``prediction_error`` fields.

Everything below the loaders is pure over plain dicts (the committed
miniature trace fixture in tests/data/ exercises it without jax), and the
summary round-trips through JSON so bench baselines and the ``/metrics``
exporter consume the same shape.

Timing convention: Chrome trace ``ts``/``dur`` are microseconds.  Within
one lane (pid, tid) events nest by containment (a CPU ``call`` thunk
encloses the ops it calls); attribution uses SELF time (duration minus
directly nested children) so nothing double-counts.  Lanes run
concurrently, so busy time is the interval UNION of top-level events
across lanes, idle is the device wall window minus that union, and the
category decomposition splits the union proportionally to per-category
self-time.
"""
from __future__ import annotations

import dataclasses
import glob
import gzip
import json
import os
import re
import typing

#: sidecar filename written next to the trace session (write_op_map)
OP_MAP_FILENAME = "graftprof_op_map.json"

#: categories every device event lands in (order = table/render order)
CATEGORIES = ("mxu", "collective", "vector", "copy", "infeed", "unknown")

#: decomposition buckets and which categories feed them; "idle" is
#: wall - busy and has no category of its own
DECOMP_BUCKETS: typing.Dict[str, typing.Tuple[str, ...]] = {
    "mxu": ("mxu",),
    "comm": ("collective", "infeed"),
    "hbm": ("vector", "copy", "unknown"),
}

_COLLECTIVE_PREFIXES = (
    "all-reduce", "all-gather", "reduce-scatter", "all-to-all",
    "collective-permute", "collective-broadcast", "send", "recv",
    "send-done", "recv-done", "partition-id", "replica-id",
)
#: async collective halves (``all-reduce-start``/``-done``) report under
#: their family; stripped before the _COLLECTIVE_PREFIXES match
_ASYNC_HALF_RE = re.compile(r"-(start|done|update)$")
_MXU_PREFIXES = ("dot", "convolution", "conv", "cublas", "gemm")
_COPY_PREFIXES = ("copy", "bitcast", "reshape", "transpose", "slice",
                  "dynamic-slice", "dynamic-update-slice", "concatenate",
                  "pad", "gather", "scatter", "broadcast", "iota",
                  "copy-start", "copy-done")
_INFEED_PREFIXES = ("infeed", "outfeed", "host-transfer")
_VECTOR_PREFIXES = (
    "fusion", "add", "subtract", "multiply", "divide", "tanh", "exp",
    "log", "rsqrt", "sqrt", "power", "maximum", "minimum", "compare",
    "select", "and", "or", "not", "xor", "negate", "abs", "sign",
    "floor", "ceil", "round", "clamp", "convert", "reduce",
    "reduce-window", "map", "rng", "sort", "reverse", "tuple",
    "get-tuple-element", "constant", "parameter", "cbrt", "logistic",
    "erf", "atan2", "rem", "shift", "popcnt", "clz", "is-finite",
    "real", "imag", "complex", "expm1", "log1p", "cos", "sin", "tan",
    "stochastic-convert", "bitcast-convert", "domain", "optimization"
)
_CONTROL_PREFIXES = ("call", "while", "conditional", "fused-computation",
                     "async-start", "async-done", "async-update")


def _base_op(name: str) -> str:
    """``all-reduce.12.clone`` -> ``all-reduce`` (strip numeric/.clone/
    .remat suffixes; keep the leading HLO opcode or fusion name)."""
    n = name.strip().lstrip("%").lower()
    n = re.sub(r"(\.(clone|remat|\d+))+$", "", n)
    return n


def categorize(name: str) -> str:
    """Category for one device event from its HLO op name alone."""
    base = _base_op(name)
    coll = _ASYNC_HALF_RE.sub("", base)
    for p in _COLLECTIVE_PREFIXES:
        if coll == p or coll.startswith(p + "."):
            return "collective"
    for p in _INFEED_PREFIXES:
        if base.startswith(p):
            return "infeed"
    for p in _MXU_PREFIXES:
        if base == p or base.startswith(p + "-") or base.startswith(p + "_"):
            return "mxu"
    if "fusion" in base:
        # named fusions ("input_multiply_dot_fusion"): a contained matmul
        # makes the whole fused loop MXU work ("convert" must NOT hit the
        # "conv" token, so match whole _/- separated tokens)
        toks = re.split(r"[^a-z0-9]+", base)
        if any(t in ("dot", "conv", "convolution", "gemm", "matmul")
               for t in toks):
            return "mxu"
        return "vector"
    for p in _COPY_PREFIXES:
        if base == p or base.startswith(p + "-") or base.startswith(p + "_"):
            return "copy"
    for p in _VECTOR_PREFIXES:
        if base == p or base.startswith(p + "-") or base.startswith(p + "_"):
            return "vector"
    if base.startswith("custom-call"):
        # opaque kernels (pallas) — compute, almost always matmul-class
        return "mxu"
    for p in _CONTROL_PREFIXES:
        if base == p or base.startswith(p + "-"):
            # control ops carry ~zero SELF time (their children hold the
            # real work); classify as vector so they don't read as unknown
            return "vector"
    return "unknown"


def collective_kind(name: str) -> typing.Optional[str]:
    """The collective family (``all-reduce``...) or None; async halves
    (``all-reduce-start.1``) report under their family."""
    base = _ASYNC_HALF_RE.sub("", _base_op(name))
    for p in _COLLECTIVE_PREFIXES:
        if base == p or base.startswith(p + "."):
            return p
    return None


# -- scope extraction from HLO metadata op_name -------------------------------

#: jax transform wrappers that may enclose a named_scope component in
#: ``metadata.op_name`` (``transpose(jvp(body))`` -> ``body``)
_WRAPPERS = ("jvp", "transpose", "vmap", "pmap", "remat", "checkpoint",
             "custom_jvp", "custom_vjp", "jit", "pjit", "xmap",
             "shard_map", "scan", "while", "cond", "custom_vjp_call",
             "rematted_computation")
_WRAP_RE = re.compile(r"^(%s)\((.*)\)$" % "|".join(_WRAPPERS))
_JIT_HEAD_RE = re.compile(r"^(jit|pjit)\(.*\)$")


def _collapse_repeat(parts: typing.Tuple[str, ...]
                     ) -> typing.Tuple[str, ...]:
    """Collapse a doubled leading run: ``gpt/body/gpt/body/d0_0`` ->
    ``gpt/body/d0_0``.  Per-block sub-builds re-enter their full preset
    scope path (models/ctx.py::_PresetScope) while the outer build's
    jax name-stack entries are still open, so compiled metadata carries
    the prefix twice; the parameter path is the single-run form."""
    parts = tuple(parts)
    changed = True
    while changed and parts:
        changed = False
        for i in range(1, len(parts) // 2 + 1):
            if parts[:i] == parts[i:2 * i]:
                parts = parts[i:]
                changed = True
                break
    return parts


def scope_of_op_name(op_name: str) -> typing.Tuple[str, ...]:
    """Model-scope components of one HLO ``metadata.op_name``.

    Drops the leading ``jit(...)`` machinery and the trailing primitive
    name, and unwraps transform decorations, so forward and backward ops
    of one layer attribute to the SAME scope path::

        jit(step)/jit(main)/transpose(jvp(body))/layer0/ffn/dot_general
        -> ("body", "layer0", "ffn")
    """
    parts = [p for p in op_name.split("/") if p]
    while parts and _JIT_HEAD_RE.match(parts[0]):
        parts.pop(0)
    out: typing.List[str] = []
    for p in parts:
        m = _WRAP_RE.match(p)
        while m:
            p = m.group(2)
            m = _WRAP_RE.match(p) if p else None
        if p:
            out.append(p)
    return _collapse_repeat(tuple(out[:-1]))  # last component = primitive


# -- HLO op map (instruction -> metadata op_name) -----------------------------

_HLO_MODULE_RE = re.compile(r"^HloModule\s+([\w.\-]+)")
_HLO_INSTR_RE = re.compile(
    r"^\s*(?:ROOT\s+)?%?([\w.\-]+)\s*=\s.*metadata=\{[^}]*?"
    r"op_name=\"([^\"]+)\"")


def op_map_from_hlo_text(text: str) -> typing.Dict[str, str]:
    """``{instruction_name: metadata op_name}`` parsed from optimized HLO
    text (``compiled.as_text()``) — covers instructions inside fused/
    called computations too, since every line carrying metadata is read."""
    out: typing.Dict[str, str] = {}
    for line in text.splitlines():
        m = _HLO_INSTR_RE.match(line)
        if m:
            out[m.group(1)] = m.group(2)
    return out


def hlo_module_name(text: str) -> str:
    m = _HLO_MODULE_RE.match(text.splitlines()[0] if text else "")
    return m.group(1) if m else ""


class OpMap:
    """Per-module instruction -> op_name lookup with suffix fallback
    (the runtime clones instructions: trace names like ``tanh.5.clone``
    must still hit the ``tanh.5`` map entry)."""

    def __init__(self, modules: typing.Dict[str, typing.Dict[str, str]]):
        self.modules = modules

    @classmethod
    def from_hlo_text(cls, text: str) -> "OpMap":
        return cls({hlo_module_name(text) or "unknown":
                    op_map_from_hlo_text(text)})

    def lookup(self, module: str, op: str) -> typing.Optional[str]:
        ops = self.modules.get(module)
        if ops is None:
            return None
        hit = ops.get(op)
        if hit is not None:
            return hit
        base = re.sub(r"(\.clone)+$", "", op)
        return ops.get(base)

    def save(self, path: str) -> str:
        with open(path, "w") as f:
            json.dump({"modules": self.modules}, f)
        return path

    @classmethod
    def load(cls, path: str) -> "OpMap":
        with open(path) as f:
            doc = json.load(f)
        return cls(doc.get("modules", {}))


def write_op_map(compiled, profile_dir: str) -> typing.Optional[str]:
    """Dump the compiled step executable's op map next to the newest trace
    session under ``profile_dir`` (or into ``profile_dir`` itself when no
    session exists yet).  Returns the sidecar path, or None when the
    executable can't render its HLO (exotic backends)."""
    try:
        text = compiled.as_text()
    except Exception:
        return None
    session = _newest_session_dir(profile_dir)
    outdir = session if session else profile_dir
    os.makedirs(outdir, exist_ok=True)
    return OpMap.from_hlo_text(text).save(
        os.path.join(outdir, OP_MAP_FILENAME))


def write_op_map_for(trainer, profile_dir: str) -> typing.Optional[str]:
    """The train-loop entry point: sidecar from the trainer's kept AOT
    executable when one exists (telemetry or ``--profile`` pre-compile),
    silently nothing otherwise — category bucketing still works without
    it, only per-scope attribution degrades."""
    compiled = getattr(trainer, "_compiled", None)
    if compiled is None:
        return None
    return write_op_map(compiled, profile_dir)


# -- trace loading ------------------------------------------------------------

def _newest_session_dir(profile_dir: str) -> typing.Optional[str]:
    sessions = sorted(glob.glob(
        os.path.join(profile_dir, "plugins", "profile", "*")))
    return sessions[-1] if sessions else None


def find_trace_file(path: str) -> typing.Optional[str]:
    """Resolve a profiler output path to one Chrome-trace JSON file: a
    direct ``*.trace.json(.gz)`` file, a session dir, or the profiler
    root dir (newest session wins).  None when the plugin directory is
    absent — the caller skips cleanly (some toolchains never write it)."""
    if os.path.isfile(path):
        return path
    if not os.path.isdir(path):
        return None
    for d in (path, _newest_session_dir(path)):
        if d is None:
            continue
        hits = sorted(glob.glob(os.path.join(d, "*.trace.json.gz"))
                      + glob.glob(os.path.join(d, "*.trace.json")))
        if hits:
            return hits[0]
    return None


def load_trace_events(path: str) -> typing.List[dict]:
    """Raw event dicts from a ``.trace.json(.gz)`` file (or a bare list /
    ``{"traceEvents": [...]}`` document)."""
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rt") as f:
        doc = json.load(f)
    if isinstance(doc, list):
        return doc
    return doc.get("traceEvents", [])


def sidecar_op_map(path: str) -> typing.Optional[OpMap]:
    """The op-map sidecar next to a resolved trace file, if present."""
    candidate = os.path.join(os.path.dirname(os.path.abspath(path)),
                             OP_MAP_FILENAME)
    if not os.path.exists(candidate):
        return None
    try:
        return OpMap.load(candidate)
    except Exception:
        return None


# -- event selection + self-time ----------------------------------------------

def _process_names(events: typing.Iterable[dict]) -> typing.Dict[int, str]:
    out: typing.Dict[int, str] = {}
    for e in events:
        if (e.get("ph") == "M" and e.get("name") == "process_name"
                and isinstance(e.get("args"), dict)):
            out[e.get("pid")] = str(e["args"].get("name", ""))
    return out


def _is_device_pid(pname: str) -> bool:
    p = pname.lower()
    return "/device:" in p or "tpu core" in p or "tpu:" in p


@dataclasses.dataclass
class DeviceEvent:
    name: str
    ts: float  # microseconds
    dur: float
    lane: typing.Tuple[int, int]  # (pid, tid)
    module: str  # hlo_module when known
    op: str  # hlo_op when known, else name
    self_us: float = 0.0


def device_events(events: typing.List[dict]
                  ) -> typing.Tuple[typing.List[DeviceEvent], int]:
    """(device events, malformed count).  A device event is an ``X`` event
    carrying an ``hlo_op`` arg (XLA:CPU thunk runtime — they interleave
    with Python events on host threads) or any ``X`` event on a device
    process (``/device:TPU:N`` in the converted TPU trace).  Garbage —
    missing/negative timing, non-dict args where one is needed — is
    counted, not raised: a truncated capture should degrade, not die."""
    pnames = _process_names(events)
    out: typing.List[DeviceEvent] = []
    bad = 0
    for e in events:
        if not isinstance(e, dict) or e.get("ph") != "X":
            continue
        args = e.get("args")
        args = args if isinstance(args, dict) else {}
        on_device_pid = _is_device_pid(pnames.get(e.get("pid"), ""))
        if "hlo_op" not in args and not on_device_pid:
            continue
        name, ts, dur = e.get("name"), e.get("ts"), e.get("dur")
        if (not isinstance(name, str)
                or not isinstance(ts, (int, float))
                or not isinstance(dur, (int, float)) or dur < 0 or ts < 0):
            bad += 1
            continue
        out.append(DeviceEvent(
            name=name, ts=float(ts), dur=float(dur),
            lane=(e.get("pid"), e.get("tid")),
            module=str(args.get("hlo_module", "")),
            op=str(args.get("hlo_op", name))))
    return out, bad


def compute_self_times(events: typing.List[DeviceEvent]) -> None:
    """Fill ``self_us`` per event: duration minus directly nested children
    on the same lane (CPU ``call`` thunks enclose their callees; without
    this the enclosed time would count twice)."""
    by_lane: typing.Dict[tuple, typing.List[DeviceEvent]] = {}
    for e in events:
        by_lane.setdefault(e.lane, []).append(e)
    eps = 1e-3  # us; trace timestamps are rounded to ns
    for lane in by_lane.values():
        lane.sort(key=lambda e: (e.ts, -e.dur))
        stack: typing.List[typing.Tuple[DeviceEvent, typing.List[float]]] = []
        for e in lane:
            while stack and e.ts >= stack[-1][0].ts + stack[-1][0].dur - eps:
                parent, kids = stack.pop()
                parent.self_us = max(0.0, parent.dur - sum(kids))
            if stack:
                stack[-1][1].append(e.dur)
            stack.append((e, []))
        while stack:
            parent, kids = stack.pop()
            parent.self_us = max(0.0, parent.dur - sum(kids))


def _interval_union_us(events: typing.List[DeviceEvent]) -> float:
    """Union length of top-level busy intervals across all lanes."""
    ivs = sorted((e.ts, e.ts + e.dur) for e in events)
    total = 0.0
    cur_s = cur_e = None
    for s, t in ivs:
        if cur_e is None or s > cur_e:
            if cur_e is not None:
                total += cur_e - cur_s
            cur_s, cur_e = s, t
        else:
            cur_e = max(cur_e, t)
    if cur_e is not None:
        total += cur_e - cur_s
    return total


# -- the summary --------------------------------------------------------------

UNATTRIBUTED = "(unattributed)"
#: ops whose metadata IS known but carries no model scope — step-level
#: glue (loss reduction tails, arg copies).  Attributed, unlike map misses.
TOPLEVEL = "(toplevel)"


@dataclasses.dataclass
class ProfileSummary:
    """One parsed capture.  All times seconds unless suffixed ``_ms``."""
    wall_s: float
    busy_s: float
    n_events: int
    n_malformed: int
    n_lanes: int
    n_steps: typing.Optional[int]
    categories_s: typing.Dict[str, float]
    collectives_s: typing.Dict[str, float]
    scopes_s: typing.Dict[str, float]
    top_ops: typing.List[dict]
    attributed_category_frac: float
    attributed_scope_frac: float
    decomposition_ms_per_step: typing.Dict[str, float]
    fractions: typing.Dict[str, float]
    #: full per-(scope, op) self seconds — flamegraph source; trimmed to
    #: top_ops in the JSON form
    op_rows: typing.List[dict] = dataclasses.field(default_factory=list)

    @property
    def comm_fraction(self) -> float:
        return self.fractions.get("comm", 0.0)

    def to_json(self) -> dict:
        d = dataclasses.asdict(self)
        d.pop("op_rows")
        return d

    @classmethod
    def from_json(cls, d: dict) -> "ProfileSummary":
        fields = {f.name for f in dataclasses.fields(cls)}
        kw = {k: v for k, v in d.items() if k in fields}
        kw.setdefault("op_rows", [])
        return cls(**kw)

    def save(self, path: str) -> str:
        with open(path, "w") as f:
            json.dump(self.to_json(), f, indent=1, sort_keys=True)
            f.write("\n")
        return path

    @classmethod
    def load(cls, path: str) -> "ProfileSummary":
        with open(path) as f:
            return cls.from_json(json.load(f))


def summarize_events(raw_events: typing.List[dict],
                     op_map: typing.Optional[OpMap] = None,
                     n_steps: typing.Optional[int] = None,
                     top_k: int = 20) -> ProfileSummary:
    """The pure core: raw Chrome-trace dicts -> :class:`ProfileSummary`."""
    events, bad = device_events(raw_events)
    compute_self_times(events)
    wall_us = busy_us = 0.0
    if events:
        t0 = min(e.ts for e in events)
        t1 = max(e.ts + e.dur for e in events)
        wall_us = t1 - t0
        busy_us = _interval_union_us(events)
    cats = {c: 0.0 for c in CATEGORIES}
    colls: typing.Dict[str, float] = {}
    per_key: typing.Dict[typing.Tuple[typing.Tuple[str, ...], str, str],
                         float] = {}
    scope_us: typing.Dict[typing.Tuple[str, ...], float] = {}
    total_self = 0.0
    for e in events:
        cat = categorize(e.op)
        cats[cat] += e.self_us
        total_self += e.self_us
        kind = collective_kind(e.op)
        if kind is not None:
            colls[kind] = colls.get(kind, 0.0) + e.self_us
        scope: typing.Tuple[str, ...] = (UNATTRIBUTED,)
        op_name = None
        if op_map is not None:
            op_name = op_map.lookup(e.module, e.op)
        if op_name:
            # argument-label metadata ("state.params['gpt/...']",
            # "batch['token_x']") is not a scope path: step-level glue
            scope = ((TOPLEVEL,) if "jit(" not in op_name
                     else scope_of_op_name(op_name) or (TOPLEVEL,))
        key = (scope, _base_op(e.op), cat)
        per_key[key] = per_key.get(key, 0.0) + e.self_us
        scope_us[scope] = scope_us.get(scope, 0.0) + e.self_us
    us = 1e-6
    attributed_cat = ((total_self - cats["unknown"]) / total_self
                      if total_self else 0.0)
    attributed_scope = ((total_self - scope_us.get((UNATTRIBUTED,), 0.0))
                        / total_self if total_self else 0.0)
    # decomposition: split the busy union across buckets proportional to
    # per-category self time (lanes overlap, so self sums can exceed the
    # union); idle = wall - busy.  Sums to the wall window by construction.
    decomp_us = {b: 0.0 for b in DECOMP_BUCKETS}
    for bucket, members in DECOMP_BUCKETS.items():
        share = sum(cats[c] for c in members)
        if total_self > 0:
            decomp_us[bucket] = busy_us * share / total_self
    decomp_us["idle"] = max(0.0, wall_us - busy_us)
    decomp_us["total"] = wall_us
    steps = max(1, n_steps) if n_steps else None
    decomp_ms = {k: (v / 1e3 / (steps or 1)) for k, v in decomp_us.items()}
    fractions = {k: (decomp_us[k] / wall_us if wall_us else 0.0)
                 for k in ("mxu", "hbm", "comm", "idle")}
    op_rows = sorted(
        ({"scope": "/".join(scope), "op": op, "category": cat,
          "self_s": round(v * us, 9)}
         for (scope, op, cat), v in per_key.items()),
        key=lambda r: -r["self_s"])
    return ProfileSummary(
        wall_s=round(wall_us * us, 9),
        busy_s=round(busy_us * us, 9),
        n_events=len(events),
        n_malformed=bad,
        n_lanes=len({e.lane for e in events}),
        n_steps=n_steps,
        categories_s={k: round(v * us, 9) for k, v in sorted(cats.items())
                      if v > 0.0},
        collectives_s={k: round(v * us, 9) for k, v in sorted(colls.items())},
        scopes_s={"/".join(k): round(v * us, 9) for k, v in
                  sorted(scope_us.items(), key=lambda kv: -kv[1])},
        top_ops=op_rows[:top_k],
        attributed_category_frac=round(attributed_cat, 6),
        attributed_scope_frac=round(attributed_scope, 6),
        decomposition_ms_per_step={k: round(v, 6)
                                   for k, v in decomp_ms.items()},
        fractions={k: round(v, 6) for k, v in fractions.items()},
        op_rows=op_rows)


def summarize_trace(path: str, op_map: typing.Optional[OpMap] = None,
                    n_steps: typing.Optional[int] = None,
                    top_k: int = 20) -> ProfileSummary:
    return summarize_events(load_trace_events(path), op_map=op_map,
                            n_steps=n_steps, top_k=top_k)


def capture_summary(profile_dir: str, n_steps: typing.Optional[int] = None,
                    top_k: int = 20) -> typing.Optional[ProfileSummary]:
    """Summarize the newest capture under a profiler output dir, joining
    the op-map sidecar when one sits next to the trace.  None when no
    trace was written (profiler plugin directory absent — the caller
    skips cleanly rather than failing the run)."""
    trace = find_trace_file(profile_dir)
    if trace is None:
        return None
    return summarize_trace(trace, op_map=sidecar_op_map(trace),
                           n_steps=n_steps, top_k=top_k)


# -- flamegraph + diff + reconcile --------------------------------------------

def collapsed_stacks(summary: ProfileSummary) -> typing.List[str]:
    """Flamegraph collapsed-stack lines (``scope;path;op <microseconds>``)
    — feed to any FlameGraph/speedscope renderer.  Uses the full op rows,
    so call on a summary built from a trace (not one re-loaded from its
    trimmed JSON form)."""
    rows = summary.op_rows or summary.top_ops
    out = []
    for r in sorted(rows, key=lambda r: (r["scope"], r["op"])):
        stack = [p for p in r["scope"].split("/") if p] + [r["op"]]
        out.append("%s %d" % (";".join(stack), round(r["self_s"] * 1e6)))
    return out


def diff_summaries(a: ProfileSummary, b: ProfileSummary) -> dict:
    """Attribution drift between two captures (``--compare``): per-bucket
    fraction deltas, per-scope ms/step deltas, and step-time movement —
    b minus a, so positive = grew in b."""
    steps_a = a.n_steps or 1
    steps_b = b.n_steps or 1
    scope_ms_a = {k: v * 1e3 / steps_a for k, v in a.scopes_s.items()}
    scope_ms_b = {k: v * 1e3 / steps_b for k, v in b.scopes_s.items()}
    scopes = {}
    for k in sorted(set(scope_ms_a) | set(scope_ms_b)):
        d = scope_ms_b.get(k, 0.0) - scope_ms_a.get(k, 0.0)
        scopes[k] = {"a_ms": round(scope_ms_a.get(k, 0.0), 6),
                     "b_ms": round(scope_ms_b.get(k, 0.0), 6),
                     "delta_ms": round(d, 6)}
    return {
        "ms_per_step": {
            "a": a.decomposition_ms_per_step.get("total", 0.0),
            "b": b.decomposition_ms_per_step.get("total", 0.0),
            "delta": round(
                b.decomposition_ms_per_step.get("total", 0.0)
                - a.decomposition_ms_per_step.get("total", 0.0), 6)},
        "fractions_delta": {
            k: round(b.fractions.get(k, 0.0) - a.fractions.get(k, 0.0), 6)
            for k in ("mxu", "hbm", "comm", "idle")},
        "attributed_scope_frac_delta": round(
            b.attributed_scope_frac - a.attributed_scope_frac, 6),
        "scopes_ms": scopes,
    }


def reconcile(summary: ProfileSummary,
              predicted_s: typing.Optional[typing.Dict[str, float]]
              ) -> dict:
    """Measured decomposition vs graftcost's static per-step estimate
    (``analysis/cost_model.py::static_step_times``: ``mxu``/``hbm``/``ici``
    seconds).  Per component: predicted ms, measured ms, and
    ``prediction_error`` = predicted/measured - 1 (positive = the model
    over-predicted).  ``predicted_s=None`` (CPU, unknown device) keeps the
    fields present but null, so the BENCH row shape is stable across
    backends."""
    pairs = {"mxu": "mxu", "hbm": "hbm", "comm": "ici"}
    out: typing.Dict[str, dict] = {}
    for component, pkey in pairs.items():
        measured_ms = summary.decomposition_ms_per_step.get(component, 0.0)
        pred_ms = None
        if predicted_s is not None and predicted_s.get(pkey) is not None:
            pred_ms = float(predicted_s[pkey]) * 1e3
        err = None
        if pred_ms is not None and measured_ms > 0:
            err = round(pred_ms / measured_ms - 1.0, 4)
        out[component] = {
            "predicted_ms": None if pred_ms is None else round(pred_ms, 6),
            "measured_ms": round(measured_ms, 6),
            "prediction_error": err,
        }
    return out


# -- bench attribution-drift baseline -----------------------------------------

#: tolerated absolute drift of any decomposition fraction (and of the
#: scope-attribution coverage) vs the committed per-device baseline
PROFILE_DRIFT_TOL = 0.15


def baseline_entry(profile_row: dict) -> dict:
    """The committed shape for one workload (bench_profile_baseline.json)."""
    return {"fractions": dict(profile_row.get("fractions", {})),
            "attributed_scope_frac":
                profile_row.get("attributed_scope_frac", 0.0)}


def evaluate_profile_baseline(workloads: dict, budgets: dict,
                              tol: float = PROFILE_DRIFT_TOL):
    """Pure attribution-drift gate (unit-testable; same contract as
    ``bench.evaluate_compile_budget``): each workload row's decomposition
    fractions must sit within ``tol`` (absolute) of the committed
    per-device baseline, and scope-attribution coverage must not drop more
    than ``tol`` below it.  Returns (per-workload rows, all_pass);
    workloads without a profile row or baseline entry are skipped —
    absence is not a regression."""
    rows: dict = {}
    ok = True
    for nm, w in sorted(workloads.items()):
        prof = w.get("profile") if isinstance(w, dict) else None
        base = (budgets or {}).get(nm)
        if (not isinstance(prof, dict) or "fractions" not in prof
                or not isinstance(base, dict)):
            continue
        drift = {k: round(prof["fractions"].get(k, 0.0)
                          - base.get("fractions", {}).get(k, 0.0), 4)
                 for k in ("mxu", "hbm", "comm", "idle")}
        cov_drop = round(base.get("attributed_scope_frac", 0.0)
                         - prof.get("attributed_scope_frac", 0.0), 4)
        passed = bool(max(abs(v) for v in drift.values()) <= tol
                      and cov_drop <= tol)
        rows[nm] = {"fraction_drift": drift,
                    "coverage_drop": cov_drop,
                    "tol": tol, "pass": passed}
        ok = ok and passed
    return rows, ok
