"""Always-on serving flight recorder (docs/observability.md "Flight
recorder").

Aggregate histograms survive an incident; the evidence that EXPLAINS it
(the slow request's span trail, the queue depth at the moment it was
admitted) used to die with the request unless an operator was already
tracing.  The recorder keeps that evidence in bounded memory at all
times and pays for persistence only when something goes wrong:

- a SPAN RING — the serving :class:`~homebrewnlp_tpu.obs.spans.SpanTracer`
  capped at ``flight_buffer_spans`` events (the recorder snapshots it at
  dump time; it never copies spans on the hot path);
- REQUEST TRAILS — the last N finished :class:`RequestRecord` summaries
  (timestamps, derived latencies, status, correlation id);
- METRIC SNAPSHOTS — the registry's rendered text, captured at most once
  per ``snapshot_interval_s`` on the request path and again at dump time.

A TRIGGER (``flight_dump_triggers``: watchdog stall, 5xx response, SLO
burn-rate alert, or manual ``POST /debugz/dump``) writes a self-contained
incident bundle — spans, trails, snapshots, config hash, identity — to
``<model_path>/diagnostics/flight_<ts>_<seq>.json``, rate-limited per
reason so a 5xx storm produces one bundle, not thousands.

TAIL-BASED SAMPLING: requests slower than the rolling p99 of recent e2e
latencies keep their full trail flagged ``tail`` and are attached as
OpenMetrics exemplars on the serve latency histograms
(``obs/registry.py``) — the default Prometheus rendering is byte-
identical whether or not exemplars exist; only the OpenMetrics flavor
shows them.
"""
from __future__ import annotations

import collections
import itertools
import json
import os
import time
import typing

from ..sync import make_lock
from .registry import sample_quantile

#: every trigger reason a bundle can cite; ``flight_dump_triggers``
#: entries are validated against this set at config load
DUMP_TRIGGERS = ("watchdog", "error", "slo", "manual")

#: bundle schema marker checked by :func:`validate_bundle`
BUNDLE_SCHEMA = "hbnlp-flight-1"

#: top-level keys every bundle must carry (validate_bundle contract)
BUNDLE_KEYS = ("schema", "reason", "wall_time_s", "identity",
               "config_hash", "spans", "requests", "metrics")


def request_trail(rec) -> dict:
    """One finished request's full trail as a JSON-ready dict — the
    record's raw monotonic stamps plus every derived latency, keyed by
    the propagated correlation id so grep-by-id works across client
    logs, server logs, and bundles."""
    trail = {
        "rid": rec.rid,
        "xid": getattr(rec, "xid", "") or "",
        "tenant": getattr(rec, "tenant", "") or "",
        "path": rec.path,
        "status": rec.status,
        "queue_depth": rec.queue_depth,
        "tokens_generated": rec.tokens_generated,
    }
    for attr in ("t_arrival", "t_parsed", "t_enqueued", "t_started",
                 "t_first_token", "t_engine_done", "t_finished"):
        trail[attr] = getattr(rec, attr)
    for name, fn in (("e2e_s", rec.e2e_s), ("parse_s", rec.parse_s),
                     ("queue_wait_s", rec.queue_wait_s),
                     ("ttft_s", rec.ttft_s), ("prefill_s", rec.prefill_s),
                     ("decode_s", rec.decode_s), ("engine_s", rec.engine_s),
                     ("decode_tokens_per_sec", rec.decode_tokens_per_sec)):
        try:
            trail[name] = fn()
        except Exception:  # noqa: BLE001 - a partial record still trails
            trail[name] = None
    gaps = rec.itl_gaps()
    trail["itl_gaps_s"] = [round(g, 6) for g in gaps] if gaps else []
    return trail


def validate_bundle(doc: dict) -> typing.List[str]:
    """Structural check of an incident bundle (CI and ``graftwatch
    --dump`` both run it); returns the list of problems, empty = valid."""
    problems = []
    if not isinstance(doc, dict):
        return ["bundle is not a JSON object"]
    for key in BUNDLE_KEYS:
        if key not in doc:
            problems.append(f"missing key {key!r}")
    if doc.get("schema") != BUNDLE_SCHEMA:
        problems.append(
            f"schema {doc.get('schema')!r} != {BUNDLE_SCHEMA!r}")
    if doc.get("reason") not in DUMP_TRIGGERS:
        problems.append(f"unknown reason {doc.get('reason')!r}")
    spans = doc.get("spans")
    if not isinstance(spans, list):
        problems.append("spans is not a list")
    else:
        for i, s in enumerate(spans):
            if not isinstance(s, dict) or not {"name", "t0_s",
                                               "t1_s"} <= set(s):
                problems.append(f"spans[{i}] lacks name/t0_s/t1_s")
                break
    reqs = doc.get("requests")
    if not isinstance(reqs, list):
        problems.append("requests is not a list")
    else:
        for i, r in enumerate(reqs):
            if not isinstance(r, dict) or "rid" not in r:
                problems.append(f"requests[{i}] lacks rid")
                break
    if not isinstance(doc.get("metrics"), str):
        problems.append("metrics is not rendered text")
    return problems


class FlightRecorder:
    """Bounded always-on evidence ring + trigger-gated bundle writer.

    Thread-safety: REST handler threads call :meth:`observe_request` and
    :meth:`dump` concurrently (and the watchdog thread may dump); all
    mutable state sits behind one declared lock.  The span ring itself
    lives in the shared ``SpanTracer`` (its own declared lock) — the
    recorder only snapshots it inside :meth:`dump`."""

    def __init__(self, max_spans: int = 4096, max_records: int = 64,
                 max_snapshots: int = 4,
                 triggers: typing.Sequence[str] = DUMP_TRIGGERS,
                 model_path: str = "", config_hash: str = "",
                 identity: typing.Optional[dict] = None,
                 registry=None,
                 tail_window: int = 128, tail_quantile: float = 0.99,
                 tail_min_samples: int = 16,
                 snapshot_interval_s: float = 30.0,
                 min_dump_interval_s: float = 30.0):
        self._lock = make_lock("obs.flight.FlightRecorder._lock")
        self.max_spans = int(max_spans)
        self.triggers = tuple(triggers)
        self.model_path = str(model_path or "")
        self.config_hash = str(config_hash or "")
        self.identity = dict(identity or {})
        self.registry = registry
        #: the serving span tracer this recorder snapshots at dump time
        #: (wired by ``serve/rest.py``; stays None in bare unit tests)
        self.tracer = None
        self._records: "collections.deque[dict]" = collections.deque(
            maxlen=int(max_records))
        self._snapshots: "collections.deque[dict]" = collections.deque(
            maxlen=int(max_snapshots))
        self._e2e: "collections.deque[float]" = collections.deque(
            maxlen=int(tail_window))
        self._tail_quantile = float(tail_quantile)
        self._tail_min = int(tail_min_samples)
        self._snapshot_interval_s = float(snapshot_interval_s)
        self._min_dump_interval_s = float(min_dump_interval_s)
        self._last_snapshot_t = 0.0
        self._last_dump: typing.Dict[str, float] = {}
        self._seq = itertools.count(1)
        self._alerts_probe: typing.Optional[typing.Callable] = None
        self._usage_probe: typing.Optional[typing.Callable] = None
        #: bundle paths written this process (newest last)
        self.dumps: typing.List[str] = []

    def set_alerts_probe(self, fn: typing.Optional[typing.Callable]
                         ) -> None:
        """Attach the SLO evaluator's ``summary`` so bundles carry the
        alert state at the moment of the incident."""
        with self._lock:
            self._alerts_probe = fn

    def set_usage_probe(self, fn: typing.Optional[typing.Callable]
                        ) -> None:
        """Attach the usage meter's ``summary`` so bundles carry the
        per-tenant accounting state at the moment of the incident."""
        with self._lock:
            self._usage_probe = fn

    # -- hot path ------------------------------------------------------------
    def observe_request(self, rec) -> dict:
        """Retain one finished request's trail; tail-sample it against
        the rolling p99 and attach exemplars on the serve latency
        histograms when it qualifies.  Returns the trail (the REST layer
        reuses it for the ``error`` trigger's bundle extra)."""
        trail = request_trail(rec)
        e2e = trail.get("e2e_s")
        now = time.time()
        with self._lock:
            tail = False
            if e2e is not None:
                if len(self._e2e) >= self._tail_min:
                    p = sample_quantile(list(self._e2e),
                                        self._tail_quantile)
                    tail = p is not None and e2e >= p
                self._e2e.append(float(e2e))
            trail["tail"] = tail
            self._records.append(trail)
            snap = (self.registry is not None
                    and now - self._last_snapshot_t
                    >= self._snapshot_interval_s)
            if snap:
                self._last_snapshot_t = now
        if snap:
            self._snapshot_metrics(now)
        if tail:
            self._attach_exemplars(trail)
        return trail

    def _attach_exemplars(self, trail: dict) -> None:
        if self.registry is None:
            return
        labels = {"request_id": trail["xid"] or str(trail["rid"])}
        for metric, value, kw in (
                ("hbnlp_serve_request_seconds", trail.get("e2e_s"),
                 {"path": trail["path"]}),
                ("hbnlp_serve_ttft_seconds", trail.get("ttft_s"), {})):
            if value is None:
                continue
            hist = self.registry.get(metric)
            if hist is None or not hasattr(hist, "attach_exemplar"):
                continue
            try:
                hist.attach_exemplar(float(value), labels, **kw)
            except ValueError:
                pass  # label mismatch on a foreign registry: skip, don't 500

    def _snapshot_metrics(self, now: float) -> None:
        try:
            text = self.registry.render()
        except Exception:  # noqa: BLE001 - snapshots are best-effort
            return
        with self._lock:
            self._snapshots.append({"wall_time_s": now, "metrics": text})

    # -- dumping -------------------------------------------------------------
    def wants(self, reason: str) -> bool:
        return reason in self.triggers

    def dump(self, reason: str,
             extra: typing.Optional[dict] = None,
             force: bool = False) -> typing.Optional[str]:
        """Write an incident bundle for ``reason``; returns its path, or
        None when the reason is not an armed trigger or the per-reason
        rate limit holds (``force`` — the manual endpoint — bypasses
        both)."""
        now = time.time()
        if not force:
            if reason not in self.triggers:
                return None
            with self._lock:
                last = self._last_dump.get(reason, 0.0)
                if now - last < self._min_dump_interval_s:
                    return None
                self._last_dump[reason] = now
        doc = self.bundle(reason, extra=extra, now=now)
        out_dir = os.path.join(self.model_path or ".", "diagnostics")
        os.makedirs(out_dir, exist_ok=True)
        stamp = time.strftime("%Y%m%d_%H%M%S", time.localtime(now))
        path = os.path.join(out_dir,
                            f"flight_{stamp}_{next(self._seq)}.json")
        try:
            with open(path, "w") as f:
                json.dump(doc, f, default=str)
        except OSError:
            return None
        with self._lock:
            self.dumps.append(path)
        return path

    def bundle(self, reason: str, extra: typing.Optional[dict] = None,
               now: typing.Optional[float] = None) -> dict:
        """The self-contained incident document (also what ``POST
        /debugz/dump`` returns inline)."""
        now = time.time() if now is None else now
        tracer = self.tracer
        spans = []
        if tracer is not None:
            try:
                spans = tracer.snapshot_events(limit=self.max_spans)
            except Exception:  # noqa: BLE001 - spans are evidence, not a gate
                spans = []
        metrics = ""
        if self.registry is not None:
            try:
                metrics = self.registry.render()
            except Exception:  # noqa: BLE001
                metrics = ""
        with self._lock:
            requests = list(self._records)
            snapshots = list(self._snapshots)
            probe = self._alerts_probe
            uprobe = self._usage_probe
        alerts = None
        if probe is not None:
            try:
                alerts = probe()
            except Exception:  # noqa: BLE001
                alerts = None
        usage = None
        if uprobe is not None:
            try:
                usage = uprobe()
            except Exception:  # noqa: BLE001
                usage = None
        doc = {
            "schema": BUNDLE_SCHEMA,
            "reason": reason,
            "wall_time_s": now,
            "identity": self.identity,
            "config_hash": self.config_hash,
            "triggers": list(self.triggers),
            "spans": spans,
            "requests": requests,
            "snapshots": snapshots,
            "metrics": metrics,
            "alerts": alerts,
            "usage": usage,
        }
        if extra:
            doc["extra"] = extra
        return doc

    def status(self) -> dict:
        """The ``GET /debugz/flight`` payload."""
        tracer = self.tracer
        # the tracer count is read BEFORE taking the recorder lock: the
        # tracer has its own declared lock, and nesting it under ours
        # would add a lock-order edge no other path needs
        n_spans = tracer.event_count() if tracer is not None else 0
        with self._lock:
            return {
                "triggers": list(self.triggers),
                "max_spans": self.max_spans,
                "n_requests": len(self._records),
                "n_snapshots": len(self._snapshots),
                "n_spans": n_spans,
                "dumps": list(self.dumps),
            }
