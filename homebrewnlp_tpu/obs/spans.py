"""Thread-aware host span tracing -> Chrome trace-event JSON + jax mirror.

The async-dispatch loop (main.py) runs three concurrent host actors — the
train loop, the ``DeviceFeeder`` producer thread, and the deferred metric
drain — whose interleaving is invisible in ``metrics.jsonl``.  A
``SpanTracer`` records named, nested spans from any thread and exports them
as Chrome trace-event JSON (the ``{"traceEvents": [...]}`` format Perfetto
and ``chrome://tracing`` load directly): overlapping spans on one thread
nest visually, and each thread gets its own labelled track.

Every span is also mirrored into ``jax.profiler.TraceAnnotation`` so a
``--profile`` capture shows the SAME host spans aligned with XLA's device
timeline — one trace answers "was the device idle while the host did X".

Zero-overhead contract: the module-level ``span()`` / ``traced()`` helpers
consult the ambient tracer installed by ``obs.Obs.start()``; with no tracer
installed they return a shared no-op context manager (one global load + one
identity call), so instrumented code paths cost nothing when observability
is off and the synchronous parity path stays bit-identical.
"""
from __future__ import annotations

import collections
import functools
import json
import os
import threading
import time
import typing

from ..sync import make_lock


class _NullSpan:
    """Shared no-op context manager for the disabled path."""

    __slots__ = ()

    def __enter__(self) -> "_NullSpan":
        return self

    def __exit__(self, *exc) -> bool:
        return False


NULL_SPAN = _NullSpan()


class _Span:
    __slots__ = ("tracer", "name", "args", "_t0", "_ann")

    def __init__(self, tracer: "SpanTracer", name: str, args: dict):
        self.tracer = tracer
        self.name = name
        self.args = args
        self._ann = None

    def __enter__(self) -> "_Span":
        if self.tracer._mirror is not None:
            self._ann = self.tracer._mirror(self.name)
            self._ann.__enter__()
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc) -> bool:
        t1 = time.perf_counter()
        if self._ann is not None:
            self._ann.__exit__(*exc)
            self._ann = None
        self.tracer._record(self.name, self._t0, t1, self.args)
        return False


class SpanTracer:
    """Collects host spans; thread-safe; exports Chrome trace-event JSON.

    ``mirror_jax=True`` (default) wraps each span in a
    ``jax.profiler.TraceAnnotation`` — free when no profiler trace is
    active, and the host/device alignment story when one is.

    ``max_events`` bounds host memory on long runs: the buffer is a ring
    keeping the MOST RECENT spans (a post-mortem wants the window before
    the event, not the first hours), and the export notes how many were
    dropped.  ``phase_totals`` accumulates separately, so bench phase sums
    stay exact regardless of the ring."""

    def __init__(self, mirror_jax: bool = True, max_events: int = 1_000_000):
        self._lock = make_lock("obs.spans.SpanTracer._lock")
        # (name, t0, t1, tid, args) with t relative to tracer creation
        self._events: typing.Deque[tuple] = collections.deque(
            maxlen=max_events)
        self._recorded = 0
        self._totals: typing.Dict[str, float] = {}
        self._thread_names: typing.Dict[int, str] = {}
        # virtual tracks (serving lane timelines): negative synthetic tids,
        # allocated per track name, so they can never collide with a real
        # thread ident and sort ahead of the thread tracks in the viewer
        self._track_ids: typing.Dict[str, int] = {}
        self._epoch = time.perf_counter()
        self._wall_epoch = time.time()
        self._pid = os.getpid()
        self._mirror = None
        if mirror_jax:
            try:
                from jax.profiler import TraceAnnotation
                self._mirror = TraceAnnotation
            except Exception:
                self._mirror = None

    # -- recording -----------------------------------------------------------
    def span(self, name: str, **args) -> _Span:
        """Context manager recording one span on the calling thread."""
        return _Span(self, name, args)

    def trace(self, name: typing.Optional[str] = None):
        """Decorator form: ``@tracer.trace("checkpoint")``."""
        def deco(fn):
            span_name = name or fn.__qualname__

            @functools.wraps(fn)
            def wrapped(*a, **kw):
                with self.span(span_name):
                    return fn(*a, **kw)
            return wrapped
        return deco

    def add(self, name: str, t0: float, t1: float,
            track: typing.Optional[str] = None, **args) -> None:
        """Record an already-measured span from explicit ``perf_counter``
        timestamps.  A request's phase trail (serve/slo.py) is stamped
        across three threads — handler, queue worker, JAX callback — and
        only assembled once the request finishes; this records each phase
        retroactively on the calling thread's track, which a live context
        manager cannot do.

        ``track`` places the span on a named VIRTUAL track instead of the
        calling thread's — the serving engine's per-lane occupancy
        timelines (docs/observability.md "Streaming and inter-token
        latency") are not threads, but each lane still deserves its own
        swimlane in the exported Chrome trace."""
        if t1 < t0:
            t0, t1 = t1, t0
        self._record(name, t0, t1, args, track=track)

    def _record(self, name: str, t0: float, t1: float, args: dict,
                track: typing.Optional[str] = None) -> None:
        with self._lock:
            if track is not None:
                tid = self._track_ids.get(track)
                if tid is None:
                    tid = -(len(self._track_ids) + 1)
                    self._track_ids[track] = tid
                    self._thread_names[tid] = track
            else:
                th = threading.current_thread()
                tid = th.ident
                self._thread_names[tid] = th.name
            self._events.append((name, t0 - self._epoch, t1 - self._epoch,
                                 tid, args))
            self._recorded += 1
            self._totals[name] = self._totals.get(name, 0.0) + (t1 - t0)

    # -- export --------------------------------------------------------------
    def event_count(self) -> int:
        """Spans currently held in the ring (the rotation trigger)."""
        with self._lock:
            return len(self._events)

    def snapshot_events(self, limit: typing.Optional[int] = None
                        ) -> typing.List[dict]:
        """The most recent ``limit`` spans as JSON-ready dicts anchored to
        WALL-CLOCK seconds (``t0_s``/``t1_s``) — the flight recorder's
        bundle format, directly comparable across processes without the
        per-tracer perf_counter epoch."""
        with self._lock:
            events = list(self._events)
            names = dict(self._thread_names)
            wall = self._wall_epoch
        if limit is not None:
            events = events[-limit:]
        return [{"name": name,
                 "t0_s": round(wall + t0, 6),
                 "t1_s": round(wall + t1, 6),
                 "track": names.get(tid, str(tid)),
                 "args": {k: str(v) for k, v in args.items()}}
                for name, t0, t1, tid, args in events]

    def chrome_events(self) -> typing.List[dict]:
        """Chrome trace-event dicts: complete ('X') events plus thread/process
        name metadata ('M') events.  Timestamps are microseconds from tracer
        creation (Perfetto renders relative times; ``otherData`` carries the
        wall-clock anchor)."""
        with self._lock:
            events = list(self._events)
            names = dict(self._thread_names)
        out: typing.List[dict] = [
            {"ph": "M", "name": "process_name", "pid": self._pid, "tid": 0,
             "args": {"name": "homebrewnlp_tpu host"}}]
        for tid, tname in sorted(names.items()):
            out.append({"ph": "M", "name": "thread_name", "pid": self._pid,
                        "tid": tid, "args": {"name": tname}})
        for name, t0, t1, tid, args in events:
            ev = {"name": name, "ph": "X", "cat": "host",
                  "ts": round(t0 * 1e6, 3),
                  "dur": round((t1 - t0) * 1e6, 3),
                  "pid": self._pid, "tid": tid}
            if args:
                ev["args"] = {k: str(v) for k, v in args.items()}
            out.append(ev)
        return out

    def chrome_trace(self) -> dict:
        """The full Perfetto-loadable document as an in-memory dict —
        what :meth:`export` writes, also served live by the REST layer's
        ``GET /debugz/trace`` so ``graftload --trace-out`` can merge
        server spans without filesystem access to the server."""
        with self._lock:
            dropped = self._recorded - len(self._events)
        return {"traceEvents": self.chrome_events(),
                "displayTimeUnit": "ms",
                "otherData": {"wall_epoch": self._wall_epoch,
                              "pid": self._pid,
                              "dropped_events": dropped}}

    def export(self, path: str) -> str:
        """Write the Perfetto-loadable trace JSON; returns the path."""
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        doc = self.chrome_trace()
        with open(path, "w") as f:
            json.dump(doc, f)
        return path

    def rotate(self, path: str) -> str:
        """Export the current ring to ``path`` and CLEAR it (track/thread
        names and phase totals survive, so later segments keep their
        swimlane labels and bench sums stay exact).  The serving engine
        rotates whenever the ring fills, so a crash loses at most one
        ring of spans instead of the whole trace."""
        out = self.export(path)
        with self._lock:
            self._events.clear()
            # the exported spans were persisted, not dropped: reset the
            # drop accounting so later segments report only real ring loss
            self._recorded = 0
        return out

    def phase_totals(self) -> typing.Dict[str, float]:
        """Total seconds per span name — the flat per-phase breakdown bench.py
        embeds in its JSON line.  Accumulated at record time (exact even
        when the event ring has dropped spans); nested spans double-count
        into their parent by design (each name answers 'how long was X
        open')."""
        with self._lock:
            return {k: self._totals[k] for k in sorted(self._totals)}


# -- ambient tracer ----------------------------------------------------------
# Installed by obs.Obs.start(); consulted per call so long-lived objects
# (DeviceFeeder, AsyncMetricWriter, the REST handler) need no plumbing.
_TRACER: typing.Optional[SpanTracer] = None


def set_tracer(tracer: typing.Optional[SpanTracer]
               ) -> typing.Optional[SpanTracer]:
    """Install (or clear, with None) the process-ambient tracer."""
    global _TRACER
    prev = _TRACER
    _TRACER = tracer
    return prev


def get_tracer() -> typing.Optional[SpanTracer]:
    return _TRACER


def span(name: str, **args):
    """Span on the ambient tracer; shared no-op when tracing is off."""
    t = _TRACER
    if t is None:
        return NULL_SPAN
    return t.span(name, **args)


def add(name: str, t0: float, t1: float,
        track: typing.Optional[str] = None, **args) -> None:
    """Retroactive span on the ambient tracer; no-op when tracing is off."""
    t = _TRACER
    if t is not None:
        t.add(name, t0, t1, track=track, **args)


def traced(name: str):
    """Decorator on the ambient tracer (resolved per CALL, so functions
    decorated at import time still trace once a tracer is installed)."""
    def deco(fn):
        @functools.wraps(fn)
        def wrapped(*a, **kw):
            with span(name):
                return fn(*a, **kw)
        return wrapped
    return deco
