"""Live health surface: /metrics + /healthz HTTP server and hang watchdog.

``start_server`` runs a stdlib ``ThreadingHTTPServer`` on a daemon thread
(zero deps — same choice as ``serve/rest.py``) exposing:

- ``GET /metrics``  — the registry in Prometheus text format (0.0.4)
- ``GET /healthz``  — JSON: last-completed-step, EMA step time, seconds
  since the last step, feeder liveness; HTTP 200 while healthy, 503 once
  the run looks stalled (so a k8s-style probe can act on it)

``Watchdog`` is the opaque-death insurance: a daemon thread that, when no
step completes within ``factor`` x the EMA step time, dumps every Python
thread's stack plus per-device ``memory_stats()`` to
``<model_path>/diagnostics/hang_*.txt`` — the two artifacts a post-mortem
of a wedged run actually needs (which actor is blocked, and whether HBM
crept).  It fires once per stall and re-arms when steps resume; it never
kills the run.
"""
from __future__ import annotations

import json
import logging
import os
import sys
import threading
import time
import traceback
import typing
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from .registry import REGISTRY, MetricsRegistry
from ..sync import make_lock

LOG = logging.getLogger("homebrewnlp_tpu.obs")


class Health:
    """Thread-safe record of run liveness, shared by /healthz + watchdog.

    ``step_completed`` is called from the metric drain (a step counts as
    completed when its metrics materialized — the async loop's definition
    of done); the EMA step time smooths over checkpoint pauses."""

    def __init__(self, stall_factor: float = 10.0, ema_alpha: float = 0.2,
                 min_stall_s: float = 5.0, max_pause_s: float = 600.0,
                 startup_stall_s: float = 600.0):
        """``min_stall_s`` floors the stall threshold: sub-millisecond CPU
        steps must not flip /healthz to 503.  ``max_pause_s`` bounds a
        declared pause — a checkpoint save hung past it reads as stalled
        again (and the watchdog dumps), otherwise a wedged save would hide
        behind its own pause forever.  ``startup_stall_s`` is the generous
        absolute bound used BEFORE a step cadence exists (compiling /
        restoring / first step): a run wedged in startup — the classic
        opaque death — still reads as stalled after it.  Health owns the
        threshold (``stall_threshold``); /healthz and the Watchdog both
        consult it, so the two consumers of the liveness signal cannot
        disagree."""
        self._lock = make_lock("obs.exporter.Health._lock")
        self.stall_factor = float(stall_factor) if stall_factor else 10.0
        self.ema_alpha = ema_alpha
        self.min_stall_s = float(min_stall_s)
        self.max_pause_s = float(max_pause_s)
        self.startup_stall_s = float(startup_stall_s)
        self.started = time.time()
        self._last_step: typing.Optional[int] = None
        self._last_wall: typing.Optional[float] = None
        self._last_dispatch: typing.Optional[float] = None
        self._ema_step_s: typing.Optional[float] = None
        self._done = False
        self._paused_for: typing.Optional[str] = None
        self._pause_wall = 0.0
        self._feeder_probe: typing.Optional[typing.Callable[[], bool]] = None
        self._util_probe: typing.Optional[
            typing.Callable[[], typing.Dict[str, float]]] = None

    def step_completed(self, step: int,
                       dispatch_wall: typing.Optional[float] = None) -> None:
        """``dispatch_wall``: when the step was DISPATCHED.  The EMA must
        measure the training cadence from dispatch spacing — a checkpoint
        or profiler ``flush()`` drains the whole in-flight window
        back-to-back, and those near-zero drain gaps would collapse the
        EMA (and with it the stall threshold) if completion times were
        used.  Stall detection itself keys on real completion time."""
        now = time.time()
        t = dispatch_wall if dispatch_wall is not None else now
        with self._lock:
            if self._last_dispatch is not None:
                dt = t - self._last_dispatch
                if dt > 0:
                    self._ema_step_s = (
                        dt if self._ema_step_s is None else
                        self.ema_alpha * dt
                        + (1 - self.ema_alpha) * self._ema_step_s)
            self._last_dispatch = t
            self._last_step = int(step)
            self._last_wall = now

    def set_feeder_probe(self, fn: typing.Callable[[], bool]) -> None:
        with self._lock:
            self._feeder_probe = fn

    def set_utilization_probe(
            self, fn: typing.Callable[[], typing.Dict[str, float]]) -> None:
        """Render-time utilization callback (mfu / tokens_per_sec / goodput,
        wired by ``Obs.watch_utilization``): /healthz carries the same
        figures a dashboard scrapes from /metrics, so a human curl answers
        'is it alive AND is it fast' in one request."""
        with self._lock:
            self._util_probe = fn

    def begin_pause(self, reason: str) -> None:
        """Declare an expected no-steps window (checkpoint save): /healthz
        stays healthy and the watchdog holds fire until ``end_pause`` —
        bounded by ``max_pause_s`` (a save hung past it is a stall)."""
        with self._lock:
            self._paused_for = reason
            self._pause_wall = time.time()

    def end_pause(self) -> None:
        """End the declared pause and restart the stall clock — the paused
        interval must not count toward the next stall measurement, NOR
        toward the next dispatch-spacing EMA sample (shifting
        ``_last_dispatch`` forward by the pause excludes it, so a 60s save
        cannot inflate the stall threshold)."""
        with self._lock:
            pause_dur = (time.time() - self._pause_wall
                         if self._paused_for is not None else 0.0)
            self._paused_for = None
            if self._last_wall is not None:
                self._last_wall = time.time()
            if self._last_dispatch is not None:
                self._last_dispatch += pause_dur

    def paused_for(self) -> typing.Optional[str]:
        with self._lock:
            return self._paused_for

    def paused_seconds(self) -> typing.Optional[float]:
        with self._lock:
            if self._paused_for is None:
                return None
            return time.time() - self._pause_wall

    def stall_threshold(self) -> typing.Optional[float]:
        """Seconds without a completed step that count as a stall; None
        before any step spacing is known.  The ONE definition both
        /healthz and the Watchdog use."""
        ema = self.ema_step_seconds()
        if ema is None or ema <= 0:
            return None
        return max(self.stall_factor * ema, self.min_stall_s)

    def stalled(self) -> bool:
        """True when the run looks wedged: past the stall threshold with no
        declared pause, inside a pause that exceeded ``max_pause_s``, or —
        before any cadence exists — past the absolute ``startup_stall_s``
        bound (so a compile/restore/first-step hang is not invisible)."""
        paused_s = self.paused_seconds()
        if paused_s is not None:
            return paused_s > self.max_pause_s
        t = self.stall_threshold()
        since = self.seconds_since_last_step()
        if t is not None and since is not None:
            return since > t
        if self.startup_stall_s <= 0:
            return False  # startup bound disabled (cfg.watchdog_startup_s=0)
        anchor = since if since is not None else time.time() - self.started
        return anchor > self.startup_stall_s

    def mark_done(self) -> None:
        with self._lock:
            self._done = True

    # -- reads ---------------------------------------------------------------
    def last_step(self) -> typing.Optional[int]:
        with self._lock:
            return self._last_step

    def ema_step_seconds(self) -> typing.Optional[float]:
        with self._lock:
            return self._ema_step_s

    def seconds_since_last_step(self) -> typing.Optional[float]:
        with self._lock:
            if self._last_wall is None:
                return None
            return time.time() - self._last_wall

    def snapshot(self) -> dict:
        with self._lock:
            last_step, last_wall = self._last_step, self._last_wall
            ema, done, probe = self._ema_step_s, self._done, self._feeder_probe
            paused, util_probe = self._paused_for, self._util_probe
        since = None if last_wall is None else time.time() - last_wall
        feeder_alive = None
        if probe is not None:
            try:
                feeder_alive = bool(probe())
            except Exception:
                feeder_alive = False
        if done:
            status = "done"
        elif self.stalled():  # checked FIRST: a wedged startup is a stall
            status = "stalled"
        elif last_step is None:
            status = "starting"  # compiling / restoring: no step yet
        else:
            status = "ok"  # includes a declared pause within max_pause_s
        utilization = None
        if util_probe is not None:
            try:
                utilization = {k: round(float(v), 6)
                               for k, v in util_probe().items()}
            except Exception:
                utilization = None
        paused_s = self.paused_seconds()
        return {"status": status,
                "utilization": utilization,
                "last_completed_step": last_step,
                "ema_step_seconds": None if ema is None else round(ema, 6),
                "seconds_since_last_step": (None if since is None
                                            else round(since, 3)),
                "paused_for": paused,
                "paused_seconds": (None if paused_s is None
                                   else round(paused_s, 3)),
                "feeder_alive": feeder_alive,
                "uptime_seconds": round(time.time() - self.started, 3),
                "stall_factor": self.stall_factor}


def device_memory_stats() -> typing.Dict[str, dict]:
    """Per-device ``memory_stats()`` (bytes in use / limit / peak where the
    backend reports them); {} on backends without stats (CPU) or before jax
    imported."""
    out: typing.Dict[str, dict] = {}
    try:
        import jax
        for i, d in enumerate(jax.devices()):
            try:
                stats = d.memory_stats()
            except Exception:
                stats = None
            if stats:
                out[str(i)] = {k: int(v) for k, v in stats.items()
                               if isinstance(v, (int, float))}
    except Exception:
        pass
    return out


# -- HTTP server -------------------------------------------------------------

class _ObsServer(ThreadingHTTPServer):
    daemon_threads = True
    registry: MetricsRegistry
    health: typing.Optional[Health]
    #: optional serving-SLO summary callable (serve/slo.py::ServeSLO.summary)
    #: merged into /healthz as the ``slo`` block
    slo_probe: typing.Optional[typing.Callable[[], dict]] = None
    #: fleet identity (obs/fleet.py::identity — rank, world_size,
    #: coordinator, generation) merged into /healthz so ANY scraped
    #: endpoint is self-describing in a multi-host fleet
    identity: typing.Optional[dict] = None
    #: optional SLO burn-rate summary callable (obs/slo_alerts.py::
    #: SLOAlerts.summary) merged into /healthz as the ``alerts`` block
    alerts_probe: typing.Optional[typing.Callable[[], dict]] = None
    #: optional per-tenant usage/capacity summary callable
    #: (obs/usage.py::UsageMeter.summary) merged into /healthz as the
    #: ``usage`` block the router federates across replicas
    usage_probe: typing.Optional[typing.Callable[[], dict]] = None


class _Handler(BaseHTTPRequestHandler):
    def _send(self, status: int, body: bytes, ctype: str) -> None:
        self.send_response(status)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):
        path, _, query = self.path.partition("?")
        if path == "/metrics":
            # content negotiation: the OpenMetrics flavor (exemplars +
            # ``# EOF``) only on explicit request — the default stays
            # byte-identical Prometheus 0.0.4 (the fleet parser contract)
            accept = self.headers.get("Accept", "")
            openmetrics = ("application/openmetrics-text" in accept
                           or "openmetrics=1" in query)
            if openmetrics and hasattr(self.server.registry,
                                       "render_openmetrics"):
                body = self.server.registry.render_openmetrics().encode()
                self._send(200, body, "application/openmetrics-text; "
                                      "version=1.0.0; charset=utf-8")
                return
            body = self.server.registry.render().encode()
            self._send(200, body, "text/plain; version=0.0.4; charset=utf-8")
        elif path == "/healthz":
            health = self.server.health
            # no Health wired (serve-mode exporter): report only what this
            # endpoint can attest to — a probe must not read "ok" as
            # "the engine is alive"
            snap = health.snapshot() if health is not None else \
                {"status": "metrics-only", "last_completed_step": None}
            ident = getattr(self.server, "identity", None)
            if ident:
                snap["identity"] = ident
            probe = getattr(self.server, "slo_probe", None)
            if probe is not None:
                # serving SLO summary (p50/p95/p99 per phase + error rate)
                # next to liveness — one curl answers "alive AND meeting SLO"
                try:
                    snap["slo"] = probe()
                except Exception:  # noqa: BLE001 - must not break the probe
                    snap["slo"] = None
            aprobe = getattr(self.server, "alerts_probe", None)
            if aprobe is not None:
                # SLO burn-rate alert state (obs/slo_alerts.py) — the block
                # graftwatch --check gates on
                try:
                    snap["alerts"] = aprobe()
                except Exception:  # noqa: BLE001 - must not break the probe
                    snap["alerts"] = None
            uprobe = getattr(self.server, "usage_probe", None)
            if uprobe is not None:
                # per-tenant usage + capacity accounting (obs/usage.py) —
                # the block graftmeter reads and the router federates
                try:
                    snap["usage"] = uprobe()
                except Exception:  # noqa: BLE001 - must not break the probe
                    snap["usage"] = None
            status = 503 if snap["status"] == "stalled" else 200
            self._send(status, json.dumps(snap).encode(), "application/json")
        else:
            self.send_error(404)

    def log_message(self, fmt, *args):  # quiet on stdout; debug-level only
        LOG.debug("obs %s %s", self.address_string(), fmt % args)


def start_server(port: int, registry: typing.Optional[MetricsRegistry] = None,
                 health: typing.Optional[Health] = None,
                 host: str = "127.0.0.1",
                 slo_probe: typing.Optional[typing.Callable[[], dict]] = None,
                 identity: typing.Optional[dict] = None,
                 alerts_probe: typing.Optional[
                     typing.Callable[[], dict]] = None,
                 usage_probe: typing.Optional[
                     typing.Callable[[], dict]] = None) -> _ObsServer:
    """Start the exporter on a daemon thread; ``port=0`` binds an ephemeral
    port (read it back from ``server.server_address[1]``).  ``slo_probe``
    (the REST layer's ``ServeSLO.summary``) adds a ``slo`` block to
    /healthz; ``identity`` (obs/fleet.py) adds the self-describing
    ``identity`` block every fleet-scraped endpoint must carry;
    ``alerts_probe`` (obs/slo_alerts.py::SLOAlerts.summary) adds the SLO
    burn-rate ``alerts`` block; ``usage_probe``
    (obs/usage.py::UsageMeter.summary) adds the per-tenant ``usage``
    block."""
    server = _ObsServer((host, port), _Handler)
    server.registry = registry if registry is not None else REGISTRY
    server.health = health
    server.slo_probe = slo_probe
    server.identity = identity
    server.alerts_probe = alerts_probe
    server.usage_probe = usage_probe
    thread = threading.Thread(target=server.serve_forever,
                              name="obs-exporter", daemon=True)
    server._thread = thread
    thread.start()
    return server


def stop_server(server: _ObsServer) -> None:
    server.shutdown()
    server.server_close()
    server._thread.join(timeout=5.0)


# -- diagnostics dump + watchdog ---------------------------------------------

_DUMP_SEQ = [0]
_DUMP_LOCK = make_lock("obs.exporter._DUMP_LOCK")


def dump_diagnostics(model_path: str, health: typing.Optional[Health] = None,
                     reason: str = "manual",
                     extra: typing.Optional[dict] = None) -> str:
    """Write thread stacks + device memory stats + health snapshot to
    ``<model_path>/diagnostics/hang_<ts>_<n>.txt``; returns the path.
    ``extra`` ({section name: json-able}) appends caller context — the
    watchdog passes the fleet straggler report so a stall dump says
    whether this rank was the fleet's straggler before it wedged."""
    outdir = os.path.join(model_path, "diagnostics")
    os.makedirs(outdir, exist_ok=True)
    with _DUMP_LOCK:
        _DUMP_SEQ[0] += 1
        seq = _DUMP_SEQ[0]
    path = os.path.join(
        outdir, time.strftime(f"hang_%Y%m%d_%H%M%S_{seq}.txt"))
    lines = [f"reason: {reason}",
             f"time: {time.strftime('%Y-%m-%d %H:%M:%S')}",
             f"pid: {os.getpid()}"]
    if health is not None:
        lines.append("health: " + json.dumps(health.snapshot()))
    mem = device_memory_stats()
    lines.append("device_memory_stats: "
                 + (json.dumps(mem, indent=1) if mem else "(unavailable)"))
    for section, doc in (extra or {}).items():
        try:
            lines.append(f"{section}: " + json.dumps(doc, sort_keys=True))
        except (TypeError, ValueError):
            lines.append(f"{section}: {doc!r}")
    # latest graftprof window (main.py writes it at profiler stop): where
    # device time was going BEFORE the stall is exactly the third artifact
    # a hang post-mortem wants next to thread stacks and memory
    summary_path = os.path.join(model_path, "profile_summary.json")
    if os.path.exists(summary_path):
        try:
            with open(summary_path) as f:
                lines.append("profile_summary: "
                             + json.dumps(json.load(f), sort_keys=True))
        except Exception as e:
            lines.append(f"profile_summary: (unreadable: {e})")
    names = {t.ident: t.name for t in threading.enumerate()}
    lines.append("")
    for ident, frame in sorted(sys._current_frames().items()):
        lines.append(f"--- thread {names.get(ident, '?')} (ident {ident}) "
                     f"---")
        lines.extend(l.rstrip("\n") for l in traceback.format_stack(frame))
        lines.append("")
    with open(path, "w") as f:
        f.write("\n".join(lines) + "\n")
    LOG.warning("diagnostics dumped to %s (%s)", path, reason)
    return path


class Watchdog(threading.Thread):
    """Dump diagnostics when ``Health.stalled()`` trips — no step within
    ``stall_factor`` x the EMA step time (floored at ``min_stall_s``), or a
    declared pause exceeding ``max_pause_s`` (a hung checkpoint save must
    not hide behind its own pause).  One dump per stall; re-arms when steps
    resume.  ``factor``/``min_stall_s``/``max_pause_s``, when given, are
    written INTO the shared Health so /healthz and the watchdog can never
    disagree about what counts as stalled."""

    _ARMED = object()

    def __init__(self, health: Health, model_path: str,
                 factor: typing.Optional[float] = None, poll_s: float = 1.0,
                 min_stall_s: typing.Optional[float] = None,
                 max_pause_s: typing.Optional[float] = None,
                 registry: typing.Optional[MetricsRegistry] = None,
                 extra_fn: typing.Optional[
                     typing.Callable[[], dict]] = None,
                 flight=None):
        super().__init__(name="obs-watchdog", daemon=True)
        self.health = health
        self.model_path = model_path
        #: optional {section: doc} provider inlined into each stall dump
        #: (Obs wires the fleet straggler summary here)
        self.extra_fn = extra_fn
        #: optional flight recorder (obs/flight.py): a stall also writes
        #: an incident bundle when its ``watchdog`` trigger is armed
        self.flight = flight
        # stall visibility beyond the diagnostics dir: the supervisor and
        # alerting watch this counter on /metrics instead of scraping files
        reg = registry if registry is not None else REGISTRY
        self._stalls = reg.counter(
            "hbnlp_watchdog_stalls_total",
            "hang-watchdog stall dumps fired (one per distinct stall)")
        if factor is not None:
            health.stall_factor = float(factor)
        if min_stall_s is not None:
            health.min_stall_s = float(min_stall_s)
        if max_pause_s is not None:
            health.max_pause_s = float(max_pause_s)
        self.poll_s = poll_s
        self.dumps: typing.List[str] = []
        self._stop_evt = threading.Event()  # NOT _stop: Thread uses that name
        # armed-state sentinel: must be distinct from step values INCLUDING
        # None (a startup stall has last_step None)
        self._fired_at_step: typing.Any = self._ARMED

    def run(self) -> None:
        while not self._stop_evt.wait(self.poll_s):
            self._check()

    def _check(self) -> None:
        h = self.health
        step = h.last_step()
        if not h.stalled():
            self._fired_at_step = self._ARMED  # steps flowing / benign
            return                             # pause: re-arm
        if (self._fired_at_step is not self._ARMED
                and self._fired_at_step == step):
            return  # already dumped for this stall
        self._fired_at_step = step
        self._stalls.inc()
        paused_s = h.paused_seconds()
        threshold = h.stall_threshold()
        if paused_s is not None:
            why = (f"declared pause {h.paused_for()!r} exceeded "
                   f"max_pause_s ({paused_s:.1f}s > {h.max_pause_s}s)")
        elif threshold is None:
            why = (f"no step cadence established within startup_stall_s "
                   f"({h.startup_stall_s}s) — wedged in compile/restore/"
                   f"first step")
        else:
            why = (f"no step completed in "
                   f"{h.seconds_since_last_step():.2f}s (threshold "
                   f"{threshold:.2f}s = max({h.stall_factor} x "
                   f"EMA {h.ema_step_seconds():.4f}s, {h.min_stall_s}s))")
        extra = None
        if self.extra_fn is not None:
            try:
                extra = {"fleet": self.extra_fn()}
            except Exception as e:  # noqa: BLE001 - the dump must land
                extra = {"fleet": {"error": repr(e)}}
        self.dumps.append(dump_diagnostics(
            self.model_path, h,
            reason=f"watchdog: {why}, last step {step}", extra=extra))
        if self.flight is not None:
            try:
                self.flight.dump("watchdog", extra={"why": why,
                                                    "last_step": step})
            except Exception:  # noqa: BLE001 - the text dump already landed
                pass

    def stop(self, timeout: float = 5.0) -> None:
        self._stop_evt.set()
        self.join(timeout)
