"""Fleet observability: cross-rank metrics federation, merged traces, and
straggler attribution (docs/observability.md "Fleet observability").

Every obs surface built so far — the registry, the span tracer, the
exporter, graftprof — sees exactly ONE rank.  The reference delegated all
multi-host visibility to the TF1 TPU runtime's opaque session; this module
is the native replacement, built on the same shared-filesystem channel the
supervisor fleet protocol already trusts (tools/supervise.py
``--fleet-dir`` — the one channel that survives the coordinator being the
casualty):

- **posting** (:class:`FleetReporter`, the child side): each rank appends
  per-step dispatch timestamps to ``<fleet_dir>/obs/steps_r<rank>.jsonl``,
  re-renders its registry to ``metrics_r<rank>.prom`` (throttled), and
  exports its span trace to ``trace_r<rank>.json`` on close;
- **federation** (:func:`federate` / :class:`FleetFederation`): per-rank
  Prometheus snapshots merge into one exposition — every sample gains a
  ``rank`` label, counters sum into ``rank="fleet"`` aggregates, gauges
  aggregate min/mean/max, histograms merge EXACTLY (the shared bucket-edge
  constants — ``SERVE_LATENCY_BUCKETS``, ``DEFAULT_BUCKETS`` — make the
  element-wise count sum lossless; mismatched edges are rejected loudly);
- **trace merge** (:func:`estimate_offsets` / :func:`merge_traces`):
  per-rank clock offsets are estimated from matching ``dist/barrier`` span
  END times (every rank leaves a barrier at nearly the same true instant),
  and the per-rank Chrome traces merge into one file with a lane (pid) per
  rank on a common timebase;
- **attribution** (:func:`straggler_report`): per-step dispatch skew, an
  EMA straggler score per rank, and the barrier-wait decomposition —
  seconds the fast ranks would spend idle waiting for the slowest — the
  fleet-level twin of graftprof's per-device ``comm + idle`` bucket.

This module is STDLIB-ONLY (no jax, no numpy): tools/supervise.py loads it
file-path style (``_load_light``) so a broken accelerator install cannot
take fleet visibility down with the child.
"""
from __future__ import annotations

import json
import logging
import math
import os
import re
import threading
import time
import typing
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

try:
    from .registry import (bucket_quantile, merge_histogram_counts,
                           sample_quantile)
except ImportError:  # loaded by file path (tools/supervise.py _load_light)
    import importlib.util as _ilu
    _spec = _ilu.spec_from_file_location(
        "hbnlp_obs_registry_for_fleet",
        os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     "registry.py"))
    _reg = _ilu.module_from_spec(_spec)
    _spec.loader.exec_module(_reg)
    bucket_quantile = _reg.bucket_quantile
    merge_histogram_counts = _reg.merge_histogram_counts
    sample_quantile = _reg.sample_quantile

try:
    from ..sync import make_lock
except ImportError:  # loaded by file path (tools/supervise.py _load_light)
    import sys as _sys
    _sync = (_sys.modules.get("homebrewnlp_tpu.sync")
             or _sys.modules.get("hbnlp_sync"))
    if _sync is not None:
        make_lock = _sync.make_lock
    else:  # truly standalone: plain lock, no recording

        def make_lock(name: str) -> "threading.Lock":
            return threading.Lock()


LOG = logging.getLogger("homebrewnlp_tpu.obs.fleet")

#: env vars the supervisor injects so the child (and its run-start markers,
#: /healthz identity block, and fleet postings) know who they are even in
#: supervision-only fleets where the HBNLP_DIST_* vars stay unset
ENV_FLEET_DIR = "HBNLP_FLEET_DIR"
ENV_FLEET_RANK = "HBNLP_FLEET_RANK"
ENV_FLEET_WORLD = "HBNLP_FLEET_WORLD"
ENV_FLEET_GENERATION = "HBNLP_FLEET_GENERATION"

OBS_SUBDIR = "obs"
EMA_ALPHA = 0.2  # straggler-score EMA weight (matches Health.ema_alpha)


def identity(cfg=None) -> dict:
    """Who this process is inside the fleet — the identity block /healthz
    and the metrics.jsonl run-start markers carry so ANY scraped endpoint
    or log file is self-describing.  Resolution is env-first (the
    supervisor injects per-host values so one config serves every host),
    falling back to the dist_* config knobs, then single-host defaults."""
    def _pick(env_names, cfg_attr, default):
        for n in env_names:
            v = os.environ.get(n)
            if v not in (None, ""):
                return v
        return getattr(cfg, cfg_attr, default) or default
    rank = int(_pick((ENV_FLEET_RANK, "HBNLP_DIST_PROCESS_ID"),
                     "dist_process_id", 0))
    world = int(_pick((ENV_FLEET_WORLD, "HBNLP_DIST_NUM_PROCESSES"),
                      "dist_num_processes", 1))
    coord = str(_pick(("HBNLP_DIST_COORDINATOR",), "dist_coordinator", ""))
    gen = os.environ.get(ENV_FLEET_GENERATION)
    out = {"rank": rank, "world_size": max(1, world), "coordinator": coord}
    if gen not in (None, ""):
        out["generation"] = int(gen)
    return out


def fleet_dir_from(cfg=None) -> str:
    """The shared fleet directory, env-first (``HBNLP_FLEET_DIR`` — the
    supervisor's injection — overrides ``cfg.fleet_dir``)."""
    return os.environ.get(ENV_FLEET_DIR) or getattr(cfg, "fleet_dir", "") \
        or ""


# -- Prometheus text parsing --------------------------------------------------

_SAMPLE_RE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(?:\{(.*)\})?\s+(\S+)\s*$")
_LABEL_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')


_UNESCAPE_RE = re.compile(r"\\(.)")


def _unescape(v: str) -> str:
    # single-pass left-to-right, like Prometheus itself: sequential
    # .replace calls would let one pass consume the backslash of the next
    # escape pair (r"a\nb" escaped is r"a\\nb", which must NOT round-trip
    # to 'a\<newline>b')
    return _UNESCAPE_RE.sub(
        lambda m: "\n" if m.group(1) == "n" else m.group(1), v)


def _escape(v: str) -> str:
    return (str(v).replace("\\", r"\\").replace('"', r'\"')
            .replace("\n", r"\n"))


def _fmt(v: float) -> str:
    f = float(v)
    if f != f:
        return "NaN"  # a rank's failing callback gauge renders NaN — one
        # bad sample must not take the whole federation down
    if f == math.inf:
        return "+Inf"
    if f == -math.inf:
        return "-Inf"
    return str(int(f)) if f == int(f) else repr(f)


def _label_str(labels: typing.Dict[str, str]) -> str:
    if not labels:
        return ""
    return "{" + ",".join(f'{k}="{_escape(v)}"'
                          for k, v in sorted(labels.items())) + "}"


class Family:
    """One metric family parsed from Prometheus text: flat samples for
    counters/gauges/untyped, reconstructed per-labelset histograms for
    histograms."""

    def __init__(self, name: str, kind: str = "untyped", help_text: str = ""):
        self.name = name
        self.kind = kind
        self.help = help_text
        #: [(labels dict, value)] for counter/gauge/untyped
        self.samples: typing.List[typing.Tuple[dict, float]] = []
        #: histogram parts: {labelset key: {"labels", "le": {edge: cum},
        #:                                  "sum", "count"}}
        self.hist: typing.Dict[tuple, dict] = {}

    def _hist_slot(self, labels: dict) -> dict:
        key = tuple(sorted(labels.items()))
        slot = self.hist.get(key)
        if slot is None:
            slot = {"labels": dict(labels), "le": {}, "sum": 0.0,
                    "count": 0.0}
            self.hist[key] = slot
        return slot

    def snapshots(self) -> typing.List[typing.Tuple[dict, tuple, list,
                                                    float, float]]:
        """Per-labelset histogram snapshots as
        ``(labels, edges, non_cumulative_counts, sum, count)`` — the
        ``registry.Histogram.snapshot`` shape ``merge_histogram_counts``
        and ``bucket_quantile`` consume."""
        out = []
        for slot in self.hist.values():
            finite = sorted(e for e in slot["le"] if e != math.inf)
            cum_prev = 0.0
            counts = []
            for e in finite:
                c = slot["le"][e]
                counts.append(c - cum_prev)
                cum_prev = c
            inf_cum = slot["le"].get(math.inf, cum_prev)
            counts.append(inf_cum - cum_prev)
            out.append((slot["labels"], tuple(finite), counts,
                        slot["sum"], slot["count"]))
        return out


def parse_prom_text(text: str) -> typing.Dict[str, Family]:
    """Parse a Prometheus 0.0.4 text exposition into families.  Built for
    OUR renderer's output (registry.render / this module's federate), but
    tolerant: unknown lines are skipped, untyped samples become untyped
    families."""
    families: typing.Dict[str, Family] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        if line.startswith("#"):
            parts = line.split(None, 3)
            if len(parts) >= 3 and parts[1] in ("HELP", "TYPE"):
                fam = families.setdefault(parts[2], Family(parts[2]))
                if parts[1] == "TYPE":
                    fam.kind = parts[3] if len(parts) > 3 else "untyped"
                elif len(parts) > 3:
                    fam.help = parts[3]
            continue
        m = _SAMPLE_RE.match(line)
        if not m:
            continue
        name, labelstr, valstr = m.group(1), m.group(2), m.group(3)
        try:
            value = float(valstr)
        except ValueError:
            continue
        labels = {k: _unescape(v)
                  for k, v in _LABEL_RE.findall(labelstr or "")}
        base = None
        for suffix in ("_bucket", "_sum", "_count"):
            cand = name[:-len(suffix)] if name.endswith(suffix) else None
            if cand and cand in families and families[cand].kind \
                    == "histogram":
                base = (cand, suffix)
                break
        if base is not None:
            fam = families[base[0]]
            if base[1] == "_bucket":
                le = labels.pop("le", None)
                if le is None:
                    continue
                edge = math.inf if le == "+Inf" else float(le)
                fam._hist_slot(labels)["le"][edge] = value
            elif base[1] == "_sum":
                fam._hist_slot(labels)["sum"] = value
            else:
                fam._hist_slot(labels)["count"] = value
            continue
        fam = families.setdefault(name, Family(name))
        fam.samples.append((labels, value))
    return {n: f for n, f in families.items() if f.samples or f.hist}


# -- federation ---------------------------------------------------------------

FLEET_RANK_LABEL = "rank"
FLEET_AGG_VALUE = "fleet"  # the rank label value aggregate series carry

#: gauges whose listed value is a DOCUMENTED "not applicable" sentinel, not
#: a measurement (serve/slo.py: -1 = no KV pool / no lane scheduler, i.e. a
#: serialized engine).  Sentinels are excluded from the fleet min/mean/max —
#: a mixed fleet (some ranks batching, some serialized) would otherwise
#: report fleet-min -1 and drag the mean below every real pool level.  A
#: fleet that is ALL sentinel keeps the sentinel as its aggregate (the
#: series stays present and honest).
GAUGE_SENTINELS = {
    "hbnlp_serve_kv_blocks_free": -1.0,
    "hbnlp_serve_lane_occupancy": -1.0,
}


def _group_key(labels: dict) -> tuple:
    return tuple(sorted((k, v) for k, v in labels.items()
                        if k != FLEET_RANK_LABEL))


def federate(rank_texts: typing.Dict[int, str],
             errors: typing.Optional[list] = None) -> str:
    """Merge per-rank Prometheus expositions into one federated text:

    - every per-rank sample keeps its series name and gains
      ``rank="<r>"`` (samples already carrying a rank label — the
      supervisor's own, satellite-fixed series — are passed through);
    - fleet aggregates ride the same family under ``rank="fleet"``:
      counters sum; gauges get ``agg="min"|"mean"|"max"``; histograms
      merge exactly via :func:`registry.merge_histogram_counts` (same
      edges summed element-wise — lossless), and mismatched edges are
      rejected LOUDLY: no aggregate, an ``hbnlp_fleet_merge_errors``
      sample, and an entry in ``errors``.

    Kind conflicts between ranks (same family name, different TYPE) are
    treated the same way — per-rank samples still render, the aggregate is
    refused."""
    if errors is None:
        errors = []
    parsed = {r: parse_prom_text(t) for r, t in sorted(rank_texts.items())}
    names = sorted({n for fams in parsed.values() for n in fams})
    lines: typing.List[str] = []
    for name in names:
        per_rank = [(r, fams[name]) for r, fams in parsed.items()
                    if name in fams]
        kinds = {f.kind for _, f in per_rank}
        fam0 = per_rank[0][1]
        kind = fam0.kind if len(kinds) == 1 else "untyped"
        if len(kinds) != 1:
            errors.append(f"{name}: TYPE differs across ranks "
                          f"({sorted(kinds)}); no aggregate emitted")
        lines.append(f"# HELP {name} {fam0.help}" if fam0.help
                     else f"# HELP {name} (federated)")
        lines.append(f"# TYPE {name} {kind}")
        # counters / gauges / untyped ----------------------------------------
        # dedup by the FINAL label set (a series already carrying a rank
        # label — e.g. the supervisor's own — may appear in several
        # snapshots; last posting wins, and aggregates see it once)
        flat: typing.Dict[str, typing.Tuple[dict, float]] = {}
        for r, fam in per_rank:
            for labels, value in fam.samples:
                out = dict(labels)
                out.setdefault(FLEET_RANK_LABEL, str(r))
                flat[_label_str(out)] = (out, value)
        groups: typing.Dict[tuple, typing.List[float]] = {}
        for ls, (out, value) in flat.items():
            lines.append(f"{name}{ls} {_fmt(value)}")
            if value == value:  # a NaN sample (failed callback gauge)
                # renders per-rank but must not poison the aggregates
                groups.setdefault(_group_key(out), []).append(value)
        if len(kinds) == 1 and kind in ("counter", "gauge"):
            for key, values in sorted(groups.items()):
                base = dict(key)
                base[FLEET_RANK_LABEL] = FLEET_AGG_VALUE
                if kind == "counter":
                    lines.append(f"{name}{_label_str(base)} "
                                 f"{_fmt(sum(values))}")
                else:
                    sentinel = GAUGE_SENTINELS.get(name)
                    if sentinel is not None:
                        real = [v for v in values if v != sentinel]
                        values = real or values  # all-sentinel: keep as-is
                    for agg, v in (("min", min(values)),
                                   ("mean", sum(values) / len(values)),
                                   ("max", max(values))):
                        lines.append(
                            f"{name}{_label_str(dict(base, agg=agg))} "
                            f"{_fmt(v)}")
        # histograms ---------------------------------------------------------
        hflat: typing.Dict[str, tuple] = {}
        for r, fam in per_rank:
            for labels, edges, counts, hsum, hcount in fam.snapshots():
                out = dict(labels)
                out.setdefault(FLEET_RANK_LABEL, str(r))
                hflat[_label_str(out)] = (out, edges, counts, hsum, hcount)
        hist_groups: typing.Dict[tuple, list] = {}
        for out, edges, counts, hsum, hcount in hflat.values():
            lines.extend(_render_hist(name, out, edges, counts,
                                      hsum, hcount))
            hist_groups.setdefault(_group_key(out), []).append(
                (edges, counts, hsum, hcount))
        if len(kinds) == 1 and kind == "histogram":
            for key, parts in sorted(hist_groups.items()):
                base = dict(key)
                base[FLEET_RANK_LABEL] = FLEET_AGG_VALUE
                try:
                    edges, merged = merge_histogram_counts(
                        [(e, c) for e, c, _, _ in parts])
                except ValueError as e:
                    errors.append(f"{name}{_label_str(dict(key))}: {e}")
                    continue
                lines.extend(_render_hist(
                    name, base, edges, merged,
                    sum(p[2] for p in parts), sum(p[3] for p in parts)))
    # a GAUGE, always emitted (including 0): the value is recomputed per
    # render, so counter semantics would read every clean scrape after a
    # bad one as a counter reset, and absent-when-zero would keep
    # increase()-style alerts from ever arming off a clean baseline
    lines.append("# HELP hbnlp_fleet_merge_errors federation aggregates "
                 "refused this render (bucket-edge or TYPE mismatch "
                 "across ranks)")
    lines.append("# TYPE hbnlp_fleet_merge_errors gauge")
    lines.append(f"hbnlp_fleet_merge_errors {len(errors)}")
    for msg in errors:
        LOG.warning("fleet federation: %s", msg)
    return "\n".join(lines) + "\n"


def _render_hist(name: str, labels: dict, edges: typing.Sequence[float],
                 counts: typing.Sequence[float], hsum: float,
                 hcount: float) -> typing.List[str]:
    lines = []
    cum = 0.0
    for e, c in zip(edges, counts):
        cum += c
        lines.append(f"{name}_bucket{_label_str(dict(labels, le=_fmt(e)))} "
                     f"{_fmt(cum)}")
    cum += counts[-1]
    lines.append(f"{name}_bucket{_label_str(dict(labels, le='+Inf'))} "
                 f"{_fmt(cum)}")
    lines.append(f"{name}_sum{_label_str(labels)} {_fmt(hsum)}")
    lines.append(f"{name}_count{_label_str(labels)} {_fmt(hcount)}")
    return lines


# -- step posts + straggler attribution ---------------------------------------

_STEPS_RE = re.compile(r"^steps_r(\d+)\.jsonl$")
_PROM_RE = re.compile(r"^(?:metrics|supervisor)_r(\d+)\.prom$")
_TRACE_RE = re.compile(r"^trace_r(\d+)\.json$")


def obs_dir(fleet_dir: str) -> str:
    return os.path.join(fleet_dir, OBS_SUBDIR)


#: how much of each rank's step-post file a read considers (the newest
#: tail): the files are append-only for the run's whole lifetime, and the
#: federated /metrics endpoint re-reads them on EVERY scrape — an
#: unbounded read would grow a week-long run's scrape cost linearly (tens
#: of MB per rank over what may be a network mount).  2 MiB is ~40k posts
#: per rank, far more than skew/EMA attribution needs.
STEP_POSTS_TAIL_BYTES = 2 * 1024 * 1024


def read_step_posts(fleet_dir: str,
                    tail_bytes: int = STEP_POSTS_TAIL_BYTES
                    ) -> typing.Dict[int, typing.Dict[int, dict]]:
    """{rank: {step: {"wall": dispatch wall, "gen": fleet generation or
    None}}} from the newest ``tail_bytes`` of each per-rank step posting
    file (0 = unbounded).  Appends across relaunches; a re-run step's
    NEWEST post wins (the resumed generation re-dispatches steps behind
    its restore point), and the generation tag lets skew attribution
    refuse to compare one rank's pre-crash walls against another's
    post-relaunch walls."""
    out: typing.Dict[int, typing.Dict[int, dict]] = {}
    d = obs_dir(fleet_dir)
    try:
        names = os.listdir(d)
    except OSError:
        return out
    for fn in names:
        m = _STEPS_RE.match(fn)
        if not m:
            continue
        rank = int(m.group(1))
        steps: typing.Dict[int, dict] = {}
        try:
            with open(os.path.join(d, fn), "rb") as f:
                if tail_bytes > 0:
                    f.seek(0, os.SEEK_END)
                    size = f.tell()
                    if size > tail_bytes:
                        f.seek(size - tail_bytes)
                        f.readline()  # discard the partial first line
                    else:
                        f.seek(0)
                for raw in f:
                    try:
                        row = json.loads(raw)
                        gen = row.get("gen")
                        steps[int(row["step"])] = {
                            "wall": float(row["wall"]),
                            "gen": None if gen is None else int(gen)}
                    except (ValueError, KeyError, TypeError):
                        continue  # torn tail line of a live writer
        except OSError:
            continue
        if steps:
            out[rank] = steps
    return out


def straggler_report(posts: typing.Dict[int, typing.Dict[int, dict]],
                     ema_alpha: float = EMA_ALPHA) -> dict:
    """Per-step skew + per-rank straggler attribution over the steps EVERY
    posting rank dispatched in the SAME fleet generation
    (docs/observability.md "Fleet observability"):

    - ``skew_ms`` — max-minus-min dispatch wall across ranks per step
      (last / mean / max / p95): how far apart the fleet runs;
    - ``straggler_score_ms`` per rank — EMA of that rank's lag behind the
      fastest rank each step; ``straggler_rank`` = argmax (None when no
      rank is measurably behind);
    - ``barrier_wait_s`` per rank — seconds this rank would idle at a
      per-step barrier waiting for the slowest rank (fast ranks accumulate
      the most); the total is the fleet-level twin of graftprof's
      ``comm + idle`` bucket: compute the whole fleet paid to its skew.

    The generation match matters after an elastic relaunch: ranks restore
    from different steps, so one rank RE-dispatches a step the other only
    ran before the crash — comparing those walls would report the whole
    outage as per-step skew.  Steps whose newest posts disagree on
    generation are excluded (counted in ``n_generation_skipped``)."""
    ranks = sorted(posts)
    report: dict = {"ranks": {}, "n_common_steps": 0,
                    "n_generation_skipped": 0, "skew_ms": None,
                    "straggler_rank": None, "barrier_wait_total_s": 0.0}
    for r in ranks:
        walls = [posts[r][s]["wall"] for s in sorted(posts[r])]
        deltas = [b - a for a, b in zip(walls, walls[1:]) if b > a]
        report["ranks"][str(r)] = {
            "steps": len(walls),
            "last_step": max(posts[r]),
            "mean_step_s": (sum(deltas) / len(deltas)) if deltas else None,
            "straggler_score_ms": 0.0,
            "barrier_wait_s": 0.0,
        }
    if len(ranks) < 2:
        return report
    candidates = sorted(set.intersection(*(set(posts[r]) for r in ranks)))
    common = [s for s in candidates
              if len({posts[r][s]["gen"] for r in ranks}) == 1]
    report["n_common_steps"] = len(common)
    report["n_generation_skipped"] = len(candidates) - len(common)
    if not common:
        return report
    skews = []
    scores = {r: 0.0 for r in ranks}
    waits = {r: 0.0 for r in ranks}
    for s in common:
        walls = {r: posts[r][s]["wall"] for r in ranks}
        lo, hi = min(walls.values()), max(walls.values())
        skews.append((hi - lo) * 1e3)
        for r in ranks:
            lag_ms = (walls[r] - lo) * 1e3
            scores[r] = ema_alpha * lag_ms + (1 - ema_alpha) * scores[r]
            waits[r] += hi - walls[r]
    for r in ranks:
        report["ranks"][str(r)]["straggler_score_ms"] = round(scores[r], 3)
        report["ranks"][str(r)]["barrier_wait_s"] = round(waits[r], 6)
    report["skew_ms"] = {
        "last": round(skews[-1], 3),
        "mean": round(sum(skews) / len(skews), 3),
        "max": round(max(skews), 3),
        "p95": round(sample_quantile(skews, 0.95), 3),
    }
    report["barrier_wait_total_s"] = round(sum(waits.values()), 6)
    worst = max(ranks, key=lambda r: scores[r])
    if scores[worst] > 0:
        report["straggler_rank"] = worst
    return report


# -- trace merge --------------------------------------------------------------

BARRIER_SPAN = "dist/barrier"


def _barrier_ends(trace: dict) -> typing.Dict[tuple, float]:
    """{(barrier name, occurrence index): wall end time} of every
    ``dist/barrier`` span in one rank's trace — ranks leave a given
    barrier at (nearly) the same true instant, so matching END times
    across ranks carry the inter-rank clock offset."""
    epoch = float(trace.get("otherData", {}).get("wall_epoch", 0.0))
    seen: typing.Dict[str, int] = {}
    out: typing.Dict[tuple, float] = {}
    events = sorted((e for e in trace.get("traceEvents", [])
                     if e.get("ph") == "X" and e.get("name") == BARRIER_SPAN),
                    key=lambda e: e.get("ts", 0.0))
    for e in events:
        name = str(e.get("args", {}).get("barrier", ""))
        k = seen.get(name, 0)
        seen[name] = k + 1
        out[(name, k)] = epoch + (e.get("ts", 0.0)
                                  + e.get("dur", 0.0)) / 1e6
    return out


def estimate_offsets(traces: typing.Dict[int, dict]) -> dict:
    """Per-rank clock offsets from matched barrier-exit pairs.

    ``offset[r]`` is the seconds to ADD to rank r's wall clock to land on
    the base rank's timebase — the lowest rank WITH barrier spans, so a
    base candidate whose trace lost its spans cannot silently zero every
    pairing — estimated as the mean of ``end_base(b) - end_r(b)`` over
    every barrier pair both ranks recorded.  ``bound_s`` is the error
    bound the docs commit to: the maximum residual of any single pair
    around that mean (barrier release skew + wall-clock sampling noise).
    A rank with NO matched pairs falls back to offset 0 (raw
    ``wall_epoch`` alignment) and is listed in ``ranks_without_pairs``;
    when that happens (or no rank has pairs) ``bound_s`` is None — the
    merge still renders, but it must not advertise an alignment one lane
    does not have."""
    ranks = sorted(traces)
    out = {"base_rank": ranks[0] if ranks else None,
           "offsets_s": {str(r): 0.0 for r in ranks},
           "bound_s": None, "n_pairs": 0, "ranks_without_pairs": [],
           "ranks_with_spans": []}
    if len(ranks) < 2:
        return out
    ends_by_rank = {r: _barrier_ends(traces[r]) for r in ranks}
    # which lanes recorded ANY barrier span: the --check gate needs to
    # tell 'no rank barriers' (supervision-only fleets: legitimate raw
    # wall-clock merge) from 'SOME lanes have barrier evidence and others
    # lost theirs' (a mixed merge that must not gate green) — with two
    # ranks, pair counts alone cannot distinguish the cases
    out["ranks_with_spans"] = [r for r in ranks if ends_by_rank[r]]
    base_rank = next((r for r in ranks if ends_by_rank[r]), ranks[0])
    out["base_rank"] = base_rank
    base = ends_by_rank[base_rank]
    residual_max = 0.0
    n_pairs = 0
    for r in ranks:
        if r == base_rank:
            continue
        ends = ends_by_rank[r]
        deltas = [base[k] - ends[k] for k in sorted(set(base) & set(ends))]
        if not deltas:
            out["ranks_without_pairs"].append(r)
            continue
        off = sum(deltas) / len(deltas)
        out["offsets_s"][str(r)] = round(off, 9)
        residual_max = max(residual_max,
                           max(abs(d - off) for d in deltas))
        n_pairs += len(deltas)
    out["n_pairs"] = n_pairs
    if n_pairs and not out["ranks_without_pairs"]:
        out["bound_s"] = round(residual_max, 9)
    return out


def merge_traces(traces: typing.Dict[int, dict],
                 offsets: typing.Optional[dict] = None) -> dict:
    """One Chrome trace with a lane (pid) per rank on a common timebase.

    Each rank's events shift onto the base rank's wall clock
    (``wall_epoch + ts + offset``); the merged origin is the earliest
    shifted event, so Perfetto renders small relative times.  Thread-name
    metadata survives per rank; each rank's process lane is named
    ``rank <r>``."""
    if offsets is None:
        offsets = estimate_offsets(traces)
    shifted: typing.List[dict] = []
    origin = None
    per_rank: typing.List[typing.Tuple[int, float, dict]] = []
    for r, trace in sorted(traces.items()):
        epoch = float(trace.get("otherData", {}).get("wall_epoch", 0.0))
        off = float(offsets["offsets_s"].get(str(r), 0.0))
        per_rank.append((r, epoch + off, trace))
        for e in trace.get("traceEvents", []):
            if e.get("ph") == "X":
                t = epoch + off + e.get("ts", 0.0) / 1e6
                origin = t if origin is None else min(origin, t)
    origin = origin or 0.0
    for r, base_wall, trace in per_rank:
        shifted.append({"ph": "M", "name": "process_name", "pid": r,
                        "tid": 0, "args": {"name": f"rank {r}"}})
        for e in trace.get("traceEvents", []):
            if e.get("ph") == "M" and e.get("name") == "thread_name":
                shifted.append(dict(e, pid=r))
            elif e.get("ph") == "X":
                ts = (base_wall + e.get("ts", 0.0) / 1e6 - origin) * 1e6
                shifted.append(dict(e, pid=r, ts=round(ts, 3)))
    return {"traceEvents": shifted, "displayTimeUnit": "ms",
            "otherData": {"wall_origin": origin,
                          "clock_offsets": offsets,
                          "ranks": sorted(traces)}}


def read_traces(fleet_dir: str) -> typing.Dict[int, dict]:
    out: typing.Dict[int, dict] = {}
    d = obs_dir(fleet_dir)
    try:
        names = os.listdir(d)
    except OSError:
        return out
    for fn in names:
        m = _TRACE_RE.match(fn)
        if not m:
            continue
        try:
            with open(os.path.join(d, fn)) as f:
                out[int(m.group(1))] = json.load(f)
        except (OSError, ValueError) as e:
            LOG.warning("fleet trace %s unreadable: %r", fn, e)
    return out


# -- child side: FleetReporter ------------------------------------------------

class FleetReporter:
    """The child-side posting half, wired by ``Obs`` and fed from the
    metric drain (``AsyncMetricWriter``): NEVER from the dispatch hot path
    — the ``host-sync`` ratchet guards the loop, and this class only runs
    where file I/O already happens.

    Every write is best-effort: the fleet dir may be a network mount, and
    a posting hiccup must degrade to a logged miss (the federation shows a
    stale rank), never kill training — the same weather contract as the
    supervisor's fleet protocol."""

    def __init__(self, fleet_dir: str, rank: int, world_size: int,
                 registry=None, min_render_s: float = 2.0,
                 clock: typing.Callable[[], float] = time.time):
        self.dir = obs_dir(fleet_dir)
        self.fleet_dir = fleet_dir
        self.rank = int(rank)
        self.world_size = int(world_size)
        self.registry = registry
        self.min_render_s = float(min_render_s)
        self.clock = clock
        #: fleet generation of THIS launch (supervisor-injected env,
        #: constant for the process lifetime): stamped on every step post
        #: so skew attribution never compares walls across relaunches
        self.generation = identity().get("generation")
        self._lock = make_lock("obs.fleet.FleetReporter._lock")
        self._last_render = 0.0
        self._steps_f = None
        self._warned = False
        try:
            os.makedirs(self.dir, exist_ok=True)
            self._steps_f = open(  # graftcheck: disable=bare-io
                os.path.join(self.dir, f"steps_r{self.rank}.jsonl"), "a")
        except OSError as e:
            self._warn(f"cannot open step-post file: {e!r}")

    def _warn(self, msg: str) -> None:
        if not self._warned:
            LOG.warning("fleet posting degraded (rank %d): %s",
                        self.rank, msg)
            self._warned = True

    def step_completed(self, step: int, dispatch_wall: float) -> None:
        """Post one step's DISPATCH wall time (drain-side call — the drain
        already holds the dispatch timestamp, and dispatch spacing is the
        cadence skew attribution needs, not drain spacing)."""
        row = {"step": int(step), "wall": float(dispatch_wall)}
        if self.generation is not None:
            row["gen"] = self.generation
        due = False
        with self._lock:
            if self._steps_f is not None:
                try:
                    self._steps_f.write(json.dumps(row) + "\n")
                    self._steps_f.flush()
                except OSError as e:
                    self._warn(f"step post failed: {e!r}")
            now = self.clock()
            if (self.registry is not None
                    and now - self._last_render >= self.min_render_s):
                self._last_render = now
                due = True
        if due:
            self.render_prom()

    def render_prom(self) -> None:
        if self.registry is None:
            return
        # evaluate the registry's render-time gauge callbacks OUTSIDE the
        # reporter lock: a callback may take its own lock (Health, engine
        # probes) and must never nest under ours — the recorded-edge
        # validation (graftsync --validate) pins this
        text = self.registry.render()
        path = os.path.join(self.dir, f"metrics_r{self.rank}.prom")
        tmp = f"{path}.tmp.{os.getpid()}"
        try:
            with self._lock:  # tmp name is per-pid, not per-thread
                with open(tmp, "w") as f:  # graftcheck: disable=bare-io
                    f.write(text)
                os.replace(tmp, path)
        except OSError as e:
            self._warn(f"prom snapshot failed: {e!r}")

    def export_trace(self, tracer) -> None:
        """Copy this rank's span trace into the fleet dir (Obs.close)."""
        if tracer is None:
            return
        try:
            tracer.export(os.path.join(self.dir,
                                       f"trace_r{self.rank}.json"))
        except Exception as e:  # noqa: BLE001 - never fail the run's exit
            self._warn(f"trace export failed: {e!r}")

    def skew_summary(self) -> dict:
        """The straggler report over the CURRENT fleet-dir postings — the
        watchdog inlines it into stall diagnostics, so a hang dump says
        whether THIS rank was the fleet's straggler before it wedged."""
        try:
            report = straggler_report(read_step_posts(self.fleet_dir))
        except Exception as e:  # noqa: BLE001 - diagnostics must not throw
            return {"error": repr(e)}
        report["own_rank"] = self.rank
        return report

    def close(self) -> None:
        self.render_prom()
        with self._lock:
            if self._steps_f is not None:
                try:
                    self._steps_f.close()
                except OSError:
                    pass
                self._steps_f = None


# -- read side: FleetFederation + federation server ---------------------------

class FleetFederation:
    """The supervisor/CLI-side read half: renders the federated exposition
    and the fleet /healthz snapshot from the fleet dir's per-rank
    postings.  ``own_registry``/``own_rank`` splice a LIVE local registry
    (the serving supervisor's own counters) in place of that rank's
    on-disk snapshot."""

    def __init__(self, fleet_dir: str, own_registry=None,
                 own_rank: typing.Optional[int] = None,
                 world_size: typing.Optional[int] = None,
                 identity_doc: typing.Optional[dict] = None,
                 generation: typing.Optional[
                     typing.Callable[[], int]] = None,
                 stale_after_s: float = 600.0):
        self.fleet_dir = fleet_dir
        self.own_registry = own_registry
        self.own_rank = own_rank
        self.world_size = world_size
        self.identity_doc = identity_doc or {}
        self.generation = generation
        self.stale_after_s = float(stale_after_s)

    def rank_texts(self) -> typing.Dict[int, str]:
        """{rank: concatenated prom text} from the per-rank child and
        supervisor snapshots (distinct family names, so concatenation is a
        valid exposition), with the own-rank supervisor snapshot replaced
        by the live registry."""
        texts: typing.Dict[int, typing.List[str]] = {}
        d = obs_dir(self.fleet_dir)
        try:
            names = os.listdir(d)
        except OSError:
            names = []
        for fn in sorted(names):
            m = _PROM_RE.match(fn)
            if not m:
                continue
            rank = int(m.group(1))
            if (self.own_registry is not None and rank == self.own_rank
                    and fn.startswith("supervisor_")):
                continue  # served live below
            try:
                with open(os.path.join(d, fn)) as f:
                    texts.setdefault(rank, []).append(f.read())
            except OSError as e:
                LOG.warning("fleet snapshot %s unreadable: %r", fn, e)
        if self.own_registry is not None and self.own_rank is not None:
            texts.setdefault(self.own_rank, []).append(
                self.own_registry.render())
        return {r: "\n".join(parts) for r, parts in texts.items()}

    def fleet_series(self, report: dict,
                     n_reporting: typing.Optional[int] = None) -> str:
        """The fleet-level attribution gauges, rendered straight to text
        (they exist only at federation scope — no single rank can compute
        them).  ``n_reporting``: ranks with a metrics snapshot OR step
        postings (the /healthz definition — a rank that posted metrics but
        no step yet must not read as a dark fleet); defaults to the
        step-posting count when the caller has nothing better."""
        if n_reporting is None:
            n_reporting = len(report["ranks"])
        lines = [
            "# HELP hbnlp_fleet_ranks_reporting ranks with a metrics "
            "snapshot or step postings in the fleet dir",
            "# TYPE hbnlp_fleet_ranks_reporting gauge",
            f"hbnlp_fleet_ranks_reporting {n_reporting}",
        ]
        skew = report.get("skew_ms")
        if skew:
            lines += ["# HELP hbnlp_fleet_step_skew_ms max-minus-min "
                      "step-dispatch wall across ranks",
                      "# TYPE hbnlp_fleet_step_skew_ms gauge"]
            for stat, v in sorted(skew.items()):
                lines.append(
                    f'hbnlp_fleet_step_skew_ms{{stat="{stat}"}} {_fmt(v)}')
        worst = report.get("straggler_rank")
        lines += ["# HELP hbnlp_fleet_straggler_rank rank with the highest "
                  "EMA lag behind the fastest rank (-1: none measurable)",
                  "# TYPE hbnlp_fleet_straggler_rank gauge",
                  f"hbnlp_fleet_straggler_rank "
                  f"{-1 if worst is None else worst}"]
        if report["ranks"]:
            lines += ["# HELP hbnlp_fleet_straggler_score_ms EMA of each "
                      "rank's per-step lag behind the fastest rank",
                      "# TYPE hbnlp_fleet_straggler_score_ms gauge"]
            for r, row in sorted(report["ranks"].items(), key=lambda kv:
                                 int(kv[0])):
                lines.append(f'hbnlp_fleet_straggler_score_ms{{rank="{r}"}} '
                             f'{_fmt(row["straggler_score_ms"])}')
            lines += ["# HELP hbnlp_fleet_barrier_wait_seconds seconds each "
                      "rank would idle at a per-step barrier waiting for "
                      "the slowest rank (the fleet twin of graftprof's "
                      "comm+idle bucket)",
                      "# TYPE hbnlp_fleet_barrier_wait_seconds gauge"]
            for r, row in sorted(report["ranks"].items(), key=lambda kv:
                                 int(kv[0])):
                lines.append(f'hbnlp_fleet_barrier_wait_seconds'
                             f'{{rank="{r}"}} {_fmt(row["barrier_wait_s"])}')
            lines += ["# HELP hbnlp_fleet_last_step newest step each rank "
                      "posted a dispatch timestamp for",
                      "# TYPE hbnlp_fleet_last_step gauge"]
            for r, row in sorted(report["ranks"].items(), key=lambda kv:
                                 int(kv[0])):
                lines.append(f'hbnlp_fleet_last_step{{rank="{r}"}} '
                             f'{row["last_step"]}')
        return "\n".join(lines) + "\n"

    def render(self) -> str:
        """The federated /metrics body: per-rank + aggregate series, then
        the fleet attribution gauges."""
        errors: typing.List[str] = []
        texts = self.rank_texts()
        body = federate(texts, errors=errors)
        posts = read_step_posts(self.fleet_dir)
        report = straggler_report(posts)
        return body + self.fleet_series(
            report, n_reporting=len(set(texts) | set(posts)))

    def snapshot(self) -> dict:
        """The fleet /healthz payload: identity, generation, which ranks
        are reporting (and how stale), and the straggler summary.

        A rank whose newest step post is older than ``stale_after_s`` is
        flagged ``stale`` and degrades the fleet status: a host that died
        without any exit posting (machine gone, not process crash) leaves
        its files behind, and file EXISTENCE alone would read as healthy
        forever.  Metrics-only ranks (posted a snapshot, no step yet) have
        no post to age and are not flagged — fleet children always post
        steps, so that state is transient startup."""
        posts = read_step_posts(self.fleet_dir)
        report = straggler_report(posts)
        texts = self.rank_texts()
        now = time.time()
        ranks = {}
        any_stale = False
        for r in sorted(set(texts) | set(posts)):
            newest = max((row["wall"] for row in posts.get(r, {}).values()),
                         default=None)
            age = None if newest is None else round(now - newest, 3)
            stale = age is not None and age > self.stale_after_s
            any_stale = any_stale or stale
            ranks[str(r)] = {
                "metrics_snapshot": r in texts,
                "last_step": (max(posts[r]) if posts.get(r) else None),
                "seconds_since_last_post": age,
                "stale": stale,
            }
        reporting = len(ranks)
        expect = self.world_size or reporting
        status = ("empty" if reporting == 0 else
                  "degraded" if reporting < expect or any_stale else "ok")
        out = {"status": status,
               "identity": dict(self.identity_doc),
               "world_size": self.world_size,
               "ranks": ranks,
               "straggler": report}
        if self.generation is not None:
            try:
                out["generation"] = int(self.generation())
            except Exception:
                out["generation"] = None
        return out


class _FederationServer(ThreadingHTTPServer):
    daemon_threads = True
    federation: FleetFederation


class _FederationHandler(BaseHTTPRequestHandler):
    def _send(self, status: int, body: bytes, ctype: str) -> None:
        self.send_response(status)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):
        path = self.path.split("?", 1)[0]
        fed = self.server.federation
        if path == "/metrics":
            try:
                body = fed.render().encode()
            except Exception as e:  # noqa: BLE001 - scrape must not 500 raw
                body = f"# federation render failed: {e!r}\n".encode()
            self._send(200, body,
                       "text/plain; version=0.0.4; charset=utf-8")
        elif path == "/healthz":
            snap = fed.snapshot()
            # 503 only when the fleet is DARK (no rank ever posted): a
            # degraded fleet still serves what it knows
            status = 503 if snap["status"] == "empty" else 200
            self._send(status, json.dumps(snap).encode(),
                       "application/json")
        else:
            self.send_error(404)

    def log_message(self, fmt, *args):
        LOG.debug("fleet %s %s", self.address_string(), fmt % args)


def serve_federation(port: int, federation: FleetFederation,
                     host: str = "127.0.0.1") -> _FederationServer:
    """Serve the federated /metrics + fleet /healthz on a daemon thread —
    stdlib-only on purpose: the supervisor must keep federating through
    exactly the toolchain failures that kill the child (the obs exporter
    import would drag jax in).  ``port=0`` binds ephemeral."""
    server = _FederationServer((host, port), _FederationHandler)
    server.federation = federation
    thread = threading.Thread(target=server.serve_forever,
                              name="fleet-federation", daemon=True)
    server._thread = thread
    thread.start()
    return server


def stop_federation(server: _FederationServer) -> None:
    server.shutdown()
    server.server_close()
    server._thread.join(timeout=5.0)
