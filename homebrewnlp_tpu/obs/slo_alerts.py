"""Multi-window SLO burn-rate alerting (docs/observability.md "SLO
alerting").

The serving layer measures TTFT/e2e/queue-wait and error rate into
histograms, but nothing EVALUATED them: a p99 breach was visible only if
an operator happened to be scraping ``/healthz`` at the time.  This
module turns the declared objectives (``slo_objectives`` config knob)
into the standard multi-window burn-rate signal:

- each objective defines a per-request BREACH predicate and an ERROR
  BUDGET — ``"error_rate": 0.01`` breaches on 5xx with budget 0.01;
  ``"ttft_p95_s": 2.0`` breaches when TTFT exceeds 2.0 s with budget
  0.05 (the ``p95`` in the key: 5% of requests may miss);
- the burn rate over a window is ``breach fraction / budget`` — 1.0
  means the budget is being spent exactly as fast as it accrues, >1
  means it is being burned;
- an alert FIRES when the burn rate exceeds :data:`ALERT_THRESHOLD` in
  BOTH the fast and the slow window (:data:`WINDOWS`) — the classic
  Google-SRE shape: the slow window keeps a transient blip from paging,
  the fast window makes the page reset quickly once the breach stops.

Everything is evaluated lazily on the caller's thread: ``observe()`` is
called per finished request (the REST handler's ``finally``), burn-rate
gauges are render-time callbacks on ``hbnlp_slo_burn_rate{objective,
window}``, and the ``/healthz`` ``alerts`` block re-evaluates on read —
no evaluator thread exists, so an idle server pays nothing.  Firing
TRANSITIONS invoke ``on_alert`` (the flight recorder's ``slo`` dump
trigger) outside the evaluator's lock.  The labelled gauges federate
through ``obs/fleet.py`` like any other gauge (min/mean/max across
ranks; 0.0 is a real measurement, so no sentinel entry is needed).
"""
from __future__ import annotations

import collections
import time
import typing

from ..sync import make_lock

#: metrics a latency objective may target — ``<metric>_p<NN>_s``
OBJECTIVE_METRICS = ("ttft", "e2e", "queue_wait")

#: (name, seconds) evaluation windows; an alert fires only when the burn
#: rate exceeds the threshold in EVERY window (fast AND slow)
WINDOWS = (("fast", 60.0), ("slow", 600.0))

#: burn rate above which an objective's alert fires (in all windows);
#: 1.0 = the error budget is being spent faster than it accrues
ALERT_THRESHOLD = 1.0


class Objective(typing.NamedTuple):
    """One parsed SLO: ``breach(status, measurements)`` semantics are
    derived from the key — see :func:`parse_objective`."""

    key: str
    kind: str          # "error_rate" | "latency"
    metric: str        # "" for error_rate, else ttft/e2e/queue_wait
    threshold: float   # latency bound in seconds, or the error budget
    budget: float      # error budget as a fraction of requests


def parse_objective(key: str, threshold) -> Objective:
    """Parse one ``slo_objectives`` entry; raises ``ValueError`` naming
    exactly what is wrong (config load surfaces typos, not silence)."""
    try:
        threshold = float(threshold)
    except (TypeError, ValueError):
        raise ValueError(
            f"slo_objectives[{key!r}] threshold {threshold!r} is not a "
            "number") from None
    if threshold <= 0:
        raise ValueError(
            f"slo_objectives[{key!r}]={threshold} must be > 0 "
            "(a zero budget/bound can never be met)")
    if key == "error_rate":
        if threshold >= 1.0:
            raise ValueError(
                f"slo_objectives['error_rate']={threshold} must be < 1 "
                "(it is the error budget as a fraction of requests)")
        return Objective(key, "error_rate", "", threshold, threshold)
    parts = key.rsplit("_p", 1)
    if len(parts) == 2 and parts[1].endswith("_s"):
        metric, pct = parts[0], parts[1][:-2]
        if metric in OBJECTIVE_METRICS and pct.isdigit():
            p = int(pct)
            if not 0 < p < 100:
                raise ValueError(
                    f"slo_objectives[{key!r}]: percentile p{p} must be in "
                    "(0, 100)")
            return Objective(key, "latency", metric, threshold,
                             1.0 - p / 100.0)
    raise ValueError(
        f"slo_objectives key {key!r} is not a known objective: use "
        "'error_rate' or '<metric>_p<NN>_s' with metric in "
        f"{'/'.join(OBJECTIVE_METRICS)} (e.g. 'ttft_p95_s')")


def validate_objectives(objectives: dict) -> dict:
    """Config-load validation hook: parse every entry, return the
    normalized ``{key: float(threshold)}`` dict."""
    return {k: parse_objective(k, v).threshold
            for k, v in objectives.items()}


def _breached(ob: Objective, status: int,
              values: typing.Dict[str, typing.Optional[float]]
              ) -> typing.Optional[bool]:
    """Whether one finished request breached ``ob`` — None means the
    request does not count toward this objective's window total (a
    successful request that never reached the measured milestone, e.g. a
    zero-token completion with no TTFT)."""
    if ob.kind == "error_rate":
        return status >= 500
    v = values.get(ob.metric)
    if v is None:
        # failed before the milestone: a 5xx with no TTFT is a breach;
        # a 2xx with no stamp is simply not a sample
        return True if status >= 500 else None
    return v > ob.threshold


class SLOAlerts:
    """Per-request breach bookkeeping + lazy multi-window burn rates.

    Thread-safety: ``observe`` runs on REST handler threads and the
    burn-rate gauge callbacks run on the exporter's render thread, so
    all state is guarded by one declared lock.  ``on_alert`` (and any
    other callback) is invoked OUTSIDE the lock."""

    def __init__(self, objectives: dict,
                 registry=None,
                 windows: typing.Sequence[tuple] = WINDOWS,
                 threshold: float = ALERT_THRESHOLD,
                 on_alert: typing.Optional[typing.Callable] = None):
        self._lock = make_lock("obs.slo_alerts.SLOAlerts._lock")
        self.objectives = tuple(parse_objective(k, v)
                                for k, v in sorted(objectives.items()))
        self.windows = tuple((str(n), float(s)) for n, s in windows)
        self.threshold = float(threshold)
        self._horizon_s = max(s for _, s in self.windows)
        #: (wall_s, status, {metric: value}) per finished request,
        #: pruned to the slow window on every touch — bounded by traffic
        #: over the horizon, never by uptime
        self._events: "collections.deque[tuple]" = collections.deque()
        self._firing: typing.Dict[str, float] = {}  # key -> since wall_s
        self._on_alert = on_alert
        if registry is not None:
            g = registry.gauge(
                "hbnlp_slo_burn_rate",
                "error-budget burn rate per declared objective and window "
                "(window breach fraction / budget; >1 = budget burning "
                "faster than it accrues)",
                labelnames=("objective", "window"))
            for ob in self.objectives:
                for wname, _ in self.windows:
                    g.labels(objective=ob.key, window=wname).set_function(
                        self._gauge_fn(ob.key, wname))

    def _gauge_fn(self, key: str, window: str) -> typing.Callable:
        return lambda: self.burn_rates().get(key, {}).get(window, 0.0)

    # -- ingestion -----------------------------------------------------------
    def observe(self, status: int,
                ttft_s: typing.Optional[float] = None,
                e2e_s: typing.Optional[float] = None,
                queue_wait_s: typing.Optional[float] = None,
                now: typing.Optional[float] = None) -> None:
        """Record one finished request and re-evaluate firing edges."""
        now = time.time() if now is None else now
        values = {"ttft": ttft_s, "e2e": e2e_s, "queue_wait": queue_wait_s}
        with self._lock:
            self._events.append((now, int(status), values))
            self._prune(now)
            fired = self._transitions(now)
        for key, info in fired:
            if self._on_alert is not None:
                try:
                    self._on_alert(key, info)
                except Exception:  # noqa: BLE001 - alerting must not 500 serving
                    pass

    def _prune(self, now: float) -> None:
        cutoff = now - self._horizon_s
        while self._events and self._events[0][0] < cutoff:
            self._events.popleft()

    # -- evaluation ----------------------------------------------------------
    def _rates_locked(self, now: float) -> typing.Dict[str, dict]:
        out: typing.Dict[str, dict] = {}
        for ob in self.objectives:
            per = {}
            for wname, wsec in self.windows:
                total = breached = 0
                for t, status, values in self._events:
                    if t < now - wsec:
                        continue
                    b = _breached(ob, status, values)
                    if b is None:
                        continue
                    total += 1
                    breached += bool(b)
                per[wname] = ((breached / total) / ob.budget
                              if total else 0.0)
            out[ob.key] = per
        return out

    def _transitions(self, now: float) -> typing.List[tuple]:
        """Update firing state; returns the objectives that JUST fired
        (rising edge) as ``(key, info)`` for the on_alert callback."""
        rates = self._rates_locked(now)
        fired = []
        for ob in self.objectives:
            per = rates[ob.key]
            hot = all(per[w] > self.threshold for w, _ in self.windows)
            if hot and ob.key not in self._firing:
                self._firing[ob.key] = now
                fired.append((ob.key, {"objective": ob.key,
                                       "burn_rates": dict(per),
                                       "threshold": ob.threshold,
                                       "budget": ob.budget,
                                       "since_s": now}))
            elif not hot and ob.key in self._firing:
                del self._firing[ob.key]
        return fired

    def burn_rates(self, now: typing.Optional[float] = None
                   ) -> typing.Dict[str, dict]:
        """``{objective: {window: burn_rate}}`` right now (0.0 with no
        samples in the window — no traffic burns no budget)."""
        now = time.time() if now is None else now
        with self._lock:
            self._prune(now)
            return self._rates_locked(now)

    def alerts(self, now: typing.Optional[float] = None
               ) -> typing.List[dict]:
        """Per-objective alert rows for the ``/healthz`` ``alerts``
        block; re-evaluates transitions so an alert CLEARS as its
        windows drain even with no new traffic."""
        now = time.time() if now is None else now
        with self._lock:
            self._prune(now)
            self._transitions(now)
            rates = self._rates_locked(now)
            firing = dict(self._firing)
        rows = []
        for ob in self.objectives:
            rows.append({
                "objective": ob.key,
                "threshold": ob.threshold,
                "budget": ob.budget,
                "burn_rates": {w: round(r, 6)
                               for w, r in rates[ob.key].items()},
                "firing": ob.key in firing,
                "since_s": firing.get(ob.key),
            })
        return rows

    def summary(self, now: typing.Optional[float] = None) -> dict:
        """The ``/healthz`` payload: alert rows + the firing subset."""
        rows = self.alerts(now)
        return {"threshold": self.threshold,
                "windows": {n: s for n, s in self.windows},
                "objectives": [r["objective"] for r in rows],
                "firing": [r["objective"] for r in rows if r["firing"]],
                "alerts": rows}
