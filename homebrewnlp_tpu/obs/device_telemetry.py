"""Device-side training telemetry: pure-``jnp`` numerics INSIDE the step.

The host-side obs layer (spans, registry, watchdog) can say *when* a run
stalls; this module says *what the model is doing on device*: global and
per-group gradient norms, parameter norm, update/param ratio, the gradient
scale, and NaN/Inf sentinels — all computed inside the jitted train step
(train/state.py) and returned as extra entries of the ordinary metrics
tree.  Those entries are device arrays like every other metric, so they
ride the existing ``AsyncMetricWriter`` deferred-drain window: **zero new
``block_until_ready``/``.item()``/``float()`` on the hot path** (the
``host-sync`` graftcheck ratchet stays pinned at zero).

This file is the ONE obs module legal in traced code: graftcheck's
``obs-in-trace`` rule allowlists ``device_telemetry`` imports while still
failing any ``spans``/``registry`` use in ``models/``/``ops/``/``optim/``/
``train/state.py`` — the in-graph half below is pure ``jnp`` (no spans, no
registry, no I/O), and the host half (:class:`AnomalyMonitor`) runs only in
the metric drain.

Anomaly policies (``cfg.anomaly_policy``), acting on the sentinels:

- ``"log"``       — observe-only: non-finite grads are logged at drain time;
                    the update applies as-is (loss sequence unchanged).
- ``"skip_step"`` — the optimizer update AND slot updates are masked
                    in-graph for non-finite grads (the step is a true no-op
                    for model state; the step counter and data cursor still
                    advance), counted on ``hbnlp_anomaly_skips_total``.
- ``"halt"``      — the drain raises :class:`AnomalyHalt`; main.py exits
                    with ``EXIT_ANOMALY_HALT`` (86), which the supervisor
                    treats as a crash (backoff, not immediate relaunch).

Detection is deferred by design: sentinels materialize when the step's
metrics drain, up to ``async_inflight_steps`` updates after dispatch —
the price of keeping the loop sync-free (docs/observability.md).
"""
from __future__ import annotations

import logging
import typing

import jax.numpy as jnp

LOG = logging.getLogger("homebrewnlp_tpu.obs.telemetry")

#: every telemetry metric key starts with this
PREFIX = "telemetry/"
#: keys that must drain EVERY step (anomaly detection), regardless of the
#: ``telemetry_interval`` thinning below
SENTINEL_KEYS = (PREFIX + "nonfinite_grads", PREFIX + "applied",
                 PREFIX + "grad_scale")

ANOMALY_POLICIES = ("log", "skip_step", "halt")


# -- in-graph half (called from the jitted step; pure jnp) -------------------

def grads_finite(grads: typing.Dict[str, jnp.ndarray]
                 ) -> typing.Tuple[jnp.ndarray, jnp.ndarray]:
    """(all_finite scalar bool, count of grad tensors with non-finite
    entries).  Per-tensor ``isfinite().all()`` reductions are fused into the
    step by XLA — no extra pass over HBM beyond the elementwise check."""
    flags = [jnp.isfinite(g).all() for g in grads.values()]
    stacked = jnp.stack(flags)
    return stacked.all(), jnp.sum(~stacked).astype(jnp.int32)


def collect(params: typing.Dict[str, jnp.ndarray],
            grads: typing.Dict[str, jnp.ndarray],
            update_sq: typing.Dict[str, jnp.ndarray],
            grad_scale: jnp.ndarray,
            nonfinite: jnp.ndarray,
            applied: typing.Optional[jnp.ndarray],
            norm_sq_fn: typing.Callable[[str, jnp.ndarray], jnp.ndarray],
            groups: typing.Sequence[str] = (),
            ) -> typing.Dict[str, jnp.ndarray]:
    """The telemetry metrics tree for one step (device arrays; the caller
    merges it into the step's metrics dict).

    - ``norm_sq_fn(name, grad)`` is the step's own norm convention (it
      de-duplicates stage-replicated pipeline 'shared' tensors) so group
      norms agree with the headline ``grad_norm``.
    - ``update_sq`` maps param name -> squared L2 of the APPLIED update
      (already zero for a masked skip_step update).
    - ``applied`` is the in-graph skip sentinel (None = policy never masks,
      rendered as a constant 1.0)."""
    out: typing.Dict[str, jnp.ndarray] = {}
    out[PREFIX + "nonfinite_grads"] = nonfinite
    out[PREFIX + "applied"] = (jnp.float32(1.0) if applied is None
                               else applied.astype(jnp.float32))
    out[PREFIX + "grad_scale"] = grad_scale.astype(jnp.float32)
    psq = sum(jnp.sum(jnp.square(p.astype(jnp.float32)))
              for p in params.values())
    usq = sum(update_sq.values())
    pnorm = jnp.sqrt(psq)
    unorm = jnp.sqrt(usq)
    out[PREFIX + "param_norm"] = pnorm
    out[PREFIX + "update_norm"] = unorm
    out[PREFIX + "update_ratio"] = unorm / jnp.maximum(pnorm, 1e-12)
    for group in groups:
        gsq = sum((norm_sq_fn(k, g) for k, g in grads.items() if group in k),
                  start=jnp.float32(0.0))
        out[PREFIX + f"grad_norm/{group}"] = jnp.sqrt(gsq)
    return out


# -- host half (metric drain / loop; never traced) ---------------------------

def thin(metrics: typing.Dict[str, typing.Any], update_index: int,
         interval: int) -> typing.Dict[str, typing.Any]:
    """Host-side thinning BEFORE the deferred drain: norm-class telemetry
    keys are dropped from updates off the ``telemetry_interval`` grid (their
    device values are never transferred), while the sentinels stay on every
    step — anomaly detection cannot be thinned away.  The device cost is
    unchanged (the step is compiled once); this bounds metrics.jsonl growth
    and the drain's D2H bytes."""
    if interval <= 1 or update_index % interval == 0:
        return metrics
    return {k: v for k, v in metrics.items()
            if not k.startswith(PREFIX) or k in SENTINEL_KEYS}


class AnomalyHalt(RuntimeError):
    """Raised by the drain under ``anomaly_policy="halt"``; main.py converts
    it into ``SystemExit(EXIT_ANOMALY_HALT)``."""


class AnomalyMonitor:
    """Drain-time consumer of the sentinels (AsyncMetricWriter hook).

    Called with each step's MATERIALIZED metrics — reading them costs
    nothing extra, the drain just pulled them.  ``skip_step`` skips were
    already applied in-graph; this side only counts and logs them."""

    def __init__(self, policy: str, registry=None):
        if policy not in ANOMALY_POLICIES:
            raise ValueError(f"unknown anomaly_policy {policy!r}; expected "
                             f"one of {ANOMALY_POLICIES}")
        from .registry import REGISTRY
        self.policy = policy
        reg = REGISTRY if registry is None else registry
        self._skips = reg.counter(
            "hbnlp_anomaly_skips_total",
            "optimizer updates masked (skipped) for non-finite gradients "
            "under anomaly_policy=skip_step")
        self.anomaly_steps: typing.List[int] = []
        self._halted = False

    def observe(self, step: int, host_metrics: typing.Dict[str, typing.Any]
                ) -> None:
        nf = host_metrics.get(PREFIX + "nonfinite_grads")
        if nf is None or self._halted:
            return
        if float(nf) == 0:
            return
        self.anomaly_steps.append(int(step))
        if self.policy == "skip_step":
            self._skips.inc()
            LOG.warning("non-finite gradients at step %d (%d tensor(s)): "
                        "update skipped in-graph (anomaly_policy=skip_step)",
                        step, int(nf))
        elif self.policy == "halt":
            # fire once: the writer's exit-path flush must not raise again
            # and mask the original halt while unwinding
            self._halted = True
            raise AnomalyHalt(
                f"non-finite gradients at step {step} "
                f"({int(nf)} tensor(s)) under anomaly_policy=halt")
        else:
            LOG.warning("non-finite gradients at step %d (%d tensor(s)); "
                        "update applied as-is (anomaly_policy=log)",
                        step, int(nf))
