"""ctypes bindings for the C++ tooling hot paths (native/hbnlp_native.cc).

Lazily builds the shared library with ``make -C native`` on first use (the
reference ships equivalent compile_*.sh scripts for its Cython components)
and degrades to pure-Python fallbacks when no toolchain is available.
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import threading
import typing

import numpy as np

from ..sync import make_lock

_REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
_NATIVE_DIR = os.path.join(_REPO, "native")
_LIB_PATH = os.path.join(_NATIVE_DIR, "libhbnlp_native.so")
_lock = make_lock("native._lock")
_lib: typing.Optional[ctypes.CDLL] = None
_build_failed = False


def _load() -> typing.Optional[ctypes.CDLL]:
    global _lib, _build_failed
    with _lock:
        if _lib is not None or _build_failed:
            return _lib
        if not os.path.exists(_LIB_PATH):
            # build to a process-unique name then atomically rename, so
            # concurrent workers (tools/text2tfrecord.py pool) never load a
            # partially-written .so
            tmp = f"{_LIB_PATH}.{os.getpid()}"
            try:
                subprocess.run(
                    ["make", "-C", _NATIVE_DIR,
                     f"TARGET={os.path.basename(tmp)}"],
                    check=True, capture_output=True)
                os.replace(tmp, _LIB_PATH)
            except Exception:
                _build_failed = True
                return None
        try:
            lib = ctypes.CDLL(_LIB_PATH)
        except OSError:
            _build_failed = True
            return None
        lib.hb_crc32c.restype = ctypes.c_uint32
        lib.hb_crc32c.argtypes = [ctypes.c_char_p, ctypes.c_size_t]
        lib.hb_masked_crc.restype = ctypes.c_uint32
        lib.hb_masked_crc.argtypes = [ctypes.c_char_p, ctypes.c_size_t]
        lib.hb_write_records.restype = ctypes.c_int
        lib.hb_write_records.argtypes = [
            ctypes.c_char_p, ctypes.c_char_p,
            ctypes.POINTER(ctypes.c_uint64), ctypes.c_uint64, ctypes.c_int]
        lib.hb_clean_text.restype = ctypes.c_size_t
        lib.hb_clean_text.argtypes = [ctypes.c_char_p, ctypes.c_size_t,
                                      ctypes.c_char_p]
        lib.hb_bpe_train_words.restype = ctypes.c_int
        lib.hb_bpe_train_words.argtypes = [
            np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS"),
            np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS"),
            np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS"),
            ctypes.c_int64, ctypes.c_int32, ctypes.c_int32,
            np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS")]
        lib.hb_bpe_encode.restype = ctypes.c_int64
        lib.hb_bpe_encode.argtypes = [
            np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS"),
            ctypes.c_int64,
            np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS"),
            ctypes.c_int32, ctypes.c_int32]
        _lib = lib
        return _lib


def available() -> bool:
    return _load() is not None


# -- crc ---------------------------------------------------------------------

def crc32c(data: bytes) -> int:
    lib = _load()
    if lib is None:
        from ..data.tfrecord import crc32c as py
        return py(data)
    return int(lib.hb_crc32c(data, len(data)))


def masked_crc(data: bytes) -> int:
    lib = _load()
    if lib is None:
        from ..data.tfrecord import masked_crc as py
        return py(data)
    return int(lib.hb_masked_crc(data, len(data)))


# -- tfrecord ----------------------------------------------------------------

def write_records(path: str, payloads: typing.Sequence[bytes],
                  append: bool = False) -> None:
    """Write framed TFRecords via the native path (falls back to the Python
    RecordWriter)."""
    lib = _load()
    if lib is None:
        from ..data.tfrecord import RecordWriter
        with RecordWriter(path, append=append) as w:
            for p in payloads:
                w.write(p)
        return
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    blob = b"".join(payloads)
    lengths = (ctypes.c_uint64 * len(payloads))(*[len(p) for p in payloads])
    rc = lib.hb_write_records(path.encode(), blob, lengths, len(payloads),
                              int(append))
    if rc != 0:
        raise IOError(f"hb_write_records({path}) failed: {rc}")


# -- text cleaning -----------------------------------------------------------

def clean_text(data: bytes) -> bytes:
    lib = _load()
    if lib is None:
        return _clean_text_py(data)
    out = ctypes.create_string_buffer(len(data))
    n = lib.hb_clean_text(data, len(data), out)
    return out.raw[:n]


def _clean_text_py(data: bytes) -> bytes:
    """Byte-exact port of hb_clean_text (same state machine, so shards built
    without the toolchain are identical to native-built ones)."""
    out = bytearray()
    newlines = 0
    n = len(data)
    i = 0
    while i < n:
        c = data[i]
        if c == 0x0D:  # \r
            if i + 1 < n and data[i + 1] == 0x0A:
                i += 1
                continue
            c = 0x0A
        if c == 0x0A:
            newlines += 1
            if newlines > 2:
                i += 1
                continue
        else:
            newlines = 0
            if c < 0x20 and c != 0x09:
                i += 1
                continue
        out.append(c)
        i += 1
    return bytes(out)


# -- BPE ---------------------------------------------------------------------

def _stream_to_words(corpus: np.ndarray) -> typing.Dict[bytes, int]:
    """int32 stream with -1 boundaries -> {word token-bytes: count}."""
    corpus = np.ascontiguousarray(corpus, np.int32)
    counts: typing.Dict[bytes, int] = {}
    for seg in np.split(corpus, np.nonzero(corpus < 0)[0]):
        seg = seg[seg >= 0]
        if len(seg):
            key = seg.tobytes()
            counts[key] = counts.get(key, 0) + 1
    return counts


def bpe_train_words(word_counts: typing.Dict[bytes, int], n_merges: int,
                    first_new_id: int = 256) -> np.ndarray:
    """Greedy BPE merges over a word-frequency table ({int32-token-bytes:
    count}, the HF-BpeTrainer-style structure).  Returns [n_done, 2]
    (left, right) pairs; merge i creates id first_new_id + i."""
    lib = _load()
    if lib is None:
        return _bpe_train_py(word_counts, n_merges, first_new_id)
    words = [np.frombuffer(k, np.int32) for k in word_counts]
    flat = (np.concatenate(words) if words else np.zeros(0, np.int32))
    flat = np.ascontiguousarray(flat, np.int32)
    offsets = np.zeros(len(words) + 1, np.int64)
    np.cumsum([len(w) for w in words], out=offsets[1:])
    counts = np.asarray(list(word_counts.values()), np.int64)
    out = np.zeros((max(n_merges, 1), 2), np.int32)
    done = lib.hb_bpe_train_words(flat, offsets, counts, len(words),
                                  n_merges, first_new_id, out.reshape(-1))
    return out[:done]


def bpe_train(corpus: np.ndarray, n_merges: int, first_new_id: int = 256
              ) -> np.ndarray:
    """Greedy BPE merges over an int32 token stream (-1 = boundary);
    convenience wrapper deduplicating into the word-frequency form."""
    return bpe_train_words(_stream_to_words(corpus), n_merges, first_new_id)


def bpe_encode(tokens: np.ndarray, pairs: np.ndarray,
               first_new_id: int = 256) -> np.ndarray:
    lib = _load()
    tokens = np.ascontiguousarray(tokens, np.int32).copy()
    pairs = np.ascontiguousarray(pairs, np.int32)
    if lib is None:
        return _bpe_encode_py(tokens, pairs, first_new_id)
    n = lib.hb_bpe_encode(tokens, len(tokens), pairs.reshape(-1),
                          len(pairs), first_new_id)
    return tokens[:n]


def _bpe_train_py(word_counts: typing.Dict[bytes, int], n_merges: int,
                  first_new_id: int) -> np.ndarray:
    """Word-frequency BPE, same tie-break as the native version (largest
    count, then smallest (left<<32)|right key)."""
    words = [list(np.frombuffer(k, np.int32)) for k in word_counts]
    wcounts = list(word_counts.values())
    merges = []
    for m in range(n_merges):
        counts: typing.Dict[tuple, int] = {}
        for word, c in zip(words, wcounts):
            for a, b in zip(word, word[1:]):
                counts[(int(a), int(b))] = counts.get((int(a), int(b)), 0) + c
        if not counts:
            break
        (left, right), count = min(counts.items(),
                                   key=lambda kv: (-kv[1], kv[0]))
        if count < 2:
            break
        new_id = first_new_id + m
        merges.append((left, right))
        for word in words:
            o, i = [], 0
            while i < len(word):
                if i + 1 < len(word) and word[i] == left and word[i + 1] == right:
                    o.append(new_id)
                    i += 2
                else:
                    o.append(word[i])
                    i += 1
            word[:] = o
    return np.asarray(merges, np.int32).reshape(-1, 2)


def _bpe_encode_py(tokens: np.ndarray, pairs: np.ndarray, first_new_id: int
                   ) -> np.ndarray:
    """Heap-driven greedy BPE (merge the globally lowest-(rank, pos)
    occurrence each step) — the same order the native encoder applies,
    O(n log n)."""
    import heapq
    rank = {(int(l), int(r)): i for i, (l, r) in enumerate(pairs)}
    n = len(tokens)
    buf = [int(t) for t in tokens]
    nxt = list(range(1, n + 1))
    prv = list(range(-1, n - 1))
    # negative INPUT tokens (word-boundary sentinels) are preserved and
    # never pair; consumption is tracked separately (same contract as the
    # native encoder)
    dead = [False] * n
    none = len(pairs)
    heap = [(rank[(a, b)], i)
            for i, (a, b) in enumerate(zip(buf, buf[1:]))
            if (a, b) in rank]
    heapq.heapify(heap)
    while heap:
        r, i = heapq.heappop(heap)
        if dead[i]:
            continue
        j = nxt[i]
        if j >= n or dead[j] or rank.get((buf[i], buf[j]), none) != r:
            continue  # stale entry: the pair at i changed since the push
        buf[i] = first_new_id + r
        dead[j] = True
        nxt[i] = nxt[j]
        if nxt[j] < n:
            prv[nxt[j]] = i
        if prv[i] >= 0:
            pr = rank.get((buf[prv[i]], buf[i]), none)
            if pr < none:
                heapq.heappush(heap, (pr, prv[i]))
        if nxt[i] < n:
            nr = rank.get((buf[i], buf[nxt[i]]), none)
            if nr < none:
                heapq.heappush(heap, (nr, i))
    return np.asarray([t for i, t in enumerate(buf) if not dead[i]],
                      np.int32)
