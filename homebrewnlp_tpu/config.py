"""Configuration system: JSON -> typed model parameters + derived named dimensions.

Reproduces the semantics of the reference's ``ModelParameter`` god-object
(/root/reference/src/dataclass.py:34-372) as a plain dataclass-style config with
explicit derivation, without the dict-compat shims.  The whole parallelism
configuration of the reference is two integers (``tpu_size``, ``heads``) that
synthesize a (mesh_shape, layout) pair (dataclass.py:247-252); here the same two
integers synthesize a `jax.sharding.Mesh` axis layout (see parallel/mesh.py),
extended with an optional sequence-parallel axis the reference lacks.
"""
from __future__ import annotations

import dataclasses
import json
import typing

import jax.numpy as jnp

# Canonical logical axis (dimension) names used across the framework.
BATCH = "batch"
SEQUENCE = "sequence"
HEADS = "heads"
KEY = "features_per_head"
INTERMEDIATE = "intermediate"
VOCAB = "vocab"
TOKEN_PATCH = "language_token_patch"
HEIGHT = "height"
WIDTH = "width"
COLOR_CHANNELS = "color_channels"
EXPERTS = "experts"
ROUTED_EXPERTS = "routed_experts"
PKM_AXES = "pkm_axes"
PKM_VALUES = "product_key_value_dim"
# leading axis of stage-stacked pipeline-parallel body parameters; maps to
# the pipeline mesh axis so each device holds only its stage's weights
PIPE_STAGE = "pipe_stage"

ANON_PREFIX = "_"

# the canonical axis constants above are THE registry the graftcheck
# axis-literal lint validates against (analysis/ast_rules.py); an anonymized
# twin ("_sequence") validates via its base name
from . import nd as _nd  # noqa: E402  (registry import, no cycle: nd is leaf)

_nd.register_axis(BATCH, SEQUENCE, HEADS, KEY, INTERMEDIATE, VOCAB,
                  TOKEN_PATCH, HEIGHT, WIDTH, COLOR_CHANNELS, EXPERTS,
                  ROUTED_EXPERTS, PKM_AXES, PKM_VALUES, PIPE_STAGE)


def anonymize_name(name: str) -> str:
    """Leading underscore marks a replicated twin of an axis (reference
    utils_mtf.py:37-54); two tensors may carry both ``sequence`` and
    ``_sequence`` simultaneously (e.g. attention logits)."""
    return name if name.startswith(ANON_PREFIX) else ANON_PREFIX + name


DTYPES = {
    "float32": jnp.float32,
    "bfloat16": jnp.bfloat16,
    "float16": jnp.float16,
    "float64": jnp.float64,
}


@dataclasses.dataclass
class BlockConfig:
    """One block = list of layer-DSL strings (reference dataclass.py:12-19)."""
    layer: typing.List[str] = dataclasses.field(default_factory=list)
    skip: bool = False
    memory_reduction_strategy: str = "none"

    @classmethod
    def make(cls, conf, strategy: str) -> "BlockConfig":
        if isinstance(conf, BlockConfig):
            return conf
        out = cls(memory_reduction_strategy=strategy)
        for k, v in conf.items():
            setattr(out, k, v)
        return out


@dataclasses.dataclass
class LearningRateConfig:
    start_step: int = 0
    final_step: int = 0
    factor: float = 1.0


_DEFAULTS: typing.Dict[str, typing.Any] = dict(
    # embeddings (reference dataclass.py:38-41)
    position_embedding="absolute",
    token_embedding="absolute",
    empty_frame_embedding="absolute",
    output_embedding="absolute-orthogonal",
    # modes
    use_video=True,
    use_language=True,
    model_mode="jannet",
    contrastive_across_samples=False,
    contrastive_across_token_embeddings=False,
    # io/model shape
    input_dropout=0.0,
    output_offset=1,
    time_patch=1,
    patch_size=16,
    frame_width=320,
    frame_height=176,
    vocab_size=256,
    color_channels=3,
    three_axes=True,
    sequence_length=32,
    heads=8,
    features=None,
    features_per_head=None,
    depth=16,
    token_patch_size=1,
    language_token_per_frame=0,
    padding_token=0,
    concat_token=4,
    # data
    dataset_configs=(),
    data_seed=456772,
    use_random_dataloader=False,
    shuffle_buffer=256,
    interleaved_datasets=256,
    buffer_size=4,
    parallel_interleave=None,
    shuffle_input_filenames=True,
    use_bit_fold_input_pipeline=False,
    bit_fold_value=4,
    color_quantization_value=256,
    # training
    train=True,
    train_batch_size=1,
    grad_accumulation=1,
    macro_batching=1,
    macro_batch_loss_smoothing=False,
    learning_rate=5e-5,
    learning_rate_config=(),
    opt_beta1=0.9,
    opt_beta2=0.999,
    momentum=0.95,
    optimizer="learning_rate",
    weight_decay=0.001,
    weight_centralisation=True,
    weight_standardisation=True,
    rezero_lr_multiplier=0.1,
    train_steps=2 ** 30,
    z_loss=1e-4,
    calc_accuracy=False,
    multi_loss_strategy="linear",
    memory_reduction_strategy="revnet",
    momentumnet_alpha=0.99,
    # precision squash (round through the given dtype) on the cotangent
    # streams BETWEEN reversible blocks during backward ("" = exact).
    # Measured round 4: under bf16 calculation_dtype the streams are
    # ALREADY bf16 (bit-identical loss, byte-identical step with
    # "bfloat16" set — docs/perf/README.md), so this only affects
    # f32-calculation configs.
    reversible_cotangent_dtype="",
    # jax.checkpoint each reversible block's backward replay: recompute
    # block internals instead of storing residuals — FLOPs for HBM bytes,
    # a win on bandwidth-bound workloads (docs/perf/README.md round 4)
    reversible_remat_blocks=False,
    # fuse the [norm, map-attention, norm, gelu, map-attention] mixer block
    # into one pallas fwd kernel + one full-vjp bwd kernel (the HBM-bytes
    # lever for the bandwidth-bound mixer workloads, ops/pallas_mixer.py).
    # Single-device only: the GSPMD/sharded paths keep the unfused chain.
    fused_mixer_block=False,
    # fuse the [norm, bottleneck_group_linear] block into two pallas
    # fwd+bwd kernel pairs split at the bottleneck activation (the second
    # bytes lever for the group workload, ops/pallas_group.py).  Same
    # single-device guard as fused_mixer_block.
    fused_group_linear=False,
    # quantized-compute scope (ops/quant.py, docs/performance.md
    # "Low-precision compute"): layer-scope substrings whose DSL linears run
    # the W8A8 quantized forward (dynamic in-graph scales, f32-accumulated
    # int8/fp8 dot, bf16 backward), e.g. ["bottleneck_group_linear",
    # "/group_linear"].  Empty (default) compiles the exact pre-quant graph
    # — bit-identical loss sequence, parity-tested like telemetry_interval=0.
    # The graftcheck quant-dtype rule pins both directions (a quant op
    # outside the scope, or a declared scope with no quantized dot).
    quant_blocks=(),
    # forward quantization format: "int8" (symmetric, qmax 127) or "fp8"
    # (e4m3, toolchain-gated)
    quant_dtype="int8",
    # recursion depth for the blocked causal map decomposition
    # (models/layers.py::_blocked_map_rows): 0 = plain masked einsum; >0
    # carves the triangle into dense sub-blocks so XLA skips the masked
    # FLOPs — the measured lever for the compute-bound long-context shape
    blocked_causal_map=0,
    debug_train_step=False,
    debug_gradients=False,
    # async-dispatch step loop (main.py, docs/performance.md): up to N
    # dispatched-but-undrained updates may be in flight before the loop
    # blocks on the oldest one's metrics.  0 (or debug_train_step) drains
    # every step synchronously — the parity-reference path.
    async_inflight_steps=2,
    # device-side batch prefetch (data/feed.py::DeviceFeeder): a background
    # thread assembles + H2D-transfers up to N upcoming global batches while
    # the current step runs.  0 assembles inline on the critical path.
    device_prefetch_depth=1,
    # observability (docs/observability.md).  All default-off: disabled runs
    # pay a single ambient-tracer load per instrumented site and the
    # synchronous parity path stays bit-identical.
    # obs_port: >0 serves /metrics (Prometheus text) + /healthz (JSON
    # liveness) on 127.0.0.1:<port> for the run's duration
    obs_port=0,
    # obs_spans: record host spans (step/feed/drain/checkpoint/serve) and
    # export model_path/trace.json (Chrome trace-event JSON, Perfetto-
    # loadable); each span also mirrors into jax.profiler.TraceAnnotation
    obs_spans=False,
    # device telemetry (docs/observability.md "Device telemetry").
    # telemetry_interval: >0 computes in-graph numerics (grad/param/update
    # norms, NaN/Inf sentinels) inside the jitted step EVERY update and
    # writes the norm-class metrics every N updates (sentinels drain every
    # step); 0 = off — the step compiles to the exact pre-telemetry graph,
    # keeping the sync-parity sequence bit-identical.
    telemetry_interval=0,
    # telemetry_groups: param-name substrings; each gets a per-group
    # gradient-norm metric telemetry/grad_norm/<group> (e.g.
    # ["embed", "body", "output"])
    telemetry_groups=(),
    # anomaly_policy: what the NaN/Inf gradient sentinels trigger —
    # "log" (observe only), "skip_step" (mask the optimizer update
    # in-graph and count hbnlp_anomaly_skips_total), "halt" (exit with
    # EXIT_ANOMALY_HALT so a supervisor restarts from the last checkpoint)
    anomaly_policy="log",
    # watchdog_factor: N>0 arms the hang watchdog — when no step completes
    # within N x the EMA step time, thread stacks + device memory stats are
    # dumped to model_path/diagnostics/ (once per stall; never kills the
    # run).  0 disables.
    watchdog_factor=0.0,
    # absolute stall bound BEFORE any step cadence exists (compile /
    # restore / first step): raise it for configs whose cold compile
    # legitimately exceeds 10 minutes, or a /healthz-driven restart loops
    # the compile forever; 0 disables the startup bound entirely
    watchdog_startup_s=600.0,
    # --profile window (main.py): start the jax.profiler trace at update
    # u0+profile_start (must be >= 1: update u0 pays the compile, which
    # would drown steady-state timing) and capture profile_steps updates
    profile_start=3,
    profile_steps=3,
    # fault tolerance (docs/reliability.md).
    # grace_deadline_s: wall budget for the SIGTERM/SIGINT grace shutdown
    # (drain the async loop + cut a final checkpoint); exceeded -> forced
    # exit EXIT_GRACE_TIMEOUT.  0 disables the forced deadline.
    grace_deadline_s=30.0,
    # ckpt_retries: storage retries (exponential backoff) around each
    # checkpoint save/restore/sidecar/manifest operation
    ckpt_retries=2,
    # corrupt_record_budget: >0 skips (and logs + counts) up to N unreadable
    # data records/shards per pipeline instead of dying; 0 = strict fail-fast
    corrupt_record_budget=0,
    # fault_plan: fault-injection spec for chaos tests, e.g.
    # "ckpt_write:fail@2;feeder:die@step10;sigterm@step25"
    # (grammar in reliability/faults.py; HBNLP_FAULT_PLAN env var when empty)
    fault_plan="",
    # elastic multi-host training (docs/reliability.md "Multi-host
    # elasticity"; reliability/dist.py).  All dist_* knobs are overridden by
    # the HBNLP_DIST_COORDINATOR / HBNLP_DIST_NUM_PROCESSES /
    # HBNLP_DIST_PROCESS_ID env vars so one config file serves every host —
    # the per-host supervisor injects the rank into its child's env.
    # dist_coordinator: "host:port" of the jax.distributed coordinator
    # (rank 0's address); "" with dist_num_processes <= 1 = single-host
    dist_coordinator="",
    # dist_num_processes: fleet size; <= 1 disables multi-host init entirely
    dist_num_processes=0,
    # dist_process_id: this host's rank in [0, dist_num_processes)
    dist_process_id=0,
    # dist_init_timeout_s: wall deadline across ALL initialize() retry
    # attempts (coordinator-unreachable backoff); each attempt gets a
    # deadline/(retries+1) slice as its jax initialization_timeout so the
    # retry path engages even against a slow coordinator.  Default matches
    # jax's own 300s join timeout: a fleet whose hosts boot minutes apart
    # must not burn its supervisors' crash-loop budget waiting.
    # 0 = attempts-only budget
    dist_init_timeout_s=300.0,
    # dist_init_retries: retries (exponential backoff) after the first
    # failed jax.distributed.initialize attempt
    dist_init_retries=3,
    # dist_barrier_timeout_s: default bound on reliability.dist.barrier();
    # an absent peer raises PeerLost (exit 87) instead of hanging forever
    dist_barrier_timeout_s=60.0,
    # fleet_dir: SHARED directory for cross-rank fleet observability
    # (docs/observability.md "Fleet observability"): each rank posts
    # per-step dispatch timestamps, /metrics snapshots, and its span trace
    # under <fleet_dir>/obs/ for federation + straggler attribution.
    # Overridden by HBNLP_FLEET_DIR (the supervisor injects its
    # --fleet-dir).  "" = off: single-process runs stay byte-identical.
    fleet_dir="",
    current_step=0,
    steps_per_checkpoint=100_000,
    use_checkpointing=False,
    max_checkpoints_keep=1,
    model_path="runs/default",
    # persistent XLA compilation cache directory (None = env var or per-user
    # default, "" = disabled; consumed at the CLI/bench entry points via
    # utils.enable_compilation_cache)
    compilation_cache_dir=None,
    # serving codec for tools/train_tokenizer.py artifacts: when set, the
    # query/REST/sample text paths encode+decode through this tokenizer
    # (serve/interface.py::HbnlpBpeTokenizer) instead of bytes/GPT-2
    tokenizer_path="",
    # None = the reference's rule (only use_random_dataloader repeats,
    # inputs.py:540-541); true forces deterministic epoch wrap-around on
    # the sequential reader, false forces single-epoch
    repeat_dataset=None,
    # dtypes (storage/compute/optimizer policy; reference dataclass.py:82-86)
    storage_dtype="float32",
    slice_dtype="float32",
    calculation_dtype="float32",
    optimizer_slice_dtype="float32",
    optimizer_calculation_dtype="float32",
    # architecture knobs
    group_linear_factor=2,
    intermediate_feed_forward_multiplier=None,
    intermediate_feed_forward_multiplier_multiplier=None,
    embedding_stddev=0.04,
    experts=64,
    moe_balance_weight=0.01,  # routed_moe load-balance aux loss (extension)
    pkm_axes=2,
    convolution_size=16,
    scale_by_depth=True,
    use_initial_position_embedding=False,
    vocab_weight_factorization=0.125,
    masked_attention_dimensions=(0,),
    block_config=(
        {"layer": ["norm-group-shift-scale", "feed_forward-in_relu-group-in_glu_add-in_norm"]},
        {"layer": ["norm-group-std-shift-scale", "attention-in_relu-embedded-relative"]},
    ),
    input_block_config=(),
    output_block_config=(),
    # intended deployment device kind ("v5e", "v4", "v5p", ... — see
    # homebrewnlp_tpu/devices.py) for the static cost model
    # (docs/static_analysis.md "Resource cost model"): when set, the
    # graftcheck resource-budget rule HARD-FAILS any config whose predicted
    # per-device peak HBM exceeds this device's capacity — the OOM surfaces
    # in CI seconds instead of after a ~2-minute TPU compile.  "" (default)
    # skips the capacity gate; predictions and the roofline verdict are
    # still recorded against the default verdict device.
    target_device="",
    # how deep into the mesh searcher's ranked sheet the committed
    # hand-written mesh may sit before graftcheck's mesh-rank rule fails
    # (docs/static_analysis.md "Mesh search"); 1 = the hand mesh must BE
    # the searcher's (possibly tied) top pick
    mesh_search_top_k=3,
    # parallelism (the reference's two knobs, plus TPU-native extensions)
    tpu_size=32,
    sequence_parallel=1,  # extension: size of the sequence-parallel mesh axis
    pipeline_parallel=1,  # extension: pipeline stages over the pipeline axis
    # "gpipe": all-forward scan + autodiff backward (residuals grow with the
    # microbatch count M).  "1f1b": interleaved schedule computing loss and
    # grads in one scan with a 2P-deep input stash, M-independent activation
    # memory (ops/pipeline.py::pipeline_1f1b)
    pipeline_schedule="gpipe",
    # sampling / serving
    initial_autoregressive_position=128,
    use_autoregressive_sampling=False,
    sampling_temperature=0.0,
    # extension: truncated sampling (the reference only has temperature).
    # top_k=0 and top_p=1.0 disable truncation; both knobs are compile-time
    # static (changing them recompiles the sampler).
    sampling_top_k=0,
    sampling_top_p=1.0,
    num_of_sample=10,
    web_workers=1,
    # serving SLO knobs (docs/observability.md "Serving SLOs").
    # serve_queue_deadline_s: a request whose ENGINE-QUEUE wait exceeds this
    # is rejected (REST: 503 + Retry-After) instead of hanging the client
    # behind the serialized engine; 0 = wait forever (the reference's
    # Manager-queue behavior)
    serve_queue_deadline_s=0.0,
    # serve_queue_limit: >0 sheds load at ADMISSION — a completion request
    # arriving with this many requests already queued is rejected
    # immediately (REST: 503 + Retry-After) without waiting out the
    # deadline; 0 = unbounded queue
    serve_queue_limit=0,
    # continuous-batching engine (docs/observability.md "Continuous
    # batching").  serve_max_batch: decode lanes sharing one persistent
    # decode loop — >1 replaces the serialized InterfaceWrapper with the
    # serve/engine.py scheduler (requests admitted BETWEEN decode steps);
    # 1 (default) keeps the reference-shaped serialized path bit-identical
    serve_max_batch=1,
    # serve_block_tokens: tokens per KV-pool block (must be a multiple of
    # token_patch_size so blocks hold whole decode rows); 0 = one
    # whole-sequence block per lane, which makes the pool byte-identical
    # to the monolithic per-lane cache
    serve_block_tokens=0,
    # serve_kv_blocks: total blocks in the fixed-capacity KV pool shared
    # by all lanes — admission takes a request's whole block footprint up
    # front and recycles it on completion; 0 = auto
    # (serve_max_batch x blocks-per-sequence, i.e. the physical pool)
    serve_kv_blocks=0,
    # serve_prefill_chunk_tokens: >0 splits admission prefill into chunks of
    # this many tokens (must be a multiple of the KV-block size, i.e. of
    # serve_block_tokens when paged, else token_patch_size), dispatched
    # asynchronously between decode steps so a long prompt admits over N
    # loop iterations while occupied lanes keep decoding
    # (docs/observability.md "Streaming and inter-token latency");
    # 0 = monolithic admission prefill on the decode thread — byte-identical
    # graphs, census/spmd goldens untouched
    serve_prefill_chunk_tokens=0,
    # serve_aot_cache_dir: directory for serialized prefill/decode
    # executables keyed by config hash + mesh + toolchain — a second
    # server start deserializes instead of re-compiling (cold start in
    # seconds, not minutes); "" = AOT executable serialization off
    serve_aot_cache_dir="",
    # serve_stream: honor `stream: true` on the completion endpoints (SSE
    # token streaming, docs/observability.md "Streaming and inter-token
    # latency"); requests without the flag are byte-identical either way.
    # False keeps the serialized samplers' graphs free of the per-row
    # token callback and buffers every response.
    serve_stream=True,
    # serve_trace_path: Chrome-trace JSON of the serving engine's decode
    # loop (per-phase spans + per-lane occupancy tracks + request phase
    # trails), exported when the engine closes — and, while the flight
    # recorder is on (flight_buffer_spans > 0), ROTATED into rolling
    # <path>.NNN.json segments whenever the span ring fills, so a crash
    # loses at most one ring of spans; "" = serving trace off
    serve_trace_path="",
    # slo_objectives: declared serving SLOs, evaluated per finished
    # request by obs/slo_alerts.py into fast/slow-window burn rates
    # (hbnlp_slo_burn_rate{objective,window} + the /healthz "alerts"
    # block), e.g. {"ttft_p95_s": 2.0, "error_rate": 0.01}.  Keys are
    # "error_rate" (value = the error budget itself) or "<metric>_p<NN>_s"
    # with metric in ttft/e2e/queue_wait (value = the latency threshold;
    # error budget = 1 - NN/100); {} = SLO alerting off
    slo_objectives={},
    # flight_buffer_spans: span capacity of the serving flight recorder's
    # ring (obs/flight.py): recent spans + last-N request trails + metric
    # snapshots held in bounded memory, written as a self-contained
    # incident bundle to <model_path>/diagnostics/ when a trigger fires
    # (flight_dump_triggers); also caps the serve_trace_path tracer and
    # arms its rolling-segment rotation; 0 = flight recorder off
    flight_buffer_spans=4096,
    # flight_dump_triggers: which events write a flight bundle — any
    # subset of ("watchdog", "error", "slo", "manual"): watchdog stall,
    # 5xx response, an SLO burn-rate alert firing, or POST /debugz/dump
    flight_dump_triggers=("watchdog", "error", "slo", "manual"),
    # multi-replica serving (docs/reliability.md "Serving resilience").
    # serve_replicas: engine replica processes tools/graftserve.py spawns
    # behind the health-aware router; 1 = a single replica (the router is
    # still useful for drain/failover semantics, but optional)
    serve_replicas=1,
    # router_port: >0 runs the health-aware replica router
    # (serve/router.py) on this port in front of the replica set;
    # 0 = no router (clients hit a replica directly)
    router_port=0,
    # router_health_interval_s: seconds between the router's /healthz
    # polls of each replica — a replica reporting stalled, draining,
    # firing SLO alerts, or a full KV pool is shed to healthy peers
    router_health_interval_s=1.0,
    # router_health_timeout_s: per-poll HTTP timeout; a wedged healthz
    # endpoint (the replica:wedge_healthz chaos action) reads as
    # unhealthy after this long instead of hanging the health watcher
    router_health_timeout_s=2.0,
    # router_failover_retries: additional replicas tried after a replica
    # death (connection refused, 5xx, or a mid-stream disconnect BEFORE
    # the first SSE token), preserving the client's X-Request-Id; once
    # any response byte has been forwarded, retries are never attempted
    # (at-most-once delivery past the first token)
    router_failover_retries=1,
    # serve_watchdog_min_stall_s: floor of the serving decode-loop
    # watchdog's stall threshold (watchdog_factor x the EMA scheduler
    # iteration time, never below this floor) — the serving twin of the
    # train watchdog; armed only when watchdog_factor > 0
    serve_watchdog_min_stall_s=1.0,
    # per-tenant usage metering (obs/usage.py; docs/observability.md
    # "Usage metering & capacity").  usage_top_k: tenants tracked EXACTLY
    # by the Misra-Gries sketch; the long tail folds into tenant="other"
    # so /metrics cardinality stays bounded at top_k+1 rows no matter how
    # many distinct tenants arrive; 0 = metering off
    usage_top_k=32,
    # usage_tenant_header: the request header carrying the tenant
    # identity; values failing the validation charset (or missing) meter
    # as tenant="anon"
    usage_tenant_header="X-Tenant",
    equal_debugging_items_per_check=16,
    debug_sample=False,
    default_sleep_duration=0.1,
)


class Config:
    """Typed hyperparameter store with validation + derived dimension registry.

    ``dims`` maps logical axis names to sizes — the JAX-side replacement for the
    reference's mtf.Dimension zoo (dataclass.py:273-341)."""

    def __init__(self, config: typing.Optional[dict] = None):
        self.__dict__.update(_DEFAULTS)
        config = dict(config or {})
        for k, v in config.items():
            if k not in _DEFAULTS and k not in ("mesh_shape", "layout"):
                print(f"WARNING: Unknown Config parameter {k}={v!r}")
            setattr(self, k, v)
        self._validate_and_derive()

    @classmethod
    def from_json(cls, path: str) -> "Config":
        with open(path) as f:
            return cls(json.load(f))

    # -- derivation ---------------------------------------------------------
    def _validate_and_derive(self) -> None:
        # macro_batching inflates the host batch by M (reference
        # dataloader_placement.py:40-44); grad_accumulation splits each
        # configured batch into G micro-slices.  The train step scans M*G
        # micro-batches per optimizer update (train/state.py).
        assert self.macro_batching > 0
        assert self.grad_accumulation > 0
        if self.async_inflight_steps < 0:
            raise ValueError("async_inflight_steps must be >= 0 "
                             "(0 = synchronous drain every step)")
        if self.device_prefetch_depth < 0:
            raise ValueError("device_prefetch_depth must be >= 0 "
                             "(0 = inline batch assembly)")
        if int(self.obs_port) < 0:
            raise ValueError("obs_port must be >= 0 (0 = exporter disabled)")
        if int(self.telemetry_interval) < 0:
            raise ValueError("telemetry_interval must be >= 0 "
                             "(0 = device telemetry disabled)")
        self.telemetry_interval = int(self.telemetry_interval)
        self.telemetry_groups = [str(g) for g in self.telemetry_groups]
        from .obs.device_telemetry import ANOMALY_POLICIES
        if self.anomaly_policy not in ANOMALY_POLICIES:
            raise ValueError(
                f"unknown anomaly_policy {self.anomaly_policy!r}; expected "
                f"one of {ANOMALY_POLICIES}")
        if isinstance(self.quant_blocks, str):
            # a bare string would iterate per-CHARACTER below and silently
            # quantize nearly every linear via single-letter substrings
            raise ValueError(
                "quant_blocks must be a list of layer-scope substrings, "
                f"not a string (got {self.quant_blocks!r}; write "
                f"[{self.quant_blocks!r}])")
        self.quant_blocks = [str(b) for b in self.quant_blocks]
        if any(not b for b in self.quant_blocks):
            raise ValueError("quant_blocks entries must be non-empty layer-"
                             "scope substrings (e.g. 'bottleneck_group_"
                             "linear'); got an empty string")
        from .ops.quant import QUANT_DTYPES
        if self.quant_dtype not in QUANT_DTYPES:
            raise ValueError(
                f"unknown quant_dtype {self.quant_dtype!r}; this toolchain "
                f"supports {sorted(QUANT_DTYPES)}")
        self.target_device = str(self.target_device or "")
        if self.target_device:
            # a typoed device kind would silently skip the OOM-before-compile
            # gate; surface it at config load (devices.py is a leaf import)
            from .devices import known_kinds, resolve_device
            if resolve_device(self.target_device) is None:
                raise ValueError(
                    f"unknown target_device {self.target_device!r}; known "
                    f"kinds: {', '.join(known_kinds())} (or \"\" to skip "
                    f"the HBM capacity gate)")
        if int(self.mesh_search_top_k) < 1:
            raise ValueError("mesh_search_top_k must be >= 1 (the rank the "
                             "hand-written mesh must reach in the searcher's "
                             "sheet)")
        self.mesh_search_top_k = int(self.mesh_search_top_k)
        if float(self.serve_queue_deadline_s) < 0:
            raise ValueError("serve_queue_deadline_s must be >= 0 "
                             "(0 = requests wait in the engine queue forever)")
        self.serve_queue_deadline_s = float(self.serve_queue_deadline_s)
        if int(self.serve_queue_limit) < 0:
            raise ValueError("serve_queue_limit must be >= 0 "
                             "(0 = unbounded engine queue)")
        self.serve_queue_limit = int(self.serve_queue_limit)
        if int(self.serve_max_batch) < 1:
            raise ValueError("serve_max_batch must be >= 1 (1 = the "
                             "serialized reference-shaped engine; >1 = the "
                             "continuous-batching scheduler)")
        self.serve_max_batch = int(self.serve_max_batch)
        if int(self.serve_block_tokens) < 0:
            raise ValueError("serve_block_tokens must be >= 0 "
                             "(0 = one whole-sequence block per lane)")
        self.serve_block_tokens = int(self.serve_block_tokens)
        if (self.serve_block_tokens
                and self.serve_block_tokens % self.token_patch_size):
            raise ValueError(
                f"serve_block_tokens={self.serve_block_tokens} must be a "
                f"multiple of token_patch_size={self.token_patch_size} "
                "(KV-pool blocks hold whole decode rows)")
        if int(self.serve_kv_blocks) < 0:
            raise ValueError("serve_kv_blocks must be >= 0 "
                             "(0 = auto: serve_max_batch x blocks per "
                             "sequence)")
        self.serve_kv_blocks = int(self.serve_kv_blocks)
        if self.serve_kv_blocks:
            # the pool must admit at least one full-length request, or every
            # completion sheds at admission forever — surface the dead pool
            # at config load, not in production 503s
            from .infer.kv_cache import blocks_per_sequence
            need = blocks_per_sequence(self)
            if self.serve_kv_blocks < need:
                raise ValueError(
                    f"serve_kv_blocks={self.serve_kv_blocks} cannot hold one "
                    f"full-length sequence ({need} blocks of "
                    f"{self.serve_block_tokens or self.sequence_length} "
                    "tokens); raise serve_kv_blocks or serve_block_tokens")
        if int(self.serve_prefill_chunk_tokens) < 0:
            raise ValueError("serve_prefill_chunk_tokens must be >= 0 "
                             "(0 = monolithic admission prefill)")
        self.serve_prefill_chunk_tokens = int(self.serve_prefill_chunk_tokens)
        if self.serve_prefill_chunk_tokens:
            # chunks scatter-write whole KV-pool blocks at the lane's running
            # position; a chunk that straddles a block boundary would split a
            # block across two asynchronous dispatches
            unit = self.serve_block_tokens or self.token_patch_size
            if self.serve_prefill_chunk_tokens % unit:
                raise ValueError(
                    f"serve_prefill_chunk_tokens="
                    f"{self.serve_prefill_chunk_tokens} must be a multiple of "
                    f"the KV-block size ({unit} = "
                    + ("serve_block_tokens" if self.serve_block_tokens
                       else "token_patch_size")
                    + "); chunks scatter whole blocks")
        self.serve_aot_cache_dir = str(self.serve_aot_cache_dir or "")
        self.serve_stream = bool(self.serve_stream)
        self.serve_trace_path = str(self.serve_trace_path or "")
        if not isinstance(self.slo_objectives, dict):
            raise ValueError(
                "slo_objectives must be a dict of objective -> threshold, "
                'e.g. {"ttft_p95_s": 2.0, "error_rate": 0.01} '
                "({} = SLO alerting off)")
        if self.slo_objectives:
            # surface a typoed objective at config load, not as a silently
            # never-firing alert; validate_objectives raises ValueError
            # naming the bad key/threshold
            from .obs.slo_alerts import validate_objectives
            self.slo_objectives = validate_objectives(self.slo_objectives)
        if int(self.flight_buffer_spans) < 0:
            raise ValueError("flight_buffer_spans must be >= 0 "
                             "(0 = flight recorder off)")
        self.flight_buffer_spans = int(self.flight_buffer_spans)
        if isinstance(self.flight_dump_triggers, str):
            # a bare string would iterate characters and silently disable
            # every real trigger — same guard as quant_blocks
            raise ValueError(
                "flight_dump_triggers must be a sequence of trigger names, "
                "not a string")
        triggers = tuple(str(t) for t in self.flight_dump_triggers)
        from .obs.flight import DUMP_TRIGGERS
        bad = [t for t in triggers if t not in DUMP_TRIGGERS]
        if bad:
            raise ValueError(
                f"flight_dump_triggers has unknown trigger(s) {bad}; "
                f"known: {sorted(DUMP_TRIGGERS)}")
        self.flight_dump_triggers = triggers
        if int(self.serve_replicas) < 1:
            raise ValueError("serve_replicas must be >= 1 "
                             "(the number of engine replica processes)")
        self.serve_replicas = int(self.serve_replicas)
        if int(self.router_port) < 0:
            raise ValueError("router_port must be >= 0 (0 = no router)")
        self.router_port = int(self.router_port)
        if float(self.router_health_interval_s) <= 0:
            raise ValueError("router_health_interval_s must be > 0 "
                             "(seconds between replica /healthz polls)")
        self.router_health_interval_s = float(self.router_health_interval_s)
        if float(self.router_health_timeout_s) <= 0:
            raise ValueError("router_health_timeout_s must be > 0 "
                             "(per-poll HTTP timeout)")
        self.router_health_timeout_s = float(self.router_health_timeout_s)
        if int(self.router_failover_retries) < 0:
            raise ValueError("router_failover_retries must be >= 0 "
                             "(extra replicas tried before giving up)")
        self.router_failover_retries = int(self.router_failover_retries)
        if float(self.serve_watchdog_min_stall_s) <= 0:
            raise ValueError("serve_watchdog_min_stall_s must be > 0 "
                             "(the decode-loop stall threshold floor)")
        self.serve_watchdog_min_stall_s = float(
            self.serve_watchdog_min_stall_s)
        if int(self.usage_top_k) < 0:
            raise ValueError("usage_top_k must be >= 0 "
                             "(0 = usage metering off)")
        self.usage_top_k = int(self.usage_top_k)
        self.usage_tenant_header = str(self.usage_tenant_header
                                       or "X-Tenant")
        if self.watchdog_factor < 0:
            raise ValueError("watchdog_factor must be >= 0 "
                             "(0 = watchdog disabled)")
        if self.watchdog_startup_s < 0:
            raise ValueError("watchdog_startup_s must be >= 0 "
                             "(0 = no startup stall bound)")
        if self.profile_start < 1:
            raise ValueError(
                "profile_start must be >= 1: update 0 pays the XLA compile, "
                "so a window starting there would not capture steady state")
        if self.profile_steps < 1:
            raise ValueError("profile_steps must be >= 1")
        if self.grace_deadline_s < 0:
            raise ValueError("grace_deadline_s must be >= 0 "
                             "(0 = no forced deadline on grace shutdown)")
        if self.ckpt_retries < 0:
            raise ValueError("ckpt_retries must be >= 0 (0 = single attempt)")
        self.dist_coordinator = str(self.dist_coordinator or "")
        self.dist_num_processes = int(self.dist_num_processes)
        self.dist_process_id = int(self.dist_process_id)
        if self.dist_num_processes < 0:
            raise ValueError("dist_num_processes must be >= 0 "
                             "(<= 1 = single-host, no distributed init)")
        if self.dist_process_id < 0:
            raise ValueError("dist_process_id must be >= 0")
        if (self.dist_num_processes > 1
                and self.dist_process_id >= self.dist_num_processes):
            raise ValueError(
                f"dist_process_id={self.dist_process_id} out of range for "
                f"dist_num_processes={self.dist_num_processes}")
        if self.dist_coordinator and self.dist_num_processes == 0:
            # the inverse (world without coordinator) already fails in
            # dist.settings(); a coordinator with no world would silently
            # train N independent models over one model_path instead
            raise ValueError(
                f"dist_coordinator={self.dist_coordinator!r} set but "
                "dist_num_processes is 0: set the fleet size (1 for a "
                "single-process pod slice) or clear the coordinator")
        if float(self.dist_init_timeout_s) < 0:
            raise ValueError("dist_init_timeout_s must be >= 0 "
                             "(0 = no wall deadline on distributed init)")
        self.dist_init_timeout_s = float(self.dist_init_timeout_s)
        if int(self.dist_init_retries) < 0:
            raise ValueError("dist_init_retries must be >= 0 "
                             "(0 = single initialize attempt)")
        self.dist_init_retries = int(self.dist_init_retries)
        if float(self.dist_barrier_timeout_s) < 0:
            raise ValueError("dist_barrier_timeout_s must be >= 0")
        self.dist_barrier_timeout_s = float(self.dist_barrier_timeout_s)
        self.fleet_dir = str(self.fleet_dir or "")
        if self.corrupt_record_budget < 0:
            raise ValueError("corrupt_record_budget must be >= 0 "
                             "(0 = fail fast on any unreadable record)")
        if self.fault_plan:
            # surface a typoed plan at config load, not mid-run; parse_plan
            # raises ValueError naming the bad entry
            from .reliability.faults import parse_plan
            rules = parse_plan(self.fault_plan)
            if (any(r.site == "grads" for r in rules)
                    and self.telemetry_interval <= 0):
                # the grads site is polled by the loop only when device
                # telemetry is on — a silently-inert chaos drill would
                # report success while testing nothing
                raise ValueError(
                    "fault_plan uses the 'grads' site, which requires "
                    "telemetry_interval > 0 (the injection rides the "
                    "telemetry grad_scale input)")

        for attr in ("position_embedding", "token_embedding", "output_embedding",
                     "empty_frame_embedding"):
            v = getattr(self, attr)
            if isinstance(v, str):
                setattr(self, attr, v.split("-"))

        self.learning_rate_config = {
            k: v if isinstance(v, LearningRateConfig) else LearningRateConfig(**v)
            for k, v in dict(self.learning_rate_config).items()}

        for attr in ("storage_dtype", "slice_dtype", "calculation_dtype",
                     "optimizer_slice_dtype", "optimizer_calculation_dtype"):
            v = getattr(self, attr)
            if isinstance(v, str):
                setattr(self, attr, DTYPES[v])

        if self.model_mode == "gpt":
            # text-only path: language on, video off (reference src/main.py:85-92)
            self.use_video = False
            self.use_language = True
        self.multi_loss_strategy = self.multi_loss_strategy.lower()
        if self.multi_loss_strategy not in ("linear", "pcgrad", "mgda"):
            print(f"unknown multi_loss_strategy {self.multi_loss_strategy}; using linear")
            self.multi_loss_strategy = "linear"
        if not self.use_language and not self.use_video:
            raise ValueError("Language and video mode are both disabled")
        if self.sampling_top_k < 0 or self.sampling_top_k > self.vocab_size:
            raise ValueError(
                f"sampling_top_k must be in [0, vocab_size]; got "
                f"{self.sampling_top_k}")
        if not 0.0 < self.sampling_top_p <= 1.0:
            raise ValueError(
                f"sampling_top_p must be in (0, 1]; got {self.sampling_top_p}")
        # GPipe pipeline parallelism (ops/pipeline.py): stages must cut the
        # depth loop evenly and compose with none/checkpoint rematerialization
        # only (reversible chains carry custom_vjp state across stages).
        # The sequence-parallel ring COMPOSES since round 5 — it nests a
        # seq-manual shard_map inside the pipe-manual region (ops/ring.py) —
        # but only under the 1f1b schedule: its per-tick jax.vjp runs the
        # ring's backward immediately, whereas jax.grad THROUGH the gpipe
        # scan delays it, and delayed partial evaluation hoists the ring
        # backward's seq-manual internals across the scan boundary where the
        # partitioner cannot express them (sdy rejects the factor order).
        if self.pipeline_parallel < 1:
            raise ValueError("pipeline_parallel must be a positive integer")
        if self.pipeline_schedule not in ("gpipe", "1f1b"):
            # validated regardless of pipeline_parallel so a typo surfaces
            # before the user scales up
            raise ValueError(
                f"unknown pipeline_schedule {self.pipeline_schedule!r}; "
                "expected 'gpipe' or '1f1b'")
        body_specs = [spec for blk in self.block_config
                      for spec in (blk["layer"] if isinstance(blk, dict)
                                   else blk.layer)]
        if self.pipeline_parallel > 1:
            if self.depth % self.pipeline_parallel:
                raise ValueError("pipeline_parallel must divide depth")
            if self.memory_reduction_strategy not in ("none", "checkpoint"):
                raise ValueError(
                    "pipeline_parallel requires memory_reduction_strategy "
                    "'none' or 'checkpoint'")
            if self.sequence_parallel > 1 and self.pipeline_schedule != "1f1b":
                raise ValueError(
                    "sequence_parallel with pipeline_parallel requires "
                    "pipeline_schedule='1f1b' (gradients through the gpipe "
                    "scan cannot express the nested ring attention's "
                    "backward — see the validation comment above)")
            if self.use_video:
                raise ValueError(
                    "pipeline_parallel supports text (gpt) models only: the "
                    "multi-axis attention rotation depends on the global "
                    "depth index, which is dynamic inside a pipeline stage")
            # cross-depth 'shared' weights compose since round 4: the tensor
            # is replicated per stage and its grad stage-summed
            # (models.sync_shared_pipeline_grads), preserving exact sharing
            # semantics — the flagship's shared mixer maps can pipeline
            if (any(s.split("-")[0] == "routed_moe" for s in body_specs)
                    and self.pipeline_schedule != "1f1b"
                    and self.moe_balance_weight > 0):
                raise ValueError(
                    "pipeline_parallel under the gpipe schedule cannot carry "
                    "the routed_moe balance aux loss across the pipeline "
                    "shard_map boundary; use pipeline_schedule='1f1b' (the "
                    "aux loss rides the schedule's stage stream) or set "
                    "moe_balance_weight=0")
            if self.pipeline_schedule == "1f1b":
                # the loss rides inside the 1F1B schedule (the last stage's
                # tail seeds each microbatch's backward), which constrains
                # what the tail can compute in v1
                if self.multi_loss_strategy != "linear":
                    raise ValueError(
                        "pipeline_schedule='1f1b' supports the linear "
                        "multi-loss strategy only")
                if (self.contrastive_across_samples
                        or self.contrastive_across_token_embeddings):
                    raise ValueError(
                        "pipeline_schedule='1f1b' does not support "
                        "contrastive losses (they need the stashed input "
                        "embedding outside the schedule)")
        # routed_moe's load-balance aux loss cannot cross the reversible
        # custom_vjp boundary (models/__init__.py _body); 'none' collects it
        # directly and 'checkpoint' threads it through jax.checkpoint as a
        # real output, but revnet/momentum would silently drop it — reject
        # rather than train with different semantics than the config names.
        if self.moe_balance_weight > 0 and self.memory_reduction_strategy in (
                "revnet", "momentum"):
            if any(s.split("-")[0] == "routed_moe" for s in body_specs):
                raise ValueError(
                    f"routed_moe with moe_balance_weight > 0 cannot combine "
                    f"with memory_reduction_strategy="
                    f"'{self.memory_reduction_strategy}': the balance aux "
                    f"loss cannot cross the reversible custom_vjp boundary. "
                    f"Use 'none' or 'checkpoint', or set "
                    f"moe_balance_weight=0 to train without the balance term")
        if self.weight_standardisation and not self.weight_centralisation:
            self.weight_centralisation = True
        if self.features is None and self.features_per_head is None:
            raise ValueError("Either features or features_per_head must be given")
        if self.features is None:
            self.features = self.features_per_head * self.heads
        if self.features_per_head is None:
            self.features_per_head = self.features // self.heads
        if self.use_video and (self.frame_width * self.frame_height // self.patch_size) % self.experts:
            raise ValueError("Frame size must be divisible by expert count")
        if self.use_video and self.use_language and self.three_axes:
            # joint mode concatenates text along the video's "height" axis,
            # which requires the flattened (height*width) video layout — the
            # reference implicitly requires the same (dataclass.py:334 names
            # the token patch-count dim "height"; mtf.concat would reject the
            # extra width axis)
            print("WARNING: three_axes disabled — joint video+language mode "
                  "requires the flattened spatial layout")
            self.three_axes = False
        if self.intermediate_feed_forward_multiplier_multiplier is not None:
            self.intermediate_feed_forward_multiplier = (
                self.group_linear_factor
                * self.intermediate_feed_forward_multiplier_multiplier / self.heads)
        if self.intermediate_feed_forward_multiplier is None:
            self.intermediate_feed_forward_multiplier = self.group_linear_factor / self.heads
        if not self.use_video and self.language_token_per_frame != self.sequence_length:
            self.language_token_per_frame = self.sequence_length

        self.masked_attention_dimensions = list(self.masked_attention_dimensions)
        self.block_config = [BlockConfig.make(c, self.memory_reduction_strategy)
                             for c in self.block_config]
        self.input_block_config = [BlockConfig.make(c, "checkpoint")
                                   for c in self.input_block_config]
        self.output_block_config = [BlockConfig.make(c, "checkpoint")
                                    for c in self.output_block_config]

        # video patch arithmetic (reference dataclass.py:262-271)
        self.time_patch_size = self.sequence_length // self.time_patch
        self.frame_height_patch = self.frame_height // self.patch_size
        self.frame_width_patch = self.frame_width // self.patch_size
        self.channel_color_size = self.color_channels * self.time_patch * self.patch_size ** 2
        self.fold_count = 32 // self.bit_fold_value
        if self.use_bit_fold_input_pipeline and 2 ** self.bit_fold_value < self.color_quantization_value:
            raise ValueError("bit-fold value too small for color quantization")
        if self.use_bit_fold_input_pipeline:
            self.channel_color_size //= self.fold_count
        self.language_token_patch = self.language_token_per_frame // self.token_patch_size

        self.intermediate_size = int(
            self.heads * self.features_per_head * self.intermediate_feed_forward_multiplier)
        self.product_key_value_vectors = self.features_per_head ** 2

        # dimension registry
        self.dims: typing.Dict[str, int] = {
            BATCH: self.train_batch_size,
            SEQUENCE: self.time_patch_size,
            HEADS: self.heads,
            KEY: self.features_per_head,
            INTERMEDIATE: self.intermediate_size,
            VOCAB: self.vocab_size,
            TOKEN_PATCH: self.token_patch_size,
            EXPERTS: self.experts,
            PKM_AXES: self.pkm_axes,
            PKM_VALUES: self.product_key_value_vectors,
            HEIGHT: self.frame_height_patch,
            WIDTH: self.frame_width_patch,
            COLOR_CHANNELS: self.channel_color_size,
            anonymize_name(KEY): self.features_per_head * self.group_linear_factor,
        }
        self.feature_dims = (HEADS, KEY)

        # parallelism synthesis: reference maps batch->b, heads->h
        # (dataclass.py:247-252); we extend with a sequence-parallel axis.
        self.mesh_data = max(1, self.tpu_size // (
            self.heads * self.sequence_parallel * self.pipeline_parallel))
        self.mesh_model = self.heads if self.heads > 1 else 1

    # -- convenience --------------------------------------------------------
    def dim_size(self, name: str) -> int:
        return self.dims[name]

    def dict(self) -> dict:
        return {k: v for k, v in self.__dict__.items() if not k.startswith("_")}

    def __repr__(self) -> str:
        return f"Config({self.model_mode}, d={self.features}, L={self.depth})"


ModelParameter = Config  # reference-compatible alias
