"""Named-axis tensor algebra over jax.numpy.

The reference expresses every model op over mtf named Dimensions; the layer DSL
depends on that algebra (axis-rotation attention, group linears, anonymize
markers — see /root/reference/src/utils_mtf.py).  This module provides the
minimal JAX-native equivalent: a :class:`NT` wrapper pairing a ``jnp.ndarray``
with a static tuple of axis names, plus einsum/reduce/broadcast helpers that
operate on names.  Unlike mtf this is pure tracing-time bookkeeping — XLA sees
ordinary arrays; there is no lowering step, and sharding is applied separately
via ``PartitionSpec`` keyed on the same names (parallel/sharding.py).
"""
from __future__ import annotations

import string
import typing

import jax
import jax.numpy as jnp

# -- axis-name registry ------------------------------------------------------
# Central registry of every logical axis name the framework may attach to an
# NT.  config.py registers its canonical dimension constants at import time;
# modules that invent additional axes (layer-local scratch axes and the like)
# register them where they are defined.  The registry is the ground truth for the graftcheck
# axis-literal lint (homebrewnlp_tpu/analysis/ast_rules.py): a string literal
# used in an axis position must resolve here, so a typoed axis name fails
# static analysis instead of silently building a mis-broadcast graph.
_KNOWN_AXES: typing.Set[str] = set()


def register_axis(*names: str) -> None:
    """Register logical axis names as valid (idempotent)."""
    _KNOWN_AXES.update(names)


def known_axes() -> typing.FrozenSet[str]:
    """Snapshot of every registered logical axis name."""
    return frozenset(_KNOWN_AXES)


# -- scope provider ----------------------------------------------------------
# Pointer at the model scope currently being built (pushed/popped by
# models/ctx.py's scope stack).  Two consumers: NT errors raised while a
# scope is active name the enclosing parameter path (diagnostics), and every
# push mirrors into ``jax.named_scope`` so compiled HLO instruction metadata
# (``op_name``) carries the layer path end to end — obs/profile.py joins
# profiler trace events against that metadata for per-layer device-time
# attribution (docs/observability.md "Profile attribution").
_SCOPE_STACK: typing.List[str] = []
_NAMED_SCOPE_CMS: typing.List[typing.Optional[typing.ContextManager]] = []


def push_scope(name: str) -> None:
    _SCOPE_STACK.append(name)
    # '@' is MLIR-special (symbol refs): a name containing it is scrubbed
    # from op_name entirely, so the depth token "@d0_x" emits as "d0_x"
    cm: typing.Optional[typing.ContextManager] = None
    try:
        cm = jax.named_scope(name.replace("@", ""))
        cm.__enter__()
    except Exception:
        cm = None
    _NAMED_SCOPE_CMS.append(cm)


def pop_scope() -> None:
    if _SCOPE_STACK:
        _SCOPE_STACK.pop()
        cm = _NAMED_SCOPE_CMS.pop()
        if cm is not None:
            try:
                cm.__exit__(None, None, None)
            except Exception:
                pass


def current_scope() -> str:
    """The innermost model scope path being built, or '' outside any scope."""
    return "/".join(_SCOPE_STACK)


@jax.tree_util.register_pytree_node_class
class NT:
    """A jnp array with named axes.  ``names`` is static metadata."""

    __slots__ = ("x", "names")

    def __init__(self, x: jnp.ndarray, names: typing.Sequence[str]):
        names = tuple(names)
        if hasattr(x, "ndim") and x.ndim != len(names):
            where = current_scope()
            raise ValueError(
                f"rank mismatch: array {x.shape} vs names {names}"
                + (f" (while building scope {where!r})" if where else ""))
        self.x = x
        self.names = names

    # pytree protocol
    def tree_flatten(self):
        return (self.x,), self.names

    @classmethod
    def tree_unflatten(cls, names, children):
        obj = object.__new__(cls)
        obj.x = children[0]
        obj.names = names
        return obj

    # -- introspection ------------------------------------------------------
    @property
    def shape(self) -> typing.Dict[str, int]:
        return dict(zip(self.names, self.x.shape))

    @property
    def dtype(self):
        return self.x.dtype

    @property
    def size(self) -> int:
        out = 1
        for s in self.x.shape:
            out *= s
        return out

    def dim_size(self, name: str) -> int:
        return self.x.shape[self.names.index(name)]

    def has(self, *names: str) -> bool:
        return all(n in self.names for n in names)

    def __repr__(self):
        return f"NT({dict(zip(self.names, getattr(self.x, 'shape', ())))}, {self.dtype})"

    # -- structural ops -----------------------------------------------------
    def rename(self, old: str, new: str) -> "NT":
        return NT(self.x, tuple(new if n == old else n for n in self.names))

    def astype(self, dtype) -> "NT":
        return NT(self.x.astype(dtype), self.names)

    def transpose_to(self, names: typing.Sequence[str]) -> "NT":
        names = tuple(names)
        if names == self.names:
            return self
        perm = [self.names.index(n) for n in names]
        return NT(self.x.transpose(perm), names)

    def expand(self, name: str, size: int, index: int = 0) -> "NT":
        """Insert a broadcast axis."""
        x = jnp.expand_dims(self.x, index)
        x = jnp.broadcast_to(x, x.shape[:index] + (size,) + x.shape[index + 1:])
        return NT(x, self.names[:index] + (name,) + self.names[index:])

    # -- arithmetic with name-based broadcasting ----------------------------
    def _binary(self, other, fn):
        if not isinstance(other, NT):
            return NT(fn(self.x, other), self.names)
        a, b = broadcast_union(self, other)
        return NT(fn(a.x, b.x), a.names)

    def __add__(self, other):
        return self._binary(other, jnp.add)

    def __radd__(self, other):
        return self._binary(other, lambda x, y: jnp.add(y, x))

    def __sub__(self, other):
        return self._binary(other, jnp.subtract)

    def __rsub__(self, other):
        return self._binary(other, lambda x, y: jnp.subtract(y, x))

    def __mul__(self, other):
        return self._binary(other, jnp.multiply)

    def __rmul__(self, other):
        return self._binary(other, lambda x, y: jnp.multiply(y, x))

    def __truediv__(self, other):
        return self._binary(other, jnp.divide)

    def __rtruediv__(self, other):
        return self._binary(other, lambda x, y: jnp.divide(y, x))

    def __neg__(self):
        return NT(-self.x, self.names)


def union_names(*tensors: NT) -> typing.Tuple[str, ...]:
    """Deduplicated concatenation of axis names, first-seen order (the mtf
    binary-op broadcast rule)."""
    seen: typing.List[str] = []
    for t in tensors:
        for n in t.names:
            if n not in seen:
                seen.append(n)
    return tuple(seen)


def broadcast_union(*tensors: NT) -> typing.List[NT]:
    names = union_names(*tensors)
    sizes = {}
    for t in tensors:
        sizes.update(t.shape)
    out = []
    for t in tensors:
        x = t.transpose_to([n for n in names if n in t.names])
        idx = 0
        for i, n in enumerate(names):
            if n not in t.names:
                x = NT(jnp.expand_dims(x.x, i), x.names[:i] + (n,) + x.names[i:])
        x = NT(jnp.broadcast_to(x.x, tuple(sizes[n] for n in names)), names)
        out.append(x)
    return out


_LETTERS = string.ascii_letters


def contraction_spec(inputs: typing.Sequence[NT],
                     out_names: typing.Sequence[str]) -> str:
    """The ``jnp.einsum`` spec string for a named contraction: axes mapped
    to letters in first-appearance order, everything absent from
    ``out_names`` contracted.  Shared by :func:`einsum` and its quantized
    twin (ops/quant.py::quant_einsum) so the two cannot drift."""
    out_names = tuple(out_names)
    mapping: typing.Dict[str, str] = {}
    for t in inputs:
        for n in t.names:
            if n not in mapping:
                mapping[n] = _LETTERS[len(mapping)]
    for n in out_names:
        if n not in mapping:
            raise ValueError(f"output axis {n} not present in any input")
    return (",".join("".join(mapping[n] for n in t.names) for t in inputs)
            + "->" + "".join(mapping[n] for n in out_names))


def einsum(inputs: typing.Sequence[NT], out_names: typing.Sequence[str],
           precision=None) -> NT:
    """Named einsum: contract all axes absent from ``out_names``."""
    out_names = tuple(out_names)
    spec = contraction_spec(inputs, out_names)
    # Accumulate half-precision matmuls in f32 (free on the MXU, strictly
    # better numerically — same policy as ops/losses.py) and cast the result
    # back to the input dtype so activation storage stays half-precision.
    in_dtype = inputs[0].dtype
    arrays = [t.x for t in inputs]
    if in_dtype in (jnp.bfloat16, jnp.float16):
        if jax.default_backend() in ("tpu", "gpu", "axon"):
            # native half-precision MXU dot with f32 accumulator
            x = jnp.einsum(spec, *arrays, precision=precision,
                           preferred_element_type=jnp.float32)
        else:
            # XLA:CPU's thunk runtime rejects BF16xBF16=F32 dots for some
            # shapes; upcast operands instead — bit-identical, since
            # half-precision products are exact in f32.
            x = jnp.einsum(spec, *[a.astype(jnp.float32) for a in arrays],
                           precision=precision)
        x = x.astype(in_dtype)
    else:
        x = jnp.einsum(spec, *arrays, precision=precision,
                       preferred_element_type=in_dtype)
    return NT(x, out_names)


def _reduce(t: NT, fn, reduced: typing.Optional[typing.Sequence[str]] = None,
            out_names: typing.Optional[typing.Sequence[str]] = None) -> NT:
    if reduced is None:
        reduced = [n for n in t.names if n not in tuple(out_names or ())]
    axes = tuple(t.names.index(n) for n in reduced)
    names = tuple(n for n in t.names if n not in reduced)
    return NT(fn(t.x, axis=axes) if axes else t.x, names)


def reduce_sum(t: NT, reduced=None, out_names=None) -> NT:
    return _reduce(t, jnp.sum, reduced, out_names)


def reduce_mean(t: NT, reduced=None, out_names=None) -> NT:
    return _reduce(t, jnp.mean, reduced, out_names)


def reduce_max(t: NT, reduced=None, out_names=None) -> NT:
    return _reduce(t, jnp.max, reduced, out_names)


def reduce_min(t: NT, reduced=None, out_names=None) -> NT:
    return _reduce(t, jnp.min, reduced, out_names)


def nt_slice(t: NT, axis: str, start: int, end: int) -> NT:
    idx = t.names.index(axis)
    sl = [slice(None)] * len(t.names)
    sl[idx] = slice(start, end)
    return NT(t.x[tuple(sl)], t.names)


def concat(tensors: typing.Sequence[NT], axis: str) -> NT:
    """Concatenate along a named axis (reference utils_mtf.py:131-141 does this
    with an anonymize round-trip; XLA needs no such marker)."""
    names = tensors[0].names
    ts = [t.transpose_to(names) for t in tensors]
    return NT(jnp.concatenate([t.x for t in ts], axis=names.index(axis)), names)


def pad(t: NT, axis: str, before: int, after: int, value=0.0) -> NT:
    cfg = [(0, 0, 0)] * len(t.names)
    cfg[t.names.index(axis)] = (before, after, 0)
    return NT(jax.lax.pad(t.x, jnp.asarray(value, t.dtype), cfg), t.names)


def one_hot(t: NT, axis_name: str, depth: int, dtype=jnp.float32) -> NT:
    return NT(jax.nn.one_hot(t.x, depth, dtype=dtype), t.names + (axis_name,))


def arange(name: str, size: int, dtype=jnp.int32) -> NT:
    return NT(jnp.arange(size, dtype=dtype), (name,))


def cumsum(t: NT, axis: str) -> NT:
    return NT(jnp.cumsum(t.x, axis=t.names.index(axis)), t.names)


def stop_gradient(t: NT) -> NT:
    return NT(jax.lax.stop_gradient(t.x), t.names)


def zeros_like(t: NT) -> NT:
    return NT(jnp.zeros_like(t.x), t.names)


def cast(t: NT, dtype) -> NT:
    return t.astype(dtype)


def full(names: typing.Sequence[str], sizes: typing.Sequence[int], value, dtype) -> NT:
    return NT(jnp.full(tuple(sizes), value, dtype), tuple(names))


def compare_range(name0: str, size0: int, name1: str, size1: int, op, dtype) -> NT:
    """Causal-style mask from two iotas (reference utils_mtf.py:411-415)."""
    a = NT(jnp.arange(size0, dtype=jnp.int32)[:, None], (name0, name1))
    b = NT(jnp.arange(size1, dtype=jnp.int32)[None, :], (name0, name1))
    return NT(op(a.x, b.x).astype(dtype), (name0, name1))


def dedup(names: typing.Iterable[str]) -> typing.Tuple[str, ...]:
    seen: typing.List[str] = []
    for n in names:
        if n not in seen:
            seen.append(n)
    return tuple(seen)
