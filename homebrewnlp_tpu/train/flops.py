"""Static utilization accounting: per-step FLOPs from the compiled HLO.

The reference framework never knew what a step *cost* — utilization was a
number someone computed by hand from the model card.  bench.py grew an XLA
cost-analysis path (EXECUTED flops of the exact compiled step) for its
offline BENCH line; this module makes that same accounting available to the
*live* run, so ``train/metrics.py`` rows, the ``/metrics`` exporter, and
``metrics.jsonl`` carry MFU / tokens-per-second / goodput continuously
instead of once per benchmark session.

One source of truth: ``PEAK_BF16`` (per-chip peak dense bf16 FLOP/s by
``device_kind``) and the cost-analysis call both live here and bench.py
imports them, so the two MFU figures cannot drift — they are the same
arithmetic over the same compiled executable (pinned by
tests/telemetry_test.py::test_flops_reconcile_with_bench_cost_analysis).

Everything here is HOST-side and runs once at startup (the cost analysis
rides the step compile the run pays anyway, via
``Trainer.step_cost_analysis``'s kept AOT executable); nothing touches the
per-step hot path.
"""
from __future__ import annotations

import dataclasses
import typing

#: Peak dense bf16 FLOP/s per chip, by device_kind substring (public specs).
#: Order matters: more specific substrings first ("v5 lite" before "v5").
PEAK_BF16: typing.Tuple[typing.Tuple[str, float], ...] = (
    ("v6", 918e12), ("trillium", 918e12),
    ("v5p", 459e12),
    ("v5 lite", 197e12), ("v5e", 197e12), ("v5litepod", 197e12),
    ("v5", 459e12),
    ("v4", 275e12),
    ("v3", 123e12),
    ("v2", 45e12),
)


def peak_flops(device_kind: str) -> typing.Optional[float]:
    """Per-chip peak bf16 FLOP/s, or None for CPU/unknown (no MFU claim)."""
    kind = device_kind.lower()
    for sub, peak in PEAK_BF16:
        if sub in kind:
            return peak
    return None


def eqn_dot_flops(eqn) -> float:
    """Multiply-add flops of one ``dot_general`` equation from its abstract
    operand shapes (2 * batch * M * N * K), zero for anything else."""
    if eqn.primitive.name != "dot_general":
        return 0.0
    try:
        (contract, batch_dims) = eqn.params["dimension_numbers"]
        (lc, rc), (lb, _rb) = contract, batch_dims
        lhs = eqn.invars[0].aval.shape
        rhs = eqn.invars[1].aval.shape
    except Exception:
        return 0.0
    k = 1
    for d in lc:
        k *= int(lhs[d])
    b = 1
    for d in lb:
        b *= int(lhs[d])
    m = 1
    for i, d in enumerate(lhs):
        if i not in lc and i not in lb:
            m *= int(d)
    n = 1
    for i, d in enumerate(rhs):
        if i not in rc and i not in (_rb or ()):
            n *= int(d)
    return 2.0 * b * m * n * k


def jaxpr_flops(jaxpr) -> float:
    """Static matmul-flop count of a (Closed)Jaxpr — the compile-free twin
    of the XLA cost analysis ``step_flops`` runs on the compiled step, used
    by the analysis cost model's roofline verdict (analysis/cost_model.py).

    ``dot_general`` dominates every workload here; elementwise/conv flops
    are ignored (they are noise next to the matmuls and XLA fuses them into
    the dots' memory traffic anyway).  Sub-jaxprs multiply by their trip
    count: ``scan`` bodies by ``length`` (gradient accumulation, pipeline
    ticks), everything else (pjit/custom_vjp/checkpoint/while/cond) by 1 —
    a ``while`` with an unknowable trip count undercounts, which keeps the
    figure a lower bound like the unfused-twin convention above."""
    inner = jaxpr.jaxpr if hasattr(jaxpr, "jaxpr") else jaxpr
    total = 0.0
    for eqn in inner.eqns:
        total += eqn_dot_flops(eqn)
        mult = 1
        if eqn.primitive.name == "scan":
            mult = int(eqn.params.get("length", 1) or 1)
        for v in eqn.params.values():
            vals = v if isinstance(v, (list, tuple)) else [v]
            for item in vals:
                if hasattr(item, "eqns") or (
                        hasattr(item, "jaxpr")
                        and hasattr(item.jaxpr, "eqns")):
                    total += mult * jaxpr_flops(item)
    return total


def step_flops(trainer, state, batch) -> float:
    """EXECUTED flops of the exact compiled train step (XLA cost analysis,
    same figure bench.py records as ``flops_per_step``).  The AOT executable
    is kept by the trainer, so the analysis costs no extra compile and the
    loop's subsequent steps reuse it."""
    cost = trainer.step_cost_analysis(state, batch)
    return float(cost.get("flops", 0.0))


def kernels_opaque(cfg) -> bool:
    """True when the config routes work through hand-written pallas kernels
    whose in-kernel flops XLA cost analysis cannot see — the executed count
    of the fused step is then incomplete (BENCH_r05's
    ``flops_executed_partial`` / ``mfu: null`` failure mode)."""
    return bool(cfg.fused_mixer_block or cfg.fused_group_linear)


def unfused_twin_flops(trainer, state, batch) -> float:
    """Flops of the SAME step with the fused pallas kernels off — an
    explicit, documented LOWER BOUND on the fused step's executed flops
    (the kernels run the identical math plus in-kernel backward recompute,
    so fusing never removes arithmetic; docs/performance.md "Utilization
    accounting").  Everything else about the config — remat, blocked-map
    depth, quantization — is kept, so the bound tracks the step actually
    being timed.

    Cost: one extra XLA compile of the unfused step (no execution, no
    init: params / optimizer slots are adopted from the measured trainer).
    The cheaper pre-compile ``Lowered.cost_analysis`` was measured and
    rejected: unoptimized-HLO counts run ~7x the compiled figure on the
    tiny test config — an OVER-estimate, which would overstate MFU and
    break the lower-bound contract.  On the live path this only runs for
    fused configs with telemetry enabled, and the compile is served by the
    persistent XLA cache on every restart after the first."""
    import copy

    from ..optim import Optimizer
    from .state import Trainer
    cfg = copy.copy(trainer.cfg)  # knob flip only; derived fields carry over
    cfg.fused_mixer_block = False
    cfg.fused_group_linear = False
    twin = Trainer(cfg, trainer.mesh)
    twin.axes = trainer.axes
    twin.optimizer = Optimizer(cfg, trainer.axes)
    return step_flops(twin, state, batch)


def executed_flops_with_bound(trainer, state, batch
                              ) -> typing.Tuple[float, bool]:
    """(hardware flops per step, is_lower_bound): the cost-analyzed count of
    the exact compiled step, replaced by the unfused twin's count whenever
    opaque kernels make the direct figure incomplete.  The second element
    flags the substitution so consumers label the resulting MFU a lower
    bound instead of an exact figure."""
    flops = step_flops(trainer, state, batch)
    if not kernels_opaque(trainer.cfg):
        return flops, False
    return max(flops, unfused_twin_flops(trainer, state, batch)), True


@dataclasses.dataclass
class Utilization:
    """Static per-step accounting; ``rates(step_seconds)`` turns a measured
    step wall time into the live MFU / throughput figures."""

    flops_per_step: float
    tokens_per_step: int
    n_chips: int
    peak_flops_per_chip: typing.Optional[float]
    device_kind: str = ""
    # True when flops_per_step is the unfused-twin LOWER BOUND (opaque
    # pallas kernels hide their in-kernel flops from cost analysis) — the
    # derived mfu is then a floor, not an exact figure
    flops_lower_bound: bool = False

    def rates(self, step_seconds: float) -> typing.Dict[str, float]:
        if not step_seconds or step_seconds <= 0:
            return {}
        out = {
            "tokens_per_sec": self.tokens_per_step / step_seconds,
            "tokens_per_sec_per_chip": (self.tokens_per_step / step_seconds
                                        / max(1, self.n_chips)),
        }
        if self.peak_flops_per_chip and self.flops_per_step:
            out["mfu"] = (self.flops_per_step / step_seconds
                          / (self.peak_flops_per_chip * max(1, self.n_chips)))
        return out


def utilization_for(trainer, state, batch, tokens_per_step: int
                    ) -> Utilization:
    """Build the static accounting for one run: cost-analyze the compiled
    step and pin the device peak.  Called once at startup when telemetry is
    enabled (main.py)."""
    import jax
    devices = jax.devices()
    kind = devices[0].device_kind
    flops, lower_bound = executed_flops_with_bound(trainer, state, batch)
    return Utilization(
        flops_per_step=flops,
        tokens_per_step=int(tokens_per_step),
        n_chips=max(1, len(devices)),
        peak_flops_per_chip=peak_flops(kind),
        device_kind=kind,
        flops_lower_bound=lower_bound)
