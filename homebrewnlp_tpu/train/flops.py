"""Static utilization accounting: per-step FLOPs from the compiled HLO.

The reference framework never knew what a step *cost* — utilization was a
number someone computed by hand from the model card.  bench.py grew an XLA
cost-analysis path (EXECUTED flops of the exact compiled step) for its
offline BENCH line; this module makes that same accounting available to the
*live* run, so ``train/metrics.py`` rows, the ``/metrics`` exporter, and
``metrics.jsonl`` carry MFU / tokens-per-second / goodput continuously
instead of once per benchmark session.

One source of truth: ``PEAK_BF16`` (per-chip peak dense bf16 FLOP/s by
``device_kind``) and the cost-analysis call both live here and bench.py
imports them, so the two MFU figures cannot drift — they are the same
arithmetic over the same compiled executable (pinned by
tests/telemetry_test.py::test_flops_reconcile_with_bench_cost_analysis).

Everything here is HOST-side and runs once at startup (the cost analysis
rides the step compile the run pays anyway, via
``Trainer.step_cost_analysis``'s kept AOT executable); nothing touches the
per-step hot path.
"""
from __future__ import annotations

import dataclasses
import typing

#: Peak dense bf16 FLOP/s per chip, by device_kind substring (public specs).
#: Order matters: more specific substrings first ("v5 lite" before "v5").
PEAK_BF16: typing.Tuple[typing.Tuple[str, float], ...] = (
    ("v6", 918e12), ("trillium", 918e12),
    ("v5p", 459e12),
    ("v5 lite", 197e12), ("v5e", 197e12), ("v5litepod", 197e12),
    ("v5", 459e12),
    ("v4", 275e12),
    ("v3", 123e12),
    ("v2", 45e12),
)


def peak_flops(device_kind: str) -> typing.Optional[float]:
    """Per-chip peak bf16 FLOP/s, or None for CPU/unknown (no MFU claim)."""
    kind = device_kind.lower()
    for sub, peak in PEAK_BF16:
        if sub in kind:
            return peak
    return None


def step_flops(trainer, state, batch) -> float:
    """EXECUTED flops of the exact compiled train step (XLA cost analysis,
    same figure bench.py records as ``flops_per_step``).  The AOT executable
    is kept by the trainer, so the analysis costs no extra compile and the
    loop's subsequent steps reuse it."""
    cost = trainer.step_cost_analysis(state, batch)
    return float(cost.get("flops", 0.0))


@dataclasses.dataclass
class Utilization:
    """Static per-step accounting; ``rates(step_seconds)`` turns a measured
    step wall time into the live MFU / throughput figures."""

    flops_per_step: float
    tokens_per_step: int
    n_chips: int
    peak_flops_per_chip: typing.Optional[float]
    device_kind: str = ""

    def rates(self, step_seconds: float) -> typing.Dict[str, float]:
        if not step_seconds or step_seconds <= 0:
            return {}
        out = {
            "tokens_per_sec": self.tokens_per_step / step_seconds,
            "tokens_per_sec_per_chip": (self.tokens_per_step / step_seconds
                                        / max(1, self.n_chips)),
        }
        if self.peak_flops_per_chip and self.flops_per_step:
            out["mfu"] = (self.flops_per_step / step_seconds
                          / (self.peak_flops_per_chip * max(1, self.n_chips)))
        return out


def utilization_for(trainer, state, batch, tokens_per_step: int
                    ) -> Utilization:
    """Build the static accounting for one run: cost-analyze the compiled
    step and pin the device peak.  Called once at startup when telemetry is
    enabled (main.py)."""
    import jax
    devices = jax.devices()
    kind = devices[0].device_kind
    return Utilization(
        flops_per_step=step_flops(trainer, state, batch),
        tokens_per_step=int(tokens_per_step),
        n_chips=max(1, len(devices)),
        peak_flops_per_chip=peak_flops(kind),
        device_kind=kind)
