"""Sharded checkpointing + deterministic resume metadata, preemption-safe.

The reference uses TF1 ``Saver(sharded=True)`` + hooks copying mesh-sharded
slices (/root/reference/src/run/run.py:158-176) and recovers ``current_step``
by parsing the checkpoint dir (src/main.py:71); the data stream resumes via a
separate run-log replay (src/inputs.py:33-128).  Here: orbax sharded
checkpoints for {params, opt_state, step}, and the data-pipeline state rides
along as JSON next to the checkpoint — same separation of concerns, without
the replay arithmetic fragility (the reader checkpoints its cursor
directly; see data/resume.py which also keeps the replay option).

Fault tolerance (docs/reliability.md): every save writes an **integrity
manifest** (``manifest_<step>.json``) — tree-structure hash, per-leaf crc32
checksums, config hash, wall time — atomically (tmp + rename) and only AFTER
``wait_until_finished``, so the manifest is the commit marker: a checkpoint
without one is torn.  ``restore`` walks checkpoints newest-first, verifies
the manifest (structure + checksums + data-state sidecar crc + sidecar step
field) and transparently falls back to the newest *verified* checkpoint when
the latest is torn or corrupt.  All storage calls go through the retry layer
(``cfg.ckpt_retries``) and the fault-injection sites ``ckpt_write`` /
``ckpt_commit``.

Elastic resharding (docs/reliability.md "Multi-host elasticity"): manifests
additionally record the **mesh shape** the checkpoint was saved under and
each leaf's **PartitionSpec**, so restore can tell "same data, different
placement" from corruption.  A checkpoint saved on mesh A restores onto the
current mesh B (orbax re-shards onto the template's shardings; global leaf
VALUES are placement-independent) — the reshard is logged loudly, counted
on ``hbnlp_ckpt_reshard_restores_total``, re-verified against the SAME
per-leaf crc32s after placement, and noted in
``restore_marker.json`` so the supervisor's crash-loop probe counts a
reshard-restore as progress.  Stale or mismatched sharding metadata
(unknown mesh axes, specs naming axes the recorded mesh lacks, spec rank
exceeding the leaf rank) is refused as :class:`CheckpointCorrupt`, falling
back to the newest verified checkpoint like any other corruption.
"""
from __future__ import annotations

import hashlib
import json
import logging
import os
import time
import typing
import zlib

import jax
import jax.numpy as jnp
import numpy as np
import orbax.checkpoint as ocp
from jax.sharding import NamedSharding

from ..obs.registry import REGISTRY
from ..parallel.mesh import MESH_AXES
from ..reliability import RetryPolicy, faults, retry_call
from .state import TrainState

LOG = logging.getLogger(__name__)

# version 2: manifests carry the save-time mesh shape + per-leaf
# PartitionSpecs (elastic resharding); version-1 manifests (no "mesh" key)
# keep restoring, just without reshard detection
MANIFEST_VERSION = 2


class CheckpointCorrupt(RuntimeError):
    """A checkpoint failed integrity verification (torn write, bit flip,
    stale/corrupt data-state sidecar).  Restore treats it as 'try the next
    older checkpoint'."""


def _spec_to_json(spec) -> typing.List[typing.Any]:
    """PartitionSpec -> JSON: each entry is None, a mesh-axis name, or a
    list of mesh-axis names (multi-axis sharding of one dim)."""
    out: typing.List[typing.Any] = []
    for part in spec:
        if part is None:
            out.append(None)
        elif isinstance(part, (tuple, list)):
            out.append([str(p) for p in part])
        else:
            out.append(str(part))
    return out


def _mesh_meta(tree) -> typing.Optional[dict]:
    """Save-time mesh metadata from the first NamedSharding-placed leaf:
    axis-name -> size plus the device count.  None for host-only trees
    (tests constructing states off-mesh)."""
    for leaf in jax.tree_util.tree_leaves(tree):
        sh = getattr(leaf, "sharding", None)
        if isinstance(sh, NamedSharding):
            return {"axes": {str(k): int(v)
                             for k, v in sh.mesh.shape.items()},
                    "n_devices": int(sh.mesh.devices.size)}
    return None


def _leaf_entries(tree, with_checksums: bool = True
                  ) -> typing.Dict[str, dict]:
    """Flatten the {params, opt_state, step} tree into ``{keypath: {shape,
    dtype[, spec][, crc32]}}``.  Checksums hash the leaf bytes exactly as
    saved (post ``master_dtype`` cast), so a restore can re-cast and
    compare; ``spec`` records the save-time PartitionSpec so restore can
    tell a reshard from corruption."""
    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    out: typing.Dict[str, dict] = {}
    for path, leaf in flat:
        entry: typing.Dict[str, typing.Any] = {
            "shape": list(getattr(leaf, "shape", ())),
            "dtype": str(getattr(leaf, "dtype", type(leaf).__name__))}
        sh = getattr(leaf, "sharding", None)
        if isinstance(sh, NamedSharding):
            entry["spec"] = _spec_to_json(sh.spec)
        if with_checksums:
            # np.asarray is the host pull: only safe when every shard is
            # addressable from this process (the with_checksums guard)
            arr = np.asarray(leaf)
            entry["crc32"] = (zlib.crc32(np.ascontiguousarray(arr).tobytes())
                              & 0xFFFFFFFF)
        out[jax.tree_util.keystr(path)] = entry
    return out


def _structure_hash(leaves: typing.Dict[str, dict]) -> str:
    doc = json.dumps([[k, leaves[k]["shape"], leaves[k]["dtype"]]
                      for k in sorted(leaves)])
    return hashlib.sha256(doc.encode()).hexdigest()[:16]


def _leaf_crc(arr: np.ndarray, dtype: str) -> int:
    """crc32 of ``arr`` in the manifest's dtype.  Restore targets may widen
    the storage dtype (bf16 master -> f32 template); casting back is exact
    for widenings, so save-time and restore-time hashes agree."""
    if str(arr.dtype) != dtype:
        # jnp handles ml_dtypes names (bfloat16) that plain numpy lacks
        arr = np.asarray(jnp.asarray(arr).astype(dtype))
    return zlib.crc32(np.ascontiguousarray(arr).tobytes()) & 0xFFFFFFFF


def _write_atomic(path: str, payload: str) -> None:
    """tmp + rename in the same directory: readers never see a torn file."""
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:  # graftcheck: disable=bare-io
        f.write(payload)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


class Checkpointer:
    def __init__(self, path: str, max_to_keep: int = 1, retries: int = 2):
        self.path = os.path.abspath(os.path.expanduser(path))
        os.makedirs(self.path, exist_ok=True)
        self._policy = RetryPolicy(max_attempts=int(retries) + 1,
                                   base_delay_s=0.2, max_delay_s=5.0)
        self.manager = retry_call(
            lambda: ocp.CheckpointManager(  # graftcheck: disable=bare-io
                self.path,
                options=ocp.CheckpointManagerOptions(max_to_keep=max_to_keep,
                                                     create=True)),
            site="ckpt_open", policy=self._policy)
        self._fallbacks = REGISTRY.counter(
            "hbnlp_ckpt_fallbacks_total",
            "corrupt/torn checkpoints skipped during restore")
        self._reshards = REGISTRY.counter(
            "hbnlp_ckpt_reshard_restores_total",
            "checkpoints restored onto a different mesh shape than they "
            "were saved on (single-process restores re-verify the manifest "
            "CRCs after placement; multi-process saves carry structure-only "
            "manifests)")

    # -- save ----------------------------------------------------------------
    def save(self, state: TrainState,
             data_state: typing.Optional[dict] = None,
             master_dtype=None,
             config_hash: typing.Optional[str] = None) -> None:
        """``master_dtype`` (cfg.storage_dtype): dtype of the checkpointed
        master copy of the params — MTF's master/slice split (reference
        dataclass.py:253-255, VariableDType.master_dtype).  Optimizer slots
        keep their own optimizer_slice_dtype.

        Commit order is the crash-safety contract: (1) orbax write + barrier
        (retried), (2) data-state sidecar (atomic), (3) manifest (atomic) —
        so a sidecar can never point at an uncommitted checkpoint, and a
        missing manifest marks the whole step torn.  The sidecar is stamped
        with its ``step`` (validated on load; ``"step"`` is therefore a
        reserved key in ``data_state``)."""
        step = int(state.step)
        params = state.params
        if master_dtype is not None:
            params = {k: v.astype(master_dtype) for k, v in params.items()}
        tree = {"params": params, "opt_state": state.opt_state,
                "step": state.step}
        # per-leaf checksums need the full array on THIS host; multi-process
        # shardings keep a structure-only manifest (still a commit marker)
        with_checksums = jax.process_count() == 1
        leaves = _leaf_entries(tree, with_checksums=with_checksums)
        manifest: typing.Dict[str, typing.Any] = {
            "version": MANIFEST_VERSION, "step": step,
            "wall_time": time.time(), "config_hash": config_hash,
            "process_count": jax.process_count(),
            "mesh": _mesh_meta(tree),
            "structure": _structure_hash(leaves), "leaves": leaves}

        def _commit() -> None:
            faults.hit("ckpt_write")
            try:
                # no force: a re-save of an already-committed step (the loop
                # tail after an on-cadence save) is silently skipped by
                # orbax's should_save, exactly as before this layer existed
                self.manager.save(  # graftcheck: disable=bare-io
                    step, args=ocp.args.StandardSave(tree))
                # the barrier: nothing below may run until the checkpoint is
                # durable (satellite: sidecar-after-wait)
                self.manager.wait_until_finished()  # graftcheck: disable=bare-io
            except Exception:
                self._scrub_partial(step)
                raise

        retry_call(_commit, site="ckpt_write", policy=self._policy)
        if data_state is not None:
            payload = json.dumps({"step": step, **data_state})
            retry_call(
                lambda: _write_atomic(self._data_state_path(step), payload),
                site="ckpt_sidecar", policy=self._policy)
            if with_checksums:
                manifest["data_state_crc"] = (zlib.crc32(payload.encode())
                                              & 0xFFFFFFFF)
        if jax.process_index() == 0:
            retry_call(
                lambda: _write_atomic(self._manifest_path(step),
                                      json.dumps(manifest)),
                site="ckpt_manifest", policy=self._policy)
        self._prune_stale_sidecars()
        faults.hit("ckpt_commit", path=self._step_dir(step))

    def _scrub_rejected(self, steps: typing.Sequence[int]) -> None:
        """Remove corrupt/torn checkpoint steps after a successful fallback
        restore.  The corrupt data is useless for continuation and its
        presence blocks progress (see restore); orbax's own delete keeps
        the manager's step list consistent.  Best-effort."""
        for s in steps:
            LOG.warning("scrubbing rejected checkpoint step %d", s)
            try:
                self.manager.delete(s)
            except Exception:
                self._scrub_partial(s)
        self._prune_stale_sidecars()

    def _prune_stale_sidecars(self) -> None:
        """Drop manifests/cursor sidecars whose step dir orbax pruned
        (max_to_keep) plus orphaned ``*.tmp.<pid>`` files from atomic
        writes interrupted between write and rename: restore ignores both,
        but a tidy dir keeps the supervisor's progress probe honest.
        Best-effort."""
        keep = set(self.manager.all_steps())
        for fn in os.listdir(self.path):
            if ".json.tmp." in fn:
                try:
                    os.remove(os.path.join(self.path, fn))
                except OSError:
                    pass
                continue
            for prefix in ("manifest_", "data_state_"):
                if not (fn.startswith(prefix) and fn.endswith(".json")):
                    continue
                stem = fn[len(prefix):-len(".json")].split("_p")[0]
                try:
                    s = int(stem)
                except ValueError:
                    continue
                if s not in keep:
                    try:
                        os.remove(os.path.join(self.path, fn))
                    except OSError:
                        pass

    def _scrub_partial(self, step: int) -> None:
        """Best-effort removal of a torn step dir so the retry's re-save
        does not trip over the leftovers."""
        import shutil
        d = self._step_dir(step)
        if os.path.isdir(d):
            LOG.warning("scrubbing partial checkpoint dir %s before retry", d)
            shutil.rmtree(d, ignore_errors=True)

    def _step_dir(self, step: int) -> str:
        return os.path.join(self.path, str(step))

    def _manifest_path(self, step: int) -> str:
        return os.path.join(self.path, f"manifest_{step}.json")

    def _read_manifest(self, step: int) -> typing.Optional[dict]:
        """The step's manifest, or None when missing/unreadable (both mean
        'not verified')."""
        path = self._manifest_path(step)
        try:
            with open(path) as f:  # graftcheck: disable=bare-io
                m = json.load(f)
        except FileNotFoundError:
            return None
        except (OSError, ValueError) as e:
            LOG.error("manifest %s unreadable (%r) — treating step %d as "
                      "unverified", path, e, step)
            return None
        if not isinstance(m, dict) or m.get("step") != step:
            LOG.error("manifest %s malformed or step-mismatched — treating "
                      "step %d as unverified", path, step)
            return None
        return m

    def _data_state_path(self, step: int) -> str:
        """Data-pipeline cursor sidecar.  Multi-process runs keep ONE cursor
        file PER PROCESS (each host's reader consumed a different slice of
        the stream — reference dataloader_placement.py:101-136 writes its
        DataLog per dataset host the same way); single-process keeps the
        plain name."""
        suffix = (f"_p{jax.process_index()}"
                  if jax.process_count() > 1 else "")
        return os.path.join(self.path, f"data_state_{step}{suffix}.json")

    def _load_data_state(self, step: int,
                         expected_crc: typing.Optional[int] = None
                         ) -> typing.Optional[dict]:
        # fall back to the other naming so cursors survive a process-count
        # change (or a checkpoint written before per-process sidecars):
        # multi-process probes its own _p{r} file then the legacy plain
        # name; single-process probes the plain name then rank 0's
        legacy = os.path.join(self.path, f"data_state_{step}.json")
        rank0 = os.path.join(self.path, f"data_state_{step}_p0.json")
        own = self._data_state_path(step)
        for path in (own, legacy, rank0):
            if not os.path.exists(path):
                continue
            if path != own:
                # loud like the params-migration NOTE: after a
                # process-count change this rank resumes from another
                # rank's (or the legacy single-process) stream position,
                # so rows may repeat or skip relative to its own history
                logging.getLogger(__name__).warning(
                    "rank %d data cursor %s missing; falling back to %s "
                    "— this rank's data-stream position comes from a "
                    "different process layout", jax.process_index(),
                    os.path.basename(own), os.path.basename(path))
            def _read(p=path) -> str:
                with open(p) as f:  # graftcheck: disable=bare-io
                    return f.read()

            raw = retry_call(_read, site="ckpt_sidecar", policy=self._policy)
            if expected_crc is not None and path == own:
                got = zlib.crc32(raw.encode()) & 0xFFFFFFFF
                if got != expected_crc:
                    raise CheckpointCorrupt(
                        f"data-state sidecar {os.path.basename(path)} fails "
                        f"its manifest checksum (crc {got:#010x} != "
                        f"{expected_crc:#010x}) — torn or corrupt cursor")
            try:
                ds = json.loads(raw)
            except ValueError as e:
                raise CheckpointCorrupt(
                    f"data-state sidecar {os.path.basename(path)} is not "
                    f"valid JSON ({e}) — torn or corrupt cursor") from e
            # refuse a stale cursor LOUDLY: a sidecar recorded for a
            # different step would silently repeat/skip training data
            if "step" in ds and int(ds["step"]) != step:
                raise CheckpointCorrupt(
                    f"data-state sidecar {os.path.basename(path)} records "
                    f"step {ds['step']} but the checkpoint is step {step} — "
                    "refusing to resume from a stale data cursor")
            if "step" not in ds:
                logging.getLogger(__name__).warning(
                    "data cursor %s predates step-stamped sidecars; "
                    "accepting without step validation",
                    os.path.basename(path))
            # the stamp is transport metadata: callers get back exactly the
            # dict they passed to save()
            ds.pop("step", None)
            return ds
        logging.getLogger(__name__).warning(
            "no data cursor found for step %d (rank %d) — the input "
            "pipeline restarts from its initial position", step,
            jax.process_index())
        return None

    def wait(self) -> None:
        # save() already waits inside its commit (the manifest depends on
        # it); this remains for callers pacing external work off the barrier
        retry_call(
            lambda: self.manager.wait_until_finished(),  # graftcheck: disable=bare-io
            site="ckpt_write", policy=self._policy)

    # -- restore -------------------------------------------------------------
    def latest_step(self) -> typing.Optional[int]:
        return self.manager.latest_step()

    def all_steps(self) -> typing.List[int]:
        return sorted(self.manager.all_steps())

    def restore(self, template: TrainState, cfg=None
                ) -> typing.Tuple[TrainState, typing.Optional[dict]]:
        """Restore the newest VERIFIED checkpoint onto the template's
        shardings, walking older checkpoints when the latest is torn (no
        manifest while siblings have one) or corrupt (structure/checksum/
        sidecar verification fails).  Checkpoints predating manifests (none
        present at all) restore unverified, exactly as before.

        With ``cfg`` given and ``pipeline_parallel > 1``, checkpoints written
        before stage-stacked pipeline residency (flat per-depth layout) are
        detected by key-set mismatch and migrated in place of a structure
        error (a one-time host-memory round trip)."""
        steps = sorted(self.manager.all_steps(), reverse=True)
        if not steps:
            return template, None
        any_manifest = any(os.path.exists(self._manifest_path(s))
                           for s in steps)
        rejected: typing.List[str] = []
        rejected_steps: typing.List[int] = []
        for step in steps:
            manifest = self._read_manifest(step) if any_manifest else None
            if any_manifest and manifest is None:
                LOG.error(
                    "checkpoint step %d has no valid integrity manifest — "
                    "torn write; falling back to previous verified "
                    "checkpoint", step)
                rejected.append(f"{step}: missing/invalid manifest")
                rejected_steps.append(step)
                self._fallbacks.inc()
                continue
            try:
                state, data_state = self._restore_step(step, template, cfg,
                                                       manifest)
            except CheckpointCorrupt as e:
                LOG.error(
                    "checkpoint step %d failed verification (%s) — falling "
                    "back to previous verified checkpoint", step, e)
                rejected.append(f"{step}: {e}")
                rejected_steps.append(step)
                self._fallbacks.inc()
                continue
            except OSError:
                # a transient storage outage that exhausted the retry budget
                # is infrastructure, NOT corruption: falling back would
                # silently discard committed progress.  Surface it.
                raise
            except Exception as e:
                # orbax-level failure (truncated/compressed-garbage leaf
                # file, missing metadata): same fallback, different layer
                LOG.error(
                    "checkpoint step %d failed restore (%r) — falling back "
                    "to previous verified checkpoint", step, e)
                rejected.append(f"{step}: {type(e).__name__}: {e}")
                rejected_steps.append(step)
                self._fallbacks.inc()
                continue
            if rejected:
                LOG.warning("restored fallback checkpoint at step %d "
                            "(rejected newer: %s)", step, "; ".join(rejected))
                # scrub the rejected (newer) steps NOW: orbax's should_save
                # skips any save whose step <= the latest on-disk step, so a
                # corrupt step-100 dir left in place would silently swallow
                # every checkpoint until training re-passed step 100
                self._scrub_rejected(rejected_steps)
            return state, data_state
        raise RuntimeError(
            f"no restorable checkpoint under {self.path} — every candidate "
            f"failed verification ({'; '.join(rejected)}).  Refusing to "
            "fresh-start over an existing checkpoint dir (max_to_keep could "
            "overwrite the evidence); repair or move it aside")

    def _check_sharding_meta(self, step: int,
                             manifest: typing.Optional[dict],
                             template: TrainState
                             ) -> typing.Optional[typing.Tuple[dict, dict]]:
        """Validate the manifest's sharding metadata and detect a reshard.

        Returns ``(saved_axes, current_axes)`` when the checkpoint was
        saved under a DIFFERENT mesh shape than the template's (a reshard
        restore), None otherwise.  Stale/mismatched metadata — unknown mesh
        axes, a leaf spec naming an axis the recorded mesh lacks, or a spec
        longer than its leaf's rank — raises :class:`CheckpointCorrupt`
        (refused loudly; restore falls back to the newest verified
        checkpoint).  Pre-elastic (version-1) manifests carry no ``mesh``
        key and skip this check entirely."""
        if manifest is None:
            return None
        mesh_meta = manifest.get("mesh")
        if mesh_meta is None:
            return None
        axes = mesh_meta.get("axes") if isinstance(mesh_meta, dict) else None
        if not isinstance(axes, dict) or not axes:
            raise CheckpointCorrupt(
                f"step {step} manifest mesh metadata is malformed "
                f"({mesh_meta!r}) — refusing to trust its sharding story")
        unknown = sorted(a for a in axes if a not in MESH_AXES)
        if unknown:
            raise CheckpointCorrupt(
                f"step {step} manifest names unknown mesh axes {unknown} "
                f"(known: {list(MESH_AXES)}) — stale or foreign sharding "
                "metadata")
        for key, entry in manifest.get("leaves", {}).items():
            spec = entry.get("spec")
            if spec is None:
                continue
            if (not isinstance(spec, list)
                    or len(spec) > len(entry.get("shape", []))):
                raise CheckpointCorrupt(
                    f"step {step} leaf {key} sharding spec {spec!r} does "
                    f"not fit its shape {entry.get('shape')} — mismatched "
                    "sharding metadata")
            for part in spec:
                names = part if isinstance(part, list) else [part]
                for nm in names:
                    if nm is not None and nm not in axes:
                        raise CheckpointCorrupt(
                            f"step {step} leaf {key} sharding spec names "
                            f"mesh axis {nm!r} absent from the manifest's "
                            f"mesh {sorted(axes)} — mismatched sharding "
                            "metadata")
        cur_sh = getattr(template.step, "sharding", None)
        if not isinstance(cur_sh, NamedSharding):
            return None  # host-only template (tests): nothing to compare
        cur_axes = {str(k): int(v) for k, v in cur_sh.mesh.shape.items()}
        saved_axes = {str(k): int(v) for k, v in axes.items()}
        if saved_axes == cur_axes:
            return None
        LOG.warning(
            "checkpoint step %d was saved on mesh %s (%s device(s)); "
            "restoring onto mesh %s — resharding (global values are "
            "placement-independent; the manifest checksums re-verify them "
            "after placement)", step, saved_axes,
            mesh_meta.get("n_devices", "?"), cur_axes)
        return saved_axes, cur_axes

    def _note_reshard_restore(self, step: int, saved_axes: dict,
                              cur_axes: dict, crc_verified: bool) -> None:
        """Persist the reshard on ``restore_marker.json`` (monotonic count)
        so the supervisor's crash-loop probe counts a successful
        reshard-restore as progress even when the step counter is frozen
        across the relaunch (tools/supervise.py::progress_signature).
        ``crc_verified`` records honestly whether per-leaf checksums were
        re-checked after placement — multi-process saves carry
        structure-only manifests, so their reshards are placement-checked
        but NOT byte-verified.

        EVERY process writes a marker (rank 0 the plain name, ranks > 0 a
        ``_p<r>`` suffix, mirroring the data-cursor sidecars): each host's
        supervisor probes its own model_path, so a rank-0-only marker
        would leave every other host's restore-heavy relaunch reading as
        a crash loop."""
        self._reshards.inc()
        if not crc_verified:
            LOG.warning(
                "reshard restore of step %d verified structure only (no "
                "per-leaf checksums in a multi-process manifest) — the "
                "placed values were not byte-verified", step)
        suffix = (f"_p{jax.process_index()}"
                  if jax.process_index() != 0 else "")
        path = os.path.join(self.path, f"restore_marker{suffix}.json")
        count = 0
        prev: dict = {}
        try:
            with open(path) as f:  # graftcheck: disable=bare-io
                prev = json.load(f)
            count = int(prev.get("count", 0))
        except (OSError, ValueError):
            pass  # absent or torn marker: restart the count
        if (prev.get("step") == step and prev.get("from_mesh") == saved_axes
                and prev.get("to_mesh") == cur_axes):
            # the SAME reshard repeating (a child that restores then dies
            # every generation, never saving a new checkpoint) is NOT new
            # recovery work — bumping the count would reset the
            # supervisor's crash-loop probe forever and the backstop
            # (EXIT_CRASH_LOOP) could never fire
            LOG.warning("repeat reshard restore of step %d onto the same "
                        "mesh; not counting it as new supervisor progress",
                        step)
            return
        payload = json.dumps({
            "count": count + 1, "step": step, "from_mesh": saved_axes,
            "to_mesh": cur_axes, "crc_verified": bool(crc_verified),
            "wall_time": time.time()})
        try:
            retry_call(lambda: _write_atomic(path, payload),
                       site="ckpt_marker", policy=self._policy)
        except OSError as e:
            # the marker is an ADVISORY progress hint for the supervisor's
            # crash-loop probe: a marker-write outage must never fail the
            # already-successful (and verified) restore it annotates
            LOG.warning("could not persist restore marker %s (%r); the "
                        "supervisor will not see this reshard as progress",
                        path, e)

    def _restore_step(self, step: int, template: TrainState, cfg,
                      manifest: typing.Optional[dict]
                      ) -> typing.Tuple[TrainState, typing.Optional[dict]]:
        # sharding metadata gate BEFORE the orbax read: stale/mismatched
        # metadata must refuse loudly (fallback), a mere mesh change is a
        # legitimate reshard the verify below re-proves bit-identical
        reshard = self._check_sharding_meta(step, manifest, template)
        tree = {"params": template.params, "opt_state": template.opt_state,
                "step": template.step}
        abstract = jax.tree_util.tree_map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype,
                                           sharding=x.sharding),
            tree)
        migrated = False
        try:
            restored = retry_call(
                lambda: self.manager.restore(  # graftcheck: disable=bare-io
                    step, args=ocp.args.StandardRestore(abstract)),
                site="ckpt_read", policy=self._policy)
            state = TrainState(restored["params"], restored["opt_state"],
                               restored["step"])
        except ValueError as e:
            # structure mismatch: possibly a pre-stage-stacked pipeline
            # checkpoint (flat per-depth layout) — migrate if so; any other
            # ValueError is re-raised unchanged from the migration probe
            if cfg is None or getattr(cfg, "pipeline_parallel", 1) <= 1:
                raise
            state = self._restore_flat_pipeline(step, template, cfg, e)
            migrated = True
        if manifest is not None and not migrated:
            self._verify(step, state, manifest)
        elif manifest is not None:
            LOG.warning("checkpoint step %d migrated from the flat pipeline "
                        "layout; leaf checksums not comparable — skipping "
                        "verification", step)
        crc = manifest.get("data_state_crc") if manifest else None
        # the sidecar can still reject this step (stale/torn cursor) —
        # it must load BEFORE the reshard is recorded as progress
        data_state = self._load_data_state(step, expected_crc=crc)
        if reshard is not None and not migrated:
            # single-process: the verify above re-proved the resharded
            # leaves bit-identical (per-leaf crc32 on the gathered values);
            # multi-process manifests are structure-only — recorded as such
            crc_verified = (manifest is not None
                            and jax.process_count() == 1
                            and any("crc32" in e for e in
                                    manifest.get("leaves", {}).values()))
            self._note_reshard_restore(step, *reshard,
                                       crc_verified=crc_verified)
        return state, data_state

    def _verify(self, step: int, state: TrainState, manifest: dict) -> None:
        """Structure + per-leaf checksum verification against the manifest.
        Checksums exist only for single-process saves; a structure-only
        manifest still catches torn/mis-keyed checkpoints."""
        tree = {"params": state.params, "opt_state": state.opt_state,
                "step": state.step}
        flat, _ = jax.tree_util.tree_flatten_with_path(tree)
        got_keys = {jax.tree_util.keystr(p) for p, _ in flat}
        want = manifest.get("leaves", {})
        if set(want) != got_keys:
            missing = sorted(set(want) - got_keys)[:3]
            extra = sorted(got_keys - set(want))[:3]
            raise CheckpointCorrupt(
                f"step {step} tree structure differs from its manifest "
                f"(missing {missing}, unexpected {extra})")
        if jax.process_count() != 1:
            return
        for path, leaf in flat:
            key = jax.tree_util.keystr(path)
            entry = want[key]
            if "crc32" not in entry:
                continue
            arr = np.asarray(leaf)
            if list(arr.shape) != entry["shape"]:
                raise CheckpointCorrupt(
                    f"step {step} leaf {key} shape {list(arr.shape)} != "
                    f"manifest {entry['shape']}")
            got = _leaf_crc(arr, entry["dtype"])
            if got != entry["crc32"]:
                raise CheckpointCorrupt(
                    f"step {step} leaf {key} fails its checksum "
                    f"({got:#010x} != {entry['crc32']:#010x}) — bit corruption "
                    "or a torn leaf write")

    def _restore_flat_pipeline(self, step: int, template: TrainState, cfg,
                               original: Exception) -> TrainState:
        """One-time migration: restore a flat per-depth pipeline checkpoint
        as saved (host numpy — a one-off host-memory round trip), stack
        params AND optimizer slots into the stage-stacked layout, and place
        them onto the template's shardings.  If the checkpoint turns out to
        already be stage-stacked, ``original`` (the structure error from the
        normal restore) is the real problem and is re-raised unchanged."""
        from ..models import pipeline_params_stacked, stack_pipeline_params
        raw = retry_call(
            lambda: self.manager.restore(  # graftcheck: disable=bare-io
                step, args=ocp.args.StandardRestore(None)),
            site="ckpt_read", policy=self._policy)
        if pipeline_params_stacked(cfg, raw["params"]):
            raise original
        print(f"NOTE: checkpoint at step {step} predates stage-stacked "
              "pipeline residency; migrating flat per-depth layout in place")
        params = stack_pipeline_params(cfg, raw["params"])
        opt_state = stack_pipeline_params(cfg, raw["opt_state"])

        def put(t, v):
            return jax.device_put(jnp.asarray(v).astype(t.dtype), t.sharding)

        params = jax.tree_util.tree_map(put, dict(template.params), params)
        opt_state = jax.tree_util.tree_map(put, dict(template.opt_state),
                                           opt_state)
        return TrainState(params, opt_state,
                          put(template.step, raw["step"]))


def current_step(model_path: str) -> int:
    """Recover the global step from a checkpoint dir at startup (the
    reference reads TF estimator internals, src/main.py:71)."""
    path = os.path.abspath(model_path)
    if not os.path.isdir(path):
        return 0
    try:
        step = ocp.CheckpointManager(path).latest_step()  # graftcheck: disable=bare-io
        return 0 if step is None else int(step)
    except Exception as e:  # pragma: no cover - corrupt metadata etc.
        # surface the problem rather than silently restarting: with
        # max_to_keep=1 a fresh run can overwrite the real checkpoint
        print(f"WARNING: failed to read checkpoint state from {path}: {e!r}; "
              "assuming step 0")
        return 0
