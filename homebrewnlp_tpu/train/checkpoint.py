"""Sharded checkpointing + deterministic resume metadata.

The reference uses TF1 ``Saver(sharded=True)`` + hooks copying mesh-sharded
slices (/root/reference/src/run/run.py:158-176) and recovers ``current_step``
by parsing the checkpoint dir (src/main.py:71); the data stream resumes via a
separate run-log replay (src/inputs.py:33-128).  Here: orbax sharded
checkpoints for {params, opt_state, step}, and the data-pipeline state rides
along as JSON next to the checkpoint — same separation of concerns, without
the replay arithmetic fragility (the reader checkpoints its cursor
directly; see data/resume.py which also keeps the replay option).
"""
from __future__ import annotations

import json
import logging
import os
import typing

import jax
import jax.numpy as jnp
import orbax.checkpoint as ocp

from .state import TrainState


class Checkpointer:
    def __init__(self, path: str, max_to_keep: int = 1):
        self.path = os.path.abspath(os.path.expanduser(path))
        os.makedirs(self.path, exist_ok=True)
        self.manager = ocp.CheckpointManager(
            self.path,
            options=ocp.CheckpointManagerOptions(max_to_keep=max_to_keep,
                                                 create=True))

    # -- save ----------------------------------------------------------------
    def save(self, state: TrainState,
             data_state: typing.Optional[dict] = None,
             master_dtype=None) -> None:
        """``master_dtype`` (cfg.storage_dtype): dtype of the checkpointed
        master copy of the params — MTF's master/slice split (reference
        dataclass.py:253-255, VariableDType.master_dtype).  Optimizer slots
        keep their own optimizer_slice_dtype."""
        step = int(state.step)
        params = state.params
        if master_dtype is not None:
            params = {k: v.astype(master_dtype) for k, v in params.items()}
        tree = {"params": params, "opt_state": state.opt_state,
                "step": state.step}
        self.manager.save(step, args=ocp.args.StandardSave(tree))
        if data_state is not None:
            with open(self._data_state_path(step), "w") as f:
                json.dump(data_state, f)

    def _data_state_path(self, step: int) -> str:
        """Data-pipeline cursor sidecar.  Multi-process runs keep ONE cursor
        file PER PROCESS (each host's reader consumed a different slice of
        the stream — reference dataloader_placement.py:101-136 writes its
        DataLog per dataset host the same way); single-process keeps the
        plain name."""
        suffix = (f"_p{jax.process_index()}"
                  if jax.process_count() > 1 else "")
        return os.path.join(self.path, f"data_state_{step}{suffix}.json")

    def _load_data_state(self, step: int) -> typing.Optional[dict]:
        # fall back to the other naming so cursors survive a process-count
        # change (or a checkpoint written before per-process sidecars):
        # multi-process probes its own _p{r} file then the legacy plain
        # name; single-process probes the plain name then rank 0's
        legacy = os.path.join(self.path, f"data_state_{step}.json")
        rank0 = os.path.join(self.path, f"data_state_{step}_p0.json")
        own = self._data_state_path(step)
        for path in (own, legacy, rank0):
            if os.path.exists(path):
                if path != own:
                    # loud like the params-migration NOTE: after a
                    # process-count change this rank resumes from another
                    # rank's (or the legacy single-process) stream position,
                    # so rows may repeat or skip relative to its own history
                    logging.getLogger(__name__).warning(
                        "rank %d data cursor %s missing; falling back to %s "
                        "— this rank's data-stream position comes from a "
                        "different process layout", jax.process_index(),
                        os.path.basename(own), os.path.basename(path))
                with open(path) as f:
                    return json.load(f)
        logging.getLogger(__name__).warning(
            "no data cursor found for step %d (rank %d) — the input "
            "pipeline restarts from its initial position", step,
            jax.process_index())
        return None

    def wait(self) -> None:
        self.manager.wait_until_finished()

    # -- restore -------------------------------------------------------------
    def latest_step(self) -> typing.Optional[int]:
        return self.manager.latest_step()

    def restore(self, template: TrainState, cfg=None
                ) -> typing.Tuple[TrainState, typing.Optional[dict]]:
        """Restore the latest checkpoint onto the template's shardings.

        With ``cfg`` given and ``pipeline_parallel > 1``, checkpoints written
        before stage-stacked pipeline residency (flat per-depth layout) are
        detected by key-set mismatch and migrated in place of a structure
        error (a one-time host-memory round trip)."""
        step = self.latest_step()
        if step is None:
            return template, None
        tree = {"params": template.params, "opt_state": template.opt_state,
                "step": template.step}
        abstract = jax.tree_util.tree_map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=x.sharding),
            tree)
        try:
            restored = self.manager.restore(
                step, args=ocp.args.StandardRestore(abstract))
        except ValueError as e:
            # structure mismatch: possibly a pre-stage-stacked pipeline
            # checkpoint (flat per-depth layout) — migrate if so; any other
            # ValueError is re-raised unchanged from the migration probe
            if cfg is None or getattr(cfg, "pipeline_parallel", 1) <= 1:
                raise
            return self._restore_flat_pipeline(step, template, cfg, e)
        return (TrainState(restored["params"], restored["opt_state"],
                           restored["step"]),
                self._load_data_state(step))

    def _restore_flat_pipeline(self, step: int, template: TrainState, cfg,
                               original: Exception
                               ) -> typing.Tuple[TrainState,
                                                 typing.Optional[dict]]:
        """One-time migration: restore a flat per-depth pipeline checkpoint
        as saved (host numpy — a one-off host-memory round trip), stack
        params AND optimizer slots into the stage-stacked layout, and place
        them onto the template's shardings.  If the checkpoint turns out to
        already be stage-stacked, ``original`` (the structure error from the
        normal restore) is the real problem and is re-raised unchanged."""
        from ..models import pipeline_params_stacked, stack_pipeline_params
        raw = self.manager.restore(step, args=ocp.args.StandardRestore(None))
        if pipeline_params_stacked(cfg, raw["params"]):
            raise original
        print(f"NOTE: checkpoint at step {step} predates stage-stacked "
              "pipeline residency; migrating flat per-depth layout in place")
        params = stack_pipeline_params(cfg, raw["params"])
        opt_state = stack_pipeline_params(cfg, raw["opt_state"])

        def put(t, v):
            return jax.device_put(jnp.asarray(v).astype(t.dtype), t.sharding)

        params = jax.tree_util.tree_map(put, dict(template.params), params)
        opt_state = jax.tree_util.tree_map(put, dict(template.opt_state),
                                           opt_state)
        state = TrainState(params, opt_state,
                           put(template.step, raw["step"]))
        return state, self._load_data_state(step)


def current_step(model_path: str) -> int:
    """Recover the global step from a checkpoint dir at startup (the
    reference reads TF estimator internals, src/main.py:71)."""
    path = os.path.abspath(model_path)
    if not os.path.isdir(path):
        return 0
    try:
        step = ocp.CheckpointManager(path).latest_step()
        return 0 if step is None else int(step)
    except Exception as e:  # pragma: no cover - corrupt metadata etc.
        # surface the problem rather than silently restarting: with
        # max_to_keep=1 a fresh run can overwrite the real checkpoint
        print(f"WARNING: failed to read checkpoint state from {path}: {e!r}; "
              "assuming step 0")
        return 0
