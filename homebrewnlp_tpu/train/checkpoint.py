"""Sharded checkpointing + deterministic resume metadata.

The reference uses TF1 ``Saver(sharded=True)`` + hooks copying mesh-sharded
slices (/root/reference/src/run/run.py:158-176) and recovers ``current_step``
by parsing the checkpoint dir (src/main.py:71); the data stream resumes via a
separate run-log replay (src/inputs.py:33-128).  Here: orbax sharded
checkpoints for {params, opt_state, step}, and the data-pipeline state rides
along as JSON next to the checkpoint — same separation of concerns, without
the replay arithmetic fragility (the reader checkpoints its cursor
directly; see data/resume.py which also keeps the replay option).
"""
from __future__ import annotations

import json
import os
import typing

import jax
import jax.numpy as jnp
import orbax.checkpoint as ocp

from .state import TrainState


class Checkpointer:
    def __init__(self, path: str, max_to_keep: int = 1):
        self.path = os.path.abspath(os.path.expanduser(path))
        os.makedirs(self.path, exist_ok=True)
        self.manager = ocp.CheckpointManager(
            self.path,
            options=ocp.CheckpointManagerOptions(max_to_keep=max_to_keep,
                                                 create=True))

    # -- save ----------------------------------------------------------------
    def save(self, state: TrainState,
             data_state: typing.Optional[dict] = None,
             master_dtype=None) -> None:
        """``master_dtype`` (cfg.storage_dtype): dtype of the checkpointed
        master copy of the params — MTF's master/slice split (reference
        dataclass.py:253-255, VariableDType.master_dtype).  Optimizer slots
        keep their own optimizer_slice_dtype."""
        step = int(state.step)
        params = state.params
        if master_dtype is not None:
            params = {k: v.astype(master_dtype) for k, v in params.items()}
        tree = {"params": params, "opt_state": state.opt_state,
                "step": state.step}
        self.manager.save(step, args=ocp.args.StandardSave(tree))
        if data_state is not None:
            with open(os.path.join(self.path, f"data_state_{step}.json"), "w") as f:
                json.dump(data_state, f)

    def wait(self) -> None:
        self.manager.wait_until_finished()

    # -- restore -------------------------------------------------------------
    def latest_step(self) -> typing.Optional[int]:
        return self.manager.latest_step()

    def restore(self, template: TrainState
                ) -> typing.Tuple[TrainState, typing.Optional[dict]]:
        """Restore the latest checkpoint onto the template's shardings."""
        step = self.latest_step()
        if step is None:
            return template, None
        tree = {"params": template.params, "opt_state": template.opt_state,
                "step": template.step}
        abstract = jax.tree_util.tree_map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=x.sharding),
            tree)
        restored = self.manager.restore(
            step, args=ocp.args.StandardRestore(abstract))
        data_state = None
        data_path = os.path.join(self.path, f"data_state_{step}.json")
        if os.path.exists(data_path):
            with open(data_path) as f:
                data_state = json.load(f)
        return TrainState(restored["params"], restored["opt_state"],
                          restored["step"]), data_state


def current_step(model_path: str) -> int:
    """Recover the global step from a checkpoint dir at startup (the
    reference reads TF estimator internals, src/main.py:71)."""
    path = os.path.abspath(model_path)
    if not os.path.isdir(path):
        return 0
    try:
        step = ocp.CheckpointManager(path).latest_step()
        return 0 if step is None else int(step)
    except Exception as e:  # pragma: no cover - corrupt metadata etc.
        # surface the problem rather than silently restarting: with
        # max_to_keep=1 a fresh run can overwrite the real checkpoint
        print(f"WARNING: failed to read checkpoint state from {path}: {e!r}; "
              "assuming step 0")
        return 0
