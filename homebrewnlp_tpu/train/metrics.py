"""Metrics logging: colored stdout + JSONL scalars (+ optional TensorBoard).

The reference emits TensorBoard scalars from inside the TPU program via
``tpu.outside_compilation`` host calls flushed every step
(/root/reference/src/run/utils_run.py:32-58, run.py:123-153) and prints
timestamped ANSI-colored phase logs (src/utils_core.py:43-48).  In JAX the
metrics come back as ordinary step outputs, so logging is plain host code; a
TensorBoard event writer is used when the `tensorboardX`/`tf` stack exists,
else JSONL only (works everywhere, greppable, and what bench.py parses).
"""
from __future__ import annotations

import datetime
import json
import os
import time
import typing

import numpy as np


def color_print(*args, color: str = "\x1b[32;1m") -> None:
    now = datetime.datetime.now().strftime("%H:%M:%S.%f")[:-3]
    print(f"{color}[{now}]\x1b[0m", *args, flush=True)


class MetricWriter:
    def __init__(self, model_path: str, flush_every: int = 1):
        self.path = model_path
        os.makedirs(model_path, exist_ok=True)
        self._f = open(os.path.join(model_path, "metrics.jsonl"), "a")
        self.flush_every = flush_every
        self._n = 0
        self._t0 = time.time()
        self._last_step_time = self._t0
        self._tb = None
        try:  # optional TensorBoard backend
            from torch.utils.tensorboard import SummaryWriter  # noqa
            self._tb = SummaryWriter(os.path.join(model_path, "tb"))
        except Exception:
            pass

    def write(self, step: int, metrics: typing.Dict[str, typing.Any]) -> None:
        now = time.time()
        scalars = {}
        for k, v in metrics.items():
            try:
                scalars[k] = float(np.asarray(v))
            except Exception:
                continue
        scalars["step"] = int(step)
        scalars["wall_time"] = now
        scalars["step_seconds"] = now - self._last_step_time
        self._last_step_time = now
        self._f.write(json.dumps(scalars) + "\n")
        self._n += 1
        if self._n % self.flush_every == 0:
            self._f.flush()
        if self._tb is not None:
            for k, v in scalars.items():
                if k not in ("step", "wall_time"):
                    self._tb.add_scalar(k, v, step)

    def close(self) -> None:
        self._f.close()
        if self._tb is not None:
            self._tb.close()
