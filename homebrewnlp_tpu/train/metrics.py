"""Metrics logging: colored stdout + JSONL scalars (+ optional TensorBoard).

The reference emits TensorBoard scalars from inside the TPU program via
``tpu.outside_compilation`` host calls flushed every step
(/root/reference/src/run/utils_run.py:32-58, run.py:123-153) and prints
timestamped ANSI-colored phase logs (src/utils_core.py:43-48).  In JAX the
metrics come back as ordinary step outputs, so logging is plain host code; a
TensorBoard event writer is used when the `tensorboardX`/`tf` stack exists,
else JSONL only (works everywhere, greppable, and what bench.py parses).
"""
from __future__ import annotations

import collections
import datetime
import hashlib
import json
import os
import time
import typing

import numpy as np

from ..obs import fleet, spans
from ..reliability import FLUSH_POLICY, retry_call

# log2-|grad| histogram bucket edges shared between the train step (which
# bins on-device, train/state.py) and the TensorBoard rendering below
GRAD_HIST_EDGES = np.arange(-30.0, 7.0, 1.0)
GRAD_HIST_PREFIX = "grad_hist/"


def color_print(*args, color: str = "\x1b[32;1m") -> None:
    now = datetime.datetime.now().strftime("%H:%M:%S.%f")[:-3]
    print(f"{color}[{now}]\x1b[0m", *args, flush=True)


def read_metric_rows(path: str) -> typing.List[dict]:
    """Rows of a ``metrics.jsonl`` that carry step metrics — run-start
    boundary markers (``write_run_start``) and any future marker records
    are skipped.  ``path`` is the file or its containing model dir.  THE
    reader every metrics.jsonl consumer should use (bench.py's guard and
    the test helpers do) so no consumer crashes on a marker row."""
    if os.path.isdir(path):
        path = os.path.join(path, "metrics.jsonl")
    with open(path) as f:  # graftcheck: disable=bare-io
        return [r for r in (json.loads(line) for line in f) if "loss" in r]


def config_hash(cfg) -> str:
    """Stable short hash of the full (derived) config — the run-start
    marker's identity, so post-mortem tooling can tell a resume from a
    hyperparameter change."""
    doc = json.dumps({k: str(v) for k, v in cfg.dict().items()},
                     sort_keys=True)
    return hashlib.sha256(doc.encode()).hexdigest()[:12]


class MetricWriter:
    def __init__(self, model_path: str, flush_every: int = 1):
        self.path = model_path
        os.makedirs(model_path, exist_ok=True)
        self._f = retry_call(
            lambda: open(os.path.join(model_path, "metrics.jsonl"), "a"),  # graftcheck: disable=bare-io
            site="metrics_open")
        self.flush_every = flush_every
        self._n = 0
        self._t0 = time.time()
        self._last_step_time = self._t0
        # utilization accounting (train/flops.py, set via set_utilization):
        # per-row mfu/tokens_per_sec derived from step_seconds, plus run
        # goodput = productive step seconds / wall seconds since run start
        self._util = None
        self._rows_in_run = 0
        self._productive_s = 0.0
        self.last_rates: typing.Dict[str, float] = {}
        self._tb = None
        try:  # optional TensorBoard backend
            from torch.utils.tensorboard import SummaryWriter  # noqa
            self._tb = SummaryWriter(os.path.join(model_path, "tb"))
        except Exception:
            pass

    def set_utilization(self, util, run_start: typing.Optional[float] = None
                        ) -> None:
        """Arm the live MFU/goodput accounting (a ``train.flops.Utilization``):
        every subsequent metric row carries ``mfu`` / ``tokens_per_sec`` /
        ``goodput`` derived from its own ``step_seconds``.

        ``run_start``: wall origin of the goodput denominator.  The caller
        passes the loop's TRUE entry time — this writer is constructed
        AFTER init/restore/compile, and a goodput that excluded exactly the
        overhead it exists to expose would read ~1.0 on a compile-dominated
        run."""
        self._util = util
        if run_start is not None and self._n == 0:
            self._t0 = float(run_start)
            self._last_step_time = self._t0

    def goodput(self) -> float:
        """Useful-step seconds / wall seconds since this writer (run)
        started.  The first row of each run is excluded from the productive
        numerator — its ``step_seconds`` spans compile + init, exactly the
        overhead goodput exists to expose."""
        wall = time.time() - self._t0
        return self._productive_s / wall if wall > 0 else 0.0

    def write_run_start(self, resume_step: int, cfg_hash: str,
                        identity: typing.Optional[dict] = None) -> None:
        """Run boundary marker: ``metrics.jsonl`` appends across restarts, so
        every run begins with ``{"run_start": true, resume_step,
        config_hash, wall_time}`` plus the fleet identity (rank /
        world_size / coordinator / generation — obs/fleet.py) so the file
        itself says which host of which fleet generation wrote it.
        ``identity``: the caller's cfg-resolved identity (main.py passes
        ``Obs.identity``) so config-driven multi-host runs — env vars
        unset, dist_* knobs set — record the same rank /healthz reports;
        the env-only fallback covers direct writer users.  Consumers that
        read metric rows must skip records without a ``"loss"``/``"step"``
        key (bench.py's guard and the test helpers do)."""
        doc = {"run_start": True, "resume_step": int(resume_step),
               "config_hash": cfg_hash, "wall_time": time.time()}
        ident = identity if identity is not None else fleet.identity()
        doc["rank"] = ident["rank"]
        doc["world_size"] = ident["world_size"]
        if ident["coordinator"]:
            doc["coordinator"] = ident["coordinator"]
        if "generation" in ident:
            doc["generation"] = ident["generation"]
        self._f.write(json.dumps(doc) + "\n")
        self._rows_in_run = 0
        self.flush()

    def write(self, step: int, metrics: typing.Dict[str, typing.Any],
              wall_time: typing.Optional[float] = None) -> None:
        """``wall_time``: when the step was DISPATCHED (the deferred drain
        below writes entries later; step_seconds must reflect the training
        cadence, not the drain cadence)."""
        now = time.time() if wall_time is None else wall_time
        scalars = {}
        hists = {}
        for k, v in metrics.items():
            try:
                arr = np.asarray(v)
            except Exception:
                continue
            if arr.size == 1:
                scalars[k] = float(arr)
            elif k.startswith(GRAD_HIST_PREFIX) and arr.ndim == 1:
                # histogram counts over GRAD_HIST_EDGES buckets emitted by
                # debug_gradients (train/state.py); other non-scalar metrics
                # are skipped
                hists[k] = arr.astype(np.float64)  # host-side TB writer, never traced — graftcheck: disable=dtype-promotion
        scalars["step"] = int(step)
        scalars["wall_time"] = now
        scalars["step_seconds"] = now - self._last_step_time
        self._last_step_time = now
        if self._util is not None:
            self._rows_in_run += 1
            if self._rows_in_run > 1:
                # the run's first step_seconds spans compile/init/restore —
                # not a training cadence; it stays out of both the rates and
                # the productive-time numerator
                self._productive_s += max(0.0, scalars["step_seconds"])
                self.last_rates = self._util.rates(scalars["step_seconds"])
                scalars.update(self.last_rates)
            scalars["goodput"] = round(self.goodput(), 6)
        self._f.write(json.dumps(scalars) + "\n")
        self._n += 1
        if self._n % self.flush_every == 0:
            self.flush()
        if self._tb is not None:
            for k, v in scalars.items():
                if k not in ("step", "wall_time"):
                    self._tb.add_scalar(k, v, step)
            for k, counts in hists.items():
                # counts over GRAD_HIST_EDGES buckets: reconstruct the
                # raw-stat form add_histogram_raw expects
                limits = GRAD_HIST_EDGES[1:][:len(counts)]
                n = float(counts.sum())
                if n <= 0:
                    continue
                centers = limits - 0.5
                self._tb.add_histogram_raw(
                    k, min=float(limits[0] - 1), max=float(limits[-1]),
                    num=n, sum=float((centers * counts).sum()),
                    sum_squares=float((centers ** 2 * counts).sum()),
                    bucket_limits=limits.tolist(),
                    bucket_counts=counts.tolist(), global_step=step)

    def flush(self) -> None:
        # bounded retry (FLUSH_POLICY): a transient EIO/ENOSPC blip must not
        # kill the run, but a wedged disk must not stall the step loop either
        retry_call(self._f.flush, site="metrics_flush", policy=FLUSH_POLICY)

    def close(self) -> None:
        self._f.close()
        if self._tb is not None:
            self._tb.close()


class AsyncMetricWriter:
    """Deferred metrics drain for the async-dispatch step loop (main.py,
    docs/performance.md).

    ``write`` only enqueues the step's still-on-device metrics; entries are
    materialized (the blocking device->host transfer) when they fall out of
    the bounded ``window`` — so the loop never synchronizes on the step it
    just dispatched, and up to ``window`` updates stay in flight.
    ``window=0`` drains every step immediately (the synchronous parity
    path).

    - ``last_loss``: loss of the most recent COMPLETED (drained) step — what
      progress prints show, never blocking on in-flight work.
    - ``host_blocked_s``: accumulated wall time inside the blocking
      device->host conversions (main.py prints it in the end-of-run
      summary; bench.py reports its own per-window figure).
    - ``flush()``: drain everything — called at checkpoints, before
      ``jax.profiler.stop_trace`` (so traces capture whole steps), and on
      exit.  Because draining the newest entry blocks until its metrics are
      ready, a returned ``flush()`` implies every dispatched step finished.
    """

    def __init__(self, writer: MetricWriter, window: int = 2,
                 health=None, registry=None, anomaly=None, reporter=None):
        """``health``/``registry`` (optional, docs/observability.md): each
        drained step reports to ``Health.step_completed`` (the /healthz +
        watchdog notion of progress — a step counts once its metrics
        materialized) and a drain-latency histogram.  ``anomaly`` (an
        ``obs.device_telemetry.AnomalyMonitor``) consumes each drained
        step's telemetry sentinels — counting skip_step skips, raising
        ``AnomalyHalt`` under the halt policy — AFTER the row is written,
        so the anomalous step itself is always in metrics.jsonl for the
        post-mortem.  ``reporter`` (an ``obs.fleet.FleetReporter``) posts
        each drained step's DISPATCH timestamp to the shared fleet dir for
        cross-rank skew attribution — drain-side like everything else
        here, so the dispatch hot path stays sync-free."""
        self.writer = writer
        self.window = max(0, int(window))
        self._anomaly = anomaly
        self._reporter = reporter
        self._pending: typing.Deque[typing.Tuple[int, float, dict]] = \
            collections.deque()
        self.last_loss: typing.Optional[float] = None
        self.host_blocked_s = 0.0
        self._health = health
        self._drain_hist = None if registry is None else registry.histogram(
            "hbnlp_metric_drain_seconds",
            "wall seconds blocked in the device->host metric pull per step")

    def write_run_start(self, resume_step: int, cfg_hash: str,
                        identity: typing.Optional[dict] = None) -> None:
        self.writer.write_run_start(resume_step, cfg_hash,
                                    identity=identity)

    def set_utilization(self, util,
                        run_start: typing.Optional[float] = None) -> None:
        self.writer.set_utilization(util, run_start=run_start)

    def goodput(self) -> float:
        return self.writer.goodput()

    @property
    def last_rates(self) -> typing.Dict[str, float]:
        return self.writer.last_rates

    def write(self, step: int, metrics: typing.Dict[str, typing.Any]) -> None:
        self._pending.append((step, time.time(), metrics))
        while len(self._pending) > self.window:
            self._drain_one()

    def _drain_one(self) -> None:
        step, wall, metrics = self._pending.popleft()
        t0 = time.perf_counter()
        host = {}
        with spans.span("drain", step=step):
            for k, v in metrics.items():
                try:
                    host[k] = np.asarray(v)  # blocks until step completed
                except Exception:
                    host[k] = v
        blocked = time.perf_counter() - t0
        self.host_blocked_s += blocked
        if self._drain_hist is not None:
            self._drain_hist.observe(blocked)
        if self._health is not None:
            # dispatch wall, not drain wall: a flush() draining the whole
            # window back-to-back must not collapse the health EMA
            self._health.step_completed(step, dispatch_wall=wall)
        if self._reporter is not None:
            # same dispatch wall: fleet skew measures training cadence
            self._reporter.step_completed(step, dispatch_wall=wall)
        loss = host.get("loss")
        if loss is not None and getattr(loss, "size", 0) == 1:
            self.last_loss = float(loss)
        self.writer.write(step, host, wall_time=wall)
        if self._anomaly is not None:
            # after the write: a halt must not lose the anomalous row
            self._anomaly.observe(step, host)

    def flush(self) -> None:
        while self._pending:
            self._drain_one()
        self.writer.flush()

    def close(self) -> None:
        while self._pending:
            self._drain_one()
        self.writer.close()
