"""Metrics logging: colored stdout + JSONL scalars (+ optional TensorBoard).

The reference emits TensorBoard scalars from inside the TPU program via
``tpu.outside_compilation`` host calls flushed every step
(/root/reference/src/run/utils_run.py:32-58, run.py:123-153) and prints
timestamped ANSI-colored phase logs (src/utils_core.py:43-48).  In JAX the
metrics come back as ordinary step outputs, so logging is plain host code; a
TensorBoard event writer is used when the `tensorboardX`/`tf` stack exists,
else JSONL only (works everywhere, greppable, and what bench.py parses).
"""
from __future__ import annotations

import datetime
import json
import os
import time
import typing

import numpy as np

# log2-|grad| histogram bucket edges shared between the train step (which
# bins on-device, train/state.py) and the TensorBoard rendering below
GRAD_HIST_EDGES = np.arange(-30.0, 7.0, 1.0)
GRAD_HIST_PREFIX = "grad_hist/"


def color_print(*args, color: str = "\x1b[32;1m") -> None:
    now = datetime.datetime.now().strftime("%H:%M:%S.%f")[:-3]
    print(f"{color}[{now}]\x1b[0m", *args, flush=True)


class MetricWriter:
    def __init__(self, model_path: str, flush_every: int = 1):
        self.path = model_path
        os.makedirs(model_path, exist_ok=True)
        self._f = open(os.path.join(model_path, "metrics.jsonl"), "a")
        self.flush_every = flush_every
        self._n = 0
        self._t0 = time.time()
        self._last_step_time = self._t0
        self._tb = None
        try:  # optional TensorBoard backend
            from torch.utils.tensorboard import SummaryWriter  # noqa
            self._tb = SummaryWriter(os.path.join(model_path, "tb"))
        except Exception:
            pass

    def write(self, step: int, metrics: typing.Dict[str, typing.Any]) -> None:
        now = time.time()
        scalars = {}
        hists = {}
        for k, v in metrics.items():
            try:
                arr = np.asarray(v)
            except Exception:
                continue
            if arr.size == 1:
                scalars[k] = float(arr)
            elif k.startswith(GRAD_HIST_PREFIX) and arr.ndim == 1:
                # histogram counts over GRAD_HIST_EDGES buckets emitted by
                # debug_gradients (train/state.py); other non-scalar metrics
                # are skipped
                hists[k] = arr.astype(np.float64)  # host-side TB writer, never traced — graftcheck: disable=dtype-promotion
        scalars["step"] = int(step)
        scalars["wall_time"] = now
        scalars["step_seconds"] = now - self._last_step_time
        self._last_step_time = now
        self._f.write(json.dumps(scalars) + "\n")
        self._n += 1
        if self._n % self.flush_every == 0:
            self._f.flush()
        if self._tb is not None:
            for k, v in scalars.items():
                if k not in ("step", "wall_time"):
                    self._tb.add_scalar(k, v, step)
            for k, counts in hists.items():
                # counts over GRAD_HIST_EDGES buckets: reconstruct the
                # raw-stat form add_histogram_raw expects
                limits = GRAD_HIST_EDGES[1:][:len(counts)]
                n = float(counts.sum())
                if n <= 0:
                    continue
                centers = limits - 0.5
                self._tb.add_histogram_raw(
                    k, min=float(limits[0] - 1), max=float(limits[-1]),
                    num=n, sum=float((centers * counts).sum()),
                    sum_squares=float((centers ** 2 * counts).sum()),
                    bucket_limits=limits.tolist(),
                    bucket_counts=counts.tolist(), global_step=step)

    def close(self) -> None:
        self._f.close()
        if self._tb is not None:
            self._tb.close()
