"""Train state + the pjit-compiled train step.

Replaces the reference's run layer graph assembly (/root/reference/src/run/
run.py:36-198) and macro-batching wrapper (src/run/train.py:19-77): what MTF
did with per-micro-batch graph rebuilds, cached variables and fused assign
ops is here one jitted function — gradient accumulation is a ``lax.scan``
over micro-batches, the optimizer update is traced inline, and GSPMD shards
everything according to parallel/sharding.py rules.
"""
from __future__ import annotations

import typing

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from ..config import Config
from ..obs import device_telemetry
from ..models import build, init_params
from ..models.ctx import Ctx
from ..nd import NT
from ..optim import Optimizer
from ..parallel import make_mesh, param_shardings, spec_for
from ..parallel.sharding import constraint


class TrainState(typing.NamedTuple):
    params: typing.Dict[str, jnp.ndarray]
    opt_state: typing.Dict[str, typing.Dict[str, jnp.ndarray]]
    step: jnp.ndarray  # int32 global update counter


class Trainer:
    """Owns mesh, optimizer, and the compiled train step."""

    def __init__(self, cfg: Config, mesh: typing.Optional[Mesh] = None):
        self.cfg = cfg
        self.mesh = mesh if mesh is not None else make_mesh(cfg)
        self.axes: typing.Dict[str, typing.Tuple[str, ...]] = {}
        self.optimizer: typing.Optional[Optimizer] = None
        self._step_fn = None
        self._compiled = None  # AOT executable (see step_cost_analysis)

    # -- initialization ------------------------------------------------------
    def init(self, batch: typing.Dict[str, NT], seed: int = 0) -> TrainState:
        """Initialize params on the mesh (sharded per axis rules) and zeroed
        optimizer state."""
        micro = self._micro_batch(batch)
        params, axes = init_params(self.cfg, micro, seed=seed)
        if self.cfg.pipeline_parallel > 1:
            # stage-stack the body params from init: leaves gain a leading
            # [P] axis mapped to the pipeline mesh axis, so params AND
            # optimizer slots live 1/P per device (ops/pipeline.py)
            from ..models import stack_pipeline_params
            params, axes = stack_pipeline_params(self.cfg, params, axes)
        self.axes = axes
        self.optimizer = Optimizer(self.cfg, axes)
        shardings = param_shardings(axes, self.mesh)
        params = {k: jax.device_put(v, shardings[k]) for k, v in params.items()}
        opt_state = self.optimizer.init(params)
        slot_axes = self.optimizer.slot_axis_names()
        opt_state = {
            name: {k: jax.device_put(
                v, NamedSharding(self.mesh, spec_for(slot_axes[name][k], self.mesh)))
                for k, v in slots.items()}
            for name, slots in opt_state.items()}
        step = jax.device_put(
            jnp.zeros((), jnp.int32),
            NamedSharding(self.mesh, PartitionSpec()))
        return TrainState(params, opt_state, step)

    @property
    def n_micro(self) -> int:
        """Micro-batches per train step.

        ``macro_batching`` inflates the host batch by M (the pipeline delivers
        ``train_batch_size * M`` rows, reference dataloader_placement.py:40-44)
        and ``grad_accumulation`` additionally splits each configured batch
        into G slices; the step scans all M*G micro-batches and applies ONE
        optimizer update from the averaged gradients (the reference applies
        ``fn="update"`` only on the last macro slice, src/run/train.py:50-56).
        """
        return self.cfg.grad_accumulation * self.cfg.macro_batching

    def _micro_batch(self, batch: typing.Dict[str, NT]) -> typing.Dict[str, NT]:
        """First micro-batch view of a (possibly accumulated) batch."""
        accum = self.n_micro
        if accum <= 1:
            return batch
        out = {}
        for k, t in batch.items():
            assert t.x.shape[0] % accum == 0, (
                f"batch axis {t.x.shape[0]} of {k!r} not divisible by "
                f"micro-batch count {accum}")
            out[k] = NT(t.x[:t.x.shape[0] // accum], t.names)
        return out

    # -- loss / gradients ----------------------------------------------------
    def _losses(self, params, batch, rng):
        ctx = Ctx(self.cfg, params=params, train=True, rng=rng, mesh=self.mesh)
        out = build(ctx, batch)
        return out

    def _grads(self, params, batch, rng):
        cfg = self.cfg
        if cfg.pipeline_parallel > 1 and cfg.pipeline_schedule == "1f1b":
            # loss and grads come from ONE interleaved pipeline schedule —
            # no outer jax.grad (models.pipelined_loss_and_grads)
            from ..models import pipelined_loss_and_grads
            # seed=0 is the same default Ctx seed _losses builds with, so
            # the 1F1B walk and the eval walk see identical apply-time
            # seed-dependent behavior
            return pipelined_loss_and_grads(cfg, params, batch, rng,
                                            self.mesh, seed=0)
        if cfg.multi_loss_strategy == "linear":
            def total(p):
                o = self._losses(p, batch, rng)
                return o.loss, o
            (loss, out), grads = jax.value_and_grad(total, has_aux=True)(params)
            return grads, out
        # per-loss gradients for pcgrad/mgda (reference gradients.py:65-66):
        # one forward (vjp) + one backward per loss via one-hot cotangents
        def losses_only(p):
            o = self._losses(p, batch, rng)
            return o.loss_list, o
        loss_list, vjp_fn, out = jax.vjp(losses_only, params, has_aux=True)
        n = len(loss_list)
        grads_per_loss = [
            vjp_fn(tuple(jnp.float32(1.0) if j == i else jnp.zeros_like(l)
                         for j, l in enumerate(loss_list)))[0]
            for i in range(n)]
        return self.optimizer.combine_losses(grads_per_loss), out

    # -- the step ------------------------------------------------------------
    def _make_step(self):
        cfg = self.cfg
        mesh = self.mesh
        accum = self.n_micro
        opt = self.optimizer
        # global_step counts macro slices, not updates, when macro-batching
        # (reference run.py:155-156: assign_add(global_step, macro_batching))
        step_increment = max(1, cfg.macro_batching)

        def aux_metrics(o):
            """Per-micro auxiliary losses as a flat dict (missing ones are
            simply absent — the model emits a consistent set per config)."""
            m = {}
            if o.token_loss is not None:
                m["token_loss"] = o.token_loss
            if o.video_loss is not None:
                m["video_loss"] = o.video_loss
            if o.accuracy is not None:
                m["accuracy"] = o.accuracy
            return m

        # device telemetry (obs/device_telemetry.py): in-graph numerics and
        # the skip_step update mask.  With the knob off the step compiles
        # WITHOUT the grad_scale input or any telemetry op — the pre-existing
        # graph, bit-identical (the sync-parity goldens pin this).
        telemetry = cfg.telemetry_interval > 0
        skip_on_nonfinite = telemetry and cfg.anomaly_policy == "skip_step"

        def step_fn(state: TrainState, batch: typing.Dict[str, NT],
                    rng: jax.Array, grad_scale: jax.Array = None):
            batch = {k: constraint(t, mesh) for k, t in batch.items()}
            metrics = {}
            if accum <= 1:
                grads, out = self._grads(state.params, batch, rng)
                loss = out.loss
                metrics.update(aux_metrics(out))
            else:
                # scan over micro-batches, averaging gradients — the JAX form
                # of the reference's graph-stitched macro-batching
                # (src/run/train.py:19-77).
                def micro(i, t):
                    assert t.x.shape[0] % accum == 0, (
                        f"batch axis {t.x.shape[0]} not divisible by "
                        f"micro-batch count {accum}")
                    bsz = t.x.shape[0] // accum
                    return NT(jax.lax.dynamic_slice_in_dim(t.x, i * bsz, bsz, 0),
                              t.names)

                def body(carry, i):
                    mb = {k: micro(i, t) for k, t in batch.items()}
                    g, o = self._grads(state.params,
                                       mb, jax.random.fold_in(rng, i))
                    acc = jax.tree_util.tree_map(jnp.add, carry, g)
                    return acc, dict(loss=o.loss, **aux_metrics(o))

                zeros = jax.tree_util.tree_map(
                    lambda p: jnp.zeros(p.shape, jnp.float32), state.params)
                grads, per_micro = jax.lax.scan(body, zeros, jnp.arange(accum))
                grads = jax.tree_util.tree_map(lambda g: g / accum, grads)
                losses = per_micro.pop("loss")
                # reference reports first/last/mean of the macro batch
                # (src/run/train.py:48-52, run.py:123-132); the smoothing knob
                # picks which figure is THE loss
                metrics["first_loss"] = losses[0]
                metrics["last_loss"] = losses[-1]
                loss = (jnp.mean(losses) if cfg.macro_batch_loss_smoothing
                        else losses[-1])
                metrics.update({k: jnp.mean(v) for k, v in per_micro.items()})
            if cfg.pipeline_parallel > 1:
                # stage-replicated 'shared' tensors: stage-sum + re-broadcast
                # keeps the replicas bit-synced (models.stack_pipeline_params)
                from ..models import sync_shared_pipeline_grads
                grads = sync_shared_pipeline_grads(cfg, grads, self.axes)

            def norm_sq(name, g):
                """Stage-replicated shared tensors hold the SAME summed grad
                in every slice after the sync — count it once, so grad_norm
                matches the sequential model's."""
                s = jnp.sum(jnp.square(g.astype(jnp.float32)))
                from ..config import PIPE_STAGE
                ax = self.axes.get(name, ())
                if ("/shared_" in name and tuple(ax)[:1] == (PIPE_STAGE,)):
                    s = s / g.shape[0]
                return s

            if telemetry:
                # grad_scale rides the fully-formed gradients (post
                # accumulation/sync, pre optimizer): 1.0 in steady state
                # (exact in IEEE — values unchanged), NaN under the
                # "grads:nan@stepN" fault site so the anomaly path is
                # drillable without wrecking params
                grads = jax.tree_util.tree_map(
                    lambda g: g * grad_scale.astype(g.dtype), grads)
                grads_ok, nonfinite = device_telemetry.grads_finite(grads)
                skip = (~grads_ok) if skip_on_nonfinite else None
                # named scope: optimizer ops attribute to their own row in
                # graftprof's per-scope table instead of "(toplevel)"
                with jax.named_scope("optimizer"):
                    new_params, new_opt, lr, upd_sq = opt.update(
                        state.params, grads, state.opt_state, state.step,
                        skip=skip, collect_update_sq=True)
                metrics.update(device_telemetry.collect(
                    state.params, grads, upd_sq, grad_scale, nonfinite,
                    applied=(grads_ok if skip_on_nonfinite else None),
                    norm_sq_fn=norm_sq, groups=cfg.telemetry_groups))
            else:
                with jax.named_scope("optimizer"):
                    new_params, new_opt, lr = opt.update(
                        state.params, grads, state.opt_state, state.step)

            gnorm = jnp.sqrt(sum(norm_sq(k, g) for k, g in grads.items()))
            # no "step" entry: the loop computes step indices on host
            # (main.py async dispatch) — shipping the device counter back
            # every update is a needless D2H scalar the metric writer would
            # overwrite anyway
            metrics.update({
                "loss": loss,
                "learning_rate": lr,
                "grad_norm": gnorm,
            })
            if cfg.debug_gradients:
                # per-variable gradient norms + log2-magnitude histograms
                # (the reference's --debug_grad histogram stream,
                # src/run/run.py:147-153); the metric writer renders the
                # grad_hist/ vectors as TensorBoard histograms
                from .metrics import GRAD_HIST_EDGES
                edges = jnp.asarray(GRAD_HIST_EDGES)
                for name, g in grads.items():
                    gf = g.astype(jnp.float32)
                    metrics[f"grad_norm/{name}"] = jnp.sqrt(norm_sq(name, g))
                    mag = jnp.log2(jnp.abs(gf).reshape(-1) + 1e-38)
                    hist, _ = jnp.histogram(mag, bins=edges)
                    metrics[f"grad_hist/{name}"] = hist
            new_state = TrainState(new_params, new_opt,
                                   state.step + step_increment)
            return new_state, metrics

        return jax.jit(step_fn, donate_argnums=(0,))

    def step_extra_args(self, grad_scale: typing.Optional[float] = None
                        ) -> typing.Tuple:
        """Trailing step-function arguments beyond (state, batch, rng): the
        telemetry gradient scale when device telemetry is enabled, else
        nothing — so every caller (loop / bench / cost analysis / abstract
        trace) stays signature-compatible with both compiles.  A host
        ``np.float32`` (not a Python float): jit must treat it as a TRACED
        input, or the one NaN-injection step would trigger a recompile."""
        if self.cfg.telemetry_interval <= 0:
            if grad_scale is not None:
                raise ValueError("grad_scale requires telemetry_interval > 0")
            return ()
        return (np.float32(1.0 if grad_scale is None else grad_scale),)

    def step(self, state: TrainState, batch: typing.Dict[str, NT],
             rng: jax.Array, grad_scale: typing.Optional[float] = None):
        if self._step_fn is None:
            self._step_fn = self._make_step()
        args = (state, batch, rng) + self.step_extra_args(grad_scale)
        if self._compiled is not None:
            # AOT executable from step_cost_analysis (jit's dispatch cache is
            # separate, so calling the jit fn would compile a second time)
            try:
                return self._compiled(*args)
            except (TypeError, ValueError):
                # shapes/dtypes/shardings changed since the AOT compile —
                # the exact exception type varies by jax version
                self._compiled = None
        with self.mesh:
            return self._step_fn(*args)

    def step_cost_analysis(self, state: TrainState,
                           batch: typing.Dict[str, NT]
                           ) -> typing.Dict[str, float]:
        """XLA cost analysis (flops, bytes accessed) of the compiled train
        step.  The compiled executable is kept and reused by ``step`` so the
        analysis does not cost a second compilation (bench.py, and the live
        MFU accounting in train/flops.py)."""
        if self._step_fn is None:
            self._step_fn = self._make_step()
        with self.mesh:
            self._compiled = self._step_fn.lower(
                state, batch, jax.random.key(0),
                *self.step_extra_args()).compile()
        cost = self._compiled.cost_analysis()
        if isinstance(cost, (list, tuple)):  # older jax returns per-device list
            cost = cost[0] if cost else {}
        return dict(cost or {})

    # -- reporting -----------------------------------------------------------
    def param_census(self, params: typing.Dict[str, jnp.ndarray]
                     ) -> typing.Dict[str, typing.Any]:
        """Parameter-count report (the reference's ``analyze_model``,
        src/run/utils_run.py:65-113) — sorted largest-first with a total."""
        rows = sorted(((k, int(v.size)) for k, v in params.items()),
                      key=lambda kv: -kv[1])
        return {"total": sum(s for _, s in rows), "by_variable": dict(rows)}
