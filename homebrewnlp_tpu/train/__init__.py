"""Run/orchestration layer: mesh-sharded train step, checkpointing, metrics.

The JAX re-design of the reference's run layer (/root/reference/src/run/):
graph build + lowering + session loop collapse into one jitted step function
(state.py); TF Saver checkpoints become orbax (checkpoint.py);
outside-compilation summaries become ordinary step outputs (metrics.py).
"""
from .state import Trainer, TrainState  # noqa: F401
from .checkpoint import Checkpointer, current_step  # noqa: F401
from .metrics import AsyncMetricWriter, MetricWriter, color_print  # noqa: F401
