"""Graph auditors: rule passes over abstractly-traced step jaxprs.

Six rules, each pinning an invariant that historically only failed at TPU
runtime (slow step, OOM, or silently wrong layout):

- ``collective-census``: count data-moving collectives (+ sharding
  constraints, half->f32 upcasts and quantized int8/fp8 ops) per step and
  diff against the config's golden budget file.  An accidental all-gather
  from a PartitionSpec mismatch — or a new upcast in the hot path —
  shows up as a census diff.
- ``dtype-promotion``: no f64/complex128 values anywhere in a step unless the
  config itself declares an f64 dtype policy.
- ``quant-dtype``: int8/fp8 compute only inside the config's declared
  ``quant_blocks`` scope (ops/quant.py) — a quantized op without the knob,
  or a declared scope whose train step has no quantized dot (silent
  high-precision fallback), is an error.
- ``donation``: every TrainState buffer entering the train step must be
  donated (``donate_argnums``) — a dropped donation doubles peak HBM.
- ``sharding-spec``: every mesh axis named by the sharding rule table or by
  an in-graph sharding annotation must exist on the mesh (``spec_for``
  silently replicates unknown axes — exactly the failure this pins); large
  parameters left fully replicated on the config's intended pod mesh are
  flagged.
- ``constant-bloat``: closed-over array constants above a size threshold are
  baked into the program (recompile hazard + wasted HBM per executable).

Golden budgets live in ``homebrewnlp_tpu/analysis/goldens/census/`` — one
JSON per config, regenerated with ``python tools/graftcheck.py
--update-goldens`` (see docs/static_analysis.md).
"""
from __future__ import annotations

import json
import os
import typing

import jax.numpy as jnp
import numpy as np

from ..config import Config
from ..parallel.mesh import MESH_AXES, axis_sizes
from ..parallel.sharding import RULES, spec_for
from .findings import Finding
from .trace import (COLLECTIVE_PRIMS, ConfigTraces, eqn_location, iter_eqns,
                    iter_closed_jaxprs)

GOLDENS_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           "goldens")

# constant-bloat thresholds (bytes): above ERROR the constant is certainly a
# closure bug; WARN..ERROR is worth a look (tables etc.)
CONST_WARN_BYTES = 64 * 1024
CONST_ERROR_BYTES = 1024 * 1024

# sharding-spec: parameters at least this large (elements) should not be
# fully replicated when the config's intended mesh has a >1 model axis
REPLICATED_PARAM_ELEMS = 1 << 23  # 8M elements (32 MB at f32)

_F64 = (jnp.float64, jnp.complex128)

#: quantized-compute dtypes the quant-dtype rule audits (ops/quant.py):
#: int8 plus every fp8 flavor this toolchain knows.  Keys are np.dtype
#: instances — an aval carries np.dtype, which compares equal to the jnp
#: scalar type but does NOT hash equal, so a scalar-type-keyed dict would
#: silently miss every hit.  Maps np.dtype -> census family ("int8"/"fp8").
_QUANT_DTYPES: typing.Dict[typing.Any, str] = {np.dtype(jnp.int8): "int8"}
for _fp8 in ("float8_e4m3fn", "float8_e5m2", "float8_e4m3b11_fnuz",
             "float8_e4m3fnuz", "float8_e5m2fnuz"):
    if hasattr(jnp, _fp8):
        _QUANT_DTYPES[np.dtype(getattr(jnp, _fp8))] = "fp8"


def _quant_family(dt) -> typing.Optional[str]:
    if dt is None:
        return None
    try:
        return _QUANT_DTYPES.get(np.dtype(dt))
    except TypeError:
        return None


def census_of(step_trace) -> typing.Dict[str, typing.Any]:
    """Static per-call-site counts of collectives, upcasts and quantized
    ops for one step.  The ``quant`` sub-dict (``<family>_dot`` quantized
    dot_generals, ``<family>_cast`` quantize conversions) is present only
    when nonzero, so pre-quant goldens stay byte-stable; quant-enabled
    configs pin their counts like any other census key."""
    collectives: typing.Dict[str, int] = {}
    upcasts = 0
    n_eqns = 0
    quant: typing.Dict[str, int] = {}
    for eqn in iter_eqns(step_trace.jaxpr):
        n_eqns += 1
        name = COLLECTIVE_PRIMS.get(eqn.primitive.name)
        if name is not None:
            collectives[name] = collectives.get(name, 0) + 1
        elif eqn.primitive.name == "convert_element_type":
            new = eqn.params.get("new_dtype")
            old = getattr(getattr(eqn.invars[0], "aval", None), "dtype", None)
            if (old is not None and new == jnp.float32
                    and old in (jnp.bfloat16, jnp.float16)):
                upcasts += 1
            fam = _quant_family(new)
            if fam is not None:
                quant[f"{fam}_cast"] = quant.get(f"{fam}_cast", 0) + 1
        elif eqn.primitive.name == "dot_general":
            for v in eqn.invars:
                fam = _quant_family(
                    getattr(getattr(v, "aval", None), "dtype", None))
                if fam is not None:
                    quant[f"{fam}_dot"] = quant.get(f"{fam}_dot", 0) + 1
                    break
    out = {"collectives": dict(sorted(collectives.items())),
           "half_to_f32_upcasts": upcasts,
           "n_eqns": n_eqns}
    if quant:
        out["quant"] = dict(sorted(quant.items()))
    return out


def golden_path(config_name: str) -> str:
    return os.path.join(GOLDENS_DIR, "census", config_name + ".json")


def _loc(traces: ConfigTraces, step: str) -> str:
    return f"configs/{traces.config_name}.json[{step}]"


def check_collective_census(traces: ConfigTraces,
                            update_goldens: bool = False
                            ) -> typing.List[Finding]:
    findings: typing.List[Finding] = []
    actual = {name: census_of(st) for name, st in sorted(traces.steps.items())}
    path = golden_path(traces.config_name)
    if update_goldens:
        os.makedirs(os.path.dirname(path), exist_ok=True)
        import jax
        # merge over any existing golden: steps pinned earlier but not
        # traced this run (e.g. --steps train, or a toolchain that cannot
        # trace one step) keep their budget instead of being erased
        merged = dict(actual)
        if os.path.exists(path):
            with open(path) as f:
                for step, budget in json.load(f).get("steps", {}).items():
                    merged.setdefault(step, budget)
        with open(path, "w") as f:
            json.dump({"config": traces.config_name,
                       "mesh": {k: int(v) for k, v in traces.mesh.shape.items()},
                       "jax": jax.__version__,
                       "steps": merged}, f, indent=2, sort_keys=True)
            f.write("\n")
        findings.append(Finding(
            "collective-census", "info", path,
            f"golden updated ({', '.join(actual) or 'no steps'}"
            + (f"; kept {', '.join(sorted(set(merged) - set(actual)))}"
               if set(merged) - set(actual) else "") + ")"))
        return findings
    if not os.path.exists(path):
        findings.append(Finding(
            "collective-census", "error", _loc(traces, "*"),
            f"no golden budget at {os.path.relpath(path)}; run "
            f"`python tools/graftcheck.py --config configs/"
            f"{traces.config_name}.json --update-goldens`"))
        return findings
    with open(path) as f:
        golden = json.load(f)
    gsteps = golden.get("steps", {})
    for step in sorted(set(actual) | set(gsteps)):
        if step not in actual:
            findings.append(Finding(
                "collective-census", "warning", _loc(traces, step),
                "step present in golden but not traced this run "
                f"(trace errors: {traces.errors.get(step, 'step skipped')})"))
            continue
        if step not in gsteps:
            # a step outside the golden's recorded set (e.g. --steps eval
            # when the budget pins train+decode) is unpinned, not wrong
            findings.append(Finding(
                "collective-census", "warning", _loc(traces, step),
                "step traced but not pinned by the golden budget; record it "
                "with --update-goldens to gate it"))
            continue
        got, want = actual[step], gsteps[step]
        for key in sorted(set(got["collectives"]) | set(want["collectives"])):
            g = got["collectives"].get(key, 0)
            w = want["collectives"].get(key, 0)
            if g != w:
                findings.append(Finding(
                    "collective-census", "error", _loc(traces, step),
                    f"{key} count {g} != golden {w} — an unplanned "
                    f"collective usually means a sharding-spec mismatch; "
                    f"if intended, re-record with --update-goldens"))
        if got["half_to_f32_upcasts"] != want.get("half_to_f32_upcasts", 0):
            findings.append(Finding(
                "collective-census", "error", _loc(traces, step),
                f"half->f32 upcast count {got['half_to_f32_upcasts']} != "
                f"golden {want.get('half_to_f32_upcasts', 0)} — check the "
                f"hot path for unintended promotions; if intended, "
                f"re-record with --update-goldens"))
        gq, wq = got.get("quant", {}), want.get("quant", {})
        for key in sorted(set(gq) | set(wq)):
            if gq.get(key, 0) != wq.get(key, 0):
                findings.append(Finding(
                    "collective-census", "error", _loc(traces, step),
                    f"quantized-op count {key} {gq.get(key, 0)} != golden "
                    f"{wq.get(key, 0)} — the quant scope changed shape "
                    f"(ops/quant.py); if intended, re-record with "
                    f"--update-goldens"))
    return findings


def check_dtype_promotion(traces: ConfigTraces) -> typing.List[Finding]:
    cfg = traces.cfg
    declared_f64 = any(
        getattr(cfg, a) == jnp.float64
        for a in ("storage_dtype", "slice_dtype", "calculation_dtype",
                  "optimizer_slice_dtype", "optimizer_calculation_dtype"))
    if declared_f64:
        return []
    findings: typing.List[Finding] = []
    for step, st in sorted(traces.steps.items()):
        hits: typing.List[str] = []
        for eqn in iter_eqns(st.jaxpr):
            for v in eqn.outvars:
                dt = getattr(getattr(v, "aval", None), "dtype", None)
                if dt in _F64:
                    hits.append(f"{eqn.primitive.name} -> {dt} at "
                                f"{eqn_location(eqn)}")
                    break
            if len(hits) >= 5:
                break
        for h in hits:
            findings.append(Finding(
                "dtype-promotion", "error", _loc(traces, step),
                f"f64 value in the graph ({h}); no config dtype declares "
                f"float64 — check for Python floats promoted via x64 or an "
                f"explicit astype"))
    return findings


def check_donation(traces: ConfigTraces) -> typing.List[Finding]:
    findings: typing.List[Finding] = []
    st = traces.steps.get("train")
    if st is not None and st.state_info is not None:
        import jax
        leaves = jax.tree_util.tree_leaves_with_path(st.state_info)
        missing = [jax.tree_util.keystr(path) for path, info in leaves
                   if not getattr(info, "donated", False)]
        shown = missing[:10]
        for name in shown:
            findings.append(Finding(
                "donation", "error", _loc(traces, "train"),
                f"train-state buffer {name} is not donated — the step keeps "
                f"a second copy live (check donate_argnums on the jitted "
                f"step, train/state.py)"))
        if len(missing) > len(shown):
            findings.append(Finding(
                "donation", "error", _loc(traces, "train"),
                f"... and {len(missing) - len(shown)} more non-donated "
                f"train-state buffers"))
    findings.extend(_check_serve_donation(traces))
    return findings


def _check_serve_donation(traces: ConfigTraces) -> typing.List[Finding]:
    """Serving twin of the train-state donation audit: the batch engine's
    decode/prefill executables carry the pooled KV caches, token pool,
    per-lane positions and rng as step state — abstractly trace the EXACT
    jitted functions the engine compiles (serve/engine.py::jit_executables)
    and require their pooled arguments donated.  Without donation the
    decode loop copies the whole KV pool every step on device (the
    ROADMAP continuous-batching residual this rule ratchets)."""
    from .trace import decode_traceable, trace_compat
    cfg = traces.cfg
    if not decode_traceable(cfg) or not traces.param_shapes:
        return []
    from ..serve import engine
    if not engine.use_batch_engine(cfg):
        # the serialized path allocates per-call caches — there is no pool
        # to donate; auditing the engine trace here would cost a full
        # decode-graph trace per config for a code path the config never
        # runs (the contract itself is pinned by the graftcheck tests on
        # an engine-enabled config)
        return []
    import jax
    findings: typing.List[Finding] = []
    params = traces.param_shapes
    if cfg.pipeline_parallel > 1:
        from ..models import pipeline_params_stacked, unstack_pipeline_params
        if pipeline_params_stacked(cfg, params):
            params = jax.eval_shape(
                lambda p: unstack_pipeline_params(cfg, p), params)
    if getattr(cfg, "serve_aot_cache_dir", ""):
        # the engine deliberately compiles WITHOUT donation when it
        # persists AOT executables (serialize_executable cannot round-trip
        # input-output aliasing on this toolchain — serve/engine.py) —
        # the audit below checks the donating contract the non-AOT path
        # uses, so surface the tradeoff instead of green-lighting it
        findings.append(Finding(
            "donation", "warning", _loc(traces, "serve"),
            "serve_aot_cache_dir is set: the batch engine compiles its "
            "executables WITHOUT pool donation (AOT serialization cannot "
            "round-trip input-output aliasing) — on device every decode "
            "step copies the whole KV pool; unset the cache dir on "
            "memory-bound deployments or re-verify donation once the "
            "toolchain serializes aliased executables"))
    rows = max(1, cfg.sequence_length // cfg.token_patch_size)
    # the pool geometry the engine actually runs (use_batch_engine gated
    # above, so serve_max_batch > 1 here)
    n_lanes = int(cfg.serve_max_batch)
    try:
        dec_jit, pre_jit, chk_jit = engine.jit_executables(cfg, rows,
                                                           n_lanes)
        dec_abs, pre_abs, chk_abs = engine.abstract_exec_args(cfg, params,
                                                              rows, n_lanes)
        with trace_compat():
            audits = (("decode", dec_jit.trace(*dec_abs),
                       engine.DECODE_DONATE_ARGNUMS,
                       engine.DECODE_DONATE_ARG_NAMES),
                      ("prefill", pre_jit.trace(*pre_abs),
                       engine.PREFILL_DONATE_ARGNUMS,
                       engine.PREFILL_DONATE_ARG_NAMES))
            if chk_jit is not None and chk_abs is not None:
                # serve_prefill_chunk_tokens > 0: the chunk executable
                # carries the same pooled state — audit it too (knob off
                # keeps the audit, and the census goldens, byte-stable)
                audits += (("prefill_chunk", chk_jit.trace(*chk_abs),
                            engine.PREFILL_CHUNK_DONATE_ARGNUMS,
                            engine.PREFILL_CHUNK_DONATE_ARG_NAMES),)
    except Exception as e:
        return findings + [Finding(
            "donation", "warning", _loc(traces, "serve"),
            f"serving executables failed to trace for the donation audit: "
            f"{type(e).__name__}: {e}")]
    for step, traced, want, arg_names in audits:
        infos = traced.args_info[0]
        for idx in want:
            if idx >= len(infos):
                continue
            leaves = jax.tree_util.tree_leaves_with_path(infos[idx])
            missing = [jax.tree_util.keystr(p) for p, info in leaves
                       if not getattr(info, "donated", False)]
            if missing:
                findings.append(Finding(
                    "donation", "error", _loc(traces, f"serve_{step}"),
                    f"batch-engine {step} does not donate its "
                    f"{arg_names.get(idx, f'arg {idx}')} "
                    f"({len(missing)} buffer(s), e.g. {missing[0]}) — the "
                    f"device copies the whole pool every step; check "
                    f"donate_argnums in serve/engine.py::jit_executables"))
    return findings


class _IntendedMesh:
    """Duck-typed stand-in for spec_for's mesh argument carrying the axis
    sizes of the config's INTENDED pod (tpu_size), not the local CPU mesh."""

    def __init__(self, shape: typing.Dict[str, int]):
        self.shape = shape
        self.axis_names = tuple(shape)


def intended_mesh(cfg: Config) -> _IntendedMesh:
    try:
        sizes = axis_sizes(cfg, max(cfg.tpu_size, 1))
    except ValueError:
        sizes = {a: 1 for a in MESH_AXES}
    return _IntendedMesh(dict(sizes))


def check_sharding_specs(traces: ConfigTraces) -> typing.List[Finding]:
    findings: typing.List[Finding] = []
    known = set(MESH_AXES)
    # 1. the rule table itself: an unknown mesh axis is SILENTLY treated as
    # replicated by spec_for — the classic mis-shard
    for logical, mesh_axis in sorted(RULES.items()):
        if mesh_axis not in known:
            findings.append(Finding(
                "sharding-spec", "error", "homebrewnlp_tpu/parallel/sharding.py",
                f"RULES maps logical axis {logical!r} to unknown mesh axis "
                f"{mesh_axis!r} (known: {sorted(known)}) — spec_for silently "
                f"replicates it"))
    # 2. in-graph sharding annotations must only name real mesh axes
    for step, st in sorted(traces.steps.items()):
        seen_bad: typing.Set[str] = set()
        for eqn in iter_eqns(st.jaxpr):
            sharding = eqn.params.get("sharding")
            spec = getattr(sharding, "spec", None)
            if spec is None:
                continue
            for part in spec:
                axes = part if isinstance(part, tuple) else (part,)
                for ax in axes:
                    if ax is not None and ax not in known and ax not in seen_bad:
                        seen_bad.add(ax)
                        findings.append(Finding(
                            "sharding-spec", "error", _loc(traces, step),
                            f"sharding annotation names unknown mesh axis "
                            f"{ax!r} at {eqn_location(eqn)}"))
    # 3. large params fully replicated on the intended pod mesh
    imesh = intended_mesh(traces.cfg)
    if any(v > 1 for v in imesh.shape.values()):
        for name, sds in sorted(traces.param_shapes.items()):
            elems = int(np.prod(sds.shape)) if sds.shape else 1
            if elems < REPLICATED_PARAM_ELEMS:
                continue
            spec = spec_for(traces.param_axes.get(name, ()), imesh)
            if not any(p is not None for p in spec):
                findings.append(Finding(
                    "sharding-spec", "warning", _loc(traces, "params"),
                    f"parameter {name} ({elems} elements, axes "
                    f"{traces.param_axes.get(name, ())}) is fully replicated "
                    f"on the intended {dict(imesh.shape)} mesh — consider a "
                    f"sharding rule for one of its axes"))
    return findings


def check_constant_bloat(traces: ConfigTraces) -> typing.List[Finding]:
    findings: typing.List[Finding] = []
    for step, st in sorted(traces.steps.items()):
        for cj in iter_closed_jaxprs(st.jaxpr):
            for c in getattr(cj, "consts", ()):
                size = getattr(c, "size", 0)
                itemsize = getattr(getattr(c, "dtype", None), "itemsize", 1)
                nbytes = int(size) * int(itemsize)
                if nbytes < CONST_WARN_BYTES:
                    continue
                sev = "error" if nbytes >= CONST_ERROR_BYTES else "warning"
                findings.append(Finding(
                    "constant-bloat", sev, _loc(traces, step),
                    f"closed-over constant {getattr(c, 'shape', ())} "
                    f"{getattr(c, 'dtype', '?')} ({nbytes} bytes) is baked "
                    f"into the program — pass it as an argument (recompile "
                    f"hazard + per-executable HBM copy)"))
    return findings


def check_quant_dtype(traces: ConfigTraces) -> typing.List[Finding]:
    """Quantized-compute allowlist (ops/quant.py, docs/static_analysis.md):
    the config's ``quant_blocks`` knob is the ONLY sanctioned source of
    int8/fp8 compute.

    - A quantized op (int8/fp8 ``dot_general`` or quantize cast) in a step
      of a config that declares NO quant scope is an error — low-precision
      math must never leak in implicitly (an accidental integer-promotion
      dot has silently destroyed model quality before it showed in loss).
    - A declared quant scope whose traced TRAIN step contains no quantized
      ``dot_general`` is an error — the scope silently fell back to the
      high-precision path (pattern typo, fused-kernel bypass, or a dtype
      gate eating the knob), i.e. the run would report quantized speedups
      it is not taking.
    """
    cfg = traces.cfg
    declared = bool(getattr(cfg, "quant_blocks", ()))
    findings: typing.List[Finding] = []
    for step, st in sorted(traces.steps.items()):
        quant = census_of(st).get("quant", {})
        dots = sum(v for k, v in quant.items() if k.endswith("_dot"))
        if not declared and quant:
            findings.append(Finding(
                "quant-dtype", "error", _loc(traces, step),
                f"quantized ops in the graph ({quant}) but the config "
                f"declares no quant scope (quant_blocks is empty) — int8/"
                f"fp8 compute is only sanctioned through ops/quant.py "
                f"behind the quant_blocks knob"))
        if declared and step == "train" and dots == 0:
            findings.append(Finding(
                "quant-dtype", "error", _loc(traces, step),
                f"quant_blocks={list(cfg.quant_blocks)} is declared but the "
                f"traced train step contains no quantized dot_general — the "
                f"scope silently fell back to the high-precision path "
                f"(check the substrings against the layer scopes, and that "
                f"no fused kernel bypasses linear())"))
    return findings


#: jax API names whose absence marks a known toolchain gap (older jax than
#: the parallel modules target), as opposed to a real defect in model code
_TOOLCHAIN_GAP_APIS = ("shard_map", "get_abstract_mesh", "pcast", "typeof",
                       "pvary", "CompilerParams")


def check_trace_errors(traces: ConfigTraces) -> typing.List[Finding]:
    """Trace failures are findings too: severity depends on whether the
    failure is a known toolchain gap (a specific missing jax API -> warning,
    the config is simply not analyzable on this toolchain) or a real defect
    (error)."""
    findings: typing.List[Finding] = []
    for step, err in sorted(traces.errors.items()):
        toolchain = ("has no attribute" in err and any(
            f"'{api}'" in err for api in _TOOLCHAIN_GAP_APIS))
        findings.append(Finding(
            "trace", "warning" if toolchain else "error",
            _loc(traces, step), f"step failed to trace: {err}"))
    return findings


def _config_tpu_size(name: str) -> typing.Optional[int]:
    """tpu_size from the raw config JSON (no Config construction, no jax) —
    None when the file is absent/unreadable.  The fallback default MUST
    match config.py's ``_DEFAULTS`` tpu_size."""
    path = os.path.join(os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))), "configs", name + ".json")
    try:
        with open(path) as f:
            return int(json.load(f).get("tpu_size", 32))
    except (OSError, ValueError, TypeError):
        return None


def check_golden_coverage(config_names: typing.Sequence[str]
                          ) -> typing.List[Finding]:
    """Tree-wide gate (run under --all-configs): every bundled config must
    have a census golden, a resources golden AND an spmd
    (implicit-collective) golden — and, when it declares a multi-device
    topology (tpu_size > 1), a mesh golden too — and no golden may outlive
    its config.  Previously a brand-new config silently skipped the census
    until someone traced it by hand — coverage is now an invariant, not a
    convention."""
    from .cost_model import resources_golden_path
    from .mesh_search import mesh_golden_path
    from .spmd import spmd_golden_path
    findings: typing.List[Finding] = []
    names = set(config_names)
    for kind, path_fn in (("census", golden_path),
                          ("resources", resources_golden_path),
                          ("spmd", spmd_golden_path),
                          ("mesh", mesh_golden_path)):
        have = set()
        d = os.path.dirname(path_fn("_"))
        if os.path.isdir(d):
            have = {os.path.splitext(f)[0] for f in os.listdir(d)
                    if f.endswith(".json")}
        missing = names - have
        if kind == "mesh":
            # only multi-device configs factor a mesh; a config whose raw
            # JSON cannot be read (e.g. a hypothetical name probed by
            # tests) is not held to the multi-device requirement
            missing = {n for n in missing
                       if (_config_tpu_size(n) or 1) > 1}
        for name in sorted(missing):
            findings.append(Finding(
                "golden-coverage", "error", f"configs/{name}.json",
                f"config has no {kind} golden — it would silently skip the "
                f"{kind} gate; run `python tools/graftcheck.py --config "
                f"configs/{name}.json --update-goldens`"))
        for name in sorted(have - names):
            findings.append(Finding(
                "golden-coverage", "warning", os.path.relpath(path_fn(name)),
                f"orphan {kind} golden: no configs/{name}.json — delete it "
                f"or restore the config"))
    # tree-wide (not per-config) goldens from the concurrency audit: the
    # sync rules error out themselves when theirs are missing, but only if
    # they run — this gate makes a deleted golden fail even rule-filtered
    # runs that skip them
    from .concurrency import (sync_lock_order_golden_path,
                              sync_shared_state_golden_path)
    for kind, path in (("sync shared-state", sync_shared_state_golden_path()),
                       ("sync lock-order", sync_lock_order_golden_path())):
        if not os.path.exists(path):
            findings.append(Finding(
                "golden-coverage", "error", os.path.relpath(path),
                f"missing {kind} golden — the concurrency audit would "
                f"refuse to ratchet; run `python tools/graftsync.py "
                f"--update-goldens`"))
    return findings


def run_graph_rules(traces: ConfigTraces, update_goldens: bool = False,
                    rules: typing.Optional[typing.Sequence[str]] = None
                    ) -> typing.List[Finding]:
    from .cost_model import check_resource_budget
    from .mesh_search import check_mesh_rank
    from .spmd import check_implicit_collectives
    table = {
        "collective-census": lambda t: check_collective_census(t, update_goldens),
        "dtype-promotion": check_dtype_promotion,
        "quant-dtype": check_quant_dtype,
        "donation": check_donation,
        "sharding-spec": check_sharding_specs,
        "constant-bloat": check_constant_bloat,
        "resource-budget": lambda t: check_resource_budget(t, update_goldens),
        "implicit-collective":
            lambda t: check_implicit_collectives(t, update_goldens),
        "mesh-rank": lambda t: check_mesh_rank(t, update_goldens),
    }
    findings = check_trace_errors(traces)
    for name, fn in table.items():
        if rules is None or name in rules:
            findings.extend(fn(traces))
    return findings
