"""graftmesh: topology-aware mesh auto-search as a static-analysis pass.

The reference framework hand-writes its mesh layouts (``SimdMeshImpl``
device assignment — two integers, ``tpu_size`` and ``heads``, guessed and
checked against a real pod); graftcost (PR 7) already prices any candidate
sharding statically — per-device HBM, per-axis alpha-beta collective bytes,
``static_step_times`` — in seconds on a CPU.  This module turns that
objective into a *search*: enumerate the DP/SP/PP/TP factorizations of a
slice topology (``parallel/mesh.py::mesh_factorizations``), score every
candidate with the one time model the roofline verdict and graftprof
already share, gate each against the ``target_device``'s HBM capacity
(OOM-before-compile), and rank.

**Objective.**  Predicted train-step seconds
``max(mxu, hbm) + ici``: compute and HBM traffic overlap within the chip
(the roofline assumption), collectives serialize against both (matching
the current non-overlapped sharded einsums — when collective/compute
overlap lands, this is the constant to revisit).  Candidates whose
predicted peak HBM exceeds the scoring device's capacity rank strictly
after every fitting candidate.  Times within :data:`RANK_RTOL` of each
other are TIED — the model's calibration error (the ``tolerance.xla``
story in docs/static_analysis.md) cannot defend finer distinctions.

**Enumeration semantics.**  By default the sequence and pipeline axes stay
pinned to the config's declared values — they are *structural* choices
that change the traced program (ring-attention chunking, pipeline stage
scans), exactly the degrees of freedom ``axis_sizes`` itself holds fixed —
so every candidate prices the SAME traced jaxpr under a different intended
mesh and the whole search costs one abstract trace.  ``free_axes``
unlocks them: each distinct (seq, pipe) structure is re-traced with an
overridden config (seconds per structure; requires the raw config dict).

**Implicit collectives.**  The traced jaxpr only contains *manual*
collectives (ring ppermutes, pipeline hops, sharding constraints); the
collectives GSPMD inserts — the data-axis gradient all-reduce, the
model-axis activation reductions of tensor-parallel contractions — are
implicit and would make pure DP (and under-charge TP) look free.  The
sharding propagation pass (``analysis/spmd.py``) predicts them per
candidate mesh, and ``StepResources.total_comm`` folds them into the same
alpha-beta pricing as the walked collectives, for every candidate
including the hand-written mesh.  One propagation walk serves every
candidate sharing a >1-axis mask, so the search still costs one abstract
trace.

Consumers: ``tools/graftmesh.py`` (ranked sheet + ``--check``), the
ratcheted ``mesh-rank`` graph rule (per-config goldens under
``analysis/goldens/mesh/``), and ``reliability/dist.py::suggest_mesh``
(degraded-resume world-size renegotiation).
"""
from __future__ import annotations

import dataclasses
import json
import os
import typing

from ..devices import resolve_device
from ..parallel.mesh import MESH_AXES, axis_sizes, mesh_factorizations
from .cost_model import (DEFAULT_VERDICT_DEVICE, format_bytes,
                         static_step_times, step_resources)
from .findings import Finding
from .trace import ConfigTraces, trace_config

GOLDENS_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           "goldens")

#: relative tolerance under which two candidates' predicted step times tie —
#: the cost model is calibrated to within 2x of XLA's own estimates
#: (``tolerance.xla`` in the resources goldens), so sub-10% distinctions
#: between layouts are noise it cannot defend; a real TPU round
#: (MULTICHIP ``mesh_search`` row) is what resolves finer orderings
RANK_RTOL = 0.10

#: the ranking objective, recorded in every golden so a future change to the
#: arithmetic is a visible golden diff, not a silent re-ranking
OBJECTIVE = "max(mxu,hbm)+ici"


@dataclasses.dataclass
class MeshCandidate:
    """One scored factorization (``predicted`` empty when the candidate's
    structure failed to trace — see ``error``)."""
    axes: typing.Dict[str, int]
    predicted: typing.Dict[str, float] = dataclasses.field(
        default_factory=dict)  # mxu_s / hbm_s / ici_s / step_s
    hbm_peak: int = 0
    fits: typing.Optional[bool] = None
    retraced: bool = False
    is_hand: bool = False
    rank: int = 0
    error: str = ""
    #: nonempty when the SPMD propagation could not price this candidate's
    #: implicit collectives (unseeded trace / propagation failure): the
    #: ranking then under-charges communication — exactly the pure-DP-
    #: looks-free bug the propagation exists to prevent — so consumers
    #: (check_mesh_rank, the graftmesh sheet) must surface it loudly
    spmd_error: str = ""

    @property
    def step_s(self) -> float:
        return self.predicted.get("step_s", float("inf"))

    def key(self) -> typing.Tuple[typing.Tuple[str, int], ...]:
        return tuple((a, int(self.axes.get(a, 1))) for a in MESH_AXES)

    def describe(self) -> str:
        return " ".join(f"{a}{v}" for a, v in self.key() if v > 1) or "1chip"

    def as_golden(self) -> dict:
        return {"axes": {a: int(v) for a, v in self.key()},
                "step_time_s": float(f"{self.step_s:.6g}"),
                "ici_s": float(f"{self.predicted.get('ici_s', 0.0):.6g}"),
                "implicit_ici_s": float(
                    f"{self.predicted.get('implicit_ici_s', 0.0):.6g}"),
                "hbm_peak_bytes": int(self.hbm_peak),
                "fits": self.fits,
                "rank": int(self.rank)}


@dataclasses.dataclass
class MeshSearchResult:
    config_name: str
    n_devices: int
    device_kind: str
    free_axes: typing.Tuple[str, ...]
    candidates: typing.List[MeshCandidate]  # ranked, best first
    skipped: typing.List[MeshCandidate]  # structures that failed to trace
    hand_axes: typing.Dict[str, int]
    hand_rank: int

    @property
    def top(self) -> MeshCandidate:
        return self.candidates[0]

    @property
    def hand(self) -> MeshCandidate:
        return next(c for c in self.candidates if c.is_hand)

    def as_json(self) -> dict:
        return {"config": self.config_name,
                "n_devices": self.n_devices,
                "device": self.device_kind,
                "objective": OBJECTIVE,
                "rank_rtol": RANK_RTOL,
                "free_axes": list(self.free_axes),
                "hand_mesh": {a: int(v) for a, v in
                              sorted(self.hand_axes.items())},
                "hand_rank": self.hand_rank,
                "candidates": [c.as_golden() for c in self.candidates],
                "skipped": [{"axes": c.axes, "error": c.error}
                            for c in self.skipped]}


def _price(traces: ConfigTraces, step: str, axes: typing.Dict[str, int],
           device_kind: str, spec) -> MeshCandidate:
    from .graph_rules import _IntendedMesh
    st = traces.steps[step]
    res = step_resources(traces, step, st, _IntendedMesh(dict(axes)),
                         device_kind)
    # manual + GSPMD-implicit collectives, both from the same walk the
    # roofline verdict uses (StepResources.total_comm); the implicit split
    # is priced separately too so the golden shows what propagation added
    times = static_step_times(res.flops_per_device, res.hbm_traffic_bytes,
                              res.total_comm(), dict(axes), device_kind)
    assert times is not None  # device_kind is resolved before pricing
    implicit = res.implicit_comm.times(dict(axes), spec) if spec else {}
    predicted = {"mxu_s": float(times["mxu"]), "hbm_s": float(times["hbm"]),
                 "ici_s": float(times["ici"]),
                 "implicit_ici_s": float(sum(implicit.values())),
                 "step_s": float(max(times["mxu"], times["hbm"])
                                 + times["ici"])}
    peak = int(res.hbm["peak"])
    fits = bool(peak <= spec.hbm_bytes) if spec is not None else None
    return MeshCandidate(axes=dict(axes), predicted=predicted, hbm_peak=peak,
                         fits=fits, spmd_error=res.spmd_error)


def _assign_ranks(cands: typing.List[MeshCandidate]
                  ) -> typing.List[MeshCandidate]:
    """Sort best-first and assign tie-tolerant ranks: a candidate's rank is
    1 + the number of fitting candidates strictly more than RANK_RTOL
    faster.  Non-fitting candidates rank after every fitting one, ordered
    by predicted peak (least-overcommitted first)."""
    fitting = sorted((c for c in cands if c.fits is not False),
                     key=lambda c: (c.step_s, c.key()))
    oom = sorted((c for c in cands if c.fits is False),
                 key=lambda c: (c.hbm_peak, c.key()))
    for c in fitting:
        c.rank = 1 + sum(1 for o in fitting
                         if o.step_s < c.step_s * (1.0 - RANK_RTOL))
    for i, c in enumerate(oom):
        c.rank = len(fitting) + 1 + i
    return fitting + oom


def search(cfg, config_name: str = "config", *,
           n_devices: typing.Optional[int] = None, device_kind: str = "",
           traces: typing.Optional[ConfigTraces] = None,
           raw: typing.Optional[dict] = None,
           free_axes: typing.Sequence[str] = (),
           step: str = "train") -> MeshSearchResult:
    """Enumerate + score + rank the mesh factorizations of ``n_devices``
    (default: the config's ``tpu_size``) for one config.

    ``traces`` reuses an existing abstract trace for the declared-structure
    candidates (the mesh-rank rule path: zero extra traces); ``raw`` (the
    config's raw JSON dict) is required only when ``free_axes`` asks for
    structural candidates, which re-trace per distinct (seq, pipe).
    Deterministic by construction: no RNG, stable sort keys."""
    n = int(n_devices) if n_devices else max(int(cfg.tpu_size), 1)
    kind = device_kind or str(getattr(cfg, "target_device", "") or "") \
        or DEFAULT_VERDICT_DEVICE
    spec = resolve_device(kind)
    if spec is None:
        raise ValueError(f"cannot score meshes on unknown device kind "
                         f"{kind!r}; pass --device one of the kinds in "
                         f"homebrewnlp_tpu/devices.py")
    hand = axis_sizes(cfg, n, quiet=True)
    factors = mesh_factorizations(cfg, n, free_axes)
    if not any(f == hand for f in factors):
        factors.append(dict(hand))  # always price the committed layout

    declared = (cfg.sequence_parallel, cfg.pipeline_parallel)
    groups: typing.Dict[typing.Tuple[int, int],
                        typing.List[typing.Dict[str, int]]] = {}
    for f in factors:
        groups.setdefault(
            (f["sequence_parallel"], f["pipeline"]), []).append(f)

    scored: typing.List[MeshCandidate] = []
    skipped: typing.List[MeshCandidate] = []
    for (seq, pipe), members in sorted(groups.items()):
        if (seq, pipe) == declared:
            gtraces = traces
            if gtraces is None or step not in gtraces.steps:
                gtraces = trace_config(cfg, config_name, steps=(step,),
                                       quiet=True)
            retraced = False
        else:
            if raw is None:
                skipped.extend(MeshCandidate(
                    axes=m, error="structural candidate needs the raw "
                    "config dict (pass raw= / run via tools/graftmesh.py)")
                    for m in members)
                continue
            from ..config import Config
            cand_raw = dict(raw)
            cand_raw.pop("_comment", None)
            cand_raw["sequence_parallel"] = seq
            cand_raw["pipeline_parallel"] = pipe
            try:
                gtraces = trace_config(Config(cand_raw),
                                       f"{config_name}@s{seq}p{pipe}",
                                       steps=(step,), quiet=True)
            except Exception as e:
                gtraces = None
                err = f"{type(e).__name__}: {e}"
            if gtraces is None or step not in gtraces.steps:
                err = (gtraces.errors.get(step, "step not traced")
                       if gtraces is not None else err)
                skipped.extend(MeshCandidate(axes=m, error=err)
                               for m in members)
                continue
            retraced = True
        if step not in gtraces.steps:
            skipped.extend(MeshCandidate(
                axes=m, error=gtraces.errors.get(step, "step not traced"))
                for m in members)
            continue
        for m in members:
            c = _price(gtraces, step, m, kind, spec)
            c.retraced = retraced
            c.is_hand = (m == hand)
            scored.append(c)

    ranked = _assign_ranks(scored)
    hand_rank = next((c.rank for c in ranked if c.is_hand), 0)
    return MeshSearchResult(
        config_name=config_name, n_devices=n, device_kind=kind,
        free_axes=tuple(free_axes), candidates=ranked, skipped=skipped,
        hand_axes=dict(hand), hand_rank=hand_rank)


# -- degraded-resume suggestion (reliability/dist.py::suggest_mesh) ----------

@dataclasses.dataclass
class MeshSuggestion:
    """The searcher's answer for a renegotiated world size: the best
    candidate, the axis_sizes fallback the runtime would otherwise build,
    and the predicted step-time delta between them (negative = the
    suggestion is faster)."""
    world_size: int
    device_kind: str
    best: MeshCandidate
    fallback: MeshCandidate
    result: MeshSearchResult

    @property
    def delta_frac(self) -> float:
        """(best - fallback) / fallback predicted step time."""
        fb = self.fallback.step_s
        return (self.best.step_s - fb) / fb if fb > 0 else 0.0

    def describe(self) -> str:
        return (f"mesh search for world_size={self.world_size} on "
                f"{self.device_kind}: suggest {{{self.best.describe()}}} "
                f"(predicted {self.best.step_s * 1e3:.3f} ms/step, peak "
                f"{format_bytes(self.best.hbm_peak).strip()}/dev) vs "
                f"fallback {{{self.fallback.describe()}}} "
                f"({self.fallback.step_s * 1e3:.3f} ms/step, "
                f"{self.delta_frac:+.1%})")


def suggest(cfg, world_size: int, *, config_name: str = "config",
            device_kind: str = "",
            traces: typing.Optional[ConfigTraces] = None) -> MeshSuggestion:
    """Searched mesh for a degraded/renegotiated ``world_size`` using the
    config's declared structure (one abstract trace; no RNG).  Raises
    ValueError when the declared seq x pipe structure cannot factor the
    world — that case stays operator-assisted (docs/reliability.md)."""
    fallback_axes = axis_sizes(cfg, world_size, quiet=True)
    result = search(cfg, config_name, n_devices=world_size,
                    device_kind=device_kind, traces=traces)
    fallback = next((c for c in result.candidates
                     if c.axes == fallback_axes), None)
    if fallback is None:  # unreachable: search always prices the hand mesh
        fallback = result.hand
    return MeshSuggestion(world_size=int(world_size),
                          device_kind=result.device_kind,
                          best=result.top, fallback=fallback, result=result)


# -- the ratcheted mesh-rank graph rule --------------------------------------

def mesh_golden_path(config_name: str) -> str:
    return os.path.join(GOLDENS_DIR, "mesh", config_name + ".json")


def _loc(traces: ConfigTraces) -> str:
    return f"configs/{traces.config_name}.json[train]"


def check_mesh_rank(traces: ConfigTraces,
                    update_goldens: bool = False) -> typing.List[Finding]:
    """The graph rule: each committed multi-device config's hand-written
    mesh must rank within the top ``mesh_search_top_k`` of the searcher's
    prediction for its declared topology, pinned by a per-config golden
    (``analysis/goldens/mesh/<config>.json``).  Ratchet semantics: the
    hand mesh's rank may not worsen past the recorded one; an improved
    rank asks for a re-record; a moved top pick is a warning."""
    cfg = traces.cfg
    if int(getattr(cfg, "tpu_size", 1)) <= 1:
        return []  # single-device configs have nothing to factor
    if "train" not in traces.steps:
        return []  # the trace failure is already a `trace` finding
    findings: typing.List[Finding] = []
    try:
        result = search(cfg, traces.config_name, traces=traces)
    except Exception as e:  # a searcher crash must name itself, not pass
        return [Finding("mesh-rank", "error", _loc(traces),
                        f"mesh search failed: {type(e).__name__}: {e}")]
    top_k = int(getattr(cfg, "mesh_search_top_k", 3))
    hand = result.hand
    unpriced = next((c for c in result.candidates if c.spmd_error), None)
    if unpriced is not None:
        # rankings computed without implicit collectives under-charge DP
        # (the exact blind spot the propagation closed) — never compare
        # them silently against the golden's fully-priced ranks
        findings.append(Finding(
            "mesh-rank", "warning", _loc(traces),
            f"implicit collectives could not be priced for candidate "
            f"{{{unpriced.describe()}}} ({unpriced.spmd_error}) — the "
            f"ranking under-charges communication-heavy layouts; fix the "
            f"sharding seeds (analysis/spmd.py) before trusting this "
            f"sheet"))
    if result.hand_rank > top_k:
        findings.append(Finding(
            "mesh-rank", "error", _loc(traces),
            f"hand-written mesh {{{hand.describe()}}} ranks "
            f"#{result.hand_rank} of {len(result.candidates)} (predicted "
            f"{hand.step_s * 1e3:.3f} ms/step vs the searcher's pick "
            f"{{{result.top.describe()}}} at "
            f"{result.top.step_s * 1e3:.3f} ms) — outside "
            f"mesh_search_top_k={top_k} on {result.device_kind}; adopt the "
            f"searched layout (or raise mesh_search_top_k in the config — "
            f"re-recording the golden cannot clear this bar)"))
    path = mesh_golden_path(traces.config_name)
    if update_goldens:
        os.makedirs(os.path.dirname(path), exist_ok=True)
        import jax
        with open(path, "w") as f:
            json.dump(dict(result.as_json(), jax=jax.__version__,
                           top_k=top_k), f, indent=2, sort_keys=True)
            f.write("\n")
        findings.append(Finding(
            "mesh-rank", "info", path,
            f"mesh golden updated (hand rank #{result.hand_rank} of "
            f"{len(result.candidates)} on {result.device_kind})"))
        return findings
    if not os.path.exists(path):
        findings.append(Finding(
            "mesh-rank", "error", _loc(traces),
            f"no mesh golden at {os.path.relpath(path)}; run `python "
            f"tools/graftcheck.py --config configs/{traces.config_name}"
            f".json --update-goldens`"))
        return findings
    with open(path) as f:
        golden = json.load(f)
    want_rank = int(golden.get("hand_rank", 1))
    if result.hand_rank > want_rank:
        findings.append(Finding(
            "mesh-rank", "error", _loc(traces),
            f"hand-written mesh's searcher rank regressed "
            f"#{want_rank} -> #{result.hand_rank} (of "
            f"{len(result.candidates)} candidates on {result.device_kind}) "
            f"— the cost model now prefers {{{result.top.describe()}}}; "
            f"adopt it or re-record with --update-goldens"))
    elif result.hand_rank < want_rank:
        findings.append(Finding(
            "mesh-rank", "info", _loc(traces),
            f"hand-written mesh's searcher rank improved "
            f"#{want_rank} -> #{result.hand_rank}; re-record with "
            f"--update-goldens to ratchet the gain"))
    want_top = (golden.get("candidates") or [{}])[0].get("axes")
    got_top = result.top.as_golden()["axes"]
    if want_top is not None and want_top != got_top:
        findings.append(Finding(
            "mesh-rank", "warning", _loc(traces),
            f"searcher's top pick moved {want_top} -> {got_top} — the cost "
            f"model's preferred layout changed; re-record if intended"))
    return findings
